"""Quickstart: the batched TPU simulation engine.

The same gossipsub semantics vectorized over all peers: state is a pytree
of arrays, one tick is a jitted function, a whole run is one lax.scan on
device — and the peer axis shards across a jax.sharding.Mesh for
multi-chip (see go_libp2p_pubsub_tpu/parallel/sharding.py).

Run:  python examples/quickstart_sim.py          (single device)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from go_libp2p_pubsub_tpu.sim import (  # noqa: E402
    SimConfig, TopicParams, init_state, topology)
from go_libp2p_pubsub_tpu.sim.engine import (  # noqa: E402
    delivery_fraction, mesh_degrees, run)


def main():
    cfg = SimConfig(
        n_peers=4096, k_slots=32, n_topics=1, msg_window=64,
        publishers_per_tick=8, prop_substeps=8,
        scoring_enabled=True, behaviour_penalty_weight=-10.0,
        gossip_threshold=-100.0, publish_threshold=-200.0,
        graylist_threshold=-300.0)
    tp = TopicParams.disabled(1)
    topo = topology.sparse(cfg.n_peers, cfg.k_slots, degree=12, seed=42)
    state = init_state(cfg, topo)

    state = run(state, cfg, tp, jax.random.PRNGKey(0), 30)   # 30 heartbeats
    deg = mesh_degrees(state)
    print(f"{cfg.n_peers} peers, 30 ticks on {jax.devices()[0].platform}: "
          f"delivery {float(delivery_fraction(state, cfg)):.4f}, "
          f"mean mesh degree {float(deg.mean()):.2f}")
    assert float(delivery_fraction(state, cfg)) > 0.99


if __name__ == "__main__":
    main()
