"""Quickstart: the per-node functional runtime.

The object model mirrors go-libp2p-pubsub (see MIGRATION.md): hosts on a
simulated network, a PubSub per host wrapping a router, Topic handles,
Subscriptions, validators, and tracing — driven by a deterministic
discrete-event scheduler instead of goroutines.

Run:  python examples/quickstart_runtime.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from go_libp2p_pubsub_tpu.api import (  # noqa: E402
    LAX_NO_SIGN, PubSub, VALIDATION_ACCEPT, VALIDATION_REJECT)
from go_libp2p_pubsub_tpu.net import Network  # noqa: E402
from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter  # noqa: E402


def main():
    net = Network()
    nodes = [PubSub(net.add_host(), GossipSubRouter(),
                    sign_policy=LAX_NO_SIGN) for _ in range(12)]
    net.dense_connect([n.host for n in nodes], degree=6)

    # every node joins + subscribes; node 3 also rejects spam
    subs = [n.join("news").subscribe() for n in nodes]
    nodes[3].register_topic_validator(
        "news",
        lambda peer, msg: VALIDATION_REJECT if b"spam" in msg.data
        else VALIDATION_ACCEPT)

    net.scheduler.run_for(3.0)            # heartbeats build the mesh

    nodes[0].my_topics["news"].publish(b"hello gossipsub")
    net.scheduler.run_for(2.0)

    got = sum(1 for s in subs if (m := s.next()) and m.data == b"hello gossipsub")
    deg = [len(n.rt.mesh["news"]) for n in nodes]
    print(f"delivered to {got}/12 nodes; mesh degrees {sorted(deg)}")
    assert got == 12


if __name__ == "__main__":
    main()
