"""Benchmarks: simulated gossipsub heartbeats/sec across the BASELINE configs.

Runs the full batched network step (publish + decay + heartbeat mesh
maintenance + scoring + propagation + gossip) on the default accelerator and
prints ONE JSON line per config — the headline 100k-peer default-gossipsub
line prints LAST. ``vs_baseline`` is value / 1000, the BASELINE.json
north-star target of >= 1000 full-network heartbeats/sec at 100k peers
(the reference router runs 1 heartbeat/sec/node in real time and publishes
no benchmarks; see BASELINE.md).

Configs (BASELINE.json `configs`, built in sim/scenarios.py):
  1. 1k-peer single-topic gossipsub, default score params
  2. 10k-peer Ethereum-beacon-style topics + scoring
  3. 50k-peer multi-topic with peer gater + backoff churn + PX
  4. 100k-peer mesh with 20% sybil attackers
  5. 100k-peer floodsub / randomsub / gossipsub propagation sweep

The record is structurally un-losable (VERDICT r5 item 1): the headline
config runs FIRST, so its number is banked before anything else can time
out, and its JSON line is RE-EMITTED last to preserve the driver's
single-line stdout parse; BENCH_TOTAL_BUDGET (seconds, default 1200)
degrades repeats 3->1 on configs running behind the per-config schedule
rather than ever dropping a config.

Env overrides: BENCH_N (peers for the headline config, default 100_000),
BENCH_MAX_N (cap on EVERY scenario's peer count — reduced-N CPU contract
runs; keep >= 128 so degree/k_slots defaults stay valid),
BENCH_TICKS (in-graph window length; default per scenario, TICKS_DEFAULT),
BENCH_REPEATS (measured windows per config, median reported; default 3),
BENCH_TOTAL_BUDGET (whole-suite seconds budget, default 1200),
BENCH_SCENARIOS (comma list to filter; "headline" names the 100k default),
GRAFT_FLEET_SIZE (lanes in the fleet_256x1k batched-fleet line, default
256 — sim/fleet.py vmap-batched scan; the line's value is the AGGREGATE
B × per-member hb/s, with per_member_hbps/fleet_size/fleet_devices
alongside).

Supervised-run hardening (ISSUE 5 — the rc=124 "empty record" class must
be structurally impossible):
- SIGTERM/SIGINT flush a PARTIAL record before exiting: a
  ``{"partial": true, "completed": [...]}`` line plus the banked headline
  (or a headline-shaped error line marked partial), so an external
  ``timeout`` kill still leaves a complete, parseable record.
- BENCH_JOURNAL=path enables the resumable journal: every completed
  config's metric line is appended (fsync'd) to the journal, and a
  re-invocation replays journaled lines instead of re-running their
  configs — a killed sweep completes incrementally across invocations.
- GRAFT_DEADLINE_S overrides the per-config deadline (alias of
  BENCH_TIMEOUT, shared with sim/supervisor.py's knob family).
"""

import json
import os
import signal
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TARGET_HBPS = 1000.0


def _fetch_rtt():
    """Measured dispatch+value-fetch round trip (the axon tunnel's is
    ~66 ms; local backends ~0), subtracted from every measured window.
    `block_until_ready` does NOT block through the tunnel, so every timing
    below syncs by fetching a value — which costs exactly this RTT. Median
    of 5 samples: a single hiccup sample would bias EVERY window the same
    way (the median over repeats cannot undo a shared offset)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    f = jax.jit(lambda: jnp.float32(1.0))
    np.asarray(f())                           # compile + warm
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(f())
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def _memory_record(cfg, fleet: int = 1) -> dict:
    """Measured per-process device memory next to the modeled estimate, so
    HBM-wall claims in PERF_MODEL are measured rather than modeled:
    ``device.memory_stats()`` peak where the backend reports it (TPU), a
    ``jax.live_arrays()`` byte-sum fallback elsewhere (CPU reports no
    peak — the sum is live bytes at sample time, an underestimate of peak,
    and says so in ``memory_source``). A fleet run vmaps ``fleet`` stacked
    member states (every leaf, message tables included), so the modeled
    estimate scales by B to stay comparable to the measured peak."""
    import jax
    from go_libp2p_pubsub_tpu.sim.state import state_nbytes
    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        stats = None
    if stats and stats.get("peak_bytes_in_use"):
        peak, src = int(stats["peak_bytes_in_use"]), "memory_stats.peak"
    else:
        try:
            peak = int(sum(a.nbytes for a in jax.live_arrays()))
            src = "live_arrays.sum"
        except Exception:
            peak, src = -1, "unavailable"
    return {"device_peak_bytes": peak, "memory_source": src,
            "state_nbytes": state_nbytes(cfg)["total"] * fleet}


def bench_one(name, cfg, tp, st, ticks, repeats, extra=None) -> str:
    """Run one config and print its JSON metric line; returns the line so
    callers can re-emit the headline last (the one-line-parse contract).
    ``extra`` merges additional record keys (e.g. the host-side
    construction cost the frontier family pays before the first
    dispatch)."""
    import jax
    import numpy as np
    from go_libp2p_pubsub_tpu.sim.engine import (
        delivery_fraction, delivery_latency_ticks, run_donated)

    keys = jax.random.split(jax.random.PRNGKey(0), 1 + repeats)
    # warmup with the SAME n_ticks (static jit arg): compiles the measured
    # program and converges the mesh; each measured window uses a DIFFERENT
    # key so it is not a cache-friendly replay of the warmup traffic.
    # run_donated: the input state buffers alias the output, halving peak
    # state memory at 100k peers
    st = run_donated(st, cfg, tp, keys[0], ticks)
    np.asarray(st.tick)                       # real sync (not block_until_ready)
    rtt = _fetch_rtt()

    # >=3 repeats, median reported: cross-round deltas must be larger than
    # run-to-run noise to mean anything (VERDICT r4 weak #3 — the r3->r4
    # driver-record comparison was drowned in single-shot variance)
    rates = []
    for k in keys[1:]:
        t0 = time.perf_counter()
        st = run_donated(st, cfg, tp, k, ticks)
        np.asarray(st.tick)
        raw = time.perf_counter() - t0
        # floor at 5% of the raw window: a mis-measured RTT must degrade
        # accuracy, never fabricate a absurd rate through a ~0 denominator
        dt = max(raw - rtt, raw * 0.05)
        rates.append(ticks / dt)

    hbps = statistics.median(rates)
    platform = jax.devices()[0].platform
    # the health word travels with the number (sim/invariants.py): a
    # poisoned or fault-injected run can never be cited silently —
    # violation bits (bits 8+) mean the rate above measured a suspect
    # trajectory
    from go_libp2p_pubsub_tpu.sim.invariants import decode_flags
    flags = int(np.asarray(st.fault_flags))
    # the RESOLVED formulation per op (not the requested "auto"): sort-vs-
    # mxu trajectory lines in BENCH_*.json stay attributable post-hoc
    # without re-deriving the dispatch logic (ops/dispatch.py)
    from go_libp2p_pubsub_tpu.ops.dispatch import resolved_formulations
    line = json.dumps({
        "metric": f"network_heartbeats_per_sec@{name}[{platform}]",
        "value": round(hbps, 2),
        "unit": "heartbeats/s",
        "platform": platform,
        "vs_baseline": round(hbps / TARGET_HBPS, 4),
        "min": round(min(rates), 2),
        "max": round(max(rates), 2),
        "repeats": repeats,
        "ticks_per_window": ticks,
        "fetch_rtt_ms": round(rtt * 1e3, 1),
        "delivery_fraction": round(float(delivery_fraction(st, cfg)), 4),
        "mean_delivery_latency_ticks": round(
            float(delivery_latency_ticks(st, cfg)), 3),
        "n_peers": cfg.n_peers,
        "fault_flags": flags,
        "fault_flag_names": decode_flags(flags),
        "resolved": resolved_formulations(cfg),
        "requested": {"edge_gather_mode": cfg.edge_gather_mode,
                      "hop_mode": cfg.hop_mode,
                      "selection_mode": cfg.selection_mode},
        # measured per-process device memory + the modeled state estimate
        # (ISSUE 8: HBM-wall claims measured, not modeled)
        **_memory_record(cfg),
        **(extra or {}),
    })
    print(line, flush=True)
    return line


NAMES = ["1k_single_topic", "fleet_256x1k", "10k_beacon",
         "50k_churn_gater_px", "100k_sybil20", "100k_floodsub",
         "100k_randomsub", "100k_gossipsub_sweep",
         "frontier_250k", "frontier_500k", "frontier_1m",
         "frontier_4m", "frontier_10m",
         "telemetry_1k", "telemetry_10k",
         "supervised_overlap_1k", "supervised_overlap_10k",
         "eclipse_50k", "flashcrowd_50k",
         "powerlaw_100k", "powerlaw_1m", "powerlaw_10m",
         "heavytail_eclipse",
         "powerlaw_100k_mh", "powerlaw_10m_mh",
         "ingest_1k", "ingest_10k",
         "verdict_1k", "verdict_10k", "headline"]
# execution order puts headline FIRST (banked before anything can time
# out — losing it cost round 5 its record, VERDICT r5 weak #2) and its
# line is re-emitted LAST so the driver's single-line stdout parse still
# picks it up


# in-graph window length per scenario when BENCH_TICKS is unset: the whole
# window is ONE lax.scan dispatch (sim/engine.run), so small-N configs need
# long windows or the ~66 ms tunnel RTT dominates the measurement — at 1k
# the roofline is sub-ms/tick, and a 10-tick window is >85% RTT (VERDICT r4
# weak #4 "dispatch-bound"). Big-N configs stay short: their per-tick cost
# already dwarfs the RTT.
# fleet window kept short: the batched window costs ~B x the 1k per-tick
# time on a serial host, and the config must fit the per-config deadline
TICKS_DEFAULT = {"1k_single_topic": 300, "10k_beacon": 60,
                 "fleet_256x1k": 10,
                 # frontier family (ROADMAP item 1): short windows — the
                 # per-tick cost at 250k+ dwarfs the dispatch RTT
                 "frontier_250k": 10, "frontier_500k": 5, "frontier_1m": 3,
                 # XL tier (ISSUE 13): compact storage precision; per-tick
                 # cost dominates everything — minimum honest window
                 "frontier_4m": 2, "frontier_10m": 2,
                 # tracing-overhead A/B (ROADMAP item 5): windows long
                 # enough that the per-chunk journal write is amortized
                 # the way a real supervised stream amortizes it
                 "telemetry_1k": 120, "telemetry_10k": 20,
                 # supervised-overlap A/B (ISSUE 12): windows long enough
                 # for a ~5-checkpoint cadence over >=10 chunks
                 "supervised_overlap_1k": 250, "supervised_overlap_10k": 40,
                 # attack family (ISSUE 10): windows cover the scenario's
                 # [3, 8) attack schedule so the measured ticks include
                 # cut + heal (the faults_degraded discipline)
                 "eclipse_50k": 10, "flashcrowd_50k": 10,
                 # heavy-tail family (ISSUE 15): frontier-style short
                 # windows; heavytail_eclipse covers its [3, 8) window
                 "powerlaw_100k": 10, "powerlaw_1m": 3, "powerlaw_10m": 2,
                 "heavytail_eclipse": 10,
                 # row-sharded bucketed family (ISSUE 16): the sharded
                 # execution path at frontier-style windows
                 "powerlaw_100k_mh": 10, "powerlaw_10m_mh": 2,
                 # live command plane (ISSUE 19): windows long enough for
                 # a >=4-chunk supervised cadence with boundary drains
                 "ingest_1k": 120, "ingest_10k": 24,
                 # live contract verdict plane (ISSUE 20): same cadence
                 # as the ingest pair — >=4 chunk boundaries so the
                 # per-boundary monitor fold is amortized the way a real
                 # supervised stream amortizes it
                 "verdict_1k": 120, "verdict_10k": 24}


def _fleet_b() -> int:
    """GRAFT_FLEET_SIZE: lanes in the fleet bench config (sim/fleet.py
    vmap-batched scan; default 256 — the ROADMAP item-3 multiplier shape
    for tiny-N configs that can't fill a chip alone)."""
    return max(1, int(os.environ.get("GRAFT_FLEET_SIZE", 256)))


def _cap_peers(n: int) -> int:
    """``n`` under the BENCH_MAX_N cap — THE one capping rule, shared by
    every scenario builder AND every label maker (parent-process safe: no
    jax import). One rule means a capped reduced-N contract run can never
    build one shape and bank under another's label."""
    cap = os.environ.get("BENCH_MAX_N")
    return min(n, int(cap)) if cap else n


def _fleet_n() -> int:
    """Per-member peer count of the fleet bench config: the 1k shape
    under the BENCH_MAX_N cap (shared with _label so a capped fleet line
    can never be banked under the full-size label)."""
    return _cap_peers(1024)


def bench_fleet(name: str, ticks: int, repeats: int) -> str:
    """The fleet_256x1k line: B seed-varied copies of the 1k config as ONE
    vmap-batched scan (sim/fleet.py). ``value`` is the AGGREGATE rate
    B × per-member hb/s — simulated network-heartbeats per wall second
    across the whole fleet, the number that must beat the sequential
    1k_single_topic line by the batching multiplier."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from go_libp2p_pubsub_tpu.sim import scenarios
    from go_libp2p_pubsub_tpu.sim.engine import delivery_fraction
    from go_libp2p_pubsub_tpu.sim.fleet import (fleet_devices,
                                                fleet_run_keys_donated,
                                                shard_fleet, stack_states)

    b = _fleet_b()
    cfg, tp, st = scenarios.single_topic_1k(n_peers=_fleet_n())
    states = stack_states([st] * b)     # same underlay, per-lane RNG
    tps = stack_states([tp] * b)
    # all windows' per-tick keys are built BEFORE timing: key-splitting is
    # host work that must not ride inside a measured window
    wins = [jnp.stack([jax.random.split(jax.random.PRNGKey(w * 100019 + i),
                                        ticks) for i in range(b)], axis=1)
            for w in range(1 + repeats)]
    n_dev = fleet_devices(b)
    if n_dev > 1:
        # fleet-axis sharding: members are independent, so D local devices
        # run D lanes in parallel with zero collectives (the parent forces
        # a host device mesh on multi-core CPU; on a TPU pod slice the
        # same placement spreads the fleet across chips)
        states, tps, wins = shard_fleet(states, tps, wins)
    states = fleet_run_keys_donated(states, cfg, tps, wins[0])   # warm+compile
    np.asarray(states.tick)
    rtt = _fetch_rtt()
    rates = []
    for kw in wins[1:]:
        t0 = time.perf_counter()
        states = fleet_run_keys_donated(states, cfg, tps, kw)
        np.asarray(states.tick)
        raw = time.perf_counter() - t0
        dt = max(raw - rtt, raw * 0.05)
        rates.append(b * ticks / dt)

    hbps = statistics.median(rates)
    platform = jax.devices()[0].platform
    from go_libp2p_pubsub_tpu.ops.dispatch import resolved_formulations
    from go_libp2p_pubsub_tpu.sim.invariants import decode_flags
    deliv = float(jnp.mean(jax.vmap(
        lambda s: delivery_fraction(s, cfg))(states)))
    flags = int(np.bitwise_or.reduce(
        np.asarray(states.fault_flags).astype(np.uint32)))
    line = json.dumps({
        "metric": f"network_heartbeats_per_sec@{_label(name)}[{platform}]",
        "value": round(hbps, 2),
        "unit": "heartbeats/s",
        "platform": platform,
        "vs_baseline": round(hbps / TARGET_HBPS, 4),
        "min": round(min(rates), 2),
        "max": round(max(rates), 2),
        "repeats": repeats,
        "ticks_per_window": ticks,
        "fetch_rtt_ms": round(rtt * 1e3, 1),
        "fleet_size": b,
        "fleet_devices": n_dev,
        "per_member_hbps": round(hbps / b, 3),
        "delivery_fraction": round(deliv, 4),
        "n_peers": cfg.n_peers,
        "fault_flags": flags,
        "fault_flag_names": decode_flags(flags),
        "resolved": resolved_formulations(cfg),
        "requested": {"edge_gather_mode": cfg.edge_gather_mode,
                      "hop_mode": cfg.hop_mode,
                      "selection_mode": cfg.selection_mode},
        **_memory_record(cfg, fleet=b),
    })
    print(line, flush=True)
    return line


# full peer counts of the tracing-overhead pair — ONE dict shared by the
# builder (_telemetry_n) and the label maker (_label), the same lockstep
# discipline as FRONTIER_FULL_N (a capped contract run must never bank
# under the full label)
TELEMETRY_FULL_N = {"telemetry_1k": 1024, "telemetry_10k": 10_000}


def _telemetry_n(name: str) -> int:
    return _cap_peers(TELEMETRY_FULL_N[name])


def bench_telemetry(name: str, ticks: int, repeats: int) -> str:
    """The tracing-overhead A/B (ROADMAP item 5 success metric): the SAME
    window measured four ways — untraced scan, device-side health
    reduction streamed through the Python encoder, the same records
    through the native codec, and the legacy per-tick JSON event sink
    (``run_traced`` + JSONTracer, the pre-telemetry bottleneck). ``value``
    is the streaming path's hb/s (native encoder when it loads); the
    ``*_overhead_pct`` fields are the numbers PERF_MODEL's "Tracing
    overhead" table tracks against the <10% target."""
    import tempfile

    import jax
    import numpy as np
    from go_libp2p_pubsub_tpu.sim import scenarios, telemetry
    from go_libp2p_pubsub_tpu.sim.engine import run_keys

    n = _telemetry_n(name)
    if name == "telemetry_1k":
        cfg, tp, st = scenarios.single_topic_1k(n_peers=n)
    else:
        cfg, tp, st = scenarios.beacon_10k(n_peers=n)
    windows = [jax.random.split(jax.random.PRNGKey(1000 + w), ticks)
               for w in range(1 + repeats)]
    rtt = None

    def measure(fn, n_ticks):
        """Median hb/s of ``fn(keys)`` over the repeat windows; every leg
        starts from the SAME state and warms on window 0."""
        nonlocal rtt
        fn(windows[0][:n_ticks])            # compile + warm
        if rtt is None:
            rtt = _fetch_rtt()
        rates = []
        for kw in windows[1:]:
            t0 = time.perf_counter()
            fn(kw[:n_ticks])
            raw = time.perf_counter() - t0
            dt = max(raw - rtt, raw * 0.05)
            rates.append(n_ticks / dt)
        return statistics.median(rates)

    def untraced(keys):
        out = run_keys(st, cfg, tp, keys)
        np.asarray(out.tick)

    tmp = tempfile.mkdtemp(prefix="graft_telemetry_bench_")

    def streaming(prefer_native, sync_every_write=True):
        path = os.path.join(tmp,
                            f"health_{prefer_native}_{sync_every_write}.jsonl")
        def leg(keys):
            out, health = run_keys(st, cfg, tp, keys, telemetry=True)
            with telemetry.HealthJournal(
                    path, prefer_native=prefer_native,
                    sync_every_write=sync_every_write) as hj:
                hj.append_records(health, ticks=int(keys.shape[0]))
            np.asarray(out.tick)
            return hj.encoder
        return leg

    from go_libp2p_pubsub_tpu.trace.native import \
        encode_health_json as _native_probe
    native_ok = _native_probe(np.zeros((1, 2)), [("a", True),
                                                 ("b", False)]) is not None

    untraced_hbps = measure(untraced, ticks)
    py_leg = streaming(prefer_native=False)
    device_hbps = measure(py_leg, ticks)
    native_hbps = measure(streaming(prefer_native=True), ticks) \
        if native_ok else None
    # batched-fsync flavor (ISSUE 12 satellite): the async supervisor's
    # writer journals with ONE fsync per queue drain instead of one per
    # write — this leg prices exactly that knob on the best encoder
    batched_hbps = measure(streaming(prefer_native=native_ok,
                                     sync_every_write=False), ticks)

    # legacy comparator: per-tick host-stepped event export into the
    # NDJSON sink — the Python-JSON-sink bottleneck the device reduction
    # replaces. Few ticks suffice (per-tick cost dominates; rate scales)
    import dataclasses
    from go_libp2p_pubsub_tpu.sim.trace_export import run_traced
    from go_libp2p_pubsub_tpu.trace.sinks import JSONTracer
    sink_ticks = min(ticks, 8)
    traced_cfg = dataclasses.replace(cfg, record_provenance=True)

    def json_sink(keys):
        sink = JSONTracer(os.path.join(tmp, "events.jsonl"))
        out, events = run_traced(st, traced_cfg, tp, None, 0, keys=keys)
        for ev in events:
            sink.trace(ev)
        sink.hard_flush()
        sink.close()
        np.asarray(out.tick)

    json_hbps = measure(json_sink, sink_ticks)
    # the measurement journals/event files are evidence only while being
    # timed; recheck cycles must not accumulate orphan temp dirs
    import shutil
    shutil.rmtree(tmp, ignore_errors=True)

    def pct(traced_rate):
        return round((untraced_hbps / traced_rate - 1.0) * 100.0, 2) \
            if traced_rate else None

    value = native_hbps if native_hbps is not None else device_hbps
    platform = jax.devices()[0].platform
    line = json.dumps({
        "metric": f"network_heartbeats_per_sec@{_label(name)}[{platform}]",
        "value": round(value, 2),
        "unit": "heartbeats/s",
        "platform": platform,
        "vs_baseline": round(value / TARGET_HBPS, 4),
        "repeats": repeats,
        "ticks_per_window": ticks,
        "fetch_rtt_ms": round(rtt * 1e3, 1),
        "n_peers": cfg.n_peers,
        "untraced_hbps": round(untraced_hbps, 2),
        "device_py_hbps": round(device_hbps, 2),
        "device_native_hbps": round(native_hbps, 2)
        if native_hbps is not None else None,
        "json_sink_hbps": round(json_hbps, 2),
        "json_sink_ticks": sink_ticks,
        "batched_fsync_hbps": round(batched_hbps, 2),
        "device_py_overhead_pct": pct(device_hbps),
        "device_native_overhead_pct": pct(native_hbps),
        "json_sink_overhead_pct": pct(json_hbps),
        "batched_fsync_overhead_pct": pct(batched_hbps),
        "native_codec": native_ok,
        **_memory_record(cfg),
    })
    print(line, flush=True)
    return line


# full peer counts of the supervised-overlap pair (ISSUE 12) —
# parent-safe like TELEMETRY_FULL_N; capped runs are labeled by what ran
OVERLAP_FULL_N = {"supervised_overlap_1k": 1024,
                  "supervised_overlap_10k": 10_000}


def bench_overlap(name: str, ticks: int, repeats: int) -> str:
    """The supervised-overlap A/B (ISSUE 12 acceptance): the SAME window
    measured three ways — the unsupervised engine scan, the synchronous
    supervised loop (``async_chunks=False``: checkpoint serialization and
    journal fsync inline at every boundary, the positive control), and
    the async pipeline (speculative chunk dispatch + off-path writer
    thread) — with the checkpoint cadence swept. ``value`` is the async
    pipeline's hb/s at the ~5-checkpoint cadence; the ``*_pause_ms_*``
    fields are the per-checkpoint visible pause (the supervisor's
    "boundary" events: what the main loop stalled at a boundary). These
    are the numbers PERF_MODEL's "Supervised execution plane" tracks."""
    import shutil
    import tempfile

    import jax
    import numpy as np
    from go_libp2p_pubsub_tpu.sim import scenarios
    from go_libp2p_pubsub_tpu.sim.engine import run_keys
    from go_libp2p_pubsub_tpu.sim.supervisor import (SupervisorConfig,
                                                     supervised_run)

    n = _cap_peers(OVERLAP_FULL_N[name])
    cfg, tp, st = scenarios.single_topic_1k(n_peers=n) \
        if name == "supervised_overlap_1k" \
        else scenarios.beacon_10k(n_peers=n)
    key = jax.random.PRNGKey(7)
    keys_all = jax.random.split(key, ticks)
    np.asarray(run_keys(st, cfg, tp, keys_all).tick)    # compile + warm
    rtt = _fetch_rtt()

    def timed(fn, cleanup=None):
        """Median hb/s over the repeat runs. ``cleanup`` runs OUTSIDE the
        timed section between repeats: checkpoint dirs must be wiped so a
        later repeat cannot resume mid-window and measure a shorter run."""
        rates = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            fn()
            raw = time.perf_counter() - t0
            dt = max(raw - rtt, raw * 0.05)
            rates.append(ticks / dt)
            if cleanup is not None:
                cleanup()
        return statistics.median(rates)

    unsup_hbps = timed(
        lambda: np.asarray(run_keys(st, cfg, tp, keys_all).tick))

    chunk = max(1, ticks // 10)
    tmp = tempfile.mkdtemp(prefix="graft_overlap_bench_")

    def leg(asynch: bool, every: int):
        ck = os.path.join(tmp, f"ck_{int(asynch)}_{every}")
        pauses: list = []

        def run_once():
            sup = SupervisorConfig(
                chunk_ticks=chunk, checkpoint_every_ticks=every,
                checkpoint_dir=ck,
                health_path=os.path.join(tmp, "health.jsonl"),
                async_chunks=asynch, max_retries=0, backoff_base_s=0.0)
            out, rep = supervised_run(st, cfg, tp, key, ticks, sup)
            np.asarray(out.tick)
            pauses.extend(e["pause_ms"] for e in rep.events
                          if e["event"] == "boundary")

        def cleanup():
            shutil.rmtree(ck, ignore_errors=True)

        run_once()      # compile + warm the chunk executables (AOT cache)
        cleanup()
        pauses.clear()
        rate = timed(run_once, cleanup)
        return rate, pauses

    def pause_stats(prefix, pauses):
        if not pauses:
            return {f"{prefix}_pause_ms_max": None,
                    f"{prefix}_pause_ms_mean": None}
        return {f"{prefix}_pause_ms_max": round(max(pauses), 3),
                f"{prefix}_pause_ms_mean":
                    round(sum(pauses) / len(pauses), 3)}

    # cadence sweep: ~5 and ~10 checkpoints over the window, clamped to
    # the chunk length (a boundary can only land on a chunk edge);
    # largest interval (fewest checkpoints) first — it is the headline
    cadences = sorted({max(chunk, ticks // 5), max(chunk, ticks // 10)},
                      reverse=True)
    sweep = []
    for every in cadences:
        sync_hbps, sync_pauses = leg(False, every)
        async_hbps, async_pauses = leg(True, every)
        sweep.append({
            "checkpoint_every_ticks": every,
            "n_checkpoints": ticks // every,
            "sync_hbps": round(sync_hbps, 2),
            "async_hbps": round(async_hbps, 2),
            **pause_stats("sync", sync_pauses),
            **pause_stats("async", async_pauses),
        })
    shutil.rmtree(tmp, ignore_errors=True)

    def pct(rate):
        return round((unsup_hbps / rate - 1.0) * 100.0, 2) if rate else None

    head = sweep[0]
    platform = jax.devices()[0].platform
    line = json.dumps({
        "metric": f"network_heartbeats_per_sec@{_label(name)}[{platform}]",
        "value": head["async_hbps"],
        "unit": "heartbeats/s",
        "platform": platform,
        "vs_baseline": round(head["async_hbps"] / TARGET_HBPS, 4),
        "repeats": repeats,
        "ticks_per_window": ticks,
        "fetch_rtt_ms": round(rtt * 1e3, 1),
        "n_peers": cfg.n_peers,
        "chunk_ticks": chunk,
        "unsupervised_hbps": round(unsup_hbps, 2),
        "sync_hbps": head["sync_hbps"],
        "async_hbps": head["async_hbps"],
        "sync_overhead_pct": pct(head["sync_hbps"]),
        "async_overhead_pct": pct(head["async_hbps"]),
        "sync_pause_ms_max": head["sync_pause_ms_max"],
        "async_pause_ms_max": head["async_pause_ms_max"],
        "cadence_sweep": sweep,
        **_memory_record(cfg),
    })
    print(line, flush=True)
    return line


# full peer counts of the live-command-plane pair (ISSUE 19) —
# parent-safe like TELEMETRY_FULL_N; capped runs are labeled by what ran
INGEST_FULL_N = {"ingest_1k": 1024, "ingest_10k": 10_000}


def bench_ingest(name: str, ticks: int, repeats: int) -> str:
    """Live-command-plane sustained ingestion rate (ISSUE 19): the SAME
    supervised window fed pre-written NDJSON directive streams at three
    offered loads — light, at the per-chunk slot watermark, and PAST it.
    The overload leg is the admission-control contract priced: load past
    the slot budget sheds deterministically (journaled counts, asserted
    below), the frames stay fixed-shape (ONE replay trace for every leg)
    and the chip never blocks on ingestion. ``value`` is commands/s
    applied at the watermark load; per-load ``hbps`` tracks what
    ingestion costs the chip vs the supervised baseline. These are the
    numbers PERF_MODEL's "Live command plane" table tracks."""
    import shutil
    import tempfile

    import jax
    import numpy as np
    from go_libp2p_pubsub_tpu.sim import scenarios
    from go_libp2p_pubsub_tpu.sim.commands import CommandQueue, write_stream
    from go_libp2p_pubsub_tpu.sim.supervisor import (SupervisorConfig,
                                                     supervised_run)

    n = _cap_peers(INGEST_FULL_N[name])
    cfg, tp, st = scenarios.single_topic_1k(n_peers=n) \
        if name == "ingest_1k" else scenarios.beacon_10k(n_peers=n)
    key = jax.random.PRNGKey(7)
    chunk = max(1, ticks // 4)
    slots = 64
    # the shed watermark: offered/tick that exactly fills the per-chunk
    # slot budget — the third load runs 4x past it
    watermark = max(1, slots // chunk)
    offered = {"light": max(1, watermark // 4),
               "watermark": watermark,
               "overload": watermark * 4}
    tmp = tempfile.mkdtemp(prefix="graft_ingest_bench_")
    streams = {}
    for leg, per_tick in offered.items():
        path = os.path.join(tmp, f"{leg}.ndjsonl")
        write_stream(path, [
            {"op": "publish", "tick": t, "peer": (t * 131 + i) % n,
             "topic": 0}
            for t in range(ticks) for i in range(per_tick)])
        streams[leg] = path
    rtt = _fetch_rtt()

    def run_once(leg):
        q = CommandQueue(streams[leg], n_peers=cfg.n_peers,
                         n_topics=cfg.n_topics, msg_window=cfg.msg_window,
                         slots=slots, stall_timeout_s=60.0, follow=False)
        sup = SupervisorConfig(chunk_ticks=chunk, commands=q,
                               max_retries=0, backoff_base_s=0.0)
        try:
            out, _rep = supervised_run(st, cfg, tp, key, ticks, sup)
            np.asarray(out.tick)
        finally:
            q.close()
        return q

    legs = {}
    run_once("light")       # compile + warm: ONE trace serves every leg
    for leg, per_tick in offered.items():
        rates, hb = [], []
        q = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            q = run_once(leg)
            raw = time.perf_counter() - t0
            dt = max(raw - rtt, raw * 0.05)
            rates.append(q.applied_total / dt)
            hb.append(ticks / dt)
        legs[leg] = {
            "offered_per_tick": per_tick,
            "offered_total": per_tick * ticks,
            "applied": q.applied_total,
            "shed": q.shed_total,
            "refused": q.refused_total,
            "commands_per_sec": round(statistics.median(rates), 2),
            "hbps": round(statistics.median(hb), 2),
        }
    shutil.rmtree(tmp, ignore_errors=True)
    # the admission-control contract, checked where the number is banked:
    # in-budget loads shed nothing, the overload leg sheds EXACTLY the
    # excess (deterministic load-shedding, never a crash or a stall)
    assert legs["light"]["shed"] == 0 and legs["watermark"]["shed"] == 0, \
        "in-budget ingest load shed"
    over = legs["overload"]
    assert over["applied"] + over["shed"] == over["offered_total"], \
        "overload leg lost directives"
    assert over["shed"] > 0, "overload leg never crossed the watermark"

    head = legs["watermark"]
    platform = jax.devices()[0].platform
    line = json.dumps({
        "metric": f"commands_per_sec@{_label(name)}[{platform}]",
        "value": head["commands_per_sec"],
        "unit": "commands/s",
        "platform": platform,
        "vs_baseline": round(head["hbps"] / TARGET_HBPS, 4),
        "repeats": repeats,
        "ticks_per_window": ticks,
        "fetch_rtt_ms": round(rtt * 1e3, 1),
        "n_peers": cfg.n_peers,
        "chunk_ticks": chunk,
        "directive_slots": slots,
        "shed_watermark_per_tick": watermark,
        "light": legs["light"],
        "watermark": legs["watermark"],
        "overload": legs["overload"],
        **_memory_record(cfg),
    })
    print(line, flush=True)
    return line


# full peer counts of the verdict-plane pair (ISSUE 20) — parent-safe
# like INGEST_FULL_N; capped runs are labeled by what ran
VERDICT_FULL_N = {"verdict_1k": 1024, "verdict_10k": 10_000}


def bench_verdict(name: str, ticks: int, repeats: int) -> str:
    """Live contract verdict plane overhead (ISSUE 20): the SAME
    supervised telemetry window run twice — journaling only (contracts
    off) vs carrying one streaming monitor of EACH kind
    (sim/adversary.py ContractMonitors, verdict notes journaled at every
    status transition). The fold is host-side at chunk confirm time,
    off the chip's critical path, so the A/B prices exactly what the
    verdict plane adds: the per-row monitor folds plus the transition
    notes. ``value`` is the monitored hb/s; the parity assert re-judges
    the journaled rows full-batch where the number is banked — a
    monitor that drifted from its contract cannot bank a line."""
    import shutil
    import tempfile

    import jax
    import numpy as np
    from go_libp2p_pubsub_tpu.sim import adversary, scenarios, telemetry
    from go_libp2p_pubsub_tpu.sim.supervisor import (SupervisorConfig,
                                                     supervised_run)

    n = _cap_peers(VERDICT_FULL_N[name])
    cfg, tp, st = scenarios.single_topic_1k(n_peers=n) \
        if name == "verdict_1k" else scenarios.beacon_10k(n_peers=n)
    key = jax.random.PRNGKey(7)
    chunk = max(1, ticks // 4)
    # one monitor of each kind, shaped to stay live over the whole
    # window (every row folds into all three — the worst-case fold)
    contracts = (
        adversary.DeliveryFloor(floor=0.0, start=0),
        adversary.RecoveryCeiling(after=0, within=ticks + 1, floor=0.0),
        adversary.ScoreResponse(by=ticks * 2, attacker_frac=0.5),
    )
    tmp = tempfile.mkdtemp(prefix="graft_verdict_bench_")
    rtt = _fetch_rtt()

    def run_once(leg, monitored):
        health = os.path.join(tmp, f"{leg}.jsonl")
        if os.path.exists(health):
            os.remove(health)
        sup = SupervisorConfig(
            chunk_ticks=chunk, max_retries=0, backoff_base_s=0.0,
            health_path=health,
            contracts=contracts if monitored else ())
        out, _rep = supervised_run(st, cfg, tp, key, ticks, sup)
        np.asarray(out.tick)
        return health

    run_once("warm", True)      # compile + warm both code paths
    legs = {}
    for leg, monitored in (("unmonitored", False), ("monitored", True)):
        hb = []
        health = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            health = run_once(leg, monitored)
            raw = time.perf_counter() - t0
            dt = max(raw - rtt, raw * 0.05)
            hb.append(ticks / dt)
        legs[leg] = {"hbps": round(statistics.median(hb), 2),
                     "health": health}
    # parity priced where the number is banked: the monitors' journaled
    # final verdicts must equal the full-batch evaluation of the same
    # journaled rows — and at least one transition note must exist
    j = telemetry.read_journal(legs["monitored"]["health"])
    notes = [x for x in j["notes"] if x.get("kind") == "contract_verdict"]
    assert notes, "monitored leg journaled no contract_verdict notes"
    latest = {}
    for v in notes:
        if v["contract"] not in latest \
                or v["seq"] >= latest[v["contract"]]["seq"]:
            latest[v["contract"]] = v
    batch = adversary.evaluate_contracts(contracts, j["rows"], final=True)
    assert [latest[i]["status"] for i in range(len(contracts))] \
        == [r.status for r in batch], "monitor verdicts drifted from batch"
    shutil.rmtree(tmp, ignore_errors=True)

    mon, unmon = legs["monitored"]["hbps"], legs["unmonitored"]["hbps"]
    platform = jax.devices()[0].platform
    line = json.dumps({
        "metric": f"network_heartbeats_per_sec@{_label(name)}[{platform}]",
        "value": mon,
        "unit": "heartbeats/s",
        "platform": platform,
        "vs_baseline": round(mon / TARGET_HBPS, 4),
        "repeats": repeats,
        "ticks_per_window": ticks,
        "fetch_rtt_ms": round(rtt * 1e3, 1),
        "n_peers": cfg.n_peers,
        "chunk_ticks": chunk,
        "n_contracts": len(contracts),
        "monitored_hbps": mon,
        "unmonitored_hbps": unmon,
        "verdict_overhead_pct":
            round((unmon / mon - 1.0) * 100.0, 2) if mon else None,
        "verdict_notes": len(notes),
        **_memory_record(cfg),
    })
    print(line, flush=True)
    return line


def bench_bucketed(name: str, ticks: int, repeats: int) -> str:
    """Heavy-tailed underlay lines (sim/bucketed.py): the degree-bucketed
    execution path measured through ``bucketed_run``, with the graph's
    degree shape (``topology.degree_stats``) and the bucket partition
    stamped into the record so every banked line states the underlay it
    ran on. The HBM gate prices the BUCKETED layout before the underlay
    builds — ``powerlaw_cfg`` is closed-form, no topology needed."""
    import resource

    import jax
    import numpy as np
    from go_libp2p_pubsub_tpu.ops.dispatch import resolved_formulations
    from go_libp2p_pubsub_tpu.sim import scenarios, topology
    from go_libp2p_pubsub_tpu.sim.bucketed import (bucketed_run,
                                                   decode_bucketed)
    from go_libp2p_pubsub_tpu.sim.engine import (delivery_fraction,
                                                 delivery_latency_ticks)
    from go_libp2p_pubsub_tpu.sim.invariants import decode_flags
    from go_libp2p_pubsub_tpu.sim.state import check_hbm_budget

    assert all(POWERLAW_FULL_N[k] == v
               for k, v in scenarios.POWERLAW_NS.items()), \
        "bench POWERLAW_FULL_N drifted from scenarios.POWERLAW_NS"
    n = _cap_peers(POWERLAW_FULL_N[name])
    check_hbm_budget(scenarios.powerlaw_cfg(n), 1,
                     what=f"{name} n={n} bucketed state")
    t_build = time.perf_counter()
    cfg, tp, bs = scenarios.BUCKETED_SCENARIOS[name](n_peers=n)
    build_extra = {
        "build_wall_s": round(time.perf_counter() - t_build, 2),
        "build_peak_rss_bytes":
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024,
    }
    # realized degrees straight off the bucketed planes — cheap row
    # reductions, no densification
    deg = np.concatenate([
        np.asarray((np.asarray(e.neighbors) >= 0).sum(axis=1))
        for e in bs.e])
    dstats = topology.degree_stats(deg)

    keys = jax.random.split(jax.random.PRNGKey(0), 1 + repeats)
    bs = bucketed_run(bs, cfg, tp, keys[0], ticks)
    np.asarray(bs.g.tick)
    rtt = _fetch_rtt()
    rates = []
    for k in keys[1:]:
        t0 = time.perf_counter()
        bs = bucketed_run(bs, cfg, tp, k, ticks)
        np.asarray(bs.g.tick)
        raw = time.perf_counter() - t0
        dt = max(raw - rtt, raw * 0.05)
        rates.append(ticks / dt)
    hbps = statistics.median(rates)

    dec = decode_bucketed(bs, cfg)
    flags = int(np.asarray(dec.g.fault_flags))
    platform = jax.devices()[0].platform
    line = json.dumps({
        "metric": f"network_heartbeats_per_sec@{_label(name)}[{platform}]",
        "value": round(hbps, 2),
        "unit": "heartbeats/s",
        "platform": platform,
        "vs_baseline": round(hbps / TARGET_HBPS, 4),
        "min": round(min(rates), 2),
        "max": round(max(rates), 2),
        "repeats": repeats,
        "ticks_per_window": ticks,
        "fetch_rtt_ms": round(rtt * 1e3, 1),
        "delivery_fraction": round(float(delivery_fraction(dec.g, cfg)), 4),
        "mean_delivery_latency_ticks": round(
            float(delivery_latency_ticks(dec.g, cfg)), 3),
        "n_peers": cfg.n_peers,
        "degree_stats": dstats,
        "degree_buckets": [list(b) for b in cfg.degree_buckets],
        "bucketed_rng": cfg.bucketed_rng,
        "fault_flags": flags,
        "fault_flag_names": decode_flags(flags),
        "resolved": resolved_formulations(cfg),
        **_memory_record(cfg),
        **build_extra,
    })
    print(line, flush=True)
    return line


def bench_bucketed_mh(name: str, ticks: int, repeats: int) -> str:
    """ROW-SHARDED bucketed lines (ISSUE 16): the same compiled unit
    scripts/run_multihost.py --engine bucketed dispatches per process —
    ``make_sharded_bucketed_run`` over the local device mesh, every
    bucket's edge planes row-split across shards — measured with the
    degree shape AND the per-(bucket x shard) byte accounting stamped
    into the record (scripts/dashboard.py renders those instead of a
    dense estimate). The HBM gate prices the sharded layout closed-form
    BEFORE the underlay builds, exactly like the launcher."""
    import resource

    import jax
    import numpy as np
    from go_libp2p_pubsub_tpu.ops.dispatch import resolved_formulations
    from go_libp2p_pubsub_tpu.parallel.sharding import (
        make_mesh, make_sharded_bucketed_run, shard_bucketed_state)
    from go_libp2p_pubsub_tpu.sim import scenarios, topology
    from go_libp2p_pubsub_tpu.sim.bucketed import (decode_bucketed,
                                                   init_bucketed_state)
    from go_libp2p_pubsub_tpu.sim.engine import (delivery_fraction,
                                                 delivery_latency_ticks)
    from go_libp2p_pubsub_tpu.sim.invariants import decode_flags
    from go_libp2p_pubsub_tpu.sim.state import check_hbm_budget

    n = _cap_peers(POWERLAW_MH_FULL_N[name])
    devs = jax.devices()
    # closed-form per-(bucket x shard) gate before any topology build —
    # the launcher's discipline (scripts/run_multihost.py)
    acct = check_hbm_budget(
        scenarios.powerlaw_cfg(n, shard_align=scenarios.POWERLAW_MH_ALIGN),
        len(devs), what=f"{name} n={n} row-sharded bucketed state")

    t_build = time.perf_counter()
    cfg, tp, topo_rows, subscribed = scenarios.powerlaw_mh_spec(n)
    bs = init_bucketed_state(cfg, topo_rows(0, n), subscribed=subscribed)
    build_extra = {
        "build_wall_s": round(time.perf_counter() - t_build, 2),
        "build_peak_rss_bytes":
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024,
    }
    deg = np.concatenate([
        np.asarray((np.asarray(e.neighbors) >= 0).sum(axis=1))
        for e in bs.e])
    dstats = topology.degree_stats(deg)

    mesh = make_mesh(devs)
    run = make_sharded_bucketed_run(mesh, cfg, tp)
    bs = shard_bucketed_state(bs, mesh, cfg)
    keys = jax.random.split(jax.random.PRNGKey(0), 1 + repeats)
    bs = run(bs, jax.random.split(keys[0], ticks))
    np.asarray(bs.g.tick)
    rtt = _fetch_rtt()
    rates = []
    for k in keys[1:]:
        t0 = time.perf_counter()
        bs = run(bs, jax.random.split(k, ticks))
        np.asarray(bs.g.tick)
        raw = time.perf_counter() - t0
        dt = max(raw - rtt, raw * 0.05)
        rates.append(ticks / dt)
    hbps = statistics.median(rates)

    dec = decode_bucketed(bs, cfg)
    flags = int(np.asarray(dec.g.fault_flags))
    platform = jax.devices()[0].platform
    line = json.dumps({
        "metric": f"network_heartbeats_per_sec@{_label(name)}[{platform}]",
        "value": round(hbps, 2),
        "unit": "heartbeats/s",
        "platform": platform,
        "vs_baseline": round(hbps / TARGET_HBPS, 4),
        "min": round(min(rates), 2),
        "max": round(max(rates), 2),
        "repeats": repeats,
        "ticks_per_window": ticks,
        "fetch_rtt_ms": round(rtt * 1e3, 1),
        "delivery_fraction": round(float(delivery_fraction(dec.g, cfg)), 4),
        "mean_delivery_latency_ticks": round(
            float(delivery_latency_ticks(dec.g, cfg)), 3),
        "n_peers": cfg.n_peers,
        "n_devices": len(devs),
        "sharded_route": cfg.sharded_route,
        "degree_stats": dstats,
        "degree_buckets": [list(b) for b in cfg.degree_buckets],
        "bucketed_rng": cfg.bucketed_rng,
        "state_nbytes_per_shard": acct["per_shard"],
        "bucket_shards": acct["bucket_shards"],
        "fault_flags": flags,
        "fault_flag_names": decode_flags(flags),
        "resolved": resolved_formulations(cfg),
        **_memory_record(cfg),
        **build_extra,
    })
    print(line, flush=True)
    return line


def run_scenario(name: str) -> str | None:
    from go_libp2p_pubsub_tpu.sim import scenarios

    env_ticks = os.environ.get("BENCH_TICKS")
    ticks = int(env_ticks) if env_ticks else TICKS_DEFAULT.get(name, 10)
    repeats = max(1, int(os.environ.get("BENCH_REPEATS", 3)))

    if name in ("telemetry_1k", "telemetry_10k"):
        # the tracing-overhead A/B rides its own four-way measurement
        # path; the kernel-mode sweep knobs don't apply
        return bench_telemetry(name, ticks, repeats)

    if name in OVERLAP_FULL_N:
        # the supervised-overlap A/B (ISSUE 12) rides its own three-way
        # measurement path; the kernel-mode sweep knobs don't apply
        return bench_overlap(name, ticks, repeats)

    if name in INGEST_FULL_N:
        # the live-command-plane pair (ISSUE 19) rides the supervised
        # loop with boundary directive drains; sweep knobs don't apply
        return bench_ingest(name, ticks, repeats)

    if name in VERDICT_FULL_N:
        # the verdict-plane pair (ISSUE 20) rides the supervised loop
        # with streaming contract monitors; sweep knobs don't apply
        return bench_verdict(name, ticks, repeats)

    if name in POWERLAW_FULL_N:
        # the heavy-tail family rides the bucketed execution path
        # (sim/bucketed.bucketed_run); the kernel-mode sweep knobs don't
        # apply — per-edge seams resolve per bucket
        return bench_bucketed(name, ticks, repeats)

    if name in POWERLAW_MH_FULL_N:
        # the row-sharded bucketed family (ISSUE 16) rides the SHARDED
        # execution path over the local device mesh
        return bench_bucketed_mh(name, ticks, repeats)

    if name == "fleet_256x1k":
        # the batched-fleet line rides its own measurement path (aggregate
        # rate over B vmapped lanes, sim/fleet.py); the kernel-mode sweep
        # knobs don't apply — the fleet runs the scenario's own modes
        return bench_fleet(name, ticks, repeats)

    # BENCH_MAX_N: reduced-N contract runs exercise the WHOLE config
    # suite on CPU within the total budget (tests/test_bench_contract)
    _cap_n = _cap_peers

    def headline():
        from __graft_entry__ import _build
        # BENCH_K right-sizes the slot capacity: the degree-12 underlay
        # needs k > Dhi=12 headroom, and every edge-slot op (sorts,
        # selections, accumulators) scales with N*K — k=16 is the same
        # simulated network at 2x less padding than the historical k=32
        return _build(n_peers=_headline_n(),
                      k_slots=int(os.environ.get("BENCH_K", 32)),
                      degree=12, msg_window=64, publishers=8)

    def _frontier(full_n, **kw):
        # the frontier family's full peer counts live in
        # scenarios.FRONTIER_NS; BENCH_MAX_N gates them for reduced-N
        # contract runs exactly like every other scenario. The state is
        # PRICED before a single array allocates (sim/state.
        # check_hbm_budget): with GRAFT_HBM_BUDGET set, an over-budget
        # frontier line refuses by name — citing its worst planes —
        # instead of OOMing mid-suite and eating the deadline
        from go_libp2p_pubsub_tpu.sim.state import check_hbm_budget
        n = _cap_n(full_n)
        pre = scenarios.frontier_cfg(
            n, state_precision=kw.get("state_precision", "f32"))
        check_hbm_budget(pre, 1, what=f"frontier n={n} state")
        return scenarios.frontier(n, **kw)

    builders = {
        "1k_single_topic":
            lambda: scenarios.single_topic_1k(n_peers=_cap_n(1024)),
        "frontier_250k":
            lambda: _frontier(scenarios.FRONTIER_NS["frontier_250k"]),
        "frontier_500k":
            lambda: _frontier(scenarios.FRONTIER_NS["frontier_500k"]),
        "frontier_1m":
            lambda: _frontier(scenarios.FRONTIER_NS["frontier_1m"]),
        # XL tier (ISSUE 13): compact storage precision by construction —
        # the f32 layout would not survive pricing at these N
        "frontier_4m":
            lambda: _frontier(scenarios.FRONTIER_NS["frontier_4m"],
                              state_precision="compact"),
        "frontier_10m":
            lambda: _frontier(scenarios.FRONTIER_NS["frontier_10m"],
                              state_precision="compact"),
        "10k_beacon": lambda: scenarios.beacon_10k(n_peers=_cap_n(10_000)),
        "50k_churn_gater_px":
            lambda: scenarios.churn_50k(n_peers=_cap_n(50_000)),
        "100k_sybil20": lambda: scenarios.sybil_100k(n_peers=_cap_n(100_000)),
        "100k_floodsub": lambda: scenarios.router_sweep_100k(
            "floodsub", n_peers=_cap_n(100_000)),
        "100k_randomsub": lambda: scenarios.router_sweep_100k(
            "randomsub", n_peers=_cap_n(100_000)),
        "100k_gossipsub_sweep": lambda: scenarios.router_sweep_100k(
            "gossipsub", n_peers=_cap_n(100_000)),
        # adversary/workload library at bench scale (ISSUE 10): the
        # eclipse + flash-crowd families with their [3, 8) attack
        # windows inside the measured ticks — degraded-mode rates with
        # the fault_flags naming exactly which attack fired
        "eclipse_50k": lambda: scenarios.eclipse_50k(
            n_peers=_cap_n(ATTACK_FULL_N["eclipse_50k"])),
        "flashcrowd_50k": lambda: scenarios.flashcrowd_50k(
            n_peers=_cap_n(ATTACK_FULL_N["flashcrowd_50k"])),
        "headline": headline,
    }
    assert set(builders) | {"fleet_256x1k", "telemetry_1k",
                            "telemetry_10k", "supervised_overlap_1k",
                            "supervised_overlap_10k"} \
        | set(POWERLAW_FULL_N) | set(POWERLAW_MH_FULL_N) \
        | set(INGEST_FULL_N) | set(VERDICT_FULL_N) == set(NAMES), \
        "scenario registry drifted from NAMES"
    assert FRONTIER_FULL_N == scenarios.FRONTIER_NS, \
        "bench FRONTIER_FULL_N drifted from scenarios.FRONTIER_NS"
    # construction cost travels with the line: at frontier scale the
    # host-side underlay build (topology.sparse_fast, measured ~14 s at
    # 1M×32 — sim/topology.py docstring) and its O(N·K) host RAM are a
    # real part of the launch price, and the record is where PERF_MODEL's
    # construction-cost table comes from. ru_maxrss is the process-lifetime
    # peak (KiB on Linux), so it upper-bounds the build's footprint.
    import resource
    t_build = time.perf_counter()
    cfg, tp, st = builders[name]()
    build_extra = {
        "build_wall_s": round(time.perf_counter() - t_build, 2),
        "build_peak_rss_bytes":
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024,
    }
    mode = os.environ.get("GRAFT_EDGE_GATHER")
    if mode:
        # formulation sweep knob for scripts/tpu_recheck.sh (ops/permgather)
        import dataclasses
        import jax.numpy as jnp
        from go_libp2p_pubsub_tpu.ops.permgather import (
            resolve_mode, resolve_words_mode)
        cfg = dataclasses.replace(cfg, edge_gather_mode=mode)
        print(json.dumps({
            "info": "edge_gather sweep", "requested": mode,
            "resolved": resolve_mode(mode, jnp.uint32, cfg.n_peers,
                                     cfg.k_slots),
            # the word-table gathers resolve separately — "mxu" rides them
            # while the generic payload permute degrades to scalar
            "resolved_words": resolve_words_mode(
                mode, (cfg.msg_window + 31) // 32, cfg.n_peers,
                cfg.k_slots)}), flush=True)
    hm = os.environ.get("GRAFT_HOP_MODE")
    if hm:
        # fused-hop sweep knob (ops/hopkernel.py): xla | pallas | pallas-mxu
        import dataclasses
        from go_libp2p_pubsub_tpu.ops.hopkernel import resolve_hop_mode
        cfg = dataclasses.replace(cfg, hop_mode=hm)
        print(json.dumps({
            "info": "hop mode sweep", "requested": hm,
            "resolved": resolve_hop_mode(
                hm, cfg, (cfg.msg_window + 31) // 32, cfg.n_peers,
                cfg.k_slots)}), flush=True)
    sel = os.environ.get("GRAFT_SELECTION")
    if sel:
        # selection-kernel sweep knob (ops/selection.py)
        import dataclasses
        cfg = dataclasses.replace(cfg, selection_mode=sel)
        print(json.dumps({"info": "selection sweep", "requested": sel}),
              flush=True)
    cdt = os.environ.get("GRAFT_COUNT_DTYPE")
    if cdt:
        # hop-count accumulator width sweep (sim/config.py count_dtype)
        import dataclasses
        cfg = dataclasses.replace(cfg, count_dtype=cdt)
        print(json.dumps({"info": "count dtype sweep", "requested": cdt}),
              flush=True)
    fp = os.environ.get("GRAFT_FAULT_PLAN")
    if fp:
        # one-flag degraded-mode sweep (sim/faults.py FaultPlan.parse):
        # e.g. GRAFT_FAULT_PLAN=partition=2@3:8,drop=0.02 — the emitted
        # fault_flags then name exactly which faults fired
        import dataclasses
        from go_libp2p_pubsub_tpu.sim.faults import FaultPlan
        cfg = dataclasses.replace(cfg, fault_plan=FaultPlan.parse(fp))
        print(json.dumps({"info": "fault plan sweep", "requested": fp}),
              flush=True)
    im = os.environ.get("GRAFT_INVARIANT_MODE")
    if im:
        # invariant-sentinel overhead sweep (sim/invariants.py): off |
        # record — measures the record-mode cost logged in PERF_MODEL.md.
        # "raise" is rejected up front: its checkify.check only
        # functionalizes under engine.run_checked, and bench's plain
        # run_donated would die deep in tracing with an opaque error
        if im not in ("off", "record"):
            raise SystemExit(
                f"GRAFT_INVARIANT_MODE={im!r}: bench supports 'off' or "
                "'record' ('raise' needs the checkify-transformed "
                "engine.run_checked, a debugging path, not a benchmark)")
        import dataclasses
        cfg = dataclasses.replace(cfg, invariant_mode=im)
        print(json.dumps({"info": "invariant mode sweep", "requested": im}),
              flush=True)
    return bench_one(_label(name), cfg, tp, st, ticks, repeats,
                     extra=build_extra)


def _headline_n() -> int:
    """The peer count the headline config ACTUALLY builds: BENCH_N under
    the BENCH_MAX_N cap. Shared by the builder and _label so a capped
    reduced-N headline can never be banked (or cited by the
    window-evidence chain) under the full-N label."""
    return _cap_peers(int(os.environ.get("BENCH_N", 100_000)))


# full peer counts of the frontier family — duplicated from
# sim/scenarios.FRONTIER_NS because the bench PARENT process must not
# import jax (platform-probe discipline); run_scenario (the child, where
# jax is live) asserts the two stay in sync
FRONTIER_FULL_N = {"frontier_250k": 262_144, "frontier_500k": 524_288,
                   "frontier_1m": 1_048_576,
                   "frontier_4m": 4_194_304, "frontier_10m": 10_485_760}

# full peer counts of the attack family (ISSUE 10) — parent-safe like
# FRONTIER_FULL_N; capped runs are labeled by what ran
ATTACK_FULL_N = {"eclipse_50k": 50_000, "flashcrowd_50k": 50_000}

# full peer counts of the heavy-tail family (ISSUE 15) — parent-safe
# duplicate of sim/scenarios.POWERLAW_NS (run_scenario asserts sync for
# the scenario pair); heavytail_eclipse rides the 100k graph
POWERLAW_FULL_N = {"powerlaw_100k": 131_072, "powerlaw_1m": 1_048_576,
                   "powerlaw_10m": 10_485_760,
                   "heavytail_eclipse": 131_072}

# row-sharded bucketed family (ISSUE 16) — the _mh lines measure the
# SHARDED bucketed execution path (parallel/sharding.
# make_sharded_bucketed_run) over the local device mesh, the same
# compiled unit scripts/run_multihost.py --engine bucketed dispatches
# per process. Parent-safe like POWERLAW_FULL_N; capped runs are
# labeled by what ran. Capped N must stay a multiple of
# scenarios.POWERLAW_MH_ALIGN (64) — the aligned partition is the
# point of the family.
POWERLAW_MH_FULL_N = {"powerlaw_100k_mh": 131_072,
                      "powerlaw_10m_mh": 10_485_760}


def _label(name: str) -> str:
    if name == "headline":
        return f"{_headline_n() // 1000}k_default"
    if name == "fleet_256x1k":
        # the label reflects what ACTUALLY ran (GRAFT_FLEET_SIZE lanes at
        # the BENCH_MAX_N-capped member size) so a reduced contract run
        # can never be banked under the full-shape label
        return f"fleet_{_fleet_b()}x{_fleet_n() // 1000}k"
    if name in FRONTIER_FULL_N:
        # a BENCH_MAX_N-capped frontier line is labeled by what ran —
        # a reduced-N contract run can never bank under the full label
        full = FRONTIER_FULL_N[name]
        n = _cap_peers(full)
        return name if n == full else f"{name}_capped_{n // 1000}k"
    if name in TELEMETRY_FULL_N:
        # same capped-label discipline as the frontier family
        full = TELEMETRY_FULL_N[name]
        n = _cap_peers(full)
        return name if n == full else f"{name}_capped_{n // 1000}k"
    if name in ATTACK_FULL_N:
        # same capped-label discipline for the attack family
        full = ATTACK_FULL_N[name]
        n = _cap_peers(full)
        return name if n == full else f"{name}_capped_{n // 1000}k"
    if name in POWERLAW_FULL_N:
        # same capped-label discipline for the heavy-tail family
        full = POWERLAW_FULL_N[name]
        n = _cap_peers(full)
        return name if n == full else f"{name}_capped_{n // 1000}k"
    if name in POWERLAW_MH_FULL_N:
        # same capped-label discipline for the row-sharded bucketed family
        full = POWERLAW_MH_FULL_N[name]
        n = _cap_peers(full)
        return name if n == full else f"{name}_capped_{n // 1000}k"
    if name in OVERLAP_FULL_N:
        # same capped-label discipline for the supervised-overlap pair
        full = OVERLAP_FULL_N[name]
        n = _cap_peers(full)
        return name if n == full else f"{name}_capped_{n // 1000}k"
    if name in INGEST_FULL_N:
        # same capped-label discipline for the live-command-plane pair
        full = INGEST_FULL_N[name]
        n = _cap_peers(full)
        return name if n == full else f"{name}_capped_{n // 1000}k"
    if name in VERDICT_FULL_N:
        # same capped-label discipline for the verdict-plane pair
        full = VERDICT_FULL_N[name]
        n = _cap_peers(full)
        return name if n == full else f"{name}_capped_{n // 1000}k"
    return name


def _probe_default_platform() -> bool:
    """True when the default JAX backend initializes and computes within a
    bounded time. The remote-TPU tunnel in this environment can wedge so
    hard that waiting would yield only timeout zeros; benching on CPU then
    still yields real numbers (tagged with platform=cpu)."""
    from go_libp2p_pubsub_tpu.utils.platform_probe import probe_default_platform
    return probe_default_platform()[0]


def _ordered(names: list) -> list:
    """Headline FIRST — banked before any later config can eat the budget
    (VERDICT r5: headline-last made the north-star number the timeout's
    first casualty); the re-emit below restores the headline-last parse."""
    return [s for s in names if s == "headline"] + \
        [s for s in names if s != "headline"]


def _is_headline_line(line: str) -> bool:
    prefix = f"network_heartbeats_per_sec@{_label('headline')}"
    try:
        return str(json.loads(line).get("metric", "")).startswith(prefix)
    except json.JSONDecodeError:
        return False


# env knobs that change what a bench line MEANS: a journaled line only
# stands in for a fresh run when all of these match the recording env
_JOURNAL_ENV_KEYS = ("BENCH_N", "BENCH_MAX_N", "BENCH_TICKS",
                     "BENCH_REPEATS", "BENCH_K", "GRAFT_EDGE_GATHER",
                     "GRAFT_HOP_MODE", "GRAFT_SELECTION",
                     "GRAFT_COUNT_DTYPE", "GRAFT_FAULT_PLAN",
                     "GRAFT_INVARIANT_MODE", "GRAFT_DISPATCH_TABLE",
                     "GRAFT_FLEET_SIZE")


def _journal_env() -> dict:
    return {k: os.environ.get(k, "") for k in _JOURNAL_ENV_KEYS}


def _journal_load() -> dict:
    """BENCH_JOURNAL records: {scenario_name: record}. A torn tail line
    (kill mid-append) is skipped — its config just re-runs. Records whose
    env fingerprint doesn't match the CURRENT env are dropped: a line
    journaled under different BENCH_*/GRAFT_* knobs must not stand in for
    this run's config."""
    path = os.environ.get("BENCH_JOURNAL")
    recs: dict = {}
    env_now = _journal_env()
    if path and os.path.exists(path):
        with open(path) as f:
            for ln in f:
                try:
                    r = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if "scenario" in r and "line" in r \
                        and r.get("env") == env_now:
                    recs[r["scenario"]] = r
    return recs


def _journal_append(name: str, line: str) -> None:
    path = os.environ.get("BENCH_JOURNAL")
    if not path:
        return
    try:
        platform = json.loads(line).get("platform", "")
    except json.JSONDecodeError:
        platform = ""
    with open(path, "a") as f:
        f.write(json.dumps({"scenario": name, "line": line,
                            "platform": platform,
                            "env": _journal_env()}) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _partial_headline(reason: str) -> str:
    return json.dumps({
        "metric": f"network_heartbeats_per_sec@{_label('headline')}",
        "value": 0.0, "unit": "heartbeats/s", "vs_baseline": 0.0,
        "error": reason, "partial": True})


def _install_flush_handlers(ctx: dict) -> None:
    """On SIGTERM/SIGINT, flush a partial-but-parseable record: the
    configs completed so far plus the banked headline line (or a
    headline-shaped error line marked partial) — the round-5 rc=124
    empty-record failure class becomes structurally impossible."""

    def _flush(signum, frame):
        try:
            sig = signal.Signals(signum).name
            print(json.dumps({"partial": True, "signal": sig,
                              "completed": list(ctx["completed"])}),
                  flush=True)
            print(ctx.get("headline_line")
                  or _partial_headline(f"interrupted:{sig}"), flush=True)
        finally:
            os._exit(128 + signum)     # re-entrancy-safe mid-subprocess

    signal.signal(signal.SIGTERM, _flush)
    signal.signal(signal.SIGINT, _flush)


def main() -> None:
    only = os.environ.get("BENCH_SCENARIOS")
    names = _ordered([s for s in NAMES
                      if not only or s in set(only.split(","))])
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", 1200))
    t_start = time.perf_counter()
    headline_line = None
    ctx = {"completed": [], "headline_line": None}
    _install_flush_handlers(ctx)
    if os.environ.get("BENCH_IN_PROC"):
        for name in names:
            line = run_scenario(name)
            ctx["completed"].append(_label(name))
            if name == "headline" and line and len(names) > 1:
                headline_line = line
                ctx["headline_line"] = line
        if headline_line:
            print(headline_line, flush=True)
        return
    def cpu_fallback_env():
        from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env
        # CPU is far slower per tick at 100k; keep the measured window
        # short so scenarios fit the per-scenario timeout
        env = cpu_mesh_env({})
        env["BENCH_TICKS"] = os.environ.get("BENCH_TICKS", "10")
        return env

    fallback_env = {}
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        pass        # CPU cannot wedge on the tunnel; skip the probe cost
    elif not _probe_default_platform():
        print(json.dumps({"warning": "default platform unreachable; "
                          "benching on CPU"}), flush=True)
        fallback_env = cpu_fallback_env()
    # one subprocess per scenario: a platform slowdown or OOM in one config
    # cannot taint the others' measurements
    journal = _journal_load()
    # a CPU-fallback line may only stand in when THIS run is also on the
    # CPU path (pinned or probed-down) — a stale wedged-window journal
    # must never mask a live TPU window's fresh numbers
    cpu_run = os.environ.get("JAX_PLATFORMS") == "cpu" or bool(fallback_env)
    for i, name in enumerate(names):
        rec = journal.get(name)
        if rec is not None and (rec.get("platform") != "cpu" or cpu_run):
            # resumable journal: a config recorded by a previous (killed)
            # invocation replays its line verbatim instead of re-running
            line = rec["line"]
            print(json.dumps({"info": "journal skip",
                              "scenario": _label(name)}), flush=True)
            print(line, flush=True)
            ctx["completed"].append(_label(name))
            if name == "headline" and _is_headline_line(line):
                headline_line = line
                ctx["headline_line"] = line
            continue
        elapsed = time.perf_counter() - t_start
        remaining = budget - elapsed
        # budget pressure: when the remaining budget per remaining config
        # drops below HALF the uniform share, degrade repeats 3 -> 1 for
        # this config rather than dropping it (a config is NEVER skipped —
        # every scenario emits a line, metric or error). Half-share, not a
        # cumulative linear schedule: the deliberately-expensive headline
        # runs first and must not push the cheap configs behind it down to
        # 1 repeat while plenty of budget remains for them.
        degrade = i > 0 and \
            remaining < (len(names) - i) * budget / (2 * len(names))
        budget_env = {}
        if degrade and int(os.environ.get("BENCH_REPEATS", 3)) > 1:
            budget_env["BENCH_REPEATS"] = "1"
            print(json.dumps({
                "info": "budget degrade", "scenario": _label(name),
                "elapsed_s": round(elapsed, 1), "budget_s": budget,
                "repeats": 1}), flush=True)
        # per-config deadline: GRAFT_DEADLINE_S (the supervisor knob
        # family) overrides BENCH_TIMEOUT; both yield to remaining budget
        scenario_timeout = int(min(
            float(os.environ.get("GRAFT_DEADLINE_S",
                                 os.environ.get("BENCH_TIMEOUT", 900))),
            max(60.0, remaining)))
        attempts = 0
        metric_line = None
        while True:
            attempts += 1
            env = dict(os.environ, BENCH_SCENARIOS=name, BENCH_IN_PROC="1",
                       **fallback_env, **budget_env)
            if name in POWERLAW_MH_FULL_N:
                # the row-sharded bucketed line needs a real mesh: on a
                # CPU host, force 8 virtual devices (8 divides the
                # POWERLAW_MH_ALIGN=64 bucket alignment, so every bucket
                # row-splits evenly; a TPU backend ignores this flag —
                # it sizes only the cpu platform)
                flags = env.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in flags:
                    env["XLA_FLAGS"] = (
                        flags + " --xla_force_host_platform_device_count"
                        "=8").strip()
            if name == "fleet_256x1k":
                # fleet lanes map onto local devices (sim/fleet.py
                # shard_fleet): on a multi-core CPU host, force a host
                # device mesh so B lanes run cores-wide in parallel — the
                # CPU realization of the fleet's throughput multiplier
                # (a TPU backend ignores this flag; it sizes only the cpu
                # platform)
                cores = os.cpu_count() or 1
                flags = env.get("XLA_FLAGS", "")
                if cores > 1 and "xla_force_host_platform_device_count" \
                        not in flags:
                    env["XLA_FLAGS"] = (
                        flags + " --xla_force_host_platform_device_count"
                        f"={cores}").strip()
            err = ""
            try:
                res = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)], env=env,
                    capture_output=True, text=True,
                    timeout=scenario_timeout)
                for line in res.stdout.splitlines():
                    if line.startswith("{"):
                        print(line, flush=True)
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            rec = {}
                        if "metric" in rec and "error" not in rec:
                            metric_line = line
                        if name == "headline" and _is_headline_line(line):
                            headline_line = line
                            ctx["headline_line"] = line
                if res.returncode != 0:
                    err = res.stderr.strip()[-300:] or f"rc={res.returncode}"
            except subprocess.TimeoutExpired:
                err = "timeout"
            if err == "timeout" and not fallback_env and attempts == 1 \
                    and not _probe_default_platform():
                # the tunnel wedged MID-RUN (round-2 failure mode: every
                # backend init hangs): finish the suite on CPU instead of
                # timing out zeros for every remaining scenario
                print(json.dumps({"warning": "default platform wedged "
                                  "mid-run; continuing on CPU"}), flush=True)
                fallback_env = cpu_fallback_env()
                continue
            break
        if err:
            err_line = json.dumps({
                "metric": f"network_heartbeats_per_sec@{_label(name)}",
                "value": 0.0, "unit": "heartbeats/s",
                "vs_baseline": 0.0, "error": err})
            print(err_line, flush=True)
            if name == "headline" and headline_line is None:
                # even a FAILED headline re-emits last: the driver's
                # single-line parse must land on the headline's own line
                # (error and all), never on another config's metric
                headline_line = err_line
                ctx["headline_line"] = err_line
        else:
            ctx["completed"].append(_label(name))
            if metric_line:
                # only SUCCESSFUL lines are journaled: a failed config
                # re-runs on the next invocation instead of being skipped
                _journal_append(name, metric_line)
    if headline_line and len(names) > 1:
        # re-emit the banked headline line LAST: the driver's single-line
        # stdout parse still lands on the north-star number
        print(headline_line, flush=True)


if __name__ == "__main__":
    main()
