"""Headline benchmark: simulated gossipsub heartbeats/sec at large N.

Runs the full batched network step (publish + decay + heartbeat mesh
maintenance + scoring + propagation + gossip) on the default accelerator and
prints ONE JSON line. ``vs_baseline`` is value / 1000 — the BASELINE.json
north-star target of >= 1000 full-network heartbeats/sec at 100k peers
(the reference router runs 1 heartbeat/sec/node in real time and publishes
no benchmarks; see BASELINE.md).

Env overrides: BENCH_N (peers, default 100_000), BENCH_TICKS (default 30).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TARGET_HBPS = 1000.0


def main() -> None:
    import jax

    n = int(os.environ.get("BENCH_N", 100_000))
    ticks = int(os.environ.get("BENCH_TICKS", 30))

    from __graft_entry__ import _build
    from go_libp2p_pubsub_tpu.sim.engine import run

    cfg, tp, st = _build(n_peers=n, k_slots=32, degree=12, msg_window=64,
                         publishers=8)
    key = jax.random.PRNGKey(0)

    # warmup with the SAME n_ticks (static jit arg): compiles the measured
    # program and converges the mesh, so the timed window is execution only
    st = run(st, cfg, tp, key, ticks)
    st.tick.block_until_ready()

    t0 = time.perf_counter()
    st = run(st, cfg, tp, key, ticks)
    st.tick.block_until_ready()
    dt = time.perf_counter() - t0

    hbps = ticks / dt
    platform = jax.devices()[0].platform
    print(json.dumps({
        "metric": f"gossipsub_network_heartbeats_per_sec@{n}peers[{platform}]",
        "value": round(hbps, 2),
        "unit": "heartbeats/s",
        "vs_baseline": round(hbps / TARGET_HBPS, 4),
    }))


if __name__ == "__main__":
    main()
