"""Benchmarks: simulated gossipsub heartbeats/sec across the BASELINE configs.

Runs the full batched network step (publish + decay + heartbeat mesh
maintenance + scoring + propagation + gossip) on the default accelerator and
prints ONE JSON line per config — the headline 100k-peer default-gossipsub
line prints LAST. ``vs_baseline`` is value / 1000, the BASELINE.json
north-star target of >= 1000 full-network heartbeats/sec at 100k peers
(the reference router runs 1 heartbeat/sec/node in real time and publishes
no benchmarks; see BASELINE.md).

Configs (BASELINE.json `configs`, built in sim/scenarios.py):
  1. 1k-peer single-topic gossipsub, default score params
  2. 10k-peer Ethereum-beacon-style topics + scoring
  3. 50k-peer multi-topic with peer gater + backoff churn + PX
  4. 100k-peer mesh with 20% sybil attackers
  5. 100k-peer floodsub / randomsub / gossipsub propagation sweep

Env overrides: BENCH_N (peers for the headline config, default 100_000),
BENCH_TICKS (default 30), BENCH_SCENARIOS (comma list to filter; "headline"
names the final line).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TARGET_HBPS = 1000.0


def bench_one(name, cfg, tp, st, ticks):
    import jax
    from go_libp2p_pubsub_tpu.sim.engine import delivery_fraction, run

    k_warm, k_meas = jax.random.split(jax.random.PRNGKey(0))
    # warmup with the SAME n_ticks (static jit arg): compiles the measured
    # program and converges the mesh; the measured window uses a DIFFERENT
    # key so it is not a cache-friendly replay of the warmup traffic
    st = run(st, cfg, tp, k_warm, ticks)
    st.tick.block_until_ready()

    t0 = time.perf_counter()
    st = run(st, cfg, tp, k_meas, ticks)
    st.tick.block_until_ready()
    dt = time.perf_counter() - t0

    hbps = ticks / dt
    platform = jax.devices()[0].platform
    print(json.dumps({
        "metric": f"network_heartbeats_per_sec@{name}[{platform}]",
        "value": round(hbps, 2),
        "unit": "heartbeats/s",
        "vs_baseline": round(hbps / TARGET_HBPS, 4),
        "delivery_fraction": round(float(delivery_fraction(st, cfg)), 4),
        "n_peers": cfg.n_peers,
    }), flush=True)


def main() -> None:
    from go_libp2p_pubsub_tpu.sim import scenarios

    n = int(os.environ.get("BENCH_N", 100_000))
    ticks = int(os.environ.get("BENCH_TICKS", 30))
    only = os.environ.get("BENCH_SCENARIOS")
    only = set(only.split(",")) if only else None

    def headline():
        from __graft_entry__ import _build
        return _build(n_peers=n, k_slots=32, degree=12, msg_window=64,
                      publishers=8)

    specs = [
        ("1k_single_topic", scenarios.single_topic_1k),
        ("10k_beacon", scenarios.beacon_10k),
        ("50k_churn_gater_px", scenarios.churn_50k),
        ("100k_sybil20", scenarios.sybil_100k),
        ("100k_floodsub", lambda: scenarios.router_sweep_100k("floodsub")),
        ("100k_randomsub", lambda: scenarios.router_sweep_100k("randomsub")),
        ("100k_gossipsub_sweep", lambda: scenarios.router_sweep_100k("gossipsub")),
        # headline last: a single-line parse of stdout picks this one up
        ("headline", headline),
    ]
    for name, build in specs:
        if only and name not in only:
            continue
        cfg, tp, st = build()
        label = f"{cfg.n_peers // 1000}k_default" if name == "headline" else name
        bench_one(label, cfg, tp, st, ticks)


if __name__ == "__main__":
    main()
