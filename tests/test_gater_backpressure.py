"""Batched-sim admission control: peer gater, validation throttling, edge
queue capacity, IGNORE verdicts, and the vectorized IWANT budget.

References modeled: peer_gater.go:119-363 (RED drop on throttled/validated),
validation.go:246-260 (queue drop-on-full), comm.go:156-191 +
gossipsub.go:1195-1202 (per-peer queue drop-on-full), validation.go:344-370
(IGNORE vs REJECT), gossipsub.go:654-676 (iasked budget).
"""

import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu.ops.gater import accept_data, gater_decay
from go_libp2p_pubsub_tpu.ops.propagate import _budgeted_iwant
from go_libp2p_pubsub_tpu.ops.bits import pack_bool, n_words
from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology
from go_libp2p_pubsub_tpu.sim.engine import delivery_fraction, run


def _run(cfg, malicious=None, ticks=40, seed=0):
    topo = topology.sparse(cfg.n_peers, cfg.k_slots, degree=6, seed=3)
    st = init_state(cfg, topo, malicious=malicious)
    tp = TopicParams.disabled(cfg.n_topics)
    return run(st, cfg, tp, jax.random.PRNGKey(seed), ticks)


class TestPeerGater:
    def _cfg(self, **kw):
        base = dict(
            n_peers=64, k_slots=16, n_topics=1, msg_window=32,
            publishers_per_tick=4, prop_substeps=4, scoring_enabled=False,
            gater_enabled=True, validation_queue_cap=3,
            gater_quiet_ticks=10)
        base.update(kw)
        return SimConfig(**base)

    def test_red_formula_collapses_spam_source(self):
        """Slot with reject-heavy stats is admitted far less often than a
        deliver-heavy slot once the gate is on (peer_gater.go:340-359)."""
        cfg2 = SimConfig(n_peers=2, k_slots=2, n_topics=1, msg_window=8,
                         gater_enabled=True, gater_quiet_ticks=10)
        topo = topology.sparse(2, 2, degree=1, seed=0)
        st = init_state(cfg2, topo)
        st = st._replace(
            tick=jnp.int32(100),
            gater_last_throttle=jnp.full(2, 99, jnp.int32),   # throttling now
            gater_throttle=jnp.full(2, 10.0),
            gater_validate=jnp.full(2, 20.0),                 # ratio 0.5 > 0.33
            gater_deliver=jnp.asarray([[20.0, 0.0]] * 2),
            gater_reject=jnp.asarray([[0.0, 10.0]] * 2))
        draws = np.stack([np.asarray(accept_data(st, cfg2, jax.random.PRNGKey(i)))
                          for i in range(200)])
        rate_good = draws[:, 0, 0].mean()
        rate_spam = draws[:, 0, 1].mean()
        assert rate_good == 1.0                               # p = 21/21
        assert rate_spam < 0.05, rate_spam                    # p = 1/161

    def test_spam_source_acceptance_collapses(self):
        """End-to-end: sybil-facing slots accumulate rejects and admit less
        than honest-facing slots (peer_gater.go:320-363 AcceptFrom)."""
        cfg = self._cfg()
        rng = np.random.default_rng(1)
        malicious = rng.random(cfg.n_peers) < 0.25
        st = _run(cfg, malicious=malicious, ticks=60)

        # gate must have engaged: throttle events happened
        assert float(jnp.max(st.gater_throttle)) > 0

        total = (st.gater_deliver
                 + cfg.gater_duplicate_weight * st.gater_duplicate
                 + cfg.gater_ignore_weight * st.gater_ignore
                 + cfg.gater_reject_weight * st.gater_reject)
        p = (1.0 + st.gater_deliver) / (1.0 + total)
        nbr = np.clip(np.asarray(st.neighbors), 0, cfg.n_peers - 1)
        valid = np.asarray(st.neighbors) >= 0
        is_mal = malicious[nbr] & valid
        is_hon = ~malicious[nbr] & valid
        # honest observers only (sybils' own stats are meaningless)
        obs = ~malicious
        p = np.asarray(p)
        rej = np.asarray(st.gater_reject)
        # rejects concentrate on sybil-facing slots
        assert rej[obs][is_mal[obs]].mean() > 5 * max(rej[obs][is_hon[obs]].mean(), 1e-6)
        p_mal = p[obs][is_mal[obs]].mean()
        p_hon = p[obs][is_hon[obs]].mean()
        assert p_mal < 0.75 * p_hon, (p_mal, p_hon)

    def test_gate_off_when_quiet(self):
        """After the quiet period with no throttling, everything is admitted
        regardless of stats (peer_gater.go:324-327)."""
        cfg = self._cfg(validation_queue_cap=0)   # nothing ever throttles
        st = _run(cfg, ticks=30)
        adm = accept_data(st, cfg, jax.random.PRNGKey(7))
        assert bool(jnp.all(adm))

    def test_decay_shrinks_stats(self):
        cfg = self._cfg()
        st = _run(cfg, ticks=30)
        st2 = gater_decay(st, cfg)
        assert float(jnp.sum(st2.gater_deliver)) <= float(jnp.sum(st.gater_deliver))
        assert float(jnp.sum(st2.gater_throttle)) <= float(jnp.sum(st.gater_throttle))


class TestValidationThrottle:
    def test_throttle_counts_and_drops(self):
        """Arrivals beyond validation_queue_cap are dropped unseen and counted
        (validation.go:246-260)."""
        cfg = SimConfig(
            n_peers=64, k_slots=16, n_topics=1, msg_window=32,
            publishers_per_tick=16, prop_substeps=4, scoring_enabled=False,
            gater_enabled=True, validation_queue_cap=2)
        st = _run(cfg, ticks=30)
        assert float(jnp.sum(st.gater_throttle)) > 0
        # uncapped twin delivers strictly more
        cfg_free = SimConfig(**{**cfg.__dict__, "validation_queue_cap": 0})
        st_free = _run(cfg_free, ticks=30)
        assert float(st_free.delivered_total) > float(st.delivered_total)


class TestEdgeQueueCap:
    def test_capacity_drops_deliveries(self):
        """An edge budget far under the traffic rate loses deliveries the way
        the reference's full per-peer queues do (comm.go:156-191)."""
        base = dict(
            n_peers=64, k_slots=16, n_topics=1, msg_window=32,
            publishers_per_tick=12, prop_substeps=4, scoring_enabled=False)
        st_capped = _run(SimConfig(**base, edge_queue_cap=1), ticks=30)
        st_free = _run(SimConfig(**base), ticks=30)
        frac_capped = float(delivery_fraction(st_capped, SimConfig(**base)))
        frac_free = float(delivery_fraction(st_free, SimConfig(**base)))
        assert frac_capped < frac_free
        assert frac_capped > 0.0     # some traffic still flows


class TestIgnoreVerdict:
    def test_ignored_seen_not_delivered_no_p4(self):
        """IGNORE: marked seen, never delivered, no P4, gater ignore stat
        (validation.go:344-370)."""
        cfg = SimConfig(
            n_peers=32, k_slots=8, n_topics=1, msg_window=16,
            publishers_per_tick=2, prop_substeps=4, scoring_enabled=False,
            gater_enabled=True, ignore_fraction=1.0)
        st = _run(cfg, ticks=10)
        # every message was ignore-class: only its publisher ever delivers it
        live = np.asarray(st.msg_topic) >= 0
        dlv = np.asarray(st.deliver_tick) < 2**30
        assert dlv[:, live].sum(axis=0).max() <= 1
        # but neighbors did SEE them (marked seen)
        from go_libp2p_pubsub_tpu.sim.state import unpack_have
        have = np.asarray(unpack_have(st, cfg.msg_window))
        assert have[:, live].sum() > dlv[:, live].sum()
        assert float(jnp.sum(st.invalid_message_deliveries)) == 0.0
        assert float(jnp.sum(st.gater_ignore)) > 0.0


class TestBudgetedIwant:
    def test_per_slot_budget_respected(self):
        """Each slot is asked at most ``budget`` ids; spillover goes to the
        next offering slot (gossipsub.go:654-676)."""
        m, k, n = 32, 2, 1
        w = n_words(m)
        offers = np.zeros((k, n, m), dtype=bool)
        offers[0, 0, [0, 1, 2]] = True       # slot 0 offers 0,1,2
        offers[1, 0, [1, 2, 3]] = True       # slot 1 offers 1,2,3
        offer = jnp.stack([pack_bool(offers[s]).T for s in range(k)], axis=1)
        have = jnp.zeros((w, n), jnp.uint32)
        pend = np.asarray(_budgeted_iwant(offer, have, m, budget=2))[0]
        assert pend[0] == 0 and pend[1] == 0          # slot 0's two
        assert pend[2] == 1 and pend[3] == 1          # spill to slot 1
        assert (pend[4:] == -1).all()
        # each slot asked <= budget
        counts = np.bincount(pend[pend >= 0], minlength=k)
        assert (counts <= 2).all()

    def test_unbudgeted_equivalence(self):
        """With budget >= M the scan picks the lowest offering slot, matching
        the fast path's semantics."""
        m, k, n = 16, 3, 4
        w = n_words(m)
        rng = np.random.default_rng(5)
        offers = rng.random((k, n, m)) < 0.4
        offer = jnp.stack([pack_bool(offers[s]).T for s in range(k)], axis=1)
        have = jnp.zeros((w, n), jnp.uint32)
        pend = np.asarray(_budgeted_iwant(offer, have, m, budget=m))
        for i in range(n):
            for mm in range(m):
                slots = [s for s in range(k) if offers[s, i, mm]]
                assert pend[i, mm] == (min(slots) if slots else -1)
