"""HLO-inspection guard (SNIPPETS [1]/[2] grep-the-IR pattern): with
``edge_gather_mode="mxu"`` + ``hop_mode="pallas-mxu"`` the lowered engine
step contains ZERO dense table gathers — the property that makes the mxu
mode immune to both the Mosaic 128-lane gather wall and the ~7 ns/index
XLA gather tax. If a scalar/rows formulation sneaks back into any seam
(a resolver regression, a new call site bypassing dispatch), this fails.

"Dense table gather" = a gather whose RESULT carries more than 4·N·T
elements: the serialized-HBM class routes N*K edge indices (32·N at the
headline K), while the benign per-row ops the engine legitimately keeps
(take_along_axis over the K-minor axis in selection/median, the P=8
publisher picks) stay at or under N·T. The threshold is checked against
a positive control — the scalar formulation MUST trip it — so the grep
can never silently match nothing."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology
from go_libp2p_pubsub_tpu.sim.engine import step


def _dense_gathers(text: str, thresh: int) -> list:
    """(result_elems, snippet) of every gather op in the StableHLO text
    whose result exceeds ``thresh`` elements."""
    out = []
    for m in re.finditer(
            r'"?stablehlo\.gather"?.*?-> tensor<([0-9x]+)x?[a-z]', text):
        dims = [int(d) for d in m.group(1).split("x") if d]
        elems = int(np.prod(dims)) if dims else 1
        if elems > thresh:
            out.append((elems, m.group(0)[:160]))
    return out


def _lowered_step_text(n: int, k: int, **overrides) -> tuple:
    cfg = SimConfig(n_peers=n, k_slots=k, n_topics=1, msg_window=64,
                    publishers_per_tick=4, prop_substeps=8,
                    scoring_enabled=True, **overrides)
    tp = TopicParams.disabled(1)
    st = init_state(cfg, topology.sparse(n, k, degree=12, seed=1))
    low = jax.jit(step, static_argnames=("cfg",)).lower(
        st, cfg, tp, jax.random.PRNGKey(0))
    return low.as_text(), cfg


def test_mxu_step_has_zero_dense_gathers():
    """Tier-1 guard at a lane-unfriendly-free shape (2048 = 16·128): the
    full step under the mxu modes lowers gather-free; the kernels run in
    interpret mode on CPU, so every in-kernel take appears as its real
    one-hot matmul formulation in the IR."""
    n, k = 2048, 32
    text, cfg = _lowered_step_text(n, k, edge_gather_mode="mxu",
                                   hop_mode="pallas-mxu")
    # the modes must actually resolve (not silently degrade to xla/scalar)
    from go_libp2p_pubsub_tpu.ops.dispatch import resolved_formulations
    resolved = resolved_formulations(cfg)
    assert resolved["hop"] == "pallas-mxu" and resolved["emit"] == "pallas-mxu"
    assert resolved["edge_packed"] == "mxu" and resolved["words"] == "mxu"
    assert resolved["edge_permute"] == "mxu"
    bad = _dense_gathers(text, 4 * n * cfg.n_topics)
    assert not bad, f"dense gathers sneaked back in: {bad[:5]}"


def test_scalar_control_trips_the_grep():
    """Positive control: the scalar word gather at the same shape MUST
    contain a dense gather, or the grep is matching nothing."""
    n, k, m = 2048, 32, 64
    from go_libp2p_pubsub_tpu.ops.permgather import gather_words
    words = jnp.zeros(((m + 31) // 32, n), jnp.uint32)
    nbr = jnp.zeros((n, k), jnp.int32)
    text = jax.jit(
        lambda x, i: gather_words(x, i, m, "scalar")).lower(
        words, nbr).as_text()
    assert _dense_gathers(text, 4 * n), \
        "control failed: scalar gather not visible to the grep"


@pytest.mark.slow
def test_headline_shape_has_zero_dense_gathers():
    """The acceptance-criteria shape: 100k-class peers (102400 — the
    128-friendly headline peer count every bench scenario uses,
    PERF_MODEL.md) × K=32. Slow tier: host-side topology build + the
    full-step lowering take minutes on CPU."""
    n, k = 102_400, 32
    text, cfg = _lowered_step_text(n, k, edge_gather_mode="mxu",
                                   hop_mode="pallas-mxu")
    from go_libp2p_pubsub_tpu.ops.dispatch import resolved_formulations
    resolved = resolved_formulations(cfg)
    assert resolved["hop"] == "pallas-mxu" and resolved["emit"] == "pallas-mxu"
    assert resolved["edge_packed"] == "mxu" and resolved["words"] == "mxu"
    bad = _dense_gathers(text, 4 * n * cfg.n_topics)
    assert not bad, f"dense gathers at the headline shape: {bad[:5]}"
