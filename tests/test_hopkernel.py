"""Parity: fused Pallas forwarding hop vs the XLA hop formulation.

The fused kernel (ops/hopkernel.py, PERF_MODEL.md S4) must be bit-identical
to the XLA hop — same frontier evolution, same seen/delivered sets, same
uint8 event counts feeding fmd/mmd/imd — at op level (one forward_tick) and
over full engine runs, including multi-topic shapes that cross the
per-topic expansion loop. Runs in interpret mode on the CPU test mesh.
"""

import dataclasses

import jax
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops.heartbeat import heartbeat
from go_libp2p_pubsub_tpu.ops.hopkernel import resolve_hop_mode
from go_libp2p_pubsub_tpu.ops.propagate import forward_tick
from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
from go_libp2p_pubsub_tpu.sim.engine import run
from go_libp2p_pubsub_tpu.sim.scenarios import default_topic_params


def _build(n=192, k=8, t=1, m=64, degree=5, **over):
    kw = dict(publishers_per_tick=4, prop_substeps=8, scoring_enabled=True)
    kw.update(over)
    cfg = SimConfig(n_peers=n, k_slots=k, n_topics=t, msg_window=m, **kw)
    tp = default_topic_params(t)
    st = init_state(cfg, topology.sparse(n, k, degree=degree))
    return cfg, tp, st


def _states_equal(a, b):
    for name in a._fields:
        va, vb = getattr(a, name), getattr(b, name)
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb),
                                      err_msg=name)


class TestHopKernelParity:
    @pytest.mark.parametrize("t", [1, 3])
    def test_forward_tick_identical(self, t):
        cfg, tp, st = _build(t=t)
        key = jax.random.PRNGKey(0)
        # converge a few ticks so the forward pass sees real traffic
        st = run(st, cfg, tp, key, 4)
        hb = heartbeat(st, cfg, tp, jax.random.PRNGKey(1))
        k2 = jax.random.PRNGKey(2)
        outs = {}
        for mode in ("xla", "pallas"):
            c = dataclasses.replace(cfg, hop_mode=mode)
            outs[mode] = forward_tick(hb.state, c, tp, hb.inc_gossip,
                                      hb.scores, k2, fwd_send=hb.fwd_send)
        _states_equal(outs["xla"], outs["pallas"])

    def test_full_run_identical(self):
        cfg, tp, st = _build()
        key = jax.random.PRNGKey(7)
        st_x = run(st, dataclasses.replace(cfg, hop_mode="xla"), tp, key, 8)
        st_p = run(st, dataclasses.replace(cfg, hop_mode="pallas"), tp, key, 8)
        _states_equal(st_x, st_p)
        # and the run actually delivered traffic (non-vacuous parity)
        assert float(st_p.delivered_total) > 0

    def _pull_heavy(self, **over):
        """A config where the gossip pull path actually fires: few eager
        hops leave peers missing messages, so IHAVE/IWANT traffic (and the
        S6/S7 kernels) carry real load — ~3k pending pulls over 8 ticks."""
        return _build(n=192, k=16, degree=14, prop_substeps=2,
                      publishers_per_tick=4, **over)

    def test_gossip_pull_path_identical_and_nonvacuous(self):
        """The fused IWANT-resolve (S6) and gossip-emit (S7) kernels must
        match the XLA formulations under REAL pull traffic."""
        import go_libp2p_pubsub_tpu.sim.engine as eng

        cfg, tp, st = self._pull_heavy()
        key = jax.random.PRNGKey(11)
        pulls = 0
        st_x, st_p = st, st
        for i in range(8):
            st_x = eng.step_jit(st_x, dataclasses.replace(cfg, hop_mode="xla"),
                                tp, jax.random.fold_in(key, i))
            st_p = eng.step_jit(st_p, dataclasses.replace(cfg, hop_mode="pallas"),
                                tp, jax.random.fold_in(key, i))
            pulls += int(np.sum(np.asarray(st_p.iwant_pending) >= 0))
        _states_equal(st_x, st_p)
        assert pulls > 500, f"pull path barely exercised: {pulls} pulls"

    def test_budgeted_iwant_identical(self):
        """The fused gossip-emit kernel's per-slot budget scan must match
        _budgeted_iwant exactly (MaxIHaveLength flood protection,
        gossipsub.go:654-676) — with a budget small enough to bind under
        real pull traffic."""
        cfg, tp, st = self._pull_heavy(max_iwant_per_tick=2)
        key = jax.random.PRNGKey(11)
        st_x = run(st, dataclasses.replace(cfg, hop_mode="xla"), tp, key, 8)
        st_p = run(st, dataclasses.replace(cfg, hop_mode="pallas"), tp, key, 8)
        _states_equal(st_x, st_p)

    def test_resolution_policy(self, monkeypatch):
        import go_libp2p_pubsub_tpu.ops.hopkernel as hk
        cfg, _, _ = _build()
        # auto keeps the XLA path on EVERY backend: Mosaic cannot lower
        # the >128-wide VMEM table gather (resolve_hop_mode docstring)
        assert resolve_hop_mode("auto", cfg, 2, 100_000, 32) == "xla"
        monkeypatch.setattr(hk.jax, "default_backend", lambda: "tpu")
        assert hk.resolve_hop_mode("auto", cfg, 2, 100_000, 32) == "xla"
        # explicit pallas resolves for eligible configs at aligned shapes
        assert hk.resolve_hop_mode("pallas", cfg, 2, 102_400, 32) == "pallas"
        # ineligible configs fall back even when pallas is requested
        for bad in (dict(gater_enabled=True), dict(record_provenance=True),
                    dict(edge_queue_cap=8), dict(validation_queue_cap=64),
                    dict(flood_publish=True)):
            c = dataclasses.replace(cfg, **bad)
            assert hk.resolve_hop_mode("pallas", c, 2, 102_400, 32) == "xla", bad

    def test_pallas_mxu_resolution_policy(self):
        import go_libp2p_pubsub_tpu.ops.hopkernel as hk
        cfg, _, _ = _build()
        # pallas-mxu resolves at lane-aligned peer counts, config gates
        # matching pallas; a non-128-multiple N falls back (the in-kernel
        # chunk-plane reshape, take_words_onehot)
        assert hk.resolve_hop_mode("pallas-mxu", cfg, 2, 102_400, 32) \
            == "pallas-mxu"
        assert hk.resolve_hop_mode("pallas-mxu", cfg, 2, 100_000, 32) == "xla"
        assert hk.resolve_emit_mode("pallas-mxu", 2, 102_400, 32) \
            == "pallas-mxu"
        assert hk.resolve_emit_mode("pallas-mxu", 2, 100_000, 32) == "xla"
        c = dataclasses.replace(cfg, gater_enabled=True)
        assert hk.resolve_hop_mode("pallas-mxu", c, 2, 102_400, 32) == "xla"
        with pytest.raises(ValueError):
            hk.resolve_hop_mode("mxu", cfg, 2, 1024, 32)


class TestPallasMxuParity:
    """hop_mode="pallas-mxu": the fused kernels with every in-kernel
    gather rewritten as the gather-free two-level one-hot select
    (ops/mxutake.take_words_onehot) — the S1-S7 resurrection candidate.
    Must be bit-identical to the XLA hop at a lane-aligned peer count."""

    def test_full_run_identical(self):
        cfg, tp, st = _build(n=256)
        key = jax.random.PRNGKey(7)
        st_x = run(st, dataclasses.replace(cfg, hop_mode="xla"), tp, key, 8)
        st_p = run(st, dataclasses.replace(cfg, hop_mode="pallas-mxu"),
                   tp, key, 8)
        _states_equal(st_x, st_p)
        assert float(st_p.delivered_total) > 0

    def test_pull_path_identical(self):
        """S6/S7 (IWANT resolve + gossip emit) under real pull traffic
        with a binding budget, gathers via the one-hot select."""
        cfg, tp, st = _build(n=256, k=16, degree=14, prop_substeps=2,
                             publishers_per_tick=4, max_iwant_per_tick=2)
        key = jax.random.PRNGKey(11)
        st_x = run(st, dataclasses.replace(cfg, hop_mode="xla"), tp, key, 8)
        st_p = run(st, dataclasses.replace(cfg, hop_mode="pallas-mxu"),
                   tp, key, 8)
        _states_equal(st_x, st_p)
        pulls = int(np.sum(np.asarray(st_p.iwant_pending) >= 0))
        assert pulls > 100, f"pull path barely exercised: {pulls} pulls"
