"""edge_gather_mode="mxu" end-to-end: the gather-free two-level MXU take
(ops/mxutake.py) as a first-class engine gather formulation.

The mode exists so the next TPU window can A/B sort-vs-mxu at the real
100k×K shapes with one env-var flip (GRAFT_EDGE_GATHER=mxu), so the CPU
tier must pin: (1) op-level bit-exactness of every word-table call site,
(2) full engine trajectories bit-identical to the sort mode — including a
shape whose N*K index count is NOT a multiple of the take's block_g, the
case the old kernel asserted away (mxutake.py r5) — and (3) the resolve
policy (word tables ride mxu; the generic payload permute rides the
blocked one-hot take; the IWANT answer table rides the exchange as
concatenated word rows — the mxu scalar tail is closed, ISSUE 6)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops.permgather import (
    resolve_edge_packed_mode,
    resolve_mode,
    resolve_words_mode,
)
from go_libp2p_pubsub_tpu.sim import (
    SimConfig,
    TopicParams,
    init_state,
    topology,
)
from go_libp2p_pubsub_tpu.sim.engine import run


class TestResolvePolicy:
    def test_word_tables_ride_mxu(self):
        # the take has no gather op, so no backend/Mosaic gate — only VMEM
        assert resolve_words_mode("mxu", 2, 100_000, 32) == "mxu"
        assert resolve_words_mode("mxu", 2, 102_400, 32) == "mxu"
        # table planes beyond the VMEM budget degrade to rows
        assert resolve_words_mode("mxu", 64, 10_000_000, 8) == "rows"
        # the chunk recombination is 4x-u8-exact: non-word dtypes degrade
        assert resolve_words_mode("mxu", 2, 1024, 8, itemsize=1) == "rows"

    def test_edge_exchange_rides_bit_table(self):
        assert resolve_edge_packed_mode("mxu", 100_000, 32, 2) == "mxu"
        assert resolve_edge_packed_mode("mxu", 10_240, 48, 18) == "mxu"
        # bit-table planes beyond the VMEM budget degrade to rows
        assert resolve_edge_packed_mode("mxu", 4_000_000, 32, 64) == "rows"

    def test_generic_payload_permute_rides_blocked_onehot(self):
        # the blocked/tiled one-hot variant (mxutake.take_payload_onehot)
        # closed the old degrade-to-scalar: any 4-byte payload rides mxu;
        # sub-word dtypes (no exact 4-u8-chunk recombination) still degrade
        assert resolve_mode("mxu", jnp.uint32, 100_000, 32) == "mxu"
        assert resolve_mode("mxu", jnp.float32, 256, 16) == "mxu"
        assert resolve_mode("mxu", jnp.bool_, 256, 16) == "scalar"

    def test_answer_ride_along_rides_the_mxu_exchange(self):
        """_iwant_answer_extras merges the IWANT answer gather into the
        heartbeat's final exchange under BOTH carrier formulations: sort
        (extra variadic-sort lanes) and now mxu (extra word rows
        concatenated onto the bit-table, one shared two-level take —
        the mode's last serialized self-gather closed). Non-carrier
        formulations still step aside."""
        from go_libp2p_pubsub_tpu.sim.engine import _iwant_answer_extras

        cfg = SimConfig(n_peers=256, k_slots=16, n_topics=1, msg_window=32,
                        edge_gather_mode="mxu")
        st = init_state(cfg, topology.sparse(256, 16, degree=6, seed=1))
        assert _iwant_answer_extras(st, cfg) is not None
        cfg_s = dataclasses.replace(cfg, edge_gather_mode="sort")
        assert _iwant_answer_extras(st, cfg_s) is not None
        for plain in ("scalar", "rows"):
            cfg_p = dataclasses.replace(cfg, edge_gather_mode=plain)
            assert _iwant_answer_extras(st, cfg_p) is None, plain


class TestOpParity:
    def test_gather_words_mxu_bit_identical(self):
        from go_libp2p_pubsub_tpu.ops.bits import (
            gather_words_rows, pack_words)

        rng = np.random.default_rng(3)
        for n, k in [(192, 8), (256, 16), (200, 12)]:   # incl. non-128 N
            m = 64
            planes = np.asarray(
                jax.random.uniform(jax.random.PRNGKey(n), (n, m)) < 0.3)
            x_w = pack_words(jnp.asarray(planes))
            nbr = jnp.asarray(rng.integers(0, n, (n, k)), jnp.int32)
            ref = gather_words_rows(x_w, nbr, m, "scalar")
            out = gather_words_rows(x_w, nbr, m, "mxu")
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(out),
                                          err_msg=f"n={n} k={k}")

    def test_edge_exchange_mxu_bit_identical(self):
        from types import SimpleNamespace

        from go_libp2p_pubsub_tpu.ops.heartbeat import edge_gather_packed

        rng = np.random.default_rng(7)
        n, k = 192, 8
        topo = topology.sparse(n, k, degree=5)
        st = SimpleNamespace(neighbors=jnp.asarray(topo.neighbors),
                             reverse_slot=jnp.asarray(topo.reverse_slot))
        for t, n_masks in ((3, 2), (12, 3)):   # 6 planes; 36 (2 groups)
            masks = [jnp.asarray(rng.random((n, t, k)) < 0.35)
                     for _ in range(n_masks)]
            ref = edge_gather_packed(masks, st, "scalar")
            got = edge_gather_packed(masks, st, "mxu")
            for r, g in zip(ref, got):
                np.testing.assert_array_equal(
                    np.asarray(r), np.asarray(g), err_msg=f"mxu t={t}")

    def test_payload_permute_mxu_bit_identical(self):
        """permutation_gather mode='mxu' (the blocked one-hot take) vs
        the scalar reference, u32 and f32, at a ragged shape."""
        from go_libp2p_pubsub_tpu.ops.permgather import permutation_gather

        rng = np.random.default_rng(13)
        n, k = 200, 12
        jn = jnp.asarray(rng.integers(0, n, (n, k)), jnp.int32)
        rk = jnp.asarray(rng.integers(0, k, (n, k)), jnp.int32)
        for pay in (jnp.asarray(rng.integers(0, 2**32, (n, k),
                                             dtype=np.uint64), jnp.uint32),
                    jnp.asarray(rng.normal(size=(n, k)), jnp.float32)):
            ref = permutation_gather(pay, jn, rk, "scalar")
            got = permutation_gather(pay, jn, rk, "mxu")
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(got),
                                          err_msg=str(pay.dtype))

    def test_extras_ride_along_mxu_bit_identical(self):
        """The mxu extras ride-along (concatenated word rows on the
        bit-table take) must reproduce the sort formulation's receiver
        views exactly — mask groups AND extras, invalid slots zeroed."""
        from types import SimpleNamespace

        from go_libp2p_pubsub_tpu.ops.heartbeat import edge_gather_packed

        rng = np.random.default_rng(17)
        n, k, t = 192, 8, 3
        topo = topology.sparse(n, k, degree=5)
        st = SimpleNamespace(neighbors=jnp.asarray(topo.neighbors),
                             reverse_slot=jnp.asarray(topo.reverse_slot))
        masks = [jnp.asarray(rng.random((n, t, k)) < 0.35)
                 for _ in range(2)]
        tab = jnp.asarray(rng.integers(0, 2**32, (2, n), dtype=np.uint64),
                          jnp.uint32)
        res_s, ex_s = edge_gather_packed(masks, st, "sort",
                                         extra_words=[tab])
        res_m, ex_m = edge_gather_packed(masks, st, "mxu",
                                         extra_words=[tab])
        for a, b in zip(res_s, res_m):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(ex_s, ex_m):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestEngineTrajectory:
    """run(..., cfg) with the mxu mode must produce bit-identical
    trajectories to the sort mode — the acceptance bar for wiring the
    take into the engine (VERDICT r5 item 3)."""

    # two bench-shaped configs: N*K = 4096 divides the take's block_g
    # (1024); N*K = 2304 does NOT — the pad path the old kernel refused
    SHAPES = [
        ("block_aligned", 256, 16),
        ("block_ragged", 192, 12),
    ]

    @pytest.mark.parametrize("label,n,k", SHAPES)
    def test_mxu_equals_sort(self, label, n, k):
        cfg = SimConfig(n_peers=n, k_slots=k, n_topics=2, msg_window=32,
                        publishers_per_tick=4, prop_substeps=4,
                        scoring_enabled=True)
        tp = TopicParams.disabled(2)
        st0 = init_state(cfg, topology.sparse(n, k, degree=6, seed=n))
        key = jax.random.PRNGKey(11)
        st_sort = run(st0, dataclasses.replace(cfg, edge_gather_mode="sort"),
                      tp, key, 5)
        st_mxu = run(st0, dataclasses.replace(cfg, edge_gather_mode="mxu"),
                     tp, key, 5)
        for name, a, b in zip(st_sort._fields, st_sort, st_mxu):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"{label}: state.{name} diverged")

    def test_mxu_under_churn_and_gater(self):
        """Churn + gater + flood-publish: every degrade seam fires in one
        run (payload permute -> scalar, answer ride-along -> None, flood
        sender-score gather -> scalar) and the trajectory still matches."""
        cfg = SimConfig(n_peers=192, k_slots=16, n_topics=2, msg_window=32,
                        publishers_per_tick=4, prop_substeps=4,
                        scoring_enabled=True, gater_enabled=True,
                        flood_publish=True,
                        churn_disconnect_prob=0.05, churn_reconnect_prob=0.3)
        tp = TopicParams.disabled(2)
        st0 = init_state(cfg, topology.sparse(192, 16, degree=6, seed=21))
        key = jax.random.PRNGKey(31)
        st_a = run(st0, dataclasses.replace(cfg, edge_gather_mode="scalar"),
                   tp, key, 6)
        st_b = run(st0, dataclasses.replace(cfg, edge_gather_mode="mxu"),
                   tp, key, 6)
        for name, a, b in zip(st_a._fields, st_a, st_b):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)
