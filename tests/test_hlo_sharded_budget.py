"""HLO-inspection guard for the SHARDED step (SNIPPETS [1]/[2] grep-the-IR
pattern, the sharded sibling of test_hlo_gatherfree.py): with
``edge_gather_mode="sort"`` + ``sharded_route="halo"`` the compiled
8-device step contains NO all-gather or dynamic-slice whose result exceeds
the packed bit-table budget — the property that keeps the per-tick
exchange at ~bit-table bytes over ICI instead of dense [N,K]/[N,T,K]
payload all-gathers (PERF_MODEL's ~10 MB/tick packed vs ~140 MB/tick
dense at 1M peers). If a dense collective sneaks back into any seam (a
new exchange bypassing the halo route, a partitioner regression), this
fails by op.

Budget: 4·N·⌈K/32⌉ 32-bit words of result elements. The legitimate
collectives stay well under it — the replicated [W, N] message tables
(W·N ≤ 2N at the bench window), the [N, T] subscribed gather for
publisher choice (T·N), per-bucket all_to_all sends (capacity-padded
local shapes) — while any replicated global sort or dense payload
all-gather carries N·K = 8N+ elements and trips it. The threshold is
checked against a positive control: the ``replicated`` route at the same
shape MUST trip, so the grep can never silently match nothing.

The guard config deliberately turns on every plane that exchanges
cross-peer state — scoring, churn + PX, flood publish, the gater — so
each wired seam (heartbeat packed exchange, forward/gossip word routes,
churn symmetric bits, flood score exchange) is inside the lowered
program.
"""

import re

import jax
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.parallel.sharding import (
    make_mesh, make_sharded_step, shard_state)
from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology

N, K, T, M = 256, 16, 2, 64


def _build(route: str):
    cfg = SimConfig(
        n_peers=N, k_slots=K, n_topics=T, msg_window=M,
        publishers_per_tick=4, prop_substeps=4,
        scoring_enabled=True, behaviour_penalty_weight=-1.0,
        gossip_threshold=-10.0, publish_threshold=-20.0,
        graylist_threshold=-30.0,
        churn_disconnect_prob=0.02, churn_reconnect_prob=0.2,
        px_enabled=True, accept_px_threshold=-5.0, retain_score_ticks=10,
        flood_publish=True, gater_enabled=True,
        edge_gather_mode="sort", sharded_route=route)
    tp = TopicParams.disabled(T)
    st = init_state(cfg, topology.sparse(N, K, degree=6, seed=11))
    return cfg, tp, st


def _dense_collectives(text: str, thresh: int) -> list:
    """(result_elems, snippet) of every all-gather / dynamic-slice in the
    compiled HLO whose result exceeds ``thresh`` elements. Tuple-shaped
    results (variadic all-gather) count each component."""
    out = []
    for m in re.finditer(
            r"= *\(?((?:[a-z][a-z0-9]*\[[0-9,]*\][^ ,()]*(?:, *)?)+)\)? "
            r"(all-gather|dynamic-slice)\(", text):
        elems = 0
        for shape in re.findall(r"\[([0-9,]*)\]", m.group(1)):
            dims = [int(d) for d in shape.split(",") if d]
            elems += int(np.prod(dims)) if dims else 1
        if elems > thresh:
            out.append((elems, m.group(0)[:160]))
    return out


def _compiled_step_text(route: str) -> str:
    cfg, tp, st = _build(route)
    mesh = make_mesh(jax.devices()[:8])
    sharded_step = make_sharded_step(mesh, cfg, tp)
    st_sh = shard_state(st, mesh, cfg)
    return sharded_step.lower(st_sh, jax.random.PRNGKey(0)).compile().as_text()


BUDGET = 4 * N * ((K + 31) // 32)       # packed bit-table words


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (conftest XLA_FLAGS)")
    return jax.devices()[:8]


def test_halo_step_within_packed_budget(eight_devices):
    """The acceptance guard: every all-gather/dynamic-slice result in the
    halo-routed sharded step fits the packed budget."""
    text = _compiled_step_text("halo")
    bad = _dense_collectives(text, BUDGET)
    assert not bad, (
        f"dense collectives above the packed budget ({BUDGET} words) "
        f"sneaked into the halo-routed step: {bad[:5]}")


def test_replicated_control_trips_the_grep(eight_devices):
    """Positive control: the replicated route's global sorts all-gather
    full [N*K] payloads — they MUST exceed the budget, or the grep is
    matching nothing."""
    text = _compiled_step_text("replicated")
    bad = _dense_collectives(text, BUDGET)
    assert bad, ("control failed: the replicated-route step shows no "
                 "dense collective to the grep")
    assert max(e for e, _ in bad) >= N * K


def test_halo_step_within_packed_budget_2d_mesh(eight_devices, tmp_path):
    """The multihost layout, EXECUTED: the halo-routed step on the 2-D
    {'dcn': 2, 'peers': 4} make_mesh_2d mesh (a) runs 3 real ticks that
    match single-device execution — the DCN axis only changes WHERE
    shards live, never what they compute — (b) leaves the peer-major
    state genuinely split into 8 distinct row blocks across BOTH axes,
    and (c) still fits the packed-budget guard (the dump the grep below
    reads). Runs in a fresh subprocess: a second mesh in one process
    hits the backend multi-mesh poison test_sharding.py documents; the
    subprocess dumps the compiled HLO and the grep runs here."""
    import os
    import subprocess
    import sys

    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env

    hlo = tmp_path / "step_2d.hlo"
    code = f"""
import jax
jax.config.update("jax_platforms", "cpu")
import sys
import numpy as np
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from tests.test_hlo_sharded_budget import _build
from go_libp2p_pubsub_tpu.parallel.sharding import (
    make_mesh_2d, make_sharded_step, shard_state)
from go_libp2p_pubsub_tpu.sim.engine import step_jit

cfg, tp, st = _build("halo")
mesh = make_mesh_2d(2, jax.devices()[:8])
assert dict(mesh.shape) == {{'dcn': 2, 'peers': 4}}, dict(mesh.shape)
sharded_step = make_sharded_step(mesh, cfg, tp)
st_sh = shard_state(st, mesh, cfg)
text = sharded_step.lower(st_sh, jax.random.PRNGKey(0)).compile().as_text()
open({str(hlo)!r}, "w").write(text)

st_un = st
key = jax.random.PRNGKey(43)
for _ in range(3):
    key, k = jax.random.split(key)
    st_sh = sharded_step(st_sh, k)
    st_un = step_jit(st_un, cfg, tp, k)
for name, a, b in zip(st_un._fields, st_un, st_sh):
    np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5,
        err_msg=f"field {{name}} diverged on the 2-D mesh")
# the dcn axis is genuinely partitioned: 8 DISTINCT peer-row blocks,
# one per (dcn, peers) coordinate — not 4 blocks replicated twice
blocks = {{(s.index[0].start, s.index[0].stop)
           for s in st_sh.mesh.addressable_shards}}
assert len(blocks) == 8, sorted(blocks)
print("HLO_2D_OK")
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = cpu_mesh_env(dict(os.environ), 8)
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=540,
                         cwd=repo)
    assert "HLO_2D_OK" in res.stdout, res.stderr[-3000:]
    bad = _dense_collectives(hlo.read_text(), BUDGET)
    assert not bad, (
        f"dense collectives above the packed budget ({BUDGET} words) in "
        f"the 2-D halo-routed step: {bad[:5]}")


def test_bucketed_halo_step_within_packed_budget(eight_devices, tmp_path):
    """The ROW-SHARDED BUCKETED engine's acceptance guard (ISSUE 16): the
    halo-routed bucketed step at a heavy-tailed partition compiles with NO
    all-gather/dynamic-slice above the packed budget — every cross-shard
    exchange rides route_bucketed_flat's capacity-padded (src,dst)-bucket
    planes at each bucket's OWN K-ceiling, never a dense [N, D_max]
    gather. Positive control IN THE SAME subprocess/mesh: the dense-padded
    layout (degree_buckets=None) on the replicated route MUST trip the
    grep with an >= N*K collective, so a budget loosened by accident can
    never pass vacuously. Fresh subprocess for the same multi-mesh
    poison reason as above; both HLO dumps are grepped here."""
    import os
    import subprocess
    import sys

    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env

    hlo_b = tmp_path / "bucketed.hlo"
    hlo_d = tmp_path / "dense_control.hlo"
    code = f"""
import dataclasses
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from tests.test_hlo_sharded_budget import _build
from go_libp2p_pubsub_tpu.parallel.halo import required_bucket_capacity
from go_libp2p_pubsub_tpu.parallel.sharding import (
    make_mesh, make_sharded_bucketed_run, make_sharded_step,
    shard_bucketed_state, shard_state)
from go_libp2p_pubsub_tpu.sim import topology
from go_libp2p_pubsub_tpu.sim.bucketed import init_bucketed_state

cfg0, tp, _ = _build("halo")
N, K = cfg0.n_peers, cfg0.k_slots
bks = topology.powerlaw_buckets(N, d_min=4, d_max=K, alpha=2.0, round_to=8)
bks = topology.align_degree_buckets(bks, 8)
topo = topology.powerlaw(N, K, d_min=4, d_max=K, alpha=2.0, seed=11)
cap = required_bucket_capacity(topo.neighbors, topo.reverse_slot, 8,
                               buckets=bks)
cfg = dataclasses.replace(cfg0, degree_buckets=bks, bucketed_rng="bucket",
                          halo_bucket_capacity=cap, flood_publish=False,
                          edge_gather_mode="auto")
mesh = make_mesh(jax.devices()[:8])
run = make_sharded_bucketed_run(mesh, cfg, tp)
bs0 = shard_bucketed_state(init_bucketed_state(cfg, topo), mesh, cfg)
keys = jax.random.split(jax.random.PRNGKey(0), 2)
open({str(hlo_b)!r}, "w").write(run.lower(bs0, keys).compile().as_text())

cfg_d = dataclasses.replace(cfg0, sharded_route="replicated")
st = shard_state(_build("replicated")[2], mesh, cfg_d)
step = make_sharded_step(mesh, cfg_d, tp)
open({str(hlo_d)!r}, "w").write(
    step.lower(st, jax.random.PRNGKey(0)).compile().as_text())
print("HLO_BUCKETED_OK")
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = cpu_mesh_env(dict(os.environ), 8)
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=540,
                         cwd=repo)
    assert "HLO_BUCKETED_OK" in res.stdout, res.stderr[-3000:]
    bad = _dense_collectives(hlo_b.read_text(), BUDGET)
    assert not bad, (
        f"dense collectives above the packed budget ({BUDGET} words) in "
        f"the sharded bucketed chunk: {bad[:5]}")
    control = _dense_collectives(hlo_d.read_text(), BUDGET)
    assert control and max(e for e, _ in control) >= N * K, (
        "control failed: the dense-padded replicated step shows no "
        "dense collective to the grep")
