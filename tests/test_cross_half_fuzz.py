"""Randomized cross-half differential fuzz (VERDICT r4 item 6b).

Fifty random (underlay, seed, subscription-pattern) scenarios per run,
each executed through BOTH halves of the framework — the functional
runtime (real PubSub nodes over the discrete-event Network) and the
batched engine (on the functional net's own connection graph via
topology.from_hosts) — comparing the INVARIANTS that define router
health, not bitwise state (the halves deliberately differ in
micro-decisions; see tests/test_statistical_parity.py):

  - mesh degrees bounded by Dhi and by the underlay in both halves,
    with close means;
  - full delivery of published messages on the (connected) underlay in
    both halves;
  - batched mesh symmetry and mesh-only-on-connected-edges.

Scenario shapes keep the batched jit signature CONSTANT (one compile for
all 50 — SimConfig is a static jit argument) and randomize everything
data-level: underlay degree, graph seed, who publishes, and the topic-1
subscriber subset. Reference anchor: the gossipsub_test.go style of
many-seeded small-swarm assertions (TestDenseGossipsub:47,
TestGossipsubFanout:370) scaled to a property-based sweep.
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
from go_libp2p_pubsub_tpu.net import Network
from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
from go_libp2p_pubsub_tpu.sim.config import TopicParams

N = 48
K_SLOTS = 24
N_SCENARIOS = 50
TOPICS = ["t0", "t1"]


def _scenario_params(rng):
    return dict(degree=int(rng.integers(3, 7)),
                graph_seed=int(rng.integers(1 << 30)),
                sub1_frac=float(rng.uniform(0.2, 0.9)),
                n_pubs=int(rng.integers(4, 10)))


def _run_functional(p, rng):
    net = Network()
    nodes = [PubSub(net.add_host(), GossipSubRouter(),
                    sign_policy=LAX_NO_SIGN) for _ in range(N)]
    hosts = [x.host for x in nodes]
    net.dense_connect(hosts, degree=p["degree"],
                      seed=p["graph_seed"])
    net.scheduler.run_for(0.1)
    sub1 = rng.random(N) < p["sub1_frac"]
    inboxes = [set() for _ in range(N)]
    for i, x in enumerate(nodes):
        sub = x.join(TOPICS[0]).subscribe()
        sub.on_message = (lambda m, box=inboxes[i]: box.add(bytes(m.data)))
        if sub1[i]:
            x.join(TOPICS[1]).subscribe()
    net.scheduler.run_until(8.0)
    published = []
    for i in range(p["n_pubs"]):
        pub = int(rng.integers(N))
        data = b"m%d" % i
        nodes[pub].my_topics[TOPICS[0]].publish(data)
        inboxes[pub].add(data)          # the publisher holds its own message
        published.append(data)
    net.scheduler.run_until(12.0)
    degrees = np.array([len(x.rt.mesh.get(TOPICS[0], ())) for x in nodes])
    got = np.array([[d in box for d in published] for box in inboxes])
    return hosts, sub1, degrees, got


def _cfg():
    return SimConfig(n_peers=N, k_slots=K_SLOTS, n_topics=2, msg_window=32,
                     publishers_per_tick=1, prop_substeps=6,
                     scoring_enabled=False)


@pytest.fixture(scope="module")
def batched_runner():
    """ONE jitted runner reused by all scenarios (cfg static, data varies)."""
    import jax

    from go_libp2p_pubsub_tpu.sim.engine import run

    cfg = _cfg()
    tp = TopicParams.disabled(2)

    def go(topo, subscribed, seed):
        st = init_state(cfg, topo, subscribed=subscribed)
        st = run(st, cfg, tp, jax.random.PRNGKey(seed), 16)
        return st

    return cfg, go


def _connected(hosts):
    """BFS connectivity of the underlay (delivery can only saturate on a
    connected graph)."""
    adj = {h.peer_id: [p for p in h.conns] for h in hosts}
    seen = {hosts[0].peer_id}
    frontier = [hosts[0].peer_id]
    while frontier:
        nxt = []
        for u in frontier:
            for v in adj[u]:
                if v not in seen:
                    seen.add(v)
                    nxt.append(v)
        frontier = nxt
    return len(seen) == len(hosts)


def test_fifty_random_scenarios_cross_half(batched_runner):
    import jax  # noqa: F401  (env pinned by conftest)

    from go_libp2p_pubsub_tpu.sim.engine import (
        delivery_fraction, mesh_degrees)

    cfg, go = batched_runner
    master = np.random.default_rng(20260731)
    checked_delivery = 0
    fracs_b = []
    for case in range(N_SCENARIOS):
        rng = np.random.default_rng(master.integers(1 << 62))
        p = _scenario_params(rng)
        hosts, sub1, deg_f, got_f = _run_functional(p, rng)
        topo, _ = topology.from_hosts(hosts, K_SLOTS)
        subscribed = np.stack([np.ones(N, bool), sub1], axis=1)
        st = go(topo, subscribed, p["graph_seed"] & 0x7FFFFFFF)
        deg_b = np.asarray(mesh_degrees(st))[:, 0]

        ctx = f"case {case} {p}"
        # mesh degree bounds: Dhi and the underlay's physical degree cap
        conns = (np.asarray(topo.neighbors) >= 0).sum(-1)
        for name, d in (("functional", deg_f), ("batched", deg_b)):
            assert d.max() <= 12, f"{ctx}: {name} above Dhi"
            assert (d <= conns).all(), f"{ctx}: {name} exceeds underlay"
        # means track each other across random underlays
        assert abs(deg_f.mean() - deg_b.mean()) <= 1.5, \
            f"{ctx}: means {deg_f.mean():.2f} vs {deg_b.mean():.2f}"
        # batched mesh structural invariants
        mesh = np.asarray(st.mesh)
        nbr = np.asarray(topo.neighbors)
        rks = np.asarray(topo.reverse_slot)
        for ti in range(2):
            m = mesh[:, ti, :]
            assert not (m & (nbr < 0)).any(), f"{ctx}: mesh on missing edge"
            # symmetry through the involution
            jn = np.clip(nbr, 0, N - 1)
            rk = np.clip(rks, 0, K_SLOTS - 1)
            assert (m == m[jn, rk])[nbr >= 0].all(), \
                f"{ctx}: batched mesh asymmetric"
        if _connected(hosts):
            checked_delivery += 1
            assert got_f.all(), f"{ctx}: functional delivery incomplete"
            # census topic 0 ONLY — the topic both halves publish on and
            # the one whose subscriber set is the whole (connected)
            # underlay. Topic 1's random subscriber subset can induce a
            # DISCONNECTED subgraph, and gossipsub only delivers over
            # edges between subscribers (the test_delivery_structural
            # reachability oracle's loss floor): counting those
            # structurally-unreachable pairs failed the sweep the first
            # time it ever executed (it shipped behind a collection error
            # in images without 'cryptography').
            frac_b = float(delivery_fraction(st, cfg, topic=0))
            # per-case floor tolerates pre-convergence stragglers on the
            # lowest-degree underlays; the sweep MEAN must saturate
            assert frac_b >= 0.97, f"{ctx}: batched delivery {frac_b:.4f}"
            fracs_b.append(frac_b)
    # the sweep must actually exercise the delivery assertion, and the
    # aggregate must saturate — a systematic delivery leak cannot hide
    # behind the per-case tolerance
    assert checked_delivery >= N_SCENARIOS * 0.8, \
        f"only {checked_delivery}/{N_SCENARIOS} connected underlays"
    assert np.mean(fracs_b) >= 0.995, \
        f"batched sweep mean delivery {np.mean(fracs_b):.4f}"
