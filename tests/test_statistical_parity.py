"""Free-running statistical parity: functional runtime vs batched engine.

SURVEY §7's missing gate (VERDICT r2 weak #4): the two halves of the
framework deliberately differ in micro-decisions (random IWANT pick vs
deterministic lowest-slot chooser, latency-scheduled wire vs hop-bounded
substeps), so free-running equivalence is STATISTICAL, not bitwise. This
harness runs the same network shape through both halves — same underlay
graph (the functional net's own connection graph, ``topology.from_hosts``),
same gossipsub degree bounds, same score params, same publish rate — and
asserts the distributions that define router health match within bands:

- mesh degree distribution (mean, dlo/dhi clamping, empirical-CDF distance)
  — gossipsub_test.go:85 TestDenseGossipsub checks exactly this shape;
- delivery fraction (both sides must saturate on a connected single topic);
- delivery latency in ticks (mesh forwarding is same-tick in both halves).
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
from go_libp2p_pubsub_tpu.core.params import (
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.net import Network
from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
from go_libp2p_pubsub_tpu.sim.config import TopicParams
from go_libp2p_pubsub_tpu.trace import MemoryTracer

TOPIC = "t"
N = 512
DEGREE = 12
# dense_connect(degree=12) gives ~24 bidirectional conns per node (each
# side dials 12); k_slots must hold the max or from_hosts truncates edges
K_SLOTS = 40
CONVERGE_T = 15.0          # virtual seconds of mesh convergence
PUBS = 24                  # 2 publishes per tick for 12 ticks
DRAIN_T = 3.0

TSP = TopicScoreParams(
    topic_weight=1.0, time_in_mesh_weight=0.05, time_in_mesh_quantum=1.0,
    time_in_mesh_cap=100.0, first_message_deliveries_weight=1.0,
    first_message_deliveries_decay=0.9, first_message_deliveries_cap=50.0,
    mesh_message_deliveries_weight=0.0, mesh_message_deliveries_decay=0.9,
    mesh_message_deliveries_cap=30.0, mesh_message_deliveries_threshold=3.0,
    mesh_message_deliveries_window=0.05, mesh_message_deliveries_activation=4.0,
    mesh_failure_penalty_weight=0.0, mesh_failure_penalty_decay=0.9,
    invalid_message_deliveries_weight=-5.0,
    invalid_message_deliveries_decay=0.9)


def _run_functional(latency=None):
    net = Network() if latency is None else Network(latency=latency)
    mem = MemoryTracer()
    nodes = []
    for _ in range(N):
        h = net.add_host()
        sp = PeerScoreParams(app_specific_score=lambda p: 0.0,
                             decay_interval=1.0, decay_to_zero=0.01,
                             topics={TOPIC: TSP})
        nodes.append(PubSub(h, GossipSubRouter(score_params=sp,
                                               thresholds=PeerScoreThresholds()),
                            sign_policy=LAX_NO_SIGN, event_tracer=mem))
    hosts = [x.host for x in nodes]
    net.dense_connect(hosts, degree=DEGREE)
    net.scheduler.run_for(0.1)
    for x in nodes:
        x.join(TOPIC).subscribe()
    net.scheduler.run_until(CONVERGE_T)
    rng = np.random.default_rng(1)
    t_pub = CONVERGE_T
    for i in range(PUBS):
        nodes[int(rng.integers(N))].my_topics[TOPIC].publish(b"m%d" % i)
        t_pub += 0.5
        net.scheduler.run_until(t_pub)
    net.scheduler.run_until(t_pub + DRAIN_T)

    degrees = np.array([len(x.rt.mesh.get(TOPIC, ())) for x in nodes])
    pub_t: dict[str, float] = {}
    delivered: dict[str, set] = {}
    latencies = []
    for e in mem.events:
        if e["type"] == "PUBLISH_MESSAGE":
            pub_t.setdefault(e["publishMessage"]["messageID"], e["timestamp"])
        elif e["type"] == "DELIVER_MESSAGE":
            mid = e["deliverMessage"]["messageID"]
            frm = e["deliverMessage"].get("receivedFrom")
            delivered.setdefault(mid, set()).add(e["peerID"])
            if frm != e["peerID"] and mid in pub_t:
                latencies.append(e["timestamp"] - pub_t[mid])
    frac = np.mean([len(delivered.get(m, ())) / N for m in pub_t])
    return hosts, degrees, float(frac), np.array(latencies)


def _run_batched(hosts):
    import jax
    from go_libp2p_pubsub_tpu.sim.engine import (
        delivery_fraction, delivery_latency_ticks, mesh_degrees, run)

    topo, _ = topology.from_hosts(hosts, K_SLOTS)
    cfg = SimConfig(n_peers=N, k_slots=K_SLOTS, n_topics=1, msg_window=64,
                    publishers_per_tick=2, prop_substeps=8,
                    scoring_enabled=True)
    tp = TopicParams.from_topic_params([TSP])
    st = init_state(cfg, topo,
                    subscribed=np.ones((N, 1), bool))
    st = run(st, cfg, tp, jax.random.PRNGKey(0), 30)
    st.tick.block_until_ready()
    degrees = np.asarray(mesh_degrees(st))
    if degrees.ndim == 2:
        degrees = degrees[:, 0]
    return (degrees, float(delivery_fraction(st, cfg)),
            float(delivery_latency_ticks(st, cfg)))


@pytest.fixture(scope="module")
def parity():
    hosts, deg_f, frac_f, lat_f = _run_functional()
    deg_b, frac_b, lat_b = _run_batched(hosts)
    return deg_f, frac_f, lat_f, deg_b, frac_b, lat_b


def _assert_parity_bands(deg_f, deg_b, frac_f, lat_f, ctx=""):
    """The canonical parity bands, shared by the module-fixture run and
    the ordering-robustness seeds so a band retune cannot silently apply
    to one site only (bands last retuned in round 4, see
    test_mesh_degree_distribution_close)."""
    assert deg_f.min() >= 5 and deg_f.max() <= 12, \
        f"{ctx}degrees [{deg_f.min()}, {deg_f.max()}]"
    assert abs(deg_f.mean() - deg_b.mean()) <= 1.0, \
        f"{ctx}means {deg_f.mean():.2f} vs {deg_b.mean():.2f}"
    grid = np.arange(0, 14)
    cdf_f = np.searchsorted(np.sort(deg_f), grid, side="right") / N
    cdf_b = np.searchsorted(np.sort(deg_b), grid, side="right") / N
    ks = np.abs(cdf_f - cdf_b).max()
    assert ks <= 0.15, f"{ctx}KS {ks:.3f}"
    assert frac_f >= 0.995, f"{ctx}delivery {frac_f:.4f}"
    assert float(lat_f.mean()) <= 0.25, f"{ctx}latency {lat_f.mean():.3f}"


class TestStatisticalParity:
    def test_canonical_run_passes_shared_bands(self, parity):
        """The canonical run must satisfy the SAME shared band helper the
        ordering-robustness seeds use — one band definition, two users."""
        deg_f, frac_f, lat_f, deg_b, _, _ = parity
        _assert_parity_bands(deg_f, deg_b, frac_f, lat_f)

    def test_mesh_degree_bounds(self, parity):
        deg_f, _, _, deg_b, _, _ = parity
        cfg_d, cfg_dlo, cfg_dhi = 6, 5, 12
        for name, d in (("functional", deg_f), ("batched", deg_b)):
            assert d.min() >= cfg_dlo, f"{name} min degree below DLO"
            assert d.max() <= cfg_dhi, f"{name} max degree above DHI"
            assert cfg_d - 1 <= d.mean() <= cfg_dhi, \
                f"{name} mean degree {d.mean():.2f} outside healthy band"

    def test_mesh_degree_distribution_close(self, parity):
        """Bands tightened in round 4 after the offset was EXPLAINED and
        fixed (ROUND4_NOTES.md "Parity offset"): the batched engine's
        pre-round-mesh Dhi check accepted every same-round graft, overshot
        during the join wave, and the over-subscription slash + 60-tick
        backoffs depressed equilibrium degree ~1.0 below the functional
        runtime. The serial-arrival capacity budget in
        ops/heartbeat.py (lowest-slot-first acceptance against the growing
        mesh, outbound bypass consuming headroom) brought the measured
        offset to ~0.2 and KS to ~0.1."""
        deg_f, _, _, deg_b, _, _ = parity
        assert abs(deg_f.mean() - deg_b.mean()) <= 1.0, \
            f"mean degrees diverge: {deg_f.mean():.2f} vs {deg_b.mean():.2f}"
        # empirical CDF distance over the shared support
        grid = np.arange(0, 14)
        cdf_f = np.searchsorted(np.sort(deg_f), grid, side="right") / N
        cdf_b = np.searchsorted(np.sort(deg_b), grid, side="right") / N
        ks = np.abs(cdf_f - cdf_b).max()
        assert ks <= 0.15, f"mesh degree CDFs diverge: KS distance {ks:.3f}"

    def test_delivery_fraction_saturates(self, parity):
        _, frac_f, _, _, frac_b, _ = parity
        assert frac_f >= 0.995, f"functional delivery {frac_f:.4f}"
        assert frac_b >= 0.995, f"batched delivery {frac_b:.4f}"

    def test_delivery_latency_close(self, parity):
        _, _, lat_f, _, _, lat_b = parity
        # heartbeat interval 1.0s == 1 tick: mesh forwarding completes
        # within the tick in both halves
        mean_f_ticks = float(lat_f.mean())  # virtual seconds == ticks
        assert mean_f_ticks <= 0.25, f"functional latency {mean_f_ticks:.3f}"
        assert lat_b <= 0.25, f"batched latency {lat_b:.3f}"
        assert abs(mean_f_ticks - lat_b) <= 0.25


class TestOrderingRobustness:
    """The reference explores many same-tick event orderings per run — a
    reader goroutine per stream (comm.go:44-99) and deliberate
    map-iteration shuffles (gossipsub.go:1954-1973) — while the functional
    runtime serializes every event on one (time, seq) heap. These runs
    perturb same-tick RPC arrival order with seeded random PER-SEND
    latency jitter (each send samples its own delay, so concurrent RPCs
    interleave differently per seed) and assert the statistical-parity
    bands still hold: the parity conclusions are properties of the
    protocol, not artifacts of one canonical event order the Go router
    never guarantees (VERDICT r4 item 7)."""

    @pytest.mark.parametrize("seed", [7, 23])
    def test_bands_hold_under_shuffled_arrival_order(self, seed, parity):
        _, _, _, deg_b, frac_b, _ = parity
        rng = np.random.default_rng(seed)

        def jitter(a, b):
            # sub-tick spread around the default 1 ms wire latency:
            # reorders every same-tick burst without crossing heartbeats
            return 0.0005 + float(rng.random()) * 0.0015

        _, deg_f, frac_f, lat_f = _run_functional(latency=jitter)
        _assert_parity_bands(deg_f, deg_b, frac_f, lat_f,
                             ctx=f"seed {seed}: ")
