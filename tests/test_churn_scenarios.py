"""Connection churn + benchmark scenario tests.

Churn models the reference's dead-peer path (pubsub.go:711-757) and score
retention (score.go:611-644 RemovePeer/RetainScore); scenarios are the
BASELINE.md benchmark configs at toy scale.
"""

import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu.ops.churn import churn_edges
from go_libp2p_pubsub_tpu.sim import (
    SimConfig, TopicParams, delivery_fraction, init_state, mesh_degrees, run,
    topology,
)
from go_libp2p_pubsub_tpu.sim import scenarios
from go_libp2p_pubsub_tpu.sim.state import NEVER


def cfg_with_churn(**kw):
    base = dict(n_peers=64, k_slots=16, n_topics=1, msg_window=32,
                publishers_per_tick=2, prop_substeps=6,
                churn_disconnect_prob=0.5, churn_reconnect_prob=0.5,
                retain_score_ticks=5)
    base.update(kw)
    return SimConfig(**base)


class TestChurnEdges:
    def _setup(self, **kw):
        cfg = cfg_with_churn(**kw)
        topo = topology.dense(cfg.n_peers, cfg.k_slots, degree=10)
        tp = TopicParams.disabled(cfg.n_topics)
        st = init_state(cfg, topo)
        return cfg, tp, st

    def test_symmetric_disconnect(self):
        cfg, tp, st = self._setup()
        st2 = churn_edges(st, cfg, tp, jax.random.PRNGKey(1))
        conn = np.asarray(st2.connected)
        nbr = np.asarray(st2.neighbors)
        rs = np.asarray(st2.reverse_slot)
        n, k = nbr.shape
        for i in range(n):
            for s in range(k):
                if nbr[i, s] >= 0 and rs[i, s] >= 0:
                    assert conn[i, s] == conn[nbr[i, s], rs[i, s]]
        # with p=0.5 a good fraction actually went down
        known = nbr >= 0
        assert conn[known].mean() < 0.9

    def test_down_edges_leave_mesh_and_stamp_tick(self):
        cfg, tp, st = self._setup()
        # put every connected edge in the mesh first
        st = st._replace(mesh=st.connected[:, None, :] & st.subscribed[:, :, None],
                         tick=jnp.int32(7))
        st2 = churn_edges(st, cfg, tp, jax.random.PRNGKey(2))
        went_down = np.asarray(st.connected & ~st2.connected)
        assert went_down.any()
        mesh2 = np.asarray(st2.mesh)
        assert not (mesh2 & went_down[:, None, :]).any()
        dt = np.asarray(st2.disconnect_tick)
        assert (dt[went_down] == 7).all()
        assert (dt[np.asarray(st2.connected)] == int(NEVER)).all()

    def test_retention_expiry_resets_counters(self):
        cfg, tp, st = self._setup(churn_disconnect_prob=0.0,
                                  churn_reconnect_prob=1.0)
        # edge (0, slot 0) went down at tick 0; counters carry score history
        connected = st.connected.at[0, 0].set(False)
        j = int(st.neighbors[0, 0]); rs = int(st.reverse_slot[0, 0])
        connected = connected.at[j, rs].set(False)
        fmd = st.first_message_deliveries.at[0, 0, 0].set(9.0)
        dtick = st.disconnect_tick.at[0, 0].set(0).at[j, rs].set(0)
        base = st._replace(connected=connected, first_message_deliveries=fmd,
                           disconnect_tick=dtick)

        # reconnect BEFORE retention expiry (tick 3 <= retain 5): score kept
        early = churn_edges(base._replace(tick=jnp.int32(3)), cfg, tp,
                            jax.random.PRNGKey(3))
        assert bool(early.connected[0, 0])
        assert float(early.first_message_deliveries[0, 0, 0]) == 9.0
        assert int(early.disconnect_tick[0, 0]) == int(NEVER)

        # reconnect AFTER expiry (tick 50 > 5): counters reset
        late = churn_edges(base._replace(tick=jnp.int32(50)), cfg, tp,
                           jax.random.PRNGKey(3))
        assert bool(late.connected[0, 0])
        assert float(late.first_message_deliveries[0, 0, 0]) == 0.0

    def test_mesh_self_heals_under_churn(self):
        # gossipsub_test.go TestReconnects analogue: the network keeps
        # delivering while edges flap
        cfg = cfg_with_churn(churn_disconnect_prob=0.05,
                             churn_reconnect_prob=0.5)
        topo = topology.dense(cfg.n_peers, cfg.k_slots, degree=10)
        tp = scenarios.default_topic_params(1)
        st = init_state(cfg, topo)
        st = run(st, cfg, tp, jax.random.PRNGKey(0), 40)
        deg = np.asarray(mesh_degrees(st))
        assert deg.mean() > 2.0
        assert float(delivery_fraction(st, cfg)) > 0.9


class TestScenarios:
    def test_all_build_and_run(self):
        for name, builder in scenarios.SCENARIOS.items():
            cfg, tp, st = builder(n_peers=96, k_slots=16, degree=6)
            st = run(st, cfg, tp, jax.random.PRNGKey(0), 8)
            assert int(st.tick) == 8, name
            assert float(delivery_fraction(st, cfg)) > 0.5, name

    def test_router_sweep_builds(self):
        for r in ("floodsub", "randomsub", "gossipsub"):
            cfg, tp, st = scenarios.router_sweep_100k(r, n_peers=96,
                                                      k_slots=16, degree=6)
            st = run(st, cfg, tp, jax.random.PRNGKey(0), 6)
            assert float(delivery_fraction(st, cfg)) > 0.9, r

    def test_sybil_scenario_graylists_attackers(self):
        # the spam-test end state: honest observers score sybil neighbors
        # negative (P4 invalid deliveries + P7 broken promises + P6 colocation)
        from go_libp2p_pubsub_tpu.ops.score_ops import compute_scores
        cfg, tp, st = scenarios.sybil_100k(n_peers=128, k_slots=16, degree=8,
                                           sybil_fraction=0.25, n_sybil_ips=2)
        st = run(st, cfg, tp, jax.random.PRNGKey(0), 30)
        scores = np.asarray(compute_scores(st, cfg, tp))
        nbr = np.asarray(jnp.clip(st.neighbors, 0, cfg.n_peers - 1))
        mal = np.asarray(st.malicious)
        honest_obs = ~mal
        edge_to_sybil = mal[nbr] & np.asarray(st.connected) & honest_obs[:, None]
        edge_to_honest = ~mal[nbr] & np.asarray(st.connected) & honest_obs[:, None]
        assert scores[edge_to_sybil].mean() < scores[edge_to_honest].mean()
        assert scores[edge_to_sybil].mean() < 0


class TestPXAndDirectConnect:
    """PX-seeded reconnects (gossipsub.go:893-973) and the forced direct-peer
    redial cadence (gossipsub.go:1648-1670) in the batched churn path."""

    def test_px_reconnect_prefers_high_score(self):
        cfg = cfg_with_churn(
            churn_disconnect_prob=0.0, churn_reconnect_prob=0.3,
            px_enabled=True, accept_px_threshold=0.0, px_low_score_factor=0.0,
            scoring_enabled=True, app_specific_weight=1.0)
        topo = topology.dense(cfg.n_peers, cfg.k_slots, degree=10)
        tp = TopicParams.disabled(cfg.n_topics)
        # half the peers score below the PX threshold via app score
        app = np.where(np.arange(cfg.n_peers) % 2 == 0, 1.0, -1.0
                       ).astype(np.float32)
        st = init_state(cfg, topo, app_score=app)
        # take every edge down
        st = st._replace(connected=jnp.zeros_like(st.connected),
                         disconnect_tick=jnp.zeros_like(st.disconnect_tick))
        key = jax.random.PRNGKey(3)
        for i in range(20):
            key, k = jax.random.split(key)
            st = churn_edges(st, cfg, tp, k)
        conn = np.asarray(st.connected)
        nbr = np.asarray(st.neighbors)
        known = nbr >= 0
        # the dial decision belongs to the lower-id endpoint (the symmetric-
        # edge tie-break): its rating of the other end sets the probability
        from go_libp2p_pubsub_tpu.ops.churn import _symmetric_value
        rated_good = np.asarray(_symmetric_value(
            st, jnp.asarray((np.clip(nbr, 0, None) % 2 == 0))))
        referred = known & rated_good      # dialer got a PX referral
        shunned = known & ~rated_good      # below threshold: factor 0.0
        assert conn[referred].mean() > 0.9, conn[referred].mean()
        assert not conn[shunned].any()

    def test_direct_edges_force_redial(self):
        cfg = cfg_with_churn(churn_disconnect_prob=0.0,
                             churn_reconnect_prob=0.0,
                             direct_connect_ticks=4)
        topo = topology.dense(cfg.n_peers, cfg.k_slots, degree=10)
        tp = TopicParams.disabled(cfg.n_topics)
        st = init_state(cfg, topo)
        direct = st.connected & (jax.random.uniform(
            jax.random.PRNGKey(5), st.connected.shape) < 0.3)
        # make direct symmetric the way WithDirectPeers is (both sides list
        # each other, gossipsub.go:331-344)
        from go_libp2p_pubsub_tpu.ops.churn import _symmetric_value
        direct = _symmetric_value(st, direct)
        st = st._replace(direct=direct,
                         connected=jnp.zeros_like(st.connected),
                         disconnect_tick=jnp.zeros_like(st.disconnect_tick))
        # off-cadence tick: nothing comes back
        st = st._replace(tick=jnp.int32(3))
        st1 = churn_edges(st, cfg, tp, jax.random.PRNGKey(6))
        assert not bool(jnp.any(st1.connected))
        # on-cadence tick: exactly the direct edges return
        st = st._replace(tick=jnp.int32(4))
        st2 = churn_edges(st, cfg, tp, jax.random.PRNGKey(7))
        np.testing.assert_array_equal(
            np.asarray(st2.connected),
            np.asarray(direct & (st.neighbors >= 0)))

    def test_sybil_mesh_heals_honest_side(self):
        """Toy sybil_100k shape: under churn with PX, honest peers keep their
        honest-edge connectivity while sybil edges wither."""
        import go_libp2p_pubsub_tpu.sim.scenarios as sc
        cfg, tp, st = sc.sybil_100k(n_peers=256, k_slots=16, degree=8,
                                    sybil_fraction=0.25, n_sybil_ips=4)
        st = run(st, cfg, tp, jax.random.PRNGKey(11), 60)
        mal = np.asarray(st.malicious)
        nbr = np.clip(np.asarray(st.neighbors), 0, cfg.n_peers - 1)
        known = np.asarray(st.neighbors) >= 0
        conn = np.asarray(st.connected)
        hon = ~mal
        hh = known[hon] & ~mal[nbr[hon]]
        hs = known[hon] & mal[nbr[hon]]
        up_hh = conn[hon][hh].mean()
        up_hs = conn[hon][hs].mean()
        assert up_hh > 0.85, up_hh          # honest mesh healed
        assert up_hs < up_hh, (up_hs, up_hh)


class TestSubscriptionChurn:
    """Batched Join/Leave (gossipsub.go:1047-1124) with unsubscribe backoff."""

    def _setup(self, **kw):
        cfg = cfg_with_churn(churn_disconnect_prob=0.0,
                             churn_reconnect_prob=0.0,
                             unsubscribe_backoff_ticks=10, **kw)
        topo = topology.dense(cfg.n_peers, cfg.k_slots, degree=10)
        tp = TopicParams.disabled(cfg.n_topics)
        st = init_state(cfg, topo)
        return cfg, tp, st

    def test_leave_prunes_with_backoff_and_penalty(self):
        from go_libp2p_pubsub_tpu.ops.churn import churn_subscriptions
        cfg, tp, st = self._setup(sub_leave_prob=0.5)
        # full mesh on connected edges, P3 active with a deficit -> penalty
        st = st._replace(
            mesh=st.connected[:, None, :] & st.subscribed[:, :, None],
            mesh_active=st.connected[:, None, :],
            tick=jnp.int32(5))
        tp_pen = scenarios.default_topic_params(1)
        st2 = churn_subscriptions(st, cfg, tp_pen, jax.random.PRNGKey(1))
        left = np.asarray(st.subscribed & ~st2.subscribed)
        assert left.any()
        # leavers hold no mesh edges on left topics
        mesh2 = np.asarray(st2.mesh)
        assert not mesh2[left[:, 0], 0, :].any()
        # removed edges entered unsubscribe backoff and took the P3b penalty
        removed = np.asarray(st.mesh) & ~mesh2
        bo = np.asarray(st2.backoff)
        assert (bo[removed] == 5 + 10).all()
        assert float(jnp.sum(st2.mesh_failure_penalty)) > 0
        # mesh stayed edge-symmetric
        nbr = np.asarray(st.neighbors); rs = np.asarray(st.reverse_slot)
        for i in range(cfg.n_peers):
            for s in range(cfg.k_slots):
                if nbr[i, s] >= 0 and rs[i, s] >= 0:
                    assert mesh2[i, 0, s] == mesh2[nbr[i, s], 0, rs[i, s]]

    def test_join_promotes_fanout(self):
        from go_libp2p_pubsub_tpu.ops.churn import churn_subscriptions
        cfg, tp, st = self._setup(sub_join_prob=1.0)
        sub = np.zeros((cfg.n_peers, 1), bool)   # nobody subscribed
        st = st._replace(subscribed=jnp.asarray(sub),
                         fanout=st.connected[:, None, :],
                         fanout_lastpub=jnp.zeros_like(st.fanout_lastpub))
        st2 = churn_subscriptions(st, cfg, tp, jax.random.PRNGKey(2))
        assert bool(jnp.all(st2.subscribed))
        # fanout edges became mesh edges; fanout cleared
        np.testing.assert_array_equal(np.asarray(st2.mesh),
                                      np.asarray(st.fanout))
        assert not bool(jnp.any(st2.fanout))

    def test_rejoin_blocked_by_unsubscribe_backoff(self):
        """After Leave, the next heartbeat cannot regraft until the
        unsubscribe backoff expires (heartbeat candidate gating)."""
        from go_libp2p_pubsub_tpu.ops.churn import churn_subscriptions
        from go_libp2p_pubsub_tpu.ops.heartbeat import heartbeat
        cfg, tp, st = self._setup(sub_leave_prob=0.5)
        st = st._replace(
            mesh=st.connected[:, None, :] & st.subscribed[:, :, None],
            tick=jnp.int32(5))
        st2 = churn_subscriptions(st, cfg, tp, jax.random.PRNGKey(3))
        # resubscribe everyone immediately
        st2 = st2._replace(subscribed=jnp.ones_like(st2.subscribed))
        hb = heartbeat(st2, cfg, tp, jax.random.PRNGKey(4))
        regrafted = np.asarray(hb.state.mesh) & ~np.asarray(st2.mesh) \
            & (np.asarray(st2.backoff) > 5)
        assert not regrafted.any()
        # after expiry the same heartbeat regrafts freely
        st3 = st2._replace(tick=jnp.int32(5 + 11))
        hb2 = heartbeat(st3, cfg, tp, jax.random.PRNGKey(4))
        assert (np.asarray(hb2.state.mesh) & ~np.asarray(st3.mesh)).any()
