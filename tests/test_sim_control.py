"""Control-plane hardening tests for the batched sim.

Mirrors the reference's adversarial suite (gossipsub_spam_test.go) and flood
protections as array assertions:
- broken IWANT promises -> P7 behaviour penalty (gossip_tracer.go:79-115,
  gossipsub.go:1620-1625)
- IWANT budget per tick (MaxIHaveLength, gossipsub.go:654-676)
- invalid-message (sybil) publishers accrue P4 and get graylisted out of the
  data plane (score.go:899-918, gossipsub.go:598-609)
- fanout lifecycle: non-subscribed publish reaches the topic, fanout degree
  bounded by D, expiry after FanoutTTL (gossipsub.go:1007-1018, 1560-1596)
"""

import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu.core.params import TopicScoreParams
from go_libp2p_pubsub_tpu.ops.heartbeat import heartbeat
from go_libp2p_pubsub_tpu.ops.propagate import forward_tick, publish
from go_libp2p_pubsub_tpu.ops.score_ops import compute_scores
from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, run, topology
from go_libp2p_pubsub_tpu.sim.state import have_set_bit, unpack_have


def strict_tp():
    return TopicParams.from_topic_params([TopicScoreParams(
        topic_weight=1.0, time_in_mesh_weight=0.01, time_in_mesh_quantum=1.0,
        time_in_mesh_cap=3600.0, first_message_deliveries_weight=1.0,
        first_message_deliveries_decay=0.5, first_message_deliveries_cap=100.0,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.9)])


class TestBrokenPromises:
    def test_unanswered_iwant_adds_behaviour_penalty(self):
        cfg = SimConfig(n_peers=8, k_slots=4, msg_window=8,
                        publishers_per_tick=1, prop_substeps=2,
                        behaviour_penalty_weight=-1.0)
        topo = topology.dense(8, 4, degree=3)
        # mark peer 0's first neighbor malicious: it will never answer
        slot = 0
        mal = np.zeros(8, bool)
        mal[topo.neighbors[0, slot]] = True
        st = init_state(cfg, topo, malicious=mal)
        tp = TopicParams.disabled(1)
        # one alive message peer 0 lacks; peer 0 has a pending IWANT to the
        # slot holding the malicious neighbor
        st = st._replace(
            msg_topic=st.msg_topic.at[0].set(0),
            msg_publish_tick=st.msg_publish_tick.at[0].set(0),
            iwant_pending=st.iwant_pending.at[0, 0].set(slot))
        scores = jnp.zeros((8, 4), jnp.float32)
        st2 = forward_tick(st, cfg, tp, jnp.zeros((8, 1, 4), bool), scores,
                           jax.random.PRNGKey(0))
        bp = np.asarray(st2.behaviour_penalty)
        assert bp[0, slot] == 1.0
        assert bp.sum() == 1.0
        # the message was not delivered
        assert not bool(st2.have[0, 0] & 1)

    def test_answered_iwant_no_penalty(self):
        cfg = SimConfig(n_peers=8, k_slots=4, msg_window=8,
                        publishers_per_tick=1, prop_substeps=2)
        topo = topology.dense(8, 4, degree=3)
        st = init_state(cfg, topo)
        tp = TopicParams.disabled(1)
        nbrs = np.asarray(st.neighbors)
        peer = int(nbrs[0, 0])
        st = st._replace(
            msg_topic=st.msg_topic.at[0].set(0),
            msg_publish_tick=st.msg_publish_tick.at[0].set(0),
            have=have_set_bit(st.have, peer, 0),
            deliver_tick=st.deliver_tick.at[peer, 0].set(0),
            iwant_pending=st.iwant_pending.at[0, 0].set(0))
        scores = jnp.zeros((8, 4), jnp.float32)
        st2 = forward_tick(st, cfg, tp, jnp.zeros((8, 1, 4), bool), scores,
                           jax.random.PRNGKey(0))
        assert np.asarray(st2.behaviour_penalty).sum() == 0.0
        assert bool(st2.have[0, 0] & 1)
        # first-delivery credit went to the answering slot
        assert float(st2.first_message_deliveries[0, 0, 0]) == 1.0


class TestIWantBudget:
    def test_no_phantom_wants_for_never_published_slots(self):
        # idle slots (msg_publish_tick == NEVER) must not be advertised even
        # by malicious peers, nor produce broken-promise penalties
        cfg = SimConfig(n_peers=8, k_slots=4, msg_window=8,
                        publishers_per_tick=1, prop_substeps=1)
        topo = topology.dense(8, 4, degree=3)
        mal = np.zeros(8, bool)
        mal[topo.neighbors[0, 0]] = True
        st = init_state(cfg, topo, malicious=mal)
        tp = TopicParams.disabled(1)
        scores = jnp.zeros((8, 4), jnp.float32)
        st2 = forward_tick(st, cfg, tp, jnp.ones((8, 1, 4), bool), scores,
                           jax.random.PRNGKey(0))
        assert (np.asarray(st2.iwant_pending) == -1).all()
        st3 = forward_tick(st2._replace(tick=st2.tick + 1), cfg, tp,
                           jnp.ones((8, 1, 4), bool), scores,
                           jax.random.PRNGKey(1))
        assert np.asarray(st3.behaviour_penalty).sum() == 0.0

    def test_budget_is_per_sender(self):
        # a flooder exhausting its own budget must not starve pulls from an
        # honest advertiser (iasked is per sending peer, gossipsub.go:654-676)
        cfg = SimConfig(n_peers=8, k_slots=4, msg_window=8,
                        publishers_per_tick=1, prop_substeps=1,
                        max_iwant_per_tick=2)
        topo = topology.dense(8, 4, degree=3)
        mal = np.zeros(8, bool)
        mal[topo.neighbors[0, 0]] = True   # slot 0: floods everything
        honest = topo.neighbors[0, 1]      # slot 1: has only message 6
        st = init_state(cfg, topo, malicious=mal)
        tp = TopicParams.disabled(1)
        st = st._replace(
            msg_topic=st.msg_topic.at[:7].set(0),
            msg_publish_tick=st.msg_publish_tick.at[:7].set(0),
            have=have_set_bit(st.have, honest, 6),
            deliver_tick=st.deliver_tick.at[honest, 6].set(0))
        scores = jnp.zeros((8, 4), jnp.float32)
        st2 = forward_tick(st, cfg, tp, jnp.ones((8, 1, 4), bool), scores,
                           jax.random.PRNGKey(0))
        pend = np.asarray(st2.iwant_pending)[0]
        per_slot = np.bincount(pend[pend >= 0], minlength=4)
        assert per_slot.max() <= 2          # budget enforced per sender
        # message 6 is offered by both; whichever slot serves it, the want
        # survives the flooder's budget exhaustion
        assert pend[6] >= 0

    def test_cap_limits_pending_iwants(self):
        cfg = SimConfig(n_peers=8, k_slots=4, msg_window=8,
                        publishers_per_tick=1, prop_substeps=1,
                        max_iwant_per_tick=2)
        topo = topology.dense(8, 4, degree=3)
        mal = np.zeros(8, bool)
        mal[1] = True  # advertises every alive message
        st = init_state(cfg, topo, malicious=mal)
        tp = TopicParams.disabled(1)
        # five alive messages nobody has
        st = st._replace(
            msg_topic=st.msg_topic.at[:5].set(0),
            msg_publish_tick=st.msg_publish_tick.at[:5].set(0))
        scores = jnp.zeros((8, 4), jnp.float32)
        gossip_all = jnp.ones((8, 1, 4), bool)
        st2 = forward_tick(st, cfg, tp, gossip_all, scores,
                           jax.random.PRNGKey(0))
        pend = np.asarray(st2.iwant_pending)
        counts = (pend >= 0).sum(axis=1)
        assert counts.max() <= 2
        assert counts.max() >= 1  # the offers did register up to the budget


class TestSybilIsolation:
    def test_invalid_publishers_scored_and_graylisted(self):
        n, k = 64, 16
        cfg = SimConfig(n_peers=n, k_slots=k, msg_window=32,
                        publishers_per_tick=4, prop_substeps=6,
                        scoring_enabled=True, graylist_threshold=-50.0,
                        gossip_threshold=-10.0, publish_threshold=-20.0)
        rng = np.random.default_rng(314159)
        mal = np.zeros(n, bool)
        mal[rng.choice(n, n // 5, replace=False)] = True
        topo = topology.dense(n, k, degree=12)
        st = init_state(cfg, topo, malicious=mal)
        tp = strict_tp()
        st = run(st, cfg, tp, jax.random.PRNGKey(7), 30)

        imd = np.asarray(st.invalid_message_deliveries)
        assert imd.sum() > 0  # invalid deliveries were counted
        # P4 charges land only on slots holding malicious peers
        nbrs = np.asarray(st.neighbors)
        slot_mal = np.where(nbrs >= 0, mal[np.clip(nbrs, 0, n - 1)], False)
        assert not (imd.sum(axis=1)[~mal][:, :] * ~slot_mal[~mal]).any()

        scores = np.asarray(compute_scores(st, cfg, tp))
        honest_view_of_mal = scores[~mal][slot_mal[~mal]]
        assert honest_view_of_mal.size > 0
        assert (honest_view_of_mal < 0).mean() > 0.9  # sybils scored down
        # sybils largely evicted from honest meshes
        mesh = np.asarray(st.mesh)[~mal, 0, :]
        mal_in_mesh = (mesh & slot_mal[~mal]).sum()
        assert mal_in_mesh <= 0.02 * mesh.sum() + 2

        # honest traffic still flows: alive valid messages reach honest peers
        alive = (int(st.tick) - np.asarray(st.msg_publish_tick)) < cfg.history_length
        valid = alive & ~np.asarray(st.msg_invalid) & (np.asarray(st.msg_topic) >= 0)
        # skip messages published this very tick boundary (tick advanced after
        # the last forward pass)
        settled = valid & ((int(st.tick) - np.asarray(st.msg_publish_tick)) >= 2)
        if settled.any():
            frac = np.asarray(unpack_have(st, cfg.msg_window))[~mal][:, settled].mean()
            assert frac > 0.9

        # invalid messages were never *delivered* at honest peers
        dt = np.asarray(st.deliver_tick)
        inv = np.asarray(st.msg_invalid)
        pub_is_mal = inv  # invalid slots were published by malicious peers
        assert (dt[~mal][:, pub_is_mal] >= 2**30).all()


class TestFanout:
    def _cfg(self):
        return SimConfig(n_peers=32, k_slots=8, msg_window=16,
                         publishers_per_tick=1, prop_substeps=6,
                         fanout_ttl_ticks=3, scoring_enabled=False)

    def _tick(self, st, cfg, tp, key):
        hb = heartbeat(st, cfg, tp, key)
        st = forward_tick(hb.state, cfg, tp, hb.inc_gossip, hb.scores, key,
                          fwd_send=hb.fwd_send)
        return st._replace(tick=st.tick + 1)

    def test_nonsubscribed_publish_reaches_topic(self):
        cfg = self._cfg()
        sub = np.ones((32, 1), bool)
        sub[0, 0] = False
        topo = topology.dense(32, 8, degree=6)
        st = init_state(cfg, topo, subscribed=sub)
        tp = TopicParams.disabled(1)
        st = publish(st, cfg, jnp.array([0]), jnp.array([0]))
        assert int(st.fanout_lastpub[0, 0]) == 0
        for i in range(4):
            st = self._tick(st, cfg, tp, jax.random.PRNGKey(i))
        have = np.asarray(unpack_have(st, cfg.msg_window))[:, 0]
        assert have[np.asarray(st.subscribed)[:, 0]].mean() > 0.9
        # fanout degree bounded by D while alive
        fdeg = np.asarray(st.fanout).sum(axis=-1)
        assert fdeg.max() <= cfg.d

    def test_fanout_expires_after_ttl(self):
        cfg = self._cfg()
        sub = np.ones((32, 1), bool)
        sub[0, 0] = False
        topo = topology.dense(32, 8, degree=6)
        st = init_state(cfg, topo, subscribed=sub)
        tp = TopicParams.disabled(1)
        st = publish(st, cfg, jnp.array([0]), jnp.array([0]))
        for i in range(2):
            st = self._tick(st, cfg, tp, jax.random.PRNGKey(i))
        assert np.asarray(st.fanout)[0, 0].sum() > 0  # fanout formed
        for i in range(2, 8):  # run past lastpub + ttl with no new publish
            st = self._tick(st, cfg, tp, jax.random.PRNGKey(i))
        assert np.asarray(st.fanout)[0, 0].sum() == 0
        assert int(st.fanout_lastpub[0, 0]) >= 2**30


class TestGraftFloodPenalty:
    """GRAFT during backoff: one P7 point, doubled when the GRAFT lands
    within GraftFloodThreshold of the PRUNE (gossipsub.go:781-795)."""

    def _two_peer(self, tick, prune_tick):
        cfg = SimConfig(n_peers=2, k_slots=2, n_topics=1, msg_window=8,
                        publishers_per_tick=1, prop_substeps=1,
                        scoring_enabled=True,
                        prune_backoff_ticks=60, graft_flood_ticks=10)
        topo = topology.full(2, 2)
        st = init_state(cfg, topo)
        # peer 0 holds a backoff against peer 1 (slot of 1 in 0's table),
        # set by a prune at prune_tick; peer 1's mesh is empty so its
        # heartbeat grafts peer 0
        slot01 = int(np.argwhere(np.asarray(st.neighbors[0]) == 1)[0, 0])
        st = st._replace(
            tick=jnp.int32(tick),
            backoff=st.backoff.at[0, 0, slot01].set(prune_tick + 60))
        return cfg, st, slot01

    def test_flood_window_doubles_penalty(self):
        # prune at 95 -> backoff till 155, flood window till 105
        cfg, st, slot01 = self._two_peer(tick=100, prune_tick=95)
        out = heartbeat(st, cfg, TopicParams.disabled(1), jax.random.PRNGKey(0))
        assert float(out.state.behaviour_penalty[0, slot01]) == 2.0

    def test_late_graft_single_penalty(self):
        cfg, st, slot01 = self._two_peer(tick=120, prune_tick=95)
        out = heartbeat(st, cfg, TopicParams.disabled(1), jax.random.PRNGKey(0))
        assert float(out.state.behaviour_penalty[0, slot01]) == 1.0

    def test_expired_backoff_no_penalty(self):
        cfg, st, slot01 = self._two_peer(tick=200, prune_tick=95)
        out = heartbeat(st, cfg, TopicParams.disabled(1), jax.random.PRNGKey(0))
        assert float(out.state.behaviour_penalty[0, slot01]) == 0.0
