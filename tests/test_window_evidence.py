"""Machine-check the TPU window-evidence chain (VERDICT r4 item 8).

The repo's on-chip numbers live in committed logs under
``tpu_watch_results/`` and are QUOTED in BASELINE.md's config table. Two
things may not drift silently:

1. every promoted bench log line must actually say ``"platform": "tpu"``
   (the watcher's promotion rule — a CPU-fallback log must never pass as
   chip evidence; directories carrying a PLATFORM_UNVERIFIED marker are
   exempt because they are explicitly quarantined);
2. every bold ``**X hb/s**`` figure in BASELINE.md's table must match a
   promoted log line for its config, to the quoted precision.
"""

import json
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "tpu_watch_results")

# BASELINE.md table row label fragments -> bench config metric names
ROW_CONFIGS = {
    "1k-peer single-topic": ["1k_single_topic"],
    "Ethereum beacon": ["10k_beacon"],
    "peer_gater + churn": ["50k_churn_gater_px"],
    "20% sybils": ["100k_sybil20"],
    "floodsub / randomsub / gossipsub sweep":
        ["100k_floodsub", "100k_randomsub", "100k_gossipsub_sweep"],
    "default gossipsub (headline)": ["100k_default"],
}


def _promoted_logs():
    logs = []
    if not os.path.isdir(RESULTS):
        return logs
    for d in sorted(os.listdir(RESULTS)):
        full = os.path.join(RESULTS, d)
        if not os.path.isdir(full) or \
                os.path.exists(os.path.join(full, "PLATFORM_UNVERIFIED")):
            continue
        for f in sorted(os.listdir(full)):
            if f.startswith("bench") and f.endswith(".log"):
                logs.append(os.path.join(full, f))
    return logs


def _metric_lines(path):
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in rec and "value" in rec:
                out.append(rec)
    return out


def test_promoted_bench_logs_are_all_tpu():
    logs = _promoted_logs()
    assert logs, "no promoted bench logs under tpu_watch_results/"
    for path in logs:
        recs = _metric_lines(path)
        assert recs, f"{path}: no metric lines"
        for rec in recs:
            assert rec.get("platform") == "tpu", \
                f"{path}: non-TPU metric line promoted as chip evidence: " \
                f"{rec['metric']}"


def _log_values():
    """config name -> set of promoted values across all window logs."""
    vals = {}
    for path in _promoted_logs():
        for rec in _metric_lines(path):
            m = re.match(r"network_heartbeats_per_sec@(\w+?)\[", rec["metric"])
            if m:
                vals.setdefault(m.group(1), set()).add(float(rec["value"]))
    return vals


def _quoted_matches(quoted: float, measured: set) -> bool:
    """A quoted figure matches if some measured value rounds to it at the
    quoted precision (29.9 quotes 29.88; 1.81 quotes 1.81)."""
    digits = len(str(quoted).split(".")[1]) if "." in str(quoted) else 0
    return any(round(v, digits) == quoted for v in measured)


def _quarantined_logs():
    """Bench logs under directories carrying a PLATFORM_UNVERIFIED marker
    — exempt from the platform=tpu promotion rule, and therefore NEVER
    allowed to back a BASELINE.md figure."""
    logs = []
    if not os.path.isdir(RESULTS):
        return logs
    for d in sorted(os.listdir(RESULTS)):
        full = os.path.join(RESULTS, d)
        if not os.path.isdir(full) or \
                not os.path.exists(os.path.join(full, "PLATFORM_UNVERIFIED")):
            continue
        for f in sorted(os.listdir(full)):
            if f.startswith("bench") and f.endswith(".log"):
                logs.append(os.path.join(full, f))
    return logs


def test_quarantined_logs_are_never_cited_by_baseline():
    """Close the PLATFORM_UNVERIFIED escape hatch (VERDICT r5 weak #6):
    the marker exempts a directory from the platform=tpu check, but a
    quarantined log must then be invisible to BASELINE.md — any bold
    figure that matches a quarantined value without a promoted log also
    carrying it means the quarantine laundered un-verified evidence into
    the table."""
    q_vals = {}
    for path in _quarantined_logs():
        for rec in _metric_lines(path):
            m = re.match(r"network_heartbeats_per_sec@(\w+?)\[", rec["metric"])
            if m:
                q_vals.setdefault(m.group(1), set()).add(float(rec["value"]))
    if not q_vals:
        return          # no quarantined evidence exists — nothing to launder
    p_vals = _log_values()
    table = open(os.path.join(REPO, "BASELINE.md")).read()
    for line in table.splitlines():
        for frag, configs in ROW_CONFIGS.items():
            if frag not in line:
                continue
            for bold in re.findall(r"\*\*([^*]+?)\s*hb/s\*\*", line):
                nums = [float(x) for x in re.findall(r"\d+(?:\.\d+)?", bold)]
                if len(nums) != len(configs):
                    continue    # range rows: the promoted-evidence test
                                # above already requires promoted logs
                # positional pairing, as the promoted-log test does: a
                # multi-figure row maps figure i -> config i
                for cfgname, q in zip(configs, nums):
                    laundered = _quoted_matches(
                        q, q_vals.get(cfgname, set())) and \
                        not _quoted_matches(q, p_vals.get(cfgname, set()))
                    assert not laundered, \
                        f"{cfgname}: quoted {q} is backed ONLY by a " \
                        f"quarantined (PLATFORM_UNVERIFIED) log"


def test_baseline_table_numbers_come_from_promoted_logs():
    vals = _log_values()
    assert vals, "no promoted metric values found"
    table = open(os.path.join(REPO, "BASELINE.md")).read()
    checked = 0
    for line in table.splitlines():
        for frag, configs in ROW_CONFIGS.items():
            if frag not in line:
                continue
            # bold chip figures: **a hb/s**, **a / b / c hb/s**, **a–b hb/s**
            for bold in re.findall(r"\*\*([^*]+?)\s*hb/s\*\*", line):
                nums = [float(x) for x in re.findall(r"\d+(?:\.\d+)?", bold)]
                if "–" in bold or "-" in bold.strip("0123456789. "):
                    # a measured range: evidence must EXIST and every
                    # config value must fall inside it
                    lo, hi = min(nums), max(nums)
                    for cfgname in configs:
                        assert vals.get(cfgname), \
                            f"range row quotes {cfgname} with no promoted log"
                        for v in vals[cfgname]:
                            assert lo <= round(v, 1) <= hi, \
                                f"{cfgname}: {v} outside quoted {bold!r}"
                    checked += 1
                    continue
                assert len(nums) == len(configs), (line, nums, configs)
                for cfgname, q in zip(configs, nums):
                    assert cfgname in vals, f"no promoted log for {cfgname}"
                    assert _quoted_matches(q, vals[cfgname]), \
                        f"{cfgname}: quoted {q} not in promoted logs " \
                        f"{sorted(vals[cfgname])}"
                    checked += 1
    assert checked >= 6, f"only {checked} BASELINE figures cross-checked — " \
        "table format drifted from what this test parses"
