"""Multi-process execution plane (parallel/multihost.py, ISSUE 8).

Three lenses:

1. **Local-shard construction** — ``init_state_local`` per virtual
   process, concatenated hosts-major, equals the full ``init_state``
   build field for field (the 1M-peer claim in miniature: the shards ARE
   the state).
2. **2-process CPU distributed smoke** — the REAL
   ``jax.distributed.initialize`` path: two subprocesses drive
   ``scripts/run_multihost.py`` against a localhost coordinator (gloo CPU
   collectives), rank 0 dumps the final gathered state, and the parent
   pins it bit-exact against the single-process
   ``engine.run(st, cfg, tp, PRNGKey(seed), ticks)`` trajectory — plus a
   resume leg: a longer second run restores rank 0's checkpoint on both
   ranks and still lands on the single-scan trajectory.
3. **Memory budget** — ``state_nbytes`` accounting: the frontier_1m
   state fits the per-shard budget on an 8-way mesh (the acceptance
   line recorded in PERF_MODEL.md), and the accounting matches the
   bytes a real (small) state actually allocates.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import SimConfig, scenarios
from go_libp2p_pubsub_tpu.sim.state import SimState, state_nbytes, state_spec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestLocalShards:
    @pytest.mark.parametrize("n_proc", [2, 4])
    def test_concat_of_local_shards_equals_full_init(self, n_proc):
        from go_libp2p_pubsub_tpu.parallel.multihost import init_state_local
        from go_libp2p_pubsub_tpu.sim import init_state

        cfg, tp, topo, subscribed = scenarios.frontier_spec(
            128, k_slots=16, degree=6)
        full = init_state(cfg, topo, subscribed=subscribed)
        locals_ = [init_state_local(cfg, topo, p, n_proc,
                                    subscribed=subscribed)
                   for p in range(n_proc)]
        spec = state_spec(cfg)
        for f in SimState._fields:
            want = np.asarray(getattr(full, f))
            if spec[f][2]:                      # peer-major: concat rows
                got = np.concatenate(
                    [np.asarray(getattr(s, f)) for s in locals_])
            else:                               # replicated: all identical
                parts = [np.asarray(getattr(s, f)) for s in locals_]
                for p in parts[1:]:
                    np.testing.assert_array_equal(parts[0], p, err_msg=f)
                got = parts[0]
            np.testing.assert_array_equal(want, got, err_msg=f)

    def test_local_rows_validation(self):
        from go_libp2p_pubsub_tpu.parallel.multihost import local_peer_rows
        assert local_peer_rows(128, 4, 3) == (96, 32)
        with pytest.raises(ValueError, match="divide evenly"):
            local_peer_rows(100, 3, 0)
        with pytest.raises(ValueError, match="outside"):
            local_peer_rows(128, 4, 4)


def _spawn_rank(rank, port, extra, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    # the launcher process must see exactly ONE local CPU device per rank
    # (the conftest 8-device flag would make an 8x2-device mesh)
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "run_multihost.py"),
         "--coordinator", f"localhost:{port}", "--num-processes", "2",
         "--process-id", str(rank), "--scenario", "frontier_250k",
         "--n", "128", "--seed", "7"] + extra,
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=str(tmp_path))


def _run_pair(port, extra, tmp_path):
    procs = [_spawn_rank(r, port, extra, tmp_path) for r in range(2)]
    # generous: two fresh jax imports + gloo handshake + compile share one
    # CPU core on the CI container
    outs = [p.communicate(timeout=600) for p in procs]
    for (out, err), p in zip(outs, procs):
        assert p.returncode == 0, f"rank rc={p.returncode}\n{err[-3000:]}"
    return outs


def _reference(ticks, of_schedule=None):
    """Single-process trajectory for the launcher's key discipline:
    ``supervised_run`` pre-splits PRNGKey(seed) into ``n_ticks`` per-tick
    keys. ``of_schedule`` computes a PREFIX of a longer schedule (the
    window-bounded first leg runs ticks [0, ticks) of an
    ``of_schedule``-tick run — per-tick keys are a function of the FULL
    schedule length)."""
    import jax

    from go_libp2p_pubsub_tpu.sim import init_state
    from go_libp2p_pubsub_tpu.sim.engine import run_keys
    cfg, tp, topo, sub = scenarios.frontier_spec(128)
    st = init_state(cfg, topo, subscribed=sub)
    keys = jax.random.split(jax.random.PRNGKey(7), of_schedule or ticks)
    return run_keys(st, cfg, tp, keys[:ticks])


def test_two_process_cpu_run_is_bit_exact(tmp_path):
    """The acceptance smoke: 2 real processes over jax.distributed on
    localhost (gloo CPU collectives), global trajectory == the
    single-process scan. Tier-1: one pair, no checkpointing — the
    window-bounded checkpoint/resume discipline rides the slow-tier
    sibling below."""
    dump1 = tmp_path / "run1.npz"
    _run_pair(19917, ["--ticks", "3", "--dump-state", str(dump1)], tmp_path)
    ref = _reference(3)
    got = np.load(dump1)
    for f in SimState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), got[f],
            err_msg=f"field {f} diverged (2-process vs single)")


def test_two_process_window_resume(tmp_path):
    """Window-bounded execution + resume across 2 real processes: the
    first leg runs 2 of 3 chunks of a 6-tick schedule and checkpoints
    (rank-0-only writes, collective gathers); the second leg re-requests
    the SAME schedule, restores the t4 checkpoint on BOTH ranks (each
    slices its rows and re-assembles), and completes to the 6-tick
    single-scan trajectory."""
    dump1 = tmp_path / "run1.npz"
    ckpt = tmp_path / "ckpt"
    _run_pair(19918, ["--ticks", "6", "--chunk-ticks", "2",
                      "--max-chunks", "2",
                      "--checkpoint-dir", str(ckpt),
                      "--dump-state", str(dump1)], tmp_path)
    ref4 = _reference(4, of_schedule=6)
    got = np.load(dump1)
    for f in SimState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref4, f)), got[f],
            err_msg=f"field {f} diverged (2-process vs single)")
    # rank-0-only write discipline: checkpoints exist
    from go_libp2p_pubsub_tpu.sim.supervisor import list_checkpoints
    ckpts = list_checkpoints(str(ckpt))
    assert ckpts and ckpts[-1][1] == 4, ckpts

    # resume leg: the SAME 6-tick schedule restores the t4 checkpoint
    # (every rank reads it, slices its rows, re-assembles) and completes
    # to the 6-tick single-scan trajectory
    dump2 = tmp_path / "run2.npz"
    outs = _run_pair(19919, ["--ticks", "6", "--chunk-ticks", "2",
                             "--checkpoint-dir", str(ckpt),
                             "--dump-state", str(dump2)], tmp_path)
    ref6 = _reference(6)
    got2 = np.load(dump2)
    for f in SimState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref6, f)), got2[f],
            err_msg=f"field {f} diverged after resume")
    rank0_line = [json.loads(ln) for ln in outs[0][0].splitlines()
                  if ln.startswith("{") and "metric" in ln]
    assert rank0_line and rank0_line[0]["resumed_from"], \
        "second run did not resume from the checkpoint"


class TestMemoryBudget:
    # v5e-class HBM per chip; the state must leave most of it for the
    # step's transients (hop-loop word planes, sort buffers)
    HBM_BYTES = 16 * 1024 ** 3
    STATE_BUDGET_FRACTION = 0.25

    def test_frontier_1m_fits_8_way_mesh(self):
        # the REAL scenario config (no topology build — accounting needs
        # only shapes), so a frontier_spec shape change is priced here too
        cfg = scenarios.frontier_cfg(scenarios.FRONTIER_NS["frontier_1m"])
        acct = state_nbytes(cfg, n_dev=8)
        assert acct["per_shard"] <= self.HBM_BYTES * \
            self.STATE_BUDGET_FRACTION, (
            f"frontier_1m per-shard state {acct['per_shard'] / 2**30:.2f} "
            "GiB blows the budget")
        # the packed seen-set is 8x smaller than the old [N, M] bool plane
        n, m = cfg.n_peers, cfg.msg_window
        assert acct["fields"]["have"] == n * ((m + 31) // 32) * 4
        assert acct["fields"]["have"] * 8 == n * m

    def test_accounting_matches_allocation(self):
        from go_libp2p_pubsub_tpu.sim import init_state
        cfg, _tp, topo, sub = scenarios.frontier_spec(256, k_slots=16,
                                                      degree=6)
        st = init_state(cfg, topo, subscribed=sub)
        measured = sum(np.asarray(x).nbytes for x in st)
        assert measured == state_nbytes(cfg)["total"]

    def test_compact_accounting_matches_allocation(self):
        """The compact layout's accounting is also what a real state
        allocates — the codecs (sim/state.py) and the spec are the same
        truth."""
        from go_libp2p_pubsub_tpu.sim import init_state
        cfg, _tp, topo, sub = scenarios.frontier_spec(
            256, k_slots=16, degree=6, state_precision="compact")
        st = init_state(cfg, topo, subscribed=sub)
        measured = sum(np.asarray(x).nbytes for x in st)
        assert measured == state_nbytes(cfg)["total"]

    def test_compact_halves_frontier_1m_per_shard(self):
        """The ISSUE 13 acceptance line: frontier_1m per-shard bytes on
        the 8-way mesh drop >= 2x under state_precision='compact'."""
        n = scenarios.FRONTIER_NS["frontier_1m"]
        f32 = state_nbytes(scenarios.frontier_cfg(n), 8)["per_shard"]
        compact = state_nbytes(scenarios.frontier_cfg(
            n, state_precision="compact"), 8)["per_shard"]
        assert f32 >= 2 * compact, (
            f"compact saves only {f32 / compact:.3f}x "
            f"({f32 / 2**30:.3f} -> {compact / 2**30:.3f} GiB/shard)")

    def test_frontier_10m_compact_fits_8_way_mesh(self):
        """The 10M frontier prices under the per-chip HBM budget on 8
        shards BEFORE anything allocates — compact storage is what makes
        the scenario priceable at all (f32 does not fit the same
        fraction)."""
        n = scenarios.FRONTIER_NS["frontier_10m"]
        compact = state_nbytes(scenarios.frontier_cfg(
            n, state_precision="compact"), 8)["per_shard"]
        assert compact <= self.HBM_BYTES * self.STATE_BUDGET_FRACTION, (
            f"frontier_10m compact per-shard {compact / 2**30:.2f} GiB "
            "blows the budget")
        f32 = state_nbytes(scenarios.frontier_cfg(n), 8)["per_shard"]
        assert f32 > self.HBM_BYTES * self.STATE_BUDGET_FRACTION, (
            "positive control: the f32 layout at 10M should NOT fit — "
            "if it does, the compact tier is pointless")

    def test_state_nbytes_2d_mesh_dict(self):
        """A {'dcn': 2, 'peers': 4} mesh dict prices identically to the
        flat 8-way sharding (the peer axis shards over every mesh axis)
        and echoes the mesh in the accounting."""
        cfg = scenarios.frontier_cfg(scenarios.FRONTIER_NS["frontier_1m"])
        flat = state_nbytes(cfg, 8)
        mesh = state_nbytes(cfg, {"dcn": 2, "peers": 4})
        assert mesh["per_shard"] == flat["per_shard"]
        assert mesh["n_dev"] == 8 and mesh["mesh"] == {"dcn": 2, "peers": 4}

    def test_hbm_budget_gate_refuses_by_name(self):
        """check_hbm_budget (the launcher/bench gate): an over-budget
        config refuses citing the worst per-shard fields and the knobs
        that shrink them; under-budget returns the accounting."""
        from go_libp2p_pubsub_tpu.sim.state import (
            check_hbm_budget, hbm_budget_bytes)
        cfg = scenarios.frontier_cfg(scenarios.FRONTIER_NS["frontier_1m"])
        with pytest.raises(ValueError, match="GRAFT_HBM_BUDGET") as ei:
            check_hbm_budget(cfg, 8, budget=64 * 2 ** 20, what="test state")
        msg = str(ei.value)
        assert "worst fields" in msg and "state_precision" in msg
        acct = check_hbm_budget(cfg, 8, budget=self.HBM_BYTES)
        assert acct["per_shard"] == state_nbytes(cfg, 8)["per_shard"]
        # env parsing: suffixes and the unparseable refusal
        os.environ["GRAFT_HBM_BUDGET"] = "1.5GiB"
        try:
            assert hbm_budget_bytes() == int(1.5 * 2 ** 30)
            os.environ["GRAFT_HBM_BUDGET"] = "lots"
            with pytest.raises(ValueError, match="GRAFT_HBM_BUDGET"):
                hbm_budget_bytes()
        finally:
            del os.environ["GRAFT_HBM_BUDGET"]

    def test_divisibility_raises_by_name(self):
        cfg = SimConfig(n_peers=100, k_slots=8)
        with pytest.raises(ValueError, match="divide evenly"):
            state_nbytes(cfg, n_dev=8)


class TestShardedTopologyConstruction:
    """init_state_local(..., topo_local=True): the 10M construction path
    where each process's topology table carries ONLY its own rows
    (topology.sparse_hash rows=...)."""

    @pytest.mark.parametrize("n_proc", [2, 4])
    def test_topo_local_concat_equals_full_build(self, n_proc):
        from go_libp2p_pubsub_tpu.parallel.multihost import init_state_local
        from go_libp2p_pubsub_tpu.sim import init_state, topology

        n, k = 128, 16
        cfg, tp, topo, sub = scenarios.frontier_spec(n, k_slots=k, degree=6)
        # the full build on the SAME underlay the shards will construct
        full_topo = topology.sparse_hash(n, k, degree=6)
        full = init_state(cfg, full_topo, subscribed=sub)
        nl = n // n_proc
        locals_ = [
            init_state_local(
                cfg,
                topology.sparse_hash(n, k, degree=6, rows=(p * nl, nl)),
                p, n_proc, subscribed=sub, topo_local=True)
            for p in range(n_proc)]
        spec = state_spec(cfg)
        for f in SimState._fields:
            want = np.asarray(getattr(full, f))
            if spec[f][2]:
                got = np.concatenate(
                    [np.asarray(getattr(s, f)) for s in locals_])
            else:
                got = np.asarray(getattr(locals_[0], f))
            np.testing.assert_array_equal(want, got, err_msg=f)

    def test_wrong_shape_for_declared_mode_refuses_by_name(self):
        from go_libp2p_pubsub_tpu.parallel.multihost import init_state_local
        from go_libp2p_pubsub_tpu.sim import topology

        n, k = 128, 16
        cfg = scenarios.frontier_cfg(n, k_slots=k)
        full_topo = topology.sparse_hash(n, k, degree=6)
        local_topo = topology.sparse_hash(n, k, degree=6, rows=(0, n // 2))
        with pytest.raises(ValueError, match="topo_local"):
            init_state_local(cfg, full_topo, 0, 2, topo_local=True)
        with pytest.raises(ValueError, match="topo_local"):
            init_state_local(cfg, local_topo, 0, 2)
