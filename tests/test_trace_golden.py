"""Golden-trace differential gate: the repo's TraceEvent codec and replay
against a fixture assembled to the REFERENCE's wire encoding, not the repo's.

The byte stream below is hand-assembled by a mini-marshaller whose tag bytes
and field ordering are copied literally from the reference's generated
encoder (`/root/reference/pb/trace.pb.go` MarshalToSizedBuffer functions —
gogo-proto writes fields back-to-front, yielding ascending field order with
minimal varints; schema `/root/reference/pb/trace.proto:5-150`). It shares
no code with `pb/codec.py`, so a wire-layout divergence in either the
encoder or the decoder fails these tests — this closed VERDICT r2 "Missing
#1" (the previous differential loop only consumed repo-produced traces, and
indeed the repo encoded Leave.topic as field 1 where the reference uses
field 2, trace.pb.go TraceEvent_Leave tag byte 0x12).

Checks:
  1. decoding the golden bytes yields the expected event dicts;
  2. re-encoding those dicts via pb/codec.py is BYTE-EXACT to the fixture
     (realistic UnixNano timestamps > 2**53 exercise the timestamp_ns path);
  3. the decoded stream replays through trace/replay.py with the mesh /
     score / delivery semantics the reference's tracer hooks imply
     (trace.go:70-531, score.go:899-981);
  4. the native C++ tensorizer consumes the same bytes to the same feed as
     the Python tensorizer (catches native/Python schema drift — the Leave
     field bug existed in both).
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.pb import codec
from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
from go_libp2p_pubsub_tpu.core.params import TopicScoreParams
from go_libp2p_pubsub_tpu.trace import native as trace_native
from go_libp2p_pubsub_tpu.trace import (
    replay_feed,
    replay_topic_params,
    tensorize_trace,
)

# --- mini gogo-proto marshaller (tag bytes from trace.pb.go, see docstring) —
# deliberately NOT pb/codec.py ---


def _uv(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _ld(tag: bytes, payload: bytes) -> bytes:
    # length-delimited field: literal tag byte(s) + varint length + payload
    return tag + _uv(len(payload)) + payload


def _publish(mid: bytes, topic: str) -> bytes:
    # TraceEvent_PublishMessage: messageID 0xa, topic 0x12
    return _ld(b"\x0a", mid) + _ld(b"\x12", topic.encode())


def _reject(mid: bytes, frm: bytes, reason: str, topic: str) -> bytes:
    # RejectMessage: messageID 0xa, receivedFrom 0x12, reason 0x1a, topic 0x22
    return (_ld(b"\x0a", mid) + _ld(b"\x12", frm) +
            _ld(b"\x1a", reason.encode()) + _ld(b"\x22", topic.encode()))


def _duplicate(mid: bytes, frm: bytes, topic: str) -> bytes:
    # DuplicateMessage: messageID 0xa, receivedFrom 0x12, topic 0x1a
    return _ld(b"\x0a", mid) + _ld(b"\x12", frm) + _ld(b"\x1a", topic.encode())


def _deliver(mid: bytes, topic: str, frm: bytes) -> bytes:
    # DeliverMessage: messageID 0xa, topic 0x12, receivedFrom 0x1a
    return _ld(b"\x0a", mid) + _ld(b"\x12", topic.encode()) + _ld(b"\x1a", frm)


def _add_peer(pid: bytes, proto: str) -> bytes:
    # AddPeer: peerID 0xa, proto 0x12
    return _ld(b"\x0a", pid) + _ld(b"\x12", proto.encode())


def _remove_peer(pid: bytes) -> bytes:
    return _ld(b"\x0a", pid)                      # RemovePeer: peerID 0xa


def _rpc(peer: bytes, meta: bytes) -> bytes:
    # RecvRPC/SendRPC/DropRPC: receivedFrom|sendTo 0xa, meta 0x12
    return _ld(b"\x0a", peer) + _ld(b"\x12", meta)


def _join(topic: str) -> bytes:
    return _ld(b"\x0a", topic.encode())           # Join: topic 0xa


def _leave(topic: str) -> bytes:
    # Leave: topic is FIELD 2 — tag 0x12 (trace.pb.go TraceEvent_Leave)
    return _ld(b"\x12", topic.encode())


def _graft_or_prune(pid: bytes, topic: str) -> bytes:
    # Graft/Prune: peerID 0xa, topic 0x12
    return _ld(b"\x0a", pid) + _ld(b"\x12", topic.encode())


def _meta(messages=(), subscription=(), control=None) -> bytes:
    # RPCMeta: messages 0xa, subscription 0x12, control 0x1a
    out = bytearray()
    for mid, topic in messages:
        out += _ld(b"\x0a", _ld(b"\x0a", mid) + _ld(b"\x12", topic.encode()))
    for subscribe, topic in subscription:
        out += _ld(b"\x12", b"\x08" + _uv(1 if subscribe else 0) +
                   _ld(b"\x12", topic.encode()))
    if control is not None:
        out += _ld(b"\x1a", control)
    return bytes(out)


def _control(ihave=(), iwant=(), graft=(), prune=()) -> bytes:
    # ControlMeta: ihave 0xa, iwant 0x12, graft 0x1a, prune 0x22
    out = bytearray()
    for topic, mids in ihave:                     # IHaveMeta: topic 0xa, mids 0x12
        body = _ld(b"\x0a", topic.encode())
        for m in mids:
            body += _ld(b"\x12", m)
        out += _ld(b"\x0a", body)
    for mids in iwant:                            # IWantMeta: mids 0xa
        body = b"".join(_ld(b"\x0a", m) for m in mids)
        out += _ld(b"\x12", body)
    for topic in graft:                           # GraftMeta: topic 0xa
        out += _ld(b"\x1a", _ld(b"\x0a", topic.encode()))
    for topic, peers in prune:                    # PruneMeta: topic 0xa, peers 0x12
        body = _ld(b"\x0a", topic.encode())
        for p in peers:
            body += _ld(b"\x12", p)
        out += _ld(b"\x22", body)
    return bytes(out)


_PAYLOAD_TAGS = {  # TraceEvent payload fields 4..16 (trace.pb.go:1603-1776)
    "PUBLISH_MESSAGE": b"\x22", "REJECT_MESSAGE": b"\x2a",
    "DUPLICATE_MESSAGE": b"\x32", "DELIVER_MESSAGE": b"\x3a",
    "ADD_PEER": b"\x42", "REMOVE_PEER": b"\x4a", "RECV_RPC": b"\x52",
    "SEND_RPC": b"\x5a", "DROP_RPC": b"\x62", "JOIN": b"\x6a",
    "LEAVE": b"\x72", "GRAFT": b"\x7a", "PRUNE": b"\x82\x01",
}


def _event(typ: str, observer: bytes, ts_ns: int, payload: bytes) -> bytes:
    # TraceEvent: type 0x08 varint, peerID 0x12, timestamp 0x18 varint
    body = (b"\x08" + _uv(codec.TRACE_TYPES[typ]) + _ld(b"\x12", observer) +
            b"\x18" + _uv(ts_ns) + _ld(_PAYLOAD_TAGS[typ], payload))
    return _uv(len(body)) + body                  # uvarint-delimited framing


# --- the fixture: a 2-peer session touching all 13 event types ---

PEER_A = bytes([0x12, 0x20]) + bytes(range(0xA0, 0xC0))  # raw sha256 multihash
PEER_B = bytes([0x12, 0x20]) + bytes(range(0x60, 0x80))
A = PEER_A.decode("utf-8", "surrogateescape")
B = PEER_B.decode("utf-8", "surrogateescape")
MID1, MID2 = b"\x01\x02\x03\x04", b"\xff\xfe\xfd\xfc"
TOPIC = "test-topic"
PROTO = "/meshsub/1.1.0"
T0_NS = 1_785_000_000_000_000_000   # ~2026 UnixNano, NOT float-representable


def _ts(k: int) -> int:
    return T0_NS + k * 250_000_000  # quarter-second steps


def build_golden(t0_ns: int = T0_NS) -> bytes:
    def ts(k):
        return t0_ns + k * 250_000_000

    full_meta = _meta(
        subscription=[(True, TOPIC)],
        control=_control(graft=[TOPIC]))
    return b"".join([
        _event("ADD_PEER", PEER_A, ts(0), _add_peer(PEER_B, PROTO)),
        _event("ADD_PEER", PEER_B, ts(1), _add_peer(PEER_A, PROTO)),
        _event("JOIN", PEER_A, ts(2), _join(TOPIC)),
        _event("JOIN", PEER_B, ts(3), _join(TOPIC)),
        _event("GRAFT", PEER_A, ts(4), _graft_or_prune(PEER_B, TOPIC)),
        _event("SEND_RPC", PEER_A, ts(5), _rpc(PEER_B, full_meta)),
        _event("RECV_RPC", PEER_B, ts(6), _rpc(PEER_A, full_meta)),
        _event("GRAFT", PEER_B, ts(7), _graft_or_prune(PEER_A, TOPIC)),
        _event("PUBLISH_MESSAGE", PEER_A, ts(8), _publish(MID1, TOPIC)),
        _event("SEND_RPC", PEER_A, ts(8), _rpc(PEER_B, _meta(
            messages=[(MID1, TOPIC)],
            control=_control(ihave=[(TOPIC, [MID1])])))),
        _event("DELIVER_MESSAGE", PEER_B, ts(9), _deliver(MID1, TOPIC, PEER_A)),
        _event("DUPLICATE_MESSAGE", PEER_B, ts(9), _duplicate(MID1, PEER_A, TOPIC)),
        _event("REJECT_MESSAGE", PEER_B, ts(11),
               _reject(MID2, PEER_A, "invalid signature", TOPIC)),
        _event("DROP_RPC", PEER_A, ts(12), _rpc(PEER_B, _meta(
            control=_control(iwant=[[MID1]],
                             prune=[(TOPIC, [PEER_B])])))),
        _event("PRUNE", PEER_A, ts(13), _graft_or_prune(PEER_B, TOPIC)),
        _event("LEAVE", PEER_B, ts(14), _leave(TOPIC)),
        _event("REMOVE_PEER", PEER_A, ts(15), _remove_peer(PEER_B)),
    ])


GOLDEN = build_golden()

_FULL_META = {
    "subscription": [{"subscribe": True, "topic": TOPIC}],
    "control": {"graft": [{"topic": TOPIC}]},
}
_M1, _M2 = MID1.decode("latin-1"), MID2.decode("latin-1")


def _exp(typ, obs, k, **payload):
    ns = _ts(k)
    return {"type": typ, "peerID": obs, "timestamp": ns / 1e9,
            "timestamp_ns": ns, **payload}


EXPECTED = [
    _exp("ADD_PEER", A, 0, addPeer={"peerID": B, "proto": PROTO}),
    _exp("ADD_PEER", B, 1, addPeer={"peerID": A, "proto": PROTO}),
    _exp("JOIN", A, 2, join={"topic": TOPIC}),
    _exp("JOIN", B, 3, join={"topic": TOPIC}),
    _exp("GRAFT", A, 4, graft={"peerID": B, "topic": TOPIC}),
    _exp("SEND_RPC", A, 5, sendRPC={"sendTo": B, "meta": _FULL_META}),
    _exp("RECV_RPC", B, 6, recvRPC={"receivedFrom": A, "meta": _FULL_META}),
    _exp("GRAFT", B, 7, graft={"peerID": A, "topic": TOPIC}),
    _exp("PUBLISH_MESSAGE", A, 8,
         publishMessage={"messageID": _M1, "topic": TOPIC}),
    _exp("SEND_RPC", A, 8, sendRPC={"sendTo": B, "meta": {
        "messages": [{"messageID": _M1, "topic": TOPIC}],
        "control": {"ihave": [{"topic": TOPIC, "messageIDs": [_M1]}]}}}),
    _exp("DELIVER_MESSAGE", B, 9, deliverMessage={
        "messageID": _M1, "topic": TOPIC, "receivedFrom": A}),
    _exp("DUPLICATE_MESSAGE", B, 9, duplicateMessage={
        "messageID": _M1, "receivedFrom": A, "topic": TOPIC}),
    _exp("REJECT_MESSAGE", B, 11, rejectMessage={
        "messageID": _M2, "receivedFrom": A, "reason": "invalid signature",
        "topic": TOPIC}),
    _exp("DROP_RPC", A, 12, dropRPC={"sendTo": B, "meta": {
        "control": {"iwant": [{"messageIDs": [_M1]}],
                    "prune": [{"topic": TOPIC, "peers": [B]}]}}}),
    _exp("PRUNE", A, 13, prune={"peerID": B, "topic": TOPIC}),
    _exp("LEAVE", B, 14, leave={"topic": TOPIC}),
    _exp("REMOVE_PEER", A, 15, removePeer={"peerID": B}),
]


class TestGoldenWire:
    def test_decode_golden(self):
        assert codec.decode_trace_bytes(GOLDEN) == EXPECTED

    def test_encode_byte_exact(self):
        """pb/codec.py must reproduce the reference encoder's exact bytes."""
        enc = b"".join(
            codec.write_uvarint(len(e)) + e
            for e in (codec.encode_trace_event(evt) for evt in EXPECTED))
        assert enc == GOLDEN

    def test_every_event_type_covered(self):
        assert {e["type"] for e in EXPECTED} == set(codec.TRACE_TYPES)

    def test_realistic_timestamps_not_float_exact(self):
        """The fixture must exercise the timestamp_ns path: UnixNano values
        this large do not survive a float-seconds round-trip."""
        assert int((_ts(1) / 1e9) * 1e9) != _ts(1)


# --- replay the decoded golden stream into the batched engine ---


def _replay_setup():
    # timestamps rebased to small values: replay decay boundaries are
    # absolute multiples of decay_interval (trace/replay.py:136)
    events = codec.decode_trace_bytes(build_golden(t0_ns=250_000_000))
    peer_index = {A: 0, B: 1}
    topic_index = {TOPIC: 0}
    feed = tensorize_trace(events, peer_index, topic_index,
                              msg_window=16, decay_interval=1.0, t_end=5.0)
    cfg = SimConfig(n_peers=2, k_slots=4, n_topics=1, msg_window=16,
                    scoring_enabled=True)
    topo = topology.full(2, 4)   # slot 0 of each peer is the other peer
    st = init_state(cfg, topo, subscribed=np.zeros((2, 1), bool))
    tp = replay_topic_params([TopicScoreParams(
        topic_weight=1.0, time_in_mesh_weight=0.05, time_in_mesh_quantum=1.0,
        time_in_mesh_cap=100.0, first_message_deliveries_weight=1.0,
        first_message_deliveries_decay=0.9, first_message_deliveries_cap=50.0,
        mesh_message_deliveries_weight=-0.5, mesh_message_deliveries_decay=0.8,
        mesh_message_deliveries_cap=30.0, mesh_message_deliveries_threshold=3.0,
        mesh_message_deliveries_window=0.05,
        mesh_message_deliveries_activation=4.0,
        mesh_failure_penalty_weight=-1.0, mesh_failure_penalty_decay=0.7,
        invalid_message_deliveries_weight=-5.0,
        invalid_message_deliveries_decay=0.9)])
    st = replay_feed(st, cfg, tp, feed)
    return st, feed, events


@pytest.fixture(scope="module")
def golden_replay():
    return _replay_setup()


class TestGoldenReplay:
    def test_mesh_final_state(self, golden_replay):
        st, _, _ = golden_replay
        mesh = np.asarray(st.mesh_active)
        # A grafted B then pruned; B grafted A then left the topic
        assert not mesh.any()

    def test_first_delivery_credited(self, golden_replay):
        st, _, _ = golden_replay
        fmd = np.asarray(st.first_message_deliveries)
        # B's slot-0 neighbor is A: DELIVER(mid1 from A) -> P2 credit at B
        assert fmd[1, 0, 0] > 0.0
        # A received nothing
        assert fmd[0].sum() == 0.0

    def test_invalid_delivery_credited(self, golden_replay):
        st, _, _ = golden_replay
        inv = np.asarray(st.invalid_message_deliveries)
        # REJECT(mid2 from A, "invalid signature") -> P4 debit at B for A
        assert inv[1, 0, 0] > 0.0

    def test_subscription_final_state(self, golden_replay):
        st, _, _ = golden_replay
        sub = np.asarray(st.subscribed)
        assert sub[0, 0] and not sub[1, 0]   # A joined; B joined then left


class TestGoldenNativeParity:
    def test_native_tensorizer_matches_python(self):
        if not trace_native.available():
            pytest.skip("no native toolchain")
        data = build_golden(t0_ns=250_000_000)
        events = codec.decode_trace_bytes(data)
        peer_index = {A: 0, B: 1}
        kw = dict(msg_window=16, decay_interval=1.0, t_end=5.0)
        py = tensorize_trace(events, peer_index, {TOPIC: 0}, **kw)
        nat = trace_native.tensorize_bytes(data, peer_index, {TOPIC: 0}, **kw)
        assert nat is not None
        for name in ("op", "a", "b", "c"):
            np.testing.assert_array_equal(
                getattr(py, name), getattr(nat, name), err_msg=name)
