"""Heavy-tailed underlay builder (sim/topology.powerlaw, ISSUE 15).

Pins the three contracts the degree-bucketed engine rides on:

- **Shard-build parity**: every row of every plane is a pure function of
  ``(n, d_min, d_max, alpha, seed, row)``, so ``rows=(start, count)``
  builds concat across RAGGED splits (including a short last shard) into
  exactly the full build, bit for bit.
- **Bucket consistency**: ``powerlaw_buckets`` tiles ``n``, ceilings are
  non-increasing (hubs first), the hub ceiling bounds the realized hub
  degree, and every peer's realized degree fits its bucket's ceiling —
  the precondition ``sim.bucketed.bucketize_state`` enforces at runtime.
- **degree_stats**: the bench-record/dashboard-header shape summary
  reports the realized min/mean/p99/max and a heavy-tail Gini.
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import topology


def _assert_topo_equal(a, b):
    np.testing.assert_array_equal(a.neighbors, b.neighbors)
    np.testing.assert_array_equal(a.outbound, b.outbound)
    np.testing.assert_array_equal(a.reverse_slot, b.reverse_slot)
    np.testing.assert_array_equal(a.degree, b.degree)


class TestShardParity:
    def test_ragged_splits_concat_to_full_build(self):
        """Ragged row splits — misaligned boundaries and a SHORT last
        shard — concat bit-for-bit into the full build."""
        n, k = 600, 16
        kw = dict(d_min=4, d_max=16, alpha=2.0, seed=11)
        full = topology.powerlaw(n, k, **kw)
        for bounds in ([0, 193, 450, 600], [0, 599, 600], [0, 7, 600]):
            parts = [topology.powerlaw(n, k, **kw, rows=(s, e - s))
                     for s, e in zip(bounds, bounds[1:])]
            cat = topology.Topology(
                *(np.concatenate([getattr(p, f) for p in parts])
                  for f in topology.Topology._fields))
            _assert_topo_equal(cat, full)

    def test_single_row_shard_matches(self):
        n, k = 128, 16
        kw = dict(d_min=4, d_max=16, alpha=2.0, seed=3)
        full = topology.powerlaw(n, k, **kw)
        one = topology.powerlaw(n, k, **kw, rows=(17, 1))
        np.testing.assert_array_equal(one.neighbors[0], full.neighbors[17])
        np.testing.assert_array_equal(one.reverse_slot[0],
                                      full.reverse_slot[17])

    def test_symmetric_and_duplicate_free(self):
        n, k = 256, 16
        topo = topology.powerlaw(n, k, d_min=4, d_max=16, seed=7)
        nbr, rsl = topo.neighbors, topo.reverse_slot
        for i in range(n):
            row = nbr[i][nbr[i] >= 0]
            assert len(set(row.tolist())) == len(row), f"dup nbrs at {i}"
            assert i not in row, f"self-edge at {i}"
        # reverse_slot closes the loop: neighbors[j, rsl] == i
        valid = (nbr >= 0) & (rsl >= 0)
        ii, ss = np.nonzero(valid)
        jj, rr = nbr[ii, ss], rsl[ii, ss]
        np.testing.assert_array_equal(nbr[jj, rr], ii)


class TestBuckets:
    def test_partition_tiles_and_bounds_degrees(self):
        n = 1024
        kw = dict(d_min=8, d_max=64, alpha=2.0)
        buckets = topology.powerlaw_buckets(n, **kw)
        assert sum(nb for nb, _ in buckets) == n
        ceils = [kb for _, kb in buckets]
        assert ceils == sorted(ceils, reverse=True), "hubs must come first"
        topo = topology.powerlaw(n, buckets[0][1], **kw, seed=5)
        start = 0
        for nb, kb in buckets:
            assert topo.degree[start:start + nb].max() <= kb, \
                f"bucket at rows [{start}, {start + nb}) overflows {kb}"
            start += nb
        # degrees are non-increasing with id (hubs are the LOW ids — the
        # region EclipseWindow targets)
        assert (np.diff(topo.degree) <= 0).all()

    def test_round_to_lane_friendly(self):
        for nb, kb in topology.powerlaw_buckets(2048, d_min=8, d_max=64,
                                                round_to=8):
            assert kb % 8 == 0


class TestDegreeStats:
    def test_known_sequence(self):
        stats = topology.degree_stats(np.array([2, 2, 2, 2]))
        assert stats == {"n": 4, "sum": 8, "min": 2, "max": 2,
                         "mean": 2.0, "p99": 2, "gini": 0.0}

    def test_heavy_tail_has_positive_gini(self):
        topo = topology.powerlaw(1024, 64, d_min=8, d_max=64, seed=5)
        stats = topology.degree_stats(topo)
        assert stats["min"] >= 8 and stats["max"] <= 64
        assert stats["n"] == 1024 and stats["sum"] == int(topo.degree.sum())
        uniform = topology.degree_stats(np.full(1024, 12))
        assert stats["gini"] > 0.2 > uniform["gini"] == 0.0

    def test_empty_refused(self):
        with pytest.raises(ValueError, match="empty"):
            topology.degree_stats(np.array([], dtype=np.int64))
