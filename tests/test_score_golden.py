"""Golden score values transcribed from the reference's score_test.go,
asserted as LITERAL constants against BOTH scorers:

- the functional per-node scorer (routers/score.py), driven through the same
  AddPeer/Graft/Deliver/refresh hook sequences the Go tests use;
- the batched sim scorer (ops/score_ops.py), driven through its own state
  transitions (decay_counters, apply_prune_penalty, churn_edges) on a tiny
  SimState.

A shared misreading of score.go can no longer hide behind matching
implementations: every expectation below is a number derived by hand from
the cited Go test, not computed by either implementation under test.

Sources: /root/reference/score_test.go — TestScoreTimeInMesh:13,
TimeInMeshCap:52, FirstMessageDeliveries:86, FMDCap:126, FMDDecay:166,
MeshMessageDeliveries:218, MMDDecay:310, MeshFailurePenalty:378,
InvalidMessageDeliveries:445, IMDDecay:482, ApplicationScore:668,
IPColocation:696, BehaviourPenalty:805, Retention:861.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.core.clock import VirtualClock
from go_libp2p_pubsub_tpu.core.params import PeerScoreParams, TopicScoreParams
from go_libp2p_pubsub_tpu.core.types import Message
from go_libp2p_pubsub_tpu.ops.churn import churn_edges
from go_libp2p_pubsub_tpu.ops.score_ops import (
    apply_prune_penalty, compute_scores, decay_counters)
from go_libp2p_pubsub_tpu.routers.score import PeerScore
from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology
from go_libp2p_pubsub_tpu.trace import events as ev

TOPIC = "mytopic"

# literal golden constants (hand-derived from the Go tests' parameters)
G_TIME_IN_MESH = 100.0          # 0.5 topic_w * 1 w * 200 quanta
G_TIME_IN_MESH_CAP = 5.0        # 0.5 * 1 * cap 10
G_FMD = 100.0                   # 1 * 1 * 100 msgs
G_FMD_CAP = 50.0                # capped at 50
G_FMD_DECAY_1 = 90.0            # 100 * 0.9
G_FMD_DECAY_11 = 31.381059609   # 100 * 0.9^11
G_MMD_C = -400.0                # -1 * (threshold 20)^2
G_MMD_DECAY = -244.0856416816794   # -(20 - 40*0.9^21)^2
G_MESH_FAILURE = -400.0         # -1 * (threshold 20)^2 on prune
G_IMD = -10000.0                # -1 * 100^2
G_IMD_DECAY = -8100.0           # -1 * (100*0.9)^2
G_APP_NEG = -50.0               # 0.5 * -100
G_APP_POS = 49.5                # 0.5 * 99
G_IP_COLOC = -4.0               # -1 * (3 shared - threshold 1)^2
G_BEHAVIOUR_1 = -1.0            # -1 * 1^2
G_BEHAVIOUR_2 = -4.0            # -1 * 2^2
G_BEHAVIOUR_DECAYED = -3.9204   # -1 * (2*0.99)^2
G_RETAINED = 9.0                # fmd 9 kept through early reconnect
G_EXPIRED = 0.0                 # counters cleared after retention


# ---------------------------------------------------------------- functional

def fn_params(**topic_kw) -> PeerScoreParams:
    defaults = dict(time_in_mesh_quantum=1.0)
    defaults.update(topic_kw)
    return PeerScoreParams(app_specific_score=lambda p: 0.0,
                           topics={TOPIC: TopicScoreParams(**defaults)})


def _msg(i: int, received_from: str) -> Message:
    return Message(from_peer="author", seqno=i.to_bytes(8, "big"), topic=TOPIC,
                   received_from=received_from)


class TestFunctionalGolden:
    def test_time_in_mesh(self):
        clk = VirtualClock()
        ps = PeerScore(fn_params(topic_weight=0.5, time_in_mesh_weight=1,
                                 time_in_mesh_quantum=1e-3,
                                 time_in_mesh_cap=3600), clk.now)
        ps.add_peer("A", "proto"); ps.graft("A", TOPIC)
        clk.advance_to(0.2)
        ps.refresh_scores()
        assert ps.score("A") == pytest.approx(G_TIME_IN_MESH)

    def test_time_in_mesh_cap(self):
        clk = VirtualClock()
        ps = PeerScore(fn_params(topic_weight=0.5, time_in_mesh_weight=1,
                                 time_in_mesh_quantum=1e-3,
                                 time_in_mesh_cap=10), clk.now)
        ps.add_peer("A", "proto"); ps.graft("A", TOPIC)
        clk.advance_to(0.04)
        ps.refresh_scores()
        assert ps.score("A") == pytest.approx(G_TIME_IN_MESH_CAP)

    def _deliver_n(self, ps, n, frm="A"):
        for i in range(n):
            m = _msg(i, frm)
            ps.validate_message(m)
            ps.deliver_message(m)

    def test_fmd_and_cap_and_decay(self):
        for cap, after_one in ((2000.0, G_FMD), (50.0, G_FMD_CAP)):
            clk = VirtualClock()
            ps = PeerScore(fn_params(
                topic_weight=1, first_message_deliveries_weight=1,
                first_message_deliveries_decay=1.0,
                first_message_deliveries_cap=cap), clk.now)
            ps.add_peer("A", "proto"); ps.graft("A", TOPIC)
            self._deliver_n(ps, 100)
            ps.refresh_scores()
            assert ps.score("A") == pytest.approx(after_one)

        clk = VirtualClock()
        ps = PeerScore(fn_params(
            topic_weight=1, first_message_deliveries_weight=1,
            first_message_deliveries_decay=0.9,
            first_message_deliveries_cap=2000.0), clk.now)
        ps.add_peer("A", "proto"); ps.graft("A", TOPIC)
        self._deliver_n(ps, 100)
        ps.refresh_scores()
        assert ps.score("A") == pytest.approx(G_FMD_DECAY_1)
        for _ in range(10):
            ps.refresh_scores()
        assert ps.score("A") == pytest.approx(G_FMD_DECAY_11)

    def _mmd_params(self, decay=1.0, activation=1.0):
        return fn_params(
            topic_weight=1, mesh_message_deliveries_weight=-1,
            mesh_message_deliveries_activation=activation,
            mesh_message_deliveries_window=0.01,
            mesh_message_deliveries_threshold=20,
            mesh_message_deliveries_cap=100,
            mesh_message_deliveries_decay=decay,
            first_message_deliveries_weight=0)

    def test_mesh_message_deliveries(self):
        clk = VirtualClock()
        ps = PeerScore(self._mmd_params(), clk.now)
        for p in "ABC":
            ps.add_peer(p, "proto"); ps.graft(p, TOPIC)
        ps.refresh_scores()
        assert all(ps.score(p) >= 0 for p in "ABC")
        clk.advance_to(1.5)     # past activation
        for i in range(100):
            m = _msg(i, "A")
            ps.validate_message(m)
            ps.deliver_message(m)
            ps.duplicate_message(_msg(i, "B"))          # within window
        clk.advance_to(1.53)                            # outside window
        for i in range(100):
            ps.duplicate_message(_msg(i, "C"))
        ps.refresh_scores()
        assert ps.score("A") >= 0
        assert ps.score("B") >= 0
        assert ps.score("C") == pytest.approx(G_MMD_C)

    def test_mmd_decay(self):
        clk = VirtualClock()
        ps = PeerScore(self._mmd_params(decay=0.9, activation=0.0), clk.now)
        ps.add_peer("A", "proto"); ps.graft("A", TOPIC)
        clk.advance_to(1e-6)    # activation 0 needs mesh_time > 0 (the Go
        self._deliver_n(ps, 40)  # test gets this from real elapsed time)
        ps.refresh_scores()
        assert ps.score("A") >= 0
        for _ in range(20):
            ps.refresh_scores()
        assert ps.score("A") == pytest.approx(G_MMD_DECAY)

    def test_mesh_failure_penalty(self):
        clk = VirtualClock()
        ps = PeerScore(fn_params(
            topic_weight=1, mesh_failure_penalty_weight=-1,
            mesh_failure_penalty_decay=1.0,
            mesh_message_deliveries_activation=0.0,
            mesh_message_deliveries_window=0.01,
            mesh_message_deliveries_threshold=20,
            mesh_message_deliveries_cap=100,
            mesh_message_deliveries_decay=1.0,
            mesh_message_deliveries_weight=0,
            first_message_deliveries_weight=0), clk.now)
        for p in "AB":
            ps.add_peer(p, "proto"); ps.graft(p, TOPIC)
        clk.advance_to(1e-6)    # activate P3 tracking (see test_mmd_decay)
        self._deliver_n(ps, 100, "A")
        ps.refresh_scores()
        assert ps.score("A") == 0 and ps.score("B") == 0
        ps.prune("B", TOPIC)
        ps.refresh_scores()
        assert ps.score("A") == 0
        assert ps.score("B") == pytest.approx(G_MESH_FAILURE)

    def test_invalid_message_deliveries(self):
        for decay, expected in ((1.0, G_IMD), (0.9, G_IMD_DECAY)):
            clk = VirtualClock()
            ps = PeerScore(fn_params(
                topic_weight=1, invalid_message_deliveries_weight=-1,
                invalid_message_deliveries_decay=decay), clk.now)
            ps.add_peer("A", "proto"); ps.graft("A", TOPIC)
            for i in range(100):
                ps.reject_message(_msg(i, "A"), ev.REJECT_INVALID_SIGNATURE)
            ps.refresh_scores()
            assert ps.score("A") == pytest.approx(expected)

    def test_application_score(self):
        val = {"v": 0.0}
        params = PeerScoreParams(app_specific_score=lambda p: val["v"],
                                 app_specific_weight=0.5,
                                 topics={TOPIC: TopicScoreParams(
                                     time_in_mesh_quantum=1.0)})
        ps = PeerScore(params, VirtualClock().now)
        ps.add_peer("A", "proto"); ps.graft("A", TOPIC)
        val["v"] = -100.0
        assert ps.score("A") == pytest.approx(G_APP_NEG)
        val["v"] = 99.0
        assert ps.score("A") == pytest.approx(G_APP_POS)

    def test_ip_colocation(self):
        params = PeerScoreParams(app_specific_score=lambda p: 0.0,
                                 ip_colocation_factor_threshold=1,
                                 ip_colocation_factor_weight=-1,
                                 topics={TOPIC: TopicScoreParams(
                                     time_in_mesh_quantum=1.0)})
        ips = {"A": ["1.2.3.4"], "B": ["2.3.4.5"],
               "C": ["2.3.4.5", "3.4.5.6"], "D": ["2.3.4.5"]}
        ps = PeerScore(params, VirtualClock().now, get_ips=lambda p: ips[p])
        for p in "ABCD":
            ps.add_peer(p, "proto"); ps.graft(p, TOPIC)
        ps.refresh_ips()
        ps.refresh_scores()
        assert ps.score("A") == 0
        for p in "BCD":
            assert ps.score(p) == pytest.approx(G_IP_COLOC)

    def test_behaviour_penalty(self):
        params = PeerScoreParams(app_specific_score=lambda p: 0.0,
                                 behaviour_penalty_weight=-1,
                                 behaviour_penalty_decay=0.99, topics={})
        ps = PeerScore(params, VirtualClock().now)
        ps.add_penalty("A", 1)               # unknown peer: no effect
        assert ps.score("A") == 0
        ps.add_peer("A", "proto")
        ps.add_penalty("A", 1)
        assert ps.score("A") == pytest.approx(G_BEHAVIOUR_1)
        ps.add_penalty("A", 1)
        assert ps.score("A") == pytest.approx(G_BEHAVIOUR_2)
        ps.refresh_scores()
        assert ps.score("A") == pytest.approx(G_BEHAVIOUR_DECAYED)

    def test_retention(self):
        clk = VirtualClock()
        params = PeerScoreParams(app_specific_score=lambda p: -1000.0,
                                 app_specific_weight=1.0,
                                 retain_score=1.0,
                                 topics={TOPIC: TopicScoreParams(
                                     time_in_mesh_quantum=1.0)})
        ps = PeerScore(params, clk.now)
        ps.add_peer("A", "proto"); ps.graft("A", TOPIC)
        ps.refresh_scores()
        assert ps.score("A") == pytest.approx(-1000.0)
        ps.remove_peer("A")
        clk.advance_to(0.5)
        ps.refresh_scores()
        assert ps.score("A") == pytest.approx(-1000.0)
        clk.advance_to(1.05)
        ps.refresh_scores()
        assert ps.score("A") == 0.0


# ----------------------------------------------------------------------- sim

def sim_tp(heartbeat=1.0, **kw) -> TopicParams:
    defaults = dict(time_in_mesh_quantum=1.0, skip_atomic_validation=True)
    defaults.update(kw)
    return TopicParams.from_topic_params([TopicScoreParams(**defaults)],
                                         heartbeat_interval=heartbeat)


def sim_state(cfg, **arrays):
    st = init_state(cfg, topology.full(cfg.n_peers, cfg.k_slots))
    return st._replace(**arrays)


class TestSimGolden:
    """The same golden constants produced by the batched scorer on a tiny
    fully-connected SimState, observer = peer 0."""

    def _cfg(self, **kw):
        base = dict(n_peers=5, k_slots=4, n_topics=1, msg_window=8,
                    scoring_enabled=True)
        base.update(kw)
        return SimConfig(**base)

    def _slot(self, st, observer, peer):
        return int(np.argwhere(np.asarray(st.neighbors[observer]) == peer)[0, 0])

    def test_time_in_mesh_and_cap(self):
        # quantum 1ms @ 1ms heartbeat == 1 tick; 200 ticks in mesh
        cfg = self._cfg()
        tp = sim_tp(heartbeat=1e-3, topic_weight=0.5, time_in_mesh_weight=1,
                    time_in_mesh_quantum=1e-3, time_in_mesh_cap=3600)
        st = sim_state(cfg, tick=jnp.int32(200))
        st = st._replace(mesh=st.connected[:, None, :],
                         graft_tick=jnp.zeros_like(st.graft_tick))
        s = compute_scores(st, cfg, tp)
        assert float(s[0, 0]) == pytest.approx(G_TIME_IN_MESH)

        tp_cap = sim_tp(heartbeat=1e-3, topic_weight=0.5, time_in_mesh_weight=1,
                        time_in_mesh_quantum=1e-3, time_in_mesh_cap=10)
        st40 = st._replace(tick=jnp.int32(40))
        s = compute_scores(st40, cfg, tp_cap)
        assert float(s[0, 0]) == pytest.approx(G_TIME_IN_MESH_CAP)

    def test_fmd_cap_decay(self):
        cfg = self._cfg()
        for cap, expected in ((2000.0, G_FMD), (50.0, G_FMD_CAP)):
            tp = sim_tp(topic_weight=1, first_message_deliveries_weight=1,
                        first_message_deliveries_decay=1.0,
                        first_message_deliveries_cap=cap)
            st = sim_state(cfg)
            # the sim caps at accumulation time (forward_tick), mirroring
            # score.go:929-934 capping inside markFirstMessageDelivery
            counted = min(100.0, cap)
            st = st._replace(first_message_deliveries=jnp.full_like(
                st.first_message_deliveries, counted))
            assert float(compute_scores(st, cfg, tp)[0, 0]) == \
                pytest.approx(expected)

        tp = sim_tp(topic_weight=1, first_message_deliveries_weight=1,
                    first_message_deliveries_decay=0.9,
                    first_message_deliveries_cap=2000.0)
        st = sim_state(cfg)
        st = st._replace(first_message_deliveries=jnp.full_like(
            st.first_message_deliveries, 100.0))
        st = decay_counters(st, cfg, tp)
        assert float(compute_scores(st, cfg, tp)[0, 0]) == \
            pytest.approx(G_FMD_DECAY_1)
        for _ in range(10):
            st = decay_counters(st, cfg, tp)
        assert float(compute_scores(st, cfg, tp)[0, 0]) == \
            pytest.approx(G_FMD_DECAY_11, rel=1e-5)

    def _mmd_tp(self, decay=1.0):
        return sim_tp(topic_weight=1, mesh_message_deliveries_weight=-1,
                      mesh_message_deliveries_activation=1.0,
                      mesh_message_deliveries_window=0.01,
                      mesh_message_deliveries_threshold=20,
                      mesh_message_deliveries_cap=100,
                      mesh_message_deliveries_decay=decay,
                      first_message_deliveries_weight=0)

    def test_mesh_message_deliveries(self):
        # A delivered 100 first (fmd+mmd at cap), B duplicated in window
        # (mmd at cap), C duplicated outside the window only (mmd 0)
        cfg = self._cfg()
        tp = self._mmd_tp()
        st = sim_state(cfg, tick=jnp.int32(10))
        a, b, c = (self._slot(st, 0, p) for p in (1, 2, 3))
        mesh = st.connected[:, None, :]
        mmd = st.mesh_message_deliveries.at[0, 0, a].set(100.0)
        mmd = mmd.at[0, 0, b].set(100.0)
        st = st._replace(mesh=mesh, mesh_active=mesh,
                         mesh_message_deliveries=mmd,
                         graft_tick=jnp.zeros_like(st.graft_tick))
        s = compute_scores(st, cfg, tp)
        assert float(s[0, a]) >= 0
        assert float(s[0, b]) >= 0
        assert float(s[0, c]) == pytest.approx(G_MMD_C)

    def test_mmd_decay(self):
        cfg = self._cfg()
        tp = self._mmd_tp(decay=0.9)
        st = sim_state(cfg)
        mesh = st.connected[:, None, :]
        st = st._replace(mesh=mesh, mesh_active=mesh,
                         mesh_message_deliveries=jnp.full_like(
                             st.mesh_message_deliveries, 40.0),
                         graft_tick=jnp.zeros_like(st.graft_tick))
        assert float(compute_scores(st, cfg, tp)[0, 0]) >= 0
        for _ in range(21):
            st = decay_counters(st, cfg, tp)
        assert float(compute_scores(st, cfg, tp)[0, 0]) == \
            pytest.approx(G_MMD_DECAY, rel=1e-5)

    def test_mesh_failure_penalty(self):
        cfg = self._cfg()
        tp = sim_tp(topic_weight=1, mesh_failure_penalty_weight=-1,
                    mesh_failure_penalty_decay=1.0,
                    mesh_message_deliveries_activation=0.0,
                    mesh_message_deliveries_window=0.01,
                    mesh_message_deliveries_threshold=20,
                    mesh_message_deliveries_cap=100,
                    mesh_message_deliveries_decay=1.0,
                    mesh_message_deliveries_weight=0,
                    first_message_deliveries_weight=0)
        st = sim_state(cfg, tick=jnp.int32(10))
        b = self._slot(st, 0, 2)
        mesh = st.connected[:, None, :]
        st = st._replace(mesh=mesh, mesh_active=mesh,
                         graft_tick=jnp.zeros_like(st.graft_tick))
        # prune peer-2's slot from observer 0's mesh via the sim transition
        pruned = jnp.zeros_like(st.mesh).at[0, 0, b].set(True)
        st = apply_prune_penalty(st, pruned, tp)
        st = st._replace(mesh=st.mesh & ~pruned)
        s = compute_scores(st, cfg, tp)
        assert float(s[0, b]) == pytest.approx(G_MESH_FAILURE)
        assert float(s[0, self._slot(st, 0, 1)]) == 0.0

    def test_invalid_message_deliveries(self):
        cfg = self._cfg()
        for decay, expected in ((1.0, G_IMD), (0.9, G_IMD_DECAY)):
            tp = sim_tp(topic_weight=1, invalid_message_deliveries_weight=-1,
                        invalid_message_deliveries_decay=decay)
            st = sim_state(cfg)
            st = st._replace(invalid_message_deliveries=jnp.full_like(
                st.invalid_message_deliveries, 100.0))
            if decay != 1.0:
                st = decay_counters(st, cfg, tp)
            assert float(compute_scores(st, cfg, tp)[0, 0]) == \
                pytest.approx(expected)

    def test_application_score(self):
        cfg = self._cfg(app_specific_weight=0.5)
        tp = sim_tp()
        app = np.zeros(5, np.float32)
        st = sim_state(cfg)
        peer = int(st.neighbors[0, 0])
        app[peer] = -100.0
        st = st._replace(app_score=jnp.asarray(app))
        assert float(compute_scores(st, cfg, tp)[0, 0]) == \
            pytest.approx(G_APP_NEG)
        app[peer] = 99.0
        st = st._replace(app_score=jnp.asarray(app))
        assert float(compute_scores(st, cfg, tp)[0, 0]) == \
            pytest.approx(G_APP_POS)

    def test_ip_colocation(self):
        # peers 1..4 are A,B,C,D as neighbors of observer 0: B,C,D share an
        # ip group (3 > threshold 1 -> -(3-1)^2), A is alone
        cfg = self._cfg(ip_colocation_factor_weight=-1.0,
                        ip_colocation_factor_threshold=1, n_ip_groups=8)
        tp = sim_tp()
        ip = np.array([0, 1, 2, 2, 2], np.int32)   # peer 1=A unique; 2,3,4 share
        st = sim_state(cfg, ip_group=jnp.asarray(ip))
        s = compute_scores(st, cfg, tp)
        assert float(s[0, self._slot(st, 0, 1)]) == 0.0
        for p in (2, 3, 4):
            assert float(s[0, self._slot(st, 0, p)]) == \
                pytest.approx(G_IP_COLOC)

    def test_behaviour_penalty(self):
        cfg = self._cfg(behaviour_penalty_weight=-1.0,
                        behaviour_penalty_decay=0.99)
        tp = sim_tp()
        st = sim_state(cfg)
        st1 = st._replace(behaviour_penalty=st.behaviour_penalty.at[0, 0].set(1.0))
        assert float(compute_scores(st1, cfg, tp)[0, 0]) == \
            pytest.approx(G_BEHAVIOUR_1)
        st2 = st._replace(behaviour_penalty=st.behaviour_penalty.at[0, 0].set(2.0))
        assert float(compute_scores(st2, cfg, tp)[0, 0]) == \
            pytest.approx(G_BEHAVIOUR_2)
        st3 = decay_counters(st2, cfg, tp)
        assert float(compute_scores(st3, cfg, tp)[0, 0]) == \
            pytest.approx(G_BEHAVIOUR_DECAYED)

    def test_retention_via_churn(self):
        # early reconnect keeps counters (score.go:611-644 RetainScore);
        # late reconnect resets them
        cfg = self._cfg(retain_score_ticks=5, churn_disconnect_prob=0.0,
                        churn_reconnect_prob=1.0)
        tp = sim_tp(topic_weight=1, first_message_deliveries_weight=1,
                    first_message_deliveries_decay=1.0,
                    first_message_deliveries_cap=2000.0)
        st = sim_state(cfg)
        j = int(st.neighbors[0, 0]); rs = int(st.reverse_slot[0, 0])
        conn = st.connected.at[0, 0].set(False).at[j, rs].set(False)
        st = st._replace(
            connected=conn,
            first_message_deliveries=st.first_message_deliveries.at[0, 0, 0].set(9.0),
            disconnect_tick=st.disconnect_tick.at[0, 0].set(0).at[j, rs].set(0))
        early = churn_edges(st._replace(tick=jnp.int32(3)), cfg, tp,
                            jax.random.PRNGKey(0))
        assert float(compute_scores(early, cfg, tp)[0, 0]) == \
            pytest.approx(G_RETAINED)
        late = churn_edges(st._replace(tick=jnp.int32(50)), cfg, tp,
                           jax.random.PRNGKey(0))
        assert float(compute_scores(late, cfg, tp)[0, 0]) == \
            pytest.approx(G_EXPIRED)
