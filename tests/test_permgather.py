"""Permutation-gather formulations must be bit-identical.

The reverse-edge gather (ops/permgather.py) has three formulations chosen
for TPU-vs-CPU memory-path reasons (scalar loads vs vector DMA rows vs an
on-chip Pallas kernel). Semantics must not depend on the choice: the engine
trajectory is the contract, so every mode is diffed against the scalar
reference both at the op level and over full engine ticks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops.permgather import (
    permutation_gather,
    resolve_mode,
)
from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
from go_libp2p_pubsub_tpu.sim.scenarios import default_topic_params

MODES = ["scalar", "rows", "pallas"]


def _random_edge_permutation(n, k, seed=0):
    """neighbors/reverse_slot of a random symmetric topology (the real
    shape of the permutation: an involution over directed edge slots)."""
    topo = topology.sparse(n, k, degree=min(6, k - 1), seed=seed)
    return np.asarray(topo.neighbors), np.asarray(topo.reverse_slot)


class TestOpParity:
    @pytest.mark.parametrize("dtype", [jnp.uint32, jnp.float32, jnp.int32])
    def test_modes_bit_identical(self, dtype):
        n, k = 256, 8
        nbr, rks = _random_edge_permutation(n, k)
        jn = jnp.clip(jnp.asarray(nbr), 0, n - 1)
        rk = jnp.clip(jnp.asarray(rks), 0, k - 1)
        key = jax.random.PRNGKey(3)
        if dtype == jnp.float32:
            payload = jax.random.normal(key, (n, k), dtype)
        else:
            payload = jax.random.randint(key, (n, k), 0, 2**31 - 1,
                                         jnp.int32).astype(dtype)
        ref = permutation_gather(payload, jn, rk, "scalar")
        for mode in MODES[1:]:
            out = permutation_gather(payload, jn, rk, mode)
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(out),
                                          err_msg=mode)

    def test_pallas_odd_shapes(self):
        # n not divisible by the preferred block sizes
        for n, k in [(24, 4), (8, 8), (72, 16)]:
            nbr, rks = _random_edge_permutation(n, k, seed=n)
            jn = jnp.clip(jnp.asarray(nbr), 0, n - 1)
            rk = jnp.clip(jnp.asarray(rks), 0, k - 1)
            payload = jax.random.randint(jax.random.PRNGKey(n), (n, k), 0,
                                         2**31 - 1, jnp.int32)
            a = permutation_gather(payload, jn, rk, "scalar")
            b = permutation_gather(payload, jn, rk, "pallas")
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_resolve_mode_policy(self):
        # auto: scalar on cpu; pallas ineligible when payload exceeds VMEM
        assert resolve_mode("auto", jnp.uint32, 100, 8) in ("scalar", "rows")
        assert resolve_mode("pallas", jnp.uint32, 1_000_000, 32) == "rows"
        assert resolve_mode("pallas", jnp.uint32, 1000, 8) == "pallas"
        # bool payloads can't ride the 32-bit kernel
        assert resolve_mode("pallas", jnp.bool_, 1000, 8) == "rows"


class TestWordsGatherParity:
    def test_modes_bit_identical(self):
        from go_libp2p_pubsub_tpu.ops.bits import (
            gather_words_rows, pack_words)

        n, k, m = 192, 8, 64
        nbr, _ = _random_edge_permutation(n, k, seed=2)
        nbr = jnp.clip(jnp.asarray(nbr), 0, n - 1)
        planes = np.asarray(
            jax.random.uniform(jax.random.PRNGKey(4), (n, m)) < 0.3)
        x_w = pack_words(jnp.asarray(planes))              # [W, N]
        ref = gather_words_rows(x_w, nbr, m, "scalar")
        for mode in ("rows", "pallas"):
            out = gather_words_rows(x_w, nbr, m, mode)
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(out),
                                          err_msg=mode)

    def test_resolve_words_policy(self):
        from go_libp2p_pubsub_tpu.ops.permgather import resolve_words_mode
        assert resolve_words_mode("pallas", 2, 1024, 8) == "pallas"
        # table too big for VMEM -> rows
        assert resolve_words_mode("pallas", 64, 1_000_000, 8) == "rows"
        # cpu auto stays on the scalar fast path
        assert resolve_words_mode("auto", 2, 1024, 8) == "scalar"

    def test_resolve_words_auto_policy(self, monkeypatch):
        """TPU auto is rows: the live-window microbench + the Mosaic
        >128-wide-gather wall (resolve_hop_mode docstring) retired the
        VMEM table kernel from auto; it stays reachable explicitly."""
        import go_libp2p_pubsub_tpu.ops.permgather as pg
        monkeypatch.setattr(pg.jax, "default_backend", lambda: "tpu")
        assert pg.resolve_words_mode("auto", 2, 100_000, 32) == "rows"
        # explicit pallas needs a lane-aligned block: 102400 has a
        # 128-multiple divisor, exactly-100000 does not (Mosaic requires
        # the blocked peer axis aligned to 128 — _block_rows docstring)
        assert pg.resolve_words_mode("pallas", 2, 102_400, 32) == "pallas"
        assert pg.resolve_words_mode("pallas", 2, 100_000, 32) == "rows"
        assert pg.resolve_words_mode("pallas", 64, 1_000_000, 8) == "rows"


class TestEdgeTableKernel:
    """The bit-table packed edge exchange (PERF_MODEL.md S2): all B sender
    planes x K slots in one [N, ceil(BK/32)] u32 VMEM table."""

    def _state(self, n, k, seed=0):
        from types import SimpleNamespace

        from go_libp2p_pubsub_tpu.sim import topology
        topo = topology.sparse(n, k, degree=min(5, k - 1))
        return SimpleNamespace(neighbors=jnp.asarray(topo.neighbors),
                               reverse_slot=jnp.asarray(topo.reverse_slot))

    def test_parity_across_modes_and_group_boundary(self):
        from go_libp2p_pubsub_tpu.ops.heartbeat import edge_gather_packed

        rng = np.random.default_rng(7)
        n, k = 192, 8
        st = self._state(n, k)
        for t, n_masks in ((3, 2), (12, 3)):   # 6 planes; 36 planes (2 groups)
            masks = [jnp.asarray(rng.random((n, t, k)) < 0.35)
                     for _ in range(n_masks)]
            ref = edge_gather_packed(masks, st, "scalar")
            for mode in ("rows", "pallas"):
                got = edge_gather_packed(masks, st, mode)
                for r, g in zip(ref, got):
                    np.testing.assert_array_equal(
                        np.asarray(r), np.asarray(g), err_msg=f"{mode} t={t}")

    def test_resolve_edge_auto_policy(self, monkeypatch):
        import go_libp2p_pubsub_tpu.ops.permgather as pg
        assert pg.resolve_edge_packed_mode("auto", 1024, 8, 2) == "scalar"
        monkeypatch.setattr(pg.jax, "default_backend", lambda: "tpu")
        # TPU auto is the sort-permute apply (fastest measured formulation
        # on the live window; Mosaic blocks the bit-table kernel's wide
        # gather — hopkernel.resolve_hop_mode docstring)
        assert pg.resolve_edge_packed_mode("auto", 100_000, 32, 2) == "sort"
        # explicit pallas still resolves while VMEM-feasible AND the peer
        # count has a 128-aligned block (102400 yes, 100000/10000 no)
        assert pg.resolve_edge_packed_mode("pallas", 102_400, 32, 2) == "pallas"
        assert pg.resolve_edge_packed_mode("pallas", 10_240, 48, 18) == "pallas"
        # ...and a table over the VMEM budget degrades to rows
        assert pg.resolve_edge_packed_mode("pallas", 2_000_000, 32, 64) == "rows"


class TestShardedStepParity:
    def test_modes_compose_with_spmd(self):
        """Every gather formulation must compile AND execute under the
        peer-sharded step (the SPMD partitioner meets the pallas_call /
        row-gather graphs when the TPU auto default flips) and produce the
        same trajectory as the scalar form."""
        import dataclasses

        from go_libp2p_pubsub_tpu.parallel.sharding import (
            make_mesh, make_sharded_step, shard_state)

        devices = jax.devices()
        if len(devices) < 8:
            pytest.skip("needs the 8-device virtual mesh")
        base = SimConfig(n_peers=128, k_slots=8, n_topics=1, msg_window=16,
                         publishers_per_tick=2, scoring_enabled=True)
        topo = topology.sparse(128, 8, degree=4, seed=2)
        tp = default_topic_params(1)
        ref = None
        for mode in MODES:
            cfg = dataclasses.replace(base, edge_gather_mode=mode)
            st = init_state(cfg, topo,
                            subscribed=np.ones((128, 1), bool))
            mesh = make_mesh(devices[:8])
            st = shard_state(st, mesh, cfg)
            step = make_sharded_step(mesh, cfg, tp)
            out = st
            for i in range(3):
                out = step(out, jax.random.PRNGKey(i))
            out.tick.block_until_ready()
            obs = (int(out.tick), int(np.asarray(out.have).astype(np.uint64).sum()),
                   float(np.asarray(out.first_message_deliveries).sum()))
            if ref is None:
                ref = obs
            assert obs == ref, f"{mode} diverged under sharding"


class TestEngineTrajectoryParity:
    @pytest.mark.parametrize("scenario", ["default", "churn_flood"])
    def test_full_ticks_identical(self, scenario):
        from go_libp2p_pubsub_tpu.sim.engine import run

        n, k = 192, 8
        if scenario == "default":
            cfg0 = SimConfig(n_peers=n, k_slots=k, n_topics=2, msg_window=16,
                             publishers_per_tick=3, scoring_enabled=True)
        else:
            cfg0 = SimConfig(n_peers=n, k_slots=k, n_topics=2, msg_window=16,
                             publishers_per_tick=3, scoring_enabled=True,
                             flood_publish=True, churn_disconnect_prob=0.05,
                             churn_reconnect_prob=0.3, retain_score_ticks=5,
                             sub_leave_prob=0.02, sub_join_prob=0.05)
        topo = topology.sparse(n, k, degree=5, seed=7)
        tp = default_topic_params(2)
        sub = np.ones((n, 2), bool)
        outs = []
        for mode in MODES:
            cfg = type(cfg0)(**{**cfg0.__dict__, "edge_gather_mode": mode})
            st = init_state(cfg, topo, subscribed=sub.copy())
            st = run(st, cfg, tp, jax.random.PRNGKey(11), 5)
            st.tick.block_until_ready()
            outs.append(st)
        for mode, st in zip(MODES[1:], outs[1:]):
            for field, a, b in zip(outs[0]._fields, outs[0], st):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b),
                    err_msg=f"{scenario}/{mode}: state.{field} diverged")


class TestSortPermute:
    """The sort-permute formulation (permgather.edge_sort_key): gathers as
    one variadic lax.sort over the edge-slot involution — the fastest
    formulation measured on real TPU (live-window round 4). Invalid slots
    carry identity-mapped garbage, so op-level parity is checked on valid
    slots; the engine masks them everywhere, so trajectory parity is
    bit-exact."""

    def test_permutation_gather_sort_parity(self):
        from go_libp2p_pubsub_tpu.ops.permgather import (
            edge_sort_key, permutation_gather)
        n, k = 256, 8
        nbr, rks = _random_edge_permutation(n, k, seed=5)
        valid = (nbr >= 0) & (rks >= 0)
        jn = jnp.clip(jnp.asarray(nbr), 0, n - 1)
        rk = jnp.clip(jnp.asarray(rks), 0, k - 1)
        sk = edge_sort_key(jnp.asarray(nbr), jnp.asarray(rks), k_major=False)
        payload = jax.random.randint(jax.random.PRNGKey(3), (n, k), 0,
                                     2**31 - 1, jnp.int32).astype(jnp.uint32)
        ref = np.asarray(permutation_gather(payload, jn, rk, "scalar"))
        out = np.asarray(permutation_gather(payload, jn, rk, "sort",
                                            sort_key=sk))
        np.testing.assert_array_equal(ref[valid], out[valid])

    def test_words_gather_sort_parity(self):
        from go_libp2p_pubsub_tpu.ops.bits import gather_words_rows, pack_words
        from go_libp2p_pubsub_tpu.ops.permgather import edge_sort_key
        n, k, m = 192, 8, 64
        nbr, rks = _random_edge_permutation(n, k, seed=6)
        valid = ((nbr >= 0) & (rks >= 0)).T[None, :, :]        # [1,K,N]
        nbr_c = jnp.clip(jnp.asarray(nbr), 0, n - 1)
        sk = edge_sort_key(jnp.asarray(nbr), jnp.asarray(rks), k_major=True)
        planes = np.asarray(
            jax.random.uniform(jax.random.PRNGKey(4), (n, m)) < 0.3)
        x_w = pack_words(jnp.asarray(planes))
        ref = np.asarray(gather_words_rows(x_w, nbr_c, m, "scalar"))
        out = np.asarray(gather_words_rows(x_w, nbr_c, m, "sort",
                                           sort_key=sk))
        np.testing.assert_array_equal(np.where(valid, ref, 0),
                                      np.where(valid, out, 0))

    def test_engine_trajectory_sort_equals_scalar(self):
        import dataclasses

        from go_libp2p_pubsub_tpu.sim import (
            SimConfig, TopicParams, init_state, topology)
        from go_libp2p_pubsub_tpu.sim.engine import run

        cfg = SimConfig(n_peers=256, k_slots=16, n_topics=2, msg_window=32,
                        publishers_per_tick=4, prop_substeps=4,
                        scoring_enabled=True)
        tp = TopicParams.disabled(2)
        st0 = init_state(cfg, topology.sparse(256, 16, degree=6, seed=9))
        key = jax.random.PRNGKey(11)
        st_a = run(st0, dataclasses.replace(cfg, edge_gather_mode="scalar"),
                   tp, key, 6)
        st_b = run(st0, dataclasses.replace(cfg, edge_gather_mode="sort"),
                   tp, key, 6)
        for name, a, b in zip(st_a._fields, st_a, st_b):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)


def test_merged_answer_exchange_equals_standalone_gather():
    """The IWANT answer table riding the heartbeat's final exchange
    (engine._iwant_answer_extras -> edge_gather_packed extra_words) must be
    trajectory-identical to forward_tick's standalone words gather — under
    a pull-heavy config so the answer lanes carry real load."""
    import go_libp2p_pubsub_tpu.sim.engine as eng
    from go_libp2p_pubsub_tpu.sim import (
        SimConfig, TopicParams, init_state, topology)

    cfg = SimConfig(n_peers=192, k_slots=16, n_topics=2, msg_window=32,
                    publishers_per_tick=4, prop_substeps=2,
                    scoring_enabled=True, edge_gather_mode="sort")
    tp = TopicParams.disabled(2)
    st0 = init_state(cfg, topology.sparse(192, 16, degree=14, seed=5))
    key = jax.random.PRNGKey(13)

    st_merged = eng.run(st0, cfg, tp, key, 8)

    real_extras = eng._iwant_answer_extras
    try:
        eng._iwant_answer_extras = lambda state, cfg: None
        st_plain = jax.jit(eng._run_impl, static_argnames=("cfg", "n_ticks")
                           )(st0, cfg, tp, key, 8)
    finally:
        eng._iwant_answer_extras = real_extras

    pulls = int(np.sum(np.asarray(st_merged.iwant_pending) >= 0))
    assert pulls > 100, f"answer lanes barely exercised: {pulls} pulls"
    for name, a, b in zip(st_merged._fields, st_merged, st_plain):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


def test_count_dtype_trajectory_parity():
    """count_dtype=int32 (the native-lane ablation of the uint8 S3
    accumulators, sim/config.py) must leave trajectories bit-identical:
    counts are bounded by msg_window and land in f32 counters either way.
    Gater on so the ig/gdup accumulators are exercised too."""
    import dataclasses

    from go_libp2p_pubsub_tpu.sim import (
        SimConfig, TopicParams, init_state, topology)
    from go_libp2p_pubsub_tpu.sim.engine import run

    cfg = SimConfig(n_peers=192, k_slots=16, n_topics=2, msg_window=32,
                    publishers_per_tick=4, prop_substeps=4,
                    scoring_enabled=True, gater_enabled=True)
    tp = TopicParams.disabled(2)
    st0 = init_state(cfg, topology.sparse(192, 16, degree=6, seed=13))
    key = jax.random.PRNGKey(5)
    st_a = run(st0, cfg, tp, key, 6)
    st_b = run(st0, dataclasses.replace(cfg, count_dtype="int32"), tp,
               key, 6)
    for name, a, b in zip(st_a._fields, st_a, st_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


def test_sort_mode_parity_under_churn():
    """Sort-permute routing under connection churn + PX reconnect: the
    edge involution keys recompute from state each tick, and churn only
    flips connected/mesh flags on the static symmetric slot structure —
    so sort must stay bit-equal to scalar through down/up rounds."""
    import dataclasses

    from go_libp2p_pubsub_tpu.sim import (
        SimConfig, TopicParams, init_state, topology)
    from go_libp2p_pubsub_tpu.sim.engine import run

    cfg = SimConfig(n_peers=192, k_slots=16, n_topics=2, msg_window=32,
                    publishers_per_tick=4, prop_substeps=4,
                    scoring_enabled=True, gater_enabled=True,
                    churn_disconnect_prob=0.05, churn_reconnect_prob=0.3)
    tp = TopicParams.disabled(2)
    st0 = init_state(cfg, topology.sparse(192, 16, degree=6, seed=21))
    key = jax.random.PRNGKey(31)
    st_a = run(st0, dataclasses.replace(cfg, edge_gather_mode="scalar"),
               tp, key, 8)
    st_b = run(st0, dataclasses.replace(cfg, edge_gather_mode="sort"),
               tp, key, 8)
    for name, a, b in zip(st_a._fields, st_a, st_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)
