"""Parity tests: native C++ trace tensorizer vs the Python reference.

The native path (native/trace_codec.cpp via trace/native.py) must produce a
byte-identical op stream to replay.tensorize_trace for the same encoded
TraceEvent bytes — same ops, same slot assignment, same decay boundaries.
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.pb import codec
from go_libp2p_pubsub_tpu.trace import native, tensorize_trace

from test_trace_replay import DUP_WINDOW, T_END, TOPIC, run_traced_network

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain for native codec")


def encode_stream(events):
    out = bytearray()
    for e in events:
        blob = codec.encode_trace_event(e)
        out += codec.write_uvarint(len(blob)) + blob
    return bytes(out)


@pytest.fixture(scope="module")
def traced():
    net, nodes, hosts, mem = run_traced_network(n=10, degree=5, publishes=6)
    peer_index = {h.peer_id: i for i, h in enumerate(hosts)}
    return mem.events, peer_index


class TestNativeParity:
    def test_op_stream_identical(self, traced):
        events, peer_index = traced
        data = encode_stream(events)
        evs = codec.decode_trace_bytes(data)
        kw = dict(msg_window=64, decay_interval=1.0,
                  dup_window=[DUP_WINDOW], t_end=T_END)
        ref = tensorize_trace(evs, peer_index, {TOPIC: 0}, **kw)
        got = native.tensorize_bytes(data, peer_index, {TOPIC: 0}, **kw)
        np.testing.assert_array_equal(got.op, ref.op)
        np.testing.assert_array_equal(got.a, ref.a)
        np.testing.assert_array_equal(got.b, ref.b)
        np.testing.assert_array_equal(got.c, ref.c)
        assert got.mid_slot == ref.mid_slot

    def test_no_t_end_no_trailing_decay(self, traced):
        events, peer_index = traced
        data = encode_stream(events[:50])
        evs = codec.decode_trace_bytes(data)
        ref = tensorize_trace(evs, peer_index, {TOPIC: 0}, msg_window=64)
        got = native.tensorize_bytes(data, peer_index, {TOPIC: 0},
                                     msg_window=64)
        np.testing.assert_array_equal(got.op, ref.op)
        np.testing.assert_array_equal(got.a, ref.a)

    def test_window_overflow_raises(self, traced):
        events, peer_index = traced
        data = encode_stream(events)
        with pytest.raises(ValueError):
            native.tensorize_bytes(data, peer_index, {TOPIC: 0}, msg_window=2)

    def test_malformed_stream_raises(self):
        with pytest.raises(ValueError):
            native.tensorize_bytes(b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff",
                                   {}, {}, msg_window=8)

    def test_empty_stream_noop(self):
        feed = native.tensorize_bytes(b"", {"a": 0}, {TOPIC: 0}, msg_window=8)
        assert list(feed.op) == [0]  # OP_NOP
