"""Parity tests: native C++ trace tensorizer vs the Python reference.

The native path (native/trace_codec.cpp via trace/native.py) must produce a
byte-identical op stream to replay.tensorize_trace for the same encoded
TraceEvent bytes — same ops, same slot assignment, same decay boundaries.
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.pb import codec
from go_libp2p_pubsub_tpu.trace import native, tensorize_trace

from test_trace_replay import DUP_WINDOW, T_END, TOPIC, run_traced_network

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain for native codec")


def encode_stream(events):
    out = bytearray()
    for e in events:
        blob = codec.encode_trace_event(e)
        out += codec.write_uvarint(len(blob)) + blob
    return bytes(out)


@pytest.fixture(scope="module")
def traced():
    net, nodes, hosts, mem = run_traced_network(n=10, degree=5, publishes=6)
    peer_index = {h.peer_id: i for i, h in enumerate(hosts)}
    return mem.events, peer_index


class TestNativeParity:
    def test_op_stream_identical(self, traced):
        events, peer_index = traced
        data = encode_stream(events)
        evs = codec.decode_trace_bytes(data)
        kw = dict(msg_window=64, decay_interval=1.0,
                  dup_window=[DUP_WINDOW], t_end=T_END)
        ref = tensorize_trace(evs, peer_index, {TOPIC: 0}, **kw)
        got = native.tensorize_bytes(data, peer_index, {TOPIC: 0}, **kw)
        np.testing.assert_array_equal(got.op, ref.op)
        np.testing.assert_array_equal(got.a, ref.a)
        np.testing.assert_array_equal(got.b, ref.b)
        np.testing.assert_array_equal(got.c, ref.c)
        assert got.mid_slot == ref.mid_slot

    def test_no_t_end_no_trailing_decay(self, traced):
        events, peer_index = traced
        data = encode_stream(events[:50])
        evs = codec.decode_trace_bytes(data)
        ref = tensorize_trace(evs, peer_index, {TOPIC: 0}, msg_window=64)
        got = native.tensorize_bytes(data, peer_index, {TOPIC: 0},
                                     msg_window=64)
        np.testing.assert_array_equal(got.op, ref.op)
        np.testing.assert_array_equal(got.a, ref.a)

    def test_window_overflow_raises(self, traced):
        events, peer_index = traced
        data = encode_stream(events)
        with pytest.raises(ValueError):
            native.tensorize_bytes(data, peer_index, {TOPIC: 0}, msg_window=2)

    def test_malformed_stream_raises(self):
        with pytest.raises(ValueError):
            native.tensorize_bytes(b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff",
                                   {}, {}, msg_window=8)

    def test_empty_stream_noop(self):
        feed = native.tensorize_bytes(b"", {"a": 0}, {TOPIC: 0}, msg_window=8)
        assert list(feed.op) == [0]  # OP_NOP


class TestNativeRpcScanner:
    """native/rpc_codec.cpp vs the pure-Python scan: identical arrays over a
    randomized uvarint-framed RPC stream (comm.go:157-171 framing over
    pb/rpc.proto)."""

    def _random_stream(self, seed=7, frames=60):
        import random
        from go_libp2p_pubsub_tpu.core.types import (
            ControlGraft, ControlIHave, ControlIWant, ControlMessage,
            ControlPrune, Message, PeerInfo, RPC, SubOpts)
        rng = random.Random(seed)
        out = bytearray()
        for _ in range(frames):
            rpc = RPC()
            for _ in range(rng.randrange(3)):
                rpc.subscriptions.append(
                    SubOpts(rng.random() < 0.5, f"t{rng.randrange(5)}"))
            for _ in range(rng.randrange(4)):
                m = Message(data=bytes(rng.randrange(40)),
                            topic=f"t{rng.randrange(5)}")
                m.from_peer = f"peer{rng.randrange(9)}"
                m.seqno = rng.randrange(1 << 48).to_bytes(8, "big")
                rpc.publish.append(m)
            if rng.random() < 0.7:
                c = ControlMessage()
                for _ in range(rng.randrange(3)):
                    c.ihave.append(ControlIHave(
                        topic=f"t{rng.randrange(5)}",
                        message_ids=[f"m{rng.randrange(50)}"
                                     for _ in range(rng.randrange(6))]))
                for _ in range(rng.randrange(2)):
                    c.iwant.append(ControlIWant(
                        message_ids=[f"m{rng.randrange(50)}"
                                     for _ in range(rng.randrange(4))]))
                for _ in range(rng.randrange(2)):
                    c.graft.append(ControlGraft(topic="g"))
                for _ in range(rng.randrange(2)):
                    pr = ControlPrune(topic="p", backoff=rng.randrange(90))
                    for _ in range(rng.randrange(3)):
                        pr.peers.append(PeerInfo(peer_id=f"px{rng.randrange(7)}"))
                    c.prune.append(pr)
                if not c.is_empty():
                    rpc.control = c
            out += codec.frame_rpc(rpc)
        return bytes(out)

    def test_native_matches_python(self):
        from go_libp2p_pubsub_tpu.pb import native_rpc
        if not native_rpc.available():
            pytest.skip("no native toolchain")
        data = self._random_stream()
        s_n, m_n, t_n = native_rpc.scan_bytes(data)
        s_p, m_p, t_p = native_rpc.scan_bytes_python(data)
        np.testing.assert_array_equal(s_n, s_p)
        np.testing.assert_array_equal(m_n, m_p)
        assert t_n == t_p
        assert s_n.shape[0] == 60 and s_n[:, 1].sum() == m_n.shape[0]

    def test_empty_topic_parity(self):
        """A publish whose topic field is PRESENT but empty (len 0) must scan
        identically in both paths: proto2 decode can't distinguish absent
        from empty on the Python side, so neither path interns it and the
        message records topic_id -1 (foreign encoders can emit this; ours
        skips empty topics, codec.encode_message)."""
        from go_libp2p_pubsub_tpu.pb import native_rpc
        from go_libp2p_pubsub_tpu.pb.codec import (
            _bytes_field, _str_field, write_uvarint)
        # Message{data="xx", topic=""} then Message{data="y", topic="t0"}
        msg_empty = _bytes_field(2, b"xx") + _str_field(4, "")
        msg_named = _bytes_field(2, b"y") + _str_field(4, "t0")
        payload = _bytes_field(2, msg_empty) + _bytes_field(2, msg_named)
        data = bytes(write_uvarint(len(payload)) + payload)
        s_p, m_p, t_p = native_rpc.scan_bytes_python(data)
        assert m_p[0, 1] == -1 and t_p == ["t0"] and m_p[1, 1] == 0
        if native_rpc.available():
            s_n, m_n, t_n = native_rpc.scan_bytes(data)
            np.testing.assert_array_equal(s_n, s_p)
            np.testing.assert_array_equal(m_n, m_p)
            assert t_n == t_p

    def test_oversize_frame_rejected(self):
        from go_libp2p_pubsub_tpu.pb import native_rpc
        from go_libp2p_pubsub_tpu.core.types import Message, RPC
        rpc = RPC()
        rpc.publish.append(Message(data=b"x" * 4096, topic="t"))
        data = codec.frame_rpc(rpc)
        with pytest.raises(ValueError):
            native_rpc.scan_bytes(data, max_frame=1024)
        if native_rpc.available():
            with pytest.raises(ValueError):
                native_rpc.scan_bytes_python(data, max_frame=1024)

    def test_empty_stream(self):
        from go_libp2p_pubsub_tpu.pb import native_rpc
        s, m, t = native_rpc.scan_bytes(b"")
        assert s.shape == (0, 8) and m.shape == (0, 4) and t == []
