"""Differential test: live functional-runtime routers vs trace replay into
the batched engine.

The BASELINE.json bit-match gate (SURVEY.md §7 step 7): run an in-process
gossipsub network with peer scoring on the deterministic substrate, record
every event through the tracer bus, tensorize the trace, inject it into a
``SimState`` on the same topology, and diff mesh membership and the P1-P7
score state against the routers that produced the trace.

Parity bounds: counters decay in f32 on the sim side vs Python floats on the
host side, so comparisons are allclose(1e-3), not bit equality; P1 and the
P3 activation latch are tick-quantized via the graft-at-next-boundary
convention (trace/replay.py module docstring).
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
from go_libp2p_pubsub_tpu.core.params import (
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.net import Network
from go_libp2p_pubsub_tpu.pb import codec
from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
from go_libp2p_pubsub_tpu.trace import (
    MemoryTracer,
    replay_feed,
    replay_topic_params,
    tensorize_trace,
)

TOPIC = "t"
T_END = 12.0
DUP_WINDOW = 0.05

TSP = TopicScoreParams(
    topic_weight=1.0, time_in_mesh_weight=0.05, time_in_mesh_quantum=1.0,
    time_in_mesh_cap=100.0, first_message_deliveries_weight=1.0,
    first_message_deliveries_decay=0.9, first_message_deliveries_cap=50.0,
    mesh_message_deliveries_weight=-0.5, mesh_message_deliveries_decay=0.8,
    mesh_message_deliveries_cap=30.0, mesh_message_deliveries_threshold=3.0,
    mesh_message_deliveries_window=DUP_WINDOW,
    mesh_message_deliveries_activation=4.0,
    mesh_failure_penalty_weight=-1.0, mesh_failure_penalty_decay=0.7,
    invalid_message_deliveries_weight=-5.0,
    invalid_message_deliveries_decay=0.9)


def run_traced_network(n=12, degree=6, publishes=8):
    net = Network()
    mem = MemoryTracer()
    nodes = []
    for _ in range(n):
        h = net.add_host()
        sp = PeerScoreParams(
            app_specific_score=lambda p: 0.0, decay_interval=1.0,
            decay_to_zero=0.01, topics={TOPIC: TSP})
        rt = GossipSubRouter(score_params=sp,
                             thresholds=PeerScoreThresholds(
                                 gossip_threshold=-10, publish_threshold=-50,
                                 graylist_threshold=-100))
        nodes.append(PubSub(h, rt, sign_policy=LAX_NO_SIGN, event_tracer=mem))
    hosts = [x.host for x in nodes]
    net.dense_connect(hosts, degree=degree)
    net.scheduler.run_for(0.1)
    for x in nodes:
        x.join(TOPIC).subscribe()
    net.scheduler.run_until(2.5)
    for i in range(publishes):
        nodes[i % n].my_topics[TOPIC].publish(b"msg %d" % i)
        net.scheduler.run_for(0.73)
    net.scheduler.run_until(T_END)
    return net, nodes, hosts, mem


def replay_into_sim(nodes, hosts, events, k_slots=16, msg_window=64):
    n = len(hosts)
    topo, peer_index = topology.from_hosts(hosts, k_slots)
    cfg = SimConfig(n_peers=n, k_slots=k_slots, n_topics=1,
                    msg_window=msg_window, scoring_enabled=True)
    tp = replay_topic_params([TSP])
    st = init_state(cfg, topo, subscribed=np.zeros((n, 1), bool))
    feed = tensorize_trace(events, peer_index, {TOPIC: 0},
                           msg_window=msg_window, decay_interval=1.0,
                           dup_window=[DUP_WINDOW], t_end=T_END)
    st = replay_feed(st, cfg, tp, feed)
    return st, cfg, tp, topo, peer_index, feed


@pytest.fixture(scope="module")
def diff_setup():
    net, nodes, hosts, mem = run_traced_network()
    st, cfg, tp, topo, peer_index, feed = replay_into_sim(
        nodes, hosts, mem.events)
    return net, nodes, hosts, mem, st, cfg, tp, topo, peer_index, feed


class TestTraceReplayDifferential:
    def test_tick_count(self, diff_setup):
        _, _, _, _, st, *_ = diff_setup
        assert int(st.tick) == int(T_END)

    def test_mesh_state_matches(self, diff_setup):
        _, nodes, hosts, _, st, cfg, tp, topo, peer_index, _ = diff_setup
        mesh = np.asarray(st.mesh)
        for i, x in enumerate(nodes):
            want = {peer_index[p] for p in x.rt.mesh.get(TOPIC, set())}
            got = {int(topo.neighbors[i, k]) for k in range(cfg.k_slots)
                   if mesh[i, 0, k]}
            assert got == want, f"node {i}: sim mesh {got} != router {want}"

    def test_score_counters_match(self, diff_setup):
        _, nodes, hosts, _, st, cfg, tp, topo, peer_index, _ = diff_setup
        fmd = np.asarray(st.first_message_deliveries)
        mmd = np.asarray(st.mesh_message_deliveries)
        mfp = np.asarray(st.mesh_failure_penalty)
        imd = np.asarray(st.invalid_message_deliveries)
        slot_of = [{int(j): k for k, j in enumerate(topo.neighbors[i])
                    if j >= 0} for i in range(len(nodes))]
        checked = 0
        for i, x in enumerate(nodes):
            for pid, pstats in x.rt.score.peer_stats.items():
                ts = pstats.topics.get(TOPIC)
                if ts is None:
                    continue
                j = peer_index[pid]
                k = slot_of[i].get(j)
                assert k is not None, f"peer {j} not adjacent to {i}"
                np.testing.assert_allclose(
                    fmd[i, 0, k], ts.first_message_deliveries, atol=1e-3,
                    err_msg=f"FMD mismatch at observer {i} slot {k} (peer {j})")
                np.testing.assert_allclose(
                    mmd[i, 0, k], ts.mesh_message_deliveries, atol=1e-3,
                    err_msg=f"MMD mismatch at observer {i} slot {k} (peer {j})")
                np.testing.assert_allclose(
                    mfp[i, 0, k], ts.mesh_failure_penalty, atol=1e-3,
                    err_msg=f"MFP mismatch at observer {i} slot {k} (peer {j})")
                np.testing.assert_allclose(
                    imd[i, 0, k], ts.invalid_message_deliveries, atol=1e-3,
                    err_msg=f"IMD mismatch at observer {i} slot {k} (peer {j})")
                checked += 1
        assert checked > len(nodes)  # scoring actually exercised

    def test_total_scores_match(self, diff_setup):
        from go_libp2p_pubsub_tpu.ops.score_ops import compute_scores
        _, nodes, hosts, _, st, cfg, tp, topo, peer_index, _ = diff_setup
        scores = np.asarray(compute_scores(st, cfg, tp))
        checked = 0
        for i, x in enumerate(nodes):
            for k in range(cfg.k_slots):
                j = int(topo.neighbors[i, k])
                if j < 0:
                    continue
                pid = hosts[j].peer_id
                if pid not in x.rt.score.peer_stats:
                    continue
                host_score = x.rt.score.score(pid)
                np.testing.assert_allclose(
                    scores[i, k], host_score, atol=5e-3,
                    err_msg=f"score mismatch: observer {i} -> peer {j}")
                checked += 1
        assert checked > len(nodes)

    def test_delivery_state_matches(self, diff_setup):
        _, nodes, hosts, _, st, cfg, tp, topo, peer_index, feed = diff_setup
        from go_libp2p_pubsub_tpu.sim.state import unpack_have
        have = np.asarray(unpack_have(st, cfg.msg_window))
        # every subscribed node saw every message (dense net, full delivery)
        n_msgs = len(feed.mid_slot)
        assert n_msgs == 8
        for i, x in enumerate(nodes):
            for mid, sl in feed.mid_slot.items():
                assert have[i, sl] == x.seen.has(mid), \
                    f"have mismatch node {i} mid {mid!r}"


class TestTraceCodecRoundTrip:
    def test_pb_file_feed_identical(self, diff_setup, tmp_path):
        """Events -> pb/trace bytes -> decode -> tensorize == in-memory feed
        (the interop path for traces recorded outside this process)."""
        _, nodes, hosts, mem, st, cfg, tp, topo, peer_index, feed = diff_setup
        path = tmp_path / "trace.pb"
        with open(path, "wb") as f:
            for e in mem.events:
                blob = codec.encode_trace_event(e)
                f.write(codec.write_uvarint(len(blob)) + blob)
        decoded = codec.read_trace_file(str(path))
        assert len(decoded) == len(mem.events)
        feed2 = tensorize_trace(decoded, peer_index, {TOPIC: 0},
                                msg_window=64, decay_interval=1.0,
                                dup_window=[DUP_WINDOW], t_end=T_END)
        np.testing.assert_array_equal(feed.op, feed2.op)
        np.testing.assert_array_equal(feed.a, feed2.a)
        np.testing.assert_array_equal(feed.b, feed2.b)
        np.testing.assert_array_equal(feed.c, feed2.c)
