"""The centralized compile plan (parallel/compile_plan.py, ISSUE 12).

Two audits: (a) donation — every plane's chunk executable exists in a
donated flavor that really aliases its carried state (and the undonated
flavor really doesn't: a silently-donating executable would delete the
supervisor's retry anchors out from under it); (b) ownership — no plane
compiles its own shardings outside compile_plan.py.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.parallel import compile_plan
from go_libp2p_pubsub_tpu.sim import (SimConfig, TopicParams, init_state,
                                      topology)
from go_libp2p_pubsub_tpu.sim.engine import run_keys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny():
    cfg = SimConfig(n_peers=64, k_slots=8, n_topics=1, msg_window=32,
                    publishers_per_tick=2, prop_substeps=4,
                    scoring_enabled=True)
    tp = TopicParams.disabled(1)
    st = init_state(cfg, topology.sparse(64, 8, degree=3))
    keys = jax.random.split(jax.random.PRNGKey(0), 5)
    return cfg, tp, st, keys


class TestDonationAudit:
    def test_engine_chunk_flavors(self, tiny):
        cfg, tp, st, keys = tiny
        donating = compile_plan.engine_chunk(cfg, st, tp, keys, donate=True)
        plain = compile_plan.engine_chunk(cfg, st, tp, keys, donate=False)
        assert compile_plan.donated_param_count(donating) >= 1
        assert compile_plan.donated_param_count(plain) == 0

    def test_engine_window_flavors(self, tiny):
        cfg, tp, st, _ = tiny
        fcfg = dataclasses.replace(cfg, key_schedule="fold_in")
        key = jax.random.PRNGKey(0)
        donating = compile_plan.engine_window(fcfg, st, tp, key, 5,
                                              donate=True)
        plain = compile_plan.engine_window(fcfg, st, tp, key, 5,
                                           donate=False)
        assert compile_plan.donated_param_count(donating) >= 1
        assert compile_plan.donated_param_count(plain) == 0

    def test_donated_executable_still_computes(self, tiny):
        """Donation changes buffer ownership, not the trajectory: the
        donated flavor (fed a copy it may consume) matches run_keys."""
        cfg, tp, st, keys = tiny
        ref = run_keys(st, cfg, tp, keys)
        exe = compile_plan.engine_chunk(cfg, st, tp, keys, donate=True)
        out = exe(jax.tree.map(jnp.copy, st), tp, keys)
        for f, x, y in zip(ref._fields, ref, out):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg=f"field {f}")

    def test_fleet_entry_never_donates(self, tiny):
        """The fleet plane's dispatch entry point must donate NOTHING
        (failed windows retry from the intact full state); the donated
        bench flavor is audited as the positive control. Lowering only —
        AOT-compiling the fleet scan is the known const-hoisting hazard
        (compile_plan module docstring)."""
        from go_libp2p_pubsub_tpu.sim.fleet import (fleet_run_keys,
                                                    fleet_run_keys_donated,
                                                    stack_states)
        cfg, tp, st, _ = tiny
        states = stack_states([st, st])
        tps = stack_states([tp, tp])
        keys = jax.random.split(jax.random.PRNGKey(1), 3 * 2)
        keys = keys.reshape(3, 2, 2)
        run_fn, _ = compile_plan.fleet_chunk(cfg, keys.shape, keys.dtype)
        assert run_fn is fleet_run_keys
        plain = run_fn.lower(states, cfg, tps, keys)
        donated = fleet_run_keys_donated.lower(states, cfg, tps, keys)
        assert compile_plan.donated_param_count(plain) == 0
        assert compile_plan.donated_param_count(donated) >= 1

    @pytest.mark.slow
    def test_sharded_chunk_flavors(self, tiny):
        """Lowering-level audit of the 8-device sharded scan (the
        multihost execution unit): the donate flavor aliases the carried
        state, the default doesn't."""
        from go_libp2p_pubsub_tpu.parallel.sharding import (make_mesh,
                                                            shard_state)
        cfg, tp, st, keys = tiny
        mesh = make_mesh()
        st_sh = shard_state(st, mesh, cfg)
        donating = compile_plan.sharded_chunk_plan(mesh, cfg, tp,
                                                   donate=True)
        plain = compile_plan.sharded_chunk_plan(mesh, cfg, tp)
        assert compile_plan.donated_param_count(
            donating.lower(st_sh, keys)) >= 1
        assert compile_plan.donated_param_count(
            plain.lower(st_sh, keys)) == 0


class TestPlanBookkeeping:
    def test_engine_aot_cache_reuses_executables(self, tiny):
        cfg, tp, st, keys = tiny
        a = compile_plan.engine_chunk(cfg, st, tp, keys)
        b = compile_plan.engine_chunk(cfg, st, tp, keys)
        assert a is b       # same (cfg, shape, lane, flavor) → same exe
        c = compile_plan.engine_chunk(cfg, st, tp, keys[:3])
        assert c is not a   # tail-chunk shape is its own entry

    def test_fleet_first_use_marks_on_demand(self):
        """mark=False is a pure query (the async fleet driver marks on
        CONFIRM, so a window that dies mid-compile keeps its compile
        deadline on retry)."""
        cfg = SimConfig(n_peers=64, k_slots=8, n_topics=1, msg_window=32)
        compile_plan.clear_caches()
        try:
            shape, dt = (3, 2, 2), "uint32"
            assert compile_plan.fleet_chunk(cfg, shape, dt,
                                            mark=False)[1] is True
            # the query did NOT consume the first use
            assert compile_plan.fleet_chunk(cfg, shape, dt,
                                            mark=False)[1] is True
            assert compile_plan.fleet_chunk(cfg, shape, dt)[1] is True
            assert compile_plan.fleet_chunk(cfg, shape, dt)[1] is False
            # a different window shape is its own first use
            assert compile_plan.fleet_chunk(cfg, (2, 2, 2), dt)[1] is True
        finally:
            compile_plan.clear_caches()


class TestShardingOwnership:
    def test_no_plane_compiles_its_own_shardings(self):
        """The tentpole's ownership contract: compile_plan.py is the ONE
        source file that binds in_shardings — every other plane goes
        through its factories."""
        offenders = []
        for root in ("go_libp2p_pubsub_tpu", "scripts"):
            for dirpath, _, names in os.walk(os.path.join(REPO, root)):
                for name in names:
                    if not name.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, name)
                    with open(path, encoding="utf-8") as f:
                        if "in_shardings=" in f.read():
                            offenders.append(os.path.relpath(path, REPO))
        assert offenders == [
            os.path.join("go_libp2p_pubsub_tpu", "parallel",
                         "compile_plan.py")]
