"""Pin the 10k-beacon delivery-loss mechanism (VERDICT r2 weak #5).

The beacon scenario's sub-1.0 delivery fraction is STRUCTURAL: attestation
subnets are joined by ~15% of peers, so the subscriber-induced subgraph has
mean degree ~2.4 on a degree-16 underlay — below connectivity, leaving some
subscribers with zero subscribed neighbors (or in small components away
from the publisher). No overlay protocol can deliver to them: gossipsub
meshes, gossip, and IWANT all ride existing connections between peers in
the topic (comm.go:156-191 is the only transport; the reference has the
same reachability floor). This test proves every missed (peer, message)
pair is graph-unreachable from its publisher through subscribers, and that
delivery over REACHABLE pairs is exactly 1.0 — i.e. the engine loses
nothing to gater admission, edge-capacity drops, or window expiry in this
configuration.
"""

from collections import deque

import jax
import numpy as np

from go_libp2p_pubsub_tpu.sim import scenarios
from go_libp2p_pubsub_tpu.sim.engine import run


def _reachable_from(publisher: int, subs_t: np.ndarray, nbr: np.ndarray,
                    conn: np.ndarray) -> np.ndarray:
    """BFS over the subscriber-induced subgraph (message relays only flow
    between peers subscribed to the topic)."""
    n = nbr.shape[0]
    seen = np.zeros(n, bool)
    seen[publisher] = True
    q = deque([publisher])
    while q:
        p = q.popleft()
        for s, nb in zip(conn[p], nbr[p]):
            if s and nb >= 0 and subs_t[nb] and not seen[nb]:
                seen[nb] = True
                q.append(nb)
    return seen


class TestBeaconDeliveryIsStructural:
    def test_all_misses_unreachable_and_reachable_is_total(self):
        cfg, tp, st = scenarios.beacon_10k(n_peers=2000, k_slots=32,
                                           degree=16)
        st = run(st, cfg, tp, jax.random.PRNGKey(0), 10)
        st.tick.block_until_ready()

        tick = int(st.tick)
        msg_topic = np.asarray(st.msg_topic)
        msg_pub = np.asarray(st.msg_publish_tick)
        msg_from = np.asarray(st.msg_publisher)
        have = np.asarray(st.have)
        sub = np.asarray(st.subscribed)
        nbr = np.asarray(st.neighbors)
        conn = np.asarray(st.connected).astype(bool)

        alive = (tick - msg_pub) < cfg.history_length
        valid = (msg_topic >= 0) & alive
        slots = np.where(valid)[0]
        assert slots.size > 0

        n_missed = n_checked = 0
        for s in slots:
            t = int(msg_topic[s])
            subs_t = sub[:, t]
            # messages this old have finished propagating (prop_substeps
            # hops/tick); younger ones may still be legitimately in flight
            if tick - msg_pub[s] < 3:
                continue
            reach = _reachable_from(int(msg_from[s]), subs_t, nbr, conn)
            should = subs_t & valid[s]
            missed = should & ~have[:, s]
            # every miss is structurally unreachable from the publisher
            assert not (missed & reach).any(), (
                f"msg slot {s} topic {t}: reachable subscriber missed — "
                f"a real drop, not topology")
            # and every reachable subscriber WAS delivered
            assert (have[:, s] | ~reach | ~should).all()
            n_missed += int(missed.sum())
            n_checked += 1
        # the scenario genuinely exercises the structural-loss path
        assert n_checked >= 5
        assert n_missed > 0, (
            "expected some structurally isolated subnet subscribers; if the "
            "topology changed to make all subnets connected, this test's "
            "premise is gone — revisit BASELINE notes for config 2")
