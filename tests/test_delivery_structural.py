"""Pin the benchmark configs' sub-1.0 delivery fractions to their causes.

Two pinned mechanisms: the 10k-beacon loss is STRUCTURAL (isolated subnet
subscribers, below), and the 100k-sybil loss is the DESIGNED security
outcome (rejected invalid traffic + starved graylisted attackers,
TestSybilDeliveryDecomposition). Both originally VERDICT r2 weak #5.

The beacon scenario's sub-1.0 delivery fraction is STRUCTURAL: attestation
subnets are joined by ~15% of peers, so the subscriber-induced subgraph has
mean degree ~2.4 on a degree-16 underlay — below connectivity, leaving some
subscribers with zero subscribed neighbors (or in small components away
from the publisher). No overlay protocol can deliver to them: gossipsub
meshes, gossip, and IWANT all ride existing connections between peers in
the topic (comm.go:156-191 is the only transport; the reference has the
same reachability floor). This test proves every missed (peer, message)
pair is graph-unreachable from its publisher through subscribers, and that
delivery over REACHABLE pairs is exactly 1.0 — i.e. the engine loses
nothing to gater admission, edge-capacity drops, or window expiry in this
configuration.
"""

from collections import deque

import jax
import numpy as np

from go_libp2p_pubsub_tpu.sim import scenarios
from go_libp2p_pubsub_tpu.sim.engine import run


def _reachable_from(publisher: int, subs_t: np.ndarray, nbr: np.ndarray,
                    conn: np.ndarray) -> np.ndarray:
    """BFS over the subscriber-induced subgraph (message relays only flow
    between peers subscribed to the topic)."""
    n = nbr.shape[0]
    seen = np.zeros(n, bool)
    seen[publisher] = True
    q = deque([publisher])
    while q:
        p = q.popleft()
        for s, nb in zip(conn[p], nbr[p]):
            if s and nb >= 0 and subs_t[nb] and not seen[nb]:
                seen[nb] = True
                q.append(nb)
    return seen


class TestSybilDeliveryDecomposition:
    def test_loss_is_rejected_and_starved_attacker_traffic(self):
        """Pin the sybil scenario's sub-1.0 delivery fraction the same way:
        the shortfall is the DESIGNED security outcome, not transport loss.
        Decomposed over (receiver class x message class):

        - honest receivers get EVERY honest message (delivery 1.0);
        - honest receivers deliver NO invalid sybil message (validation
          rejects them, validation.go:293-370 -> P4);
        - graylisted sybil receivers are starved of honest messages
          (scoring cuts them out of mesh + gossip, gossipsub.go:598-645,
          the gossipsub_spam_test.go end state).

        The bench's headline delivery_fraction for config 4 is therefore
        dominated by the honest x honest block over all pairs."""
        cfg, tp, st = scenarios.sybil_100k(n_peers=2000, k_slots=16,
                                           degree=10, sybil_fraction=0.2,
                                           n_sybil_ips=8)
        st = run(st, cfg, tp, jax.random.PRNGKey(0), 25)
        st.tick.block_until_ready()

        tick = int(st.tick)
        mal = np.asarray(st.malicious)
        mt = np.asarray(st.msg_topic)
        mp = np.asarray(st.msg_publish_tick)
        inv = np.asarray(st.msg_invalid)
        from go_libp2p_pubsub_tpu.sim.state import unpack_have
        have = np.asarray(unpack_have(st, cfg.msg_window))
        sub = np.asarray(st.subscribed)
        alive = (tick - mp) < cfg.history_length
        # like the beacon test: skip messages young enough to be
        # legitimately in flight so only real drops can fail the 1.0 gate
        settled = (tick - mp) >= 3
        valid = (mt >= 0) & alive & settled
        should = sub[:, np.clip(mt, 0, cfg.n_topics - 1)] & valid[None, :]
        got = have & should

        def frac(rmask, cmask):
            s = should[rmask][:, cmask]
            return got[rmask][:, cmask].sum() / max(s.sum(), 1), int(s.sum())

        hh, n_hh = frac(~mal, valid & ~inv)
        hi, n_hi = frac(~mal, valid & inv)
        sh, n_sh = frac(mal, valid & ~inv)
        assert min(n_hh, n_hi, n_sh) > 1000, "scenario too small to pin"
        assert hh == 1.0, f"honest-to-honest delivery lost traffic: {hh}"
        assert hi == 0.0, f"invalid sybil messages were delivered: {hi}"
        assert sh < 0.05, f"graylisted sybils still receive: {sh}"


class TestBeaconDeliveryIsStructural:
    def test_all_misses_unreachable_and_reachable_is_total(self):
        cfg, tp, st = scenarios.beacon_10k(n_peers=2000, k_slots=32,
                                           degree=16)
        st = run(st, cfg, tp, jax.random.PRNGKey(0), 10)
        st.tick.block_until_ready()

        tick = int(st.tick)
        msg_topic = np.asarray(st.msg_topic)
        msg_pub = np.asarray(st.msg_publish_tick)
        msg_from = np.asarray(st.msg_publisher)
        from go_libp2p_pubsub_tpu.sim.state import unpack_have
        have = np.asarray(unpack_have(st, cfg.msg_window))
        sub = np.asarray(st.subscribed)
        nbr = np.asarray(st.neighbors)
        conn = np.asarray(st.connected).astype(bool)

        alive = (tick - msg_pub) < cfg.history_length
        valid = (msg_topic >= 0) & alive
        slots = np.where(valid)[0]
        assert slots.size > 0

        n_missed = n_checked = 0
        for s in slots:
            t = int(msg_topic[s])
            subs_t = sub[:, t]
            # messages this old have finished propagating (prop_substeps
            # hops/tick); younger ones may still be legitimately in flight
            if tick - msg_pub[s] < 3:
                continue
            reach = _reachable_from(int(msg_from[s]), subs_t, nbr, conn)
            should = subs_t & valid[s]
            missed = should & ~have[:, s]
            # every miss is structurally unreachable from the publisher
            assert not (missed & reach).any(), (
                f"msg slot {s} topic {t}: reachable subscriber missed — "
                f"a real drop, not topology")
            # and every reachable subscriber WAS delivered
            assert (have[:, s] | ~reach | ~should).all()
            n_missed += int(missed.sum())
            n_checked += 1
        # the scenario genuinely exercises the structural-loss path
        assert n_checked >= 5
        assert n_missed > 0, (
            "expected some structurally isolated subnet subscribers; if the "
            "topology changed to make all subnets connected, this test's "
            "premise is gone — revisit BASELINE notes for config 2")
