"""The two-level gather-free table take (ops/mxutake.py) must be exact.

Interpret-mode parity is the CPU-tier contract; native lowering is probed
by scripts/tpu_kernel_smoke.py on live windows. Exactness matters more
than usual here: the select rides bf16 one-hot matmuls, legal ONLY because
u8 chunks (<=255) are exact in bf16 and each dot row has exactly one
nonzero term — these tests would catch any chunking/padding mistake that
breaks that argument."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops.mxutake import (
    take_words_twolevel,
    take_words_twolevel_ref,
)


@pytest.mark.parametrize("n,r,bg", [
    (256, 512, 512),      # single grid step
    (1024, 2048, 512),    # multi grid step
    (1000, 512, 512),     # N not a multiple of 128 (pad path)
    (128, 128, 128),      # one block exactly
])
def test_twolevel_take_exact(n, r, bg):
    rng = np.random.default_rng(n + r)
    x = jnp.asarray(rng.integers(0, 2**32, (2, n), dtype=np.uint64),
                    jnp.uint32)
    idx = jnp.asarray(rng.integers(0, n, (r,)), jnp.int32)
    got = np.asarray(take_words_twolevel(x, idx, block_g=bg, interpret=True))
    np.testing.assert_array_equal(got, np.asarray(take_words_twolevel_ref(x, idx)))


def test_twolevel_take_extreme_values():
    """All-ones words and boundary indices: the u8-chunk recombination and
    the last-block/last-lane selects must be exact."""
    n = 384
    x = jnp.stack([jnp.full((n,), 0xFFFFFFFF, jnp.uint32),
                   jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(0x01010101)])
    idx = jnp.asarray([0, 127, 128, 255, 256, n - 1, n - 1, 0], jnp.int32)
    got = np.asarray(take_words_twolevel(x, idx, block_g=8, interpret=True))
    np.testing.assert_array_equal(got, np.asarray(take_words_twolevel_ref(x, idx)))
