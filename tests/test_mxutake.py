"""The two-level gather-free table take (ops/mxutake.py) must be exact.

Interpret-mode parity is the CPU-tier contract; native lowering is probed
by scripts/tpu_kernel_smoke.py on live windows. Exactness matters more
than usual here: the select rides bf16 one-hot matmuls, legal ONLY because
u8 chunks (<=255) are exact in bf16 and each dot row has exactly one
nonzero term — these tests would catch any chunking/padding mistake that
breaks that argument."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops.mxutake import (
    cost_model,
    take_words_onehot,
    take_words_twolevel,
    take_words_twolevel_ref,
)


@pytest.mark.parametrize("n,r,bg", [
    (256, 512, 512),      # single grid step
    (1024, 2048, 512),    # multi grid step
    (1000, 512, 512),     # N not a multiple of 128 (pad path)
    (128, 128, 128),      # one block exactly
    (512, 2500, 1024),    # r NOT a multiple of block_g (idx pad path) —
                          # engine shapes like 100000*32 need this
    (512, 700, 1024),     # r below one block, non-128-multiple
    (384, 3072 + 77, 512),  # multi-block + ragged tail
])
def test_twolevel_take_exact(n, r, bg):
    rng = np.random.default_rng(n + r)
    x = jnp.asarray(rng.integers(0, 2**32, (2, n), dtype=np.uint64),
                    jnp.uint32)
    idx = jnp.asarray(rng.integers(0, n, (r,)), jnp.int32)
    got = np.asarray(take_words_twolevel(x, idx, block_g=bg, interpret=True))
    np.testing.assert_array_equal(got, np.asarray(take_words_twolevel_ref(x, idx)))


def test_twolevel_take_extreme_values():
    """All-ones words and boundary indices: the u8-chunk recombination and
    the last-block/last-lane selects must be exact."""
    n = 384
    x = jnp.stack([jnp.full((n,), 0xFFFFFFFF, jnp.uint32),
                   jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(0x01010101)])
    idx = jnp.asarray([0, 127, 128, 255, 256, n - 1, n - 1, 0], jnp.int32)
    got = np.asarray(take_words_twolevel(x, idx, block_g=8, interpret=True))
    np.testing.assert_array_equal(got, np.asarray(take_words_twolevel_ref(x, idx)))


def test_onehot_take_exact_and_guards():
    """take_words_onehot (the in-kernel pure-jnp variant the pallas-mxu
    hop mode inlines) must match the reference bit-for-bit, and the
    lane-alignment contract must raise (not assert — -O safety)."""
    rng = np.random.default_rng(42)
    x = jnp.asarray(rng.integers(0, 2**32, (3, 512), dtype=np.uint64),
                    jnp.uint32)
    idx = jnp.asarray(rng.integers(0, 512, (193,)), jnp.int32)
    np.testing.assert_array_equal(np.asarray(take_words_onehot(x, idx)),
                                  np.asarray(take_words_twolevel_ref(x, idx)))
    with pytest.raises(ValueError, match="lane-aligned"):
        take_words_onehot(x[:, :100], idx)


def test_cost_model_tracks_compiled_bytes():
    """The bytes-touched sanity check (VERDICT r5 item 8): the analytic
    cost model's VMEM-resident inventory (table planes + output) must
    agree with XLA's own bytes-accessed for the interpret lowering within
    a small factor — so the model's 100k-headline projection in
    PERF_MODEL.md rests on an inventory a compiler has actually seen, not
    on FLOP counting."""
    n, r, w = 1024, 2048, 2
    x = jnp.zeros((w, n), jnp.uint32)
    idx = jnp.zeros((r,), jnp.int32)
    fn = jax.jit(lambda a, b: take_words_twolevel(a, b, interpret=True))
    cost = fn.lower(x, idx).compile().cost_analysis()
    if isinstance(cost, list):          # older jax returns [dict]
        cost = cost[0]
    compiled = float(cost.get("bytes accessed", 0.0))
    if compiled == 0.0:
        pytest.skip("backend reports no bytes-accessed estimate")
    m = cost_model(n, r, w)
    # resident floor: inputs once + output once; streamed ceiling adds the
    # per-chunk one-hot re-reads and the materialized [G, 128] lane
    # intermediates. The compiled estimate must land between 0.25x the
    # floor and 4x the ceiling — outside that the model (and every
    # PERF_MODEL.md number derived from it) is wrong.
    floor = m["table_bytes"] + m["out_bytes"]
    ceiling = m["onehot_bytes"] + m["lane_bytes"] \
        + m["table_bytes"] + m["out_bytes"]
    assert 0.25 * floor <= compiled <= 4.0 * ceiling, \
        (compiled, floor, ceiling)


def test_cost_model_headline_shape_magnitudes():
    """Pin the honest headline accounting quoted in PERF_MODEL.md
    "Two-level MXU take" for the 3.2M-index hop take at N=102400
    (NB=800): ~5 GB one full one-hot pass, ~42 GB with the per-chunk
    re-reads, ~1.6 MB resident tile, ~49 Gflop."""
    m = cost_model(102_400, 3_276_800, 2)
    one_pass = m["onehot_bytes"] / (4 * 2)        # per chunk-and-word pass
    assert 3e9 < one_pass < 8e9
    assert 2e10 < m["onehot_bytes"] < 1e11        # streamed worst case
    assert m["vmem_bytes"] < 8 * 1024 * 1024      # fits the VMEM budget
    assert 1e10 < m["flops"] < 1e11               # ~49 Gflop
