"""The two-level gather-free table take (ops/mxutake.py) must be exact.

Interpret-mode parity is the CPU-tier contract; native lowering is probed
by scripts/tpu_kernel_smoke.py on live windows. Exactness matters more
than usual here: the select rides bf16 one-hot matmuls, legal ONLY because
u8 chunks (<=255) are exact in bf16 and each dot row has exactly one
nonzero term — these tests would catch any chunking/padding mistake that
breaks that argument."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops.mxutake import (
    cost_model,
    cost_model_payload,
    pad_lanes,
    take_payload_onehot,
    take_words_onehot,
    take_words_twolevel,
    take_words_twolevel_ref,
)


@pytest.mark.parametrize("n,r,bg", [
    (256, 512, 512),      # single grid step
    (1024, 2048, 512),    # multi grid step
    (1000, 512, 512),     # N not a multiple of 128 (pad path)
    (128, 128, 128),      # one block exactly
    (512, 2500, 1024),    # r NOT a multiple of block_g (idx pad path) —
                          # engine shapes like 100000*32 need this
    (512, 700, 1024),     # r below one block, non-128-multiple
    (384, 3072 + 77, 512),  # multi-block + ragged tail
])
def test_twolevel_take_exact(n, r, bg):
    rng = np.random.default_rng(n + r)
    x = jnp.asarray(rng.integers(0, 2**32, (2, n), dtype=np.uint64),
                    jnp.uint32)
    idx = jnp.asarray(rng.integers(0, n, (r,)), jnp.int32)
    got = np.asarray(take_words_twolevel(x, idx, block_g=bg, interpret=True))
    np.testing.assert_array_equal(got, np.asarray(take_words_twolevel_ref(x, idx)))


def test_twolevel_take_extreme_values():
    """All-ones words and boundary indices: the u8-chunk recombination and
    the last-block/last-lane selects must be exact."""
    n = 384
    x = jnp.stack([jnp.full((n,), 0xFFFFFFFF, jnp.uint32),
                   jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(0x01010101)])
    idx = jnp.asarray([0, 127, 128, 255, 256, n - 1, n - 1, 0], jnp.int32)
    got = np.asarray(take_words_twolevel(x, idx, block_g=8, interpret=True))
    np.testing.assert_array_equal(got, np.asarray(take_words_twolevel_ref(x, idx)))


def test_onehot_take_exact_and_guards():
    """take_words_onehot (the in-kernel pure-jnp variant the pallas-mxu
    hop mode inlines) must match the reference bit-for-bit, and the
    lane-alignment contract must raise (not assert — -O safety)."""
    rng = np.random.default_rng(42)
    x = jnp.asarray(rng.integers(0, 2**32, (3, 512), dtype=np.uint64),
                    jnp.uint32)
    idx = jnp.asarray(rng.integers(0, 512, (193,)), jnp.int32)
    np.testing.assert_array_equal(np.asarray(take_words_onehot(x, idx)),
                                  np.asarray(take_words_twolevel_ref(x, idx)))
    with pytest.raises(ValueError, match="lane-aligned"):
        take_words_onehot(x[:, :100], idx)


def test_cost_model_tracks_compiled_bytes():
    """The bytes-touched sanity check (VERDICT r5 item 8): the analytic
    cost model's VMEM-resident inventory (table planes + output) must
    agree with XLA's own bytes-accessed for the interpret lowering within
    a small factor — so the model's 100k-headline projection in
    PERF_MODEL.md rests on an inventory a compiler has actually seen, not
    on FLOP counting."""
    n, r, w = 1024, 2048, 2
    x = jnp.zeros((w, n), jnp.uint32)
    idx = jnp.zeros((r,), jnp.int32)
    fn = jax.jit(lambda a, b: take_words_twolevel(a, b, interpret=True))
    cost = fn.lower(x, idx).compile().cost_analysis()
    if isinstance(cost, list):          # older jax returns [dict]
        cost = cost[0]
    compiled = float(cost.get("bytes accessed", 0.0))
    if compiled == 0.0:
        pytest.skip("backend reports no bytes-accessed estimate")
    m = cost_model(n, r, w)
    # resident floor: inputs once + output once; streamed ceiling adds the
    # per-chunk one-hot re-reads and the materialized [G, 128] lane
    # intermediates. The compiled estimate must land between 0.25x the
    # floor and 4x the ceiling — outside that the model (and every
    # PERF_MODEL.md number derived from it) is wrong.
    floor = m["table_bytes"] + m["out_bytes"]
    ceiling = m["onehot_bytes"] + m["lane_bytes"] \
        + m["table_bytes"] + m["out_bytes"]
    assert 0.25 * floor <= compiled <= 4.0 * ceiling, \
        (compiled, floor, ceiling)


@pytest.mark.parametrize("n,k", [
    (200, 12),     # N and K both non-multiples of 128 (pad + w-tiling)
    (1000, 16),    # N non-multiple, larger
    (384, 32),     # lane-aligned N, full word-tile
    (129, 7),      # pathological ragged tail on both axes
])
def test_payload_take_exact_ragged(n, k):
    """The blocked/tiled one-hot payload permute (the mxu formulation of
    the generic [N, K] gather — the last scalar degradation of the mxu
    mode) must be bit-exact vs the scalar reference at non-multiple-of-
    128 N and K, for u32 AND bitcast f32 payloads."""
    rng = np.random.default_rng(n * k)
    jn = jnp.asarray(rng.integers(0, n, (n, k)), jnp.int32)
    rk = jnp.asarray(rng.integers(0, k, (n, k)), jnp.int32)
    pay_u = jnp.asarray(rng.integers(0, 2**32, (n, k), dtype=np.uint64),
                        jnp.uint32)
    got = take_payload_onehot(pay_u, jn, rk, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(pay_u[jn, rk]))
    pay_f = jnp.asarray(rng.normal(size=(n, k)), jnp.float32)
    got_f = take_payload_onehot(pay_f, jn, rk, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_f),
                                  np.asarray(pay_f[jn, rk]))


def test_payload_take_dtype_guard():
    with pytest.raises(ValueError, match="4-byte"):
        take_payload_onehot(jnp.zeros((64, 8), jnp.uint8),
                            jnp.zeros((64, 8), jnp.int32),
                            jnp.zeros((64, 8), jnp.int32))


def test_pad_lanes_seam():
    x = jnp.arange(2 * 200, dtype=jnp.uint32).reshape(2, 200)
    p = pad_lanes(x)
    assert p.shape == (2, 256)
    np.testing.assert_array_equal(np.asarray(p[:, :200]), np.asarray(x))
    assert not np.asarray(p[:, 200:]).any()
    assert pad_lanes(p) is p        # aligned tables pass through untouched


def test_payload_cost_model_tracks_compiled_bytes():
    """test_cost_model_tracks_compiled_bytes extended to the blocked
    one-hot payload permute: the analytic inventory must bracket XLA's
    own bytes-accessed for the interpret lowering."""
    n, k = 512, 16
    pay = jnp.zeros((n, k), jnp.uint32)
    jn = jnp.zeros((n, k), jnp.int32)
    rk = jnp.zeros((n, k), jnp.int32)
    fn = jax.jit(lambda p, a, b: take_payload_onehot(p, a, b,
                                                     interpret=True))
    cost = fn.lower(pay, jn, rk).compile().cost_analysis()
    if isinstance(cost, list):          # older jax returns [dict]
        cost = cost[0]
    compiled = float(cost.get("bytes accessed", 0.0))
    if compiled == 0.0:
        pytest.skip("backend reports no bytes-accessed estimate")
    m = cost_model_payload(n, k)
    floor = m["table_bytes"] + m["out_bytes"]
    ceiling = m["onehot_bytes"] + m["lane_bytes"] + m["select_bytes"] \
        + m["table_bytes"] + m["out_bytes"]
    assert 0.25 * floor <= compiled <= 4.0 * ceiling, \
        (compiled, floor, ceiling)


def test_extras_ride_along_cost_tracks_compiled_bytes():
    """...and to the mxu formulation of _iwant_answer_extras: the
    bit-table take with W extra word rows concatenated must stay within
    the cost model priced at (wb + W) words — the extras ride the SAME
    one-hot operand instead of paying their own take."""
    from go_libp2p_pubsub_tpu.ops.permgather import _edge_table_mxu

    n, k, b, we = 512, 8, 2, 2
    wb = (b * k + 31) // 32
    table = jnp.zeros((n, wb), jnp.uint32)
    jn = jnp.zeros((n, k), jnp.int32)
    rk = jnp.zeros((n, k), jnp.int32)
    extra = jnp.zeros((we, n), jnp.uint32)
    fn = jax.jit(lambda t, a, b_, e: _edge_table_mxu(
        t, a, b_, 2, extra_words=(e,), interpret=True))
    cost = fn.lower(table, jn, rk, extra).compile().cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    compiled = float(cost.get("bytes accessed", 0.0))
    if compiled == 0.0:
        pytest.skip("backend reports no bytes-accessed estimate")
    m = cost_model(n, n * k, wb + we)
    floor = m["table_bytes"] + m["out_bytes"]
    ceiling = m["onehot_bytes"] + m["lane_bytes"] \
        + m["table_bytes"] + m["out_bytes"]
    # the bit-extract/transpose passes outside the take add small-factor
    # traffic over the take's own inventory
    assert 0.25 * floor <= compiled <= 8.0 * ceiling, \
        (compiled, floor, ceiling)


def test_cost_model_headline_shape_magnitudes():
    """Pin the honest headline accounting quoted in PERF_MODEL.md
    "Two-level MXU take" for the 3.2M-index hop take at N=102400
    (NB=800): ~5 GB one full one-hot pass, ~42 GB with the per-chunk
    re-reads, ~1.6 MB resident tile, ~49 Gflop."""
    m = cost_model(102_400, 3_276_800, 2)
    one_pass = m["onehot_bytes"] / (4 * 2)        # per chunk-and-word pass
    assert 3e9 < one_pass < 8e9
    assert 2e10 < m["onehot_bytes"] < 1e11        # streamed worst case
    assert m["vmem_bytes"] < 8 * 1024 * 1024      # fits the VMEM budget
    assert 1e10 < m["flops"] < 1e11               # ~49 Gflop
