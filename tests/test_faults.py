"""Fault injection + the invariant sentinel (sim/faults.py, sim/invariants.py).

Acceptance contract of the fault plane (ISSUE 4): clean BASELINE scenarios
run with ``fault_flags == 0`` over 20+ ticks; a seeded plan sets EXACTLY
the expected injected-fault bits and no violation bits; a partition heals
back to ``delivery_fraction >= 0.99`` within a bounded tick budget in BOTH
the batched engine and the host-side functional runtime driven by the same
plan shape; seeded state poison trips the sentinel in ``record`` mode and
throws in ``raise`` mode.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import (
    SimConfig, TopicParams, init_state, topology,
)
from go_libp2p_pubsub_tpu.sim import invariants, scenarios
from go_libp2p_pubsub_tpu.sim.engine import (
    delivery_fraction, run, run_checked, step_jit,
)
from go_libp2p_pubsub_tpu.sim.faults import (
    FaultPlan, HostFaultInjector, OutageWindow, PartitionWindow,
    outage_peers_host,
)

pytestmark = pytest.mark.faults


def _cfg(n=64, k=16, degree=8, plan=None, **kw):
    base = dict(n_peers=n, k_slots=k, n_topics=1, msg_window=32,
                publishers_per_tick=4, prop_substeps=6,
                scoring_enabled=True, fault_plan=plan)
    base.update(kw)
    cfg = SimConfig(**base)
    topo = topology.sparse(n, k, degree=degree, seed=7)
    return cfg, scenarios.default_topic_params(cfg.n_topics), \
        init_state(cfg, topo)


class TestCleanScenariosZeroFlags:
    def test_baseline_scenarios_run_clean(self):
        """fault_flags == 0 across the clean BASELINE builders, 24 ticks
        each at toy scale — the engine must never trip its own sentinel."""
        clean = {k: v for k, v in scenarios.SCENARIOS.items()
                 if k not in ("50k_partition", "10k_outage",
                              "partition_small", "outage_small",
                              # the adversary library (ISSUE 10) injects
                              # by design; its flag contract is pinned in
                              # tests/test_adversary.py
                              "eclipse_small", "censor_small",
                              "flashcrowd_small", "slowlink_small",
                              "diurnal_small", "eclipse_50k",
                              "flashcrowd_50k")}
        for name, builder in clean.items():
            cfg, tp, st = builder(n_peers=96, k_slots=16, degree=6)
            assert cfg.invariant_mode == "record"
            st = run(st, cfg, tp, jax.random.PRNGKey(0), 24)
            assert int(st.fault_flags) == 0, \
                (name, invariants.decode_flags(int(st.fault_flags)))

    def test_router_sweep_runs_clean(self):
        for r in ("floodsub", "randomsub", "gossipsub"):
            cfg, tp, st = scenarios.router_sweep_100k(r, n_peers=96,
                                                      k_slots=16, degree=6)
            st = run(st, cfg, tp, jax.random.PRNGKey(0), 20)
            assert int(st.fault_flags) == 0, r

    def test_fault_scenarios_clean_before_window(self):
        """The two fault scenarios carry plans starting at tick 10: the
        pre-window prefix must be flag-free, the full run must set exactly
        the plan's bit and no violations."""
        for name, bit in (("50k_partition", invariants.FAULT_PARTITION),
                          ("10k_outage", invariants.FAULT_OUTAGE)):
            cfg, tp, st = scenarios.SCENARIOS[name](n_peers=96, k_slots=16,
                                                    degree=6)
            pre = run(st, cfg, tp, jax.random.PRNGKey(0), 8)
            assert int(pre.fault_flags) == 0, name
            full = run(st, cfg, tp, jax.random.PRNGKey(0), 30)
            assert int(full.fault_flags) == bit, \
                (name, invariants.decode_flags(int(full.fault_flags)))


class TestInjectedBitsExact:
    def test_partition_sets_exactly_partition_bit(self):
        plan = FaultPlan(partitions=(PartitionWindow(3, 8, components=2),))
        cfg, tp, st = _cfg(plan=plan)
        st = run(st, cfg, tp, jax.random.PRNGKey(1), 12)
        assert int(st.fault_flags) == invariants.FAULT_PARTITION

    def test_each_fault_class_sets_its_bit(self):
        for plan, bit in (
                (FaultPlan(link_drop_prob=0.3), invariants.FAULT_LINK_DROP),
                (FaultPlan(link_dup_prob=0.3), invariants.FAULT_LINK_DUP),
                (FaultPlan(corrupt_prob=0.5), invariants.FAULT_CORRUPT),
                (FaultPlan(outages=(OutageWindow(2, 6, fraction=0.25),)),
                 invariants.FAULT_OUTAGE)):
            cfg, tp, st = _cfg(plan=plan)
            st = run(st, cfg, tp, jax.random.PRNGKey(2), 8)
            assert int(st.fault_flags) == bit, invariants.decode_flags(
                int(st.fault_flags))

    def test_combined_plan_sets_union(self):
        plan = FaultPlan(link_drop_prob=0.2, corrupt_prob=0.5,
                         partitions=(PartitionWindow(2, 5),))
        cfg, tp, st = _cfg(plan=plan)
        st = run(st, cfg, tp, jax.random.PRNGKey(3), 8)
        want = (invariants.FAULT_LINK_DROP | invariants.FAULT_CORRUPT
                | invariants.FAULT_PARTITION)
        assert int(st.fault_flags) == want

    def test_null_plan_is_flag_free(self):
        cfg, tp, st = _cfg(plan=FaultPlan())
        st = run(st, cfg, tp, jax.random.PRNGKey(4), 8)
        assert int(st.fault_flags) == 0


class TestPartitionSemantics:
    def test_cut_heal_connectivity(self):
        plan = FaultPlan(partitions=(PartitionWindow(2, 6, components=2),))
        cfg, tp, st = _cfg(plan=plan)
        nbr = np.asarray(st.neighbors)
        known = (nbr >= 0) & (np.asarray(st.reverse_slot) >= 0)
        cross = known & ((np.arange(cfg.n_peers)[:, None] % 2)
                         != (np.clip(nbr, 0, None) % 2))
        mid = run(st, cfg, tp, jax.random.PRNGKey(5), 4)   # inside window
        conn_mid = np.asarray(mid.connected)
        assert not conn_mid[cross].any()                   # cut edges down
        assert conn_mid[known & ~cross].all()              # others untouched
        # mesh must not reference the cut edges (RemovePeer semantics)
        assert not (np.asarray(mid.mesh) & cross[:, None, :]).any()
        end = run(st, cfg, tp, jax.random.PRNGKey(5), 8)   # past heal
        assert np.asarray(end.connected)[known].all()      # healed

    def test_partition_recovers_delivery(self):
        """The acceptance bar, batched half: the partition_50k scenario
        shape at toy N recovers delivery_fraction >= 0.99 within a bounded
        budget after heal (window [5, 12), recovery check at tick 25 —
        the live message window is then entirely post-heal)."""
        cfg, tp, st = scenarios.partition_50k(
            n_peers=128, k_slots=16, degree=8, start=5, heal=12)
        mid = run(st, cfg, tp, jax.random.PRNGKey(6), 11)
        mid_frac = float(delivery_fraction(mid, cfg))
        end = run(st, cfg, tp, jax.random.PRNGKey(6), 25)
        end_frac = float(delivery_fraction(end, cfg))
        # during the partition, cross-component deliveries are impossible
        assert mid_frac < 0.95, mid_frac
        assert end_frac >= 0.99, end_frac
        assert int(end.fault_flags) == invariants.FAULT_PARTITION

    def test_heal_redials_only_the_plan_cut(self):
        """A heal must redial exactly the ending window's own cut set —
        an edge ordinary churn (or a test) took down stays on the normal
        reconnect path (code-review finding: a blanket heal bypassed the
        churn_reconnect_prob/PX gates for unrelated down edges)."""
        plan = FaultPlan(partitions=(PartitionWindow(2, 5, components=2),))
        cfg, tp, st = _cfg(plan=plan)
        nbr = np.asarray(st.neighbors)
        known = (nbr >= 0) & (np.asarray(st.reverse_slot) >= 0)
        cross = known & ((np.arange(cfg.n_peers)[:, None] % 2)
                         != (np.clip(nbr, 0, None) % 2))
        # take one SAME-component and one CROSS-component edge down
        # OUTSIDE the plan (pre-window, disconnect_tick=0 < start): the
        # heal must redial neither — the cross one was down before the
        # window opened, so the window never cut it (disconnect-stamp
        # gate in edge_cut_mask)
        conn, dt = st.connected, st.disconnect_tick
        downed = []
        for pick in (known & ~cross, known & cross):
            i, s = map(int, np.argwhere(pick)[0])
            j, rs = int(nbr[i, s]), int(np.asarray(st.reverse_slot)[i, s])
            conn = conn.at[i, s].set(False).at[j, rs].set(False)
            dt = dt.at[i, s].set(0).at[j, rs].set(0)
            downed.append((i, s, j, rs))
        st = st._replace(connected=conn, disconnect_tick=dt)
        end = run(st, cfg, tp, jax.random.PRNGKey(5), 8)   # past heal at 5
        conn_end = np.asarray(end.connected)
        pre_downed = np.zeros_like(conn_end)
        for i, s, j, rs in downed:
            pre_downed[i, s] = pre_downed[j, rs] = True
        assert conn_end[cross & ~pre_downed].all()  # the plan's cut healed
        assert not conn_end[pre_downed].any()       # pre-window downs stay

    def test_back_to_back_windows_still_heal(self):
        """Back-to-back (and overlapping) windows over the same edges: the
        later window inherits the earlier cut (the edge's disconnect stamp
        predates its start) and must heal it at its own end — the batched
        twin of the host injector's _reknit bookkeeping (code-review
        finding: the stamp gate alone left shared cuts down forever)."""
        plan = FaultPlan(partitions=(PartitionWindow(2, 5, components=2),
                                     PartitionWindow(5, 8, components=2),))
        cfg, tp, st = _cfg(plan=plan)
        known = (np.asarray(st.neighbors) >= 0) \
            & (np.asarray(st.reverse_slot) >= 0)
        mid = run(st, cfg, tp, jax.random.PRNGKey(5), 7)   # inside window 2
        assert not np.asarray(mid.connected)[known].all()  # still cut
        end = run(st, cfg, tp, jax.random.PRNGKey(5), 10)  # past both ends
        assert np.asarray(end.connected)[known].all(), \
            "shared cut edges never healed after the window chain ended"

    def test_outage_darkens_and_returns(self):
        plan = FaultPlan(outages=(OutageWindow(2, 7, fraction=0.3),))
        cfg, tp, st = _cfg(plan=plan, retain_score_ticks=30)
        dark = np.asarray(outage_peers_host(cfg.n_peers, 0, plan))
        assert 0 < dark.sum() < cfg.n_peers
        known = (np.asarray(st.neighbors) >= 0) \
            & (np.asarray(st.reverse_slot) >= 0)
        mid = run(st, cfg, tp, jax.random.PRNGKey(7), 5)
        conn = np.asarray(mid.connected)
        assert not conn[dark].any()                     # dark side down
        nbr_dark = dark[np.clip(np.asarray(st.neighbors), 0, None)]
        assert not conn[known & nbr_dark].any()         # both directions
        end = run(st, cfg, tp, jax.random.PRNGKey(7), 10)
        assert np.asarray(end.connected)[known].all()   # returned
        # outage_10k scenario shape builds and recovers at toy scale
        cfg2, tp2, st2 = scenarios.outage_10k(n_peers=96, k_slots=16,
                                              degree=8, start=3, heal=8)
        end2 = run(st2, cfg2, tp2, jax.random.PRNGKey(8), 20)
        assert float(delivery_fraction(end2, cfg2)) > 0.95
        assert int(end2.fault_flags) & invariants.FAULT_OUTAGE


class TestLinkFaults:
    def test_drop_degrades_delivery(self):
        clean_cfg, tp, st = _cfg(plan=None)
        lossy_cfg = dataclasses.replace(clean_cfg,
                                        fault_plan=FaultPlan(
                                            link_drop_prob=0.6))
        clean = run(st, clean_cfg, tp, jax.random.PRNGKey(9), 10)
        lossy = run(st, lossy_cfg, tp, jax.random.PRNGKey(9), 10)
        assert float(delivery_fraction(lossy, lossy_cfg)) < \
            float(delivery_fraction(clean, clean_cfg))
        # the drop bit and NO violation bits — lossy is degraded, not
        # poisoned (link-eaten answers do charge P7 broken promises, the
        # host tracer's expiry-based semantics, but that is scoring, not
        # an invariant violation)
        assert int(lossy.fault_flags) == invariants.FAULT_LINK_DROP

    def test_dup_feeds_duplicate_stats(self):
        # the P3 duplicate-credit window must be open for a re-offer of a
        # previously-delivered message to earn mesh credit (score.go:949-981
        # windowed duplicates; window 0 = same-tick only). Both plans are
        # non-None so the RNG streams match and the dup wiring is the ONLY
        # difference.
        plan = FaultPlan(link_dup_prob=1.0)
        cfg, tp, st = _cfg(plan=plan,
                           mesh_message_deliveries_window_ticks=2)
        clean = run(st, dataclasses.replace(cfg, fault_plan=FaultPlan()),
                    tp, jax.random.PRNGKey(10), 6)
        dup = run(st, cfg, tp, jax.random.PRNGKey(10), 6)
        assert float(jnp.sum(dup.mesh_message_deliveries)) > \
            float(jnp.sum(clean.mesh_message_deliveries))
        assert int(dup.fault_flags) == invariants.FAULT_LINK_DUP
        assert int(clean.fault_flags) == 0

    def test_corrupt_feeds_p4(self):
        plan = FaultPlan(corrupt_prob=0.5)
        cfg, tp, st = _cfg(plan=plan)
        clean = run(st, dataclasses.replace(cfg, fault_plan=None), tp,
                    jax.random.PRNGKey(11), 10)
        bad = run(st, cfg, tp, jax.random.PRNGKey(11), 10)
        assert float(jnp.sum(clean.invalid_message_deliveries)) == 0.0
        assert float(jnp.sum(bad.invalid_message_deliveries)) > 0.0
        assert int(bad.fault_flags) == invariants.FAULT_CORRUPT


class TestSentinel:
    def test_record_mode_flags_poison(self):
        cfg, tp, st = _cfg()
        poisoned = st._replace(first_message_deliveries=(
            st.first_message_deliveries.at[0, 0, 0].set(jnp.nan)))
        out = step_jit(poisoned, cfg, tp, jax.random.PRNGKey(0))
        flags = int(out.fault_flags)
        assert flags & invariants.FLAG_NONFINITE
        # negative seed in a counter the tick carries verbatim (the gater
        # stats are untouched when the gater is off): the zclamp at the
        # scored counters' write sites would wash a seed there back to 0
        neg = st._replace(gater_deliver=(
            st.gater_deliver.at[0, 0].set(-3.0)))
        out2 = step_jit(neg, cfg, tp, jax.random.PRNGKey(0))
        assert int(out2.fault_flags) & invariants.FLAG_NEG_COUNTER

    def test_record_mode_flags_dead_mesh_edge(self):
        cfg, tp, st = _cfg()
        st = run(st, cfg, tp, jax.random.PRNGKey(1), 5)
        # point a mesh slot at a disconnected edge behind the engine's back
        bad = st._replace(connected=st.connected.at[:, :].set(False))
        out = step_jit(bad, cfg, tp, jax.random.PRNGKey(2))
        if bool(jnp.any(out.mesh)):
            assert int(out.fault_flags) & invariants.FLAG_MESH_DEAD_EDGE

    def test_slot_garbage_flagged(self):
        cfg, tp, st = _cfg()
        # deliver_from persists through a provenance-free tick (dormant
        # buffer), so seeded garbage survives to the end-of-tick check —
        # iwant_pending would be consumed and rewritten by the emit step
        bad = st._replace(deliver_from=st.deliver_from.at[0, 0].set(99))
        out = step_jit(bad, cfg, tp, jax.random.PRNGKey(0))
        assert int(out.fault_flags) & invariants.FLAG_SLOT_GARBAGE

    def test_deliver_future_flagged(self):
        cfg, tp, st = _cfg()
        # slot 10 is not recycled at tick 0 (publish rotates slots 0..P-1)
        from go_libp2p_pubsub_tpu.sim.state import have_set_bit
        bad = st._replace(deliver_tick=st.deliver_tick.at[0, 10].set(500),
                          have=have_set_bit(st.have, 0, 10))
        out = step_jit(bad, cfg, tp, jax.random.PRNGKey(0))
        assert int(out.fault_flags) & invariants.FLAG_DELIVER_FUTURE

    def test_off_mode_writes_nothing(self):
        cfg, tp, st = _cfg(invariant_mode="off")
        bad = st._replace(delivered_total=jnp.float32(-1.0))
        out = step_jit(bad, cfg, tp, jax.random.PRNGKey(0))
        assert int(out.fault_flags) == 0

    def test_raise_mode_throws_on_poison_not_on_clean(self):
        cfg, tp, st = _cfg(invariant_mode="raise")
        # clean: no throw
        out = run_checked(st, cfg, tp, jax.random.PRNGKey(0), 4)
        assert int(out.tick) == 4
        # mesh_failure_penalty has no cap to wash an Inf back to finite
        poisoned = st._replace(mesh_failure_penalty=(
            st.mesh_failure_penalty.at[0, 0, 0].set(jnp.inf)))
        with pytest.raises(Exception, match="invariant violation"):
            run_checked(poisoned, cfg, tp, jax.random.PRNGKey(0), 4)

    def test_decode_flags_names(self):
        names = invariants.decode_flags(
            invariants.FAULT_PARTITION | invariants.FLAG_NONFINITE)
        assert names == ["partition", "VIOLATION:nonfinite_counter"]
        assert invariants.decode_flags(0) == []


class TestTraceExportHealth:
    def test_run_traced_emits_health_records(self):
        from go_libp2p_pubsub_tpu.sim.trace_export import run_traced
        plan = FaultPlan(partitions=(PartitionWindow(1, 3),))
        cfg, tp, st = _cfg(n=24, k=8, degree=4, plan=plan,
                           record_provenance=True)
        health = []
        st, events = run_traced(st, cfg, tp, jax.random.PRNGKey(0), 4,
                                health_out=health)
        assert len(health) == 4
        assert [h["tick"] for h in health] == [0, 1, 2, 3]
        assert health[0]["fault_flags"] == 0          # pre-window tick
        assert health[1]["fault_flags"] == invariants.FAULT_PARTITION
        assert health[1]["flags"] == ["partition"]
        # the flag word is sticky: later ticks keep the marker
        assert health[3]["fault_flags"] == invariants.FAULT_PARTITION
        assert events, "event stream must still export"


class TestPlanParse:
    def test_parse_roundtrip(self):
        plan = FaultPlan.parse(
            "drop=0.05,dup=0.01,corrupt=0.1,partition=2@10:30,"
            "outage=0.2@5:15,seed=7")
        assert plan == FaultPlan(
            link_drop_prob=0.05, link_dup_prob=0.01, corrupt_prob=0.1,
            partitions=(PartitionWindow(10, 30, components=2),),
            outages=(OutageWindow(5, 15, fraction=0.2),), seed=7)

    def test_parse_rejects_unknown(self):
        with pytest.raises(ValueError, match="unknown fault-plan item"):
            FaultPlan.parse("chaos=1")


class TestHostRuntimeParity:
    """The same plan shape against the functional runtime: partition-heal
    recovery parity with the batched half (>= 0.99 of subscribers get a
    post-heal publish), and the link hook's drop behavior."""

    def _swarm(self, n):
        from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
        from go_libp2p_pubsub_tpu.net import Network
        from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
        net = Network()
        nodes = [PubSub(net.add_host(), GossipSubRouter(),
                        sign_policy=LAX_NO_SIGN) for _ in range(n)]
        net.dense_connect([p.host for p in nodes], degree=8)
        subs = [p.join("t").subscribe() for p in nodes]
        return net, nodes, subs

    def test_host_partition_heals(self):
        net, nodes, subs = self._swarm(20)
        plan = FaultPlan(partitions=(PartitionWindow(3, 10, components=2),))
        HostFaultInjector(net, [p.host for p in nodes], plan)
        net.scheduler.run_for(6.0)       # mesh forms, partition bites
        # inside the window: a publish from component 0 stays there
        nodes[0].my_topics["t"].publish(b"partitioned")
        net.scheduler.run_for(2.0)
        got_mid = [s.next() is not None for s in subs[1:]]
        comp = [i % 2 for i in range(1, 20)]
        cross_got = [g for g, c in zip(got_mid, comp) if c == 1]
        assert not any(cross_got)        # nothing crossed the cut
        # past heal + recovery budget: a fresh publish reaches everyone
        net.scheduler.run_for(8.0)       # heal at t=10, settle to t=16
        nodes[0].my_topics["t"].publish(b"healed")
        net.scheduler.run_for(3.0)
        got = sum(1 for s in subs[1:]
                  if self._drain_for(s, b"healed"))
        assert got / (len(subs) - 1) >= 0.99, got

    @staticmethod
    def _drain_for(sub, payload):
        while (m := sub.next()) is not None:
            if m.data == payload:
                return True
        return False

    def test_host_outage_matches_batched_peer_choice(self):
        net, nodes, subs = self._swarm(12)
        plan = FaultPlan(outages=(OutageWindow(2, 5, fraction=0.3),), seed=3)
        inj = HostFaultInjector(net, [p.host for p in nodes], plan)
        dark = outage_peers_host(12, 0, plan)
        net.scheduler.run_for(3.0)       # inside the outage window
        for i, p in enumerate(nodes):
            if dark[i]:
                assert not p.host.conns, f"dark peer {i} kept connections"
        net.scheduler.run_for(4.0)       # past the window end at t=5
        for i, p in enumerate(nodes):
            assert p.host.conns, f"peer {i} never came back"
        assert inj.plan is plan

    def test_host_overlapping_windows_reknit_correctly(self):
        """Overlapping windows (code-review finding): a window's end must
        restore only pairs no OTHER active window still cuts, and an
        outage ending must not un-darken another window's peers."""
        net, nodes, subs = self._swarm(12)
        plan = FaultPlan(partitions=(PartitionWindow(2, 6, components=2),),
                         outages=(OutageWindow(4, 9, fraction=0.3),), seed=3)
        HostFaultInjector(net, [p.host for p in nodes], plan)
        dark = outage_peers_host(12, 0, plan)
        net.scheduler.run_for(7.0)    # partition ended at 6, outage live
        for i, p in enumerate(nodes):
            if dark[i]:
                assert not p.host.conns, f"dark peer {i} resurrected by " \
                    "the partition window's end"
            else:
                # lit peers regained their cross-component lit pairs
                assert p.host.conns, f"lit peer {i} still fully severed"
        net.scheduler.run_for(3.0)    # outage ends at 9
        for i, p in enumerate(nodes):
            assert p.host.conns, f"peer {i} never came back"

    def test_host_link_drop_counts_faulted(self):
        net, nodes, subs = self._swarm(8)
        plan = FaultPlan(link_drop_prob=1.0)
        HostFaultInjector(net, [p.host for p in nodes], plan)
        net.scheduler.run_for(3.0)
        nodes[0].my_topics["t"].publish(b"x")
        net.scheduler.run_for(2.0)
        # every RPC was eaten by the link: nothing delivered anywhere else
        assert all(s.next() is None for s in subs[1:])
        assert sum(p.host.faulted_rpcs for p in nodes) > 0
