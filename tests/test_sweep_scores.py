"""Tiny-grid smoke of the peer-score sweep (scripts/sweep_scores.py,
ISSUE 7 CI satellite): the grid runs as fleet groups, rows carry
delivery/resistance/flags, the journal makes a re-invocation skip
recorded cells verbatim, and the PERF_MODEL frontier-table rewrite is
idempotent."""

import json
import os

import pytest

from scripts.sweep_scores import (PERF_BEGIN, PERF_END, VARIANTS, _pareto,
                                  render_table, run_sweep, write_perf_model)

pytestmark = pytest.mark.fleet

GRID = dict(scenario_names=["sybil_small", "partition_small"],
            variant_names=["baseline", "p4_harsh"],
            n=128, ticks=10, seeds=1)


@pytest.fixture(scope="module")
def sweep(tmp_path_factory):
    td = tmp_path_factory.mktemp("sweep")
    journal = str(td / "sweep.jsonl")
    lines = []
    rows = run_sweep(GRID["scenario_names"], GRID["variant_names"],
                     n=GRID["n"], ticks=GRID["ticks"], seeds=GRID["seeds"],
                     journal=journal, emit=lines.append)
    return journal, rows, lines


def test_rows_cover_grid_with_metrics(sweep):
    _, rows, _ = sweep
    assert [(r["scenario"], r["variant"]) for r in rows] == [
        ("sybil_small", "baseline"), ("sybil_small", "p4_harsh"),
        ("partition_small", "baseline"), ("partition_small", "p4_harsh")]
    for r in rows:
        assert 0.0 <= r["delivery"] <= 1.0
        assert not r["tripped"]
    # sybil resistance is the mesh-eviction metric, always defined
    assert all(0.0 <= r["resistance"] <= 1.0 for r in rows
               if r["scenario"] == "sybil_small")
    # 10 ticks end before the partition heals (heal=20): the recovery
    # census is EMPTY and must surface as None, never a silent 0.0
    assert all(r["resistance"] is None for r in rows
               if r["scenario"] == "partition_small")
    # the partition plan fired and self-identified
    assert all("partition" in r["fault_flag_names"] for r in rows
               if r["scenario"] == "partition_small")


def test_journal_resume_skips_recorded_cells(sweep):
    journal, rows, _ = sweep
    n_lines = sum(1 for _ in open(journal))
    assert n_lines == 4
    lines2 = []
    rows2 = run_sweep(GRID["scenario_names"], GRID["variant_names"],
                      n=GRID["n"], ticks=GRID["ticks"], seeds=GRID["seeds"],
                      journal=journal, emit=lines2.append)
    skips = [json.loads(ln) for ln in lines2
             if json.loads(ln).get("info") == "journal skip"]
    assert len(skips) == 4
    assert rows2 == rows
    assert sum(1 for _ in open(journal)) == n_lines   # nothing re-recorded
    # no fleet ran at all on the resume
    assert not any(json.loads(ln).get("info") == "fleet done"
                   for ln in lines2)


def test_env_drift_invalidates_journal(sweep, tmp_path):
    """A journal recorded at different grid knobs must not stand in."""
    journal, _, _ = sweep
    lines = []
    run_sweep(["sybil_small"], ["baseline"], n=128, ticks=8,
              seeds=1, journal=journal, emit=lines.append)
    assert not any(json.loads(ln).get("info") == "journal skip"
                   for ln in lines)


def test_perf_model_rewrite_idempotent(sweep, tmp_path):
    _, rows, _ = sweep
    pm = str(tmp_path / "PM.md")
    with open(pm, "w") as f:
        f.write("# scratch perf model\n\nexisting text\n")
    write_perf_model(rows, pm)
    first = open(pm).read()
    assert PERF_BEGIN in first and PERF_END in first
    assert "existing text" in first            # surrounding text preserved
    write_perf_model(rows, pm)
    assert open(pm).read() == first            # marker replace, not append


def test_pareto_marks_nondominated_only():
    rows = [{"delivery": 0.9, "resistance": 0.5},
            {"delivery": 0.8, "resistance": 0.9},
            {"delivery": 0.7, "resistance": 0.4},    # dominated by both
            {"delivery": 0.95, "resistance": None}]  # empty census: out
    assert _pareto(rows) == {0, 1}


def test_variant_specs_resolve():
    """Every shipped variant spec splits cleanly into weight overrides +
    config overrides and applies to a real scenario build."""
    from scripts.sweep_scores import apply_variant
    from go_libp2p_pubsub_tpu.sim import scenarios
    cfg, tp, _ = scenarios.sybil_small(n_peers=128)
    for name, spec in VARIANTS.items():
        out_cfg, out_tp = apply_variant(cfg, tp, spec)
        assert out_tp.topic_weight.shape == tp.topic_weight.shape, name


def test_render_table_has_frontier_column(sweep):
    _, rows, _ = sweep
    table = render_table(rows)
    assert "| scenario | variant | delivery | resistance | frontier |" \
        in table
    assert "n/a" in table          # the empty partition census renders n/a
