"""Unit tests for the pure state machines (mcache, timecache, backoff,
blacklist, midgen, subscription filters).

Scenarios mirror the reference's mcache_test.go / backoff_test.go /
timecache tests / blacklist_test.go / subscription_filter_test.go coverage.
"""

import random

import pytest

from go_libp2p_pubsub_tpu.core.clock import VirtualClock
from go_libp2p_pubsub_tpu.core.types import Message, SubOpts
from go_libp2p_pubsub_tpu.utils import (
    AllowlistSubscriptionFilter,
    Backoff,
    LimitSubscriptionFilter,
    MapBlacklist,
    MaxBackoffAttemptsError,
    MessageCache,
    MsgIdGenerator,
    RegexpSubscriptionFilter,
    Strategy,
    TimeCache,
    TimeCachedBlacklist,
    TooManySubscriptionsError,
    default_msg_id_fn,
    filter_subscriptions,
)
from go_libp2p_pubsub_tpu.utils.backoff import (
    MAX_BACKOFF_DELAY,
    MIN_BACKOFF_DELAY,
    TIME_TO_LIVE,
)


def _msg(i: int, topic="test") -> Message:
    return Message(from_peer=f"peer-{i}", seqno=i.to_bytes(8, "big"), data=b"d" * i, topic=topic)


# --- mcache (mcache_test.go semantics) ---

class TestMessageCache:
    def test_put_get_window(self):
        mc = MessageCache(3, 5)
        msgs = [_msg(i) for i in range(60)]
        for m in msgs[:10]:
            mc.put(m)
        for m in msgs[:10]:
            assert mc.get(default_msg_id_fn(m)) is m
        gids = mc.get_gossip_ids("test")
        assert len(gids) == 10

        mc.shift()
        for m in msgs[10:20]:
            mc.put(m)
        assert len(mc.get_gossip_ids("test")) == 20

        # fill all history slots
        for k in range(2, 6):
            mc.shift()
            for m in msgs[k * 10:(k + 1) * 10]:
                mc.put(m)
        # gossip window only covers the newest 3 slots
        gids = mc.get_gossip_ids("test")
        assert len(gids) == 30
        # oldest slot evicted after enough shifts
        mc.shift()
        assert mc.get(default_msg_id_fn(msgs[10])) is None
        assert mc.get(default_msg_id_fn(msgs[50])) is not None

    def test_topic_filter(self):
        mc = MessageCache(2, 3)
        mc.put(_msg(1, topic="a"))
        mc.put(_msg(2, topic="b"))
        assert len(mc.get_gossip_ids("a")) == 1
        assert len(mc.get_gossip_ids("c")) == 0

    def test_get_for_peer_counts(self):
        mc = MessageCache(2, 3)
        m = _msg(1)
        mc.put(m)
        mid = default_msg_id_fn(m)
        for expect in (1, 2, 3):
            got, count = mc.get_for_peer(mid, "p1")
            assert got is m and count == expect
        _, count = mc.get_for_peer(mid, "p2")
        assert count == 1
        got, count = mc.get_for_peer("missing", "p1")
        assert got is None and count == 0

    def test_gossip_gt_history_rejected(self):
        with pytest.raises(ValueError):
            MessageCache(5, 3)


# --- timecache ---

class TestTimeCache:
    def test_first_seen(self):
        clk = VirtualClock()
        tc = TimeCache(120.0, clk.now)
        assert tc.add("a")
        assert not tc.add("a")  # already present
        assert tc.has("a")
        clk.advance_to(121.0)
        tc.sweep()
        assert not tc.has("a")

    def test_expiry_needs_sweep(self):
        # faithful to Go: has() alone does not expire
        clk = VirtualClock()
        tc = TimeCache(10.0, clk.now)
        tc.add("a")
        clk.advance_to(50.0)
        assert tc.has("a")
        tc.sweep()
        assert not tc.has("a")

    def test_last_seen_slides(self):
        clk = VirtualClock()
        tc = TimeCache(10.0, clk.now, Strategy.LAST_SEEN)
        tc.add("a")
        clk.advance_to(8.0)
        assert tc.has("a")  # refreshes expiry to t=18
        clk.advance_to(15.0)
        tc.sweep()
        assert tc.has("a")
        clk.advance_to(30.0)
        tc.sweep()
        assert not tc.has("a")

    def test_last_seen_add_refreshes(self):
        clk = VirtualClock()
        tc = TimeCache(10.0, clk.now, Strategy.LAST_SEEN)
        assert tc.add("a")
        clk.advance_to(5.0)
        assert not tc.add("a")  # not new, but refreshed to t=15
        clk.advance_to(12.0)
        tc.sweep()
        assert tc.has("a")


# --- backoff (backoff_test.go semantics) ---

class TestBackoff:
    def test_schedule(self):
        clk = VirtualClock()
        b = Backoff(clk.now, random.Random(314159))
        # first attempt: immediate
        assert b.update_and_get("p") == 0.0
        # second: min delay
        assert b.update_and_get("p") == MIN_BACKOFF_DELAY
        # subsequent: doubling + jitter, capped
        prev = MIN_BACKOFF_DELAY
        d = b.update_and_get("p")
        assert 2 * prev <= d <= 2 * prev + 0.1
        d2 = b.update_and_get("p")
        assert 2 * d <= d2 <= min(2 * d + 0.1, MAX_BACKOFF_DELAY)
        # max attempts reached
        with pytest.raises(MaxBackoffAttemptsError):
            b.update_and_get("p")

    def test_ttl_resets_history(self):
        clk = VirtualClock()
        b = Backoff(clk.now, random.Random(1))
        for _ in range(4):
            b.update_and_get("p")
        clk.advance_to(TIME_TO_LIVE + 1.0)
        assert b.update_and_get("p") == 0.0  # fresh history

    def test_cleanup(self):
        clk = VirtualClock()
        b = Backoff(clk.now, random.Random(1))
        b.update_and_get("p")
        clk.advance_to(TIME_TO_LIVE + 1.0)
        b.cleanup()
        assert len(b) == 0


# --- blacklist (blacklist_test.go semantics) ---

class TestBlacklist:
    def test_map(self):
        bl = MapBlacklist()
        assert not bl.contains("p")
        bl.add("p")
        assert bl.contains("p")

    def test_timecached(self):
        clk = VirtualClock()
        bl = TimeCachedBlacklist(10.0, clk.now)
        assert bl.add("p")
        assert not bl.add("p")  # duplicate add returns False
        assert bl.contains("p")
        clk.advance_to(11.0)
        bl.sweep()
        assert not bl.contains("p")


# --- midgen ---

class TestMsgIdGenerator:
    def test_default_and_override(self):
        g = MsgIdGenerator()
        m = _msg(1)
        assert g.id(m) == "peer-1" + (1).to_bytes(8, "big").decode("latin-1")
        g.set("other", lambda msg: "X")
        assert g.raw_id(_msg(1, topic="other")) == "X"
        # cached id short-circuits
        m2 = _msg(2)
        g.id(m2)
        g.set("test", lambda msg: "Y")
        assert g.id(m2) != "Y"  # cache wins
        assert g.raw_id(_msg(3)) == "Y"


# --- subscription filters (subscription_filter_test.go semantics) ---

class TestSubscriptionFilters:
    def test_allowlist(self):
        f = AllowlistSubscriptionFilter("test1", "test2")
        assert f.can_subscribe("test1")
        assert not f.can_subscribe("test3")
        out = f.filter_incoming_subscriptions("p", [
            SubOpts(True, "test1"), SubOpts(True, "test2"), SubOpts(True, "test3")])
        assert [s.topicid for s in out] == ["test1", "test2"]

    def test_regexp(self):
        f = RegexpSubscriptionFilter(r"^test[12]$")
        assert f.can_subscribe("test1")
        assert not f.can_subscribe("test3")

    def test_dedup_and_cancel(self):
        out = filter_subscriptions([
            SubOpts(True, "a"), SubOpts(True, "a"),       # duplicate kept once
            SubOpts(True, "b"), SubOpts(False, "b"),      # contradictory -> dropped
            SubOpts(True, "c"), SubOpts(False, "c"), SubOpts(True, "c"),  # re-enters
        ], lambda t: True)
        assert [(s.topicid, s.subscribe) for s in out] == [("a", True), ("c", True)]

    def test_limit(self):
        f = LimitSubscriptionFilter(AllowlistSubscriptionFilter("a"), 2)
        subs = [SubOpts(True, "a")] * 3
        with pytest.raises(TooManySubscriptionsError):
            f.filter_incoming_subscriptions("p", subs)
        assert len(f.filter_incoming_subscriptions("p", subs[:2])) == 1
