"""Live command plane (sim/commands.py, ISSUE 19): bounded host→device
directive ingestion with admission control, coast-mode degradation, and
exactly-once resume.

Layers under test, cheapest first: the jit-free parser fuzz (every
malformed line refused BY NAME, none crash), the bounded queue's drain /
shed / stall / offset-cursor semantics, the jitted replay apply
(supervised run with a directive stream bit-exact vs a manually
interleaved engine+replay reference, ONE replay trace for the whole
run), the exactly-once kill→resume leg (stamped ``stream_offset``
sidecar), the overload leg (deterministic journaled shedding, zero
retraces, chip never blocked) — capped by THE acceptance test: a real
supervised 2-process CPU run fed by an external producer subprocess that
is SIGKILLed mid-window (run coasts, journals the stall, producer
restarts from the stamped offset) plus a rank-SIGKILL group-relaunch
leg, both finishing bit-exact vs the same stream ingested uninterrupted.
"""

import json
import os
import random
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from go_libp2p_pubsub_tpu.sim import commands as cmds  # noqa: E402
from go_libp2p_pubsub_tpu.sim.commands import (  # noqa: E402
    CommandQueue, DirectiveError, parse_line, write_stream)

pytestmark = pytest.mark.commands


# ---------------------------------------------------------------------------
# directive parser: refusal BY NAME, jit-free (no jax import in sight)


class TestDirectiveParser:
    N, T = 64, 2

    def _parse(self, line, **kw):
        kw.setdefault("n_peers", self.N)
        kw.setdefault("n_topics", self.T)
        return parse_line(line, **kw)

    def test_valid_publish_join_leave(self):
        p = self._parse('{"op":"publish","tick":3,"peer":5,"topic":1}')
        assert p.ops == (("publish", 5, 1),) and p.tick == 3
        j = self._parse('{"op":"join","peer":0,"topic":0}')
        assert j.ops == (("join", 0, 0),) and j.tick == -1  # untimed
        v = self._parse('{"op":"leave","tick":0,"peer":63,"topic":1}')
        assert v.ops == (("leave", 63, 1),)

    def test_attack_storm_expands_to_publishes(self):
        p = self._parse('{"op":"attack","tick":2,"kind":"storm",'
                        '"topic":1,"peers":[1,2,3]}')
        assert p.ops == (("publish", 1, 1), ("publish", 2, 1),
                         ("publish", 3, 1))

    def test_attack_eclipse_censor_expand_to_peer_ops(self):
        """ISSUE 20 attack kinds: peer-targeted ops (no topic lane)."""
        e = self._parse('{"op":"attack","tick":2,"kind":"eclipse",'
                        '"peers":[4,5]}')
        assert e.ops == (("eclipse", 4, 0), ("eclipse", 5, 0))
        c = self._parse('{"op":"attack","kind":"censor","peers":[9]}')
        assert c.ops == (("censor", 9, 0),) and c.tick == -1

    def test_compose_mixes_parts_at_one_boundary(self):
        """The compose form: one timed line, several parts, every
        primitive op timed by the compose line's tick."""
        p = self._parse(json.dumps({"op": "compose", "tick": 4, "parts": [
            {"op": "attack", "kind": "eclipse", "peers": [0, 1]},
            {"op": "attack", "kind": "censor", "peers": [2]},
            {"op": "publish", "peer": 3, "topic": 1},
            {"op": "join", "peer": 4, "topic": 0},
        ]}))
        assert p.tick == 4 and p.kind == "directive"
        assert p.ops == (("eclipse", 0, 0), ("eclipse", 1, 0),
                         ("censor", 2, 0), ("publish", 3, 1),
                         ("join", 4, 0))

    @pytest.mark.parametrize("line,name", [
        ('{"op":"attack","kind":"eclipse","topic":0,"peers":[1]}',
         "takes no 'topic'"),
        ('{"op":"attack","kind":"censor","topic":1,"peers":[1]}',
         "takes no 'topic'"),
        ('{"op":"attack","kind":"eclipse","peers":[64]}', "out of range"),
        ('{"op":"attack","kind":"censor","peers":[true]}', "out of range"),
        # the unknown-kind refusal advertises the compose escape hatch
        ('{"op":"attack","kind":"partition","peers":[1]}', "compose"),
        ('{"op":"compose","tick":1,"parts":[]}', "non-empty"),
        ('{"op":"compose","tick":1,"parts":"x"}', "non-empty"),
        ('{"op":"compose","tick":1,"parts":[7]}', "JSON object"),
        ('{"op":"compose","tick":1,"parts":[{"op":"publish","tick":2,'
         '"peer":1,"topic":0}]}', "must not carry its own tick"),
        ('{"op":"compose","tick":1,"parts":[{"op":"compose",'
         '"parts":[]}]}', "cannot nest"),
        ('{"op":"compose","tick":1,"parts":[{"op":"tick"}]}',
         "part 0 op 'tick' unknown"),
        ('{"op":"compose","tick":1,"parts":[{"op":"end"}]}',
         "part 0 op 'end' unknown"),
    ])
    def test_composed_attacks_refused_by_name(self, line, name):
        with pytest.raises(DirectiveError, match=name):
            self._parse(line)

    def test_compose_oversized_total_refused(self):
        parts = [{"op": "attack", "kind": "eclipse",
                  "peers": list(range(6))},
                 {"op": "attack", "kind": "censor",
                  "peers": list(range(6, 12))}]
        with pytest.raises(DirectiveError, match="max_batch"):
            self._parse(json.dumps({"op": "compose", "tick": 0,
                                    "parts": parts}), max_batch=10)

    def test_watermark_and_end(self):
        assert self._parse('{"op":"tick","tick":9}').kind == "tick"
        assert self._parse('{"op":"end"}').kind == "end"
        assert self._parse("").kind == "blank"

    @pytest.mark.parametrize("line,name", [
        ("not json at all", "not valid JSON"),
        ('{"op":"publish","peer":5}', "topic"),
        ('{"op":"frobnicate"}', "unknown"),
        ('[1, 2, 3]', "JSON object"),
        ('{"op":"publish","peer":-1,"topic":0}', "out of range"),
        ('{"op":"publish","peer":64,"topic":0}', "out of range"),
        ('{"op":"publish","peer":0,"topic":2}', "out of range"),
        ('{"op":"publish","peer":"x","topic":0}', "must be an integer"),
        ('{"op":"publish","peer":true,"topic":0}', "must be an integer"),
        ('{"op":"join","peer":0,"topic":0,"tick":-7}', "tick"),
        ('{"op":"attack","kind":"surge","topic":0,"peers":[1]}',
         "unknown kind"),
        ('{"op":"attack","kind":"storm","topic":0,"peers":[]}',
         "non-empty"),
        ('{"op":"attack","kind":"storm","topic":0,"peers":[999]}',
         "out of range"),
        ('{"op":"tick"}', "watermark"),
    ])
    def test_refused_by_name(self, line, name):
        with pytest.raises(DirectiveError, match=name):
            self._parse(line)

    def test_oversized_batch_refused(self):
        peers = list(range(50))
        with pytest.raises(DirectiveError, match="max_batch"):
            self._parse(json.dumps({"op": "attack", "kind": "storm",
                                    "topic": 0, "peers": peers}),
                        max_batch=10)

    def test_fuzz_garbage_never_crashes(self):
        """Random byte garbage: every line either parses or raises
        DirectiveError — no other exception type ever escapes."""
        rng = random.Random(314159)
        alphabet = '{}[]",:0-9abcdef\\ \t\x00\xff'
        for _ in range(500):
            line = "".join(rng.choice(alphabet)
                           for _ in range(rng.randrange(0, 60)))
            try:
                self._parse(line)
            except DirectiveError:
                pass

    def test_fuzz_structured_never_crashes(self):
        """Structured fuzz: valid JSON objects with adversarial field
        types/values — same contract."""
        rng = random.Random(7)
        vals = [None, True, -1, 0, 63, 64, 10**12, 0.5, "x", [], {},
                [1, 2], {"a": 1}, [{"op": "attack"}],
                [{"op": "compose", "parts": []}],
                [{"op": "attack", "kind": "censor", "peers": [0]}] * 3]
        keys = ["op", "tick", "peer", "topic", "kind", "peers", "type",
                "timestamp", "peerID", "parts"]
        ops = ["publish", "join", "leave", "attack", "compose", "tick",
               "end", "nonsense", 7, None]
        for _ in range(500):
            d = {k: rng.choice(vals)
                 for k in rng.sample(keys, rng.randrange(0, len(keys)))}
            if rng.random() < 0.7:
                d["op"] = rng.choice(ops)
            try:
                self._parse(json.dumps(d))
            except DirectiveError:
                pass

    def test_trace_events_map_to_directives(self):
        j = self._parse(json.dumps(
            {"type": "JOIN", "timestamp": 3.0, "peerID": "5",
             "join": {"topic": "1"}}))
        assert j.ops == (("join", 5, 1),) and j.tick == 3
        pub = self._parse(json.dumps(
            {"type": "PUBLISH_MESSAGE", "timestamp": 2.5, "peerID": 7,
             "publishMessage": {"topic": 0}}))
        assert pub.ops == (("publish", 7, 0),) and pub.tick == 2
        # unsupported event types are counted skips, not refusals
        assert self._parse(json.dumps(
            {"type": "GRAFT", "timestamp": 1.0,
             "peerID": "5"})).kind == "skip:GRAFT"

    def test_trace_events_with_index_maps(self):
        p = self._parse(json.dumps(
            {"type": "JOIN", "timestamp": 0, "peerID": "Qmfoo",
             "join": {"topic": "blocks"}}),
            peer_index={"Qmfoo": 9}, topic_index={"blocks": 1})
        assert p.ops == (("join", 9, 1),)
        with pytest.raises(DirectiveError, match="not in peer_index"):
            self._parse(json.dumps(
                {"type": "JOIN", "timestamp": 0, "peerID": "Qmbar",
                 "join": {"topic": "blocks"}}),
                peer_index={"Qmfoo": 9}, topic_index={"blocks": 1})

    def test_op_codes_mirror_replay(self):
        """commands.py duplicates the replay op codes to stay jax-free;
        this is the pin that keeps the mirror honest."""
        import importlib
        rp = importlib.import_module("go_libp2p_pubsub_tpu.trace.replay")
        assert (cmds.OP_NOP, cmds.OP_JOIN, cmds.OP_LEAVE,
                cmds.OP_PUBLISH) == (rp.OP_NOP, rp.OP_JOIN, rp.OP_LEAVE,
                                     rp.OP_PUBLISH)

    def test_attack_op_codes_outside_replay_space(self):
        """The ISSUE 20 attack lanes live ABOVE the replay op space:
        apply_frame masks them to NOP before the replay trace sees the
        frame, so the single compiled trace keeps serving every frame."""
        import importlib
        rp = importlib.import_module("go_libp2p_pubsub_tpu.trace.replay")
        assert cmds.ATTACK_OP_BASE == 16
        assert (cmds.OP_ECLIPSE, cmds.OP_CENSOR) == (16, 17)
        assert min(cmds.OP_ECLIPSE, cmds.OP_CENSOR) > max(
            rp.OP_NOP, rp.OP_JOIN, rp.OP_LEAVE, rp.OP_PUBLISH)


# ---------------------------------------------------------------------------
# CommandQueue: drain / shed / stall / offset-cursor semantics (host-only)


def _mkq(src, slots=4, stall=2.0, **kw):
    kw.setdefault("n_peers", 64)
    kw.setdefault("n_topics", 2)
    kw.setdefault("msg_window", 32)
    kw.setdefault("coast_poll_s", 0.01)
    return CommandQueue(str(src), slots=slots, stall_timeout_s=stall, **kw)


STREAM = [
    {"op": "publish", "tick": 1, "peer": 3, "topic": 0},
    {"op": "join", "tick": 3, "peer": 7, "topic": 1},
    {"op": "bogus"},                                # refused, consumed
    {"op": "tick", "tick": 9},
    {"op": "publish", "tick": 9, "peer": 2, "topic": 0},
]


class TestCommandQueue:
    def test_boundary_drain_routes_by_tick(self, tmp_path):
        src = tmp_path / "s.ndjsonl"
        size = write_stream(str(src), STREAM)
        q = _mkq(src).start(0)
        try:
            f0 = q.frame_for(0, 5)       # [0,5): publish@1, join@3
            assert f0.count == 2
            assert list(f0.op[:2]) == [cmds.OP_PUBLISH, cmds.OP_JOIN]
            assert list(f0.a[:2]) == [3, 7]
            assert [k for k, _m in f0.notes] == ["directive_refused"]
            f1 = q.frame_for(5, 5)       # [5,10): publish@9
            assert f1.count == 1 and f1.a[0] == 2
            f2 = q.frame_for(10, 5)      # past EOF: empty, fully consumed
            assert f2.count == 0 and f2.offset == size
            assert q.applied_total == 3 and q.refused_total == 1
        finally:
            q.close()

    def test_frame_cache_returns_identical_frame(self, tmp_path):
        src = tmp_path / "s.ndjsonl"
        write_stream(str(src), STREAM)
        q = _mkq(src).start(0)
        try:
            f0 = q.frame_for(0, 5)
            again = q.frame_for(0, 5)    # a retry's re-fetch
            assert again is f0
        finally:
            q.close()

    def test_offset_cursor_is_exactly_once(self, tmp_path):
        """A queue seeked to frame k's stamped offset reproduces frames
        k+1... bit for bit: the byte offset is a complete ingestion
        cursor (prefix consumption, refusals included)."""
        src = tmp_path / "s.ndjsonl"
        write_stream(str(src), STREAM)
        q = _mkq(src).start(0)
        f0 = q.frame_for(0, 5)
        f1 = q.frame_for(5, 5)
        q.close()
        q2 = _mkq(src).start(f0.offset)
        g1 = q2.frame_for(5, 5)
        q2.close()
        for fld in ("op", "a", "b", "c"):
            np.testing.assert_array_equal(getattr(g1, fld),
                                          getattr(f1, fld), err_msg=fld)
        assert g1.offset == f1.offset and g1.count == f1.count

    def test_overflow_sheds_deterministically(self, tmp_path):
        src = tmp_path / "s.ndjsonl"
        write_stream(str(src), [
            {"op": "publish", "tick": 0, "peer": p, "topic": 0}
            for p in range(10)])
        q = _mkq(src, slots=4).start(0)
        try:
            f = q.frame_for(0, 2)
            assert f.count == 4 and f.shed == 6 and f.shed_total == 6
            # shed by stream position: the FIRST four peers won
            assert list(f.a) == [0, 1, 2, 3]
            assert ("ingest_shed", {"tick": 0, "shed": 6, "slots": 4}) \
                in f.notes
            # shed lines are consumed — nothing replays them
            assert q.frame_for(2, 2).count == 0
        finally:
            q.close()

    def test_stall_coast_resume_markers(self, tmp_path):
        src = tmp_path / "s.ndjsonl"
        with open(src, "w") as f:
            f.write(json.dumps(
                {"op": "publish", "tick": 1, "peer": 1, "topic": 0})
                + "\n")
        q = _mkq(src, stall=0.3).start(0)
        try:
            f0 = q.frame_for(0, 2)       # watermark 1 < 2: stalls, coasts
            assert f0.coasting and f0.count == 1
            assert [k for k, _m in f0.notes] == ["ingest_stalled"]
            stall_meta = dict(f0.notes)["ingest_stalled"]
            assert stall_meta["offset"] == os.path.getsize(src)
            assert "directive_producer.py" in stall_meta["resume_cmd"]
            f1 = q.frame_for(2, 2)       # still silent: keeps coasting,
            assert f1.coasting and not f1.notes    # marker NOT repeated
            with open(src, "a") as fh:   # producer comes back
                fh.write(json.dumps(
                    {"op": "publish", "tick": 5, "peer": 2, "topic": 0})
                    + "\n")
                fh.write(json.dumps({"op": "end"}) + "\n")
            deadline = time.monotonic() + 5.0
            while not q._eof and time.monotonic() < deadline:
                time.sleep(0.02)    # let the tailing reader catch up
            f2 = q.frame_for(4, 2)
            assert not f2.coasting and f2.count == 1
            assert "ingest_resumed" in [k for k, _m in f2.notes]
        finally:
            q.close()

    def test_unread_stream_blocks_untimed_stream_does_not(self, tmp_path):
        src = tmp_path / "s.ndjsonl"
        write_stream(str(src), [{"op": "join", "peer": 1, "topic": 0}])
        q = _mkq(src, stall=5.0).start(0)
        try:
            t0 = time.monotonic()
            f = q.frame_for(0, 2)        # blocks only until primed
            assert time.monotonic() - t0 < 4.0
            assert f.count == 1 and not f.coasting
        finally:
            q.close()

    def test_backpressure_bounds_queue_memory(self, tmp_path):
        src = tmp_path / "s.ndjsonl"
        write_stream(str(src), [
            {"op": "publish", "tick": p // 8, "peer": p % 64, "topic": 0}
            for p in range(200)])
        q = _mkq(src, slots=4, maxlen=16).start(0)
        try:
            deadline = time.monotonic() + 10.0
            start = 0
            while q.applied_total + q.shed_total < 200 \
                    and time.monotonic() < deadline:
                with q._cond:
                    assert len(q._q) <= 16       # the reader blocked
                q.frame_for(start, 1)   # fresh boundary each drain
                start += 1
                time.sleep(0.005)
            assert q.applied_total + q.shed_total == 200
        finally:
            q.close()


class TestIngestChaos:
    def test_parse_ingest_specs(self):
        from go_libp2p_pubsub_tpu.parallel.resilience import ChaosPlan
        specs = ChaosPlan.parse("ingest_stall@4:2.5, ingest_kill@8")
        assert specs == [
            {"action": "ingest_stall", "rank": 0, "tick": 4,
             "seconds": 2.5},
            {"action": "ingest_kill", "rank": 0, "tick": 8,
             "seconds": 0.0}]

    @pytest.mark.parametrize("bad", ["ingest_stall@4", "ingest_kill@4:2",
                                     "ingest_stall@x:1"])
    def test_parse_refuses_by_name(self, bad):
        from go_libp2p_pubsub_tpu.parallel.resilience import ChaosPlan
        with pytest.raises(ValueError, match="GRAFT_CHAOS"):
            ChaosPlan.parse(bad)

    def test_ingest_specs_live_on_rank0_and_skip_fire(self, tmp_path):
        from go_libp2p_pubsub_tpu.parallel.resilience import ChaosPlan
        plan = ChaosPlan(ChaosPlan.parse("ingest_kill@2"), rank=0,
                         run_dir=str(tmp_path))
        assert plan.specs == [] and len(plan.ingest_specs) == 1
        plan.fire({"chunk_start": 5})    # chunk-hook path must skip them
        assert not os.listdir(tmp_path)
        assert ChaosPlan(ChaosPlan.parse("ingest_kill@2"),
                         rank=1).ingest_specs == []

    def test_fire_ingest_once_per_run_dir(self, tmp_path):
        from go_libp2p_pubsub_tpu.parallel.resilience import ChaosPlan

        class Q:
            killed = 0

            def kill_reader(self):
                self.killed += 1

        plan = ChaosPlan(ChaosPlan.parse("ingest_kill@2"), rank=0,
                         run_dir=str(tmp_path))
        q = Q()
        plan.fire_ingest(0, q)
        assert q.killed == 0
        plan.fire_ingest(2, q)
        plan.fire_ingest(4, q)
        assert q.killed == 1
        # relaunched process, same run dir: durable marker holds
        ChaosPlan(ChaosPlan.parse("ingest_kill@2"), rank=0,
                  run_dir=str(tmp_path)).fire_ingest(2, q)
        assert q.killed == 1
        assert [n for n in os.listdir(tmp_path)
                if n.endswith(".fired")] == ["chaos_ingest_kill_r0_t2.fired"]

    def test_chaos_ingest_kill_coasts_the_queue(self, tmp_path):
        from go_libp2p_pubsub_tpu.parallel.resilience import ChaosPlan
        src = tmp_path / "s.ndjsonl"
        with open(src, "w") as f:
            f.write(json.dumps(
                {"op": "publish", "tick": 1, "peer": 1, "topic": 0})
                + "\n")
        plan = ChaosPlan(ChaosPlan.parse("ingest_kill@2"), rank=0,
                         run_dir=str(tmp_path))
        q = _mkq(src, stall=0.3, chaos=plan).start(0)
        try:
            f0 = q.frame_for(0, 2)
            assert f0.count == 1
            f1 = q.frame_for(2, 2)       # chaos kills the reader: coast
            assert f1.coasting
            assert "ingest_stalled" in [k for k, _m in
                                        f0.notes + f1.notes]
        finally:
            q.close()


# ---------------------------------------------------------------------------
# the jitted apply + supervised integration (single process)


@pytest.fixture(scope="module")
def small():
    import jax

    from go_libp2p_pubsub_tpu.sim import scenarios
    cfg, tp, state = scenarios.single_topic_1k(n_peers=128, k_slots=16,
                                               degree=6)
    return cfg, tp, state, jax.random.PRNGKey(42)


DIRECTIVES = [
    {"op": "publish", "tick": 1, "peer": 3, "topic": 0},
    {"op": "join", "tick": 4, "peer": 7, "topic": 0},
    {"op": "attack", "tick": 7, "kind": "storm", "topic": 0,
     "peers": [10, 11, 12]},
    {"op": "leave", "tick": 10, "peer": 7, "topic": 0},
]

SLOTS, CHUNK, TICKS = 8, 3, 12


def _queue_for(cfg, src, **kw):
    kw.setdefault("stall_timeout_s", 30.0)
    return CommandQueue(str(src), n_peers=cfg.n_peers,
                        n_topics=cfg.n_topics, msg_window=cfg.msg_window,
                        slots=SLOTS, **kw)


def _sup(q, **kw):
    from go_libp2p_pubsub_tpu.sim.supervisor import SupervisorConfig
    return SupervisorConfig(chunk_ticks=CHUNK, commands=q,
                            backoff_base_s=0.0, sleep=lambda s: None,
                            **kw)


def _run(state, cfg, tp, key, q, n_ticks=TICKS, **kw):
    from go_libp2p_pubsub_tpu.sim.supervisor import supervised_run
    try:
        return supervised_run(state, cfg, tp, key, n_ticks, _sup(q, **kw))
    finally:
        q.close()


def _assert_states_equal(a, b):
    for f, x, y in zip(a._fields, a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"field {f}")


def _manual_reference(state, cfg, tp, key, directives, n_ticks=TICKS,
                      chunk=CHUNK, slots=SLOTS):
    """First-principles reference: engine chunks interleaved with replay
    frames built by hand — the trajectory the command plane must hit."""
    import jax

    from go_libp2p_pubsub_tpu.sim import engine
    st = state
    all_keys = jax.random.split(key, n_ticks)
    for start in range(0, n_ticks, chunk):
        prims = []
        for d in directives:
            if not start <= d["tick"] < start + chunk:
                continue
            if d["op"] == "attack":
                prims += [("publish", p, d["topic"]) for p in d["peers"]]
            else:
                prims.append((d["op"], d["peer"], d["topic"]))
        if prims:
            op = np.zeros(slots, np.int32)
            a = np.zeros(slots, np.int32)
            b = np.zeros(slots, np.int32)
            c = np.zeros(slots, np.int32)
            for i, (kind, peer, topic) in enumerate(prims):
                a[i], c[i] = peer, topic
                if kind == "publish":
                    op[i] = cmds.OP_PUBLISH
                    b[i] = (start * slots + i) % cfg.msg_window
                else:
                    op[i] = cmds.OP_JOIN if kind == "join" \
                        else cmds.OP_LEAVE
                    b[i] = -1
            st = cmds.apply_frame(st, cfg, tp, cmds.empty_frame(slots)
                                  ._replace(op=op, a=a, b=b, c=c,
                                            count=len(prims)))
        st = engine.run_keys(st, cfg, tp, all_keys[start:start + chunk])
    return st


class TestSupervisedIngest:
    def test_stream_run_bit_exact_vs_manual_replay(self, small, tmp_path):
        """The promotion claim: a supervised run fed the NDJSON stream
        equals engine chunks manually interleaved with replay frames —
        trace/replay.py IS the ingestion path."""
        cfg, tp, state, key = small
        src = tmp_path / "s.ndjsonl"
        write_stream(str(src), DIRECTIVES)
        out, rep = _run(state, cfg, tp, key, _queue_for(cfg, src))
        ref = _manual_reference(state, cfg, tp, key, DIRECTIVES)
        _assert_states_equal(ref, out)
        assert [e.get("directives") for e in rep.events
                if "directives" in e] == [1, 1, 3, 1]

    def test_kill_resume_exactly_once_bit_exact(self, small, tmp_path):
        """ISSUE 19 single-process resume leg: kill mid-run, resume from
        the checkpoint — the stamped stream_offset seeks the queue so
        every directive applies exactly once; final state bit-exact vs
        the uninterrupted run of the same stream."""
        import glob

        from go_libp2p_pubsub_tpu.sim import checkpoint
        cfg, tp, state, key = small
        src = tmp_path / "s.ndjsonl"
        write_stream(str(src), DIRECTIVES)
        ref, _ = _run(state, cfg, tp, key, _queue_for(cfg, src))

        ck = str(tmp_path / "ck")

        def kill(info):
            if info["chunk_start"] >= 9:
                raise KeyboardInterrupt("simulated preemption")

        from go_libp2p_pubsub_tpu.sim.supervisor import supervised_run
        q1 = _queue_for(cfg, src)
        with pytest.raises(KeyboardInterrupt):
            try:
                supervised_run(state, cfg, tp, key, TICKS,
                               _sup(q1, checkpoint_dir=ck),
                               _chunk_hook=kill)
            finally:
                q1.close()
        # every drained checkpoint carries the ingestion cursor
        stamped = [checkpoint.sidecar_meta(p).get("stream_offset")
                   for p in glob.glob(os.path.join(ck, "*"))
                   if not p.endswith(".fingerprint")]
        assert stamped and all(s is not None for s in stamped)

        out, rep = _run(state, cfg, tp, key, _queue_for(cfg, src),
                        checkpoint_dir=ck)
        assert rep.resumed_tick is not None
        start = next(e for e in rep.events
                     if e["event"] == "ingest_start")
        assert start["offset"] > 0      # seeked, not replayed from 0
        _assert_states_equal(ref, out)

    def test_overload_sheds_deterministically_zero_retrace(
            self, small, tmp_path):
        """ISSUE 19 overload leg: offered load past the slot budget is
        journaled load-shedding — exact counts, zero retraces (compile
        caches asserted), and the chip never blocks on ingest (no stall
        markers, EOF stream)."""
        import importlib

        from go_libp2p_pubsub_tpu.parallel import compile_plan
        from go_libp2p_pubsub_tpu.sim.telemetry import read_journal
        rp = importlib.import_module("go_libp2p_pubsub_tpu.trace.replay")
        cfg, tp, state, key = small
        src = tmp_path / "s.ndjsonl"
        # 4x the slot budget offered into chunk [0,3), plus steady load
        over = [{"op": "publish", "tick": 1, "peer": p, "topic": 0}
                for p in range(4 * SLOTS)]
        over += [{"op": "publish", "tick": t, "peer": t, "topic": 0}
                 for t in range(3, TICKS)]
        write_stream(str(src), over)
        health = str(tmp_path / "health.jsonl")

        aot_before = None
        seen_keys = set()

        out, rep = _run(state, cfg, tp, key, _queue_for(cfg, src),
                        health_path=health)
        j = read_journal(health)
        ing = [n for n in j["notes"] if n.get("kind") == "ingest"]
        shed = [n for n in j["notes"] if n.get("kind") == "ingest_shed"]
        assert ing and ing[-1]["shed_total"] == 3 * SLOTS
        assert sum(n["shed"] for n in shed) == 3 * SLOTS
        assert shed[0]["slots"] == SLOTS
        # deterministic: the journaled counts are a pure function of the
        # stream — a second identical run sheds identically
        out2, _ = _run(state, cfg, tp, key, _queue_for(cfg, src))
        _assert_states_equal(out, out2)
        # chip never blocked: no coast markers anywhere
        assert not [n for n in j["notes"]
                    if n.get("kind") == "ingest_stalled"]
        assert all(not n["coasting"] for n in ing)
        # zero retraces: ONE replay trace serves every frame, and the
        # second run added no engine executables either
        assert rp.replay._cache_size() == 1
        aot = set(compile_plan._ENGINE_AOT)
        out3, _ = _run(state, cfg, tp, key, _queue_for(cfg, src))
        assert set(compile_plan._ENGINE_AOT) == aot
        assert rp.replay._cache_size() == 1
        assert rep.retries == 0

    def test_coast_mode_steps_through_producer_silence(self, small,
                                                       tmp_path):
        """A stream that goes silent mid-run: the run coasts (empty
        frames, stall marker), keeps stepping to completion, and the
        coasted trajectory equals the no-directives-after-silence run."""
        cfg, tp, state, key = small
        src = tmp_path / "s.ndjsonl"
        early = [d for d in DIRECTIVES if d["tick"] < 6]
        with open(src, "w") as f:            # no end marker: silence
            for d in early:
                f.write(json.dumps(d) + "\n")
        q = _queue_for(cfg, src, stall_timeout_s=0.3, coast_poll_s=0.01)
        out, rep = _run(state, cfg, tp, key, q)
        src2 = tmp_path / "s2.ndjsonl"
        write_stream(str(src2), early)       # same stream, clean EOF
        ref, _ = _run(state, cfg, tp, key, _queue_for(cfg, src2))
        _assert_states_equal(ref, out)

    def test_broadcast_wrapper_single_process_identity(self, small,
                                                       tmp_path):
        """BroadcastCommands at process_count=1 hands back the inner
        queue's frames unchanged (the rank-0 side of the multihost
        broadcast) — and its totals mirror the frame metadata."""
        cfg, tp, state, key = small
        src = tmp_path / "s.ndjsonl"
        write_stream(str(src), DIRECTIVES)
        inner = _queue_for(cfg, src)
        bc = cmds.BroadcastCommands(inner, slots=SLOTS)
        out, _ = _run(state, cfg, tp, key, bc)
        ref, _ = _run(state, cfg, tp, key, _queue_for(cfg, src))
        _assert_states_equal(ref, out)
        assert bc.applied_total == 6 and bc.shed_total == 0

    def test_composed_attack_lights_both_fault_bits(self, small,
                                                    tmp_path):
        """ISSUE 20 composed attack end to end in-process: the canonical
        eclipse+censor stream (scripts/directive_producer.py --scenario)
        lands at ONE boundary, the invariant sentinel lights BOTH fault
        bits in the health rows, and the attack lanes cost zero replay
        retraces (apply_frame masks them to NOP for the trace)."""
        import importlib

        from go_libp2p_pubsub_tpu.sim.invariants import (FAULT_CENSOR,
                                                         FAULT_ECLIPSE)
        from go_libp2p_pubsub_tpu.sim.telemetry import read_journal
        rp = importlib.import_module("go_libp2p_pubsub_tpu.trace.replay")
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            from directive_producer import scenario_directives
        finally:
            sys.path.pop(0)
        cfg, tp, state, key = small
        src = tmp_path / "s.ndjsonl"
        # region 4 + cohort 4 = 8 primitive ops: exactly the SLOTS
        # budget, nothing shed
        write_stream(str(src), scenario_directives(
            "eclipse_censor", at=4, region=4, attackers=4, bursts=1),
            end=True)
        health = str(tmp_path / "health.jsonl")
        out, rep = _run(state, cfg, tp, key, _queue_for(cfg, src),
                        health_path=health)
        j = read_journal(health)
        flags = [int(r.get("fault_flags") or 0) for r in j["rows"]]
        # the tick-4 directive routes to chunk [3,6): applied at its
        # opening boundary, so the sticky bits light from tick 3 on
        pre = [f for r, f in zip(j["rows"], flags) if r["tick"] < 3]
        post = max(f for r, f in zip(j["rows"], flags) if r["tick"] >= 3)
        assert not any(f & (FAULT_ECLIPSE | FAULT_CENSOR) for f in pre)
        assert post & FAULT_ECLIPSE and post & FAULT_CENSOR
        assert not [n for n in j["notes"] if n.get("kind") == "ingest_shed"]
        # deterministic: the composed attack replays bit-exact
        out2, _ = _run(state, cfg, tp, key, _queue_for(cfg, src))
        _assert_states_equal(out, out2)
        assert rp.replay._cache_size() == 1 and rep.retries == 0


# ---------------------------------------------------------------------------
# dashboard ingest view


class TestDashboardIngest:
    def _journal(self, tmp_path, coasting):
        path = tmp_path / "health.jsonl"
        now = time.time()
        with open(path, "w") as f:
            f.write(json.dumps({"kind": "run", "wall": now - 10,
                                "scenario": "frontier_250k",
                                "n_peers": 128, "n_topics": 1,
                                "flags_version": 1}) + "\n")
            if coasting:
                f.write(json.dumps(
                    {"kind": "ingest_stalled", "wall": now - 2, "tick": 6,
                     "offset": 1234, "source": "/shared/live.ndjsonl",
                     "resume_cmd": "python scripts/directive_producer.py "
                                   "--stream <input> --out "
                                   "/shared/live.ndjsonl "
                                   "--from-offset 1234"}) + "\n")
            f.write(json.dumps(
                {"kind": "ingest", "wall": now - 1, "tick": 8,
                 "directives": 0 if coasting else 3, "shed": 0,
                 "shed_total": 5, "refused_total": 2, "queue_depth": 1,
                 "lag_ticks": 0, "offset": 1234,
                 "coasting": coasting}) + "\n")
        return str(path)

    def test_snapshot_attaches_ingest_vitals(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import dashboard
        finally:
            sys.path.pop(0)
        snap = dashboard.snapshot(self._journal(tmp_path, coasting=False))
        ing = snap["ingest"]
        assert ing["shed_total"] == 5 and ing["offset"] == 1234
        assert not ing.get("coasting")
        text = dashboard.render(snap)
        assert "ingest" in text and "shed 5" in text
        assert "COASTING" not in text

    def test_coasting_banner_carries_resume_cmd(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import dashboard
        finally:
            sys.path.pop(0)
        snap = dashboard.snapshot(self._journal(tmp_path, coasting=True))
        assert snap["ingest"]["coasting"]
        assert snap["ingest"]["resume_cmd"].startswith(
            "python scripts/directive_producer.py")
        text = dashboard.render(snap)
        assert "COASTING" in text
        assert "--from-offset 1234" in text


# ---------------------------------------------------------------------------
# THE acceptance test: 2-process run + external producer subprocess


MH_TICKS, MH_CHUNK, MH_SEED, MH_N = 16, 2, 7, 128

MH_STREAM = [
    {"op": "publish", "tick": 1, "peer": 3, "topic": 0},
    {"op": "join", "tick": 3, "peer": 9, "topic": 0},
    # --- producer parks/dies here; the run coasts through [4, 12) ---
    {"op": "publish", "tick": 13, "peer": 5, "topic": 0},
    {"op": "attack", "tick": 15, "kind": "storm", "topic": 0,
     "peers": [20, 21]},
]


def _mh_env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)      # conftest's 8-device flag must not leak
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="", **extra)
    return env


def _mh_cmd(run_dir, final, source, stall="1.0", coast="0.5",
            procs="2"):
    return [sys.executable,
            os.path.join(REPO, "scripts", "mh_supervisor.py"),
            "--procs", procs, "--scenario", "frontier_250k",
            "--n", str(MH_N), "--ticks", str(MH_TICKS),
            "--seed", str(MH_SEED), "--chunk-ticks", str(MH_CHUNK),
            "--run-dir", str(run_dir), "--max-relaunches", "2",
            "--backoff-base-s", "0.05", "--dump-state", str(final),
            "--health", str(run_dir / "health.jsonl"),
            "--source", str(source), "--directive-slots", "8",
            "--ingest-stall-timeout", stall,
            "--ingest-coast-poll", coast]


@pytest.fixture(scope="module")
def mh_reference(tmp_path_factory):
    """The same stream ingested uninterrupted, single process — the
    trajectory both acceptance legs must reproduce bit for bit (the
    1-proc == 2-proc contract is tests/test_multihost.py's pin; the
    directive frames apply at the same chunk boundaries either way)."""
    import jax

    from go_libp2p_pubsub_tpu.parallel import multihost
    from go_libp2p_pubsub_tpu.sim import scenarios
    from go_libp2p_pubsub_tpu.sim.supervisor import (SupervisorConfig,
                                                     supervised_run)
    d = tmp_path_factory.mktemp("ref")
    src = d / "full.ndjsonl"
    write_stream(str(src), MH_STREAM)
    cfg, tp, topo, subscribed = scenarios.frontier_spec(MH_N)
    st = multihost.init_state_local(cfg, topo, 0, 1,
                                    subscribed=subscribed)
    q = CommandQueue(str(src), n_peers=cfg.n_peers,
                     n_topics=cfg.n_topics, msg_window=cfg.msg_window,
                     slots=8, stall_timeout_s=60.0)
    sup = SupervisorConfig(chunk_ticks=MH_CHUNK, commands=q,
                           backoff_base_s=0.0, sleep=lambda s: None)
    try:
        out, _ = supervised_run(st, cfg, tp,
                                jax.random.PRNGKey(MH_SEED), MH_TICKS,
                                sup)
    finally:
        q.close()
    return out


def _assert_dump_equals(final, ref):
    got = np.load(final)
    for f in ref._fields:
        assert np.array_equal(np.asarray(getattr(ref, f)), got[f]), f


@pytest.mark.slow
def test_mh_producer_sigkill_coast_restart_bit_exact(tmp_path,
                                                     mh_reference):
    """THE ISSUE 19 acceptance leg: a real supervised 2-process CPU run
    fed by an external producer subprocess. The producer is SIGKILLed
    mid-window → the run coasts and journals ``ingest_stalled`` with the
    stamped offset → a new producer resumes the feed from that offset →
    the run journals ``ingest_resumed`` and finishes bit-exact vs the
    same stream ingested uninterrupted."""
    run_dir = tmp_path / "mh"
    run_dir.mkdir()
    final = tmp_path / "final.npz"
    stream = tmp_path / "full.ndjsonl"
    write_stream(str(stream), MH_STREAM)
    live = tmp_path / "live.ndjsonl"
    health = run_dir / "health.jsonl"

    producer_cmd = [sys.executable,
                    os.path.join(REPO, "scripts", "directive_producer.py"),
                    "--stream", str(stream), "--out", str(live)]
    # feed the two early lines, then park (SIGKILL fodder)
    prod = subprocess.Popen(producer_cmd + ["--lines", "2"])
    run = subprocess.Popen(
        _mh_cmd(run_dir, final, live),
        env=_mh_env(GRAFT_MH_PEER_TIMEOUT_S="8", GRAFT_MH_ABORT_GRACE_S="4",
                    GRAFT_MH_BEAT_INTERVAL_S="0.5"),
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        # wait for the run to notice the silence and journal the stall
        stall = None
        deadline = time.monotonic() + 240
        while stall is None and time.monotonic() < deadline:
            assert run.poll() is None, run.communicate()[0]
            if health.exists():
                for ln in health.read_text().splitlines():
                    try:
                        d = json.loads(ln)
                    except json.JSONDecodeError:
                        continue
                    if d.get("kind") == "ingest_stalled":
                        stall = d
                        break
            time.sleep(0.05)
        assert stall is not None, "run never journaled ingest_stalled"
        prod.kill()                     # SIGKILL the parked producer
        prod.wait(timeout=30)
        # restart the producer exactly as the COASTING banner instructs
        prod2 = subprocess.run(
            producer_cmd + ["--from-offset", str(stall["offset"])],
            timeout=60)
        assert prod2.returncode == 0
        out, _ = run.communicate(timeout=420)
        assert run.returncode == 0, out
    finally:
        for p in (prod, run):
            if p.poll() is None:
                p.kill()

    notes = [json.loads(ln) for ln in health.read_text().splitlines()
             if ln.strip()]
    kinds = [n.get("kind") for n in notes]
    assert "ingest_stalled" in kinds and "ingest_resumed" in kinds
    assert stall["resume_cmd"].endswith(
        f"--out {live} --from-offset {stall['offset']}")
    # the run COASTED: at least one ingest marker flagged the mode
    ing = [n for n in notes if n.get("kind") == "ingest"]
    assert any(n["coasting"] for n in ing)
    assert not ing[-1]["coasting"] and ing[-1]["shed_total"] == 0
    _assert_dump_equals(final, mh_reference)


@pytest.mark.slow
def test_mh_rank_sigkill_relaunch_ingest_exactly_once(tmp_path,
                                                      mh_reference):
    """ISSUE 19 rank-SIGKILL leg: rank 1 of the 2-process run SIGKILLs
    itself (GRAFT_CHAOS) mid-stream; the group supervisor relaunches and
    the resumed rank 0 seeks its queue to the checkpoint's stamped
    ``stream_offset`` — the early directives (consumed before the kill)
    apply exactly once, and the final state is bit-exact vs the
    uninterrupted ingestion of the same stream."""
    run_dir = tmp_path / "mh"
    run_dir.mkdir()
    final = tmp_path / "final.npz"
    stream = tmp_path / "full.ndjsonl"
    write_stream(str(stream), MH_STREAM)

    proc = subprocess.run(
        _mh_cmd(run_dir, final, stream, stall="30", coast="0.05",
                procs="2,2"),
        env=_mh_env(GRAFT_CHAOS="kill@1:8", GRAFT_MH_PEER_TIMEOUT_S="6",
                    GRAFT_MH_ABORT_GRACE_S="3",
                    GRAFT_MH_BEAT_INTERVAL_S="0.5"),
        cwd=REPO, capture_output=True, text=True, timeout=560)
    journal = [json.loads(ln)
               for ln in (run_dir / "mh_journal.jsonl").read_text()
               .splitlines()]
    assert proc.returncode == 0, (proc.stdout, proc.stderr, journal)
    # the relaunch really happened
    assert any(r["kind"] == "mh_failure" for r in journal)
    assert len([r for r in journal if r["kind"] == "mh_attempt"]) >= 2
    # the surviving checkpoint sidecar carries the ingestion cursor
    from go_libp2p_pubsub_tpu.sim import checkpoint
    ck = run_dir / "ckpt"
    stamped = [checkpoint.sidecar_meta(str(ck / p)[:-len(".npz")])
               .get("stream_offset")
               for p in os.listdir(ck) if p.endswith(".npz")]
    assert stamped and all(s is not None for s in stamped)
    # exactly-once across the group relaunch: bit-exact final state
    _assert_dump_equals(final, mh_reference)
