"""Multi-chip execution tests: the peer-sharded step on a real 8-device mesh.

conftest.py provisions 8 virtual CPU devices; these tests actually EXECUTE
``make_sharded_step`` over a ``jax.sharding.Mesh`` of all of them and assert
the sharded trajectory equals the single-device one. This is the TPU-native
replacement for the reference's per-peer comm layer (comm.go:44-191) — peers
shard across devices, cross-shard mesh edges ride XLA collectives
(SURVEY.md §2.3, §5.7-8).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.parallel.sharding import (
    make_mesh, make_sharded_step, shard_state)
from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology
from go_libp2p_pubsub_tpu.sim.engine import step_jit


def _build(n_peers=64, k_slots=8, n_topics=2, msg_window=32):
    cfg = SimConfig(
        n_peers=n_peers, k_slots=k_slots, n_topics=n_topics,
        msg_window=msg_window, publishers_per_tick=2, prop_substeps=4,
        scoring_enabled=True, behaviour_penalty_weight=-1.0,
        gossip_threshold=-10.0, publish_threshold=-20.0,
        graylist_threshold=-30.0)
    tp = TopicParams.disabled(n_topics)
    topo = topology.sparse(n_peers, k_slots, degree=4, seed=7)
    st = init_state(cfg, topo)
    return cfg, tp, st


@pytest.fixture(scope="module")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices (conftest XLA_FLAGS)")
    return devs[:8]


def test_sharded_step_matches_unsharded(eight_devices):
    """Trajectory equality: 5 sharded ticks == 5 single-device ticks."""
    cfg, tp, st = _build()
    mesh = make_mesh(eight_devices)
    sharded_step = make_sharded_step(mesh, cfg, tp)

    st_sh = shard_state(st, mesh, cfg)
    st_un = st
    key = jax.random.PRNGKey(42)
    for i in range(5):
        key, k = jax.random.split(key)
        st_sh = sharded_step(st_sh, k)
        st_un = step_jit(st_un, cfg, tp, k)

    for name, a, b in zip(st_un._fields, st_un, st_sh):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5,
            err_msg=f"field {name} diverged between sharded and unsharded")


def test_state_actually_sharded(eight_devices):
    """Peer-major arrays are split across devices, not replicated."""
    cfg, tp, st = _build()
    mesh = make_mesh(eight_devices)
    st_sh = shard_state(st, mesh, cfg)
    shards = st_sh.mesh.addressable_shards
    assert len(shards) == 8
    per_dev = cfg.n_peers // 8
    assert shards[0].data.shape[0] == per_dev
    assert {s.device for s in shards} == set(eight_devices)


def test_sharded_run_executes_collectives(eight_devices):
    """The sharded step compiles to a program with cross-device comms (the
    neighbor gathers span shards) and still advances state."""
    cfg, tp, st = _build()
    mesh = make_mesh(eight_devices)
    sharded_step = make_sharded_step(mesh, cfg, tp)
    st_sh = shard_state(st, mesh, cfg)
    hlo = sharded_step.lower(st_sh, jax.random.PRNGKey(0)).compile().as_text()
    assert any(op in hlo for op in
               ("all-gather", "collective-permute", "all-to-all")), \
        "sharded step compiled without any cross-device collectives"
    out = sharded_step(st_sh, jax.random.PRNGKey(0))
    assert int(out.tick) == 1
    # degrees stay within capacity
    assert int(jnp.max(jnp.sum(out.mesh, -1))) <= cfg.k_slots


def test_2d_dcn_mesh_matches_unsharded(eight_devices):
    """Multi-host layout: a (2 hosts x 4 chips) mesh with the peer axis
    sharded over both axes (hosts-major) must produce the same trajectory
    as single-device execution — the DCN axis only changes WHERE shards
    live, never what they compute."""
    from go_libp2p_pubsub_tpu.parallel.sharding import make_mesh_2d

    cfg, tp, st = _build()
    mesh = make_mesh_2d(2, eight_devices)
    assert mesh.axis_names == ("dcn", "peers")
    sharded_step = make_sharded_step(mesh, cfg, tp)

    st_sh = shard_state(st, mesh, cfg)
    st_un = st
    key = jax.random.PRNGKey(43)
    for _ in range(3):
        key, k = jax.random.split(key)
        st_sh = sharded_step(st_sh, k)
        st_un = step_jit(st_un, cfg, tp, k)

    for name, a, b in zip(st_un._fields, st_un, st_sh):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5,
            err_msg=f"field {name} diverged on the 2-D mesh")
    # the mesh state is genuinely split 8 ways across both axes
    shards = st_sh.mesh.sharding
    assert shards.num_devices == 8


def test_sharded_pallas_kernels_match_unsharded(eight_devices):
    """The shard_map-wrapped Pallas kernels (fused hop / IWANT-resolve /
    gossip-emit + the two VMEM table gathers) produce the same trajectory
    sharded over 8 devices as the unsharded dispatch — proving the
    kernel_context specs (tables replicated, receiver rows local) preserve
    semantics. Runs in interpret mode on the CPU mesh; on TPU the same
    dispatch path compiles the kernels natively per shard."""
    import dataclasses

    cfg, tp, st = _build()
    cfg = dataclasses.replace(cfg, hop_mode="pallas",
                              edge_gather_mode="pallas")
    mesh = make_mesh(eight_devices)
    sharded_step = make_sharded_step(mesh, cfg, tp)

    st_sh = shard_state(st, mesh, cfg)
    st_un = st
    key = jax.random.PRNGKey(42)
    for i in range(4):
        key, k = jax.random.split(key)
        st_sh = sharded_step(st_sh, k)
        st_un = step_jit(st_un, cfg, tp, k)

    for name, a, b in zip(st_un._fields, st_un, st_sh):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"field {name} diverged between sharded and unsharded "
                    "pallas dispatch")


def test_sharded_sort_mode_matches_unsharded(eight_devices):
    """The sort-permute gathers (TPU auto's formulation of record) under
    the peer-sharded pjit step: a global lax.sort over a sharded flat edge
    axis must still route every payload identically. This is the path a
    real multi-chip TPU run takes after round 4's auto-mode flip."""
    import dataclasses

    cfg, tp, st = _build()
    cfg = dataclasses.replace(cfg, edge_gather_mode="sort")
    mesh = make_mesh(eight_devices)
    sharded_step = make_sharded_step(mesh, cfg, tp)

    st_sh = shard_state(st, mesh, cfg)
    st_un = st
    key = jax.random.PRNGKey(17)
    for i in range(4):
        key, k = jax.random.split(key)
        st_sh = sharded_step(st_sh, k)
        st_un = step_jit(st_un, cfg, tp, k)

    for name, a, b in zip(st_un._fields, st_un, st_sh):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"field {name} diverged under sharded sort mode")


def test_sharded_halo_route_matches_unsharded(eight_devices):
    """sharded_route='halo' (parallel/halo.py): per-shard sorts + one
    all_to_all of capacity-padded buckets replace the replicated global
    sorts. Must be bit-exact vs the unsharded sort-mode trajectory —
    this also proves the capacity factor holds and invalid slots merge
    back via the local-identity path."""
    import dataclasses

    cfg, tp, st = _build()
    cfg_sort = dataclasses.replace(cfg, edge_gather_mode="sort")
    cfg_halo = dataclasses.replace(cfg_sort, sharded_route="halo")
    mesh = make_mesh(eight_devices)
    sharded_step = make_sharded_step(mesh, cfg_halo, tp)

    st_sh = shard_state(st, mesh, cfg_halo)
    st_un = st
    key = jax.random.PRNGKey(23)
    for i in range(4):
        key, k = jax.random.split(key)
        st_sh = sharded_step(st_sh, k)
        st_un = step_jit(st_un, cfg_sort, tp, k)

    for name, a, b in zip(st_un._fields, st_un, st_sh):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"field {name} diverged under halo routing")


def test_sharded_halo_2d_mesh_and_multigroup():
    """Halo routing on the (dcn, peers) 2-D mesh with a multi-topic config
    whose packed exchange spans >32 bit-planes (two payload groups riding
    one halo) — the all_to_all and axis_index over the combined axis
    tuple must linearize consistently with the hosts-major peer layout.

    Runs in a FRESH subprocess: executing a sort-mode sharded step on a
    1-D mesh earlier in the same process poisons the later 2-D all_to_all
    at the backend level ("supplied 41 buffers but compiled program
    expected 60" — it survives jax.clear_caches(), so it is backend
    runtime state, not the jit cache). Real deployments build one mesh
    per process (the driver dryrun does too), so process isolation is
    also the honest shape of the check."""
    import os
    import subprocess
    import sys

    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env

    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import dataclasses
import numpy as np
from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology
from go_libp2p_pubsub_tpu.sim.engine import step_jit
from go_libp2p_pubsub_tpu.parallel.sharding import (
    make_mesh_2d, make_sharded_step, shard_state)

cfg = SimConfig(n_peers=64, k_slots=8, n_topics=12, msg_window=32,
                publishers_per_tick=2, prop_substeps=4, scoring_enabled=True,
                behaviour_penalty_weight=-1.0, gossip_threshold=-10.0,
                publish_threshold=-20.0, graylist_threshold=-30.0)
cfg_sort = dataclasses.replace(cfg, edge_gather_mode="sort")
cfg_halo = dataclasses.replace(cfg_sort, sharded_route="halo")
tp = TopicParams.disabled(12)
st = init_state(cfg, topology.sparse(64, 8, degree=4, seed=7))
mesh = make_mesh_2d(2, jax.devices()[:8])
sharded_step = make_sharded_step(mesh, cfg_halo, tp)
st_sh = shard_state(st, mesh, cfg_halo)
st_un = st
key = jax.random.PRNGKey(29)
for i in range(3):
    key, k = jax.random.split(key)
    st_sh = sharded_step(st_sh, k)
    st_un = step_jit(st_un, cfg_sort, tp, k)
for name, a, b in zip(st_un._fields, st_un, st_sh):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
print("HALO2D_OK")
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = cpu_mesh_env(dict(os.environ), 8)
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=420,
                         cwd=repo)
    assert "HALO2D_OK" in res.stdout, res.stderr[-2000:]


def test_lower_then_call_same_instance(eight_devices):
    """Regression for the round-4 AOT/dispatch disagreement: calling
    .lower().compile() and then dispatching the SAME sharded step used to
    fail with 'compiled for 60 inputs but called with 41' because closure
    arrays (tp) became hoisted constants. tp now rides as a traced
    argument, so both paths agree."""
    cfg, tp, st = _build()
    mesh = make_mesh(eight_devices)
    stp = make_sharded_step(mesh, cfg, tp)
    st_sh = shard_state(st, mesh, cfg)
    txt = stp.lower(st_sh, jax.random.PRNGKey(0)).compile().as_text()
    assert txt                                   # AOT path works...
    out = stp(st_sh, jax.random.PRNGKey(0))      # ...and dispatch after it
    assert int(out.tick) == 1


def test_halo_mixed_dtype_payloads_bit_exact():
    """route_payloads_halo's by_dtype branch: payloads of MIXED dtypes
    (f32 + u32 + i32) stack into one all_to_all per dtype and must land
    bit-exact against the direct unsharded permutation at a ragged N
    (96 = 12 rows/shard, nothing 128-friendly). Valid slots route the
    involution value; invalid slots keep their local identity — the same
    contract the sort formulation pins. Runs in a FRESH subprocess (the
    second mesh in one process hits the backend multi-mesh poison the 2-D
    test documents)."""
    import os
    import subprocess
    import sys

    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env

    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from go_libp2p_pubsub_tpu.sim import topology
from go_libp2p_pubsub_tpu.parallel.kernel_context import kernel_mesh
from go_libp2p_pubsub_tpu.parallel.halo import route_payloads_halo
from go_libp2p_pubsub_tpu.parallel.sharding import make_mesh

n, k = 96, 8
topo = topology.sparse(n, k, degree=4, seed=13)
nbr, rks = topo.neighbors, topo.reverse_slot
rng = np.random.default_rng(5)
payloads = [rng.random((n, k)).astype(np.float32),
            rng.integers(0, 2**32, (n, k), dtype=np.uint32),
            rng.random((n, k)).astype(np.float32),
            rng.integers(-2**31, 2**31, (n, k)).astype(np.int32)]
valid = (nbr >= 0) & (rks >= 0)
jn = np.clip(nbr, 0, n - 1)
rk = np.clip(rks, 0, k - 1)
expect = [np.where(valid, p[jn, rk], p) for p in payloads]

mesh = make_mesh(jax.devices()[:8])
fn = jax.jit(lambda *ps: tuple(route_payloads_halo(
    list(ps), jnp.asarray(nbr), jnp.asarray(rks))))
with kernel_mesh(mesh, ("peers",), route="halo", capacity_factor=4):
    got = fn(*[jnp.asarray(p) for p in payloads])
for i, (e, g) in enumerate(zip(expect, got)):
    np.testing.assert_array_equal(e, np.asarray(g), err_msg=f"payload {i}")
print("MIXED_DTYPE_OK")
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = cpu_mesh_env(dict(os.environ), 8)
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=420,
                         cwd=repo)
    assert "MIXED_DTYPE_OK" in res.stdout, res.stderr[-2000:]


def test_halo_capacity_rule_on_bench_underlays():
    """The CAPACITY RULE (parallel/halo.py): required_capacity_factor — the
    exact worst bucket of an underlay over the uniform mean — must sit
    under the default factor 4 on the underlays the benchmarks actually
    route (sparse random at the bench degrees, incl. the beacon config's
    degree-16 underlay), on both the 8-way and 2x4 peer shardings."""
    from go_libp2p_pubsub_tpu.parallel.halo import required_capacity_factor

    worst = 0.0
    for n, k, degree, seed in [(1024, 32, 12, 42), (2048, 48, 16, 42),
                               (1024, 16, 6, 7), (512, 16, 10, 9)]:
        topo = topology.sparse(n, k, degree=degree, seed=seed)
        for d in (4, 8):
            f = required_capacity_factor(topo.neighbors, topo.reverse_slot, d)
            worst = max(worst, f)
            assert f <= 4.0, (n, k, degree, d, f)
    # headroom documented in halo.py: random underlays measure ~<=1.3x
    assert worst <= 2.0, f"random underlays drifted to {worst}x the mean"


def test_halo_overflow_counter_fires_on_starved_capacity():
    """Overflow surfacing (VERDICT r4 weak #5): with the capacity factor
    forced to 1 the bucket tails overflow — SimState.halo_overflow must
    count it (and the keys poison, per the documented semantics). The
    clean-run half of the contract is carried by
    test_sharded_halo_route_matches_unsharded: its field-by-field equality
    vs the unsharded trajectory includes halo_overflow == 0 at the default
    factor. Runs in a FRESH subprocess (the second mesh in one process
    hits the backend multi-mesh poison the 2-D test documents)."""
    import os
    import subprocess
    import sys

    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env

    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology
from go_libp2p_pubsub_tpu.parallel.sharding import (
    make_mesh, make_sharded_step, shard_state)

cfg = SimConfig(n_peers=64, k_slots=8, n_topics=2, msg_window=32,
                publishers_per_tick=2, prop_substeps=4, scoring_enabled=True,
                behaviour_penalty_weight=-1.0, gossip_threshold=-10.0,
                publish_threshold=-20.0, graylist_threshold=-30.0,
                edge_gather_mode="sort", sharded_route="halo",
                halo_capacity_factor=1)
tp = TopicParams.disabled(2)
st = init_state(cfg, topology.sparse(64, 8, degree=4, seed=7))
mesh = make_mesh(jax.devices()[:8])
sharded = make_sharded_step(mesh, cfg, tp)
s = shard_state(st, mesh, cfg)
key = jax.random.PRNGKey(31)
for _ in range(3):
    key, k = jax.random.split(key)
    s = sharded(s, k)
ovf = int(np.asarray(s.halo_overflow))
assert ovf > 0, f"capacity factor 1 must overflow some bucket: {ovf}"
print(f"OVERFLOW_OK {ovf}")
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = cpu_mesh_env(dict(os.environ), 8)
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=420,
                         cwd=repo)
    assert "OVERFLOW_OK" in res.stdout, res.stderr[-2000:]


def test_required_bucket_capacity_is_the_exact_worst_bucket():
    """Degree-aware halo pricing (ISSUE 13): required_bucket_capacity is
    the exact worst (src,dst)-device bucket population — cross-checked
    against a brute-force count, bounded above by the factor rule's
    allocation, and refusing ragged shardings by name."""
    from go_libp2p_pubsub_tpu.parallel.halo import (
        required_bucket_capacity, required_capacity_factor)

    for n, k, degree, seed in [(96, 16, 6, 3), (256, 16, 6, 11)]:
        topo = topology.sparse(n, k, degree=degree, seed=seed)
        nbr, rks = np.asarray(topo.neighbors), np.asarray(topo.reverse_slot)
        for d in (4, 8):
            nl = n // d
            brute = 0
            for sd in range(d):
                rows = slice(sd * nl, (sd + 1) * nl)
                v = (nbr[rows] >= 0) & (rks[rows] >= 0)
                dest = nbr[rows][v] // nl
                brute = max(brute, int(np.bincount(dest, minlength=d).max()))
            got = required_bucket_capacity(nbr, rks, d)
            assert got == brute, (n, d, got, brute)
            # the factor rule's allocation always covers the exact price
            f = required_capacity_factor(nbr, rks, d)
            assert got <= f * (-(-nl * k // d)), (n, d)
    with pytest.raises(ValueError, match="divide evenly"):
        required_bucket_capacity(nbr[:100], rks[:100], 8)


def test_halo_exact_bucket_capacity_trajectory_and_starved_control():
    """SimConfig.halo_bucket_capacity end to end (config -> compile plan
    -> kernel context -> halo route): priced at EXACTLY the underlay's
    required_bucket_capacity the sharded trajectory is bit-exact vs the
    unsharded step with zero overflow; priced one below, some bucket
    must overflow (the degree histogram's answer is tight, not padded).
    Fresh subprocess: the second mesh in one process hits the backend
    multi-mesh poison the 2-D test documents."""
    import os
    import subprocess
    import sys

    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env

    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import dataclasses
import numpy as np
from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology
from go_libp2p_pubsub_tpu.sim.engine import step_jit
from go_libp2p_pubsub_tpu.parallel.halo import required_bucket_capacity
from go_libp2p_pubsub_tpu.parallel.sharding import (
    make_mesh, make_sharded_step, shard_state)

topo = topology.sparse(64, 8, degree=4, seed=7)
need = required_bucket_capacity(topo.neighbors, topo.reverse_slot, 8)
assert need > 0
cfg = SimConfig(n_peers=64, k_slots=8, n_topics=2, msg_window=32,
                publishers_per_tick=2, prop_substeps=4, scoring_enabled=True,
                behaviour_penalty_weight=-1.0, gossip_threshold=-10.0,
                publish_threshold=-20.0, graylist_threshold=-30.0,
                edge_gather_mode="sort", sharded_route="halo",
                halo_bucket_capacity=need)
tp = TopicParams.disabled(2)
st = init_state(cfg, topo)
mesh = make_mesh(jax.devices()[:8])
sharded = make_sharded_step(mesh, cfg, tp)
s = shard_state(st, mesh, cfg)
un = st
key = jax.random.PRNGKey(31)
for _ in range(3):
    key, k = jax.random.split(key)
    s = sharded(s, k)
    un = step_jit(un, cfg, tp, k)
for f in un._fields:
    np.testing.assert_array_equal(np.asarray(getattr(un, f)),
                                  np.asarray(getattr(s, f)), err_msg=f)
assert int(np.asarray(s.halo_overflow)) == 0

# starved control: one below the exact price must overflow somewhere
cfg1 = dataclasses.replace(cfg, halo_bucket_capacity=need - 1)
sharded1 = make_sharded_step(mesh, cfg1, tp)
s1 = shard_state(st, mesh, cfg1)
key = jax.random.PRNGKey(31)
for _ in range(3):
    key, k = jax.random.split(key)
    s1 = sharded1(s1, k)
ovf = int(np.asarray(s1.halo_overflow))
assert ovf > 0, f"capacity need-1 must overflow: {ovf}"
print(f"EXACT_CAP_OK {need} {ovf}")
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = cpu_mesh_env(dict(os.environ), 8)
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=420,
                         cwd=repo)
    assert "EXACT_CAP_OK" in res.stdout, res.stderr[-2000:]
