"""Unit tests for the shared wedge-proofing helpers (utils/platform_probe)
and the slow-heartbeat warning (gossipsub.go:1346-1354 parity)."""

import logging

from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
from go_libp2p_pubsub_tpu.core.params import GossipSubParams
from go_libp2p_pubsub_tpu.net import Network
from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
from go_libp2p_pubsub_tpu.utils.platform_probe import (
    cpu_mesh_env,
    forced_cpu_device_count,
)


class TestCpuMeshEnv:
    def test_forces_cpu_and_disables_plugin(self):
        env = cpu_mesh_env({"XLA_FLAGS": "--foo", "OTHER": "1"})
        assert env["JAX_PLATFORMS"] == "cpu"
        assert env["PALLAS_AXON_POOL_IPS"] == ""
        assert env["OTHER"] == "1"
        assert env["XLA_FLAGS"] == "--foo"      # no device count requested

    def test_device_count_appended(self):
        env = cpu_mesh_env({"XLA_FLAGS": "--foo"}, 8)
        assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
        assert "--foo" in env["XLA_FLAGS"]

    def test_does_not_mutate_input(self):
        src = {"XLA_FLAGS": "--foo"}
        cpu_mesh_env(src, 4)
        assert src == {"XLA_FLAGS": "--foo"}


class TestForcedCpuDeviceCount:
    def test_default_is_one(self):
        assert forced_cpu_device_count({}) == 1
        assert forced_cpu_device_count({"XLA_FLAGS": "--other"}) == 1

    def test_parses_flag(self):
        env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
        assert forced_cpu_device_count(env) == 8

    def test_last_flag_wins(self):
        env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=4 "
                            "--xla_force_host_platform_device_count=16"}
        assert forced_cpu_device_count(env) == 16


class TestCollectiveInventory:
    def test_parses_ops_and_tuple_payloads(self):
        from __graft_entry__ import _collective_inventory
        hlo = "\n".join([
            "  %ag.1 = f32[16,8]{1,0} all-gather(%x), replica_groups={{0,1}}",
            "  %ar = (f32[16]{0}, f32[1024]{0}) all-reduce(%a, %b), "
            "replica_groups={{0,1,2,3}}, to_apply=%sum",
            "  %cp = u32[64]{0} collective-permute(%y), "
            "source_target_pairs={{0,1},{1,0}}",
            "  %notacollective = f32[4]{0} add(%p, %q)",
        ])
        out = _collective_inventory(hlo)
        assert "all-gather x1" in out and "all-reduce x1" in out
        assert "collective-permute x1" in out
        # 16*8*4 + (16+1024)*4 + 64*4 = 4928 bytes = 4.8 KiB
        assert "4.8 KiB" in out

    def test_empty(self):
        from __graft_entry__ import _collective_inventory
        assert "none" in _collective_inventory("%add = f32[2]{0} add(%a,%b)")


class TestSlowHeartbeatWarning:
    def _net(self, warning_ratio):
        net = Network()
        params = GossipSubParams(slow_heartbeat_warning=warning_ratio)
        nodes = [PubSub(net.add_host(), GossipSubRouter(params=params),
                        sign_policy=LAX_NO_SIGN) for _ in range(4)]
        net.dense_connect([x.host for x in nodes], degree=3)
        net.scheduler.run_for(0.1)
        for x in nodes:
            x.join("t").subscribe()
        return net

    def test_warns_when_heartbeat_slow(self, caplog):
        # ratio so small that ANY wall-clock heartbeat exceeds it
        net = self._net(1e-12)
        with caplog.at_level(logging.WARNING,
                             logger="go_libp2p_pubsub_tpu.routers.gossipsub"):
            net.scheduler.run_until(2.5)
        assert any("slow heartbeat" in r.message for r in caplog.records)

    def test_silent_when_disabled(self, caplog):
        net = self._net(0.0)
        with caplog.at_level(logging.WARNING,
                             logger="go_libp2p_pubsub_tpu.routers.gossipsub"):
            net.scheduler.run_until(2.5)
        assert not any("slow heartbeat" in r.message for r in caplog.records)
