"""Live contract verdict plane (sim/adversary.py monitors +
sim/supervisor.py supervised verdict response, ISSUE 20).

Layers under test, cheapest first: per-kind streaming monitors proven
bit-exact against the batch evaluators at EVERY row prefix (the
pending→fail final settlement included), the contract_from_json fuzz
(200 adversarial specs all refused BY NAME), the checkpoint-sidecar
state token round-trip (a mid-stream save/restore continues the verdict
stream identically; a contract-set mismatch refuses by name), the
in-process supervised policy legs (journal / snapshot / abort — never a
silent continue), the engineered kill→resume duplicate (the raw journal
carries the re-derived note twice, the DEDUPED stream exactly once),
the dashboard's journal-first render (never re-evaluating O(rows) once
verdicts exist) — capped by THE acceptance leg: a real 2-process CPU
run fed a composed eclipse+censor stream, rank 0 SIGKILLed between a
breach and its journaled verdict, relaunched off the sidecar monitor
state, finishing with the verdict note stream identical to the
uninterrupted run (each verdict exactly once, state bit-exact).
"""

import json
import os
import random
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from go_libp2p_pubsub_tpu.sim import adversary  # noqa: E402
from go_libp2p_pubsub_tpu.sim.adversary import (  # noqa: E402
    ContractMonitors, DeliveryFloor, RecoveryCeiling, ScoreResponse,
    contract_from_json, monitor_for)

pytestmark = pytest.mark.verdicts


def _rows(deliv, att_edges=0, att_gray=0, hon_gray=0, conn=100, t0=0):
    return [{"tick": t0 + i, "member": -1, "delivery_frac_t0": d,
             "attacker_edges": att_edges, "attacker_graylisted": g,
             "honest_graylisted": hon_gray, "connected_edges": conn}
            for i, (d, g) in enumerate(
                zip(deliv, att_gray if isinstance(att_gray, list)
                    else [att_gray] * len(deliv)))]


# ---------------------------------------------------------------------------
# per-kind monitors: bit-exact vs the batch evaluators at every prefix


PARITY_CONTRACTS = [
    DeliveryFloor(floor=0.7),
    DeliveryFloor(floor=0.7, start=2, end=5),
    DeliveryFloor(floor=0.5, topic=0),
    RecoveryCeiling(after=2, within=3, floor=0.8),
    RecoveryCeiling(after=1, within=2, floor=0.99),
    ScoreResponse(by=3),
    ScoreResponse(by=2, attacker_frac=0.0),
    ScoreResponse(by=1, honest_max_frac=0.0, start=1),
]

PARITY_STREAMS = {
    "recovers": _rows([0.9, 0.6, 0.5, 0.7, 0.95, 0.99], att_edges=10,
                      att_gray=[0, 0, 2, 5, 6, 8]),
    "degrades": _rows([0.95, 0.9, 0.65, 0.6, 0.55, 0.5]),
    "honest_collateral": _rows([0.9] * 6, att_edges=10, att_gray=9,
                               hon_gray=30, conn=100),
    "short": _rows([0.8]),
    "late_window": _rows([0.9, 0.9], t0=10),
}


class TestMonitorParity:
    def test_prefix_parity_bit_exact(self):
        """Every monitor kind equals its batch evaluator at EVERY row
        prefix — status, detail string AND measured dict, under both
        mid-stream and final semantics (the pending→fail settlement of a
        too-short stream included)."""
        for c in PARITY_CONTRACTS:
            for sname, rows in PARITY_STREAMS.items():
                mon = monitor_for(c)
                for n in range(len(rows) + 1):
                    if n:
                        mon.fold(rows[n - 1])
                    for final in (False, True):
                        want = c.evaluate(rows[:n], final=final)
                        got = mon.result(final=final)
                        assert (got.status, got.detail, got.measured) \
                            == (want.status, want.detail, want.measured), \
                            (c, sname, n, final)
                        assert mon.status(final=final) == want.status

    def test_transition_events_deterministic(self):
        """The event stream is a pure function of the rows: re-folding
        from scratch re-derives byte-identical ids (the exactly-once
        dedup key), and every id encodes its own fields."""
        cs = (DeliveryFloor(floor=0.7),
              RecoveryCeiling(after=2, within=3, floor=0.8),
              ScoreResponse(by=3))
        rows = PARITY_STREAMS["recovers"]
        m = ContractMonitors(cs)
        evs = m.fold_rows(rows) + m.finalize()
        ids = [e["id"] for e in evs]
        assert ids and len(ids) == len(set(ids))
        for e in evs:
            assert e["id"] == (f"c{e['contract']}.s{e['seq']}"
                               f".{e['status']}@{e['tick']}")
        m2 = ContractMonitors(cs)
        assert [e["id"] for e in m2.fold_rows(rows) + m2.finalize()] == ids
        # finalize is idempotent: a relaunch that re-finalizes re-derives
        # nothing new once the statuses already settled
        assert m2.finalize() == []

    def test_state_token_roundtrip_mid_stream(self):
        """Serialize mid-stream, restore, continue folding: the restored
        monitors emit the same events and land on the same results as
        the uninterrupted fold — and the state is JSON/sidecar-safe."""
        cs = (DeliveryFloor(floor=0.7),
              RecoveryCeiling(after=2, within=3, floor=0.8),
              ScoreResponse(by=3))
        rows = PARITY_STREAMS["recovers"]
        a = ContractMonitors(cs)
        a.fold_rows(rows[:3])
        tok = a.state_token()
        assert not set(tok) & set(" \t\n")      # sidecar-safe: no spaces
        json.dumps(a.to_state())                # JSON-serializable state
        b = ContractMonitors.from_token(tok, cs)
        assert b.statuses == a.statuses and b.seqs == a.seqs
        ea = a.fold_rows(rows[3:]) + a.finalize()
        eb = b.fold_rows(rows[3:]) + b.finalize()
        assert ea == eb
        assert [r.status for r in a.results(final=True)] \
            == [r.status for r in b.results(final=True)]

    def test_contract_set_mismatch_refused(self):
        a = ContractMonitors((DeliveryFloor(floor=0.5),))
        tok = a.state_token()
        with pytest.raises(ValueError,
                           match="refusing a silent verdict reset"):
            ContractMonitors.from_token(tok, (DeliveryFloor(floor=0.6),))


# ---------------------------------------------------------------------------
# contract_from_json fuzz: adversarial specs all refused BY NAME


class TestContractJsonFuzz:
    BASES = {
        "delivery_floor": {"kind": "delivery_floor", "floor": 0.5},
        "recovery_ceiling": {"kind": "recovery_ceiling", "after": 3,
                             "within": 5},
        "score_response": {"kind": "score_response", "by": 4},
    }
    FIELDS = {
        "delivery_floor": ["floor", "start", "end", "topic"],
        "recovery_ceiling": ["after", "within", "floor", "topic"],
        "score_response": ["by", "attacker_frac", "honest_max_frac",
                           "start"],
    }
    NON_NULLABLE = {
        "delivery_floor": ["floor", "start"],
        "recovery_ceiling": ["after", "within", "floor"],
        "score_response": ["by", "attacker_frac", "honest_max_frac",
                           "start"],
    }
    OUT_OF_RANGE = {
        "delivery_floor": [("floor", 1.5), ("floor", -0.25),
                           ("start", -1), ("end", -3), ("start", 2.5)],
        "recovery_ceiling": [("after", -1), ("within", 0),
                             ("floor", 2.0), ("after", 2.5)],
        "score_response": [("by", -5), ("attacker_frac", 1.01),
                           ("honest_max_frac", -0.5), ("start", -2),
                           ("by", 3.5)],
    }

    def test_bases_parse(self):
        for b in self.BASES.values():
            assert contract_from_json(dict(b)).kind == b["kind"]

    def test_fuzz_200_adversarial_specs_refused_by_name(self):
        """200 seeded adversarial specs (bad kinds, unknown fields,
        wrong types incl. bools, nulls on non-nullable fields, range
        violations, non-dict specs, empty census windows): every single
        one raises ValueError with a non-empty named message — never a
        crash, never a silent default."""
        rng = random.Random(20)
        refused = 0
        while refused < 200:
            kind = rng.choice(list(self.BASES))
            d = dict(self.BASES[kind])
            mode = rng.randrange(6)
            if mode == 0:       # unknown / malformed kind
                d["kind"] = rng.choice(
                    [None, 7, True, "", "delivery", "eclipse",
                     "DELIVERY_FLOOR", ["delivery_floor"]])
                spec = d
            elif mode == 1:     # unknown field
                d[rng.choice(["florr", "peers", "tick", "Kind",
                              "stop", "window"])] = rng.choice([0, "x"])
                spec = d
            elif mode == 2:     # wrong type (bools excluded from ints)
                d[rng.choice(self.FIELDS[kind])] = rng.choice(
                    ["x", [], {}, True, False, [1]])
                spec = d
            elif mode == 3:     # null on a non-nullable field
                d[rng.choice(self.NON_NULLABLE[kind])] = None
                spec = d
            elif mode == 4:     # out of range / float where int required
                f, v = rng.choice(self.OUT_OF_RANGE[kind])
                d[f] = v
                spec = d
            else:               # not a JSON object at all / empty window
                spec = rng.choice(
                    [None, 7, "spec", ["kind"], [dict(d)], True,
                     {"kind": "delivery_floor", "floor": 0.5,
                      "start": 5, "end": 5},
                     {"kind": "delivery_floor", "floor": 0.5,
                      "start": 9, "end": 2}])
            with pytest.raises(ValueError) as ei:
                contract_from_json(spec)
            assert str(ei.value), spec
            refused += 1


# ---------------------------------------------------------------------------
# supervised verdict response: fold at every chunk confirm, journaled
# notes, policy on FAIL — never a silent continue
#
# Shapes mirror tests/test_commands.py exactly (the tier-1 suite runs
# that module first, so every compile here is a jit-cache hit), and the
# contracts are chosen to be deterministic INDEPENDENT of simulated
# delivery values: with no attackers ScoreResponse(by=0) fails at tick
# 0, DeliveryFloor(floor=0.0) passes at tick 0, and a 12-tick run can
# never satisfy RecoveryCeiling(after=20) — the pending→fail final leg.


CHUNK, TICKS = 3, 12

C_FAIL = ScoreResponse(by=0)
C_PASS = DeliveryFloor(floor=0.0)
C_PEND = RecoveryCeiling(after=20, within=5)


@pytest.fixture(scope="module")
def small():
    import jax

    from go_libp2p_pubsub_tpu.sim import scenarios
    cfg, tp, state = scenarios.single_topic_1k(n_peers=128, k_slots=16,
                                               degree=6)
    return cfg, tp, state, jax.random.PRNGKey(42)


def _sup(**kw):
    from go_libp2p_pubsub_tpu.sim.supervisor import SupervisorConfig
    kw.setdefault("chunk_ticks", CHUNK)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("sleep", lambda s: None)
    return SupervisorConfig(**kw)


def _notes(path, kind):
    """Notes of one kind via telemetry.read_journal — the DEDUPED
    read-side view (contract_verdict dedups by deterministic id)."""
    from go_libp2p_pubsub_tpu.sim.telemetry import read_journal
    return [n for n in read_journal(str(path))["notes"]
            if n.get("kind") == kind]


def _raw_notes(path, kind):
    """Raw journal lines of one kind — duplicates included (what a
    relaunch re-derived on top of what the killed run already wrote)."""
    out = []
    with open(path) as f:
        for ln in f:
            if not ln.strip():
                continue
            try:
                d = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if d.get("kind") == kind:
                out.append(d)
    return out


def _assert_states_equal(a, b):
    for f, x, y in zip(a._fields, a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"field {f}")


class TestSupervisedVerdicts:
    def test_journal_policy_verdicts_and_alarm(self, small, tmp_path):
        """Default policy: every transition journaled as a
        contract_verdict note (contract kind under ``contract_kind``),
        a FAIL leaves a contract_alarm note, and the run completes."""
        from go_libp2p_pubsub_tpu.sim.supervisor import supervised_run
        cfg, tp, state, key = small
        health = tmp_path / "health.jsonl"
        _out, rep = supervised_run(
            state, cfg, tp, key, TICKS,
            _sup(health_path=str(health),
                 contracts=(C_FAIL, C_PASS, C_PEND)))
        assert rep.chunks_run == TICKS // CHUNK
        verd = _notes(health, "contract_verdict")
        by_c = {}
        for v in verd:
            by_c.setdefault(v["contract"], []).append(v)
        assert [(v["status"], v["tick"]) for v in by_c[0]] == [("fail", 0)]
        assert by_c[0][0]["contract_kind"] == "score_response"
        assert by_c[0][0]["id"] == "c0.s1.fail@0"
        assert [(v["status"], v["tick"]) for v in by_c[1]] == [("pass", 0)]
        # the too-short stream settles pending→fail at the TRUE run end
        assert [(v["status"], v["final"]) for v in by_c[2]] \
            == [("fail", True)]
        alarms = _notes(health, "contract_alarm")
        assert alarms and alarms[0]["policy"] == "journal"
        assert {a["contract"] for a in alarms} == {0, 2}
        assert not _notes(health, "verdict_abort")
        # the per-event report log mirrors the journal
        evs = [e for e in rep.events if e["event"] == "contract_verdict"]
        assert sorted(e["id"] for e in evs) \
            == sorted(v["id"] for v in verd)

    def test_snapshot_policy_forces_offcadence_checkpoint(self, small,
                                                          tmp_path):
        """Policy ``snapshot``: the breach boundary checkpoints OFF the
        9-tick cadence (tick 3), and the sidecar's monitor token carries
        the post-breach verdict state."""
        from go_libp2p_pubsub_tpu.sim import checkpoint
        from go_libp2p_pubsub_tpu.sim.supervisor import supervised_run
        cfg, tp, state, key = small
        ck = tmp_path / "ck"
        _out, rep = supervised_run(
            state, cfg, tp, key, TICKS,
            _sup(health_path=str(tmp_path / "health.jsonl"),
                 contracts=(C_FAIL,), verdict_policy="snapshot",
                 checkpoint_dir=str(ck), checkpoint_every_ticks=9,
                 keep_checkpoints=8))
        assert rep.chunks_run == TICKS // CHUNK     # run continued
        names = sorted(os.listdir(ck))
        assert "ckpt_t000000003" in names           # forced at breach
        meta = checkpoint.sidecar_meta(str(ck / "ckpt_t000000003"))
        mons = ContractMonitors.from_token(meta["monitors"], (C_FAIL,))
        assert mons.statuses == ["fail"]

    def test_snapshot_policy_without_dir_leaves_named_note(self, small,
                                                           tmp_path):
        from go_libp2p_pubsub_tpu.sim.supervisor import supervised_run
        cfg, tp, state, key = small
        health = tmp_path / "health.jsonl"
        supervised_run(state, cfg, tp, key, TICKS,
                       _sup(health_path=str(health), contracts=(C_FAIL,),
                            verdict_policy="snapshot"))
        skipped = _notes(health, "contract_snapshot_skipped")
        assert skipped and skipped[0]["reason"] == "no checkpoint_dir"
        assert skipped[0]["contract_kind"] == "score_response"

    def test_abort_policy_named_teardown_and_restore(self, small,
                                                     tmp_path):
        """Policy ``abort``: the run tears down at the breach chunk
        boundary with a named note carrying the failing contract and
        breach tick — and the forced breach checkpoint restores to the
        exact boundary state."""
        import jax

        from go_libp2p_pubsub_tpu.sim import checkpoint, engine
        from go_libp2p_pubsub_tpu.sim.supervisor import (VerdictAbort,
                                                         supervised_run)
        cfg, tp, state, key = small
        health = tmp_path / "health.jsonl"
        ck = tmp_path / "ck"
        with pytest.raises(VerdictAbort,
                           match="verdict_policy='abort'") as ei:
            supervised_run(
                state, cfg, tp, key, TICKS,
                _sup(health_path=str(health), contracts=(C_FAIL, C_PASS),
                     verdict_policy="abort", checkpoint_dir=str(ck),
                     keep_checkpoints=8))
        e = ei.value.event
        assert (e["contract"], e["kind"], e["tick"]) \
            == (0, "score_response", 0)
        # the teardown note drained durably before the raise
        aborts = _notes(health, "verdict_abort")
        assert len(aborts) == 1
        assert aborts[0]["contract_kind"] == "score_response"
        assert aborts[0]["tick"] == 0 and aborts[0]["detail"]
        # the passing contract's verdict was journaled too, not eaten
        # by the teardown
        assert {v["status"] for v in _notes(health, "contract_verdict")} \
            == {"pass", "fail"}
        # the breach checkpoint restores cleanly to the boundary state
        restored = checkpoint.restore(str(ck / "ckpt_t000000003"),
                                      like=state, cfg=cfg)
        ref = engine.run_keys(state, cfg, tp,
                              jax.random.split(key, TICKS)[:CHUNK])
        _assert_states_equal(ref, restored)

    def test_bad_policy_refused_by_name(self, small, tmp_path):
        from go_libp2p_pubsub_tpu.sim.supervisor import supervised_run
        cfg, tp, state, key = small
        with pytest.raises(ValueError, match="verdict_policy"):
            supervised_run(
                state, cfg, tp, key, TICKS,
                _sup(health_path=str(tmp_path / "h.jsonl"),
                     contracts=(C_PASS,), verdict_policy="panic"))

    def test_contracts_without_telemetry_lane_refused(self, small):
        from go_libp2p_pubsub_tpu.sim.supervisor import supervised_run
        cfg, tp, state, key = small
        with pytest.raises(ValueError, match="telemetry lane"):
            supervised_run(state, cfg, tp, key, TICKS,
                           _sup(contracts=(C_PASS,)))

    def test_kill_resume_rederives_verdict_exactly_once(self, small,
                                                        tmp_path):
        """The engineered duplicate: DeliveryFloor(start=7) transitions
        at the tick-9 confirm, AFTER the tick-6 checkpoint stamped the
        pre-transition monitor state. A kill before the next chunk
        leaves the note durable but not the post-transition state — the
        resume re-derives the SAME deterministic id (raw journal holds
        it twice), the deduped read-side stream exactly once, and both
        stream and final state equal the uninterrupted run's."""
        from go_libp2p_pubsub_tpu.sim.supervisor import supervised_run
        cfg, tp, state, key = small
        contracts = (DeliveryFloor(floor=0.0, start=7),)
        ref_health = tmp_path / "ref.jsonl"
        ref_out, _ = supervised_run(
            state, cfg, tp, key, TICKS,
            _sup(health_path=str(ref_health), contracts=contracts))
        ref_ids = [(v["id"], v["status"])
                   for v in _notes(ref_health, "contract_verdict")]
        assert ref_ids == [("c0.s1.pass@7", "pass")]

        health = tmp_path / "health.jsonl"
        ck = tmp_path / "ck"

        def kill(info):
            if info["chunk_start"] >= 9:
                raise KeyboardInterrupt("simulated preemption")

        with pytest.raises(KeyboardInterrupt):
            supervised_run(
                state, cfg, tp, key, TICKS,
                _sup(health_path=str(health), contracts=contracts,
                     checkpoint_dir=str(ck), checkpoint_every_ticks=6,
                     keep_checkpoints=8),
                _chunk_hook=kill)
        # the transition note IS on disk; the newest checkpoint (t6)
        # predates it
        assert [d["id"] for d in _raw_notes(health, "contract_verdict")] \
            == ["c0.s1.pass@7"]

        out, rep = supervised_run(
            state, cfg, tp, key, TICKS,
            _sup(health_path=str(health), contracts=contracts,
                 checkpoint_dir=str(ck), checkpoint_every_ticks=6,
                 keep_checkpoints=8))
        assert rep.resumed_tick == 6
        vr = [e for e in rep.events if e["event"] == "verdict_resume"]
        assert vr and vr[0]["statuses"] == ["pending"]
        raw = _raw_notes(health, "contract_verdict")
        assert [d["id"] for d in raw] == ["c0.s1.pass@7"] * 2
        deduped = [(v["id"], v["status"])
                   for v in _notes(health, "contract_verdict")]
        assert deduped == ref_ids
        _assert_states_equal(ref_out, out)


class TestVerdictChaos:
    def test_parse_verdict_kill(self):
        from go_libp2p_pubsub_tpu.parallel.resilience import ChaosPlan
        assert ChaosPlan.parse("verdict_kill@8") == [
            {"action": "verdict_kill", "rank": 0, "tick": 8,
             "seconds": 0.0}]
        with pytest.raises(ValueError, match="GRAFT_CHAOS"):
            ChaosPlan.parse("verdict_kill@x")
        with pytest.raises(ValueError, match="GRAFT_CHAOS"):
            ChaosPlan.parse("verdict_kill@8:2")

    def test_verdict_specs_pin_to_rank0(self, tmp_path):
        from go_libp2p_pubsub_tpu.parallel.resilience import ChaosPlan
        specs = ChaosPlan.parse("verdict_kill@8")
        plan = ChaosPlan(specs, rank=0, run_dir=str(tmp_path))
        assert len(plan.verdict_specs) == 1 and plan.specs == []
        assert ChaosPlan(specs, rank=1).verdict_specs == []
        # chunk-hook and ingest fire points must skip verdict specs
        plan.fire({"chunk_start": 99})
        assert not os.listdir(tmp_path)


# ---------------------------------------------------------------------------
# dashboard: journal-first verdict render, incremental-monitor fallback


def _load_dashboard():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft_dashboard", os.path.join(REPO, "scripts", "dashboard.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_lines(path, lines):
    with open(path, "w") as f:
        for d in lines:
            f.write(json.dumps(d) + "\n")
    return str(path)


class TestDashboardVerdicts:
    CONTRACTS = (DeliveryFloor(floor=0.7),
                 RecoveryCeiling(after=2, within=3, floor=0.8),
                 ScoreResponse(by=3))

    def _header(self, now):
        return {"kind": "run", "wall": now - 30, "scenario": "eclipse",
                "n_peers": 64, "n_topics": 1, "flags_version": 1,
                "contracts": adversary.contracts_to_json(self.CONTRACTS),
                "attack_windows": [{"start": 2, "end": 5,
                                    "kind": "eclipse"}]}

    @staticmethod
    def _row(now, tick, d=0.9):
        return {"kind": "health", "wall": now - 20 + tick, "tick": tick,
                "member": -1, "delivery_frac_t0": d,
                "attacker_edges": 0, "attacker_graylisted": 0,
                "honest_graylisted": 0, "connected_edges": 100}

    def test_journaled_verdicts_render_without_reevaluation(self,
                                                            tmp_path):
        """Journaled notes win: latest-seq status per contract, sourced
        'journal', the breach banner up — and duplicate ids (a relaunch
        re-derivation) render exactly once via the tailer's dedup."""
        dash = _load_dashboard()
        now = time.time()
        dup = {"kind": "contract_verdict", "wall": now - 4, "contract": 0,
               "contract_kind": "delivery_floor", "seq": 2,
               "status": "fail", "tick": 4, "final": False,
               "detail": "min delivery 0.5000 @ tick 4 vs floor 0.7",
               "id": "c0.s2.fail@4"}
        path = _write_lines(tmp_path / "h.jsonl", [
            self._header(now), self._row(now, 0), self._row(now, 4, 0.5),
            {"kind": "contract_verdict", "wall": now - 9, "contract": 0,
             "contract_kind": "delivery_floor", "seq": 1,
             "status": "pass", "tick": 0, "final": False,
             "detail": "min delivery 0.9000 @ tick 0 vs floor 0.7",
             "id": "c0.s1.pass@0"},
            dup, dup,
            {"kind": "contract_verdict", "wall": now - 3, "contract": 2,
             "contract_kind": "score_response", "seq": 1,
             "status": "pass", "tick": 3, "final": False,
             "detail": "graylisted by tick 3", "id": "c2.s1.pass@3"},
            {"kind": "contract_alarm", "wall": now - 3, "policy":
             "journal", "contract": 0, "contract_kind": "delivery_floor",
             "tick": 4, "id": "c0.s2.fail@4", "detail": "breach"},
        ])
        snap = dash.snapshot(path)
        cs = {c["kind"]: c for c in snap["contracts"]}
        assert cs["delivery_floor"]["status"] == "fail"     # latest seq
        assert cs["delivery_floor"]["source"] == "journal"
        assert cs["score_response"]["status"] == "pass"
        assert snap.get("contract_alarm")
        assert "verdict_abort" not in snap
        text = dash.render(snap)
        assert "CONTRACT BREACH" in text and "VERDICT ABORT" not in text
        # tailer path: the duplicated id collapses to ONE verdict
        t = dash._Tailer(path)
        t.poll()
        j = t.journal()
        assert len(j["verdicts"]) == 3
        live = dash._snapshot_of(j, path)
        assert {c["kind"]: c["status"] for c in live["contracts"]} \
            == {c["kind"]: c["status"] for c in snap["contracts"]}

    def test_verdict_abort_banner(self, tmp_path):
        dash = _load_dashboard()
        now = time.time()
        path = _write_lines(tmp_path / "h.jsonl", [
            self._header(now), self._row(now, 0), self._row(now, 4, 0.5),
            {"kind": "contract_verdict", "wall": now - 2, "contract": 0,
             "contract_kind": "delivery_floor", "seq": 1,
             "status": "fail", "tick": 4, "final": False,
             "detail": "min delivery 0.5000 @ tick 4 vs floor 0.7",
             "id": "c0.s1.fail@4"},
            {"kind": "verdict_abort", "wall": now - 1, "policy": "abort",
             "contract": 0, "contract_kind": "delivery_floor", "tick": 4,
             "id": "c0.s1.fail@4",
             "detail": "min delivery 0.5000 @ tick 4 vs floor 0.7"},
        ])
        snap = dash.snapshot(path)
        va = snap["verdict_abort"]
        assert va["kind"] == "delivery_floor" and va["tick"] == 4
        text = dash.render(snap)
        assert "VERDICT ABORT" in text
        assert "restore from the last checkpoint" in text
        assert "CONTRACT BREACH" not in text    # superseded by the abort

    def test_tailer_incremental_monitors_match_batch(self, tmp_path):
        """The live fallback (runs that stamp contracts but journal no
        verdicts): the tailer's O(1)-per-row monitors agree with the
        batch O(all rows) re-evaluation the --once path still does."""
        dash = _load_dashboard()
        now = time.time()
        deliv = [0.9, 0.8, 0.6, 0.75, 0.85, 0.95]
        gray = [0, 1, 3, 5, 7, 8]
        rows = [{"kind": "health", "wall": now - 20 + i, "tick": i,
                 "member": -1, "delivery_frac_t0": d,
                 "attacker_edges": 10, "attacker_graylisted": g,
                 "honest_graylisted": 0, "connected_edges": 100}
                for i, (d, g) in enumerate(zip(deliv, gray))]
        path = _write_lines(tmp_path / "h.jsonl",
                            [self._header(now)] + rows)
        batch = dash.snapshot(path)["contracts"]
        assert batch and all("source" not in c for c in batch)
        t = dash._Tailer(path)
        t.poll()
        live = dash._snapshot_of(t.journal(), path)["contracts"]
        assert all(c["source"] == "monitor" for c in live)
        assert {c["kind"]: c["status"] for c in live} \
            == {c["kind"]: c["status"] for c in batch}


# ---------------------------------------------------------------------------
# THE acceptance leg: 2-process run, composed attack stream, rank 0
# SIGKILLed between a breach and its journaled verdict


V_TICKS, V_CHUNK, V_SEED, V_N = 16, 2, 7, 128

# the composed eclipse+censor stream (scripts/directive_producer.py
# --scenario eclipse_censor --at 4 --region 8 --attackers 8)
V_CONTRACTS = [
    # transitions pending→pass at tick 6 — mid-attack, detected at the
    # tick-8 confirm, exactly where verdict_kill@8 drops the rank
    {"kind": "delivery_floor", "floor": 0.0, "start": 6},
    # can never settle in 16 ticks: the pending→fail FINAL leg
    {"kind": "recovery_ceiling", "after": 20, "within": 5,
     "floor": 0.95},
]


def _mh_env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)     # conftest's 8-device flag must not leak
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="", **extra)
    return env


@pytest.fixture(scope="module")
def verdict_reference(tmp_path_factory):
    """The same composed stream + contracts run uninterrupted, single
    process: the state AND deduped verdict-note stream the killed →
    relaunched 2-process run must reproduce exactly once each."""
    import jax

    from go_libp2p_pubsub_tpu.parallel import multihost
    from go_libp2p_pubsub_tpu.sim import scenarios
    from go_libp2p_pubsub_tpu.sim.commands import CommandQueue
    from go_libp2p_pubsub_tpu.sim.supervisor import (SupervisorConfig,
                                                     supervised_run)
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from directive_producer import scenario_directives
    finally:
        sys.path.pop(0)
    from go_libp2p_pubsub_tpu.sim.commands import write_stream

    d = tmp_path_factory.mktemp("vref")
    src = d / "attack.ndjsonl"
    write_stream(str(src), scenario_directives(
        "eclipse_censor", at=4, region=8, attackers=8, bursts=3),
        end=True)
    health = d / "health.jsonl"
    cfg, tp, topo, subscribed = scenarios.frontier_spec(V_N)
    st = multihost.init_state_local(cfg, topo, 0, 1,
                                    subscribed=subscribed)
    q = CommandQueue(str(src), n_peers=cfg.n_peers, n_topics=cfg.n_topics,
                     msg_window=cfg.msg_window, slots=16,
                     stall_timeout_s=60.0)
    sup = SupervisorConfig(
        chunk_ticks=V_CHUNK, commands=q, backoff_base_s=0.0,
        sleep=lambda s: None, health_path=str(health),
        contracts=adversary.contracts_from_json(V_CONTRACTS))
    try:
        out, _ = supervised_run(st, cfg, tp, jax.random.PRNGKey(V_SEED),
                                V_TICKS, sup)
    finally:
        q.close()
    return out, str(src), str(health)


@pytest.mark.slow
def test_mh_verdict_kill_relaunch_journals_exactly_once(
        tmp_path, verdict_reference):
    """THE ISSUE 20 acceptance leg: a 2-process CPU run carrying live
    contracts is fed the composed eclipse+censor stream; GRAFT_CHAOS
    verdict_kill@8 SIGKILLs rank 0 between the DeliveryFloor breach
    detection and its journaled verdict. The group supervisor relaunches
    the run off the checkpoint sidecar's monitor token — the relaunch
    re-derives the verdict, the deduped note stream is identical to the
    uninterrupted run's (each verdict exactly once), and the final state
    is bit-exact."""
    ref_state, src, ref_health = verdict_reference
    ref_ids = [(v["id"], v["status"], v["contract_kind"])
               for v in _notes(ref_health, "contract_verdict")]
    assert ("c0.s1.pass@6", "pass", "delivery_floor") in ref_ids
    assert any(i[1] == "fail" and i[2] == "recovery_ceiling"
               for i in ref_ids)

    run_dir = tmp_path / "mh"
    run_dir.mkdir()
    final = tmp_path / "final.npz"
    health = run_dir / "health.jsonl"
    cmd = [sys.executable,
           os.path.join(REPO, "scripts", "mh_supervisor.py"),
           "--procs", "2,2", "--scenario", "frontier_250k",
           "--n", str(V_N), "--ticks", str(V_TICKS),
           "--seed", str(V_SEED), "--chunk-ticks", str(V_CHUNK),
           "--run-dir", str(run_dir), "--max-relaunches", "2",
           "--backoff-base-s", "0.05", "--dump-state", str(final),
           "--health", str(health), "--source", src,
           "--directive-slots", "16", "--ingest-stall-timeout", "30",
           "--contracts", json.dumps(V_CONTRACTS),
           "--verdict-policy", "journal"]
    proc = subprocess.run(
        cmd,
        env=_mh_env(GRAFT_CHAOS="verdict_kill@8",
                    GRAFT_MH_PEER_TIMEOUT_S="6",
                    GRAFT_MH_ABORT_GRACE_S="3",
                    GRAFT_MH_BEAT_INTERVAL_S="0.5"),
        cwd=REPO, capture_output=True, text=True, timeout=560)
    journal = [json.loads(ln)
               for ln in (run_dir / "mh_journal.jsonl").read_text()
               .splitlines()]
    assert proc.returncode == 0, (proc.stdout, proc.stderr, journal)
    # the kill really fired (durable once-per-run-dir marker) and the
    # group really relaunched
    assert "chaos_verdict_kill_r0_t8.fired" in os.listdir(run_dir)
    assert any(r["kind"] == "mh_failure" for r in journal)
    assert len([r for r in journal if r["kind"] == "mh_attempt"]) >= 2
    # sidecars carry the verdict-monitor token next to stream_offset
    from go_libp2p_pubsub_tpu.sim import checkpoint
    ck = run_dir / "ckpt"
    metas = [checkpoint.sidecar_meta(
                str(ck / p)[:-len(".npz")] if p.endswith(".npz")
                else str(ck / p))
             for p in os.listdir(ck) if not p.endswith(".fingerprint")]
    # a SIGKILL can leave one payload without its sidecar (payload lands
    # first; restore skips it) — every SIDECAR-COMPLETE checkpoint must
    # carry the verdict-monitor token next to the ingestion cursor
    stamped = [m for m in metas if m]
    assert stamped and all(m.get("monitors") and
                           m.get("stream_offset") is not None
                           for m in stamped)
    # exactly-once: the deduped verdict stream equals the uninterrupted
    # run's, and no id appears twice after read-side dedup
    got = [(v["id"], v["status"], v["contract_kind"])
           for v in _notes(health, "contract_verdict")]
    assert sorted(got) == sorted(ref_ids)
    assert len({g[0] for g in got}) == len(got)
    # the composed attack really landed: both fault bits lit
    from go_libp2p_pubsub_tpu.sim.invariants import (FAULT_CENSOR,
                                                     FAULT_ECLIPSE)
    from go_libp2p_pubsub_tpu.sim.telemetry import read_journal
    flags = 0
    for r in read_journal(str(health))["rows"]:
        flags |= int(r.get("fault_flags", 0))
    assert flags & FAULT_ECLIPSE and flags & FAULT_CENSOR
    # bit-exact final state vs the uninterrupted reference
    got_state = np.load(final)
    for f in ref_state._fields:
        assert np.array_equal(np.asarray(getattr(ref_state, f)),
                              got_state[f]), f
