"""Checkpoint/resume: an interrupted run continues bit-exactly
(SURVEY.md §5.4 — the pytree IS the network)."""

import jax
import numpy as np

from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology
from go_libp2p_pubsub_tpu.sim import checkpoint
from go_libp2p_pubsub_tpu.sim.engine import run


def _setup():
    cfg = SimConfig(n_peers=64, k_slots=8, n_topics=1, msg_window=32,
                    publishers_per_tick=2, prop_substeps=4,
                    scoring_enabled=True)
    tp = TopicParams.disabled(1)
    st = init_state(cfg, topology.sparse(64, 8, degree=3))
    return cfg, tp, st


def _assert_states_equal(a, b):
    for f, x, y in zip(a._fields, a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"field {f}")


class TestCheckpointResume:
    def test_resume_matches_uninterrupted(self, tmp_path):
        cfg, tp, st = _setup()
        key = jax.random.PRNGKey(42)
        k1, k2 = jax.random.split(key)
        # uninterrupted: 6 + 6 ticks
        ref = run(run(st, cfg, tp, k1, 6), cfg, tp, k2, 6)
        # interrupted: 6 ticks, save, restore, 6 more
        mid = run(st, cfg, tp, k1, 6)
        path = str(tmp_path / "ckpt")
        checkpoint.save(path, mid)
        back = checkpoint.restore(path, jax.tree.map(jnp_like, mid))
        _assert_states_equal(mid, back)
        resumed = run(back, cfg, tp, k2, 6)
        _assert_states_equal(ref, resumed)

    def test_npz_fallback_roundtrip(self, tmp_path):
        cfg, tp, st = _setup()
        st = run(st, cfg, tp, jax.random.PRNGKey(0), 3)
        path = str(tmp_path / "state.npz")
        checkpoint.save(path, st)
        back = checkpoint.restore(path, st)
        _assert_states_equal(st, back)


def jnp_like(x):
    import jax.numpy as jnp
    return jnp.zeros_like(x)
