"""Checkpoint/resume: an interrupted run continues bit-exactly
(SURVEY.md §5.4 — the pytree IS the network)."""

import jax
import numpy as np

from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state, topology
from go_libp2p_pubsub_tpu.sim import checkpoint
from go_libp2p_pubsub_tpu.sim.engine import run


def _setup():
    cfg = SimConfig(n_peers=64, k_slots=8, n_topics=1, msg_window=32,
                    publishers_per_tick=2, prop_substeps=4,
                    scoring_enabled=True)
    tp = TopicParams.disabled(1)
    st = init_state(cfg, topology.sparse(64, 8, degree=3))
    return cfg, tp, st


def _assert_states_equal(a, b):
    for f, x, y in zip(a._fields, a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"field {f}")


class TestCheckpointResume:
    def test_resume_matches_uninterrupted(self, tmp_path):
        cfg, tp, st = _setup()
        key = jax.random.PRNGKey(42)
        k1, k2 = jax.random.split(key)
        # uninterrupted: 6 + 6 ticks
        ref = run(run(st, cfg, tp, k1, 6), cfg, tp, k2, 6)
        # interrupted: 6 ticks, save, restore, 6 more
        mid = run(st, cfg, tp, k1, 6)
        path = str(tmp_path / "ckpt")
        checkpoint.save(path, mid)
        back = checkpoint.restore(path, jax.tree.map(jnp_like, mid))
        _assert_states_equal(mid, back)
        resumed = run(back, cfg, tp, k2, 6)
        _assert_states_equal(ref, resumed)

    def test_npz_fallback_roundtrip(self, tmp_path):
        cfg, tp, st = _setup()
        st = run(st, cfg, tp, jax.random.PRNGKey(0), 3)
        path = str(tmp_path / "state.npz")
        checkpoint.save(path, st)
        back = checkpoint.restore(path, st)
        _assert_states_equal(st, back)


class TestRestoreValidation:
    """restore must refuse shape/dtype-mismatched checkpoints loudly,
    naming the offending field, and verify the config fingerprint stamped
    at save — a checkpoint from a different config silently mis-resuming
    was the failure class this guards (ISSUE 4 satellite)."""

    def test_shape_mismatch_names_field(self, tmp_path):
        import pytest
        cfg, tp, st = _setup()
        path = str(tmp_path / "state.npz")
        checkpoint.save(path, st)
        # a `like` from a DIFFERENT config (more peers): every peer-major
        # field mismatches; the error must name the first offending field
        cfg2 = SimConfig(n_peers=128, k_slots=8, n_topics=1, msg_window=32,
                         publishers_per_tick=2, prop_substeps=4)
        like2 = init_state(cfg2, topology.sparse(128, 8, degree=3))
        with pytest.raises(ValueError, match="checkpoint field 'neighbors'"):
            checkpoint.restore(path, like2)

    def test_dtype_mismatch_names_field(self, tmp_path):
        import jax.numpy as jnp
        import pytest
        cfg, tp, st = _setup()
        path = str(tmp_path / "state.npz")
        checkpoint.save(path, st)
        like = st._replace(app_score=st.app_score.astype(jnp.int32))
        with pytest.raises(ValueError, match="checkpoint field 'app_score'"):
            checkpoint.restore(path, like)

    def test_missing_field_still_restores_from_like(self, tmp_path):
        """Forward compat: fields added after a checkpoint was written
        (e.g. fault_flags) restore from `like` — only PRESENT fields are
        validated."""
        import numpy as np
        cfg, tp, st = _setup()
        st = run(st, cfg, tp, jax.random.PRNGKey(1), 2)
        path = str(tmp_path / "old.npz")
        arrs = {f: np.asarray(v) for f, v in zip(st._fields, st)}
        arrs.pop("fault_flags")                 # simulate an old checkpoint
        np.savez_compressed(path, **arrs)
        back = checkpoint.restore(path, st)
        _assert_states_equal(st, back)

    def test_orbax_missing_field_restores_from_like(self, tmp_path):
        """Orbax primary-backend twin of the npz forward-compat path: a
        checkpoint written before a SimState field existed (orbax stores
        the namedtuple as a field-keyed dict) restores with the missing
        field taken from `like` instead of failing the structure match."""
        import numpy as np
        import pytest
        from go_libp2p_pubsub_tpu.sim.checkpoint import _HAVE_ORBAX
        if not _HAVE_ORBAX:
            pytest.skip("orbax not installed")
        import orbax.checkpoint as ocp
        cfg, tp, st = _setup()
        st = run(st, cfg, tp, jax.random.PRNGKey(2), 2)
        old = {f: np.asarray(v) for f, v in zip(st._fields, st)}
        old.pop("fault_flags")                  # simulate an old checkpoint
        path = str(tmp_path / "old_orbax")
        with ocp.StandardCheckpointer() as ck:
            ck.save(path, old)
        back = checkpoint.restore(path, st)
        _assert_states_equal(st, back)

    def test_config_fingerprint_checked(self, tmp_path):
        import dataclasses
        import pytest
        cfg, tp, st = _setup()
        path = str(tmp_path / "state.npz")
        checkpoint.save(path, st, cfg=cfg)
        # same config: clean restore
        back = checkpoint.restore(path, st, cfg=cfg)
        _assert_states_equal(st, back)
        # any knob drift (here: a fault plan appears) flips the digest
        from go_libp2p_pubsub_tpu.sim.faults import FaultPlan
        cfg2 = dataclasses.replace(cfg, fault_plan=FaultPlan(
            link_drop_prob=0.1))
        with pytest.raises(ValueError, match="different config"):
            checkpoint.restore(path, st, cfg=cfg2)
        # no cfg passed: fingerprint not enforced (old-caller compat)
        _assert_states_equal(st, checkpoint.restore(path, st))


def jnp_like(x):
    import jax.numpy as jnp
    return jnp.zeros_like(x)
