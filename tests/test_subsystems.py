"""Tests for the auxiliary subsystems: wire codec (with protoc
cross-validation), peer gater, tag tracer/connmgr, discovery, seqno
validator, trace sinks.
"""

import random
import shutil
import subprocess
import sys

import pytest

from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
from go_libp2p_pubsub_tpu.api.discovery import Discover, NetworkDiscovery
from go_libp2p_pubsub_tpu.api.seqno_validator import BasicSeqnoValidator
from go_libp2p_pubsub_tpu.api.validation import VALIDATION_ACCEPT, VALIDATION_IGNORE
from go_libp2p_pubsub_tpu.core.clock import VirtualClock
from go_libp2p_pubsub_tpu.core.types import (
    RPC,
    AcceptStatus,
    ControlIHave,
    ControlIWant,
    ControlMessage,
    ControlPrune,
    Message,
    PeerInfo,
    SubOpts,
)
from go_libp2p_pubsub_tpu.net import Network
from go_libp2p_pubsub_tpu.net.connmgr import ConnManager
from go_libp2p_pubsub_tpu.pb import codec
from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
from go_libp2p_pubsub_tpu.routers.peer_gater import PeerGater, PeerGaterParams
from go_libp2p_pubsub_tpu.routers.tag_tracer import TagTracer
from go_libp2p_pubsub_tpu.trace.sinks import JSONTracer, PBTracer, RemoteTracer


def full_rpc() -> RPC:
    return RPC(
        subscriptions=[SubOpts(True, "topic-a"), SubOpts(False, "topic-b")],
        publish=[Message(from_peer="peer-1", data=b"\x00\x01payload",
                         seqno=b"\x00" * 8, topic="topic-a",
                         signature=b"sig", key=b"key")],
        control=ControlMessage(
            ihave=[ControlIHave(topic="topic-a", message_ids=["m1", "m\xff2"])],
            iwant=[ControlIWant(message_ids=["m3"])],
            prune=[ControlPrune(topic="topic-b",
                                peers=[PeerInfo(peer_id="peer-2")],
                                backoff=60.0)]),
    )


class TestCodec:
    def test_rpc_roundtrip(self):
        rpc = full_rpc()
        buf = codec.encode_rpc(rpc)
        out = codec.decode_rpc(buf)
        assert [s.topicid for s in out.subscriptions] == ["topic-a", "topic-b"]
        assert out.subscriptions[0].subscribe and not out.subscriptions[1].subscribe
        m = out.publish[0]
        assert (m.from_peer, m.data, m.topic) == ("peer-1", b"\x00\x01payload", "topic-a")
        assert m.signature == b"sig" and m.key == b"key"
        assert out.control.ihave[0].message_ids == ["m1", "m\xff2"]
        assert out.control.prune[0].backoff == 60.0
        assert out.control.prune[0].peers[0].peer_id == "peer-2"

    def test_framing(self):
        rpcs = [full_rpc(), RPC(subscriptions=[SubOpts(True, "x")])]
        stream = b"".join(codec.frame_rpc(r) for r in rpcs)
        out = codec.read_frames(stream)
        assert len(out) == 2
        assert out[1].subscriptions[0].topicid == "x"

    def test_trace_event_roundtrip(self):
        evt = {"type": "DELIVER_MESSAGE", "peerID": "peer-9", "timestamp": 12.5,
               "deliverMessage": {"messageID": "mid\xfe", "topic": "t",
                                  "receivedFrom": "peer-3"}}
        out = codec.decode_trace_event(codec.encode_trace_event(evt))
        assert out["type"] == "DELIVER_MESSAGE"
        assert out["peerID"] == "peer-9"
        assert out["timestamp"] == pytest.approx(12.5)
        assert out["deliverMessage"]["messageID"] == "mid\xfe"

    def test_compat_message(self):
        # old multi-topic schema (compat_test.go:10-83)
        m = Message(from_peer="p", data=b"d", seqno=b"s", topic="t1")
        buf = codec.encode_compat_message(m, topics=["t1", "t2"])
        out, topics = codec.decode_compat_message(buf)
        assert topics == ["t1", "t2"] and out.topic == "t1"
        # new single-topic decoder reads the first topic of old messages
        new = codec.decode_message(buf)
        assert new.topic in ("t1", "t2")

    @pytest.mark.skipif(shutil.which("protoc") is None, reason="protoc missing")
    def test_wire_compat_with_protoc(self, tmp_path):
        """Golden interop: our encoder's bytes parse under protoc-generated
        code for the reference schema, field for field."""
        proto = tmp_path / "rpc_check.proto"
        proto.write_text("""
syntax = "proto2";
package check;
message RPC {
  repeated SubOpts subscriptions = 1;
  repeated Message publish = 2;
  message SubOpts { optional bool subscribe = 1; optional string topicid = 2; }
  optional ControlMessage control = 3;
}
message Message {
  optional bytes from = 1; optional bytes data = 2; optional bytes seqno = 3;
  optional string topic = 4; optional bytes signature = 5; optional bytes key = 6;
}
message ControlMessage {
  repeated ControlIHave ihave = 1; repeated ControlIWant iwant = 2;
  repeated ControlGraft graft = 3; repeated ControlPrune prune = 4;
}
message ControlIHave { optional string topicID = 1; repeated bytes messageIDs = 2; }
message ControlIWant { repeated bytes messageIDs = 1; }
message ControlGraft { optional string topicID = 1; }
message ControlPrune { optional string topicID = 1; repeated PeerInfo peers = 2; optional uint64 backoff = 3; }
message PeerInfo { optional bytes peerID = 1; optional bytes signedPeerRecord = 2; }
""")
        subprocess.run(["protoc", f"--python_out={tmp_path}",
                        f"-I{tmp_path}", "rpc_check.proto"], check=True)
        sys.path.insert(0, str(tmp_path))
        try:
            import rpc_check_pb2  # type: ignore
        finally:
            sys.path.pop(0)
        buf = codec.encode_rpc(full_rpc())
        parsed = rpc_check_pb2.RPC()
        parsed.ParseFromString(buf)
        assert [s.topicid for s in parsed.subscriptions] == ["topic-a", "topic-b"]
        assert parsed.publish[0].data == b"\x00\x01payload"
        assert parsed.publish[0].topic == "topic-a"
        assert parsed.control.ihave[0].messageIDs[1] == "m\xff2".encode("latin-1")
        assert parsed.control.prune[0].backoff == 60
        # and the reverse: protoc-encoded bytes decode under our codec
        back = codec.decode_rpc(parsed.SerializeToString())
        assert back.control.prune[0].topic == "topic-b"


class TestPeerGater:
    def _gater(self, clk):
        params = PeerGaterParams(threshold=0.33, global_decay=0.9,
                                 source_decay=0.9)
        return PeerGater(params, get_ip=lambda p: f"ip-{p}",
                         rng=random.Random(42))

    def test_accepts_when_quiet(self):
        clk = VirtualClock()
        g = self._gater(clk)
        g._now = clk.now
        assert g.accept_from("p") == AcceptStatus.ACCEPT_ALL

    def test_throttles_bad_peer(self):
        clk = VirtualClock()
        g = self._gater(clk)
        g._now = clk.now
        g.add_peer("bad", "proto")
        # lots of throttle events -> gater active
        from go_libp2p_pubsub_tpu.trace import events as ev
        for i in range(100):
            g.validate_message(Message(received_from="bad"))
            g.reject_message(Message(received_from="bad"),
                             ev.REJECT_VALIDATION_THROTTLED)
        # bad peer has many rejections
        for i in range(50):
            g.reject_message(Message(received_from="bad", topic="t"),
                             ev.REJECT_VALIDATION_FAILED)
        results = [g.accept_from("bad") for _ in range(50)]
        assert AcceptStatus.ACCEPT_CONTROL in results
        # a good peer with deliveries mostly passes
        g.add_peer("good", "proto")
        for i in range(50):
            g.deliver_message(Message(received_from="good", topic="t"))
        good = [g.accept_from("good") for _ in range(50)]
        assert good.count(AcceptStatus.ACCEPT_ALL) > 45

    def test_quiet_period_disables(self):
        clk = VirtualClock()
        g = self._gater(clk)
        g._now = clk.now
        from go_libp2p_pubsub_tpu.trace import events as ev
        g.add_peer("p", "proto")
        g.reject_message(Message(received_from="p"), ev.REJECT_VALIDATION_THROTTLED)
        clk.advance_to(61.0)  # > Quiet (60s)
        assert g.accept_from("p") == AcceptStatus.ACCEPT_ALL

    def test_params_validation(self):
        with pytest.raises(ValueError):
            PeerGaterParams(threshold=0).validate()
        with pytest.raises(ValueError):
            PeerGaterParams(ignore_weight=0.5).validate()


class TestTagTracer:
    def test_mesh_protection_and_delivery_tags(self):
        from go_libp2p_pubsub_tpu.net.network import Scheduler
        sched = Scheduler()
        cm = ConnManager(sched)
        t = TagTracer(cm)
        t.join("topic")
        t.graft("peer-1", "topic")
        assert cm.is_protected("peer-1", "pubsub:topic")
        t.prune("peer-1", "topic")
        assert not cm.is_protected("peer-1", "pubsub:topic")
        # delivery bumps, near-first counted
        m = Message(from_peer="a", seqno=b"1", topic="topic", received_from="peer-1")
        t.validate_message(m)
        dup = Message(from_peer="a", seqno=b"1", topic="topic", received_from="peer-2")
        t.duplicate_message(dup)
        t.deliver_message(m)
        tag = cm.tags["pubsub-deliveries:topic"]
        assert tag.values["peer-1"] == 1 and tag.values["peer-2"] == 1
        # decaying: after the interval the values decay away
        sched.run_for(601.0)
        assert "peer-1" not in tag.values
        # leave closes the tag
        t.leave("topic")

    def test_direct_peer_protection(self):
        from go_libp2p_pubsub_tpu.net.network import Scheduler
        cm = ConnManager(Scheduler())
        t = TagTracer(cm, direct={"d"})
        t.add_peer("d", "proto")
        assert cm.is_protected("d", "pubsub:<direct>")


class TestDiscovery:
    def test_thin_topic_gets_peers(self):
        net = Network()
        svc = NetworkDiscovery()
        nodes = []
        for i in range(8):
            h = net.add_host()
            nodes.append(PubSub(h, GossipSubRouter(), sign_policy=LAX_NO_SIGN,
                                discovery=Discover(svc)))
        # NO manual connections: discovery must bootstrap connectivity
        subs = [x.join("t").subscribe() for x in nodes]
        net.scheduler.run_for(10.0)
        # all nodes discovered and connected each other
        for x in nodes:
            assert len(x.host.conns) >= 1
        nodes[0].my_topics["t"].publish(b"found-you")
        net.scheduler.run_for(5.0)
        delivered = sum(1 for s in subs if s.next() is not None)
        assert delivered == 8

    def test_bootstrap_readiness(self):
        net = Network()
        svc = NetworkDiscovery()
        a = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN,
                   discovery=Discover(svc))
        b = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN,
                   discovery=Discover(svc))
        a.join("t").subscribe()
        b.join("t").subscribe()
        ok = a.disc.bootstrap("t", ready=lambda: a.rt.enough_peers("t", 1))
        assert ok


class TestSeqnoValidator:
    def test_replay_suppression(self):
        v = BasicSeqnoValidator()
        m1 = Message(from_peer="a", seqno=(1).to_bytes(8, "big"))
        m2 = Message(from_peer="a", seqno=(2).to_bytes(8, "big"))
        assert v("src", m1) == VALIDATION_ACCEPT
        assert v("src", m2) == VALIDATION_ACCEPT
        assert v("src", m1) == VALIDATION_IGNORE   # replay
        assert v("src", m2) == VALIDATION_IGNORE
        m3 = Message(from_peer="b", seqno=(1).to_bytes(8, "big"))
        assert v("src", m3) == VALIDATION_ACCEPT   # other author unaffected

    def test_wired_into_pipeline(self):
        net = Network()
        nodes = [PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
                 for _ in range(2)]
        net.connect_all([x.host for x in nodes])
        net.scheduler.run_for(0.1)
        for x in nodes:
            x.val.add_default_validator(BasicSeqnoValidator())
        sub = nodes[1].join("t").subscribe()
        nodes[0].join("t").subscribe()
        net.scheduler.run_for(2.0)
        # hand-replay: send the same message twice directly
        msg = Message(from_peer=nodes[0].pid, seqno=(9).to_bytes(8, "big"),
                      data=b"x", topic="t")
        nodes[0].host.send(nodes[1].pid, RPC(publish=[msg]))
        net.scheduler.run_for(0.5)
        replay = Message(from_peer=nodes[0].pid, seqno=(9).to_bytes(8, "big"),
                         data=b"x", topic="t")
        nodes[0].host.send(nodes[1].pid, RPC(publish=[replay]))
        net.scheduler.run_for(0.5)
        got = []
        while (m := sub.next()) is not None:
            got.append(m)
        assert len(got) == 1


class TestSinks:
    def test_json_tracer(self, tmp_path):
        path = str(tmp_path / "trace.ndjson")
        t = JSONTracer(path)
        t.trace({"type": "JOIN", "peerID": "p", "timestamp": 1.0,
                 "join": {"topic": "t"}})
        t.close()
        import json
        lines = [json.loads(x) for x in open(path)]
        assert lines[0]["type"] == "JOIN"

    def test_pb_tracer_roundtrip(self, tmp_path):
        path = str(tmp_path / "trace.pb")
        t = PBTracer(path)
        t.trace({"type": "GRAFT", "peerID": "p", "timestamp": 2.0,
                 "graft": {"peerID": "q", "topic": "t"}})
        t.trace({"type": "PRUNE", "peerID": "p", "timestamp": 3.0,
                 "prune": {"peerID": "q", "topic": "t"}})
        t.close()
        events = codec.read_trace_file(path)
        assert [e["type"] for e in events] == ["GRAFT", "PRUNE"]
        assert events[0]["graft"]["peerID"] == "q"

    def test_remote_tracer_batches(self):
        batches = []
        t = RemoteTracer(batches.append)
        for i in range(20):
            t.trace({"type": "JOIN", "peerID": "p", "timestamp": float(i),
                     "join": {"topic": "t"}})
        t.flush()
        assert len(batches) == 1
        decoded = RemoteTracer.decode_batch(batches[0])
        assert len(decoded) == 20

    def test_remote_tracer_reconnects_on_write_failure(self):
        """tracer.go:268-276: a write failure resets the stream and reopens;
        the batch is retried on the fresh stream."""
        batches, opened = [], []

        def open_stream():
            opened.append(1)
            calls = {"n": 0}

            def write(payload):
                calls["n"] += 1
                if len(opened) == 1 and calls["n"] == 1:
                    raise IOError("stream reset")
                batches.append(payload)
            return write

        t = RemoteTracer(open_stream=open_stream)
        for i in range(20):
            t.trace({"type": "JOIN", "peerID": "p", "timestamp": float(i),
                     "join": {"topic": "t"}})
        t.flush()
        assert len(opened) == 2 and len(batches) == 1 and t.dropped == 0
        assert len(RemoteTracer.decode_batch(batches[0])) == 20

    def test_remote_tracer_drops_when_collector_down(self):
        """Lossy contract: unreachable collector drops the batch, counted."""
        def open_stream():
            raise IOError("dial failed")

        t = RemoteTracer(open_stream=open_stream)
        for i in range(20):
            t.trace({"type": "JOIN", "peerID": "p", "timestamp": float(i),
                     "join": {"topic": "t"}})
        t.flush()
        assert t.dropped == 20

    def test_event_tracer_wired_into_node(self, tmp_path):
        path = str(tmp_path / "node.ndjson")
        sink = JSONTracer(path)
        net = Network()
        nodes = [PubSub(net.add_host(), GossipSubRouter(),
                        sign_policy=LAX_NO_SIGN, event_tracer=sink)
                 for _ in range(2)]
        net.connect_all([x.host for x in nodes])
        net.scheduler.run_for(0.1)
        sub = nodes[0].join("t").subscribe()
        nodes[1].join("t").subscribe()
        net.scheduler.run_for(2.0)
        nodes[1].my_topics["t"].publish(b"traced")
        net.scheduler.run_for(1.0)
        sink.close()
        import json
        types = {json.loads(x)["type"] for x in open(path)}
        assert {"JOIN", "SEND_RPC", "RECV_RPC", "DELIVER_MESSAGE"} <= types
