"""Fleet plane (sim/fleet.py, ISSUE 7).

The core correctness claim: fleet(B) member trajectories are BIT-IDENTICAL
to B sequential ``engine.run`` calls — plain, with a FaultPlan firing on
one member only, under heterogeneous tick counts (early-exit compaction),
sharded across the test CPU mesh, and across supervised chunking with a
kill/resume. Everything else (per-member flag isolation, trip retirement,
the fleet-axis checkpoint fingerprint, with_score_weights) is fleet
plumbing proven on top of that claim.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import (SimConfig, checkpoint, init_state,
                                      scenarios, topology)
from go_libp2p_pubsub_tpu.sim.config import with_score_weights
from go_libp2p_pubsub_tpu.sim.engine import run
from go_libp2p_pubsub_tpu.sim.fleet import (FleetMember, fleet_devices,
                                            fleet_run, fleet_run_keys,
                                            shard_fleet, stack_states,
                                            supervised_fleet_run)
from go_libp2p_pubsub_tpu.sim.supervisor import SupervisorConfig

pytestmark = pytest.mark.fleet

# 8 = 2 x the supervised chunk of 4: every supervised case below lands on
# the same (4, B) window shapes, so the vmapped-scan compiles are shared
N_TICKS = 8


def _assert_states_equal(a, b, msg=""):
    for f, x, y in zip(a._fields, a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"{msg} field {f}")


@pytest.fixture(scope="module")
def base():
    """Shared tiny config (module-scoped: tests reuse the jit cache)."""
    cfg = SimConfig(n_peers=64, k_slots=8, n_topics=1, msg_window=32,
                    publishers_per_tick=2, prop_substeps=4,
                    scoring_enabled=True)
    tp = scenarios.default_topic_params(1)
    st = init_state(cfg, topology.sparse(64, 8, degree=3))
    return cfg, tp, st


def _members(base, b, n_ticks=N_TICKS):
    cfg, tp, st = base
    return [FleetMember(cfg, tp, st, jax.random.PRNGKey(100 + i), n_ticks,
                        name=f"m{i}") for i in range(b)]


def _sup(**kw):
    kw.setdefault("chunk_ticks", 4)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("sleep", lambda s: None)
    return SupervisorConfig(**kw)


class TestFleetParity:
    @pytest.mark.parametrize("b", [1, 4])
    def test_fleet_bit_exact_vs_sequential(self, base, b):
        """THE acceptance case at B=1 and B=4: every member's final state
        equals its own sequential engine.run, bit for bit."""
        members = _members(base, b)
        results = fleet_run(members)
        for m, r in zip(members, results):
            ref = run(m.state, m.cfg, m.tp, m.key, m.n_ticks)
            _assert_states_equal(ref, r.state, m.name)
            assert r.ticks_run == m.n_ticks and not r.tripped

    def test_score_weight_variants_batch_together(self, base):
        """P1-P4 weight variants are traced TopicParams rows: they share
        the jit-static config, batch into ONE group, and stay bit-exact
        per member."""
        cfg, tp, st = base
        variants = [tp, with_score_weights(tp, p2=4.0),
                    with_score_weights(tp, p3=0.0, p3b=0.0)]
        members = [FleetMember(cfg, v, st, jax.random.PRNGKey(7 + i),
                               N_TICKS, name=f"v{i}")
                   for i, v in enumerate(variants)]
        results, rep = supervised_fleet_run(members, _sup())
        plan = next(e for e in rep.events if e["event"] == "fleet_plan")
        assert plan["groups"] == 1 and plan["sizes"] == [3]
        for m, r in zip(members, results):
            _assert_states_equal(run(m.state, m.cfg, m.tp, m.key, N_TICKS),
                                 r.state, m.name)

    def test_fault_plan_on_one_member_only(self, base):
        """A FaultPlan member rides its own config group; its siblings'
        trajectories AND fault_flags are untouched (per-member
        isolation), and every member still matches its sequential run."""
        cfg, tp, st = base
        fcfg, ftp, fst = scenarios.partition_small(
            n_peers=64, k_slots=8, degree=3, start=2, heal=6)
        members = [
            FleetMember(cfg, tp, st, jax.random.PRNGKey(1), N_TICKS, "a"),
            FleetMember(fcfg, ftp, fst, jax.random.PRNGKey(2), N_TICKS,
                        "faulty"),
            FleetMember(cfg, tp, st, jax.random.PRNGKey(3), N_TICKS, "b"),
        ]
        results = fleet_run(members, chunk_ticks=4)
        for m, r in zip(members, results):
            _assert_states_equal(run(m.state, m.cfg, m.tp, m.key, m.n_ticks),
                                 r.state, m.name)
        from go_libp2p_pubsub_tpu.sim.invariants import FAULT_PARTITION
        assert results[1].fault_flags & FAULT_PARTITION
        assert "partition" in results[1].flag_names
        assert results[0].fault_flags == 0 and results[2].fault_flags == 0

    def test_heterogeneous_ticks_compact_finished_members(self, base):
        """Members finish at their own n_ticks; finished lanes compact out
        of the batch (the long-tail member does not hold idle lanes) and
        every trajectory still matches its sequential run."""
        cfg, tp, st = base
        members = [FleetMember(cfg, tp, st, jax.random.PRNGKey(20 + i), t,
                               name=f"t{t}") for i, t in enumerate((3, 7, 12))]
        results, rep = supervised_fleet_run(members, _sup(chunk_ticks=4))
        compacts = [e for e in rep.events if e["event"] == "compact"]
        assert compacts, rep.events          # the batch DID shrink
        assert compacts[-1]["active"] == 1   # long tail ran alone
        for m, r in zip(members, results):
            _assert_states_equal(run(m.state, m.cfg, m.tp, m.key, m.n_ticks),
                                 r.state, m.name)
            assert r.ticks_run == m.n_ticks

    def test_sharded_fleet_matches_sequential(self, base):
        """The fleet axis sharded across the test CPU mesh (conftest
        forces 8 virtual devices) stays bit-exact — the multi-device
        scaling path of bench.py's fleet line."""
        cfg, tp, st = base
        b, ticks = 8, 5
        keys = [jax.random.PRNGKey(40 + i) for i in range(b)]
        states = stack_states([st] * b)
        tps = stack_states([tp] * b)
        kw = jnp.stack([jax.random.split(k, ticks) for k in keys], axis=1)
        assert fleet_devices(b) == jax.local_device_count() == 8
        sstates, stps, skw = shard_fleet(states, tps, kw)
        out = fleet_run_keys(sstates, cfg, stps, skw)
        for i in range(b):
            ref = run(st, cfg, tp, keys[i], ticks)
            _assert_states_equal(ref, jax.tree.map(lambda a: a[i], out),
                                 f"lane{i}")


class TestFleetSupervised:
    def test_kill_resume_bit_identical(self, base, tmp_path):
        """Interrupt the fleet mid-schedule, re-invoke with the same
        checkpoint dir: resume from the fleet checkpoint, final states
        bit-identical to uninterrupted sequential runs."""
        members = _members(base, 3)
        ck = str(tmp_path / "ck")

        def kill(info):
            if info["window_start"] >= 4:
                raise KeyboardInterrupt("simulated preemption")

        with pytest.raises(KeyboardInterrupt):
            supervised_fleet_run(members, _sup(checkpoint_dir=ck),
                                 _chunk_hook=kill)
        results, rep = supervised_fleet_run(members,
                                            _sup(checkpoint_dir=ck))
        assert rep.resumed_tick == 4
        assert rep.ticks_run == 3 * 4        # only the missing window re-ran
        for m, r in zip(members, results):
            _assert_states_equal(run(m.state, m.cfg, m.tp, m.key, m.n_ticks),
                                 r.state, m.name)

    def test_b4_journal_cannot_resume_into_b8(self, base, tmp_path):
        """The fleet-axis fingerprint satellite: checkpoints from a B=4
        run are REJECTED BY NAME when a B=8 run (same config!) tries to
        resume from the same directory, and the B=8 run completes from
        scratch."""
        ck = str(tmp_path / "ck")
        _, rep4 = supervised_fleet_run(_members(base, 4),
                                       _sup(checkpoint_dir=ck))
        assert rep4.checkpoints
        results, rep8 = supervised_fleet_run(_members(base, 8),
                                             _sup(checkpoint_dir=ck))
        skips = [e for e in rep8.events if e["event"] == "resume_skip"]
        assert skips and "fleet-axis mismatch" in skips[0]["error"]
        assert rep8.resumed_from is None
        for m, r in zip(_members(base, 8), results):
            _assert_states_equal(run(m.state, m.cfg, m.tp, m.key, m.n_ticks),
                                 r.state, m.name)

    def test_deadline_trip_backoff_then_parity(self, base):
        """The fleet window watchdog: a deadline overrun on one window is
        a transient failure (kind=deadline, NOT a KeyError from the
        supervisor's info schema — the two callers' dicts differ), and
        the retried fleet lands bit-exact."""
        import time as _time
        members = _members(base, 3)

        def slow_once(info):
            # the SECOND window: the first window of a shape compiles and
            # runs under the (unbounded) compile deadline by design
            if info["window_start"] == 4 and info["attempt"] == 0:
                _time.sleep(1.0)

        results, rep = supervised_fleet_run(
            members, _sup(deadline_s=0.4, max_retries=2),
            _chunk_hook=slow_once)
        assert rep.retries == 1
        fails = [e for e in rep.events if e["event"] == "chunk_failed"]
        assert fails and "deadline" in fails[0]["error"]
        for m, r in zip(members, results):
            _assert_states_equal(run(m.state, m.cfg, m.tp, m.key, m.n_ticks),
                                 r.state, m.name)

    def test_retry_ladder_then_parity(self, base):
        """A transient window failure degrades down the shared supervisor
        ladder and the fleet still lands bit-exact."""
        members = _members(base, 2)
        fails = iter([True])

        def flaky(info):
            if next(fails, False):
                raise RuntimeError("transient")

        results, rep = supervised_fleet_run(members, _sup(max_retries=2),
                                            _chunk_hook=flaky)
        assert rep.retries == 1
        assert any(e["event"] == "degrade" for e in rep.events)
        for m, r in zip(members, results):
            _assert_states_equal(run(m.state, m.cfg, m.tp, m.key, m.n_ticks),
                                 r.state, m.name)

    def test_crash_dump_carries_per_member_flags(self, base, tmp_path):
        import json
        from go_libp2p_pubsub_tpu.sim.supervisor import SupervisorCrash
        members = _members(base, 2)

        def boom(info):
            raise RuntimeError("permanent failure")

        with pytest.raises(SupervisorCrash) as ei:
            supervised_fleet_run(
                members, _sup(max_retries=1, crash_dir=str(tmp_path)),
                _chunk_hook=boom)
        meta = json.load(open(os.path.join(ei.value.dump_dir, "crash.json")))
        assert meta["fleet_size"] == 2
        assert meta["member_names"] == ["m0", "m1"]
        assert len(meta["fault_flags"]) == 2
        assert meta["config_fingerprint"] == checkpoint.config_fingerprint(
            members[0].cfg, fleet=2)
        # the batched last-good checkpoint restores at the fleet axis
        like = stack_states([members[0].state, members[1].state])
        back = checkpoint.restore(os.path.join(ei.value.dump_dir,
                                               "last_good"), like,
                                  cfg=members[0].cfg)
        assert np.asarray(back.tick).shape == (2,)


class TestTripIsolation:
    def test_raise_member_retires_without_killing_siblings(self, base):
        """An invariant_mode="raise" member whose sentinel fires is
        retired at the chunk boundary (state frozen, tripped=True); its
        siblings run to completion bit-exact — one poisoned lane cannot
        kill or mask B-1 healthy ones."""
        cfg, tp, st = base
        rcfg = dataclasses.replace(cfg, invariant_mode="raise")
        poisoned = st._replace(halo_overflow=jnp.int32(3))
        members = [
            FleetMember(cfg, tp, st, jax.random.PRNGKey(1), N_TICKS, "ok0"),
            FleetMember(rcfg, tp, poisoned, jax.random.PRNGKey(2), N_TICKS,
                        "poisoned"),
            FleetMember(cfg, tp, st, jax.random.PRNGKey(3), N_TICKS, "ok1"),
        ]
        results, rep = supervised_fleet_run(members, _sup(chunk_ticks=4))
        assert results[1].tripped
        assert results[1].ticks_run < N_TICKS      # retired early
        assert any("VIOLATION" in n for n in results[1].flag_names)
        assert any(e["event"] == "member_tripped" for e in rep.events)
        for i in (0, 2):
            m, r = members[i], results[i]
            assert not r.tripped and r.fault_flags == 0
            _assert_states_equal(run(m.state, m.cfg, m.tp, m.key, m.n_ticks),
                                 r.state, m.name)

    def test_record_member_with_flags_is_not_retired(self, base):
        """record-mode members carry their flags to completion — only
        "raise" members are retired on violations."""
        cfg, tp, st = base
        poisoned = st._replace(halo_overflow=jnp.int32(3))
        members = [FleetMember(cfg, tp, poisoned, jax.random.PRNGKey(5),
                               N_TICKS, "recorded")]
        results = fleet_run(members)
        assert not results[0].tripped
        assert results[0].ticks_run == N_TICKS
        assert any("VIOLATION" in n for n in results[0].flag_names)


class TestScoreWeights:
    """with_score_weights satellite: the P1-P7 override constructor."""

    def test_topic_level_overrides_broadcast(self):
        tp = scenarios.default_topic_params(3)
        out = with_score_weights(tp, p2=4.0, p4=-40.0)
        np.testing.assert_array_equal(
            np.asarray(out.first_message_deliveries_weight), [4.0] * 3)
        np.testing.assert_array_equal(
            np.asarray(out.invalid_message_deliveries_weight), [-40.0] * 3)
        # untouched rows are untouched
        np.testing.assert_array_equal(
            np.asarray(out.mesh_message_deliveries_weight),
            np.asarray(tp.mesh_message_deliveries_weight))

    def test_full_field_names_and_per_topic_values(self):
        tp = scenarios.default_topic_params(2)
        out = with_score_weights(tp, time_in_mesh_weight=[0.5, 0.25])
        np.testing.assert_array_equal(
            np.asarray(out.time_in_mesh_weight), [0.5, 0.25])

    def test_config_level_weights_need_cfg(self):
        tp = scenarios.default_topic_params(1)
        with pytest.raises(ValueError, match="pass cfg="):
            with_score_weights(tp, p7=-40.0)
        cfg = SimConfig(n_peers=64, k_slots=8)
        out_tp, out_cfg = with_score_weights(tp, cfg=cfg, p7=-40.0,
                                             p6=-200.0, p1=0.0)
        assert out_cfg.behaviour_penalty_weight == -40.0
        assert out_cfg.ip_colocation_factor_weight == -200.0
        np.testing.assert_array_equal(
            np.asarray(out_tp.time_in_mesh_weight), [0.0])
        # cfg passed but no cfg-level overrides: cfg returned unchanged
        same_tp, same_cfg = with_score_weights(tp, cfg=cfg, p2=2.0)
        assert same_cfg is cfg

    def test_unknown_weight_raises(self):
        tp = scenarios.default_topic_params(1)
        with pytest.raises(ValueError, match="unknown score weight"):
            with_score_weights(tp, p9=1.0)


class TestFleetCheckpointFingerprint:
    """checkpoint.save/restore fleet-axis satellite at the unit level."""

    def test_fingerprint_binds_fleet_axis(self, base):
        cfg, _, _ = base
        assert checkpoint.config_fingerprint(cfg) \
            != checkpoint.config_fingerprint(cfg, fleet=4)
        assert checkpoint.config_fingerprint(cfg, fleet=4) \
            != checkpoint.config_fingerprint(cfg, fleet=8)

    def test_batched_save_names_mismatch(self, base, tmp_path):
        cfg, tp, st = base
        b4 = stack_states([st] * 4)
        path = str(tmp_path / "fleet_ck")
        checkpoint.save(path, b4, cfg=cfg)
        # B=8 `like` → named fleet error, not a shape crash
        b8 = stack_states([st] * 8)
        with pytest.raises(ValueError, match="fleet-axis mismatch"):
            checkpoint.restore(path, b8, cfg=cfg)
        # unbatched `like` → named fleet error too
        with pytest.raises(ValueError, match="fleet-axis mismatch"):
            checkpoint.restore(path, st, cfg=cfg)
        # matching axis restores cleanly
        back = checkpoint.restore(path, b4, cfg=cfg)
        _assert_states_equal(b4, back)

    def test_unbatched_save_rejects_fleet_like(self, base, tmp_path):
        cfg, tp, st = base
        path = str(tmp_path / "single_ck")
        checkpoint.save(path, st, cfg=cfg)
        with pytest.raises(ValueError, match="fleet-axis mismatch"):
            checkpoint.restore(path, stack_states([st] * 4), cfg=cfg)
        _assert_states_equal(st, checkpoint.restore(path, st, cfg=cfg))
