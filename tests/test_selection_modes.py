"""Selection formulations must be bit-identical (ops/selection.py).

Like the permutation-gather modes, the masked-selection kernels have
backend-tuned formulations (O(K^2) ranks, sort+threshold, O(c*K) iterative
argmax); the engine trajectory is the contract, so every mode is diffed
against the ranks reference at op level (including deliberate key ties) and
over full engine ticks.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops.selection import (
    _select_by_keys,
    resolve_selection_mode,
    select_random,
    select_top,
)
from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
from go_libp2p_pubsub_tpu.sim.scenarios import default_topic_params

MODES = ["ranks", "sort", "iter"]


class TestOpParity:
    def test_random_keys(self):
        n, t, k = 128, 3, 16
        key = jax.random.PRNGKey(0)
        mask = jax.random.uniform(jax.random.PRNGKey(1), (n, t, k)) < 0.5
        score = jax.random.normal(key, (n, t, k))
        count = jax.random.randint(jax.random.PRNGKey(2), (n, t), 0, 7)
        ref = select_top(score, mask, count, mode="ranks")
        for mode in ("sort", "iter"):
            out = select_top(score, mask, count, max_count=6, mode=mode)
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(out),
                                          err_msg=mode)

    def test_tied_keys_break_to_lower_slot(self):
        """Duplicate keys across slots: all modes must pick the lower slot."""
        k = 8
        keys = jnp.array([[1.0, 2.0, 2.0, 1.0, 2.0, 0.5, -1e30, 2.0]])
        mask = jnp.array([[True] * 6 + [False, True]])
        count = jnp.array([3])
        ref = _select_by_keys(keys, mask, count, mode="ranks")
        # ranks: the three lowest-index 2.0s -> slots 1, 2, 4
        np.testing.assert_array_equal(
            np.asarray(ref)[0],
            [False, True, True, False, True, False, False, False])
        for mode in ("sort", "iter"):
            out = _select_by_keys(keys, mask, count, max_count=4, mode=mode)
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(out),
                                          err_msg=mode)

    def test_count_exceeds_candidates(self):
        keys = jnp.array([[3.0, 1.0, 2.0, 0.0]])
        mask = jnp.array([[True, False, True, False]])
        count = jnp.array([4])
        ref = _select_by_keys(keys, mask, count, mode="ranks")
        np.testing.assert_array_equal(np.asarray(ref)[0],
                                      [True, False, True, False])
        for mode in ("sort", "iter"):
            out = _select_by_keys(keys, mask, count, max_count=4, mode=mode)
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(out),
                                          err_msg=mode)

    def test_select_random_parity(self):
        n, t, k = 256, 2, 16
        mask = jax.random.uniform(jax.random.PRNGKey(3), (n, t, k)) < 0.6
        count = jnp.full((n, t), 5)
        key = jax.random.PRNGKey(7)
        ref = select_random(mask, count, key, mode="ranks")
        for mode in ("sort", "iter"):
            out = select_random(mask, count, key, max_count=5, mode=mode)
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(out),
                                          err_msg=mode)

    def test_count_bound_guard_fires(self, monkeypatch):
        """selection.CHECK_COUNT_BOUND turns the iter formulation's silent
        count > max_count truncation into a loud failure (the precondition
        documented on select_random/select_top)."""
        import go_libp2p_pubsub_tpu.ops.selection as sel
        monkeypatch.setattr(sel, "CHECK_COUNT_BOUND", True)
        jax.clear_caches()   # the flag is read at trace time (see its doc)
        try:
            keys = jnp.array([[4.0, 3.0, 2.0, 1.0]])
            mask = jnp.ones((1, 4), bool)
            with pytest.raises(Exception, match="max_count"):
                out = _select_by_keys(keys, mask, jnp.array([3]), max_count=2,
                                      mode="iter")
                jax.block_until_ready(out)
            # in-bound counts pass through the guard untouched
            ok = _select_by_keys(keys, mask, jnp.array([2]), max_count=2,
                                 mode="iter")
            assert int(jnp.sum(ok)) == 2
        finally:
            # purge guard-instrumented traces so the rest of the session
            # dispatches guard-free code again
            jax.clear_caches()

    def test_resolver_policy(self):
        # iter requires a static bound well under K
        assert resolve_selection_mode("iter", 16, None) in ("ranks", "sort")
        assert resolve_selection_mode("iter", 16, 16) in ("ranks", "sort")
        assert resolve_selection_mode("iter", 16, 6) == "iter"
        if jax.default_backend() == "cpu":
            # cpu auto prefers iter only when bounded
            assert resolve_selection_mode("auto", 48, 12) == "iter"
            assert resolve_selection_mode("auto", 48, None) == "sort"
        else:
            assert resolve_selection_mode("auto", 48, 12) == "ranks"


class TestEngineTrajectoryParity:
    @pytest.mark.parametrize("router", ["gossipsub", "randomsub"])
    def test_full_ticks_identical(self, router):
        from go_libp2p_pubsub_tpu.sim.engine import run

        n, k = 192, 8
        cfg0 = SimConfig(n_peers=n, k_slots=k, n_topics=2, msg_window=16,
                         publishers_per_tick=3, scoring_enabled=True,
                         router=router)
        topo = topology.sparse(n, k, degree=5, seed=7)
        tp = default_topic_params(2)
        sub = np.ones((n, 2), bool)
        outs = []
        for mode in MODES:
            cfg = dataclasses.replace(cfg0, selection_mode=mode)
            st = init_state(cfg, topo, subscribed=sub.copy())
            st = run(st, cfg, tp, jax.random.PRNGKey(11), 6)
            st.tick.block_until_ready()
            outs.append(st)
        for mode, st in zip(MODES[1:], outs[1:]):
            for field, a, b in zip(outs[0]._fields, outs[0], st):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b),
                    err_msg=f"{router}/{mode}: state.{field} diverged")
