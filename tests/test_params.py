"""Parameter validation matrix tests.

Scenario structure mirrors the reference's score_params_test.go (atomic vs
selective validation, legal/illegal combinations) without porting its code.
"""

import math

import pytest

from go_libp2p_pubsub_tpu.core.params import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
    score_parameter_decay,
)


def test_default_gossipsub_params():
    p = GossipSubParams()
    assert (p.d, p.dlo, p.dhi, p.dscore, p.dout) == (6, 5, 12, 4, 2)
    assert p.history_length == 5 and p.history_gossip == 3
    assert p.heartbeat_interval == 1.0
    assert p.prune_backoff == 60.0
    assert p.max_ihave_length == 5000


def test_thresholds_valid():
    PeerScoreThresholds(
        gossip_threshold=-1, publish_threshold=-2, graylist_threshold=-3,
        accept_px_threshold=10, opportunistic_graft_threshold=2,
    ).validate()


@pytest.mark.parametrize("kw", [
    dict(gossip_threshold=1),
    dict(publish_threshold=1),
    dict(gossip_threshold=-1, publish_threshold=-0.5),  # publish > gossip
    dict(gossip_threshold=-1, publish_threshold=-2, graylist_threshold=-1.5),
    dict(accept_px_threshold=-1),
    dict(opportunistic_graft_threshold=-1),
    dict(gossip_threshold=math.nan),
    dict(gossip_threshold=-1, publish_threshold=-2, graylist_threshold=-math.inf),
])
def test_thresholds_invalid(kw):
    with pytest.raises(ValueError):
        PeerScoreThresholds(**kw).validate()


def test_thresholds_skip_atomic():
    # with skip_atomic_validation, untouched groups are not validated
    PeerScoreThresholds(skip_atomic_validation=True).validate()
    PeerScoreThresholds(skip_atomic_validation=True, accept_px_threshold=5).validate()
    with pytest.raises(ValueError):
        PeerScoreThresholds(skip_atomic_validation=True, accept_px_threshold=-5).validate()


def _valid_topic_params() -> TopicScoreParams:
    return TopicScoreParams(
        topic_weight=1,
        time_in_mesh_weight=0.01, time_in_mesh_quantum=1.0, time_in_mesh_cap=10,
        first_message_deliveries_weight=1, first_message_deliveries_decay=0.5,
        first_message_deliveries_cap=10,
        mesh_message_deliveries_weight=-1, mesh_message_deliveries_decay=0.5,
        mesh_message_deliveries_cap=10, mesh_message_deliveries_threshold=5,
        mesh_message_deliveries_window=0.01, mesh_message_deliveries_activation=1.0,
        mesh_failure_penalty_weight=-1, mesh_failure_penalty_decay=0.5,
        invalid_message_deliveries_weight=-1, invalid_message_deliveries_decay=0.5,
    )


def test_topic_params_valid():
    _valid_topic_params().validate()


@pytest.mark.parametrize("field,value", [
    ("topic_weight", -1),
    ("time_in_mesh_weight", -1),
    ("time_in_mesh_quantum", 0),
    ("time_in_mesh_cap", -3),
    ("first_message_deliveries_weight", -1),
    ("first_message_deliveries_decay", 2),
    ("first_message_deliveries_cap", -3),
    ("mesh_message_deliveries_weight", 1),
    ("mesh_message_deliveries_decay", 2),
    ("mesh_message_deliveries_cap", -3),
    ("mesh_message_deliveries_threshold", -3),
    ("mesh_message_deliveries_window", -1),
    ("mesh_message_deliveries_activation", 0.5),
    ("mesh_failure_penalty_weight", 1),
    ("mesh_failure_penalty_decay", 2),
    ("invalid_message_deliveries_weight", 1),
    ("invalid_message_deliveries_decay", 2),
    ("invalid_message_deliveries_decay", math.nan),
])
def test_topic_params_invalid(field, value):
    tp = _valid_topic_params()
    setattr(tp, field, value)
    with pytest.raises(ValueError):
        tp.validate()


def test_topic_params_selective():
    # zeroed groups skipped in selective mode
    TopicScoreParams(skip_atomic_validation=True).validate()
    tp = TopicScoreParams(skip_atomic_validation=True, first_message_deliveries_weight=1)
    with pytest.raises(ValueError):  # group touched -> full group validation
        tp.validate()
    tp.first_message_deliveries_decay = 0.5
    tp.first_message_deliveries_cap = 10
    tp.validate()


def test_peer_score_params():
    p = PeerScoreParams(
        app_specific_score=lambda pid: 0.0,
        decay_interval=1.0, decay_to_zero=0.01,
        ip_colocation_factor_weight=-1, ip_colocation_factor_threshold=1,
        behaviour_penalty_weight=-1, behaviour_penalty_decay=0.5,
    )
    p.validate()
    with pytest.raises(ValueError):
        PeerScoreParams(decay_interval=1.0, decay_to_zero=0.01).validate()  # missing app score
    # skip_atomic fills in a default app score
    ps = PeerScoreParams(skip_atomic_validation=True)
    ps.validate()
    assert ps.app_specific_score("x") == 0.0
    with pytest.raises(ValueError):
        PeerScoreParams(app_specific_score=lambda pid: 0.0, decay_interval=0.5,
                        decay_to_zero=0.01).validate()
    with pytest.raises(ValueError):
        PeerScoreParams(app_specific_score=lambda pid: 0.0, decay_interval=1.0,
                        decay_to_zero=0.01, ip_colocation_factor_weight=-1).validate()
    with pytest.raises(ValueError):
        PeerScoreParams(app_specific_score=lambda pid: 0.0, decay_interval=1.0,
                        decay_to_zero=0.01, topic_score_cap=-1).validate()
    p.topics["bad"] = TopicScoreParams(topic_weight=-1)
    with pytest.raises(ValueError):
        p.validate()


def test_score_parameter_decay():
    # decaying over 10 ticks to 0.01: factor = 0.01^(1/10)
    assert abs(score_parameter_decay(10.0) - 0.01 ** 0.1) < 1e-12
    assert abs(score_parameter_decay(1.0) - 0.01) < 1e-12
