"""The measured cost-model dispatch layer (ops/dispatch.py): the shipped
default table parses, covers every (op, backend) key, dispatch is
deterministic for a fixed table + shape, quarantine markers bind, and —
the migration contract — CPU ``auto`` resolutions at the bench shapes
match the legacy static rules exactly (so the dispatched choice can only
match or beat the old resolution on the 1k/10k bench configs)."""

import json

import jax
import jax.numpy as jnp
import pytest

from go_libp2p_pubsub_tpu.ops import dispatch as dp


@pytest.fixture(autouse=True)
def _fresh_cache(monkeypatch):
    monkeypatch.delenv("GRAFT_DISPATCH_TABLE", raising=False)
    dp.clear_table_cache()
    yield
    dp.clear_table_cache()


NOMINAL = {
    "edge_permute": dict(n=10_000, k=32),
    "words": dict(w=2, n=10_000, k=32),
    "edge_packed": dict(n=10_000, k=32, b=4),
    "hop": dict(w=2, n=10_000, k=32),
    "emit": dict(w=2, n=10_000, k=32),
    "selection": dict(k=32, max_count=12),
}


class TestShippedTable:
    def test_parses_and_versioned(self):
        table = dp.load_table()
        assert table["version"] >= 1
        assert {"cpu", "tpu"} <= set(table["platforms"])

    def test_covers_every_op_backend_key(self):
        """Every (op, backend) pair must yield a non-empty ranking whose
        members are known formulations — the tier-1 coverage gate the
        CI satellite asks for."""
        for backend in ("cpu", "tpu", "default"):
            for op, forms in dp.OPS.items():
                ranked = dp.choose(op, backend=backend, **NOMINAL[op])
                assert ranked, (op, backend)
                assert set(ranked) <= set(forms), (op, backend, ranked)

    def test_quarantined_excluded_from_auto_ranking(self):
        table = dp.load_table()
        for backend, entry in table["platforms"].items():
            for op, losers in entry.get("quarantined", {}).items():
                ranked = dp.choose(op, backend=backend, **NOMINAL[op])
                assert not set(ranked) & set(losers), (backend, op, ranked)

    def test_dispatch_deterministic(self):
        """Fixed table + shape => identical ranking, across repeated
        calls AND across a cache flush (a reload must not reorder)."""
        first = {(op, b): dp.choose(op, backend=b, **NOMINAL[op])
                 for op in dp.OPS for b in ("cpu", "tpu")}
        dp.clear_table_cache()
        again = {(op, b): dp.choose(op, backend=b, **NOMINAL[op])
                 for op in dp.OPS for b in ("cpu", "tpu")}
        assert first == again


class TestCpuParityWithLegacyStatic:
    """The dispatched CPU choice must equal the legacy static rule at the
    bench shapes (1k: N=1024 K=32; 10k beacon: N=10000 K=48 T=9) — the
    acceptance bar that the dispatched choice matches or beats the old
    resolution on the 1k and 10k bench configs."""

    def test_gather_families(self):
        from go_libp2p_pubsub_tpu.ops.permgather import (
            resolve_edge_packed_mode,
            resolve_mode,
            resolve_words_mode,
        )
        assert jax.default_backend() == "cpu"
        for n, k, t in ((1024, 32, 1), (10_000, 48, 9)):
            w = 2
            assert resolve_mode("auto", jnp.uint32, n, k,
                                have_sort_key=True) == "scalar"
            assert resolve_mode("auto", jnp.uint32, n, k) == "scalar"
            assert resolve_words_mode("auto", w, n, k,
                                      have_sort_key=True) == "scalar"
            assert resolve_edge_packed_mode("auto", n, k, 2 * t) == "scalar"

    def test_hop_emit_and_selection(self):
        from go_libp2p_pubsub_tpu.ops.hopkernel import (
            resolve_emit_mode,
            resolve_hop_mode,
        )
        from go_libp2p_pubsub_tpu.ops.selection import resolve_selection_mode
        from go_libp2p_pubsub_tpu.sim.config import SimConfig

        for n, k in ((1024, 32), (10_000, 48)):
            cfg = SimConfig(n_peers=n, k_slots=k)
            assert resolve_hop_mode("auto", cfg, 2, n, k) == "xla"
            assert resolve_emit_mode("auto", 2, n, k) == "xla"
        # the legacy CPU rule: iter while 2*max_count <= k, else sort
        assert resolve_selection_mode("auto", 48, 12) == "iter"
        assert resolve_selection_mode("auto", 48, 24) == "iter"
        assert resolve_selection_mode("auto", 48, 25) == "sort"
        assert resolve_selection_mode("auto", 48, None) == "sort"


class TestTpuRankingConservative:
    """Under the shipped conservative table (streamed one-hot pricing),
    TPU auto keeps the measured sort-era winners — mxu stays an explicit
    mode until a calibrated table promotes it."""

    def test_tpu_gather_families(self, monkeypatch):
        import go_libp2p_pubsub_tpu.ops.permgather as pg
        monkeypatch.setattr(pg.jax, "default_backend", lambda: "tpu")
        assert pg.resolve_mode("auto", jnp.uint32, 100_000, 32,
                               have_sort_key=True) == "sort"
        assert pg.resolve_mode("auto", jnp.uint32, 100_000, 32) == "scalar"
        assert pg.resolve_words_mode("auto", 2, 100_000, 32,
                                     have_sort_key=True) == "sort"
        assert pg.resolve_words_mode("auto", 2, 100_000, 32) == "rows"
        assert pg.resolve_edge_packed_mode("auto", 100_000, 32, 2) == "sort"

    def test_tpu_hop_emit_selection(self, monkeypatch):
        import go_libp2p_pubsub_tpu.ops.hopkernel as hk
        import go_libp2p_pubsub_tpu.ops.selection as sel
        from go_libp2p_pubsub_tpu.sim.config import SimConfig
        monkeypatch.setattr(hk.jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(sel.jax, "default_backend", lambda: "tpu")
        cfg = SimConfig(n_peers=102_400, k_slots=32)
        assert hk.resolve_hop_mode("auto", cfg, 2, 102_400, 32) == "xla"
        assert hk.resolve_emit_mode("auto", 2, 102_400, 32) == "xla"
        # legacy TPU rule was ranks UNCONDITIONALLY — incl. large K and
        # small max_count, where the analytic iter estimate would
        # otherwise win (its serial-pass cost is unmeasured on chip, so
        # the shipped table quarantines iter/sort from TPU auto)
        for k in (16, 32, 48, 64, 96, 128):
            for mc in (1, 4, 12, None):
                assert sel.resolve_selection_mode("auto", k, mc) \
                    == "ranks", (k, mc)


class TestCalibratedTableOverride:
    """GRAFT_DISPATCH_TABLE promotion path: a measured table that times
    mxu under sort flips the TPU auto choice — the one-env-flip product
    of ROADMAP item 2 — and a quarantine marker in the loaded table
    excludes a formulation from auto without touching explicit modes."""

    def _write(self, tmp_path, measured=(), quarantined=None):
        table = json.loads(json.dumps(dp.load_table()))     # deep copy
        entry = table["platforms"]["tpu"]
        entry["measured"] = list(measured)
        if quarantined is not None:
            entry["quarantined"] = quarantined
        path = tmp_path / "calibrated.json"
        path.write_text(json.dumps(table))
        return str(path)

    def test_measured_bucket_promotes_mxu(self, tmp_path, monkeypatch):
        import go_libp2p_pubsub_tpu.ops.permgather as pg
        path = self._write(tmp_path, measured=[
            {"op": "words", "shape": {"w": 2, "n": 102_400, "k": 32},
             "ms": {"sort": 9.0, "mxu": 0.8, "rows": 24.7}}])
        monkeypatch.setenv("GRAFT_DISPATCH_TABLE", path)
        dp.clear_table_cache()
        monkeypatch.setattr(pg.jax, "default_backend", lambda: "tpu")
        assert pg.resolve_words_mode("auto", 2, 102_400, 32,
                                     have_sort_key=True) == "mxu"
        # a far-off shape does not match the bucket: analytic ranking
        assert pg.resolve_words_mode("auto", 2, 1024, 32,
                                     have_sort_key=True) == "sort"

    def test_quarantine_marker_binds(self, tmp_path, monkeypatch):
        import go_libp2p_pubsub_tpu.ops.permgather as pg
        table = json.loads(json.dumps(dp.load_table()))
        table["platforms"]["tpu"]["quarantined"]["edge_packed"] = ["sort"]
        path = tmp_path / "q.json"
        path.write_text(json.dumps(table))
        monkeypatch.setenv("GRAFT_DISPATCH_TABLE", str(path))
        dp.clear_table_cache()
        monkeypatch.setattr(pg.jax, "default_backend", lambda: "tpu")
        # auto avoids the quarantined sort; explicit sort still resolves
        assert pg.resolve_edge_packed_mode("auto", 100_000, 32, 2) != "sort"
        assert pg.resolve_edge_packed_mode("sort", 100_000, 32, 2) == "sort"

    def test_malformed_table_raises(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"platforms": {"cpu": {}}}))
        with pytest.raises(dp.DispatchTableError):
            dp.load_table(str(bad))


class TestResolvedFormulations:
    def test_bench_record_stamp(self):
        """resolved_formulations covers every dispatched seam with a
        concrete (non-auto) formulation — what bench.py stamps into
        records."""
        from go_libp2p_pubsub_tpu.sim.config import SimConfig
        cfg = SimConfig(n_peers=1024, k_slots=32)
        got = dp.resolved_formulations(cfg)
        assert set(got) == set(dp.OPS)
        for op, form in got.items():
            assert form in dp.OPS[op] and form != "auto", (op, form)
