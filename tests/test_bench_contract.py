"""The driver contract for bench.py: every result line is standalone JSON
with metric/value/unit/vs_baseline keys, the headline scenario runs FIRST
(banked before anything can time out — losing it to a timeout cost round 5
its record, VERDICT r5) and its line is RE-EMITTED last so a single-line
parse of stdout still picks it up; BENCH_TOTAL_BUDGET degrades repeats
3->1 per config rather than dropping configs."""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env, timeout=900):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               **extra_env)
    t0 = time.perf_counter()
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    elapsed = time.perf_counter() - t0
    lines = [ln for ln in res.stdout.splitlines() if ln.startswith("{")]
    recs = [json.loads(ln) for ln in lines]
    return res, [r for r in recs if "metric" in r], recs, elapsed


def _is_headline(metric: str) -> bool:
    # BENCH_N=256 -> label "0k_default" (256 // 1000)
    return metric.startswith("network_heartbeats_per_sec@0k_default")


def test_bench_emits_driver_parseable_json():
    res, metrics, _, _ = _run_bench({
        "BENCH_SCENARIOS": "1k_single_topic,headline",
        "BENCH_N": "256", "BENCH_TICKS": "3"}, timeout=480)
    assert res.returncode == 0, res.stderr[-500:]
    # headline banked FIRST + re-emitted LAST around the other config
    assert len(metrics) == 3
    for m in metrics:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(m)
        assert m["unit"] == "heartbeats/s"
        assert m["value"] > 0, m
    assert _is_headline(metrics[0]["metric"])
    assert _is_headline(metrics[-1]["metric"])
    assert metrics[0] == metrics[-1]            # the re-emit is verbatim


def test_full_suite_fits_budget_at_reduced_n():
    """All 26 configs at reduced N must complete, rc=0, within
    BENCH_TOTAL_BUDGET on CPU — the structural guarantee that the r5
    timeout (rc=124, headline line missing) cannot recur. Every metric
    line must be present, the 100k_default headline first AND last.
    GRAFT_FLEET_SIZE=4 keeps the batched-fleet line (ISSUE 7) at
    contract scale; the frontier family (ISSUE 8), the tracing-overhead
    pair (ISSUE 9), the attack pair (ISSUE 10), the heavy-tail family
    (ISSUE 15), the row-sharded bucketed family (ISSUE 16) and the
    live-command-plane pair (ISSUE 19) and the verdict-plane pair
    (ISSUE 20) ride the same BENCH_MAX_N cap
    with capped-N labels — reduced runs can never bank under the full
    labels."""
    budget = 900
    res, metrics, _, elapsed = _run_bench({
        "BENCH_N": "256", "BENCH_MAX_N": "256", "BENCH_TICKS": "2",
        "BENCH_REPEATS": "1", "BENCH_TOTAL_BUDGET": str(budget),
        "GRAFT_FLEET_SIZE": "4"},
        timeout=budget + 120)
    assert res.returncode == 0, res.stderr[-500:]
    assert elapsed < budget, f"suite blew the budget: {elapsed:.0f}s"
    # 30 configs + the headline re-emit
    assert len(metrics) == 31, [m["metric"] for m in metrics]
    for m in metrics:
        assert m["value"] > 0, m
        # every record carries the memory accounting (ISSUE 8 satellite)
        assert m["state_nbytes"] > 0 and "memory_source" in m, m
    assert _is_headline(metrics[0]["metric"])
    assert _is_headline(metrics[-1]["metric"])
    names = {m["metric"].split("@")[1].split("[")[0] for m in metrics}
    assert names == {"0k_default", "1k_single_topic", "fleet_4x0k",
                     "10k_beacon", "50k_churn_gater_px", "100k_sybil20",
                     "100k_floodsub", "100k_randomsub",
                     "100k_gossipsub_sweep",
                     "frontier_250k_capped_0k", "frontier_500k_capped_0k",
                     "frontier_1m_capped_0k",
                     "frontier_4m_capped_0k", "frontier_10m_capped_0k",
                     "telemetry_1k_capped_0k", "telemetry_10k_capped_0k",
                     "supervised_overlap_1k_capped_0k",
                     "supervised_overlap_10k_capped_0k",
                     "eclipse_50k_capped_0k", "flashcrowd_50k_capped_0k",
                     "powerlaw_100k_capped_0k", "powerlaw_1m_capped_0k",
                     "powerlaw_10m_capped_0k",
                     "heavytail_eclipse_capped_0k",
                     "powerlaw_100k_mh_capped_0k",
                     "powerlaw_10m_mh_capped_0k",
                     "ingest_1k_capped_0k", "ingest_10k_capped_0k",
                     "verdict_1k_capped_0k", "verdict_10k_capped_0k"}
    fleet = next(m for m in metrics if "fleet_4x0k" in m["metric"])
    assert fleet["fleet_size"] == 4
    assert fleet["per_member_hbps"] > 0
    # the tracing-overhead line (ISSUE 9): all four measurement legs
    # present so the PERF_MODEL table can always be rebuilt from a record
    tele = next(m for m in metrics if "telemetry_1k" in m["metric"])
    assert tele["untraced_hbps"] > 0 and tele["json_sink_hbps"] > 0
    assert tele["device_py_hbps"] > 0 and tele["batched_fsync_hbps"] > 0
    # the supervised-overlap line (ISSUE 12): all three measurement legs
    # present so PERF_MODEL's table can always be rebuilt from a record
    ovl = next(m for m in metrics
               if "supervised_overlap_1k" in m["metric"])
    assert ovl["unsupervised_hbps"] > 0 and ovl["sync_hbps"] > 0
    assert ovl["async_hbps"] > 0 and ovl["cadence_sweep"]
    # the construction-cost record (ISSUE 13): every scenario line
    # carries the host-side build wall + peak RSS next to state_nbytes,
    # including the XL frontier pair (compact storage by construction)
    xl = next(m for m in metrics if "frontier_10m" in m["metric"])
    assert xl["build_wall_s"] >= 0 and xl["build_peak_rss_bytes"] > 0
    # the live-command-plane line (ISSUE 19): all three offered loads
    # present, and the overload leg's deterministic shed travels with
    # the banked number (load past the watermark MUST shed, in-budget
    # loads must not)
    ing = next(m for m in metrics if "ingest_1k" in m["metric"])
    assert ing["unit"] == "commands/s"
    assert ing["light"]["shed"] == 0
    assert ing["overload"]["shed"] > 0
    assert ing["overload"]["applied"] + ing["overload"]["shed"] \
        == ing["overload"]["offered_total"]
    # the verdict-plane line (ISSUE 20): both A/B legs present and at
    # least one journaled verdict transition rode the banked run — the
    # in-bench parity assert already re-judged the rows full-batch
    ver = next(m for m in metrics if "verdict_1k" in m["metric"])
    assert ver["monitored_hbps"] > 0 and ver["unmonitored_hbps"] > 0
    assert ver["n_contracts"] == 3 and ver["verdict_notes"] > 0
    # the heavy-tail line (ISSUE 15): the degree shape and bucket
    # partition travel with every banked number
    pl = next(m for m in metrics if "powerlaw_100k_capped" in m["metric"])
    assert pl["degree_stats"]["n"] == 256 and pl["degree_buckets"]
    # the row-sharded bucketed line (ISSUE 16): the SHARDED execution
    # path over a real 8-device mesh, with the per-(bucket x shard)
    # byte accounting dashboards render stamped into the record
    mh = next(m for m in metrics if "powerlaw_100k_mh" in m["metric"])
    assert mh["n_devices"] == 8
    assert mh["state_nbytes_per_shard"] > 0
    assert mh["degree_stats"]["n"] == 256 and mh["sharded_route"]
    assert len(mh["bucket_shards"]) == len(mh["degree_buckets"])
    for entry, (rows, k_ceil) in zip(mh["bucket_shards"],
                                     mh["degree_buckets"]):
        assert entry["rows"] == rows and entry["k_ceil"] == k_ceil
        assert entry["neighbors"] > 0 and entry["bucket_rev"] > 0


def test_sigterm_flushes_partial_record():
    """The rc=124 empty-record class (round 5) is structurally impossible:
    a SIGTERM mid-suite flushes a {"partial": true} marker listing the
    configs completed so far, and the LAST line is still the headline
    (banked, or a headline-shaped error line marked partial)."""
    import signal
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               BENCH_SCENARIOS="1k_single_topic,10k_beacon,headline",
               BENCH_N="256", BENCH_MAX_N="256", BENCH_TICKS="2")
    p = subprocess.Popen([sys.executable, os.path.join(REPO, "bench.py")],
                         stdout=subprocess.PIPE, text=True, env=env,
                         cwd=REPO)
    lines = []
    deadline = time.time() + 600
    while time.time() < deadline:           # headline runs (banks) FIRST
        ln = p.stdout.readline()
        if not ln:
            break
        lines.append(ln.rstrip())
        if '"metric"' in ln:
            # let the parent finish banking the config (journal append +
            # completed-list update happen just after the line is relayed);
            # the next scenario needs seconds of jit compile, so this
            # cannot skid past it
            time.sleep(1.0)
            p.send_signal(signal.SIGTERM)
            break
    rest, _ = p.communicate(timeout=120)
    lines += rest.splitlines()
    assert p.returncode == 128 + signal.SIGTERM
    recs = [json.loads(ln) for ln in lines if ln.startswith("{")]
    partial = [r for r in recs if r.get("partial") and "signal" in r]
    assert len(partial) == 1 and partial[0]["signal"] == "SIGTERM"
    assert partial[0]["completed"] == ["0k_default"]
    # last line is the banked headline, verbatim
    assert _is_headline(recs[-1]["metric"]) and recs[-1]["value"] > 0


def test_journal_resume_skips_recorded_configs(tmp_path):
    """BENCH_JOURNAL makes a killed sweep complete incrementally: configs
    recorded by a previous invocation replay their journaled line verbatim
    instead of re-running."""
    journal = str(tmp_path / "bench.jsonl")
    # both invocations share the env knobs that shape a config: the
    # journal's env fingerprint must match for a record to replay
    res1, metrics1, _, _ = _run_bench({
        "BENCH_SCENARIOS": "headline", "BENCH_N": "256",
        "BENCH_MAX_N": "256", "BENCH_TICKS": "2",
        "BENCH_JOURNAL": journal}, timeout=480)
    assert res1.returncode == 0, res1.stderr[-500:]
    assert len(metrics1) == 1 and _is_headline(metrics1[0]["metric"])
    res2, metrics2, recs2, _ = _run_bench({
        "BENCH_SCENARIOS": "1k_single_topic,headline", "BENCH_N": "256",
        "BENCH_MAX_N": "256", "BENCH_TICKS": "2",
        "BENCH_JOURNAL": journal}, timeout=480)
    assert res2.returncode == 0, res2.stderr[-500:]
    skips = [r for r in recs2 if r.get("info") == "journal skip"]
    assert [s["scenario"] for s in skips] == ["0k_default"]
    # replayed verbatim (first), 1k ran fresh, headline re-emitted last
    assert len(metrics2) == 3
    assert metrics2[0] == metrics1[0] and metrics2[-1] == metrics1[0]
    assert "1k_single_topic" in metrics2[1]["metric"]
    # the fresh config was journaled too: a third run would skip both
    with open(journal) as f:
        assert len(f.readlines()) == 2
    # env drift (different BENCH_TICKS) invalidates the fingerprint: the
    # config re-runs fresh instead of replaying a line that means
    # something else
    res3, metrics3, recs3, _ = _run_bench({
        "BENCH_SCENARIOS": "headline", "BENCH_N": "256",
        "BENCH_MAX_N": "256", "BENCH_TICKS": "3",
        "BENCH_JOURNAL": journal}, timeout=480)
    assert res3.returncode == 0, res3.stderr[-500:]
    assert not [r for r in recs3 if r.get("info") == "journal skip"]
    assert metrics3[0]["ticks_per_window"] == 3


def test_exhausted_budget_degrades_repeats_not_configs():
    """With the budget already blown after the first config, every later
    config must still run (repeats degraded to 1) and the headline line
    must still be present and last — configs are never dropped."""
    res, metrics, recs, _ = _run_bench({
        "BENCH_SCENARIOS": "1k_single_topic,10k_beacon,headline",
        "BENCH_N": "256", "BENCH_MAX_N": "256", "BENCH_TICKS": "2",
        "BENCH_REPEATS": "3", "BENCH_TOTAL_BUDGET": "1"}, timeout=600)
    assert res.returncode == 0, res.stderr[-500:]
    # 3 configs + re-emit, all with real values
    assert len(metrics) == 4, [m["metric"] for m in metrics]
    for m in metrics:
        assert m["value"] > 0, m
    assert _is_headline(metrics[0]["metric"])
    assert _is_headline(metrics[-1]["metric"])
    # the headline (first, inside budget) kept its repeats; the laggards
    # were degraded to 1 and announced it
    assert metrics[0]["repeats"] == 3
    degraded = [m for m in metrics[1:-1]]
    assert all(m["repeats"] == 1 for m in degraded), degraded
    infos = [r for r in recs if r.get("info") == "budget degrade"]
    assert len(infos) == 2, infos
