"""The driver contract for bench.py: every result line is standalone JSON
with metric/value/unit/vs_baseline keys, and the headline scenario prints
LAST so a single-line parse of stdout picks it up."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_driver_parseable_json():
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               BENCH_SCENARIOS="1k_single_topic,headline",
               BENCH_N="256", BENCH_TICKS="3")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=480, env=env, cwd=REPO)
    assert res.returncode == 0, res.stderr[-500:]
    lines = [ln for ln in res.stdout.splitlines() if ln.startswith("{")]
    metrics = [json.loads(ln) for ln in lines]
    metrics = [m for m in metrics if "metric" in m]
    assert len(metrics) == 2
    for m in metrics:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(m)
        assert m["unit"] == "heartbeats/s"
        assert m["value"] > 0, m
    # headline (BENCH_N-peer default config) prints last
    assert metrics[-1]["metric"].startswith("network_heartbeats_per_sec@0k_default") or \
        metrics[-1]["metric"].startswith("network_heartbeats_per_sec@256")
