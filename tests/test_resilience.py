"""Distributed resilience plane (ISSUE 14): rank liveness & coordinated
abort, the GRAFT_CHAOS fault-injection knob, the mh_supervisor relaunch
driver, elastic cross-process-count checkpoint resume, and the
fault_flags layout versioning — capped by THE acceptance test: a real
2-process CPU run whose rank 1 is SIGKILLed mid-window, automatically
relaunched by scripts/mh_supervisor.py at a DIFFERENT process count from
the last drained checkpoint, finishing bit-exact vs the uninterrupted
single-process run.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from go_libp2p_pubsub_tpu.parallel.resilience import (  # noqa: E402
    EXIT_PEER_DEAD, ChaosPlan, PeerDeadError, RankLiveness, heartbeat_path)


# ---------------------------------------------------------------------------
# ChaosPlan: the GRAFT_CHAOS knob


class TestChaosPlan:
    def test_parse_kill_and_stall(self):
        specs = ChaosPlan.parse("kill@1:4, stall@0:2:1.5")
        assert specs == [
            {"action": "kill", "rank": 1, "tick": 4, "seconds": 0.0},
            {"action": "stall", "rank": 0, "tick": 2, "seconds": 1.5}]

    @pytest.mark.parametrize("bad", ["boom@1:2", "kill@1", "kill@x:2",
                                     "stall@0:2", "kill@1:2:3"])
    def test_parse_refuses_by_name(self, bad):
        with pytest.raises(ValueError, match="GRAFT_CHAOS"):
            ChaosPlan.parse(bad)

    def test_from_env_unset_is_none(self, monkeypatch):
        monkeypatch.delenv("GRAFT_CHAOS", raising=False)
        assert ChaosPlan.from_env(0) is None
        monkeypatch.setenv("GRAFT_CHAOS", "kill@0:3")
        assert ChaosPlan.from_env(0) is not None

    def test_rank_filter(self, tmp_path):
        fired = []
        plan = ChaosPlan(ChaosPlan.parse("kill@1:0"), rank=0,
                         run_dir=str(tmp_path),
                         kill=lambda: fired.append("kill"))
        plan.fire({"chunk_start": 5})
        assert fired == []          # the spec names rank 1, we are rank 0

    def test_fires_once_and_marker_persists_across_instances(self, tmp_path):
        fired = []
        mk = lambda: ChaosPlan(ChaosPlan.parse("kill@0:2"), rank=0,
                               run_dir=str(tmp_path),
                               kill=lambda: fired.append("kill"))
        plan = mk()
        plan.fire({"chunk_start": 0})       # below the armed tick
        assert fired == []
        plan.fire({"chunk_start": 2})
        plan.fire({"chunk_start": 4})       # same spec, already fired
        assert fired == ["kill"]
        # a RELAUNCHED rank (fresh process, same run dir) must not refire:
        # the durable marker is what lets mh_supervisor relaunch a
        # chaos-killed group without the chaos killing it again
        mk().fire({"chunk_start": 2})
        assert fired == ["kill"]
        markers = [n for n in os.listdir(tmp_path) if n.endswith(".fired")]
        assert markers == ["chaos_kill_r0_t2.fired"]

    def test_stall_sleeps(self, tmp_path):
        slept = []
        plan = ChaosPlan(ChaosPlan.parse("stall@0:1:7.5"), rank=0,
                         run_dir=str(tmp_path), sleep=slept.append)
        plan.fire({"chunk_start": 3})
        assert slept == [7.5]


# ---------------------------------------------------------------------------
# RankLiveness: heartbeats, dead-peer detection, the watchdog


def _mk_liveness(run_dir, rank, nproc, **kw):
    kw.setdefault("peer_timeout_s", 0.3)
    kw.setdefault("beat_interval_s", 0.05)
    kw.setdefault("startup_grace_s", 0.15)
    kw.setdefault("abort_grace_s", 0.1)
    kw.setdefault("hard_exit", lambda code: None)
    return RankLiveness(str(run_dir), rank, nproc, **kw)


class TestRankLiveness:
    def test_beat_writes_progress(self, tmp_path):
        lv = _mk_liveness(tmp_path, 0, 1)
        lv.beat(tick=7, chunk=3)
        with open(heartbeat_path(str(tmp_path), 0)) as f:
            d = json.load(f)
        assert (d["rank"], d["tick"], d["chunk"], d["done"]) == (0, 7, 3,
                                                                 False)

    def test_missing_peer_after_grace(self, tmp_path):
        lv = _mk_liveness(tmp_path, 0, 2)
        lv.beat()
        assert lv.dead_peers() == []        # still inside startup grace
        time.sleep(0.2)
        with pytest.raises(PeerDeadError, match="rank 1"):
            lv.check()

    def test_stale_peer_then_refresh(self, tmp_path):
        lv = _mk_liveness(tmp_path, 0, 2)
        peer = _mk_liveness(tmp_path, 1, 2)
        peer.beat(tick=1)
        lv.check()                          # fresh peer: healthy
        time.sleep(0.4)                     # > peer_timeout_s
        with pytest.raises(PeerDeadError, match="rank 1.*stale"):
            lv.check()
        peer.beat(tick=2)                   # peer came back
        lv.check()

    def test_finished_peer_is_never_dead(self, tmp_path):
        lv = _mk_liveness(tmp_path, 0, 2)
        peer = _mk_liveness(tmp_path, 1, 2)
        peer.finish()
        time.sleep(0.4)                     # stale by age, but done=True
        lv.check()

    def test_error_names_the_relaunch_supervisor(self, tmp_path):
        lv = _mk_liveness(tmp_path, 0, 2, startup_grace_s=0.0)
        with pytest.raises(PeerDeadError, match="mh_supervisor"):
            lv.check()

    def test_watchdog_hard_exits_when_blocked(self, tmp_path):
        # the backstop for a rank BLOCKED inside a collective: the beater
        # thread sights the dead peer and, after abort_grace_s, calls
        # hard_exit(EXIT_PEER_DEAD) — injected here so the test survives
        exits = []
        lv = _mk_liveness(tmp_path, 0, 2, startup_grace_s=0.0,
                          hard_exit=exits.append)
        lv.start()
        try:
            deadline = time.time() + 3.0
            while not exits and time.time() < deadline:
                time.sleep(0.05)
        finally:
            lv.stop()
        assert exits and exits[0] == EXIT_PEER_DEAD

    def test_watchdog_keeps_own_heartbeat_fresh(self, tmp_path):
        lv = _mk_liveness(tmp_path, 0, 1)
        lv.start()
        try:
            time.sleep(0.2)
            with open(heartbeat_path(str(tmp_path), 0)) as f:
                age = time.time() - json.load(f)["wall"]
            assert age < 0.2                # refreshed by the beater
        finally:
            lv.stop()

    def test_from_env_reads_knobs(self, tmp_path, monkeypatch):
        monkeypatch.setenv("GRAFT_MH_PEER_TIMEOUT_S", "11")
        monkeypatch.setenv("GRAFT_MH_ABORT_GRACE_S", "4")
        lv = RankLiveness.from_env(str(tmp_path), 1, 3)
        assert (lv.peer_timeout_s, lv.abort_grace_s,
                lv.rank, lv.num_processes) == (11.0, 4.0, 1, 3)


# ---------------------------------------------------------------------------
# fault_flags layout versioning (the PR 10 decode hazard, closed by name)


class TestFlagsVersion:
    def test_current_version_decodes(self):
        from go_libp2p_pubsub_tpu.sim.invariants import (
            FAULT_ECLIPSE, FLAGS_VERSION, decode_flags)
        assert decode_flags(FAULT_ECLIPSE,
                            flags_version=FLAGS_VERSION) == ["eclipse"]
        # None (a pre-versioning artifact) still decodes, as before
        assert decode_flags(FAULT_ECLIPSE) == ["eclipse"]

    def test_old_version_refused_by_name(self):
        from go_libp2p_pubsub_tpu.sim.invariants import decode_flags
        # a v1 word's bits 8-9 were violations; decoding them as
        # FAULT_CENSOR/FAULT_WAVE would be silent misreading
        with pytest.raises(ValueError, match="flags_version"):
            decode_flags(1 << 8, flags_version=1)

    def test_journal_header_stamps_version(self, tmp_path):
        from go_libp2p_pubsub_tpu.sim.invariants import FLAGS_VERSION
        from go_libp2p_pubsub_tpu.sim.scenarios import single_topic_1k
        from go_libp2p_pubsub_tpu.sim.telemetry import (
            HealthJournal, read_journal)
        cfg, _tp, _st = single_topic_1k(n_peers=64, k_slots=8, degree=4)
        path = str(tmp_path / "health.jsonl")
        j = HealthJournal(path)
        j.header(cfg, scenario="x")
        j.close()
        run = read_journal(path)["runs"][-1]
        assert run["flags_version"] == FLAGS_VERSION

    def test_crash_dump_stamps_version(self, tmp_path):
        import jax

        from go_libp2p_pubsub_tpu.sim.invariants import FLAGS_VERSION
        from go_libp2p_pubsub_tpu.sim.scenarios import single_topic_1k
        from go_libp2p_pubsub_tpu.sim.supervisor import (
            SupervisorConfig, SupervisorReport, _write_crash_dump)
        cfg, tp, st = single_topic_1k(n_peers=64, k_slots=8, degree=4)
        sup = SupervisorConfig(crash_dir=str(tmp_path / "crash"))
        dump = _write_crash_dump(
            sup, cfg, st, jax.random.split(jax.random.PRNGKey(0), 2),
            0, 0, 2, 4, RuntimeError("boom"), SupervisorReport())
        with open(os.path.join(dump, "crash.json")) as f:
            assert json.load(f)["flags_version"] == FLAGS_VERSION

    def test_replay_refuses_old_dump_by_name(self, tmp_path):
        from scripts.replay_crash import replay
        dump = tmp_path / "crash_old"
        dump.mkdir()
        (dump / "crash.json").write_text(json.dumps(
            {"flags_version": 1, "scenario": "1k_single_topic",
             "fault_flags": 1 << 8}))
        with pytest.raises(SystemExit, match="flags_version"):
            replay(str(dump))


# ---------------------------------------------------------------------------
# Elastic checkpoint: save at P, restore/re-slice at P'


def _frontier_state():
    from go_libp2p_pubsub_tpu.parallel import multihost
    from go_libp2p_pubsub_tpu.sim import scenarios
    cfg, tp, topo, subscribed = scenarios.frontier_spec(128)
    full = multihost.init_state_local(cfg, topo, 0, 1,
                                      subscribed=subscribed)
    return cfg, tp, full


class TestElasticCheckpoint:
    def test_sidecar_stamps_processes_and_meta_reads_it(self, tmp_path):
        from go_libp2p_pubsub_tpu.sim import checkpoint
        cfg, _tp, full = _frontier_state()
        path = str(tmp_path / "ck.npz")
        checkpoint.save(path, full, cfg=cfg, processes=2)
        meta = checkpoint.sidecar_meta(path)
        assert meta["processes"] == "2"
        assert meta["fingerprint"] == checkpoint.config_fingerprint(cfg)
        assert checkpoint.sidecar_meta(str(tmp_path / "nope.npz")) == {}

    def test_cross_process_count_restore_bit_exact(self, tmp_path):
        # save "at P=2" (the gathered state is host-complete either way),
        # restore at P'=1: bit-exact; then re-slice the restored state at
        # P'=4 and reassemble: the elastic path end to end
        from go_libp2p_pubsub_tpu.parallel import multihost
        from go_libp2p_pubsub_tpu.sim import checkpoint
        from go_libp2p_pubsub_tpu.sim.state import SimState, state_spec
        cfg, _tp, full = _frontier_state()
        path = str(tmp_path / "ck.npz")
        checkpoint.save(path, full, cfg=cfg, processes=2)
        got = checkpoint.restore(path, full, cfg=cfg)   # P'=1: no refusal
        for f in SimState._fields:
            assert np.array_equal(np.asarray(getattr(got, f)),
                                  np.asarray(getattr(full, f))), f
        spec = state_spec(cfg)
        slices = [multihost.local_rows_state(got, cfg, r, 4)
                  for r in range(4)]
        for f in SimState._fields:
            want = np.asarray(getattr(full, f))
            if spec[f][2]:      # peer-major: the rank slices concat back
                assert np.array_equal(np.concatenate(
                    [np.asarray(getattr(s, f)) for s in slices]), want), f
            else:               # replicated: every rank holds the whole
                for s in slices:
                    assert np.array_equal(np.asarray(getattr(s, f)),
                                          want), f

    def test_non_dividing_process_count_refused_by_name(self, tmp_path):
        from go_libp2p_pubsub_tpu.parallel import multihost
        cfg, _tp, full = _frontier_state()
        with pytest.raises(ValueError, match="divide evenly"):
            multihost.local_rows_state(full, cfg, 0, 3)     # 128 % 3 != 0

    def test_drifted_layout_still_refused_by_name(self, tmp_path):
        from go_libp2p_pubsub_tpu.parallel import multihost
        from go_libp2p_pubsub_tpu.sim import checkpoint, scenarios
        cfg, _tp, full = _frontier_state()
        path = str(tmp_path / "ck.npz")
        checkpoint.save(path, full)                 # no cfg: shape check
        cfg2, _tp2, topo2, sub2 = scenarios.frontier_spec(256)
        like2 = multihost.init_state_local(cfg2, topo2, 0, 1,
                                           subscribed=sub2)
        with pytest.raises(ValueError, match="checkpoint field"):
            checkpoint.restore(path, like2)

    def test_cross_precision_still_refused_by_name(self, tmp_path):
        from go_libp2p_pubsub_tpu.sim import checkpoint
        cfg, _tp, full = _frontier_state()
        path = str(tmp_path / "ck.npz")
        checkpoint.save(path, full, cfg=cfg, processes=2)
        compact = dataclasses.replace(cfg, state_precision="compact")
        with pytest.raises(ValueError, match="state_precision"):
            checkpoint.restore(path, full, cfg=compact)

    def test_knob_drift_still_refused(self, tmp_path):
        from go_libp2p_pubsub_tpu.sim import checkpoint
        cfg, _tp, full = _frontier_state()
        path = str(tmp_path / "ck.npz")
        checkpoint.save(path, full, cfg=cfg, processes=2)
        drifted = dataclasses.replace(cfg, dhi=cfg.dhi + 1)
        with pytest.raises(ValueError, match="different config"):
            checkpoint.restore(path, full, cfg=drifted)


# ---------------------------------------------------------------------------
# Supervisor integration: initial_degrade (the rank-symmetric rung) and
# the liveness hook


def _tiny_run(n_ticks=6):
    import jax

    from go_libp2p_pubsub_tpu.sim.scenarios import single_topic_1k
    cfg, tp, st = single_topic_1k(n_peers=64, k_slots=8, degree=4)
    return cfg, tp, st, jax.random.PRNGKey(3), n_ticks


class TestSupervisorResilience:
    def test_initial_degrade_is_trajectory_neutral(self):
        from go_libp2p_pubsub_tpu.sim.engine import run
        from go_libp2p_pubsub_tpu.sim.supervisor import (
            SupervisorConfig, supervised_run)
        cfg, tp, st, key, n_ticks = _tiny_run()
        ref = run(st, cfg, tp, key, n_ticks)
        out, rep = supervised_run(
            st, cfg, tp, key, n_ticks,
            SupervisorConfig(chunk_ticks=2, initial_degrade=2))
        for a, b in zip(out, ref):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        assert rep.degrade_level >= 2
        assert [e for e in rep.events if e["event"] == "degrade"]

    def test_initial_degrade_from_env(self, monkeypatch):
        from go_libp2p_pubsub_tpu.sim.supervisor import SupervisorConfig
        monkeypatch.setenv("GRAFT_MH_RUNG", "3")
        assert SupervisorConfig.from_env().initial_degrade == 3

    def test_dead_peer_aborts_at_chunk_boundary(self, tmp_path):
        # single-process stand-in for the multi-rank abort: a liveness
        # that claims 2 processes with no peer file trips check() at the
        # pre-dispatch safe point; with retries exhausted the run crashes
        # (dump written) instead of dispatching into dead collectives
        from go_libp2p_pubsub_tpu.sim.supervisor import (
            SupervisorConfig, SupervisorCrash, supervised_run)
        cfg, tp, st, key, n_ticks = _tiny_run()
        lv = _mk_liveness(tmp_path, 0, 2, startup_grace_s=0.0)
        sup = SupervisorConfig(
            chunk_ticks=2, max_retries=0, backoff_base_s=0.0,
            sleep=lambda s: None, liveness=lv,
            crash_dir=str(tmp_path / "crash"))
        with pytest.raises(SupervisorCrash):
            supervised_run(st, cfg, tp, key, n_ticks, sup)

    def test_healthy_liveness_beats_to_completion(self, tmp_path):
        from go_libp2p_pubsub_tpu.sim.engine import run
        from go_libp2p_pubsub_tpu.sim.supervisor import (
            SupervisorConfig, supervised_run)
        cfg, tp, st, key, n_ticks = _tiny_run()
        lv = _mk_liveness(tmp_path, 0, 1)
        out, _rep = supervised_run(
            st, cfg, tp, key, n_ticks,
            SupervisorConfig(chunk_ticks=2, liveness=lv))
        ref = run(st, cfg, tp, key, n_ticks)
        for a, b in zip(out, ref):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        with open(heartbeat_path(str(tmp_path), 0)) as f:
            assert json.load(f)["tick"] == n_ticks


# ---------------------------------------------------------------------------
# mh_supervisor helpers (jax-free parent)


class TestMhSupervisorHelpers:
    def test_parse_procs(self):
        from scripts.mh_supervisor import parse_procs
        assert parse_procs("8,8,4") == [8, 8, 4]
        assert parse_procs("2") == [2]
        for bad in ("", "2,x", "0", "-1,2"):
            with pytest.raises(ValueError, match="--procs"):
                parse_procs(bad)

    def test_newest_ckpt_tick(self, tmp_path):
        from scripts.mh_supervisor import _newest_ckpt_tick
        assert _newest_ckpt_tick(str(tmp_path / "nope")) is None
        d = tmp_path / "ckpt"
        d.mkdir()
        assert _newest_ckpt_tick(str(d)) is None
        (d / "ckpt_t000000002.npz").touch()
        (d / "ckpt_t000000010.npz").touch()
        (d / "ckpt_t000000004.fingerprint").touch()
        (d / "garbage.txt").touch()
        assert _newest_ckpt_tick(str(d)) == 10


# ---------------------------------------------------------------------------
# Dashboard: rank liveness rendering


class TestDashboardLiveness:
    def _fabricate(self, tmp_path, dead=True):
        run_dir = tmp_path / "mh"
        run_dir.mkdir()
        now = time.time()
        (run_dir / "hb_rank0.json").write_text(json.dumps(
            {"rank": 0, "tick": 4, "chunk": 2, "wall": now, "done": False}))
        (run_dir / "hb_rank1.json").write_text(json.dumps(
            {"rank": 1, "tick": 2, "chunk": 1,
             "wall": now - (100 if dead else 0), "done": False}))
        # a stale file from an earlier 4-rank attempt must be filtered
        (run_dir / "hb_rank3.json").write_text(json.dumps(
            {"rank": 3, "tick": 0, "chunk": 0, "wall": 0, "done": False}))
        with open(run_dir / "mh_journal.jsonl", "w") as f:
            f.write(json.dumps({"kind": "mh_run", "resume_cmd":
                                "python scripts/mh_supervisor.py --procs "
                                "2,1 --run-dir X"}) + "\n")
            f.write(json.dumps({"kind": "mh_attempt", "attempt": 0,
                                "procs": 2, "rung": 0}) + "\n")
            f.write(json.dumps({"kind": "mh_attempt", "attempt": 1,
                                "procs": 2, "rung": 1}) + "\n")
        health = tmp_path / "health.jsonl"
        health.write_text(json.dumps(
            {"kind": "run", "wall": now, "scenario": "frontier_250k",
             "n_peers": 128, "processes": 2, "flags_version": 2,
             "mh_run_dir": str(run_dir), "mh_rung": 0,
             "mh_relaunches": 0, "mh_peer_timeout_s": 5.0}) + "\n")
        return str(health)

    def test_snapshot_carries_liveness(self, tmp_path):
        from scripts.dashboard import snapshot
        snap = snapshot(self._fabricate(tmp_path))
        mh = snap["mh"]
        assert [r["rank"] for r in mh["ranks"]] == [0, 1]   # rank 3 gone
        assert mh["dead_ranks"] == [1]
        assert mh["relaunches"] == 1        # two attempts = one relaunch
        assert mh["rung"] == 1
        assert "mh_supervisor" in mh["resume_cmd"]

    def test_render_dead_rank_banner_and_resume(self, tmp_path):
        from scripts.dashboard import render, snapshot
        text = render(snapshot(self._fabricate(tmp_path)))
        assert "DEAD RANK 1" in text
        assert "mh_supervisor" in text
        assert "relaunches 1" in text and "rung 1" in text

    def test_healthy_ranks_no_banner(self, tmp_path):
        from scripts.dashboard import render, snapshot
        snap = snapshot(self._fabricate(tmp_path, dead=False))
        assert snap["mh"]["dead_ranks"] == []
        assert "DEAD RANK" not in render(snap)

    def test_decode_refusal_renders_by_name(self):
        from scripts.dashboard import _decode_flags
        names = _decode_flags(1 << 8, version=1)
        assert len(names) == 1 and names[0].startswith("UNDECODABLE(")
        assert "flags_version" in names[0]


def test_mh_supervisor_sigterm_tears_down_group(tmp_path):
    """The group must never outlive its owner: SIGTERM to mh_supervisor
    (scheduler preemption, ctrl-C) tears down every rank it launched —
    orphaned ranks would keep beating (possibly wedged in collectives)
    forever, poisoning the run dir for the resume."""
    run_dir = tmp_path / "mh"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               # both ranks stall 120s at the first chunk: plenty of
               # window to signal the parent while children are alive
               GRAFT_CHAOS="stall@0:0:120,stall@1:0:120",
               GRAFT_MH_BEAT_INTERVAL_S="0.5")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "mh_supervisor.py"),
         "--procs", "2", "--scenario", "frontier_250k", "--n", "128",
         "--ticks", "6", "--seed", "7", "--chunk-ticks", "2",
         "--run-dir", str(run_dir), "--max-relaunches", "0"],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        pids = {}
        deadline = time.time() + 120
        while len(pids) < 2 and time.time() < deadline:
            for r in (0, 1):
                try:
                    with open(heartbeat_path(str(run_dir), r)) as f:
                        pids[r] = json.load(f)["pid"]
                except (OSError, ValueError):
                    pass
            time.sleep(0.25)
        assert len(pids) == 2, "ranks never started beating"
        proc.send_signal(15)            # SIGTERM the group owner
        assert proc.wait(timeout=30) == 143
        deadline = time.time() + 15     # teardown: TERM, 5s grace, KILL
        live = lambda pid: os.path.exists(f"/proc/{pid}")
        while any(live(p) for p in pids.values()) \
                and time.time() < deadline:
            time.sleep(0.25)
        assert not any(live(p) for p in pids.values()), \
            f"orphaned rank processes survived the owner: {pids}"
    finally:
        if proc.poll() is None:
            proc.kill()
    journal = [json.loads(ln)
               for ln in (run_dir / "mh_journal.jsonl").read_text()
               .splitlines()]
    assert any(r["kind"] == "mh_signal" and r["signum"] == 15
               for r in journal)


# ---------------------------------------------------------------------------
# THE acceptance test: SIGKILL a rank mid-run, supervised relaunch at a
# different process count, bit-exact final state


def _reference_state(ticks: int):
    import jax

    from go_libp2p_pubsub_tpu.parallel import multihost
    from go_libp2p_pubsub_tpu.sim import scenarios
    from go_libp2p_pubsub_tpu.sim.engine import run_keys
    cfg, tp, topo, subscribed = scenarios.frontier_spec(128)
    st = multihost.init_state_local(cfg, topo, 0, 1, subscribed=subscribed)
    keys = jax.random.split(jax.random.PRNGKey(7), ticks)
    return run_keys(st, cfg, tp, keys)


@pytest.mark.slow
def test_mh_supervisor_sigkill_relaunch_elastic_bit_exact(tmp_path):
    """ISSUE 14 acceptance: rank 1 of a 2-process CPU run SIGKILLs itself
    (GRAFT_CHAOS) at the speculation of chunk [4,6) — after the t=2
    checkpoint drained — the group supervisor observes the death, tears
    the group down, and relaunches at P'=1 (elastic re-shard of the P=2
    checkpoint); the final state is bit-exact vs the uninterrupted
    single-process run."""
    run_dir = tmp_path / "mh"
    final = tmp_path / "final.npz"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)      # conftest's 8-device flag must not leak
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               GRAFT_CHAOS="kill@1:4",
               GRAFT_MH_PEER_TIMEOUT_S="6", GRAFT_MH_ABORT_GRACE_S="3",
               GRAFT_MH_BEAT_INTERVAL_S="0.5")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "mh_supervisor.py"),
         "--procs", "2,1", "--scenario", "frontier_250k", "--n", "128",
         "--ticks", "6", "--seed", "7", "--chunk-ticks", "2",
         "--run-dir", str(run_dir), "--max-relaunches", "2",
         "--backoff-base-s", "0.05", "--dump-state", str(final),
         # --health changes the compiled program (telemetry lane): the
         # supervisor must hand it to EVERY rank or the group wedges on
         # mismatched collectives — regression pin for exactly that
         "--health", str(run_dir / "health.jsonl")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=560)
    journal = [json.loads(ln)
               for ln in (run_dir / "mh_journal.jsonl").read_text()
               .splitlines()]
    assert proc.returncode == 0, (proc.stdout, proc.stderr, journal)

    # the relaunch really happened, elastically: attempt 0 at P=2 died to
    # the chaos kill, the final attempt ran at P=1 from the drained ckpt
    attempts = [r for r in journal if r["kind"] == "mh_attempt"]
    assert len(attempts) >= 2
    assert attempts[0]["procs"] == 2 and attempts[-1]["procs"] == 1
    assert any(r["kind"] == "mh_failure" and "rank_exit" in r["why"]
               for r in journal)
    assert any(r["kind"] == "mh_done" for r in journal)

    # the relaunched rank RESUMED (not re-ran): its metric line names the
    # checkpoint it restored — the elastic P=2 → P'=1 re-slice
    last = attempts[-1]["attempt"]
    rank0_log = (run_dir / f"rank0.attempt{last}.log").read_text()
    metric = next(json.loads(ln) for ln in rank0_log.splitlines()
                  if ln.startswith("{") and "\"metric\"" in ln)
    assert metric["resumed_from"] is not None
    assert metric["mh_relaunches"] == last

    # the health journal streamed (rank 0 writes; all ranks ran the
    # telemetry lane) and its run header carries the liveness pointers
    # the dashboard's rank view reads
    from go_libp2p_pubsub_tpu.sim.telemetry import read_journal
    runs = read_journal(str(run_dir / "health.jsonl"))["runs"]
    assert runs and runs[-1]["mh_run_dir"] == str(run_dir)
    assert runs[-1]["flags_version"] is not None

    # bit-exact vs the uninterrupted single-process run
    from go_libp2p_pubsub_tpu.sim.state import SimState
    ref = _reference_state(6)
    got = np.load(final)
    for f in SimState._fields:
        assert np.array_equal(np.asarray(getattr(ref, f)), got[f]), f
