"""Adversary & workload library (sim/adversary.py + sim/faults.py attack
families, ISSUE 10).

Acceptance contract: the five scenario families (eclipse / censorship /
flash-crowd / slow-link / diurnal churn) run end-to-end at small N with
at least one ENFORCED behavior contract each; the score-response
contract demonstrably FAILS when scoring is disabled (positive control —
a broken assertion cannot silently pass); the new ``FaultPlan.parse``
keys round-trip through ``format`` and reject malformed specs by name;
contract evaluation itself is pinned against synthetic HealthRecord row
streams that must pass/fail each contract type; the host runtime mirrors
the connection/link-layer families (eclipse cut set, wave schedule,
slow-link stall) from the same plan.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import adversary, invariants, scenarios
from go_libp2p_pubsub_tpu.sim.adversary import (
    AttackScenario, DeliveryFloor, RecoveryCeiling, ScoreResponse,
    contract_from_json, contract_to_json, contracts_from_schedule,
    evaluate_contracts,
)
from go_libp2p_pubsub_tpu.sim.faults import (
    CensorWindow, ChurnWave, EclipseWindow, FaultPlan, HostFaultInjector,
    OutageWindow, PartitionWindow, SlowLinkClass, StormWindow,
    attack_end_tick, attack_schedule, censor_peers_host,
    eclipse_targets_host, wave_peers_host, wave_windows,
)

pytestmark = pytest.mark.adversarial

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# FaultPlan parse / format round-trip (satellite 1)


class TestPlanParseFormat:
    FULL = FaultPlan(
        link_drop_prob=0.05, link_dup_prob=0.01, corrupt_prob=0.1,
        partitions=(PartitionWindow(10, 30, components=2),),
        outages=(OutageWindow(5, 15, fraction=0.2),),
        eclipses=(EclipseWindow(5, 15, fraction=0.1),),
        censorships=(CensorWindow(5, 15, fraction=0.2, victim=3),),
        storms=(StormWindow(5, 15, hot=8, skew=0.9, topic=1),),
        slowlinks=(SlowLinkClass(0.3, period=4, drop=0.05),),
        waves=(ChurnWave(period=20, duty=5, until=60, fraction=0.25,
                         phase=2),),
        seed=7)

    def test_full_roundtrip(self):
        spec = self.FULL.format()
        assert FaultPlan.parse(spec) == self.FULL
        # and the canonical form is a fixed point
        assert FaultPlan.parse(spec).format() == spec

    def test_each_new_key_parses(self):
        plan = FaultPlan.parse(
            "eclipse=0.1@5:15,censor=0.2x3@5:15,storm=8x0.9x1@5:15,"
            "slowlink=0.3@4:0.05,wave=0.25@20:5:60:2")
        assert plan.eclipses == (EclipseWindow(5, 15, fraction=0.1),)
        assert plan.censorships == (CensorWindow(5, 15, fraction=0.2,
                                                 victim=3),)
        assert plan.storms == (StormWindow(5, 15, hot=8, skew=0.9,
                                           topic=1),)
        assert plan.slowlinks == (SlowLinkClass(0.3, period=4, drop=0.05),)
        assert plan.waves == (ChurnWave(period=20, duty=5, until=60,
                                        fraction=0.25, phase=2),)
        assert plan.active()

    def test_defaults_fill_in(self):
        plan = FaultPlan.parse("censor=0.2@5:15,storm=8@5:15,slowlink=0.3@4")
        assert plan.censorships[0].victim == 0
        assert plan.storms[0].skew == 0.9 and plan.storms[0].topic == 0
        assert plan.slowlinks[0].drop == 0.0

    @pytest.mark.parametrize("bad, fragment", [
        ("eclipse=0.1@30:10", "empty window"),
        ("eclipse=1.5@5:15", "outside"),
        ("censor=0.2x3x9@5:15", "too many"),
        ("storm=0@5:15", "must be >= 1"),
        ("storm=8x2.0@5:15", "outside"),
        ("slowlink=0.3", "missing @PERIOD"),
        ("slowlink=0.3@0", "must be >= 1"),
        ("wave=0.25@20:5", "PERIOD:DUTY:UNTIL"),
        ("wave=0.25@20:25:60", "duty <= period"),
        ("eclipse=0.1", "missing @START:END"),
    ])
    def test_malformed_specs_raise_named(self, bad, fragment):
        with pytest.raises(ValueError, match="malformed fault-plan item"):
            FaultPlan.parse(bad)
        with pytest.raises(ValueError, match=fragment):
            FaultPlan.parse(bad)

    def test_unknown_key_names_known_keys(self):
        with pytest.raises(ValueError, match="unknown fault-plan item"):
            FaultPlan.parse("chaos=1")

    def test_wave_windows_expansion(self):
        w = ChurnWave(period=20, duty=5, until=60, phase=2)
        assert wave_windows(w) == [(2, 7), (22, 27), (42, 47)]
        assert attack_end_tick(FaultPlan(waves=(w,))) == 47

    def test_attack_end_tick_spans_families(self):
        assert attack_end_tick(None) == 0
        assert attack_end_tick(FaultPlan()) == 0
        plan = self.FULL
        assert attack_end_tick(plan) == 47       # last wave window end
        # permanent slow-link classes never move the end tick
        assert attack_end_tick(
            FaultPlan(slowlinks=(SlowLinkClass(0.5),))) == 0

    def test_attack_schedule_shapes(self):
        sched = attack_schedule(self.FULL)
        kinds = [w["kind"] for w in sched]
        for k in ("partition", "outage", "eclipse", "censor", "storm",
                  "slowlink", "wave"):
            assert k in kinds
        assert sum(1 for w in sched if w["kind"] == "wave") == 3
        slow = next(w for w in sched if w["kind"] == "slowlink")
        assert slow["end"] is None
        assert json.loads(json.dumps(sched)) == sched     # JSON-able


# ---------------------------------------------------------------------------
# contract evaluation on synthetic row streams (satellite 3): each
# contract type must PASS on a stream built to satisfy it and FAIL on a
# stream built to violate it — a broken evaluator cannot silently pass


def _rows(deliv, att_edges=0, att_gray=0, hon_gray=0, conn=100, t0=0):
    return [{"tick": t0 + i, "member": -1, "delivery_frac_t0": d,
             "attacker_edges": att_edges, "attacker_graylisted": g,
             "honest_graylisted": hon_gray, "connected_edges": conn}
            for i, (d, g) in enumerate(
                zip(deliv, att_gray if isinstance(att_gray, list)
                    else [att_gray] * len(deliv)))]


class TestContractEvaluation:
    def test_delivery_floor_pass_fail(self):
        c = DeliveryFloor(floor=0.8, start=2, end=6, topic=0)
        ok = c.evaluate(_rows([0.5, 0.5, 0.9, 0.85, 0.99, 0.81, 0.1]))
        assert ok.status == "pass"                # dips outside [2, 6) ignored
        bad = c.evaluate(_rows([0.9, 0.9, 0.9, 0.79, 0.9, 0.9, 0.9]))
        assert bad.status == "fail" and "0.79" in bad.detail

    def test_delivery_floor_topic_mean_modes(self):
        rows = [{"tick": 0, "member": -1, "delivery_frac_t0": 1.0,
                 "delivery_frac_t1": 0.5}]
        assert DeliveryFloor(floor=0.9, topic=0).evaluate(rows).passed
        assert not DeliveryFloor(floor=0.9, topic=1).evaluate(rows).passed
        assert not DeliveryFloor(floor=0.9).evaluate(rows).passed  # mean .75

    def test_delivery_floor_empty_census_fails_final(self):
        c = DeliveryFloor(floor=0.5, start=10, end=20)
        r = c.evaluate(_rows([1.0, 1.0]), final=True)
        assert r.status == "fail" and "no rows" in r.detail
        assert c.evaluate(_rows([1.0, 1.0]), final=False).status == "pending"

    def test_recovery_ceiling_pass_fail_pending(self):
        c = RecoveryCeiling(after=3, within=4, floor=0.95)
        ok = c.evaluate(_rows([0.2, 0.2, 0.2, 0.3, 0.6, 0.96, 1.0, 1.0]))
        assert ok.status == "pass" and "tick 5" in ok.detail
        late = c.evaluate(_rows([0.2] * 8 + [0.96]))     # recovers at 8 > 3+4
        assert late.status == "fail"
        never = c.evaluate(_rows([0.2] * 12))
        assert never.status == "fail" and "never" in never.detail
        short = c.evaluate(_rows([0.2] * 5), final=False)
        assert short.status == "pending"
        # a FINAL stream too short to prove recovery fails by name
        assert c.evaluate(_rows([0.2] * 5), final=True).status == "fail"

    def test_score_response_pass_fail(self):
        c = ScoreResponse(by=5, attacker_frac=0.5, honest_max_frac=0.05)
        ok = c.evaluate(_rows([1.0] * 8, att_edges=100,
                              att_gray=[0, 0, 10, 30, 60, 80, 80, 80]))
        assert ok.status == "pass" and "tick 4" in ok.detail
        slow = c.evaluate(_rows([1.0] * 8, att_edges=100,
                                att_gray=[0] * 6 + [60, 80]))
        assert slow.status == "fail"              # responded at 6 > by 5
        none = c.evaluate(_rows([1.0] * 8, att_edges=100, att_gray=0))
        assert none.status == "fail" and "responded_at=None" in none.detail

    def test_score_response_honest_leg(self):
        c = ScoreResponse(by=5, attacker_frac=0.5, honest_max_frac=0.05)
        # attacker leg satisfied but honest collateral blows the bound
        r = c.evaluate(_rows([1.0] * 8, att_edges=100, att_gray=80,
                             hon_gray=50, conn=200))   # 50 > 5% of 100
        assert r.status == "fail" and "honest" in r.detail
        # attacker_frac=0 drops the attacker leg entirely (slow-link shape)
        c0 = ScoreResponse(by=0, attacker_frac=0.0, honest_max_frac=0.05)
        assert c0.evaluate(_rows([1.0] * 4)).status == "pass"
        assert c0.evaluate(_rows([1.0] * 4, hon_gray=50,
                                 conn=200)).status == "fail"

    def test_contract_json_roundtrip(self):
        for c in (DeliveryFloor(floor=0.8, start=2, end=6, topic=1),
                  RecoveryCeiling(after=25, within=10, floor=0.97),
                  ScoreResponse(by=30, attacker_frac=0.4,
                                honest_max_frac=0.01, start=8)):
            assert contract_from_json(
                json.loads(json.dumps(contract_to_json(c)))) == c
        with pytest.raises(ValueError, match="unknown contract kind"):
            contract_from_json({"kind": "nope"})

    def test_contracts_from_schedule_defaults(self):
        sched = attack_schedule(FaultPlan(
            eclipses=(EclipseWindow(5, 15, fraction=0.1),)))
        cs = contracts_from_schedule(sched)
        assert any(c.kind == "recovery_ceiling" and c.after == 15
                   for c in cs)
        assert any(c.kind == "score_response" for c in cs)


# ---------------------------------------------------------------------------
# the five families end-to-end with ENFORCED contracts (the acceptance
# core). One jitted telemetry run each at the scenario's tuned shape.


class TestFiveFamiliesEndToEnd:
    @pytest.mark.parametrize("name, bit", [
        ("eclipse_small", invariants.FAULT_ECLIPSE),
        ("censor_small", invariants.FAULT_CENSOR),
        ("flashcrowd_small", invariants.FAULT_STORM),
        ("slowlink_small", invariants.FAULT_SLOWLINK),
        ("diurnal_small", invariants.FAULT_WAVE),
    ])
    def test_family_contracts_hold(self, name, bit):
        scn = adversary.ATTACKS[name]()
        assert scn.contracts, name
        rep = adversary.run_with_contracts(scn)
        for r in rep.results:
            assert r.passed, (name, r.kind, r.detail)
        # the family's injected bit fired and nothing violated
        assert rep.fault_flags & bit, (name, hex(rep.fault_flags))
        assert rep.fault_flags & invariants.VIOLATION_MASK == 0, \
            (name, invariants.decode_flags(rep.fault_flags))

    def test_scenarios_registry_returns_triples(self):
        for name in adversary.ATTACKS:
            cfg, tp, st = scenarios.SCENARIOS[name](n_peers=96, k_slots=16,
                                                    degree=6)
            assert cfg.n_peers == 96
            assert cfg.fault_plan is not None and cfg.fault_plan.active()


class TestPositiveControl:
    def test_score_response_fails_without_scoring(self):
        """The library's broken-assertion guard: with scoring disabled
        nothing is ever graylisted, so the score-response contract MUST
        fail — if it passes, the contract (or the telemetry split it
        reads) is vacuous."""
        scn = adversary.censorship(n_peers=256)
        off = dataclasses.replace(scn.cfg, scoring_enabled=False)
        rep = adversary.run_with_contracts(AttackScenario(
            off, scn.tp, scn.state, scn.contracts, scn.n_ticks, scn.name))
        sr = [r for r in rep.results if r.kind == "score_response"]
        assert sr and sr[0].status == "fail", sr


# ---------------------------------------------------------------------------
# host-half parity for the connection/link-layer families


class TestHostRuntimeAttacks:
    def _swarm(self, n):
        from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
        from go_libp2p_pubsub_tpu.net import Network
        from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
        net = Network()
        nodes = [PubSub(net.add_host(), GossipSubRouter(),
                        sign_policy=LAX_NO_SIGN) for _ in range(n)]
        net.dense_connect([p.host for p in nodes], degree=8)
        subs = [p.join("t").subscribe() for p in nodes]
        return net, nodes, subs

    def test_host_eclipse_cuts_target_honest_edges(self):
        net, nodes, subs = self._swarm(20)
        mal = [False] * 16 + [True] * 4          # rows 16..19 are sybils
        plan = FaultPlan(eclipses=(EclipseWindow(2, 8, fraction=0.2),))
        HostFaultInjector(net, [p.host for p in nodes], plan, malicious=mal)
        tgt = eclipse_targets_host(20, 0, plan, malicious=mal)
        assert tgt[:4] == [True] * 4 and not any(tgt[4:])
        net.scheduler.run_for(3.0)               # inside the window
        for i in (0, 1, 2, 3):                   # targets keep NO honest
            for pid in nodes[i].host.conns:      # non-target connections
                j = next(k for k, p in enumerate(nodes)
                         if p.host.peer_id == pid)
                assert mal[j] or tgt[j], (i, j)
        net.scheduler.run_for(7.0)               # past the heal at t=8
        for i in (0, 1, 2, 3):
            js = {next(k for k, p in enumerate(nodes)
                       if p.host.peer_id == pid)
                  for pid in nodes[i].host.conns}
            assert any(not mal[j] and not tgt[j] for j in js), \
                f"target {i} never re-knit to the honest majority"

    def test_host_eclipse_requires_malicious(self):
        net, nodes, _ = self._swarm(4)
        plan = FaultPlan(eclipses=(EclipseWindow(2, 8),))
        with pytest.raises(ValueError, match="malicious"):
            HostFaultInjector(net, [p.host for p in nodes], plan)

    def test_host_wave_cohort_matches_batched_choice(self):
        net, nodes, _ = self._swarm(12)
        plan = FaultPlan(waves=(ChurnWave(period=6, duty=2, until=13,
                                          fraction=0.3),), seed=3)
        HostFaultInjector(net, [p.host for p in nodes], plan)
        dark = wave_peers_host(12, 0, plan)
        assert any(dark) and not all(dark)
        net.scheduler.run_for(1.0)               # inside dark phase [0, 2)
        for i, p in enumerate(nodes):
            if dark[i]:
                assert not p.host.conns, f"dark peer {i} kept connections"
        net.scheduler.run_for(3.0)               # lit phase [2, 6)
        for i, p in enumerate(nodes):
            assert p.host.conns, f"peer {i} not back between waves"
        net.scheduler.run_for(3.0)               # second dark phase [6, 8)
        for i, p in enumerate(nodes):
            if dark[i]:
                assert not p.host.conns, \
                    f"dark peer {i} lit during the second wave"
        net.scheduler.run_for(7.0)               # schedule over (until=13)
        for i, p in enumerate(nodes):
            assert p.host.conns, f"peer {i} never came back after waves"

    def test_host_slowlink_stalls_data_plane(self):
        """A 100%-membership slow-link class with period 1000 stalls
        (almost) every data send; control still flows, so meshes form
        but payloads do not cross."""
        net, nodes, subs = self._swarm(8)
        plan = FaultPlan(slowlinks=(SlowLinkClass(1.0, period=1000),))
        HostFaultInjector(net, [p.host for p in nodes], plan)
        net.scheduler.run_for(3.0)
        nodes[0].my_topics["t"].publish(b"stalled")
        net.scheduler.run_for(2.0)
        got = sum(1 for s in subs[1:]
                  if any(m is not None and m.data == b"stalled"
                         for m in iter(s.next, None)))
        # hash phase opens ~1/1000 of edge-ticks; at 8 peers the payload
        # must be (near-)fully stalled
        assert got <= 1, got

    def test_batched_censor_cohort_excludes_victim(self):
        plan = FaultPlan(censorships=(CensorWindow(0, 10, fraction=0.5,
                                                   victim=5),))
        mask = censor_peers_host(64, 0, plan)
        assert not mask[5]
        assert 10 < sum(mask) < 54          # ~half, hash-chosen


# ---------------------------------------------------------------------------
# telemetry plumbing: split columns + header schedule + dashboard


class TestAttackTelemetry:
    def test_graylist_split_columns_present_and_consistent(self):
        from go_libp2p_pubsub_tpu.sim import telemetry
        cols = [n for n, _ in telemetry.health_columns(1)]
        for c in ("connected_edges", "attacker_edges",
                  "attacker_graylisted", "honest_graylisted"):
            assert c in cols

    def test_journal_header_stamps_schedule_and_contracts(self, tmp_path):
        from go_libp2p_pubsub_tpu.sim import telemetry
        scn = adversary.diurnal(n_peers=96)
        path = str(tmp_path / "health.jsonl")
        with telemetry.HealthJournal(path, prefer_native=False) as hj:
            hj.header(scn.cfg,
                      contracts=adversary.contracts_to_json(scn.contracts))
        run = telemetry.read_journal(path)["runs"][0]
        assert [w["kind"] for w in run["attack_windows"]] == ["wave"] * 3
        assert adversary.contracts_from_json(run["contracts"]) \
            == scn.contracts

    def test_dashboard_renders_attacks_and_contracts(self, tmp_path):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "graft_dashboard", os.path.join(REPO, "scripts", "dashboard.py"))
        dash = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(dash)
        from go_libp2p_pubsub_tpu.sim import telemetry

        path = str(tmp_path / "health.jsonl")
        contracts = (DeliveryFloor(floor=0.9, start=0, topic=0),
                     ScoreResponse(by=3, attacker_frac=0.5))
        plan = FaultPlan(eclipses=(EclipseWindow(1, 6, fraction=0.1),))
        cfg = scenarios.SCENARIOS["1k_single_topic"](n_peers=64,
                                                     k_slots=16)[0]
        cfg = dataclasses.replace(cfg, fault_plan=plan)
        with telemetry.HealthJournal(path, prefer_native=False) as hj:
            hj.header(cfg, scenario="eclipse_small",
                      contracts=adversary.contracts_to_json(contracts))
            hj.append_dicts([
                {"tick": t, "member": -1, "delivery_frac_t0": 0.95,
                 "attacker_edges": 100, "attacker_graylisted": 80 * (t >= 2),
                 "honest_graylisted": 0, "connected_edges": 500}
                for t in range(4)])
        snap = dash.snapshot(path)
        assert snap["attacks"][0]["kind"] == "eclipse"
        assert snap["attacks"][0]["active"] is True       # tick 3 in [1, 6)
        st = {c["kind"]: c["status"] for c in snap["contracts"]}
        assert st == {"delivery_floor": "pass", "score_response": "pass"}
        text = dash.render(snap)
        assert "ATTACK eclipse [1, 6) ACTIVE" in text
        assert "contract delivery_floor: ok" in text
        # and a floor violation renders FAIL
        with telemetry.HealthJournal(path, prefer_native=False) as hj:
            hj.append_dicts([{"tick": 4, "member": -1,
                              "delivery_frac_t0": 0.2,
                              "attacker_edges": 100,
                              "attacker_graylisted": 80,
                              "honest_graylisted": 0,
                              "connected_edges": 500}])
        snap = dash.snapshot(path)
        assert {c["kind"]: c["status"] for c in snap["contracts"]}[
            "delivery_floor"] == "fail"
        assert "contract delivery_floor: FAIL" in dash.render(snap)


# ---------------------------------------------------------------------------
# fleet + sweep integration: the same contracts per member


class TestFleetContracts:
    def test_fleet_collect_health_rows_judge_contracts(self):
        from go_libp2p_pubsub_tpu.sim.fleet import FleetMember, fleet_run

        scn = adversary.diurnal(n_peers=96, k_slots=16, degree=6)
        members = [FleetMember(scn.cfg, scn.tp, scn.state,
                               jax.random.PRNGKey(s), scn.n_ticks,
                               name=f"s{s}") for s in range(2)]
        results = fleet_run(members, collect_health=True)
        for res in results:
            assert res.health_rows and len(res.health_rows) == scn.n_ticks
            ticks = [r["tick"] for r in res.health_rows]
            assert ticks == sorted(ticks)
            verdicts = evaluate_contracts(scn.contracts, res.health_rows)
            assert all(v.status in ("pass", "fail") for v in verdicts)

    def test_sweep_heal_tick_uses_plan_end(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "graft_sweep", os.path.join(REPO, "scripts", "sweep_scores.py"))
        sweep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sweep)
        for name in adversary.ATTACKS:
            cfg = scenarios.SCENARIOS[name](n_peers=96, k_slots=16,
                                            degree=6)[0]
            assert sweep._heal_tick(cfg) == attack_end_tick(cfg.fault_plan)
        # the new families all declare a nonzero end except slow links
        cfg = scenarios.SCENARIOS["slowlink_small"](n_peers=96)[0]
        assert sweep._heal_tick(cfg) == 0
        cfg = scenarios.SCENARIOS["eclipse_small"](n_peers=96)[0]
        assert sweep._heal_tick(cfg) == 25


# ---------------------------------------------------------------------------
# engine-level mechanics pinned (storm skew, censor starvation)


class TestAttackMechanics:
    def test_storm_skews_publishers_inside_window_only(self):
        from go_libp2p_pubsub_tpu.sim.engine import choose_publishers
        from go_libp2p_pubsub_tpu.sim import topology
        from go_libp2p_pubsub_tpu.sim.state import init_state
        from go_libp2p_pubsub_tpu.sim.config import SimConfig

        plan = FaultPlan(storms=(StormWindow(5, 10, hot=4, skew=1.0,
                                             topic=1),))
        cfg = SimConfig(n_peers=64, k_slots=16, n_topics=2, msg_window=32,
                        publishers_per_tick=8, fault_plan=plan)
        st = init_state(cfg, topology.sparse(64, 16, degree=6, seed=7))
        inside = st._replace(tick=jax.numpy.int32(6))
        peers, topics = choose_publishers(inside, cfg, jax.random.PRNGKey(1))
        assert np.asarray(peers).max() < 4            # hot set only
        assert (np.asarray(topics) == 1).all()
        outside = st._replace(tick=jax.numpy.int32(12))
        peers, topics = choose_publishers(outside, cfg,
                                          jax.random.PRNGKey(1))
        assert np.asarray(peers).max() >= 4           # back to uniform

    def test_censor_suppresses_victim_messages_from_cohort(self):
        """With EVERY non-victim peer censoring and eager forwarding the
        only path, the victim's publishes must reach only its direct
        recipients' first hop... in fact nobody re-forwards, so coverage
        stays near the victim's own mesh; without the plan the same
        publish saturates. The differential pins the forwarding mask."""
        from go_libp2p_pubsub_tpu.sim.config import SimConfig
        from go_libp2p_pubsub_tpu.sim.engine import run
        from go_libp2p_pubsub_tpu.sim.state import init_state, unpack_have
        from go_libp2p_pubsub_tpu.sim import topology

        def build(plan):
            cfg = SimConfig(n_peers=64, k_slots=16, n_topics=1,
                            msg_window=32, publishers_per_tick=2,
                            prop_substeps=6, scoring_enabled=False,
                            fault_plan=plan)
            st = init_state(cfg, topology.sparse(64, 16, degree=6, seed=7))
            return cfg, scenarios.default_topic_params(1), st

        storm = StormWindow(0, 20, hot=1, skew=1.0, topic=0)
        plan_c = FaultPlan(censorships=(CensorWindow(0, 20, fraction=1.0,
                                                     victim=0),),
                           storms=(storm,))
        plan_f = FaultPlan(storms=(storm,))
        covs = {}
        for tag, plan in (("censored", plan_c), ("free", plan_f)):
            cfg, tp, st = build(plan)
            out = run(st, cfg, tp, jax.random.PRNGKey(0), 8)
            mt = np.asarray(out.msg_topic)
            alive = (int(out.tick) - np.asarray(out.msg_publish_tick)) \
                < cfg.history_length
            have = np.asarray(unpack_have(out, cfg.msg_window))
            m = alive & (np.asarray(out.msg_publisher) == 0) & (mt >= 0)
            covs[tag] = have[:, m].mean()
        assert covs["free"] > 0.95, covs
        assert covs["censored"] < 0.5, covs

    def test_slowlink_hash_symmetric_and_host_parity(self):
        from go_libp2p_pubsub_tpu.sim.faults import (
            _family_salt, _slow_edge_hash_host, _slow_edge_hash_jax)
        from go_libp2p_pubsub_tpu.sim import topology

        topo = topology.sparse(64, 16, degree=6, seed=7)
        nbrs = np.asarray(topo.neighbors)
        salt = _family_salt(0, "slowlink", 0)
        h = np.asarray(_slow_edge_hash_jax(jax.numpy.asarray(nbrs), salt))
        for i in range(0, 64, 7):
            for k in range(16):
                j = nbrs[i, k]
                if j < 0:
                    continue
                assert h[i, k] == _slow_edge_hash_host(i, int(j), salt)
                # symmetric: the reverse direction hashes identically
                rk = list(nbrs[j]).index(i)
                assert h[j, rk] == h[i, k]
