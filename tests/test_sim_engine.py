"""Batched simulation engine tests (CPU, small N).

Mirrors the reference's behavioral integration suite as array assertions:
- mesh formation/convergence into [Dlo, Dhi] (TestDenseGossipsub,
  gossipsub_test.go:85; mesh bounds gossipsub.go:1413-1490)
- full propagation of published messages (checkMessageRouting semantics)
- floodsub/randomsub variants (floodsub_test.go, randomsub_test.go)
- batched score decay against the host-side scorer's semantics
- backoff honored after prune
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.core.params import TopicScoreParams
from go_libp2p_pubsub_tpu.ops.heartbeat import edge_gather, heartbeat
from go_libp2p_pubsub_tpu.ops.score_ops import compute_scores, decay_counters
from go_libp2p_pubsub_tpu.sim import (
    SimConfig,
    TopicParams,
    delivery_fraction,
    init_state,
    mesh_degrees,
    run,
    topology,
)


def small_cfg(**kw):
    base = dict(n_peers=64, k_slots=16, n_topics=1, msg_window=32,
                publishers_per_tick=2, prop_substeps=6)
    base.update(kw)
    return SimConfig(**base)


@pytest.fixture(scope="module")
def converged():
    cfg = small_cfg()
    topo = topology.dense(64, 16, degree=10)
    tp = TopicParams.disabled(1)
    st = init_state(cfg, topo)
    st = run(st, cfg, tp, jax.random.PRNGKey(0), 20)
    return cfg, st


def test_scanned_window_equals_per_dispatch_ticks():
    """The benched in-graph lax.scan window (engine.run) must produce the
    BIT-IDENTICAL trajectory as dispatching step_jit once per tick — the
    multi-tick window bench.py times is not allowed to drift from the
    stepwise semantics (VERDICT r4 item 2)."""
    from go_libp2p_pubsub_tpu.sim.engine import step_jit

    cfg = small_cfg()
    topo = topology.dense(64, 16, degree=10)
    tp = TopicParams.disabled(1)
    st0 = init_state(cfg, topo)
    key = jax.random.PRNGKey(42)

    scanned = run(st0, cfg, tp, key, 8)
    stepped = st0
    for k in jax.random.split(key, 8):
        stepped = step_jit(stepped, cfg, tp, k)
    for name, a, b in zip(scanned._fields, scanned, stepped):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


class TestMeshFormation:
    def test_degrees_within_bounds(self, converged):
        cfg, st = converged
        deg = np.asarray(mesh_degrees(st))
        assert deg.min() >= 1  # weak bound: sparse corners may sit below Dlo
        assert deg.max() <= cfg.dhi

    def test_mesh_symmetric(self, converged):
        # a mesh edge only persists when both sides agree (GRAFT accepted/
        # refused and PRUNE applied in the same round), so the batched mesh
        # is exactly symmetric
        cfg, st = converged
        inc = np.asarray(edge_gather(st.mesh, st))
        mesh = np.asarray(st.mesh)
        assert (mesh == (mesh & inc)).all()

    def test_mesh_only_on_connected_edges(self, converged):
        cfg, st = converged
        mesh = np.asarray(st.mesh)
        conn = np.asarray(st.connected)[:, None, :]
        assert not (mesh & ~conn).any()

    def test_full_delivery(self, converged):
        cfg, st = converged
        assert float(delivery_fraction(st, cfg)) == 1.0


class TestFreeRunningCrossValidation:
    def test_mesh_statistics_match_functional_runtime(self):
        """SURVEY.md §7: free-running mode is validated statistically —
        the batched sim and the per-node functional runtime, run on
        same-sized networks with default parameters, must converge to the
        same mesh-degree regime ([dlo, dhi], symmetric) and both deliver
        every message."""
        # functional runtime: 24 nodes, dense
        from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
        from go_libp2p_pubsub_tpu.net import Network
        from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
        fnet = Network()
        fnodes = [PubSub(fnet.add_host(), GossipSubRouter(),
                         sign_policy=LAX_NO_SIGN) for _ in range(24)]
        fnet.dense_connect([x.host for x in fnodes], degree=10)
        fsubs = [x.join("t").subscribe() for x in fnodes]
        fnet.scheduler.run_for(6.0)
        fnodes[0].my_topics["t"].publish(b"x")
        fnet.scheduler.run_for(3.0)
        fdegs = np.array([len(x.rt.mesh["t"]) for x in fnodes])
        fdeliv = sum(1 for s in fsubs if any(True for _ in iter(s.next, None)))

        # batched sim: same scale and degree budget
        cfg = SimConfig(n_peers=24, k_slots=16, n_topics=1, msg_window=8,
                        publishers_per_tick=1, prop_substeps=6,
                        scoring_enabled=False)
        topo = topology.dense(24, 16, degree=10)
        st = init_state(cfg, topo)
        st = run(st, cfg, TopicParams.disabled(1), jax.random.PRNGKey(0), 9)
        sdegs = np.asarray(mesh_degrees(st))[:, 0]

        from go_libp2p_pubsub_tpu.core.params import GossipSubParams
        p = GossipSubParams()
        for name, degs in (("functional", fdegs), ("sim", sdegs)):
            assert degs.max() <= p.dhi, name
            assert degs.min() >= 1, name
        # same regime: mean degrees within 2 of each other, around D
        assert abs(fdegs.mean() - sdegs.mean()) <= 2.0, (fdegs.mean(),
                                                        sdegs.mean())
        assert fdeliv == 24
        from go_libp2p_pubsub_tpu.sim.engine import delivery_fraction
        assert float(delivery_fraction(st, cfg)) == 1.0


class TestNbrSubscribedCache:
    def test_cache_stays_consistent_under_subscription_churn(self):
        """nbr_subscribed is a cached gather that every subscribed-mutation
        must refresh (state.py); run with Join/Leave churn and recheck."""
        from go_libp2p_pubsub_tpu.sim.state import refresh_nbr_subscribed
        cfg = SimConfig(n_peers=64, k_slots=16, n_topics=3, msg_window=16,
                        publishers_per_tick=2, prop_substeps=4,
                        scoring_enabled=False,
                        sub_join_prob=0.05, sub_leave_prob=0.05)
        topo = topology.dense(64, 16, degree=10)
        st = init_state(cfg, topo,
                        subscribed=np.random.default_rng(0).random((64, 3)) < 0.5)
        st = run(st, cfg, TopicParams.disabled(3), jax.random.PRNGKey(0), 15)
        want = np.asarray(refresh_nbr_subscribed(st).nbr_subscribed)
        assert (np.asarray(st.nbr_subscribed) == want).all()


class TestEdgeGatherPacked:
    def test_matches_per_mask_edge_gather(self, converged):
        """The packed multi-mask permutation gather must be bit-identical to
        gathering each [N,T,K] mask separately — including across the 32-bit
        word boundary (checked with 13 x 3 = 39 bit-planes)."""
        from go_libp2p_pubsub_tpu.ops.heartbeat import edge_gather_packed
        cfg, st = converged
        n, t, k = st.mesh.shape
        keys = jax.random.split(jax.random.PRNGKey(3), 13)
        masks = [jax.random.uniform(kk, (n, 3, k)) < 0.4 for kk in keys]
        st3 = st._replace(mesh=jnp.zeros((n, 3, k), bool))  # 3-topic shapes
        got = edge_gather_packed(masks, st3)
        for g, mk in zip(got, masks):
            want = np.asarray(edge_gather(mk, st3))
            assert (np.asarray(g) == want).all()


class TestRouterVariants:
    @pytest.mark.parametrize("router", ["floodsub", "randomsub"])
    def test_variant_delivers(self, router):
        cfg = small_cfg(router=router, scoring_enabled=False)
        topo = topology.dense(64, 16, degree=10)
        tp = TopicParams.disabled(1)
        st = init_state(cfg, topo)
        st = run(st, cfg, tp, jax.random.PRNGKey(1), 10)
        frac = float(delivery_fraction(st, cfg))
        assert frac > 0.95, f"{router} delivered only {frac}"

    def test_floodsub_has_no_mesh(self):
        cfg = small_cfg(router="floodsub", scoring_enabled=False)
        # floodsub ignores the mesh for forwarding; mesh state may still form
        # (heartbeat runs) but delivery must work from tick 0
        topo = topology.sparse(64, 16, degree=3)
        tp = TopicParams.disabled(1)
        st = init_state(cfg, topo)
        st = run(st, cfg, tp, jax.random.PRNGKey(2), 5)
        assert float(delivery_fraction(st, cfg)) > 0.9


class TestStarTopology:
    def test_star_bounds_hub_and_partially_delivers(self):
        # gossipsub_test.go:1044-1127 star scenarios. Without PX or flood
        # publish the hub's mesh saturates at Dhi and pruned leaves wait out
        # their backoff, so only mesh + gossip recipients get each message —
        # matching the reference's known star-topology behavior (its star
        # tests enable PX to fix exactly this).
        n = 32
        cfg = small_cfg(n_peers=n, k_slots=n, publishers_per_tick=1)
        topo = topology.star(n, n)
        tp = TopicParams.disabled(1)
        st = init_state(cfg, topo)
        st = run(st, cfg, tp, jax.random.PRNGKey(3), 10)
        frac = float(delivery_fraction(st, cfg))
        assert 0.1 < frac < 1.0
        # hub degree is bounded by Dhi despite n-1 connections
        deg = np.asarray(mesh_degrees(st))
        assert deg[0, 0] <= cfg.dhi
        # leaves in the hub's mesh do receive everything the hub has
        hub_mesh_slots = np.where(np.asarray(st.mesh)[0, 0])[0]
        assert len(hub_mesh_slots) >= cfg.dlo


class TestBatchedScoring:
    def _tp(self):
        return TopicParams.from_topic_params([TopicScoreParams(
            topic_weight=1.0, time_in_mesh_weight=1.0, time_in_mesh_quantum=1.0,
            time_in_mesh_cap=100.0, first_message_deliveries_weight=1.0,
            first_message_deliveries_decay=0.9, first_message_deliveries_cap=100.0,
            mesh_message_deliveries_weight=-1.0, mesh_message_deliveries_decay=0.9,
            mesh_message_deliveries_cap=100.0, mesh_message_deliveries_threshold=5.0,
            mesh_message_deliveries_window=0.01, mesh_message_deliveries_activation=3.0,
            mesh_failure_penalty_weight=-1.0, mesh_failure_penalty_decay=0.9,
            invalid_message_deliveries_weight=-1.0, invalid_message_deliveries_decay=0.9)])

    def test_decay_matches_host_scorer(self):
        """Device decay == host-side PeerScore.refresh_scores on one counter."""
        cfg = small_cfg(scoring_enabled=True)
        topo = topology.dense(64, 16, degree=10)
        tp = self._tp()
        st = init_state(cfg, topo)
        st = st._replace(
            first_message_deliveries=st.first_message_deliveries.at[0, 0, 0].set(10.0),
            behaviour_penalty=st.behaviour_penalty.at[0, 0].set(5.0),
            tick=jnp.int32(1))
        cfg2 = small_cfg(scoring_enabled=True, behaviour_penalty_decay=0.9)
        st2 = decay_counters(st, cfg2, tp)
        assert float(st2.first_message_deliveries[0, 0, 0]) == pytest.approx(9.0)
        assert float(st2.behaviour_penalty[0, 0]) == pytest.approx(4.5)
        # decay to zero below threshold
        st3 = st._replace(
            first_message_deliveries=st.first_message_deliveries.at[0, 0, 0].set(0.01))
        st3 = decay_counters(st3, cfg2, tp)
        assert float(st3.first_message_deliveries[0, 0, 0]) == 0.0

    def test_score_p1_p2_p4(self):
        """Spot-check batched P1/P2/P4 against hand values (score.go:265-342)."""
        cfg = small_cfg(n_peers=8, scoring_enabled=True)
        topo = topology.full(8, 16)
        tp = self._tp()
        st = init_state(cfg, topo)
        st = st._replace(tick=jnp.int32(10))
        # peer 0 slot 0: in mesh since tick 3 -> mesh_time 7 -> P1 = 7
        st = st._replace(
            mesh=st.mesh.at[0, 0, 0].set(True),
            graft_tick=st.graft_tick.at[0, 0, 0].set(3),
            first_message_deliveries=st.first_message_deliveries.at[0, 0, 0].set(4.0),
            invalid_message_deliveries=st.invalid_message_deliveries.at[0, 0, 0].set(3.0))
        # apply_decay=False: this spot-checks the P-term arithmetic on the
        # stored values verbatim (counters are stored pre-decay and scored
        # through an inline decay in the engine — score_ops docstring)
        s = compute_scores(st, cfg, tp, apply_decay=False)
        # 7 (P1) + 4 (P2) - 9 (P4) = 2
        assert float(s[0, 0]) == pytest.approx(2.0)
        # empty slot scores 0
        assert float(s[0, 7]) == 0.0  # full(8): 7 neighbors, slot 7 empty

    def test_negative_score_peer_gets_pruned(self):
        """Heartbeat prunes mesh members with negative score
        (gossipsub.go:1404-1410) and sets backoff."""
        cfg = small_cfg(n_peers=8, scoring_enabled=True)
        topo = topology.full(8, 16)
        tp = self._tp()
        st = init_state(cfg, topo)
        st = run(st, cfg, tp, jax.random.PRNGKey(4), 3)
        # poison peer 1 from everyone's perspective
        imd = st.invalid_message_deliveries
        for n in range(8):
            slot = int(np.where(np.asarray(st.neighbors[n]) == 1)[0][0]) if 1 in np.asarray(st.neighbors[n]) else None
            if slot is not None:
                imd = imd.at[n, 0, slot].set(50.0)
        st = st._replace(invalid_message_deliveries=imd)
        out = heartbeat(st, cfg, tp, jax.random.PRNGKey(5))
        mesh = np.asarray(out.state.mesh)
        nbrs = np.asarray(st.neighbors)
        for n in range(8):
            if n == 1:
                continue
            slots = np.where(nbrs[n] == 1)[0]
            for s in slots:
                assert not mesh[n, 0, s], f"peer {n} kept negative-score peer 1"
                assert int(out.state.backoff[n, 0, s]) > int(st.tick)


class TestBackoff:
    def test_backoff_blocks_regraft(self):
        cfg = small_cfg(n_peers=32, scoring_enabled=False, prune_backoff_ticks=1000)
        topo = topology.dense(32, 16, degree=10)
        tp = TopicParams.disabled(1)
        st = init_state(cfg, topo)
        st = run(st, cfg, tp, jax.random.PRNGKey(6), 5)
        # force-prune everything via backoff: set all backoffs far in future
        st = st._replace(mesh=jnp.zeros_like(st.mesh),
                         backoff=jnp.full_like(st.backoff, 10_000))
        st2 = run(st, cfg, tp, jax.random.PRNGKey(7), 3)
        assert int(jnp.sum(st2.mesh)) == 0  # nothing regrafts under backoff


class TestDeterminism:
    def test_same_key_same_result(self):
        cfg = small_cfg()
        topo = topology.dense(64, 16, degree=10)
        tp = TopicParams.disabled(1)
        st = init_state(cfg, topo)
        a = run(st, cfg, tp, jax.random.PRNGKey(42), 8)
        b = run(st, cfg, tp, jax.random.PRNGKey(42), 8)
        assert jnp.array_equal(a.mesh, b.mesh)
        assert jnp.array_equal(a.have, b.have)
        assert float(a.delivered_total) == float(b.delivered_total)


class TestRandomsubExactSample:
    def test_sender_degree_exact(self):
        """randomsub forwards to EXACTLY max(D, ceil(sqrt N)) random topic
        peers per sender (randomsub.go:124-143), not a Bernoulli approx."""
        import math
        from go_libp2p_pubsub_tpu.ops.propagate import _edge_forward_mask
        cfg = SimConfig(n_peers=64, k_slots=32, n_topics=1, msg_window=16,
                        router="randomsub", scoring_enabled=False, d=3)
        topo = topology.dense(cfg.n_peers, cfg.k_slots, degree=20)
        st = init_state(cfg, topo)
        mask = np.asarray(_edge_forward_mask(st, cfg, jax.random.PRNGKey(0)))
        nbr = np.asarray(st.neighbors)
        target = max(cfg.d, math.ceil(math.sqrt(cfg.n_peers)))
        out_deg = np.zeros(cfg.n_peers, int)
        for i in range(cfg.n_peers):
            for s in range(cfg.k_slots):
                if nbr[i, s] >= 0 and mask[i, 0, s]:
                    out_deg[nbr[i, s]] += 1
        deg = np.asarray(st.connected).sum(axis=1)
        expect = np.minimum(deg, target)
        np.testing.assert_array_equal(out_deg, expect)


class TestFloodPublish:
    def test_origin_floods_topic_peers_despite_empty_mesh(self):
        """WithFloodPublish (gossipsub.go:989-1004): the publisher reaches
        every topic peer it scores above the publish threshold even with no
        mesh; forwarding hops stay mesh-only."""
        from go_libp2p_pubsub_tpu.ops.propagate import forward_tick, publish

        def one_tick(flood):
            cfg = SimConfig(n_peers=32, k_slots=32, n_topics=1, msg_window=8,
                            publishers_per_tick=1, prop_substeps=2,
                            scoring_enabled=False, flood_publish=flood)
            topo = topology.full(cfg.n_peers, cfg.k_slots)
            st = init_state(cfg, topo)        # mesh is empty: no heartbeat ran
            st = publish(st, cfg, jnp.asarray([0]), jnp.asarray([0]))
            gossip_sel = jnp.zeros_like(st.mesh)
            scores = jnp.zeros(st.behaviour_penalty.shape, jnp.float32)
            st = forward_tick(st, cfg, TopicParams.disabled(1), gossip_sel,
                              scores, jax.random.PRNGKey(0))
            from go_libp2p_pubsub_tpu.sim.state import unpack_have
            return int(np.asarray(unpack_have(st, cfg.msg_window))[:, 0].sum())

        assert one_tick(flood=False) == 1     # only the publisher holds it
        assert one_tick(flood=True) == 32     # everyone got the origin copy


class TestDeliveryLatency:
    def test_latency_counts_receivers_not_publisher(self):
        from go_libp2p_pubsub_tpu.sim import delivery_latency_ticks
        from go_libp2p_pubsub_tpu.sim.state import NEVER
        cfg = small_cfg(n_peers=4, k_slots=4, msg_window=4, history_length=100)
        topo = topology.full(4, 4)
        tp = TopicParams.disabled(1)
        st = init_state(cfg, topo)
        # message 0 published by peer 0 at tick 10; peers 1,2 get it at 11
        # and 13; peer 3 never does -> mean over receivers = (1+3)/2
        st = st._replace(
            tick=jnp.int32(14),
            msg_topic=st.msg_topic.at[0].set(0),
            msg_publish_tick=st.msg_publish_tick.at[0].set(10),
            deliver_tick=st.deliver_tick.at[0, 0].set(10)
                                        .at[1, 0].set(11)
                                        .at[2, 0].set(13))
        assert float(delivery_latency_ticks(st, cfg)) == pytest.approx(2.0)

    def test_publisher_only_message_reports_zero(self):
        from go_libp2p_pubsub_tpu.sim import delivery_latency_ticks
        cfg = small_cfg(n_peers=4, k_slots=4, msg_window=4, history_length=100)
        topo = topology.full(4, 4)
        tp = TopicParams.disabled(1)
        st = init_state(cfg, topo)
        st = st._replace(
            tick=jnp.int32(14),
            msg_topic=st.msg_topic.at[0].set(0),
            msg_publish_tick=st.msg_publish_tick.at[0].set(10),
            deliver_tick=st.deliver_tick.at[0, 0].set(10))
        # nobody but the publisher delivered: no receiver pairs, mean 0
        assert float(delivery_latency_ticks(st, cfg)) == 0.0

    def test_expired_messages_excluded(self):
        from go_libp2p_pubsub_tpu.sim import delivery_latency_ticks
        cfg = small_cfg(n_peers=4, k_slots=4, msg_window=4, history_length=2)
        topo = topology.full(4, 4)
        tp = TopicParams.disabled(1)
        st = init_state(cfg, topo)
        st = st._replace(
            tick=jnp.int32(50),                 # long past history_length
            msg_topic=st.msg_topic.at[0].set(0),
            msg_publish_tick=st.msg_publish_tick.at[0].set(10),
            deliver_tick=st.deliver_tick.at[0, 0].set(10).at[1, 0].set(12))
        assert float(delivery_latency_ticks(st, cfg)) == 0.0
