"""The precision ladder (ISSUE 13): compact storage planes vs the f32
reference layout.

``SimConfig.state_precision`` selects how the SimState is STORED between
steps (sim/state.py codecs — bf16-as-u16 score planes, i16 relative-tick
planes, u32 bit-packed bool planes, i8 slot planes); the step always
COMPUTES in the f32/i32/bool layout (decode at entry, encode at exit).
The ladder this file pins:

- the codec round trip is bit-exact for every in-range value (packed
  bool planes are lossless by construction);
- a compact init equals the encoded f32 init bit-for-bit on every plane
  except ``gater_last_throttle`` (its -NEVER sentinel saturates to the
  i16 floor — documented in sim/state.py; quiet-period compares are
  unaffected);
- compact-vs-f32 trajectories: every DISCRETE plane (mesh topology,
  connectivity, delivery provenance, tick planes) is bit-exact over the
  asserted window; bf16-coded score planes stay within the documented
  rounding tolerance; delivery fraction is identical;
- contract verdicts (sim/adversary.py) are unchanged under compact;
- the audit: state_spec walks every field against the independent
  per-peer byte ceilings, so a layout regression cannot land silently;
- refusals by name: k_slots > 127 under compact (the i8 slot codec),
  cross-precision checkpoint restore (sim/checkpoint.py sidecar).
"""

import dataclasses

import jax
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import checkpoint, scenarios
from go_libp2p_pubsub_tpu.sim.config import SimConfig
from go_libp2p_pubsub_tpu.sim.engine import delivery_fraction, run
from go_libp2p_pubsub_tpu.sim.state import (
    _COMPACT_CODECS, NEVER, SimState, decode_state, encode_state,
    per_peer_byte_ceilings, state_spec)

# the one init-time exception: gater_last_throttle initializes to -NEVER,
# which the i16 relative-tick codec saturates (sim/state.py _TICK16_SAT);
# every quiet-period compare still resolves identically
SATURATED = ("gater_last_throttle",)

# bf16 rounding bound for the score planes over the short parity windows
# below (measured max ≈ 0.04 at 8 ticks; the counters are O(1..100) so
# bf16's ~2^-8 relative step prices well under this)
SCORE_TOL = 0.25


def _pair(n=256, k=16, degree=6, **kw):
    """(f32, compact) builds of the same frontier scenario."""
    cfg_f, tp, st_f = scenarios.frontier(n, k_slots=k, degree=degree, **kw)
    cfg_c, _, st_c = scenarios.frontier(n, k_slots=k, degree=degree,
                                        state_precision="compact", **kw)
    return cfg_f, cfg_c, tp, st_f, st_c


def _assert_parity(a, b_decoded, skip=SATURATED):
    """a (f32-layout) vs b_decoded: discrete planes bit-exact, bf16-coded
    score planes within SCORE_TOL."""
    for f in SimState._fields:
        av, bv = np.asarray(getattr(a, f)), np.asarray(getattr(b_decoded, f))
        if f in skip:
            continue
        if _COMPACT_CODECS[f] == "bf16":
            assert av.shape == bv.shape, f
            if av.size:
                d = float(np.max(np.abs(av - bv)))
                assert d <= SCORE_TOL, (f, d)
        else:
            np.testing.assert_array_equal(av, bv, err_msg=f)


class TestCodecs:
    def test_compact_init_equals_encoded_f32_init(self):
        cfg_f, cfg_c, tp, st_f, st_c = _pair()
        enc = encode_state(st_f, cfg_c)
        for f in SimState._fields:
            if f in SATURATED:
                continue
            np.testing.assert_array_equal(
                np.asarray(getattr(enc, f)), np.asarray(getattr(st_c, f)),
                err_msg=f)

    def test_round_trip_is_bit_exact_for_in_range_values(self):
        """decode(encode(state)) == state for every plane whose values the
        codecs represent exactly: all discrete planes, and score planes
        holding bf16-representable values (the init state's zeros)."""
        cfg_f, cfg_c, tp, st_f, st_c = _pair()
        back = decode_state(encode_state(st_f, cfg_c), cfg_c)
        _assert_parity(st_f, back)
        # the saturated sentinel decodes to the i16 floor relative to tick,
        # NOT the original -NEVER — pinned so the exception stays deliberate
        glt = np.asarray(back.gater_last_throttle)
        assert np.all(glt == int(np.asarray(st_f.tick)) - 32766), glt
        assert np.all(np.asarray(st_f.gater_last_throttle) == -int(NEVER))

    def test_packed_bool_planes_are_lossless(self):
        """pack/unpack of every bool plane is exact — including ragged
        last words (k=20 does not divide 32)."""
        from go_libp2p_pubsub_tpu.ops.bits import pack_bool, unpack_bool
        rng = np.random.default_rng(3)
        for shape, m in [((7, 20), 20), ((3, 2, 33), 33), ((5, 64), 64)]:
            v = rng.random(shape) < 0.5
            import jax.numpy as jnp
            got = np.asarray(unpack_bool(pack_bool(jnp.asarray(v)), m))
            np.testing.assert_array_equal(v, got)

    def test_never_sentinel_round_trips_on_tick_planes(self):
        """NEVER (the far-future sentinel) must survive the i16 relative
        codec exactly on every tick16 plane — a saturated NEVER would
        un-stick backoffs and deliveries."""
        cfg_f, cfg_c, tp, st_f, st_c = _pair()
        back = decode_state(st_c, cfg_c)
        for f in ("graft_tick", "deliver_tick", "fanout_lastpub",
                  "disconnect_tick"):
            v = np.asarray(getattr(back, f))
            ref = np.asarray(getattr(st_f, f))
            assert v.dtype == np.int32, f
            np.testing.assert_array_equal(v, ref, err_msg=f)
            assert np.any(ref == int(NEVER)), f  # the sentinel is present

    def test_encode_decode_layout_guards_raise(self):
        cfg_f, cfg_c, tp, st_f, st_c = _pair()
        with pytest.raises(TypeError, match="compact storage layout"):
            encode_state(st_c, cfg_c)          # already encoded
        with pytest.raises(TypeError, match="compute layout"):
            decode_state(st_f, cfg_c)          # already decoded


class TestTrajectoryParity:
    def test_parity_1k(self):
        """The acceptance trajectory at 1k: 8 ticks of the frontier config,
        same key — discrete planes bit-exact, scores within SCORE_TOL,
        delivery fraction identical."""
        cfg_f, cfg_c, tp, st_f, st_c = _pair(n=1024)
        key = jax.random.PRNGKey(7)
        a = run(st_f, cfg_f, tp, key, 8)
        b = run(st_c, cfg_c, tp, key, 8)
        _assert_parity(a, decode_state(b, cfg_c))
        assert float(delivery_fraction(a, cfg_f)) == \
            float(delivery_fraction(b, cfg_c))

    def test_parity_10k(self):
        """The 10k rung of the ladder (slow tier)."""
        cfg_f, cfg_c, tp, st_f, st_c = _pair(n=10_240, k=32, degree=8)
        key = jax.random.PRNGKey(11)
        a = run(st_f, cfg_f, tp, key, 8)
        b = run(st_c, cfg_c, tp, key, 8)
        _assert_parity(a, decode_state(b, cfg_c))
        assert float(delivery_fraction(a, cfg_f)) == \
            float(delivery_fraction(b, cfg_c))


def _compact_attack(scn):
    """The same AttackScenario with the state re-encoded compact."""
    from go_libp2p_pubsub_tpu.sim.adversary import AttackScenario
    cfg_c = dataclasses.replace(scn.cfg, state_precision="compact")
    return AttackScenario(cfg_c, scn.tp, encode_state(scn.state, cfg_c),
                          scn.contracts, scn.n_ticks, scn.name)


class TestContractVerdicts:
    def _verdicts_match(self, name):
        from go_libp2p_pubsub_tpu.sim import adversary
        scn = adversary.ATTACKS[name]()
        rep_f = adversary.run_with_contracts(scn)
        rep_c = adversary.run_with_contracts(_compact_attack(scn))
        assert [(r.kind, r.status) for r in rep_f.results] == \
            [(r.kind, r.status) for r in rep_c.results], name
        assert rep_f.fault_flags == rep_c.fault_flags, name
        assert all(r.passed for r in rep_c.results), name

    def test_eclipse_verdicts_unchanged_under_compact(self):
        """Tier-1 sentinel: the eclipse family's enforced contracts give
        the same verdicts under compact storage."""
        self._verdicts_match("eclipse_small")

    @pytest.mark.parametrize("name", ["censor_small", "flashcrowd_small",
                                      "slowlink_small", "diurnal_small"])
    def test_remaining_families_verdicts_unchanged(self, name):
        """The other four families (slow tier — one pair of full contract
        runs each)."""
        self._verdicts_match(name)


class TestAudit:
    """The tier-1 layout audit: state_spec against the INDEPENDENT
    per-peer byte ceilings — a codec or shape regression moves the spec
    and trips here, and must be re-priced deliberately."""

    @pytest.mark.parametrize("precision", ["f32", "compact"])
    def test_every_field_prices_under_its_ceiling(self, precision):
        cfg = scenarios.frontier_cfg(1024, state_precision=precision)
        spec = state_spec(cfg)
        ceil = per_peer_byte_ceilings(cfg)
        assert set(spec) == set(SimState._fields)
        for f, entry in spec.items():
            assert len(entry) == 3, f"{f}: spec entry must be " \
                "(shape, dtype, peer_major)"
            shape, dtype, peer_major = entry
            assert f in _COMPACT_CODECS, \
                f"{f}: new SimState field has no codec decision " \
                "(sim/state.py _COMPACT_CODECS — None is an explicit choice)"
            if not peer_major:
                continue
            assert shape[0] == cfg.n_peers, (f, shape)
            bpp = int(np.prod(shape[1:], dtype=np.int64) if len(shape) > 1
                      else 1) * np.dtype(dtype).itemsize
            assert f in ceil, f"{f}: peer-major field missing from " \
                "per_peer_byte_ceilings"
            assert bpp <= ceil[f], \
                f"{f}: {bpp} B/peer breaches the {ceil[f]} B/peer ceiling " \
                f"under {precision!r}"

    def test_compact_strictly_beats_f32_on_coded_planes(self):
        cfg_f = scenarios.frontier_cfg(1024)
        cfg_c = scenarios.frontier_cfg(1024, state_precision="compact")
        cf, cc = per_peer_byte_ceilings(cfg_f), per_peer_byte_ceilings(cfg_c)
        for f, codec in _COMPACT_CODECS.items():
            if codec is not None and f in cf:
                assert cc[f] < cf[f], (f, codec, cc[f], cf[f])

    def test_f32_spec_is_unchanged_by_the_precision_field(self):
        """The default layout stays bit-for-bit the seed layout: the spec
        under f32 must not mention any compact dtype."""
        cfg = scenarios.frontier_cfg(1024)
        for f, (shape, dtype, _) in state_spec(cfg).items():
            assert np.dtype(dtype) not in (np.dtype(np.uint16),
                                           np.dtype(np.int16),
                                           np.dtype(np.int8)), (f, dtype)


class TestRefusals:
    def test_k_slots_over_127_refuses_compact_by_name(self):
        cfg = SimConfig(n_peers=256, k_slots=128, state_precision="compact")
        with pytest.raises(ValueError, match="k_slots"):
            state_spec(cfg)

    def test_unknown_precision_refuses_by_name(self):
        cfg = SimConfig(n_peers=256, k_slots=16, state_precision="f16")
        with pytest.raises(ValueError, match="state_precision"):
            state_spec(cfg)

    def test_checkpoint_cross_precision_restore_refuses_by_name(self, tmp_path):
        cfg_f, cfg_c, tp, st_f, st_c = _pair(n=128)
        p = str(tmp_path / "ck.npz")
        checkpoint.save(p, st_c, cfg=cfg_c)
        with pytest.raises(ValueError, match="state_precision mismatch"):
            checkpoint.restore(p, st_f, cfg=cfg_f)
        # the matching restore still round-trips bit-exact
        back = checkpoint.restore(p, st_c, cfg=cfg_c)
        for f in SimState._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(st_c, f)), np.asarray(getattr(back, f)),
                err_msg=f)
