"""prefix_count (ops/bits.py) must equal the cumsum it strength-reduces.

The masked-popcount prefix replaces jnp.cumsum at the heartbeat GRAFT
capacity-vetting and budgeted-IWANT call sites (XLA's cumsum lowering
measured ~16x slower at those shapes on CPU — the r3->r4 driver-record
regression, ROUND5_NOTES.md). Exactness is the contract: integer counts,
bit-identical to cumsum at every shape the engine uses (K=16/32/48, M=64)
plus awkward ones (non-multiples of 32, K=1, multi-word)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops.bits import prefix_count


@pytest.mark.parametrize("k", [1, 7, 16, 31, 32, 33, 48, 64, 65, 100])
@pytest.mark.parametrize("exclusive", [False, True])
def test_prefix_count_matches_cumsum(k, exclusive):
    x = jax.random.bernoulli(jax.random.PRNGKey(k), 0.3, (17, 3, k))
    want = jnp.cumsum(x.astype(jnp.int32), axis=-1)
    if exclusive:
        want = want - x.astype(jnp.int32)
    got = prefix_count(x, exclusive=exclusive)
    assert got.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("k", [32, 48, 64, 100])
def test_prefix_count_words_matches_bool_form(k):
    from go_libp2p_pubsub_tpu.ops.bits import pack_bool, prefix_count_words
    x = jax.random.bernoulli(jax.random.PRNGKey(k + 1), 0.4, (9, k))
    np.testing.assert_array_equal(
        np.asarray(prefix_count_words(pack_bool(x), k)),
        np.asarray(prefix_count(x)))


def test_prefix_count_all_set_and_empty():
    for k in (32, 48):
        ones = jnp.ones((4, k), bool)
        np.testing.assert_array_equal(
            np.asarray(prefix_count(ones)), np.arange(1, k + 1)[None].repeat(4, 0))
        np.testing.assert_array_equal(
            np.asarray(prefix_count(jnp.zeros((4, k), bool))), np.zeros((4, k), np.int32))
