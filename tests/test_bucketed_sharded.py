"""Row-sharded bucketed engine (ISSUE 16): the heavy-tailed mesh across a
real (dcn x peers) slice.

Lenses, in order of importance:

- **Ragged shard construction** — ``bucketize_state(rows=(start, count))``
  builds one shard's per-bucket planes directly; shard-concat equals the
  full build bit for bit, including a short last shard and a shard
  boundary landing INSIDE a degree bucket.
- **Bucketed checkpoints** — npz round-trip through the named-leaf layout,
  bucket-partition mismatch refused BY NAME (a bucketed checkpoint only
  resumes under its own partition), and the elastic P -> P' re-slice
  (``local_bucketed_rows_state``) recomposing the gathered state.
- **Per-(bucket x shard) pricing** — the closed-form ``powerlaw_10m``
  partition prices under GRAFT_HBM_BUDGET per (bucket x shard) with no
  topology build, and an over-budget refusal names the worst
  ``field[b# rowsxk]`` plane.
- **Refusal by name** — the dense-padded sharded plan refuses bucketed
  configs pointing at the row-sharded route; unaligned partitions refuse
  naming ``topology.align_degree_buckets``.
- **The real multi-process run** (slow tier) — 2 CPU processes over a
  localhost coordinator drive ``run_multihost.py --engine bucketed``,
  bit-exact (under ``bucketed_rng="dense"``) against the single-process
  bucketed AND dense engines; plus the SIGKILL -> relaunch -> P'=1
  elastic-resume leg under scripts/mh_supervisor.py.
"""

import dataclasses
import functools
import json
import os
import re
import subprocess
import sys

import jax
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import (SimConfig, init_state, scenarios,
                                      topology)
from go_libp2p_pubsub_tpu.sim import bucketed as bk
from go_libp2p_pubsub_tpu.sim import checkpoint
from go_libp2p_pubsub_tpu.sim.state import (check_hbm_budget, decode_state,
                                            state_spec)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, K = 128, 16
BUCKETS = topology.powerlaw_buckets(N, d_min=4, d_max=16, alpha=2.0,
                                    round_to=4)
NP = 256            # the launcher smoke's peer count (powerlaw family)


def _cfg(**over):
    kw = dict(n_peers=N, k_slots=K, n_topics=2, msg_window=8,
              publishers_per_tick=2, prop_substeps=4,
              scoring_enabled=True, gater_enabled=True,
              churn_disconnect_prob=0.05, churn_reconnect_prob=0.2,
              state_precision="f32", degree_buckets=BUCKETS,
              bucketed_rng="dense")
    kw.update(over)
    return SimConfig(**kw)


@functools.lru_cache(maxsize=None)
def _dense_decoded():
    """One decoded full-width dense state every construction lens slices."""
    cfg = _cfg()
    topo = topology.powerlaw(N, K, d_min=4, d_max=16, alpha=2.0, seed=11)
    return cfg, decode_state(init_state(cfg, topo), cfg)


def _rows_view(dense, cfg, start, count):
    """The [start, start+count) row slice of a dense state — what one rank
    of the sharded construction holds."""
    spec = state_spec(cfg)
    return dense._replace(**{
        f: getattr(dense, f)[start:start + count]
        for f in dense._fields
        if getattr(dense, f) is not None and spec[f][2]})


def _assert_parts_equal_full(full, parts, cfg):
    spec = state_spec(cfg)
    for f in full.g._fields:
        want = getattr(full.g, f)
        if want is None:
            continue
        want = np.asarray(want)
        vals = [np.asarray(getattr(p.g, f)) for p in parts]
        if spec[f][2]:
            np.testing.assert_array_equal(want, np.concatenate(vals),
                                          err_msg=f"g.{f}")
        else:
            for v in vals:
                np.testing.assert_array_equal(want, v, err_msg=f"g.{f}")
    for b in range(len(cfg.degree_buckets)):
        for f in full.e[b]._fields:
            want = np.asarray(getattr(full.e[b], f))
            got = np.concatenate(
                [np.asarray(getattr(p.e[b], f)) for p in parts])
            np.testing.assert_array_equal(want, got, err_msg=f"e{b}.{f}")
        want = np.asarray(full.rev[b])
        got = np.concatenate([np.asarray(p.rev[b]) for p in parts])
        np.testing.assert_array_equal(want, got, err_msg=f"rev{b}")


class TestRaggedRowsBuild:
    """bucketize_state(rows=) — the per-rank construction primitive."""

    @pytest.mark.parametrize("n_shards", [2, 4])
    def test_even_shard_concat_equals_full(self, n_shards):
        cfg, dense = _dense_decoded()
        full = bk.bucketize_state(dense, cfg)
        nl = N // n_shards
        parts = [bk.bucketize_state(_rows_view(dense, cfg, p * nl, nl),
                                    cfg, rows=(p * nl, nl))
                 for p in range(n_shards)]
        _assert_parts_equal_full(full, parts, cfg)

    def test_short_last_shard(self):
        """A ragged split whose last shard is shorter than the others."""
        cfg, dense = _dense_decoded()
        full = bk.bucketize_state(dense, cfg)
        splits = [(0, 48), (48, 48), (96, 32)]
        parts = [bk.bucketize_state(_rows_view(dense, cfg, s, c), cfg,
                                    rows=(s, c)) for s, c in splits]
        _assert_parts_equal_full(full, parts, cfg)

    def test_shard_boundary_splits_a_bucket(self):
        """A shard boundary strictly INSIDE a degree bucket: both sides
        carry a partial block of that bucket's rows and the concat must
        still equal the full build (the row_offsets path in _flat_rev)."""
        cfg, dense = _dense_decoded()
        starts = np.cumsum([0] + [r for r, _ in BUCKETS])
        # cut the second bucket in half
        cut = int(starts[1]) + int(BUCKETS[1][0]) // 2
        assert starts[1] < cut < starts[2], (starts, cut)
        splits = [(0, cut), (cut, N - cut)]
        full = bk.bucketize_state(dense, cfg)
        parts = [bk.bucketize_state(_rows_view(dense, cfg, s, c), cfg,
                                    rows=(s, c)) for s, c in splits]
        _assert_parts_equal_full(full, parts, cfg)

    def test_declared_rows_must_match_state(self):
        cfg, dense = _dense_decoded()
        half = _rows_view(dense, cfg, 0, N // 2)
        with pytest.raises(ValueError, match="rows"):
            bk.bucketize_state(half, cfg, rows=(0, N))


class TestLocalShards:
    """init_bucketed_local / local_bucketed_rows_state — the multi-host
    construction and elastic re-slice planes (slow tier: per-bucket
    device_init compiles)."""

    @pytest.mark.parametrize("n_proc", [2, 4])
    def test_init_bucketed_local_concat_equals_full(self, n_proc):
        from go_libp2p_pubsub_tpu.parallel.multihost import (
            init_bucketed_local, local_bucketed_rows_state)
        cfg, _ = _dense_decoded()
        topo = topology.powerlaw(N, K, d_min=4, d_max=16, alpha=2.0,
                                 seed=11)
        full = jax.tree.map(np.asarray, bk.init_bucketed_state(cfg, topo))
        locals_ = [init_bucketed_local(cfg, topo, p, n_proc)
                   for p in range(n_proc)]
        for p, loc in enumerate(locals_):
            want = local_bucketed_rows_state(full, cfg, p, n_proc)
            for (f, a), (_, b) in zip(checkpoint._named_leaves(want),
                                      checkpoint._named_leaves(loc)):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b),
                    err_msg=f"rank {p}/{n_proc} leaf {f}")


class TestBucketedCheckpoint:
    def _host_state(self):
        cfg, dense = _dense_decoded()
        return cfg, jax.tree.map(
            np.asarray, bk.encode_bucketed(bk.bucketize_state(dense, cfg),
                                           cfg))

    def test_npz_roundtrip(self, tmp_path):
        cfg, bs = self._host_state()
        path = str(tmp_path / "ckpt_t0")
        checkpoint.save(path, bs, cfg=cfg)
        back = checkpoint.restore(path, bs, cfg=cfg)
        for (f, a), (_, b) in zip(checkpoint._named_leaves(bs),
                                  checkpoint._named_leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"leaf {f}")

    def test_sidecar_stamps_bucket_partition(self, tmp_path):
        cfg, bs = self._host_state()
        path = str(tmp_path / "ckpt_t0")
        checkpoint.save(path, bs, cfg=cfg)
        meta = checkpoint.sidecar_meta(path)
        assert meta["degree_buckets"] == ",".join(
            f"{r}x{k}" for r, k in BUCKETS)

    def test_partition_mismatch_refuses_by_name(self, tmp_path):
        cfg, bs = self._host_state()
        path = str(tmp_path / "ckpt_t0")
        checkpoint.save(path, bs, cfg=cfg)
        realigned = topology.align_degree_buckets(BUCKETS, 64)
        assert realigned != BUCKETS      # or the lens is vacuous
        cfg2 = dataclasses.replace(cfg, degree_buckets=realigned,
                                   k_slots=realigned[0][1])
        with pytest.raises(ValueError, match="bucket-partition mismatch"):
            checkpoint.restore(path, bs, cfg=cfg2)

    def test_dense_checkpoint_refused_for_bucketed_run(self, tmp_path):
        cfg, dense = _dense_decoded()
        cfg_d = dataclasses.replace(cfg, degree_buckets=None)
        from go_libp2p_pubsub_tpu.sim.state import encode_state
        host = jax.tree.map(np.asarray, encode_state(dense, cfg_d))
        path = str(tmp_path / "ckpt_t0")
        checkpoint.save(path, host, cfg=cfg_d)
        _, bs = self._host_state()
        with pytest.raises(ValueError, match="bucket-partition mismatch"):
            checkpoint.restore(path, bs, cfg=cfg)

    @pytest.mark.parametrize("n_proc", [2, 4])
    def test_elastic_reslice_concat_is_identity(self, n_proc):
        """local_bucketed_rows_state at P' recomposes the gathered state:
        per-rank g rows are peer-major contiguous blocks, per-rank bucket
        rows are that bucket's own split — concatenating every rank's
        slices reproduces every leaf."""
        from go_libp2p_pubsub_tpu.parallel.multihost import (
            local_bucketed_rows_state)
        cfg, bs = self._host_state()
        spec = state_spec(cfg)
        parts = [local_bucketed_rows_state(bs, cfg, p, n_proc)
                 for p in range(n_proc)]
        for f in bs.g._fields:
            want = getattr(bs.g, f)
            if want is None or not spec[f][2]:
                continue
            got = np.concatenate([np.asarray(getattr(p.g, f))
                                  for p in parts])
            np.testing.assert_array_equal(np.asarray(want), got,
                                          err_msg=f"g.{f}")
        for b in range(len(BUCKETS)):
            for f in bs.e[b]._fields:
                got = np.concatenate([np.asarray(getattr(p.e[b], f))
                                      for p in parts])
                np.testing.assert_array_equal(
                    np.asarray(getattr(bs.e[b], f)), got,
                    err_msg=f"e{b}.{f}")
            got = np.concatenate([np.asarray(p.rev[b]) for p in parts])
            np.testing.assert_array_equal(np.asarray(bs.rev[b]), got,
                                          err_msg=f"rev{b}")


class TestBucketShardPricing:
    def test_powerlaw_10m_prices_per_bucket_shard(self):
        """The acceptance gate: the closed-form 10M partition prices under
        16 GiB/shard on an 8-way mesh with NO topology build, and the
        accounting carries the per-(bucket x shard) rows dashboards and
        refusals read."""
        cfg = scenarios.powerlaw_cfg(
            scenarios.POWERLAW_NS["powerlaw_10m"],
            shard_align=scenarios.POWERLAW_MH_ALIGN)
        acct = check_hbm_budget(cfg, 8, budget=16 * 2 ** 30,
                                what="powerlaw_10m")
        assert acct["per_shard"] <= 16 * 2 ** 30
        shards = acct["bucket_shards"]
        assert len(shards) == len(cfg.degree_buckets)
        for entry, (r, k) in zip(shards, cfg.degree_buckets):
            assert entry["rows"] == r and entry["k_ceil"] == k
            assert r % scenarios.POWERLAW_MH_ALIGN == 0
        # per-shard is exactly the sum of the per-bucket ceiling splits
        # plus the g half's row/replicated planes
        edge = sum(v for e in shards for f, v in e.items()
                   if f not in ("rows", "k_ceil"))
        assert edge < acct["per_shard"]

    def test_refusal_names_field_and_bucket(self):
        cfg = scenarios.powerlaw_cfg(
            scenarios.POWERLAW_NS["powerlaw_10m"],
            shard_align=scenarios.POWERLAW_MH_ALIGN)
        with pytest.raises(ValueError) as ei:
            check_hbm_budget(cfg, 8, budget=1 << 20, what="powerlaw_10m")
        msg = str(ei.value)
        assert "GRAFT_HBM_BUDGET" in msg
        assert re.search(r"\w+\[b\d+ \d+x\d+\]=", msg), msg


class TestShardedRefusals:
    @pytest.fixture()
    def mesh8(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices (conftest XLA_FLAGS)")
        from go_libp2p_pubsub_tpu.parallel.sharding import make_mesh
        return make_mesh(jax.devices()[:8])

    def test_dense_sharded_plan_refuses_bucketed_cfg(self, mesh8):
        from go_libp2p_pubsub_tpu.parallel.compile_plan import (
            sharded_chunk_plan)
        from go_libp2p_pubsub_tpu.sim.scenarios import default_topic_params
        cfg = _cfg()
        with pytest.raises(ValueError, match="row-sharded bucketed"):
            sharded_chunk_plan(mesh8, cfg, default_topic_params(2))

    def test_unaligned_partition_refuses_naming_the_fix(self, mesh8):
        from go_libp2p_pubsub_tpu.parallel.sharding import (
            bucketed_state_shardings)
        r0, k0 = BUCKETS[0]
        ragged = ((1, k0), (r0 - 1, k0)) + tuple(BUCKETS[1:])
        cfg = _cfg(degree_buckets=ragged)
        with pytest.raises(ValueError, match="align_degree_buckets"):
            bucketed_state_shardings(mesh8, cfg)

    def test_bucketed_step_guard_under_mesh(self, mesh8):
        from go_libp2p_pubsub_tpu.parallel.kernel_context import kernel_mesh
        from go_libp2p_pubsub_tpu.sim.scenarios import default_topic_params
        _, dense = _dense_decoded()
        r0, k0 = BUCKETS[0]
        ragged = ((1, k0), (r0 - 1, k0)) + tuple(BUCKETS[1:])
        cfg = _cfg(degree_buckets=ragged)
        bs = bk.bucketize_state(dense, cfg)
        with kernel_mesh(mesh8, ("peers",)):
            with pytest.raises(ValueError, match="align_degree_buckets"):
                bk.bucketed_step(bs, cfg, default_topic_params(2),
                                 jax.random.PRNGKey(0))

    def test_route_bucketed_flat_needs_a_mesh(self):
        from go_libp2p_pubsub_tpu.parallel.halo import route_bucketed_flat
        with pytest.raises(ValueError, match="kernel_mesh"):
            route_bucketed_flat([np.zeros((8, 4), np.uint32)],
                                [np.zeros((8, 4), np.int32)])

    def test_align_degree_buckets_contract(self):
        aligned = topology.align_degree_buckets(BUCKETS, 64)
        assert sum(r for r, _ in aligned) == N
        assert all(r % 64 == 0 for r, _ in aligned)
        ks = [k for _, k in aligned]
        assert ks == sorted(ks, reverse=True)
        with pytest.raises(ValueError, match="multiple"):
            topology.align_degree_buckets(((100, 8),), 64)


# ---------------------------------------------------------------------------
# 8-device sharded execution parity (slow tier; fresh subprocess — the
# backend multi-mesh poison test_sharding.py documents)


def _subprocess(code, timeout=540):
    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env
    env = cpu_mesh_env(dict(os.environ), 8)
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=timeout,
                          cwd=REPO)


def test_sharded_bucketed_routes_bit_exact():
    """Both sharded routes of the bucketed step on a real 8-device mesh —
    'replicated' and 'halo' (route_bucketed_flat: per-(src, dst)-bucket
    push at exact measured capacity) — reproduce the single-device
    bucketed trajectory bit for bit, with zero halo overflow."""
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, topology
from go_libp2p_pubsub_tpu.sim.bucketed import (
    bucketed_run, init_bucketed_state, densify_state, decode_bucketed)
from go_libp2p_pubsub_tpu.parallel.sharding import (
    make_mesh, make_sharded_bucketed_run, shard_bucketed_state)
from go_libp2p_pubsub_tpu.parallel.halo import required_bucket_capacity

N, K = 128, 16
bks = topology.powerlaw_buckets(N, d_min=4, d_max=16, alpha=2.0, round_to=4)
bks = topology.align_degree_buckets(bks, 8)
topo = topology.powerlaw(N, K, d_min=4, d_max=16, alpha=2.0, seed=11)
cap = required_bucket_capacity(topo.neighbors, topo.reverse_slot, 8,
                               buckets=bks)
kw = dict(n_peers=N, k_slots=K, n_topics=2, msg_window=8,
          publishers_per_tick=2, prop_substeps=4,
          scoring_enabled=True, behaviour_penalty_weight=-1.0,
          gossip_threshold=-10.0, publish_threshold=-20.0,
          graylist_threshold=-30.0,
          churn_disconnect_prob=0.05, churn_reconnect_prob=0.2,
          px_enabled=True, accept_px_threshold=-5.0, retain_score_ticks=10,
          gater_enabled=True, degree_buckets=bks, bucketed_rng="dense",
          invariant_mode="record", state_precision="f32")
tp = TopicParams.disabled(2)
key = jax.random.PRNGKey(0)
T = 4
cfg0 = SimConfig(**kw)
bs_ref = bucketed_run(init_bucketed_state(cfg0, topo), cfg0, tp, key, T)
ref = jax.tree.map(np.asarray,
                   densify_state(decode_bucketed(bs_ref, cfg0), cfg0))
mesh = make_mesh(jax.devices()[:8])
for route in ("replicated", "halo"):
    cfg = SimConfig(**kw, sharded_route=route,
                    halo_bucket_capacity=cap if route == "halo" else 0)
    run = make_sharded_bucketed_run(mesh, cfg, tp)
    bs0 = shard_bucketed_state(init_bucketed_state(cfg, topo), mesh, cfg)
    out = run(bs0, jax.random.split(key, T))
    got = jax.tree.map(np.asarray,
                       densify_state(decode_bucketed(out, cfg), cfg))
    bad = [f for f in ref._fields
           if getattr(ref, f) is not None
           and not np.array_equal(getattr(ref, f), getattr(got, f))]
    assert not bad, (route, bad)
    assert int(got.halo_overflow) == 0, int(got.halo_overflow)
print("BUCKETED_SHARDED_OK")
"""
    res = _subprocess(code)
    assert "BUCKETED_SHARDED_OK" in res.stdout, res.stderr[-3000:]


# ---------------------------------------------------------------------------
# THE acceptance smoke (slow tier): 2 real CPU processes over a localhost
# coordinator drive the bucketed engine; bit-exact vs single-process
# bucketed AND dense engines; then the SIGKILL -> relaunch -> P'=1 leg.


def _spawn_rank(rank, port, extra, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env.pop("XLA_FLAGS", None)      # one local CPU device per rank
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "run_multihost.py"),
         "--coordinator", f"localhost:{port}", "--num-processes", "2",
         "--process-id", str(rank), "--engine", "bucketed",
         "--scenario", "powerlaw_100k", "--n", str(NP), "--seed", "7",
         "--bucketed-rng", "dense"] + extra,
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=str(tmp_path))


def _run_pair(port, extra, tmp_path):
    procs = [_spawn_rank(r, port, extra, tmp_path) for r in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for (out, err), p in zip(outs, procs):
        assert p.returncode == 0, f"rank rc={p.returncode}\n{err[-3000:]}"
    return outs


@functools.lru_cache(maxsize=None)
def _mh_reference(ticks):
    """Single-process bucketed trajectory under the launcher's key
    discipline (supervised_run pre-splits PRNGKey(seed) into per-tick
    keys) on the exact powerlaw_mh_spec the launcher builds."""
    cfg, tp, topo_rows, sub = scenarios.powerlaw_mh_spec(
        NP, bucketed_rng="dense")
    topo = topo_rows(0, NP)
    bs = bk.init_bucketed_state(cfg, topo, subscribed=sub)
    step = jax.jit(lambda s, k: bk.bucketed_step(s, cfg, tp, k))
    for k in jax.random.split(jax.random.PRNGKey(7), ticks):
        bs = step(bs, k)
    return cfg, tp, topo, sub, jax.block_until_ready(bs)


def _assert_dump_matches(dump_path, bs_ref):
    got = np.load(dump_path)
    for f, v in checkpoint._named_leaves(bs_ref):
        np.testing.assert_array_equal(
            np.asarray(v), got[f],
            err_msg=f"leaf {f} diverged (multi-process vs single)")


def test_two_process_bucketed_bit_exact(tmp_path):
    """2 real processes, gloo collectives, the row-sharded bucketed step:
    the gathered final state equals the single-process bucketed scan leaf
    for leaf, and (bucketed_rng='dense') the dense engine field for field
    — the layout is an execution strategy, not a model change."""
    dump = tmp_path / "run1.npz"
    _run_pair(19931, ["--ticks", "3", "--dump-state", str(dump)], tmp_path)
    cfg, tp, topo, sub, bs_ref = _mh_reference(3)
    _assert_dump_matches(dump, bs_ref)

    # the dense engine on the same graph, same keys: bit-exact too
    from go_libp2p_pubsub_tpu.sim.engine import run_keys
    cfg_d = dataclasses.replace(cfg, degree_buckets=None)
    st = init_state(cfg_d, topo, subscribed=sub)
    out = run_keys(st, cfg_d, tp,
                   jax.random.split(jax.random.PRNGKey(7), 3))
    dense = decode_state(jax.block_until_ready(out), cfg_d)
    buck = bk.densify_state(bk.decode_bucketed(bs_ref, cfg), cfg)
    for f in dense._fields:
        a, b = getattr(dense, f), getattr(buck, f)
        if a is None and b is None:
            continue
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"field {f}: bucketed multi-process vs dense engine")


def test_mh_supervisor_bucketed_sigkill_relaunch_elastic(tmp_path):
    """The resilience acceptance on the BUCKETED plane: rank 1 of a
    2-process run SIGKILLs itself (GRAFT_CHAOS) after the t=2 bucketed
    checkpoint drained; the group supervisor relaunches at P'=1, the
    relaunched rank restores the P=2 bucketed checkpoint through
    local_bucketed_rows_state (elastic re-slice), and the final state is
    bit-exact vs the uninterrupted single-process bucketed scan."""
    run_dir = tmp_path / "mh"
    final = tmp_path / "final.npz"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               GRAFT_CHAOS="kill@1:4",
               GRAFT_MH_PEER_TIMEOUT_S="6", GRAFT_MH_ABORT_GRACE_S="3",
               GRAFT_MH_BEAT_INTERVAL_S="0.5")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "mh_supervisor.py"),
         "--procs", "2,1", "--engine", "bucketed",
         "--scenario", "powerlaw_100k", "--n", str(NP),
         "--bucketed-rng", "dense",
         "--ticks", "6", "--seed", "7", "--chunk-ticks", "2",
         "--run-dir", str(run_dir), "--max-relaunches", "2",
         "--backoff-base-s", "0.05", "--dump-state", str(final)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=560)
    journal = [json.loads(ln)
               for ln in (run_dir / "mh_journal.jsonl").read_text()
               .splitlines()]
    assert proc.returncode == 0, (proc.stdout, proc.stderr, journal)

    attempts = [r for r in journal if r["kind"] == "mh_attempt"]
    assert len(attempts) >= 2
    assert attempts[0]["procs"] == 2 and attempts[-1]["procs"] == 1
    assert any(r["kind"] == "mh_done" for r in journal)

    # the relaunched rank RESUMED from the bucketed checkpoint
    last = attempts[-1]["attempt"]
    rank0_log = (run_dir / f"rank0.attempt{last}.log").read_text()
    metric = next(json.loads(ln) for ln in rank0_log.splitlines()
                  if ln.startswith("{") and "\"metric\"" in ln)
    assert metric["resumed_from"] is not None
    assert metric["engine"] == "bucketed"

    _, _, _, _, bs_ref = _mh_reference(6)
    _assert_dump_matches(final, bs_ref)


def test_powerlaw_10m_gate_refuses_before_building(tmp_path):
    """GRAFT_HBM_BUDGET gates the real 10M launch CLOSED-FORM: the refusal
    lands in seconds (a 10M underlay build would take minutes and the
    state would OOM first) and names a (field x bucket) plane."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               GRAFT_HBM_BUDGET="64MiB")
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_multihost.py"),
         "--engine", "bucketed", "--scenario", "powerlaw_10m",
         "--topology", "sharded", "--ticks", "1"],
        env=env, capture_output=True, text=True, timeout=240,
        cwd=str(tmp_path))
    assert res.returncode != 0
    assert "GRAFT_HBM_BUDGET" in res.stderr
    assert re.search(r"\w+\[b\d+ \d+x\d+\]=", res.stderr), \
        res.stderr[-2000:]
