"""Round-trip: batched-sim run -> pb/trace event stream -> replay -> state.

Closes the trace-interop loop (SURVEY.md §5.1): sim/trace_export.py emits
the same tracer-bus dicts the functional runtime's EventTracer produces;
pb/codec serializes them; trace/replay.py re-injects them. Mesh,
subscriptions, delivery state, and the first-delivery score counters must
survive the full cycle exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu.core.params import TopicScoreParams
from go_libp2p_pubsub_tpu.pb.codec import decode_trace_bytes, encode_trace_event
from go_libp2p_pubsub_tpu.pb.codec import write_uvarint
from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
from go_libp2p_pubsub_tpu.sim.state import NEVER
from go_libp2p_pubsub_tpu.sim.trace_export import run_traced
from go_libp2p_pubsub_tpu.trace.replay import (
    replay_feed, replay_topic_params, tensorize_trace)

TSP = TopicScoreParams(
    topic_weight=1.0, time_in_mesh_quantum=1.0,
    first_message_deliveries_weight=1.0, first_message_deliveries_decay=1.0,
    first_message_deliveries_cap=100.0)

N, K, TICKS = 24, 8, 6


def _run_and_replay():
    from go_libp2p_pubsub_tpu.sim.config import TopicParams

    cfg = SimConfig(n_peers=N, k_slots=K, n_topics=1, msg_window=32,
                    publishers_per_tick=2, prop_substeps=4,
                    scoring_enabled=True, record_provenance=True)
    tp = TopicParams.from_topic_params([TSP])
    topo = topology.sparse(N, K, degree=4, seed=9)
    st0 = init_state(cfg, topo)
    st, events = run_traced(st0, cfg, tp, jax.random.PRNGKey(5), TICKS)

    # initial conditions as events: everyone joined topic 0 at t=0
    pre = [{"type": "JOIN", "peerID": f"p{i}", "timestamp": 0.1,
            "join": {"topic": "t0"}} for i in range(N)]
    events = pre + events

    # serialize through the pb/trace wire format and back (schema fidelity)
    blob = b"".join(write_uvarint(len(b)) + b
                    for b in map(encode_trace_event, events))
    decoded = decode_trace_bytes(blob)
    assert len(decoded) == len(events)

    peer_index = {f"p{i}": i for i in range(N)}
    feed = tensorize_trace(decoded, peer_index, {"t0": 0},
                           msg_window=64, decay_interval=1.0,
                           t_end=float(TICKS))
    rcfg = SimConfig(n_peers=N, k_slots=K, n_topics=1, msg_window=64,
                     scoring_enabled=True)
    rtp = replay_topic_params([TSP])
    rst = init_state(rcfg, topo, subscribed=np.zeros((N, 1), bool))
    rst = replay_feed(rst, rcfg, rtp, feed)
    return st, rst, cfg, rcfg


class TestSimTraceRoundTrip:
    def setup_method(self):
        self.st, self.rst, self.cfg, self.rcfg = _run_and_replay()

    def test_subscriptions_match(self):
        np.testing.assert_array_equal(np.asarray(self.st.subscribed),
                                      np.asarray(self.rst.subscribed))

    def test_mesh_matches(self):
        np.testing.assert_array_equal(np.asarray(self.st.mesh),
                                      np.asarray(self.rst.mesh))

    def test_first_message_deliveries_match(self):
        np.testing.assert_allclose(
            np.asarray(self.st.first_message_deliveries),
            np.asarray(self.rst.first_message_deliveries), atol=1e-5)

    def test_delivery_counts_match(self):
        # per-peer count of delivered messages (slot numbering differs
        # between the run and the replay, counts must not)
        sim_live = np.asarray(self.st.msg_topic) >= 0
        sim_cnt = ((np.asarray(self.st.deliver_tick) < int(NEVER))
                   & sim_live[None, :]).sum(axis=1)
        rep_cnt = (np.asarray(self.rst.deliver_tick) < int(NEVER)).sum(axis=1)
        np.testing.assert_array_equal(sim_cnt, rep_cnt)
