"""Latency-hiding supervisor pipeline (ISSUE 12).

The correctness claim of the async lane: double-buffered dispatch,
speculation, the off-path writer thread, and on-device key generation
change WHEN work happens, never WHAT is computed — the final state of an
``async_chunks=True`` run is bit-identical to the synchronous supervised
run and to the unsupervised single scan, on every plane (plain / fleet /
sharded), through failures mid-overlap, donated-input retries, kills,
and writer backpressure.

Shapes are harmonized with test_supervisor.py (64 peers, chunk 5) so the
chunk executables come out of the shared AOT cache.
"""

import dataclasses
import json
import time

import jax
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import (SimConfig, TopicParams, init_state,
                                      topology)
from go_libp2p_pubsub_tpu.sim import checkpoint
from go_libp2p_pubsub_tpu.sim import supervisor as supervisor_mod
from go_libp2p_pubsub_tpu.sim.engine import run
from go_libp2p_pubsub_tpu.sim.supervisor import (ChunkDeadline,
                                                 SupervisorConfig,
                                                 supervised_run)

pytestmark = pytest.mark.supervisor

N_TICKS = 20


def _assert_states_equal(a, b):
    for f, x, y in zip(a._fields, a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"field {f}")


@pytest.fixture(scope="module")
def plain():
    """Same tiny config as test_supervisor.py (shared jit cache), with a
    20-tick reference so the chunk-5 pipeline gets a boundary mid-run
    (ckpt cadence 10) AND donated mid-cadence chunks on both sides."""
    cfg = SimConfig(n_peers=64, k_slots=8, n_topics=1, msg_window=32,
                    publishers_per_tick=2, prop_substeps=4,
                    scoring_enabled=True)
    tp = TopicParams.disabled(1)
    st = init_state(cfg, topology.sparse(64, 8, degree=3))
    key = jax.random.PRNGKey(42)
    return cfg, tp, st, key, run(st, cfg, tp, key, N_TICKS)


def _sup(asynch, **kw):
    kw.setdefault("chunk_ticks", 5)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("async_chunks", asynch)
    return SupervisorConfig(**kw)


def _events(rep, name):
    return [e for e in rep.events if e["event"] == name]


class TestAsyncParity:
    def test_async_equals_sync_equals_unsupervised(self, plain, tmp_path):
        """THE acceptance case: with checkpoints on a mid-run cadence
        (mid-cadence chunk outputs are donated into their successor's
        dispatch), the async pipeline lands bit-identical to both the
        synchronous supervised run and the plain single scan."""
        cfg, tp, st, key, ref = plain
        out_a, rep_a = supervised_run(
            st, cfg, tp, key, N_TICKS,
            _sup(True, checkpoint_dir=str(tmp_path / "a"),
                 checkpoint_every_ticks=10))
        out_s, rep_s = supervised_run(
            st, cfg, tp, key, N_TICKS,
            _sup(False, checkpoint_dir=str(tmp_path / "s"),
                 checkpoint_every_ticks=10))
        _assert_states_equal(ref, out_a)
        _assert_states_equal(out_a, out_s)
        assert rep_a.chunks_run == rep_s.chunks_run == 4
        assert rep_a.retries == 0
        # both wrote the same checkpoint cadence
        assert len(rep_a.checkpoints) == len(rep_s.checkpoints) == 2

    def test_fold_in_schedule_parity(self, plain):
        """key_schedule="fold_in" (per-tick keys derived ON DEVICE from
        the master key + carried tick): supervised async == supervised
        sync == engine.run under the same schedule."""
        cfg, tp, st, key, _ = plain
        fcfg = dataclasses.replace(cfg, key_schedule="fold_in")
        ref = run(st, fcfg, tp, key, N_TICKS)
        out_a, rep_a = supervised_run(st, fcfg, tp, key, N_TICKS,
                                      _sup(True))
        out_s, _ = supervised_run(st, fcfg, tp, key, N_TICKS, _sup(False))
        _assert_states_equal(ref, out_a)
        _assert_states_equal(out_a, out_s)
        assert rep_a.retries == 0


class TestOverlapFailures:
    def test_spec_dispatch_failure_discards_and_retries(self, plain):
        """A speculative dispatch that fails must not poison chunk k:
        k's result is kept, the in-flight k+1 is discarded, and the
        retry of k+1 is bit-exact."""
        cfg, tp, st, key, ref = plain

        def boom(info):
            if info["chunk_start"] == 10 and info["attempt"] == 0:
                raise RuntimeError("injected overlap fault")

        out, rep = supervised_run(st, cfg, tp, key, N_TICKS, _sup(True),
                                  _chunk_hook=boom)
        _assert_states_equal(ref, out)
        assert rep.retries == 1
        assert len(_events(rep, "chunk_failed")) == 1
        # the confirmed carry chain never includes the failed attempt
        assert rep.ticks_run == N_TICKS and rep.chunks_run == 4

    def test_confirm_failure_on_donated_input_catches_up(self, plain,
                                                         monkeypatch):
        """The hard donation case: chunk k=[5,10)'s input ([0,5).out,
        mid-cadence under ckpt_every=10) was donated into k's own
        dispatch, and k+1=[10,15) is already in flight when k's
        confirmation trips the watchdog. The retry lands on a deleted
        input, silently replays [0,5) from the anchor (the "catchup"
        event — no journal/report double-count), discards the in-flight
        speculation unseen ("spec_discarded"), and still finishes
        bit-exact."""
        cfg, tp, st, key, ref = plain
        real = supervisor_mod._confirm
        tripped = []

        def flaky(pend, sup, scale=1.0):
            if pend.info.get("chunk_start") == 5 and not tripped:
                tripped.append(1)
                raise ChunkDeadline("injected confirm deadline")
            return real(pend, sup, scale)

        monkeypatch.setattr(supervisor_mod, "_confirm", flaky)
        out, rep = supervised_run(st, cfg, tp, key, N_TICKS,
                                  _sup(True, checkpoint_every_ticks=10))
        _assert_states_equal(ref, out)
        assert rep.retries == 1
        assert len(_events(rep, "spec_discarded")) == 1
        assert len(_events(rep, "catchup")) == 1
        # counters only ever saw confirmed chunks: the replay is silent
        assert rep.ticks_run == N_TICKS and rep.chunks_run == 4

    def test_kill_mid_overlap_resumes_from_drained_checkpoint(self, plain,
                                                              tmp_path):
        """A kill arriving while chunk k+1 speculates must not lose the
        already-confirmed work: chunk k is confirmed and its writes
        drained before the interrupt escapes, so the resume picks up the
        last durable checkpoint."""
        cfg, tp, st, key, ref = plain
        ck = str(tmp_path / "ck")

        def kill(info):
            if info["chunk_start"] >= 15:
                raise KeyboardInterrupt("simulated preemption")

        with pytest.raises(KeyboardInterrupt):
            supervised_run(st, cfg, tp, key, N_TICKS,
                           _sup(True, checkpoint_dir=ck,
                                checkpoint_every_ticks=10),
                           _chunk_hook=kill)
        out, rep = supervised_run(st, cfg, tp, key, N_TICKS,
                                  _sup(True, checkpoint_dir=ck,
                                       checkpoint_every_ticks=10))
        assert rep.resumed_tick == 10   # the t10 checkpoint WAS drained
        assert rep.ticks_run == 10      # only [10, 20) re-ran
        _assert_states_equal(ref, out)


class TestWriterPlane:
    def test_writer_backpressure_stays_bounded(self, plain, tmp_path,
                                               monkeypatch):
        """A slow writer (50 ms per checkpoint save) against a depth-1
        queue: submit blocks instead of queueing unboundedly, every
        checkpoint still lands, and the result is bit-exact."""
        cfg, tp, st, key, ref = plain
        depths = []

        class Probe(supervisor_mod._Writer):
            def submit(self, task):
                if self._thread is not None:
                    depths.append(self._q.qsize())
                super().submit(task)

        real_save = checkpoint.save

        def slow_save(*a, **kw):
            time.sleep(0.05)
            return real_save(*a, **kw)

        monkeypatch.setattr(supervisor_mod, "_Writer", Probe)
        monkeypatch.setattr(checkpoint, "save", slow_save)
        out, rep = supervised_run(
            st, cfg, tp, key, N_TICKS,
            _sup(True, checkpoint_dir=str(tmp_path / "ck"),
                 checkpoint_every_ticks=5, writer_queue=1))
        _assert_states_equal(ref, out)
        assert len(rep.checkpoints) == 4
        assert depths and max(depths) <= 1   # the bound held throughout
        # drain barrier: the newest checkpoints are durable on return
        from go_libp2p_pubsub_tpu.sim.supervisor import list_checkpoints
        assert [t for _, t in list_checkpoints(str(tmp_path / "ck"))] \
            == [15, 20]

    def test_journal_chunk_markers_carry_done_wall(self, plain, tmp_path):
        """The dashboard's honest hb/s clock: every streamed chunk
        marker carries a dispatch-complete ``done_wall`` stamp (wall
        stamps at append time happen in writer-thread bursts and would
        distort rates)."""
        cfg, tp, st, key, _ = plain
        hp = str(tmp_path / "health.jsonl")
        supervised_run(st, cfg, tp, key, N_TICKS,
                       _sup(True, health_path=hp))
        with open(hp) as f:
            chunks = [json.loads(ln) for ln in f
                      if ln.startswith("{") and '"kind": "chunk"' in ln]
        assert len(chunks) == 4
        walls = [c["done_wall"] for c in chunks]
        assert walls == sorted(walls)
        # confirm-time stamp precedes (or equals) the writer's append
        assert all(c["done_wall"] <= c["wall"] for c in chunks)


class TestFleetOverlap:
    def test_fleet_async_parity_heterogeneous_ticks(self, plain):
        """Fleet windows pipeline too (speculation composes _take_rows /
        _put_rows on in-flight futures): async == sync == per-member
        engine.run, across a compaction boundary (member finishing
        mid-run shrinks the batch)."""
        from go_libp2p_pubsub_tpu.sim.fleet import (FleetMember,
                                                    supervised_fleet_run)
        cfg, tp, st, _, _ = plain
        members = [FleetMember(cfg=cfg, tp=tp, state=st,
                               key=jax.random.PRNGKey(100 + i),
                               n_ticks=n, name=f"m{i}")
                   for i, n in enumerate((12, 20))]
        refs = [run(st, cfg, tp, m.key, m.n_ticks) for m in members]
        res_a, rep_a = supervised_fleet_run(members, _sup(True))
        res_s, rep_s = supervised_fleet_run(members, _sup(False))
        for ref, ra, rs in zip(refs, res_a, res_s):
            _assert_states_equal(ref, ra.state)
            _assert_states_equal(ra.state, rs.state)
        assert rep_a.retries == 0
        assert [r.ticks_run for r in res_a] == [12, 20]

    def test_fleet_failure_mid_overlap_retries_bit_exact(self, plain):
        """A window failing while its successor speculates: the in-flight
        window is discarded (fleet never donates — the retry re-runs
        from the intact full state) and the fleet still lands bit-exact."""
        from go_libp2p_pubsub_tpu.sim.fleet import (FleetMember,
                                                    supervised_fleet_run)
        cfg, tp, st, _, _ = plain
        members = [FleetMember(cfg=cfg, tp=tp, state=st,
                               key=jax.random.PRNGKey(200 + i), n_ticks=15,
                               name=f"m{i}") for i in range(2)]
        refs = [run(st, cfg, tp, m.key, m.n_ticks) for m in members]

        def boom(info):
            if info.get("window_start") == 10 and info["attempt"] == 0:
                raise RuntimeError("injected fleet overlap fault")

        res, rep = supervised_fleet_run(members, _sup(True),
                                        _chunk_hook=boom)
        for ref, r in zip(refs, res):
            _assert_states_equal(ref, r.state)
        assert rep.retries == 1
        assert len(_events(rep, "chunk_failed")) == 1


class TestShardedOverlap:
    def test_sharded_async_parity(self):
        """The run_fn lane (the multihost sharded scan's dispatch path)
        pipelines without donation: async supervised over the 8-device
        sharded chunk runner == plain unsharded engine.run."""
        from go_libp2p_pubsub_tpu.parallel.sharding import (
            make_mesh, make_sharded_run_keys, shard_state)
        from go_libp2p_pubsub_tpu.sim import scenarios

        cfg, tp, topo, sub = scenarios.frontier_spec(128)
        st = init_state(cfg, topo, subscribed=sub)
        key = jax.random.PRNGKey(11)
        ref = run(st, cfg, tp, key, 10)
        mesh = make_mesh()
        runner = make_sharded_run_keys(mesh, cfg, tp)
        out, rep = supervised_run(
            shard_state(st, mesh, cfg), cfg, tp, key, 10,
            _sup(True, max_retries=0,
                 run_fn=lambda state, exec_cfg, tp_arg, keys:
                     runner(state, keys, tp_arg)))
        _assert_states_equal(ref, out)
        assert rep.chunks_run == 2 and rep.retries == 0
