"""Adversarial + behavioral parity tests for the host-side functional
runtime, mirroring the reference's hostile-actor suite:

- IWANT spam retransmission cutoff (gossipsub_spam_test.go:23)
- IHAVE flood protection (gossipsub_spam_test.go:134, gossipsub.go:630-660)
- GRAFT during backoff -> behaviour penalty (gossipsub_spam_test.go:365)
- direct peers (gossipsub_test.go:1221)
- flood publish (gossipsub_test.go:1412)
- opportunistic grafting (gossipsub_test.go:1804)
- star topology relay (gossipsub_test.go:1044-1127)

The raw mock peer speaks hand-built RPCs over the substrate without a
PubSub instance — the newMockGS pattern (gossipsub_spam_test.go:767).
"""

from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
from go_libp2p_pubsub_tpu.core.params import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.core.types import (
    RPC,
    ControlGraft,
    ControlIHave,
    ControlIWant,
    ControlMessage,
    ControlPrune,
    SubOpts,
)
from go_libp2p_pubsub_tpu.net import Network
from go_libp2p_pubsub_tpu.routers.feat import GOSSIPSUB_ID_V11
from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
from go_libp2p_pubsub_tpu.trace import MemoryTracer


class RawPeer:
    """Hand-rolled gossipsub speaker: records every inbound RPC, sends
    whatever control messages the test scripts (newMockGS, spam suite)."""

    def __init__(self, net: Network):
        self.host = net.add_host()
        self.inbox: list[RPC] = []
        self.host.set_protocols([GOSSIPSUB_ID_V11], lambda p, proto: None,
                                lambda src, rpc: self.inbox.append(rpc))

    @property
    def pid(self):
        return self.host.peer_id

    def connect(self, node: PubSub) -> None:
        self.host.connect(node.host)

    def send(self, node: PubSub, rpc: RPC) -> None:
        self.host.send(node.pid, rpc)

    def subscribe(self, node: PubSub, topic: str) -> None:
        self.send(node, RPC(subscriptions=[SubOpts(True, topic)]))

    def received_messages(self):
        return [m for rpc in self.inbox for m in rpc.publish]

    def received_ihave_ids(self):
        return [mid for rpc in self.inbox if rpc.control
                for ih in rpc.control.ihave for mid in ih.message_ids]

    def received_iwant_ids(self):
        return [mid for rpc in self.inbox if rpc.control
                for iw in rpc.control.iwant for mid in iw.message_ids]

    def received_prunes(self):
        return [pr for rpc in self.inbox if rpc.control
                for pr in rpc.control.prune]


def one_node(net, **router_kw):
    h = net.add_host()
    return PubSub(h, GossipSubRouter(**router_kw), sign_policy=LAX_NO_SIGN)


class TestIWantRetransmissionCutoff:
    def test_repeated_iwant_cut_off(self):
        # gossipsub_spam_test.go:23: the same id re-requested more than
        # GossipRetransmission times stops being served (mcache.go:66-80)
        net = Network()
        node = one_node(net, params=GossipSubParams(gossip_retransmission=3))
        node.join("t").subscribe()
        mock = RawPeer(net)
        mock.connect(node)
        net.scheduler.run_for(0.2)
        mock.subscribe(node, "t")
        net.scheduler.run_for(1.0)
        node.my_topics["t"].publish(b"payload")
        net.scheduler.run_for(0.5)
        # the mock (grafted as the only topic peer) got the eager push;
        # like iwantEverything it re-requests the id it already has
        got = mock.received_messages()
        assert got, "expected the eager mesh push"
        mid = node.id_gen.id(got[0])
        mock.inbox.clear()
        for _ in range(10):
            mock.send(node, RPC(control=ControlMessage(
                iwant=[ControlIWant(message_ids=[mid])])))
            net.scheduler.run_for(0.05)
        # served at most GossipRetransmission times, then cut off
        assert len(mock.received_messages()) == 3


class TestIHaveFloodProtection:
    def test_max_ihave_messages_per_heartbeat(self):
        # gossipsub_spam_test.go:134 / gossipsub.go:645-660: more than
        # MaxIHaveMessages advertisements within one heartbeat are ignored
        net = Network()
        node = one_node(net, params=GossipSubParams(max_ihave_messages=5))
        node.join("t").subscribe()
        mock = RawPeer(net)
        mock.connect(node)
        net.scheduler.run_for(0.2)
        mock.subscribe(node, "t")
        net.scheduler.run_for(0.95)  # stay inside one heartbeat window
        for i in range(20):
            mock.send(node, RPC(control=ControlMessage(ihave=[
                ControlIHave(topic="t", message_ids=["fake-%d" % i])])))
        net.scheduler.run_for(0.04)
        # one IWANT per accepted IHAVE; the 6th..20th are dropped
        assert len(mock.received_iwant_ids()) == 5

    def test_max_ihave_length_budget(self):
        # iasked budget: ids asked per advertiser per heartbeat is capped by
        # MaxIHaveLength (gossipsub.go:662-676)
        net = Network()
        node = one_node(net, params=GossipSubParams(max_ihave_length=7))
        node.join("t").subscribe()
        mock = RawPeer(net)
        mock.connect(node)
        net.scheduler.run_for(0.2)
        mock.subscribe(node, "t")
        net.scheduler.run_for(0.95)
        mock.send(node, RPC(control=ControlMessage(ihave=[
            ControlIHave(topic="t",
                         message_ids=["fake-%d" % i for i in range(30)])])))
        net.scheduler.run_for(0.04)
        assert len(mock.received_iwant_ids()) == 7


class TestGraftBackoffPenalty:
    def test_regraft_during_backoff_penalized(self):
        # gossipsub_spam_test.go:365: GRAFT while in PRUNE backoff earns
        # behaviour penalties (one + one for flood regraft) and a re-PRUNE
        net = Network()
        sp = PeerScoreParams(
            app_specific_score=lambda p: 0.0, decay_interval=1.0,
            decay_to_zero=0.01, behaviour_penalty_weight=-1.0,
            behaviour_penalty_decay=0.9,
            topics={"t": TopicScoreParams(topic_weight=1.0,
                                          time_in_mesh_quantum=1.0)})
        node = one_node(net, score_params=sp,
                        thresholds=PeerScoreThresholds(
                            gossip_threshold=-100, publish_threshold=-200,
                            graylist_threshold=-300))
        node.join("t").subscribe()
        mock = RawPeer(net)
        mock.connect(node)
        net.scheduler.run_for(0.2)
        mock.subscribe(node, "t")
        net.scheduler.run_for(0.2)
        # graft in, then prune ourselves out: node records a backoff for us
        mock.send(node, RPC(control=ControlMessage(
            graft=[ControlGraft(topic="t")])))
        net.scheduler.run_for(0.05)
        assert mock.pid in node.rt.mesh["t"]
        mock.send(node, RPC(control=ControlMessage(
            prune=[ControlPrune(topic="t")])))
        net.scheduler.run_for(0.05)
        assert mock.pid not in node.rt.mesh["t"]
        # regraft during backoff: penalized (double: within flood threshold)
        mock.inbox.clear()
        mock.send(node, RPC(control=ControlMessage(
            graft=[ControlGraft(topic="t")])))
        net.scheduler.run_for(0.05)
        assert mock.pid not in node.rt.mesh["t"]
        assert [pr.topic for pr in mock.received_prunes()] == ["t"]
        # P7: two penalty points -> -(2^2) = -4
        assert node.rt.score.score(mock.pid) == -4.0


class TestDirectPeers:
    def test_direct_always_accepted_never_meshed(self):
        # gossipsub_test.go:1221: direct peers bypass the gater/graylist but
        # GRAFTs from them are refused (gossipsub.go:761-767)
        net = Network()
        hA = net.add_host()
        hB = net.add_host()
        a = PubSub(hA, GossipSubRouter(direct_peers=[hB.peer_id]),
                   sign_policy=LAX_NO_SIGN)
        b = PubSub(hB, GossipSubRouter(direct_peers=[hA.peer_id]),
                   sign_policy=LAX_NO_SIGN)
        net.connect_all([hA, hB])
        net.scheduler.run_for(0.2)
        sa = a.join("t").subscribe()
        sb = b.join("t").subscribe()
        net.scheduler.run_for(2.5)
        from go_libp2p_pubsub_tpu.core.types import AcceptStatus
        assert a.rt.accept_from(b.pid) == AcceptStatus.ACCEPT_ALL
        # direct peers are excluded from the mesh on both sides
        assert b.pid not in a.rt.mesh.get("t", set())
        assert a.pid not in b.rt.mesh.get("t", set())
        # ...but messages still flow (Publish includes direct peers,
        # gossipsub.go:996-1000)
        a.my_topics["t"].publish(b"direct hello")
        net.scheduler.run_for(0.5)
        got = [m for m in iter(sb.next, None)]
        assert any(m.data == b"direct hello" for m in got)

    def test_direct_connect_retries(self):
        # gossipsub.go:1648-1670: direct peers are dialed at attach and
        # re-dialed every DirectConnectTicks if the connection dropped
        net = Network()
        hA = net.add_host()
        hB = net.add_host()
        a = PubSub(hA, GossipSubRouter(
            direct_peers=[hB.peer_id],
            params=GossipSubParams(direct_connect_ticks=2,
                                   direct_connect_initial_delay=0.1)),
            sign_policy=LAX_NO_SIGN)
        PubSub(hB, GossipSubRouter(direct_peers=[hA.peer_id]),
               sign_policy=LAX_NO_SIGN)
        net.scheduler.run_for(0.5)
        assert hB.peer_id in hA.conns
        hA.disconnect(hB.peer_id)
        net.scheduler.run_for(0.1)
        assert hB.peer_id not in hA.conns
        net.scheduler.run_for(3.0)   # next direct-connect sweep re-dials
        assert hB.peer_id in hA.conns


class TestFloodPublish:
    def _count_receivers(self, flood: bool) -> int:
        net = Network()
        mem = MemoryTracer()
        nodes = []
        for _ in range(12):
            h = net.add_host()
            nodes.append(PubSub(
                h, GossipSubRouter(flood_publish=flood,
                                   params=GossipSubParams(dhi=8)),
                sign_policy=LAX_NO_SIGN, event_tracer=mem))
        net.connect_all([x.host for x in nodes])
        net.scheduler.run_for(0.2)
        for x in nodes:
            x.join("t").subscribe()
        net.scheduler.run_for(2.5)
        mem.events.clear()
        nodes[0].my_topics["t"].publish(b"wide")
        net.scheduler.run_for(0.2)
        first_hop = {e["sendTo"] for e in mem.events
                     if e["type"] == "SEND_RPC"
                     and e["peerID"] == nodes[0].pid
                     and any("messageID" in m
                             for m in e.get("meta", {}).get("messages", []))}
        return len(first_hop)

    def test_flood_publish_hits_all_topic_peers(self):
        # gossipsub_test.go:1412: with flood publish the first hop is every
        # topic peer, not just the D-bounded mesh (gossipsub.go:989-995)
        assert self._count_receivers(flood=True) == 11
        assert self._count_receivers(flood=False) <= 8  # Dhi-bounded


class TestOpportunisticGrafting:
    def test_grafts_above_median_peers(self):
        # gossipsub_test.go:1804: when the median mesh score sags below the
        # threshold, heartbeats graft up to OpportunisticGraftPeers peers
        # scoring above the median (gossipsub.go:1521-1552)
        net = Network()
        good_ids = set()
        sp = PeerScoreParams(
            app_specific_score=lambda p: 20.0 if p in good_ids else 0.0,
            app_specific_weight=1.0,
            decay_interval=1.0, decay_to_zero=0.01,
            topics={"t": TopicScoreParams(topic_weight=1.0,
                                          time_in_mesh_quantum=1.0)})
        hub = one_node(net, score_params=sp,
                       thresholds=PeerScoreThresholds(
                           gossip_threshold=-10, publish_threshold=-20,
                           graylist_threshold=-30,
                           opportunistic_graft_threshold=5.0),
                       params=GossipSubParams(opportunistic_graft_ticks=2))
        # 8 zero-score leaves fill the mesh first
        leaves = [one_node(net) for _ in range(8)]
        for lf in leaves:
            hub.host.connect(lf.host)
        net.scheduler.run_for(0.2)
        hub.join("t").subscribe()
        for lf in leaves:
            lf.join("t").subscribe()
        net.scheduler.run_for(3.0)
        assert len(hub.rt.mesh["t"]) >= 6
        # two high-score leaves join late: only opportunistic grafting can
        # pull them in (mesh is already >= Dlo, so no undersubscription fill)
        good = [one_node(net) for _ in range(2)]
        good_ids.update(g.pid for g in good)
        for g in good:
            hub.host.connect(g.host)
        net.scheduler.run_for(0.1)
        for g in good:
            g.join("t").subscribe()
        net.scheduler.run_for(6.0)
        assert good_ids & hub.rt.mesh["t"], \
            "opportunistic grafting never pulled in the high-score peers"


class TestStarTopology:
    def test_hub_relays_to_all_leaves(self):
        # gossipsub_test.go:1044-1127 star topologies: every leaf only sees
        # the hub; published messages still reach the whole network
        net = Network()
        hub = one_node(net)
        leaves = [one_node(net) for _ in range(10)]
        for lf in leaves:
            lf.host.connect(hub.host)
        net.scheduler.run_for(0.2)
        subs = [x.join("t").subscribe() for x in [hub] + leaves]
        net.scheduler.run_for(3.0)
        leaves[0].my_topics["t"].publish(b"via hub")
        net.scheduler.run_for(1.0)
        for i, s in enumerate(subs):
            got = [m for m in iter(s.next, None)]
            assert any(m.data == b"via hub" for m in got), f"node {i} missed"


class TestPeerExchange:
    """PX: refused GRAFTs carry peer records, the pruned side dials them
    score-permitting (gossipsub.go:893-973, 1866-1906; handlePrune
    gossipsub.go:860-866)."""

    def _node_with_full_mesh(self, net):
        params = GossipSubParams(d=2, dlo=1, dhi=2, dscore=1, dout=0)
        node = one_node(net, params=params, do_px=True)
        node.join("t").subscribe()
        raws = [RawPeer(net) for _ in range(4)]
        for r in raws:
            r.connect(node)
        net.scheduler.run_for(0.2)
        for r in raws:
            r.subscribe(node, "t")
        # first two graft into the mesh (fills to dhi=2)
        for r in raws[:2]:
            r.send(node, RPC(control=ControlMessage(
                graft=[ControlGraft(topic="t")])))
        net.scheduler.run_for(0.2)
        return node, raws

    def test_refused_graft_carries_px_records(self):
        net = Network()
        node, raws = self._node_with_full_mesh(net)
        late = raws[2]
        late.inbox.clear()
        late.send(node, RPC(control=ControlMessage(
            graft=[ControlGraft(topic="t")])))
        net.scheduler.run_for(0.2)
        prunes = late.received_prunes()
        assert prunes, "expected a PRUNE refusal at Dhi"
        assert prunes[0].backoff > 0
        suggested = {pi.peer_id for pr in prunes for pi in pr.peers}
        assert suggested, "PRUNE should carry PX records"
        assert late.pid not in suggested      # never suggest the pruned peer
        assert suggested <= {r.pid for r in raws}

    def test_pruned_node_dials_px_suggestion(self):
        # two real nodes + a raw mesh peer that prunes node1 while
        # suggesting node2 (not yet connected)
        net = Network()
        node1 = one_node(net)
        node1.join("t").subscribe()
        node2 = one_node(net)
        raw = RawPeer(net)
        raw.connect(node1)
        net.scheduler.run_for(0.2)
        raw.subscribe(node1, "t")
        net.scheduler.run_for(1.2)            # heartbeat grafts raw
        assert raw.pid in node1.rt.mesh["t"]
        assert node2.pid not in node1.peers
        from go_libp2p_pubsub_tpu.core.types import PeerInfo
        raw.send(node1, RPC(control=ControlMessage(prune=[ControlPrune(
            topic="t", peers=[PeerInfo(peer_id=node2.pid)], backoff=60.0)])))
        net.scheduler.run_for(0.5)
        assert raw.pid not in node1.rt.mesh["t"]
        assert node2.pid in node1.peers       # PX dial happened

    def test_px_ignored_below_accept_threshold(self):
        net = Network()
        node1 = one_node(
            net,
            score_params=PeerScoreParams(app_specific_score=lambda p: 0.0,
                                         topics={}),
            thresholds=PeerScoreThresholds(accept_px_threshold=10.0))
        node1.join("t").subscribe()
        node2 = one_node(net)
        raw = RawPeer(net)
        raw.connect(node1)
        net.scheduler.run_for(0.2)
        raw.subscribe(node1, "t")
        net.scheduler.run_for(1.2)
        from go_libp2p_pubsub_tpu.core.types import PeerInfo
        raw.send(node1, RPC(control=ControlMessage(prune=[ControlPrune(
            topic="t", peers=[PeerInfo(peer_id=node2.pid)], backoff=60.0)])))
        net.scheduler.run_for(0.5)
        # score 0 < accept_px_threshold 10: PX records ignored
        assert node2.pid not in node1.peers


class TestRPCFragmentation:
    """fragment_rpc (gossipsub.go:1204-1293; TestFragmentRPCFunction,
    gossipsub_test.go:2338)."""

    def _mk_msg(self, i, size):
        from go_libp2p_pubsub_tpu.core.types import Message
        return Message(from_peer="p", seqno=i.to_bytes(8, "big"), topic="t",
                       data=b"x" * size)

    def test_fragments_stay_under_limit_and_preserve_messages(self):
        from go_libp2p_pubsub_tpu.routers.gossipsub import fragment_rpc
        limit = 1024
        msgs = [self._mk_msg(i, 300) for i in range(10)]
        rpc = RPC(publish=list(msgs))
        frags = fragment_rpc(rpc, limit)
        assert len(frags) > 1
        for f in frags:
            assert f.size() < limit
        out = [m for f in frags for m in f.publish]
        assert [m.seqno for m in out] == [m.seqno for m in msgs]

    def test_oversize_single_message_raises(self):
        import pytest
        from go_libp2p_pubsub_tpu.routers.gossipsub import fragment_rpc
        rpc = RPC(publish=[self._mk_msg(0, 5000)])
        with pytest.raises(ValueError):
            fragment_rpc(rpc, 1024)

    def test_large_ihave_id_lists_split(self):
        from go_libp2p_pubsub_tpu.routers.gossipsub import fragment_rpc
        limit = 512
        ids = [f"msgid-{i:06d}" for i in range(200)]
        rpc = RPC(control=ControlMessage(ihave=[ControlIHave(
            topic="t", message_ids=list(ids))]))
        frags = fragment_rpc(rpc, limit)
        for f in frags:
            assert f.size() < limit
        got = [m for f in frags if f.control
               for ih in f.control.ihave for m in ih.message_ids]
        assert sorted(got) == sorted(ids)

    def test_oversized_iwant_reply_is_fragmented_on_send(self):
        # end-to-end: one IWANT asking for 8 large messages coalesces into a
        # single reply RPC bigger than max_message_size, which the send path
        # must fragment (gossipsub.go:626-627 single reply; 1167-1182)
        net = Network()
        node = one_node(net, params=GossipSubParams())
        node.max_message_size = 2048
        node.join("t").subscribe()
        raw = RawPeer(net)
        raw.connect(node)
        net.scheduler.run_for(0.2)
        raw.subscribe(node, "t")
        net.scheduler.run_for(1.2)
        for i in range(8):
            node.my_topics["t"].publish(b"y" * 400)
        net.scheduler.run_for(0.5)
        pushed = raw.received_messages()
        assert len(pushed) == 8
        mids = [node.id_gen.id(m) for m in pushed]
        raw.inbox.clear()
        raw.send(node, RPC(control=ControlMessage(
            iwant=[ControlIWant(message_ids=mids)])))
        net.scheduler.run_for(0.3)
        data_rpcs = [r for r in raw.inbox if r.publish]
        assert len(data_rpcs) > 1, "the coalesced reply must be fragmented"
        assert len([m for r in data_rpcs for m in r.publish]) == 8
        for r in raw.inbox:
            assert r.size() < 2048


class TestPiggybacking:
    """Queued control rides the next outbound RPC; stale entries are
    filtered against current mesh state (gossipsub.go:1142-1160,
    1822-1864)."""

    def test_pending_graft_rides_data_rpc(self):
        net = Network()
        node = one_node(net)
        node.join("t").subscribe()
        raw = RawPeer(net)
        raw.connect(node)
        net.scheduler.run_for(0.2)
        raw.subscribe(node, "t")
        net.scheduler.run_for(1.2)
        assert raw.pid in node.rt.mesh["t"]
        raw.inbox.clear()
        node.rt.push_control(raw.pid, ControlMessage(
            graft=[ControlGraft(topic="t")]))
        node.my_topics["t"].publish(b"payload")
        net.scheduler.run_for(0.3)
        combined = [r for r in raw.inbox if r.publish and r.control
                    and r.control.graft]
        assert combined, "pending GRAFT should piggyback on the data RPC"

    def test_stale_prune_filtered(self):
        net = Network()
        node = one_node(net)
        node.join("t").subscribe()
        raw = RawPeer(net)
        raw.connect(node)
        net.scheduler.run_for(0.2)
        raw.subscribe(node, "t")
        net.scheduler.run_for(1.2)
        assert raw.pid in node.rt.mesh["t"]
        raw.inbox.clear()
        # a queued PRUNE for a peer currently IN the mesh is stale: filtered
        node.rt.push_control(raw.pid, ControlMessage(
            prune=[ControlPrune(topic="t")]))
        node.my_topics["t"].publish(b"payload")
        net.scheduler.run_for(0.3)
        assert not [r for r in raw.inbox if r.control and r.control.prune]


class TestProtocolMatchFn:
    """WithProtocolMatchFn (pubsub.go:520-531; gossipsub_matchfn_test.go:12):
    custom multistream acceptance — semver-sloppy custom protocols mesh with
    their base name, different names don't connect."""

    def test_name_match_connects_custom_versions(self):
        from go_libp2p_pubsub_tpu.routers.feat import GOSSIPSUB_ID_V11

        def name_match(base):
            base_name = base.split("/")[1]

            def check(proposal):
                return proposal.split("/")[1] == base_name
            return check

        custom_a100 = "/customsub_a/1.0.0"
        custom_a101b = "/customsub_a/1.0.1-beta"
        custom_b100 = "/customsub_b/1.0.0"
        net = Network()
        protos = [[custom_a100, GOSSIPSUB_ID_V11], [custom_a101b],
                  [GOSSIPSUB_ID_V11], [custom_b100]]
        nodes = [PubSub(net.add_host(),
                        GossipSubRouter(protocols=pl_),
                        sign_policy=LAX_NO_SIGN,
                        protocol_match_fn=name_match)
                 for pl_ in protos]
        hubs = [n.host for n in nodes]
        assert hubs[0].connect(hubs[1])        # via customsub_a name
        assert hubs[0].connect(hubs[2])        # via exact v1.1
        assert not hubs[0].connect(hubs[3])    # different names: no streams
        subs = [n.join("t").subscribe() for n in nodes]
        net.scheduler.run_for(2.0)
        nodes[0].my_topics["t"].publish(b"m")
        net.scheduler.run_for(1.0)

        def drain(s):
            out = []
            while s.pending():
                out.append(s.next().data)
            return out

        assert drain(subs[1]) == [b"m"]
        assert drain(subs[2]) == [b"m"]
        assert drain(subs[3]) == []


class TestFeatureNegotiation:
    """Protocol feature tests (gossipsub_feat.go:24-36;
    gossipsub_matchfn_test.go): v1.0 peers participate in the mesh but
    never receive PX records; custom feature tests rewire both."""

    def _node_with_v10_mesh_peer(self, feature_test=None):
        from go_libp2p_pubsub_tpu.routers.feat import GOSSIPSUB_ID_V10
        net = Network()
        kw = dict(params=GossipSubParams(d=2, dlo=1, dhi=2, dscore=1, dout=0),
                  do_px=True)
        if feature_test is not None:
            kw["feature_test"] = feature_test
        node = one_node(net, **kw)
        sub = node.join("t").subscribe()
        # an old v1.0 speaker plus v1.1 peers to fill the mesh
        old = RawPeer(net)
        old.host.set_protocols([GOSSIPSUB_ID_V10], lambda p, proto: None,
                               lambda src, rpc: old.inbox.append(rpc))
        news = [RawPeer(net) for _ in range(3)]
        old.connect(node)
        for r in news:
            r.connect(node)
        net.scheduler.run_for(0.2)
        old.subscribe(node, "t")
        for r in news:
            r.subscribe(node, "t")
        net.scheduler.run_for(0.2)
        # old + one new graft in; mesh (dhi=2) fills
        old.send(node, RPC(control=ControlMessage(graft=[ControlGraft(topic="t")])))
        news[0].send(node, RPC(control=ControlMessage(graft=[ControlGraft(topic="t")])))
        net.scheduler.run_for(0.2)
        return net, node, old, news, sub

    def test_v10_peer_grafts_but_gets_no_px(self):
        net, node, old, news, sub = self._node_with_v10_mesh_peer()
        assert old.pid in node.rt.mesh["t"]          # MESH feature: yes
        # force a PRUNE toward the old peer by unsubscribing the topic
        old.inbox.clear()
        sub.cancel()
        net.scheduler.run_for(0.3)
        prunes = old.received_prunes()
        assert prunes, "Leave must PRUNE the v1.0 mesh member"
        assert all(not pr.peers for pr in prunes), \
            "PX records must never go to a v1.0 peer"
        assert all(pr.backoff == 0 for pr in prunes), \
            "v1.0 prunes carry no backoff field"

    def test_v11_peer_gets_px_on_leave(self):
        net, node, old, news, sub = self._node_with_v10_mesh_peer()
        grafted = news[0]
        assert grafted.pid in node.rt.mesh["t"]
        grafted.inbox.clear()
        sub.cancel()
        net.scheduler.run_for(0.3)
        prunes = grafted.received_prunes()
        assert prunes and prunes[0].backoff > 0
        # unsubscribe-leave does PX to v1.1 peers when do_px is on
        assert any(pr.peers for pr in prunes)

    def test_custom_feature_test_disables_px_everywhere(self):
        from go_libp2p_pubsub_tpu.routers.feat import GossipSubFeature
        def no_px(feat, proto):
            return feat == GossipSubFeature.MESH
        net, node, old, news, sub = self._node_with_v10_mesh_peer(feature_test=no_px)
        grafted = news[0]
        assert grafted.pid in node.rt.mesh["t"]
        grafted.inbox.clear()
        sub.cancel()
        net.scheduler.run_for(0.3)
        prunes = grafted.received_prunes()
        assert prunes and all(not pr.peers for pr in prunes)


class TestSybilCrossCheck:
    """Cross-check the batched engine's sybil-scenario decomposition against
    the functional runtime under the same 20%-sybil shape (VERDICT r3 #5).

    tests/test_delivery_structural.py proves three properties of the
    batched sybil run (the number behind BASELINE config 4's ~0.65
    delivery fraction); this test asserts the SAME decomposition from the
    independent half of the codebase — real PubSub nodes, raw spam RPCs
    (gossipsub_spam_test.go:615 invalid-spam accounting):

    - honest receivers deliver EVERY honest message (1.0);
    - honest receivers deliver ZERO invalid sybil messages;
    - graylisted sybils are starved of honest traffic.
    """

    def _sybil_net(self, n=40, sybil_frac=0.2):
        from go_libp2p_pubsub_tpu.core.types import Message

        net = Network()
        nodes = []
        for i in range(n):
            h = net.add_host()
            sp = PeerScoreParams(
                app_specific_score=lambda p: 0.0,
                decay_interval=1.0, decay_to_zero=0.01,
                topics={"t": TopicScoreParams(
                    topic_weight=1.0, time_in_mesh_quantum=1.0,
                    invalid_message_deliveries_weight=-10.0,
                    invalid_message_deliveries_decay=0.99)})
            th = PeerScoreThresholds(
                gossip_threshold=-10.0, publish_threshold=-50.0,
                graylist_threshold=-100.0)
            rt = GossipSubRouter(score_params=sp, thresholds=th)
            nodes.append(PubSub(h, rt, sign_policy=LAX_NO_SIGN))
        n_sybil = int(n * sybil_frac)
        sybils, honest = nodes[:n_sybil], nodes[n_sybil:]
        for x in nodes:
            x.register_topic_validator(
                "t", lambda src, msg: b"spam" not in msg.data)
        net.dense_connect([x.host for x in nodes], degree=10)
        net.scheduler.run_for(0.2)
        subs = {x.pid: x.join("t").subscribe() for x in nodes}
        net.scheduler.run_for(2.0)

        def spam_round(i):
            # sybils push raw invalid RPCs to every peer, bypassing their
            # own local validation (the gossipsub_spam_test.go actor)
            for j, s in enumerate(sybils):
                for peer in list(s.peers):
                    s.host.send(peer, RPC(publish=[Message(
                        from_peer=s.pid,
                        seqno=(i * 100 + j).to_bytes(8, "big"),
                        data=b"spam %d %d" % (i, j), topic="t")]))
        return net, nodes, sybils, honest, subs, spam_round

    def test_decomposition_matches_batched_engine(self):
        net, nodes, sybils, honest, subs, spam_round = self._sybil_net()
        # interleave honest publishes with sybil spam for 12 rounds
        sent = []
        for i in range(12):
            spam_round(i)
            pub = honest[i % len(honest)]
            data = b"honest %d" % i
            pub.my_topics["t"].publish(data)
            sent.append(data)
            net.scheduler.run_for(1.0)
        net.scheduler.run_for(10.0)

        def drain(sub):
            out = []
            while (m := sub.next()) is not None:
                out.append(m)
            return out

        # 1. honest x honest = 1.0 (each honest node got every honest msg,
        #    minus its own publishes which deliver to self — included too)
        spam_seen = 0
        for x in honest:
            got = drain(subs[x.pid])
            datas = {m.data for m in got if b"honest" in m.data}
            assert datas == set(sent), \
                f"honest node missing honest traffic: {len(datas)}/{len(sent)}"
            spam_seen += sum(1 for m in got if b"spam" in m.data)
        # 2. honest x invalid = 0 (validation rejects every spam message)
        assert spam_seen == 0, f"{spam_seen} invalid deliveries to honest"
        # 3. graylisted sybils starve: once scores collapse, later honest
        #    messages stop reaching them (mesh prune + no gossip,
        #    gossipsub.go:598-645). Early messages may have landed before
        #    the scores crossed the threshold, so assert on the tail half.
        tail = set(sent[len(sent) // 2:])
        starved = 0
        for s in sybils:
            got_tail = {m.data for m in drain(subs[s.pid])} & tail
            if len(got_tail) <= len(tail) // 4:
                starved += 1
        assert starved >= int(0.75 * len(sybils)), \
            f"only {starved}/{len(sybils)} sybils starved of honest traffic"
        # and the honest nodes each sybil actually spammed (its direct
        # neighbors — scoring is a LOCAL observation, score.go:265-342)
        # score it below the graylist line
        pairs = graylisted = 0
        by_pid = {x.pid: x for x in honest}
        for s in sybils:
            for peer in s.peers:
                x = by_pid.get(peer)
                if x is None:
                    continue            # sybil-sybil edge
                pairs += 1
                if x.rt.score.score(s.pid) < -100.0:
                    graylisted += 1
        assert pairs > 0
        assert graylisted >= 0.9 * pairs, \
            f"only {graylisted}/{pairs} spammed neighbors graylisted"
