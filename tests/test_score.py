"""Score engine tests: virtual-clock versions of score_test.go's scenarios.

Each test drives AddPeer/Graft/Deliver/refresh_scores by hand and asserts
exact numeric P1-P7 values. Unlike the reference's sleep-based tests, the
virtual clock makes every expectation exact.
"""

import pytest

from go_libp2p_pubsub_tpu.core.clock import VirtualClock
from go_libp2p_pubsub_tpu.core.params import PeerScoreParams, TopicScoreParams
from go_libp2p_pubsub_tpu.core.types import Message
from go_libp2p_pubsub_tpu.routers.score import PeerScore
from go_libp2p_pubsub_tpu.trace import events as ev

TOPIC = "mytopic"


def make_params(**topic_kw) -> PeerScoreParams:
    defaults = dict(time_in_mesh_quantum=1.0)
    defaults.update(topic_kw)
    return PeerScoreParams(
        app_specific_score=lambda p: 0.0,
        topics={TOPIC: TopicScoreParams(**defaults)},
    )


def _msg(i: int, received_from: str) -> Message:
    return Message(from_peer="author", seqno=i.to_bytes(8, "big"), topic=TOPIC,
                   received_from=received_from)


def test_time_in_mesh():
    clk = VirtualClock()
    params = make_params(topic_weight=0.5, time_in_mesh_weight=1,
                         time_in_mesh_quantum=1e-3, time_in_mesh_cap=3600)
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    assert ps.score("A") == 0
    ps.graft("A", TOPIC)
    clk.advance_to(0.2)  # 200 quanta
    ps.refresh_scores()
    assert ps.score("A") == pytest.approx(0.5 * 1 * 200)


def test_time_in_mesh_cap():
    clk = VirtualClock()
    params = make_params(topic_weight=0.5, time_in_mesh_weight=1,
                         time_in_mesh_quantum=1e-3, time_in_mesh_cap=10)
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", TOPIC)
    clk.advance_to(0.04)  # 40 quanta, cap 10
    ps.refresh_scores()
    assert ps.score("A") == pytest.approx(0.5 * 1 * 10)


def test_first_message_deliveries():
    clk = VirtualClock()
    params = make_params(topic_weight=1, first_message_deliveries_weight=1,
                         first_message_deliveries_decay=1.0,
                         first_message_deliveries_cap=2000)
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", TOPIC)
    for i in range(100):
        m = _msg(i, "A")
        ps.validate_message(m)
        ps.deliver_message(m)
    ps.refresh_scores()
    assert ps.score("A") == pytest.approx(100.0)


def test_first_message_deliveries_cap():
    clk = VirtualClock()
    params = make_params(topic_weight=1, first_message_deliveries_weight=1,
                         first_message_deliveries_decay=1.0,
                         first_message_deliveries_cap=50)
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", TOPIC)
    for i in range(100):
        m = _msg(i, "A")
        ps.validate_message(m)
        ps.deliver_message(m)
    ps.refresh_scores()
    assert ps.score("A") == pytest.approx(50.0)


def test_first_message_deliveries_decay():
    clk = VirtualClock()
    params = make_params(topic_weight=1, first_message_deliveries_weight=1,
                         first_message_deliveries_decay=0.9,
                         first_message_deliveries_cap=2000)
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", TOPIC)
    for i in range(100):
        m = _msg(i, "A")
        ps.validate_message(m)
        ps.deliver_message(m)
    ps.refresh_scores()
    expected = 0.9 * 100
    assert ps.score("A") == pytest.approx(expected)
    for _ in range(10):
        ps.refresh_scores()
        expected *= 0.9
    assert ps.score("A") == pytest.approx(expected)


def test_mesh_message_deliveries():
    clk = VirtualClock()
    params = make_params(topic_weight=1, mesh_message_deliveries_weight=-1,
                         mesh_message_deliveries_activation=1.0,
                         mesh_message_deliveries_window=0.01,
                         mesh_message_deliveries_threshold=20,
                         mesh_message_deliveries_cap=100,
                         mesh_message_deliveries_decay=1.0)
    ps = PeerScore(params, clk.now)
    for p in "ABC":
        ps.add_peer(p, "proto")
        ps.graft(p, TOPIC)
    # before activation: no penalty
    ps.refresh_scores()
    assert all(ps.score(p) >= 0 for p in "ABC")
    # pass the activation window
    clk.advance_to(1.5)
    ps.refresh_scores()  # sets mesh_time > activation -> active
    # A delivers first, B duplicates in-window, C duplicates out-of-window
    t = clk.now()
    for i in range(100):
        m = _msg(i, "A")
        ps.validate_message(m)
        ps.deliver_message(m)
        m_b = _msg(i, "B")
        ps.duplicate_message(m_b)
    t += 0.05  # 50ms later: outside the 10ms window
    clk.advance_to(t)
    for i in range(100):
        ps.duplicate_message(_msg(i, "C"))
    ps.refresh_scores()
    assert ps.score("A") >= 0
    assert ps.score("B") >= 0
    assert ps.score("C") == pytest.approx(-(20.0 ** 2))


def test_mesh_failure_penalty():
    clk = VirtualClock()
    params = make_params(topic_weight=1, mesh_failure_penalty_weight=-1,
                         mesh_failure_penalty_decay=1.0,
                         mesh_message_deliveries_activation=1.0,
                         mesh_message_deliveries_window=0.01,
                         mesh_message_deliveries_threshold=20,
                         mesh_message_deliveries_cap=100,
                         mesh_message_deliveries_decay=1.0)
    # NOTE: mesh_message_deliveries_weight stays 0 so only P3b counts
    ps = PeerScore(params, clk.now)
    for p in "AB":
        ps.add_peer(p, "proto")
        ps.graft(p, TOPIC)
    clk.advance_to(1.5)
    ps.refresh_scores()  # activate
    # prune B while it has a deficit -> sticky penalty
    ps.prune("B", TOPIC)
    ps.refresh_scores()
    assert ps.score("A") == 0.0
    assert ps.score("B") == pytest.approx(-(20.0 ** 2))


def test_invalid_message_deliveries():
    clk = VirtualClock()
    params = make_params(topic_weight=1, invalid_message_deliveries_weight=-1,
                         invalid_message_deliveries_decay=1.0)
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", TOPIC)
    for i in range(100):
        m = _msg(i, "A")
        ps.reject_message(m, ev.REJECT_INVALID_SIGNATURE)
    ps.refresh_scores()
    assert ps.score("A") == pytest.approx(-(100.0 ** 2))


def test_invalid_message_deliveries_decay():
    clk = VirtualClock()
    params = make_params(topic_weight=1, invalid_message_deliveries_weight=-1,
                         invalid_message_deliveries_decay=0.9)
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", TOPIC)
    for i in range(100):
        ps.reject_message(_msg(i, "A"), ev.REJECT_INVALID_SIGNATURE)
    ps.refresh_scores()
    expected = -((0.9 * 100) ** 2)
    assert ps.score("A") == pytest.approx(expected)


def test_reject_message_deliveries_status_machine():
    """Once rejected as invalid, later duplicates also get penalized;
    ignored/throttled rejections penalize nobody (score_test.go:536-668)."""
    clk = VirtualClock()
    params = make_params(topic_weight=1, invalid_message_deliveries_weight=-1,
                         invalid_message_deliveries_decay=1.0)
    ps = PeerScore(params, clk.now)
    for p in "AB":
        ps.add_peer(p, "proto")
    # A delivers, validation pending; B duplicates; then the message is rejected
    m = _msg(0, "A")
    ps.validate_message(m)
    ps.duplicate_message(_msg(0, "B"))
    ps.reject_message(m, ev.REJECT_VALIDATION_FAILED)
    assert ps.score("A") == pytest.approx(-1.0)
    assert ps.score("B") == pytest.approx(-1.0)
    # duplicate after the fact also penalized
    ps.duplicate_message(_msg(0, "B"))
    assert ps.score("B") == pytest.approx(-4.0)

    # ignored: no penalties
    ps2 = PeerScore(make_params(topic_weight=1, invalid_message_deliveries_weight=-1,
                                invalid_message_deliveries_decay=1.0), clk.now)
    for p in "AB":
        ps2.add_peer(p, "proto")
    m = _msg(1, "A")
    ps2.validate_message(m)
    ps2.duplicate_message(_msg(1, "B"))
    ps2.reject_message(m, ev.REJECT_VALIDATION_IGNORED)
    assert ps2.score("A") == 0.0 and ps2.score("B") == 0.0
    # throttled likewise
    m = _msg(2, "A")
    ps2.validate_message(m)
    ps2.reject_message(m, ev.REJECT_VALIDATION_THROTTLED)
    assert ps2.score("A") == 0.0


def test_application_score():
    clk = VirtualClock()
    app_score = {"value": 0.0}
    params = PeerScoreParams(app_specific_score=lambda p: app_score["value"],
                             app_specific_weight=0.5, topics={})
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    for v in (-100.0, 0.0, 42.0):
        app_score["value"] = v
        assert ps.score("A") == pytest.approx(0.5 * v)


def test_ip_colocation():
    clk = VirtualClock()
    ips = {"A": ["1.2.3.4"], "B": ["2.3.4.5"], "C": ["2.3.4.5"], "D": ["2.3.4.5"]}
    params = PeerScoreParams(app_specific_score=lambda p: 0.0,
                             ip_colocation_factor_weight=-1,
                             ip_colocation_factor_threshold=1, topics={})
    ps = PeerScore(params, clk.now, get_ips=lambda p: ips[p])
    for p in "ABCD":
        ps.add_peer(p, "proto")
    assert ps.score("A") == 0.0
    # B, C, D share an IP: 3 peers, threshold 1 -> surplus 2 -> penalty 4 each
    for p in "BCD":
        assert ps.score(p) == pytest.approx(-4.0)


def test_ip_colocation_whitelist():
    clk = VirtualClock()
    ips = {"B": ["2.3.4.5"], "C": ["2.3.4.5"]}
    params = PeerScoreParams(app_specific_score=lambda p: 0.0,
                             ip_colocation_factor_weight=-1,
                             ip_colocation_factor_threshold=1,
                             ip_colocation_factor_whitelist=["2.3.0.0/16"], topics={})
    ps = PeerScore(params, clk.now, get_ips=lambda p: ips[p])
    for p in "BC":
        ps.add_peer(p, "proto")
    assert ps.score("B") == 0.0 and ps.score("C") == 0.0


def test_behaviour_penalty():
    clk = VirtualClock()
    params = PeerScoreParams(app_specific_score=lambda p: 0.0,
                             behaviour_penalty_weight=-1,
                             behaviour_penalty_threshold=1,
                             behaviour_penalty_decay=0.99, topics={})
    ps = PeerScore(params, clk.now)
    # penalty for unknown peer is a no-op
    ps.add_penalty("A", 2)
    assert ps.score("A") == 0.0
    ps.add_peer("A", "proto")
    ps.add_penalty("A", 2)
    # excess = 2 - 1 = 1 -> -1
    assert ps.score("A") == pytest.approx(-1.0)
    ps.add_penalty("A", 2)
    # counter 4, excess 3 -> -9
    assert ps.score("A") == pytest.approx(-9.0)
    ps.refresh_scores()
    # counter 3.96, excess 2.96
    assert ps.score("A") == pytest.approx(-(2.96 ** 2))


def test_score_retention():
    clk = VirtualClock()
    params = make_params(topic_weight=1, invalid_message_deliveries_weight=-1,
                         invalid_message_deliveries_decay=1.0)
    params.retain_score = 10.0
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", TOPIC)
    ps.reject_message(_msg(0, "A"), ev.REJECT_INVALID_SIGNATURE)
    assert ps.score("A") < 0
    # disconnect: negative score is retained, does not decay
    ps.remove_peer("A")
    clk.advance_to(5.0)
    ps.refresh_scores()
    assert ps.score("A") == pytest.approx(-1.0)
    # after the retention period the record is purged
    clk.advance_to(11.0)
    ps.refresh_scores()
    assert ps.score("A") == 0.0
    assert "A" not in ps.peer_stats


def test_positive_score_not_retained():
    clk = VirtualClock()
    params = make_params(topic_weight=1, first_message_deliveries_weight=1,
                         first_message_deliveries_decay=1.0,
                         first_message_deliveries_cap=100)
    params.retain_score = 10.0
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", TOPIC)
    m = _msg(0, "A")
    ps.validate_message(m)
    ps.deliver_message(m)
    assert ps.score("A") > 0
    ps.remove_peer("A")
    assert "A" not in ps.peer_stats  # positive scores are dropped immediately


def test_recap_topic_params():
    clk = VirtualClock()
    params = make_params(topic_weight=1, first_message_deliveries_weight=1,
                         first_message_deliveries_decay=1.0,
                         first_message_deliveries_cap=100)
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", TOPIC)
    for i in range(80):
        m = _msg(i, "A")
        ps.validate_message(m)
        ps.deliver_message(m)
    assert ps.score("A") == pytest.approx(80.0)
    # lower the cap: counters are recapped
    newp = TopicScoreParams(topic_weight=1, first_message_deliveries_weight=1,
                            first_message_deliveries_decay=1.0,
                            first_message_deliveries_cap=50,
                            time_in_mesh_quantum=1.0)
    ps.set_topic_score_params(TOPIC, newp)
    assert ps.score("A") == pytest.approx(50.0)


def test_delivery_record_gc():
    clk = VirtualClock()
    params = make_params(topic_weight=1)
    params.seen_msg_ttl = 5.0
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    for i in range(10):
        ps.validate_message(_msg(i, "A"))
    assert len(ps.deliveries.records) == 10
    clk.advance_to(6.0)
    ps.gc_delivery_records()
    assert len(ps.deliveries.records) == 0


def test_unscored_topic_ignored():
    clk = VirtualClock()
    params = PeerScoreParams(app_specific_score=lambda p: 0.0, topics={})
    ps = PeerScore(params, clk.now)
    ps.add_peer("A", "proto")
    ps.graft("A", "unknown-topic")
    m = Message(from_peer="x", seqno=b"1", topic="unknown-topic", received_from="A")
    ps.validate_message(m)
    ps.deliver_message(m)
    assert ps.score("A") == 0.0
