"""Streaming telemetry plane (sim/telemetry.py, ISSUE 9).

The core claim is PARITY: the per-tick aggregates streamed out of the
scan (device-side reduction, one fetch per chunk) are identical to
:func:`telemetry.health_record` computed post-hoc from the full state
trajectory — across the plain scan, supervised chunking (journal rows
included), the vmap-batched fleet, and the SPMD-sharded step (where ONE
column, ``score_mean``, is allowed ~ulp reassociation slack — module
docstring). On top of that: the native NDJSON encoder parses equal to
the Python one, the journal reader survives torn tails and resume
overlaps, the dashboard renders a recorded journal (``--once`` smoke),
``run_traced`` emits health rows even with invariants off, and a fleet
crash dump replays per member (clean AND tripped reproduction).
"""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import scenarios, telemetry
from go_libp2p_pubsub_tpu.sim.engine import run_keys, step_jit
from go_libp2p_pubsub_tpu.sim.supervisor import (SupervisorConfig,
                                                 SupervisorCrash,
                                                 supervised_run)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny(n=96, **kw):
    return scenarios.single_topic_1k(n_peers=n, k_slots=16, degree=6, **kw)


def _posthoc_rows(st, cfg, tp, keys):
    """The reference: step the engine tick by tick and apply the SAME
    reduction to every stored state."""
    rows = []
    for i in range(len(keys)):
        st = step_jit(st, cfg, tp, keys[i])
        rows.append(telemetry.record_to_row(
            telemetry.health_record_jit(st, cfg, tp)))
    return rows


def _strip(rows):
    return [{k: v for k, v in r.items() if k != "kind"} for r in rows]


class TestStreamedParity:
    def test_plain_scan_matches_posthoc(self):
        cfg, tp, st = _tiny()
        keys = jax.random.split(jax.random.PRNGKey(0), 6)
        out, health = run_keys(st, cfg, tp, keys, telemetry=True)
        mat, cols = telemetry.records_to_rows(health)
        streamed = telemetry.rows_to_dicts(mat, cols)
        assert streamed == _posthoc_rows(st, cfg, tp, keys)
        # the telemetry lane never perturbs the trajectory
        plain = run_keys(st, cfg, tp, keys)
        np.testing.assert_array_equal(np.asarray(out.have),
                                      np.asarray(plain.have))
        assert int(out.tick) == int(plain.tick)

    def test_chunked_supervised_stream_matches_posthoc(self, tmp_path):
        # 6 ticks / chunk 3: shapes harmonized with the other tier-1
        # cases so the compiled window programs are shared (the tier-1
        # wall budget is the binding constraint — conftest rationale)
        cfg, tp, st = _tiny()
        hp = str(tmp_path / "health.jsonl")
        sup = SupervisorConfig(chunk_ticks=3, health_path=hp,
                               checkpoint_dir=str(tmp_path / "ck"),
                               scenario="single_topic_1k")
        out, report = supervised_run(st, cfg, tp, jax.random.PRNGKey(0),
                                     6, sup)
        j = telemetry.read_journal(hp)
        keys = jax.random.split(jax.random.PRNGKey(0), 6)
        assert _strip(j["rows"]) == _posthoc_rows(st, cfg, tp, keys)
        # journal structure: header + one chunk marker per chunk +
        # checkpoint notes; the wall stamps are the dashboard's hb/s feed
        assert j["runs"] and j["runs"][0]["n_peers"] == cfg.n_peers
        assert len(j["chunks"]) == report.chunks_run
        assert all("wall" in c for c in j["chunks"])
        assert any(n["kind"] == "checkpoint" for n in j["notes"])
        assert any(n["kind"] == "run_end" for n in j["notes"])

    def test_bare_state_run_fn_not_mistaken_for_telemetry_pair(self,
                                                               tmp_path):
        """SimState is a NamedTuple (a tuple subclass): a custom run_fn
        returning the bare state must not be unpacked as the
        (state, HealthRecord) telemetry pair even when a health stream
        is configured (the multihost launcher without --health)."""
        cfg, tp, st = _tiny()

        def run_fn(state, exec_cfg, tp_arg, keys):
            return run_keys(state, exec_cfg, tp_arg, keys)   # bare state

        sup = SupervisorConfig(chunk_ticks=3, run_fn=run_fn,
                               health_path=str(tmp_path / "h.jsonl"))
        out, report = supervised_run(st, cfg, tp, jax.random.PRNGKey(0),
                                     6, sup)
        assert report.ticks_run == 6 and int(out.tick) == 6
        # no records from a plain runner — but the journal still frames
        # the run (header + chunk markers + run_end)
        j = telemetry.read_journal(str(tmp_path / "h.jsonl"))
        assert j["rows"] == [] and len(j["chunks"]) == 2
        assert any(n["kind"] == "run_end" for n in j["notes"])

    def test_retried_chunk_rows_never_double_count(self, tmp_path):
        """A failed attempt's records die with its discarded output: the
        journal holds each tick exactly once."""
        cfg, tp, st = _tiny()
        hp = str(tmp_path / "health.jsonl")
        fails = {"n": 0}

        def hook(info):
            if info["chunk_start"] == 3 and fails["n"] < 2:
                fails["n"] += 1
                raise RuntimeError("injected chunk failure")

        sup = SupervisorConfig(chunk_ticks=3, health_path=hp,
                               sleep=lambda s: None)
        supervised_run(st, cfg, tp, jax.random.PRNGKey(0), 9, sup,
                       _chunk_hook=hook)
        with open(hp) as f:
            ticks = [json.loads(ln)["tick"] for ln in f
                     if '"health"' in ln]
        assert ticks == list(range(9))

    def test_fleet_stream_matches_per_member(self, tmp_path):
        from go_libp2p_pubsub_tpu.sim.fleet import (FleetMember,
                                                    supervised_fleet_run)

        cfg, tp, st = _tiny()
        b = 4
        members = [FleetMember(cfg=cfg, tp=tp, state=st,
                               key=jax.random.PRNGKey(100 + i), n_ticks=6,
                               name=f"m{i}") for i in range(b)]
        hp = str(tmp_path / "fleet_health.jsonl")
        sup = SupervisorConfig(chunk_ticks=3, health_path=hp,
                               sleep=lambda s: None)
        supervised_fleet_run(members, sup)
        j = telemetry.read_journal(hp)
        assert len(j["rows"]) == b * 6
        assert j["runs"][0]["plane"] == "fleet"
        assert j["runs"][0]["member_names"] == [m.name for m in members]
        for i in range(b):
            keys = jax.random.split(jax.random.PRNGKey(100 + i), 6)
            ref = _posthoc_rows(st, cfg, tp, keys)
            for r in ref:
                r["member"] = i
            got = [{k: v for k, v in r.items() if k != "kind"}
                   for r in j["rows"] if r["member"] == i]
            assert got == ref, f"member {i} diverged"

    @pytest.mark.slow
    def test_sharded_scan_matches_unsharded(self):
        """The SPMD lens: telemetry records out of the 8-device sharded
        scan equal the unsharded ones — exactly for every column except
        ``score_mean``, whose cross-shard f32 partial sums legitimately
        reassociate (~ulp; telemetry module docstring)."""
        from go_libp2p_pubsub_tpu.parallel.sharding import (
            make_mesh, make_sharded_run_keys, shard_state)
        from go_libp2p_pubsub_tpu.sim import init_state

        cfg, tp, topo, sub = scenarios.frontier_spec(128)
        st = init_state(cfg, topo, subscribed=sub)
        mesh = make_mesh()
        fn = make_sharded_run_keys(mesh, cfg, tp, telemetry=True)
        keys = jax.random.split(jax.random.PRNGKey(7), 5)
        out_sh, health_sh = fn(shard_state(st, mesh, cfg), keys)
        out, health = run_keys(st, cfg, tp, keys, telemetry=True)
        m_sh, cols = telemetry.records_to_rows(health_sh)
        m, _ = telemetry.records_to_rows(health)
        names = [nm for nm, _ in cols]
        sm = names.index("score_mean")
        exact = [i for i in range(len(names)) if i != sm]
        np.testing.assert_array_equal(m_sh[:, exact], m[:, exact])
        np.testing.assert_allclose(m_sh[:, sm], m[:, sm], rtol=1e-5)
        # the sharded state trajectory itself stays bit-exact
        np.testing.assert_array_equal(np.asarray(out_sh.have),
                                      np.asarray(out.have))


class TestRunTracedHealth:
    def test_emits_even_with_invariants_off(self):
        from go_libp2p_pubsub_tpu.sim.trace_export import run_traced

        cfg, tp, st = _tiny()
        cfg = dataclasses.replace(cfg, record_provenance=True,
                                  invariant_mode="off")
        health = []
        run_traced(st, cfg, tp, jax.random.PRNGKey(0), 4,
                   health_out=health)
        assert len(health) == 4
        assert [h["tick"] for h in health] == [0, 1, 2, 3]
        # delivery/mesh metrics stream regardless of the sentinel; the
        # flag keys say "not tracked", not "clean"
        for h in health:
            assert h["fault_flags"] is None and h["flags"] is None
            assert 0.0 <= h["delivery_frac_t0"] <= 1.0
            assert h["mesh_deg_max"] >= h["mesh_deg_min"] >= 0

    def test_record_mode_rows_match_device_stream(self):
        from go_libp2p_pubsub_tpu.sim.trace_export import run_traced

        cfg, tp, st = _tiny()
        cfg_t = dataclasses.replace(cfg, record_provenance=True)
        keys = jax.random.split(jax.random.PRNGKey(3), 4)
        health = []
        run_traced(st, cfg_t, tp, None, 0, health_out=health,
                   keys=keys)
        # provenance maintenance must not change the aggregates: compare
        # against the device stream of the SAME traced config
        _, dev = run_keys(st, cfg_t, tp, keys, telemetry=True)
        mat, cols = telemetry.records_to_rows(dev)
        ref = telemetry.rows_to_dicts(mat, cols)
        got = [{k: v for k, v in h.items() if k != "flags"}
               for h in health]
        assert got == ref


def _synthetic_records(c=4, b=None, t=2, seed=0):
    """A hand-built stacked HealthRecord (numpy leaves — no jit): awkward
    float values exercise the encoders' round-trip without paying an
    engine compile in tier-1."""
    rng = np.random.RandomState(seed)
    shape = (c,) if b is None else (c, b)

    def f32(lo, hi):
        return rng.uniform(lo, hi, shape).astype(np.float32)

    def i32(hi):
        return rng.randint(0, hi, shape).astype(np.int32)

    return telemetry.HealthRecord(
        tick=np.arange(c, dtype=np.int32) if b is None else
        np.repeat(np.arange(c, dtype=np.int32)[:, None], b, axis=1),
        delivery_frac=rng.uniform(0, 1, shape + (t,)).astype(np.float32),
        mesh_deg_min=i32(4), mesh_deg_mean=f32(0, 12), mesh_deg_max=i32(16),
        backoff_count=i32(999), graylist_count=i32(50),
        connected_edges=i32(4000), attacker_edges=i32(900),
        attacker_graylisted=i32(40), honest_graylisted=i32(10),
        score_mean=f32(-7, 7) / 3.0, score_min=f32(-100, 0),
        published_window=i32(64), delivered_total=f32(0, 1e7),
        halo_overflow=i32(2), fault_flags=i32(1 << 14).astype(np.uint32))


class TestEncodersAndJournal:
    def test_native_encoder_parses_equal_to_python(self):
        from go_libp2p_pubsub_tpu.trace import native

        mat, cols = telemetry.records_to_rows(_synthetic_records())
        payload = native.encode_health_json(mat, cols)
        if payload is None:
            pytest.skip("native codec unavailable (no compiler)")
        py = [json.loads(ln)
              for ln in telemetry.encode_rows_py(mat, cols).splitlines()]
        nat = [json.loads(ln) for ln in payload.splitlines()]
        assert py == nat

    def test_native_encoder_nonfinite_to_null(self):
        from go_libp2p_pubsub_tpu.trace import native

        cols = [("a", True), ("b", False)]
        mat = np.array([[1.0, np.nan], [2.0, np.inf]])
        payload = native.encode_health_json(mat, cols)
        if payload is None:
            pytest.skip("native codec unavailable (no compiler)")
        rows = [json.loads(ln) for ln in payload.splitlines()]
        assert rows == [{"kind": "health", "a": 1, "b": None},
                        {"kind": "health", "a": 2, "b": None}]
        assert rows == [json.loads(ln) for ln in
                        telemetry.encode_rows_py(mat, cols).splitlines()]

    def test_read_journal_torn_tail_and_resume_dedup(self, tmp_path):
        path = str(tmp_path / "health.jsonl")
        with telemetry.HealthJournal(path, prefer_native=False) as hj:
            hj.note("run", n_peers=64)
            hj.append_dicts([{"tick": 0, "member": -1, "x": 1.0},
                             {"tick": 1, "member": -1, "x": 2.0}])
            # a resume re-streams tick 1 with a newer value: last wins
            hj.append_dicts([{"tick": 1, "member": -1, "x": 9.0}])
        with open(path, "a") as f:
            f.write('{"kind": "health", "tick": 2, "tru')   # torn tail
        j = telemetry.read_journal(path)
        assert [r["tick"] for r in j["rows"]] == [0, 1]
        assert j["rows"][1]["x"] == 9.0
        assert len(j["runs"]) == 1 and len(j["chunks"]) == 2

    def test_live_tailer_matches_full_read(self, tmp_path):
        """The live dashboard's incremental tailer (bounded memory, O(new
        bytes) per poll) must agree with the full-file reader, including
        across a torn tail that completes on a later poll."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "graft_dashboard", os.path.join(REPO, "scripts",
                                            "dashboard.py"))
        dash = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(dash)

        path = str(tmp_path / "health.jsonl")
        with telemetry.HealthJournal(path, prefer_native=False) as hj:
            hj.note("run", n_peers=8, n_topics=1, invariant_mode="record")
            hj.append_dicts([{"tick": t, "member": -1,
                              "delivery_frac_t0": t / 4} for t in range(3)])
        tailer = dash._Tailer(path)
        tailer.poll()
        # torn tail: half a line now, the rest on the next poll
        line = json.dumps({"kind": "health", "tick": 3, "member": -1,
                           "delivery_frac_t0": 0.75}) + "\n"
        with open(path, "a") as f:
            f.write(line[:12])
            f.flush()
        tailer.poll()
        with open(path, "a") as f:
            f.write(line[12:])
        tailer.poll()
        full = telemetry.read_journal(path)
        tj = tailer.journal()
        assert tj["rows"] == full["rows"]
        assert tj["chunks_total"] == len(full["chunks"])
        assert dash._snapshot_of(tj, path)["tick"] == 3

    def test_fleet_rows_interleave_and_bind_member_ids(self):
        recs = _synthetic_records(c=3, b=2)
        mat, cols = telemetry.records_to_rows(recs, member_ids=[5, 9])
        rows = telemetry.rows_to_dicts(mat, cols)
        assert [(r["tick"], r["member"]) for r in rows] == \
            [(0, 5), (0, 9), (1, 5), (1, 9), (2, 5), (2, 9)]
        with pytest.raises(ValueError, match="member ids"):
            telemetry.records_to_rows(recs, member_ids=[0, 1, 2])


class TestDashboard:
    def _journal(self, tmp_path):
        cfg, tp, st = _tiny()
        hp = str(tmp_path / "health.jsonl")
        sup = SupervisorConfig(chunk_ticks=3, health_path=hp,
                               scenario="single_topic_1k")
        supervised_run(st, cfg, tp, jax.random.PRNGKey(0), 6, sup)
        return hp

    def test_once_snapshot_smoke(self, tmp_path):
        hp = self._journal(tmp_path)
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "dashboard.py"),
             hp, "--once"],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
        assert res.returncode == 0, res.stderr[-800:]
        assert "graft telemetry" in res.stdout
        assert "delivery" in res.stdout and "mesh degree" in res.stdout

        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "dashboard.py"),
             hp, "--once", "--json"],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
        assert res.returncode == 0, res.stderr[-800:]
        snap = json.loads(res.stdout)
        assert snap["tick"] == 5 and snap["rows"] == 6
        assert snap["run"]["scenario"] == "single_topic_1k"
        assert snap["fault_flags"] == 0 and snap["done"] is True
        assert 0.0 <= snap["delivery_frac"] <= 1.0

    def test_window_end_is_paused_not_ended(self, tmp_path):
        """A max_chunks bounded-window stop journals "window_end", not
        "run_end": the dashboard must keep a resumable run tailable
        (PAUSED), and only true completion reads ENDED — markers from a
        previous window don't leak into the resumed run's status."""
        cfg, tp, st = _tiny()
        hp = str(tmp_path / "health.jsonl")
        ck = str(tmp_path / "ck")

        def sup():
            return SupervisorConfig(chunk_ticks=3, health_path=hp,
                                    checkpoint_dir=ck, max_chunks=1,
                                    scenario="single_topic_1k")

        supervised_run(st, cfg, tp, jax.random.PRNGKey(0), 6, sup())
        j = telemetry.read_journal(hp)
        kinds = [n["kind"] for n in j["notes"]]
        assert "window_end" in kinds and "run_end" not in kinds
        snap = self._snap(hp)
        assert snap["paused"] is True and snap["done"] is False
        # resume the same schedule: second window completes the run
        supervised_run(st, cfg, tp, jax.random.PRNGKey(0), 6, sup())
        snap = self._snap(hp)
        assert snap["done"] is True
        assert [r["tick"] for r in telemetry.read_journal(hp)["rows"]] \
            == list(range(6))

    def test_invariants_off_rows_never_read_clean(self, tmp_path):
        """The numeric row schema streams fault_flags=0 when the sentinel
        is off; the dashboard must surface "not tracked", not "clean"
        (the run header's invariant_mode is the discriminator)."""
        hp = str(tmp_path / "health.jsonl")
        with telemetry.HealthJournal(hp, prefer_native=False) as hj:
            hj.note("run", n_peers=64, n_topics=1, invariant_mode="off",
                    scenario="x")
            hj.append_dicts([{"tick": 0, "member": -1,
                              "delivery_frac_t0": 0.5, "mesh_deg_min": 1,
                              "mesh_deg_mean": 2.0, "mesh_deg_max": 3,
                              "fault_flags": 0}])
        snap = self._snap(hp)
        assert snap["fault_flags"] is None
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "dashboard.py"),
             hp, "--once"],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
        assert "(invariants off)" in res.stdout
        assert "clean" not in res.stdout

    def _snap(self, hp):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "dashboard.py"),
             hp, "--once", "--json"],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
        assert res.returncode == 0, res.stderr[-800:]
        return json.loads(res.stdout)

    def test_missing_journal_exits_1(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "dashboard.py"),
             str(tmp_path / "nope.jsonl"), "--once"],
            capture_output=True, text=True, timeout=60, env=env, cwd=REPO)
        assert res.returncode == 1


class TestFleetCrashReplay:
    def _crash_fleet(self, tmp_path, members):
        from go_libp2p_pubsub_tpu.sim.fleet import supervised_fleet_run

        def bomb(info):
            raise RuntimeError("injected window failure")

        sup = SupervisorConfig(chunk_ticks=4, max_retries=0,
                               crash_dir=str(tmp_path / "crash"),
                               sleep=lambda s: None)
        with pytest.raises(SupervisorCrash) as ei:
            supervised_fleet_run(members, sup, _chunk_hook=bomb)
        return ei.value.dump_dir

    def test_member_replay_clean_and_tripped(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import replay_crash
        from go_libp2p_pubsub_tpu.sim.fleet import FleetMember

        cfg, tp, st = _tiny()
        # member 1 carries a poisoned counter: its lane's replay must
        # REPRODUCE the invariant trip; member 0 replays clean
        poisoned = st._replace(mesh_failure_penalty=st.mesh_failure_penalty
                               .at[0, 0, 0].set(jnp.inf))
        members = [FleetMember(cfg=cfg, tp=tp, state=st,
                               key=jax.random.PRNGKey(5), n_ticks=4,
                               name="clean"),
                   FleetMember(cfg=cfg, tp=tp, state=poisoned,
                               key=jax.random.PRNGKey(6), n_ticks=4,
                               name="poisoned")]
        dump = self._crash_fleet(tmp_path, members)
        meta = replay_crash.load_meta(dump)
        assert replay_crash.is_fleet_dump(meta)
        assert meta["member_names"] == ["clean", "poisoned"]

        clean = replay_crash.replay_fleet(dump, 0, like=st, cfg=cfg, tp=tp)
        assert clean["tripped"] is False and clean["ticks"] == 4
        assert clean["member_name"] == "clean"

        tripped = replay_crash.replay_fleet(dump, 1, like=st, cfg=cfg,
                                            tp=tp)
        assert tripped["tripped"] is True
        assert "invariant violation" in tripped["error"]

        # wrong config must be refused by the fleet-axis fingerprint
        import dataclasses as dc
        with pytest.raises(SystemExit, match="fingerprint"):
            replay_crash.replay_fleet(
                dump, 0, like=st,
                cfg=dc.replace(cfg, history_length=cfg.history_length + 1),
                tp=tp)
        with pytest.raises(SystemExit, match="not in this dump"):
            replay_crash.replay_fleet(dump, 7, like=st, cfg=cfg, tp=tp)

    def test_mixed_config_groups_map_input_indices(self, tmp_path):
        """A mixed-config fleet splits into groups; the dump stamps each
        group's member INPUT indices so --member keeps meaning the input
        index (group position is an implementation detail)."""
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import dataclasses as dc

        import replay_crash
        from go_libp2p_pubsub_tpu.sim.fleet import FleetMember

        cfg, tp, st = _tiny()
        cfg2 = dc.replace(cfg, gater_enabled=True)
        members = [FleetMember(cfg=cfg, tp=tp, state=st,
                               key=jax.random.PRNGKey(1), n_ticks=4,
                               name="A"),
                   FleetMember(cfg=cfg2, tp=tp, state=st,
                               key=jax.random.PRNGKey(2), n_ticks=4,
                               name="B"),
                   FleetMember(cfg=cfg, tp=tp, state=st,
                               key=jax.random.PRNGKey(3), n_ticks=4,
                               name="C")]
        dump = self._crash_fleet(tmp_path, members)
        meta = replay_crash.load_meta(dump)
        # group 0 = the cfg members, input indices 0 and 2
        assert meta["member_ids"] == [0, 2]
        assert meta["member_names"] == ["A", "C"]
        r = replay_crash.replay_fleet(dump, 2, like=st, cfg=cfg, tp=tp)
        assert r["member_name"] == "C" and r["tripped"] is False
        # member 1 belongs to the OTHER config group — refused by name
        with pytest.raises(SystemExit, match="not in this dump"):
            replay_crash.replay_fleet(dump, 1, like=st, cfg=cfg, tp=tp)


@pytest.mark.slow
def test_two_process_multihost_health_smoke(tmp_path):
    """The multihost lens: a REAL 2-process jax.distributed CPU run with
    --health streams rank-0-only journal rows that match the
    single-process telemetry stream (score_mean exempted — sharded
    reduction reassociation, module docstring)."""
    from go_libp2p_pubsub_tpu.sim import init_state

    def spawn(rank):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        env.pop("XLA_FLAGS", None)      # one device per rank
        return subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "scripts", "run_multihost.py"),
             "--coordinator", "localhost:19923", "--num-processes", "2",
             "--process-id", str(rank), "--scenario", "frontier_250k",
             "--n", "128", "--seed", "7", "--ticks", "4",
             "--health", str(tmp_path / f"health_r{rank}.jsonl")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=str(tmp_path))

    procs = [spawn(r) for r in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for (out, err), p in zip(outs, procs):
        assert p.returncode == 0, f"rank rc={p.returncode}\n{err[-3000:]}"
    # rank-0-only write discipline
    assert os.path.exists(tmp_path / "health_r0.jsonl")
    assert not os.path.exists(tmp_path / "health_r1.jsonl")
    j = telemetry.read_journal(str(tmp_path / "health_r0.jsonl"))
    assert [r["tick"] for r in j["rows"]] == [0, 1, 2, 3]

    cfg, tp, topo, sub = scenarios.frontier_spec(128)
    st = init_state(cfg, topo, subscribed=sub)
    keys = jax.random.split(jax.random.PRNGKey(7), 4)
    _, health = run_keys(st, cfg, tp, keys, telemetry=True)
    mat, cols = telemetry.records_to_rows(health)
    ref = telemetry.rows_to_dicts(mat, cols)
    for got, want in zip(_strip(j["rows"]), ref):
        for (nm, _ii) in cols:
            if nm == "score_mean":
                assert got[nm] == pytest.approx(want[nm], rel=1e-5)
            else:
                assert got[nm] == want[nm], nm
