"""Signed-peer-record validation on the PX dial path.

Mirrors the reference's envelope checks before dialing peers learned through
PRUNE peer exchange (gossipsub.go:893-926: unmarshal envelope over the
peer-record domain, payload must be a peer record, record id must match the
announced id — else skip without dialing) and the certified-store flows
around it (GetPeerRecord on the prune side, gossipsub.go:1885-1901;
ConsumePeerRecord after a successful dial, gossipsub.go:954-958).
"""

import pytest

from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub, generate_keypair
from go_libp2p_pubsub_tpu.api.peer_record import (
    PEER_RECORD_PAYLOAD_TYPE,
    PeerRecord,
    RecordError,
    consume_peer_record,
    encode_peer_record,
    seal_record,
)
from go_libp2p_pubsub_tpu.core.types import PeerInfo
from go_libp2p_pubsub_tpu.net import Network
from go_libp2p_pubsub_tpu.pb.codec import _bytes_field
from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter


class TestEnvelope:
    def test_seal_consume_roundtrip(self):
        key, pid = generate_keypair(seed=b"alice")
        rec = PeerRecord(peer_id=pid, seq=7, addrs=("10.0.0.1", "10.0.0.2"))
        out = consume_peer_record(seal_record(rec, key))
        assert out == rec

    def test_tampered_payload_rejected(self):
        key, pid = generate_keypair(seed=b"alice")
        env = bytearray(seal_record(PeerRecord(peer_id=pid, seq=1), key))
        env[-1] ^= 0x01          # flip a signature bit
        with pytest.raises(RecordError, match="signature"):
            consume_peer_record(bytes(env))

    def test_wrong_payload_type_rejected(self):
        key, pid = generate_keypair(seed=b"alice")
        env = seal_record(PeerRecord(peer_id=pid, seq=1), key)
        bogus = env.replace(
            _bytes_field(2, PEER_RECORD_PAYLOAD_TYPE), _bytes_field(2, b"\x99\x99"))
        with pytest.raises(RecordError, match="not a peer record"):
            consume_peer_record(bogus)

    def test_impersonation_rejected(self):
        """A record claiming someone else's id, signed with the attacker's
        own (valid) key, must not validate: the id is self-certifying."""
        key_attacker, _ = generate_keypair(seed=b"mallory")
        _, pid_victim = generate_keypair(seed=b"alice")
        env = seal_record(PeerRecord(peer_id=pid_victim, seq=1), key_attacker)
        with pytest.raises(RecordError, match="doesn't match signing key"):
            consume_peer_record(env)

    def test_garbage_rejected(self):
        with pytest.raises(RecordError):
            consume_peer_record(b"\xff\xfe not an envelope")

    def test_varint_field_attack_rejected(self):
        """Envelope fields encoded as huge varints (wire type 0) must raise
        RecordError, not attempt a terabyte allocation."""
        from go_libp2p_pubsub_tpu.pb.codec import _varint_field
        # field 5 (signature) as varint 2**40
        evil = _varint_field(5, 1 << 40)
        with pytest.raises(RecordError):
            consume_peer_record(evil)

    def test_signed_garbage_payload_rejected(self):
        """A validly SIGNED but malformed record payload (attacker signs
        arbitrary bytes with their own key) must raise RecordError."""
        from cryptography.hazmat.primitives.serialization import (
            Encoding, PublicFormat)
        from go_libp2p_pubsub_tpu.api.peer_record import (
            PEER_RECORD_ENVELOPE_DOMAIN, _unsigned_bytes)
        from go_libp2p_pubsub_tpu.pb.codec import _bytes_field
        key, _ = generate_keypair(seed=b"mallory")
        # seq (field 2) as length-delimited non-integer bytes
        payload = _bytes_field(2, b"notanint")
        sig = key.sign(_unsigned_bytes(
            PEER_RECORD_ENVELOPE_DOMAIN, PEER_RECORD_PAYLOAD_TYPE, payload))
        pub = key.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        env = (_bytes_field(1, pub) + _bytes_field(2, PEER_RECORD_PAYLOAD_TYPE)
               + _bytes_field(3, payload) + _bytes_field(5, sig))
        with pytest.raises(RecordError):
            consume_peer_record(env)

    def test_peer_record_codec(self):
        rec = PeerRecord(peer_id="ed25519:00ff", seq=3, addrs=("a", "b"))
        assert consume_peer_record.__doc__  # silence lint on import use
        from go_libp2p_pubsub_tpu.api.peer_record import decode_peer_record
        assert decode_peer_record(encode_peer_record(rec)) == rec


def _keyed_node(net, seed):
    key, pid = generate_keypair(seed=seed)
    h = net.add_host(peer_id=pid)
    rt = GossipSubRouter(do_px=True)
    ps = PubSub(h, rt, sign_policy=LAX_NO_SIGN, sign_key=key)
    return ps, rt, h


class TestPXDialGate:
    def _net3(self):
        """A (dialer), B (existing peer), C (PX target, never yet dialed)."""
        net = Network()
        a, rt_a, ha = _keyed_node(net, b"a")
        b, _, hb = _keyed_node(net, b"b")
        c, _, hc = _keyed_node(net, b"c")
        ha.connect(hb)
        net.scheduler.run_for(0.1)
        return net, (a, rt_a, ha), (b, None, hb), (c, None, hc)

    def test_forged_record_produces_zero_dials(self):
        net, (a, rt_a, ha), _, (c, _, hc) = self._net3()
        key_m, _ = generate_keypair(seed=b"mallory")
        forged = seal_record(PeerRecord(peer_id=hc.peer_id, seq=1), key_m)
        rt_a.px_connect([PeerInfo(peer_id=hc.peer_id,
                                  signed_peer_record=forged)])
        net.scheduler.run_for(1.0)
        assert hc.peer_id not in ha.conns

    def test_mismatched_announced_id_produces_zero_dials(self):
        """Valid envelope, but certifying a different peer than announced —
        the announced id must be a NON-peer so the check itself is hit."""
        net, (a, rt_a, ha), _, (c, _, hc) = self._net3()
        d, _, hd = _keyed_node(net, b"d")     # never connected to A
        # C's genuine record announced under D's id -> reject, no dial
        rt_a.px_connect([PeerInfo(peer_id=hd.peer_id,
                                  signed_peer_record=hc.local_record)])
        net.scheduler.run_for(1.0)
        assert hd.peer_id not in ha.conns
        assert hc.peer_id not in ha.conns

    def test_valid_record_dials_and_persists(self):
        net, (a, rt_a, ha), _, (c, _, hc) = self._net3()
        rt_a.px_connect([PeerInfo(peer_id=hc.peer_id,
                                  signed_peer_record=hc.local_record)])
        net.scheduler.run_for(1.0)
        assert hc.peer_id in ha.conns
        # ConsumePeerRecord analogue: the validated record is retained
        assert ha.certified_records[hc.peer_id] == hc.local_record

    def test_recordless_px_still_dials(self):
        """No signed record attached: dial anyway (the reference trusts the
        DHT for addresses, not PX; the id alone is allowed through)."""
        net, (a, rt_a, ha), _, (c, _, hc) = self._net3()
        rt_a.px_connect([PeerInfo(peer_id=hc.peer_id)])
        net.scheduler.run_for(1.0)
        assert hc.peer_id in ha.conns


class TestPruneAttachesRecords:
    def test_prune_px_carries_certified_records(self):
        """make_prune attaches stored records for exchanged peers
        (gossipsub.go:1885-1901)."""
        net = Network()
        a, rt_a, ha = _keyed_node(net, b"a")
        b, _, hb = _keyed_node(net, b"b")
        c, _, hc = _keyed_node(net, b"c")
        ha.connect(hb)
        ha.connect(hc)
        net.scheduler.run_for(0.1)
        for n in (a, b, c):
            n.join("t").subscribe()
        net.scheduler.run_until(3.0)
        pr = rt_a.make_prune(hb.peer_id, "t", do_px=True, is_unsubscribe=False)
        assert [pi.peer_id for pi in pr.peers] == [hc.peer_id]
        assert pr.peers[0].signed_peer_record == hc.local_record
        # and the attached record validates against the announced id
        rec = consume_peer_record(pr.peers[0].signed_peer_record)
        assert rec.peer_id == hc.peer_id
