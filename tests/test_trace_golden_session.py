"""Extended gogo-byte golden corpus: a 4-peer multi-tick session replayed
into BOTH halves of the framework (VERDICT r4 item 6a).

`test_trace_golden.py` pins the wire layout with a 2-peer session; this
corpus widens the BEHAVIORAL evidence: four peers, six virtual seconds,
every gossipsub control type on the wire (GRAFT, PRUNE-with-PX peers,
IHAVE, IWANT), mesh delivery + in-window duplicate, a gossip pull
(IHAVE -> IWANT -> delivery from a non-mesh peer), an invalid-signature
reject, prune-time P3b penalties on BOTH sides of a pruned edge, peer
removal with score retention, and five decay boundaries.

The same byte stream (assembled by the test_trace_golden mini-marshaller,
whose tag bytes come from the reference's generated encoder,
/root/reference/pb/trace.pb.go) is:

  1. decoded + re-encoded BYTE-EXACT through pb/codec.py;
  2. replayed into the BATCHED half (trace/tensorize -> replay_feed on a
     4-peer SimState);
  3. driven into the FUNCTIONAL half (routers/score.py PeerScore, one
     scorer per observer, refreshed at the same absolute decay
     boundaries the replay uses — score.go:504-565 semantics);
  4. the two halves' per-(observer, peer) counters — first/mesh/invalid
     message deliveries and the sticky mesh-failure penalty — must agree
     to float tolerance, with hand-derived literal spot checks so a
     shared misreading cannot hide behind matching implementations.
"""

import numpy as np
import pytest

import test_trace_golden as g
from go_libp2p_pubsub_tpu.core.params import PeerScoreParams, TopicScoreParams
from go_libp2p_pubsub_tpu.core.types import Message
from go_libp2p_pubsub_tpu.pb import codec
from go_libp2p_pubsub_tpu.routers.score import PeerScore
from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
from go_libp2p_pubsub_tpu.trace import replay_feed, replay_topic_params, tensorize_trace

TOPIC = g.TOPIC
PROTO = g.PROTO
PEER_C = bytes([0x12, 0x20]) + bytes(range(0x20, 0x40))
PEER_D = bytes([0x12, 0x20]) + bytes(range(0x00, 0x20))
A, B = g.A, g.B
C = PEER_C.decode("utf-8", "surrogateescape")
D = PEER_D.decode("utf-8", "surrogateescape")
PEERS = {A: 0, B: 1, C: 2, D: 3}
RAW = {A: g.PEER_A, B: g.PEER_B, C: PEER_C, D: PEER_D}
M1, M2, M3 = b"\x11\x22\x33\x44", b"\xaa\xbb\xcc\xdd", b"\x55\x66\x77\x88"

TSP = TopicScoreParams(
    topic_weight=1.0, time_in_mesh_weight=0.05, time_in_mesh_quantum=1.0,
    time_in_mesh_cap=100.0, first_message_deliveries_weight=1.0,
    first_message_deliveries_decay=0.9, first_message_deliveries_cap=50.0,
    mesh_message_deliveries_weight=-0.5, mesh_message_deliveries_decay=0.8,
    mesh_message_deliveries_cap=30.0, mesh_message_deliveries_threshold=3.0,
    mesh_message_deliveries_window=0.05,
    mesh_message_deliveries_activation=1.0,
    mesh_failure_penalty_weight=-1.0, mesh_failure_penalty_decay=0.7,
    invalid_message_deliveries_weight=-5.0,
    invalid_message_deliveries_decay=0.9)

T_END = 6.0


def build_session(t0_ns: int = 250_000_000) -> bytes:
    def ts(k):                      # quarter-second steps from 0.25 s
        return t0_ns + k * 250_000_000

    ev = g._event
    sub_graft = g._meta(subscription=[(True, TOPIC)],
                        control=g._control(graft=[TOPIC]))
    px_prune = g._meta(control=g._control(prune=[(TOPIC, [g.PEER_B])]))
    return b"".join([
        # k0-k1: connections (A hub; B-C cross edge)
        ev("ADD_PEER", g.PEER_A, ts(0), g._add_peer(g.PEER_B, PROTO)),
        ev("ADD_PEER", g.PEER_B, ts(0), g._add_peer(g.PEER_A, PROTO)),
        ev("ADD_PEER", g.PEER_A, ts(0), g._add_peer(PEER_C, PROTO)),
        ev("ADD_PEER", PEER_C, ts(0), g._add_peer(g.PEER_A, PROTO)),
        ev("ADD_PEER", g.PEER_A, ts(1), g._add_peer(PEER_D, PROTO)),
        ev("ADD_PEER", PEER_D, ts(1), g._add_peer(g.PEER_A, PROTO)),
        ev("ADD_PEER", g.PEER_B, ts(1), g._add_peer(PEER_C, PROTO)),
        ev("ADD_PEER", PEER_C, ts(1), g._add_peer(g.PEER_B, PROTO)),
        # k2: everyone joins
        ev("JOIN", g.PEER_A, ts(2), g._join(TOPIC)),
        ev("JOIN", g.PEER_B, ts(2), g._join(TOPIC)),
        ev("JOIN", PEER_C, ts(2), g._join(TOPIC)),
        ev("JOIN", PEER_D, ts(2), g._join(TOPIC)),
        # k3 (1.0 s): mutual graft A-B, on the wire and in the tracer
        ev("GRAFT", g.PEER_A, ts(3), g._graft_or_prune(g.PEER_B, TOPIC)),
        ev("SEND_RPC", g.PEER_A, ts(3), g._rpc(g.PEER_B, sub_graft)),
        ev("RECV_RPC", g.PEER_B, ts(3), g._rpc(g.PEER_A, sub_graft)),
        ev("GRAFT", g.PEER_B, ts(3), g._graft_or_prune(g.PEER_A, TOPIC)),
        # k4 (1.25 s): mutual graft A-C
        ev("GRAFT", g.PEER_A, ts(4), g._graft_or_prune(PEER_C, TOPIC)),
        ev("SEND_RPC", g.PEER_A, ts(4), g._rpc(PEER_C, sub_graft)),
        ev("RECV_RPC", PEER_C, ts(4), g._rpc(g.PEER_A, sub_graft)),
        ev("GRAFT", PEER_C, ts(4), g._graft_or_prune(g.PEER_A, TOPIC)),
        # k6 (1.75 s): A publishes M1 into its mesh
        ev("PUBLISH_MESSAGE", g.PEER_A, ts(6), g._publish(M1, TOPIC)),
        ev("SEND_RPC", g.PEER_A, ts(6), g._rpc(
            g.PEER_B, g._meta(messages=[(M1, TOPIC)]))),
        ev("SEND_RPC", g.PEER_A, ts(6), g._rpc(
            PEER_C, g._meta(messages=[(M1, TOPIC)]))),
        # k7 (2.0 s, decay boundary first): mesh deliveries + duplicate
        ev("DELIVER_MESSAGE", g.PEER_B, ts(7), g._deliver(M1, TOPIC, g.PEER_A)),
        ev("DELIVER_MESSAGE", PEER_C, ts(7), g._deliver(M1, TOPIC, g.PEER_A)),
        ev("SEND_RPC", g.PEER_B, ts(7), g._rpc(
            PEER_C, g._meta(messages=[(M1, TOPIC)]))),
        ev("DUPLICATE_MESSAGE", PEER_C, ts(7), g._duplicate(M1, g.PEER_B, TOPIC)),
        # k8-k10: the gossip pull path A -> D (IHAVE -> IWANT -> delivery)
        ev("SEND_RPC", g.PEER_A, ts(8), g._rpc(PEER_D, g._meta(
            control=g._control(ihave=[(TOPIC, [M1])])))),
        ev("RECV_RPC", PEER_D, ts(8), g._rpc(g.PEER_A, g._meta(
            control=g._control(ihave=[(TOPIC, [M1])])))),
        ev("SEND_RPC", PEER_D, ts(9), g._rpc(g.PEER_A, g._meta(
            control=g._control(iwant=[[M1]])))),
        ev("RECV_RPC", g.PEER_A, ts(9), g._rpc(PEER_D, g._meta(
            control=g._control(iwant=[[M1]])))),
        ev("SEND_RPC", g.PEER_A, ts(10), g._rpc(
            PEER_D, g._meta(messages=[(M1, TOPIC)]))),
        ev("DELIVER_MESSAGE", PEER_D, ts(10), g._deliver(M1, TOPIC, g.PEER_A)),
        # k11-k12: C publishes an invalid message, A rejects it (P4)
        ev("PUBLISH_MESSAGE", PEER_C, ts(11), g._publish(M2, TOPIC)),
        ev("SEND_RPC", PEER_C, ts(11), g._rpc(
            g.PEER_A, g._meta(messages=[(M2, TOPIC)]))),
        ev("REJECT_MESSAGE", g.PEER_A, ts(12),
           g._reject(M2, PEER_C, "invalid signature", TOPIC)),
        # k13 (3.5 s): A prunes C with PX (peers=[B]) — P3b on both sides
        ev("SEND_RPC", g.PEER_A, ts(13), g._rpc(PEER_C, px_prune)),
        ev("RECV_RPC", PEER_C, ts(13), g._rpc(g.PEER_A, px_prune)),
        ev("PRUNE", g.PEER_A, ts(13), g._graft_or_prune(PEER_C, TOPIC)),
        ev("PRUNE", PEER_C, ts(13), g._graft_or_prune(g.PEER_A, TOPIC)),
        # k14-k15: B publishes M3, A mesh-delivers it
        ev("PUBLISH_MESSAGE", g.PEER_B, ts(14), g._publish(M3, TOPIC)),
        ev("SEND_RPC", g.PEER_B, ts(14), g._rpc(
            g.PEER_A, g._meta(messages=[(M3, TOPIC)]))),
        ev("DELIVER_MESSAGE", g.PEER_A, ts(15), g._deliver(M3, TOPIC, g.PEER_B)),
        # k16-k17: D leaves; A drops the connection (retention path)
        ev("LEAVE", PEER_D, ts(16), g._leave(TOPIC)),
        ev("REMOVE_PEER", g.PEER_A, ts(17), g._remove_peer(PEER_D)),
    ])


SESSION = build_session()


class TestSessionWire:
    def test_decode_and_reencode_byte_exact(self):
        events = codec.decode_trace_bytes(SESSION)
        assert len(events) == 45
        out = b"".join(
            codec.write_uvarint(len(e)) + e
            for e in (codec.encode_trace_event(evt) for evt in events))
        assert out == SESSION

    def test_every_control_type_on_the_wire(self):
        events = codec.decode_trace_bytes(SESSION)
        seen = set()
        px_peers = []
        for e in events:
            for key in ("sendRPC", "recvRPC"):
                ctl = e.get(key, {}).get("meta", {}).get("control", {})
                seen.update(ctl.keys())
                for p in ctl.get("prune", ()):
                    px_peers.extend(p.get("peers", ()))
        assert seen == {"ihave", "iwant", "graft", "prune"}
        # the PRUNE carries PX: peer B offered as a reconnect candidate
        assert B in px_peers


def _replay_batched():
    events = codec.decode_trace_bytes(SESSION)
    feed = tensorize_trace(events, PEERS, {TOPIC: 0}, msg_window=16,
                           decay_interval=1.0,
                           dup_window=TSP.mesh_message_deliveries_window,
                           t_end=T_END)
    cfg = SimConfig(n_peers=4, k_slots=4, n_topics=1, msg_window=16,
                    scoring_enabled=True)
    topo = topology.full(4, 4)
    st = init_state(cfg, topo, subscribed=np.zeros((4, 1), bool))
    tp = replay_topic_params([TSP])
    st = replay_feed(st, cfg, tp, feed)
    slot = {}
    nbr = np.asarray(topo.neighbors)
    for i in range(4):
        for s, j in enumerate(nbr[i]):
            if j >= 0:
                slot[(i, int(j))] = s
    return st, slot


class _MidIs:
    """id(msg) = the trace messageID literal (stashed in seqno)."""

    def id(self, msg):
        return msg.seqno


def _drive_functional():
    params = PeerScoreParams(app_specific_score=lambda p: 0.0,
                             decay_interval=1.0, decay_to_zero=0.01,
                             retain_score=10.0, topics={TOPIC: TSP})
    clocks = {p: {"t": 0.0} for p in PEERS}
    scorers = {p: PeerScore(params, now=(lambda c=clocks[p]: c["t"]),
                            id_gen=_MidIs()) for p in PEERS}
    events = codec.decode_trace_bytes(SESSION)
    next_decay = [1.0]

    def advance(ts):
        while ts >= next_decay[0] - 1e-9:
            for p, sc in scorers.items():
                clocks[p]["t"] = next_decay[0]
                sc.refresh_scores()
            next_decay[0] += 1.0

    def msg(payload):
        return Message(topic=payload.get("topic", TOPIC),
                       seqno=payload["messageID"],
                       received_from=payload.get("receivedFrom"))

    for e in events:
        advance(e["timestamp"])
        obs = e["peerID"]
        sc = scorers[obs]
        clocks[obs]["t"] = e["timestamp"]
        t = e["type"]
        if t == "ADD_PEER":
            sc.add_peer(e["addPeer"]["peerID"], e["addPeer"]["proto"])
        elif t == "REMOVE_PEER":
            sc.remove_peer(e["removePeer"]["peerID"])
        elif t == "GRAFT":
            sc.graft(e["graft"]["peerID"], e["graft"]["topic"])
        elif t == "PRUNE":
            sc.prune(e["prune"]["peerID"], e["prune"]["topic"])
        elif t == "DELIVER_MESSAGE":
            sc.deliver_message(msg(e["deliverMessage"]))
        elif t == "DUPLICATE_MESSAGE":
            sc.duplicate_message(msg(e["duplicateMessage"]))
        elif t == "REJECT_MESSAGE":
            sc.reject_message(msg(e["rejectMessage"]),
                              e["rejectMessage"]["reason"])
    advance(T_END)
    return scorers


@pytest.fixture(scope="module")
def both_halves():
    st, slot = _replay_batched()
    scorers = _drive_functional()
    return st, slot, scorers


class TestCrossHalfCounters:
    """Every per-(observer, peer) score counter must agree between the
    batched replay and the functional PeerScore at t_end."""

    def _counters(self, both, field, fn_attr):
        st, slot, scorers = both
        batched = np.asarray(getattr(st, field))
        out = []
        for obs, oi in PEERS.items():
            for peer, pi in PEERS.items():
                if obs == peer:
                    continue
                b = float(batched[oi, 0, slot[(oi, pi)]])
                ts = scorers[obs].peer_stats.get(peer)
                f = 0.0
                if ts is not None and TOPIC in ts.topics:
                    f = float(getattr(ts.topics[TOPIC], fn_attr))
                out.append((obs[:4], peer[:4], b, f))
        return out

    @pytest.mark.parametrize("field,attr", [
        ("first_message_deliveries", "first_message_deliveries"),
        ("mesh_message_deliveries", "mesh_message_deliveries"),
        ("invalid_message_deliveries", "invalid_message_deliveries"),
        ("mesh_failure_penalty", "mesh_failure_penalty"),
    ])
    def test_counters_match(self, both_halves, field, attr):
        for obs, peer, b, f in self._counters(both_halves, field, attr):
            assert b == pytest.approx(f, abs=1e-5), \
                f"{field}[{obs}->{peer}]: batched {b} vs functional {f}"

    def test_hand_derived_spot_checks(self, both_halves):
        st, slot, scorers = both_halves
        fmd = np.asarray(st.first_message_deliveries)
        imd = np.asarray(st.invalid_message_deliveries)
        mfp = np.asarray(st.mesh_failure_penalty)
        ai, bi, ci, di = (PEERS[p] for p in (A, B, C, D))
        # B's FMD for A: M1 delivered at 2.0, decayed at 3,4,5,6 -> 0.9^4
        assert fmd[bi, 0, slot[(bi, ai)]] == pytest.approx(0.9 ** 4, abs=1e-6)
        # A's FMD for B: M3 delivered at 4.0, decayed at 5,6 -> 0.9^2
        assert fmd[ai, 0, slot[(ai, bi)]] == pytest.approx(0.9 ** 2, abs=1e-6)
        # D's FMD for A (gossip pull, non-mesh): 2.75, decayed 3..6 -> 0.9^4
        assert fmd[di, 0, slot[(di, ai)]] == pytest.approx(0.9 ** 4, abs=1e-6)
        # A's IMD for C: reject at 3.25, decayed at 4,5,6 -> 0.9^3
        assert imd[ai, 0, slot[(ai, ci)]] == pytest.approx(0.9 ** 3, abs=1e-6)
        # A prunes C at 3.5 with C's mmd 0 and P3 active (grafted 1.25,
        # activation 1.0, activated at the 3.0 refresh): deficit 3^2 = 9,
        # then mfp decay 0.7 at 4,5,6
        assert mfp[ai, 0, slot[(ai, ci)]] == pytest.approx(
            9.0 * 0.7 ** 3, abs=1e-6)
        # C prunes A at 3.5: A's mmd at C was 1 (the mesh delivery at 2.0;
        # the duplicate came from B, who is NOT in C's mesh — duplicates
        # only credit mesh senders, score.go:949-981), decayed 0.8 at
        # 3.0 -> 0.8; deficit 2.2^2 = 4.84
        assert mfp[ci, 0, slot[(ci, ai)]] == pytest.approx(
            4.84 * 0.7 ** 3, abs=1e-5)
        # retention: D was removed at 4.5 with score 0 -> stats retained,
        # frozen (no decay while disconnected, score.go:611-644)
        ts_d = scorers[A].peer_stats[D]
        assert not ts_d.connected
