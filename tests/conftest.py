"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

This image routes every JAX process to the single remote TPU via an axon
sitecustomize hook; the TPU admits one client at a time, so tests must NOT
touch it. The hook registers the backend at interpreter start (jax is already
imported by the time conftest runs) but nothing is *initialized* until the
first jax.devices()/dispatch — so overriding jax_platforms via jax.config
here, before any test imports run, reliably pins the whole session to CPU.
XLA_FLAGS is also read at backend init, so setting it here still works.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
