"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

This image routes every JAX process to the single remote TPU via an axon
sitecustomize hook; the TPU admits one client at a time, so tests must NOT
touch it. The hook registers the backend at interpreter start (jax is already
imported by the time conftest runs) but nothing is *initialized* until the
first jax.devices()/dispatch — so overriding jax_platforms via jax.config
here, before any test imports run, reliably pins the whole session to CPU.
XLA_FLAGS is also read at backend init, so setting it here still works.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# ---- fast/slow tiers (VERDICT r3 #8) ----------------------------------------
# The full suite crossed 20 minutes; iteration needs a < 4 min core. The
# slow tier is defined HERE, centrally, instead of scattering decorators:
# whole files (value ALL) or nodeid substrings. Everything else is the fast
# tier: `python -m pytest tests/ -m "not slow"`.

import pytest  # noqa: E402

ALL = ()
_SLOW = {
    # long multi-scenario scans and module-scoped 512-peer swarm fixtures
    "test_churn_scenarios.py": ALL,
    "test_statistical_parity.py": ALL,
    "test_delivery_structural.py": ALL,
    "test_gater_backpressure.py": ALL,
    "test_checkpoint.py": ALL,
    "test_trace_export.py": ALL,
    "test_hopkernel.py": ALL,
    # spawns bench.py subprocesses / bounded-timeout platform probes
    "test_bench_contract.py": ALL,
    "test_platform_probe.py": ALL,
    # long engine-trajectory sweeps; op-level parity stays fast
    "test_permgather.py": ("TestEngineTrajectoryParity",
                           "TestShardedStepParity",
                           "test_engine_trajectory_sort_equals_scalar",
                           "test_sort_mode_parity_under_churn",
                           "test_count_dtype_trajectory_parity"),
    # the aligned acceptance trajectory case (mxu == sort) stays fast;
    # the ragged-block twin and the churn+gater+flood degrade-seam
    # sweep are belt-and-braces (PR 13 re-balanced the tier-1 wall)
    "test_mxu_mode.py": ("test_mxu_under_churn_and_gater",
                         "test_mxu_equals_sort[block_ragged"),
    # fault plane: the per-class bit lenses (partition/null/union), the
    # link-fault + sentinel + trace-health cores, and one cut-heal
    # connectivity case stay tier-1; the multi-scenario clean sweeps,
    # the aggregated every-class bit sweep, and the longer partition
    # trajectories are belt-and-braces (each mechanism keeps a cheaper
    # tier-1 sibling; the faults marker tier runs them all)
    "test_faults.py": ("test_baseline_scenarios_run_clean",
                       "test_fault_scenarios_clean_before_window",
                       "test_router_sweep_runs_clean",
                       "test_each_fault_class_sets_its_bit",
                       "test_outage_darkens_and_returns",
                       "test_partition_recovers_delivery",
                       "test_back_to_back_windows_still_heal"),
    # 50-scenario randomized sweep — belt-and-braces by construction
    "test_cross_half_fuzz.py": ("test_fifty_random_scenarios_cross_half",),
    # burst-churn self-healing: the stamp/clear mechanism lens stays
    # tier-1; the longer degree-recovery trajectory is belt-and-braces
    "test_self_healing.py": ("test_degree_recovers_after_burst",),
    "test_selection_modes.py": ("TestEngineTrajectoryParity",
                                "test_count_bound_guard_fires"),
    # multihost (ISSUE 8): the subprocess smokes (fresh jax imports +
    # gloo handshakes) and the 8-device step compiles ride the slow tier
    # — tier-1 keeps the instant accounting/validation lenses. The
    # tier-1 wall budget is the binding constraint (ROADMAP verify
    # command's 870 s timeout).
    "test_multihost.py": ("test_two_process_cpu_run_is_bit_exact",
                          "test_two_process_window_resume",
                          "test_concat_of_local_shards_equals_full_init",
                          "test_topo_local_concat_equals_full_build"),
    "test_hlo_sharded_budget.py": ALL,
    # row-sharded bucketed engine (ISSUE 16): the subprocess smokes
    # (8-device sharded parity, 2-process launcher runs, the supervised
    # SIGKILL -> relaunch leg, the 10M gate subprocess) and the
    # per-bucket device_init compiles ride the slow tier — tier-1 keeps
    # the ragged construction, checkpoint, pricing and refusal lenses
    "test_bucketed_sharded.py": ("TestLocalShards",
                                 "test_sharded_bucketed_routes_bit_exact",
                                 "test_two_process_bucketed_bit_exact",
                                 "test_mh_supervisor_bucketed_sigkill",
                                 "test_powerlaw_10m_gate_refuses"),
    "test_sharding.py": ("test_halo_mixed_dtype_payloads_bit_exact",
                         "test_sharded_step_matches_unsharded",
                         "test_2d_dcn_mesh_matches_unsharded",
                         "test_sharded_pallas_kernels_match_unsharded",
                         "test_sharded_sort_mode_matches_unsharded",
                         "test_sharded_halo_route_matches_unsharded",
                         "test_sharded_halo_2d_mesh_and_multigroup",
                         "test_halo_overflow_counter_fires_on_starved_capacity",
                         "test_halo_exact_bucket_capacity_trajectory_and_starved_control"),
    "test_sim_control.py": ("TestFanout", "TestGraftFloodPenalty"),
    # supervised execution plane: the chunk-parity/watchdog/crash-dump
    # core and the full-ladder smoke stay tier-1 (ISSUE 5 CI satellite);
    # the partition-scenario resume, replay reproduction, and traced-mode
    # sweeps are belt-and-braces
    # the full-ladder smoke (50 s: deadline trip -> backoff -> degrade ->
    # resume -> crash dump -> replay) moved to the slow tier in PR 8 —
    # the tier-1 wall budget is the binding constraint, and the same
    # ladder runs as scripts/supervisor_smoke.py first in every
    # tpu_recheck window
    "test_supervisor.py": ("TestPartitionFaultsResume",
                           "test_replay_crash_reproduces_clean_and_tripped",
                           "test_mode_fallback_rung_first",
                           "test_full_ladder_smoke",
                           "TestTracedMode"),
    # fleet plane (ISSUE 7): the acceptance core — B∈{1,4} parity,
    # one-member FaultPlan isolation, supervised kill/resume, the
    # fleet-axis fingerprint (the save/restore unit lens; the
    # end-to-end B4→B8 journal refusal rides slow since PR 13),
    # trip retirement — stays tier-1 (shapes
    # harmonized so the vmapped-scan compiles are shared); the extra
    # lenses (device-sharded parity, compaction schedule, ladder/crash
    # plumbing, weight-variant batching) are belt-and-braces
    "test_fleet.py": ("test_b4_journal_cannot_resume_into_b8",
                      "test_sharded_fleet_matches_sequential",
                      "test_heterogeneous_ticks_compact_finished_members",
                      "test_retry_ladder_then_parity",
                      "test_crash_dump_carries_per_member_flags",
                      "test_score_weight_variants_batch_together",
                      "test_record_member_with_flags_is_not_retired"),
    # latency-hiding pipeline (ISSUE 12): the plain-plane parity/failure/
    # kill/writer lenses stay tier-1 (shapes shared with test_supervisor);
    # the fleet and 8-device sharded overlap parities are belt-and-braces
    # (test_fleet/test_telemetry already exercise those planes under the
    # async default in tier-1)
    "test_overlap.py": ("TestFleetOverlap", "TestShardedOverlap"),
    # streaming telemetry plane (ISSUE 9): the core parity lenses (plain
    # scan, supervised chunked journal, fleet per-member) + encoders +
    # dashboard smoke stay tier-1; the retry/no-double-count and traced-
    # mode cross-checks, the fleet crash replay, and the sharded/
    # multihost smokes (8-device compile / subprocess pairs) are
    # belt-and-braces
    "test_telemetry.py": ("test_retried_chunk_rows_never_double_count",
                          "TestRunTracedHealth",
                          "TestFleetCrashReplay",
                          "test_fleet_stream_matches_per_member",
                          "test_bare_state_run_fn_not_mistaken",
                          "test_window_end_is_paused_not_ended"),
    # adversary & workload library (ISSUE 10): the acceptance core — the
    # five families with enforced contracts, the positive control, parse/
    # format round-trips, contract-evaluation pins, dashboard/telemetry
    # plumbing — stays tier-1; the host-runtime swarm parities and the
    # fleet collect_health integration are belt-and-braces
    "test_adversary.py": ("TestHostRuntimeAttacks",
                          "test_fleet_collect_health_rows_judge_contracts",
                          "test_censor_suppresses_victim_messages"),
    # precision ladder (ISSUE 13): codec round-trips, the layout audit,
    # and the refusal lenses stay tier-1 (the spec audit is the cheap
    # canary — a silently widened dtype fails by field name in
    # seconds); the trajectory/verdict parities (1k 39 s, eclipse
    # verdict pair 52 s, the 10k rung, the remaining four families)
    # ride the slow tier — the tier-1 wall budget is the binding
    # constraint
    "test_state_precision.py": ("test_parity_1k",
                                "test_parity_10k",
                                "test_eclipse_verdicts_unchanged_under_compact",
                                "test_remaining_families_verdicts_unchanged"),
    "test_sim_engine.py": ("test_scanned_window_equals_per_dispatch_ticks",
                           "test_negative_score_peer_gets_pruned",
                           "TestBackoff",
                           "TestNbrSubscribedCache",
                           "TestStarTopology",
                           "TestFloodPublish",
                           "TestDeterminism",
                           "TestFreeRunningCrossValidation",
                           "TestRouterVariants"),
}


# ---- optional-dependency gating ---------------------------------------------
# api/sign.py and api/peer_record.py degrade gracefully without the
# 'cryptography' package (minimal images; PR 4 robustness): the modules
# import, LAX_NO_SIGN swarms run, and only the ed25519 entry points raise.
# Tests that genuinely NEED signing/sealed-record crypto skip instead of
# failing — full environments run them all.

try:
    import cryptography  # noqa: F401
    _HAVE_CRYPTO = True
except ImportError:
    _HAVE_CRYPTO = False

_NEEDS_CRYPTO = {
    "test_px_records.py": ("TestEnvelope", "TestPXDialGate",
                           "TestPruneAttachesRecords"),
    "test_functional_runtime.py": ("TestSigning", "TestInvalidAuthor"),
}


def pytest_collection_modifyitems(config, items):
    skip_crypto = pytest.mark.skip(
        reason="needs the optional 'cryptography' package (ed25519)")
    for item in items:
        pats = _SLOW.get(item.path.name)
        if pats is not None and (pats is ALL
                                 or any(p in item.nodeid for p in pats)):
            item.add_marker(pytest.mark.slow)
        if not _HAVE_CRYPTO:
            cpats = _NEEDS_CRYPTO.get(item.path.name)
            if cpats is not None and any(p in item.nodeid for p in cpats):
                item.add_marker(skip_crypto)
