"""Integration tests for the host-side functional runtime.

In-process multi-node swarms over the deterministic substrate, mirroring the
reference's test pyramid (SURVEY.md §4): floodsub routing
(floodsub_test.go), gossipsub mesh/fanout/gossip/backoff
(gossipsub_test.go), signing, validation, blacklists, subscription
announcements, and mixed-router networks.
"""

import pytest

from go_libp2p_pubsub_tpu.api import (
    LAX_NO_SIGN,
    STRICT_SIGN,
    PubSub,
    ValidationError,
    generate_keypair,
)
from go_libp2p_pubsub_tpu.core.params import GossipSubParams
from go_libp2p_pubsub_tpu.net import Network
from go_libp2p_pubsub_tpu.routers import FloodSubRouter, RandomSubRouter
from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
from go_libp2p_pubsub_tpu.utils.subscription_filter import AllowlistSubscriptionFilter


def make_net(n, router_factory, connect="dense", degree=10, **pubsub_kw):
    net = Network()
    nodes = []
    for _ in range(n):
        h = net.add_host()
        nodes.append(PubSub(h, router_factory(), sign_policy=LAX_NO_SIGN,
                            **pubsub_kw))
    hosts = [x.host for x in nodes]
    if connect == "dense":
        net.dense_connect(hosts, degree=degree)
    elif connect == "sparse":
        net.sparse_connect(hosts, degree=3)
    elif connect == "all":
        net.connect_all(hosts)
    net.scheduler.run_for(0.1)
    return net, nodes


def drain(sub):
    out = []
    while (m := sub.next()) is not None:
        out.append(m)
    return out


class TestFloodSub:
    def test_basic_routing(self):
        # TestBasicFloodsub (floodsub_test.go:151)
        net, nodes = make_net(20, FloodSubRouter, connect="sparse")
        subs = [x.join("foobar").subscribe() for x in nodes]
        net.scheduler.run_for(0.5)
        for i in range(5):
            nodes[i].my_topics["foobar"].publish(b"msg %d" % i)
            net.scheduler.run_for(0.5)
        for s in subs:
            got = sorted(m.data for m in drain(s))
            assert got == [b"msg %d" % i for i in range(5)]

    def test_no_subscription_no_delivery(self):
        net, nodes = make_net(5, FloodSubRouter, connect="all")
        sub0 = nodes[0].join("a").subscribe()
        nodes[1].join("b").subscribe()
        net.scheduler.run_for(0.5)
        nodes[1].my_topics["b"].publish(b"to-b")
        net.scheduler.run_for(0.5)
        assert drain(sub0) == []

    def test_self_delivery(self):
        net, nodes = make_net(2, FloodSubRouter, connect="all")
        sub = nodes[0].join("t").subscribe()
        net.scheduler.run_for(0.2)
        nodes[0].my_topics["t"].publish(b"self")
        net.scheduler.run_for(0.2)
        assert [m.data for m in drain(sub)] == [b"self"]


class TestRandomSub:
    def test_propagation(self):
        # randomsub_test.go:TestRandomsubBig-ish, small scale
        net, nodes = make_net(20, lambda: RandomSubRouter(20), connect="dense")
        subs = [x.join("t").subscribe() for x in nodes]
        net.scheduler.run_for(0.5)
        for i in range(5):
            nodes[i].my_topics["t"].publish(b"m%d" % i)
            net.scheduler.run_for(0.5)
        # randomsub is probabilistic per hop; with sqrt(20)+flood target and
        # dense topology every node should see everything
        counts = [len(drain(s)) for s in subs]
        assert min(counts) >= 4


class TestGossipSub:
    def test_dense_full_delivery(self):
        # TestDenseGossipsub (gossipsub_test.go:85)
        net, nodes = make_net(20, GossipSubRouter)
        subs = [x.join("foobar").subscribe() for x in nodes]
        net.scheduler.run_for(3.0)
        for i in range(10):
            nodes[i % 20].my_topics["foobar"].publish(b"%d" % i)
            net.scheduler.run_for(0.3)
        net.scheduler.run_for(2.0)
        for s in subs:
            assert len(drain(s)) == 10

    def test_mesh_degree_bounds(self):
        net, nodes = make_net(24, GossipSubRouter)
        for x in nodes:
            x.join("t").subscribe()
        net.scheduler.run_for(5.0)
        p = GossipSubParams()
        degs = [len(x.rt.mesh["t"]) for x in nodes]
        assert max(degs) <= p.dhi
        assert min(degs) >= 1
        # meshes are symmetric
        by_pid = {x.pid: x for x in nodes}
        for x in nodes:
            for peer in x.rt.mesh["t"]:
                assert x.pid in by_pid[peer].rt.mesh["t"]

    def test_fanout_publish_without_subscribe(self):
        # TestGossipsubFanout (gossipsub_test.go:126)
        net, nodes = make_net(10, GossipSubRouter)
        subs = [x.join("t").subscribe() for x in nodes[1:]]
        net.scheduler.run_for(2.0)
        pub = nodes[0].join("t")
        pub.publish(b"from-fanout")
        net.scheduler.run_for(2.0)
        for s in subs:
            assert [m.data for m in drain(s)] == [b"from-fanout"]
        assert "t" in nodes[0].rt.fanout
        # fanout expires after FanoutTTL without publishing
        net.scheduler.run_for(GossipSubParams().fanout_ttl + 3.0)
        assert "t" not in nodes[0].rt.fanout

    def test_leave_sets_unsubscribe_backoff(self):
        net, nodes = make_net(6, GossipSubRouter, connect="all")
        subs = {x.pid: x.join("t").subscribe() for x in nodes}
        net.scheduler.run_for(2.0)
        leaver = nodes[0]
        mesh_peers = set(leaver.rt.mesh["t"])
        assert mesh_peers
        subs[leaver.pid].cancel()
        net.scheduler.run_for(0.5)
        assert "t" not in leaver.rt.mesh
        # the pruned peers recorded a backoff for the leaver
        for x in nodes[1:]:
            if x.pid in mesh_peers:
                assert leaver.pid in x.rt.backoff.get("t", {})

    def test_gossip_reaches_non_mesh_peers(self):
        # gossip propagation (TestGossipsubGossip semantics,
        # gossipsub_test.go:339): even peers outside the mesh receive via
        # IHAVE/IWANT within a few heartbeats
        net, nodes = make_net(20, GossipSubRouter)
        subs = [x.join("t").subscribe() for x in nodes]
        net.scheduler.run_for(3.0)
        nodes[0].my_topics["t"].publish(b"gossiped")
        # several heartbeats so IHAVE/IWANT can fire
        net.scheduler.run_for(4.0)
        assert all(len(drain(s)) == 1 for s in subs)

    def test_mixed_floodsub_gossipsub(self):
        # TestMixedGossipsub (gossipsub_test.go:909)
        net = Network()
        nodes = []
        for i in range(20):
            h = net.add_host()
            rt = GossipSubRouter() if i % 2 == 0 else FloodSubRouter()
            nodes.append(PubSub(h, rt, sign_policy=LAX_NO_SIGN))
        net.dense_connect([x.host for x in nodes], degree=10)
        net.scheduler.run_for(0.1)
        subs = [x.join("t").subscribe() for x in nodes]
        net.scheduler.run_for(3.0)
        for i in range(5):
            nodes[i].my_topics["t"].publish(b"m%d" % i)
            net.scheduler.run_for(0.5)
        net.scheduler.run_for(2.0)
        for s in subs:
            assert len(drain(s)) == 5


class TestSigning:
    def _signed_pair(self):
        net = Network()
        nodes = []
        for i in range(2):
            key, pid = generate_keypair(seed=b"node%d" % i)
            h = net.add_host(peer_id=pid)
            nodes.append(PubSub(h, FloodSubRouter(), sign_policy=STRICT_SIGN,
                                sign_key=key))
        net.connect_all([x.host for x in nodes])
        net.scheduler.run_for(0.1)
        return net, nodes

    def test_signed_roundtrip(self):
        net, nodes = self._signed_pair()
        sub = nodes[1].join("t").subscribe()
        nodes[0].join("t").subscribe()
        net.scheduler.run_for(0.5)
        nodes[0].my_topics["t"].publish(b"signed")
        net.scheduler.run_for(0.5)
        msgs = drain(sub)
        assert len(msgs) == 1 and msgs[0].signature is not None

    def test_tampered_message_rejected(self):
        net, nodes = self._signed_pair()
        sub = nodes[1].join("t").subscribe()
        nodes[0].join("t").subscribe()
        net.scheduler.run_for(0.5)
        # craft a tampered message: sign then modify data
        from go_libp2p_pubsub_tpu.core.types import Message, RPC
        msg = Message(data=b"original", topic="t", from_peer=nodes[0].pid,
                      seqno=b"\0" * 8)
        from go_libp2p_pubsub_tpu.api.sign import sign_message
        sign_message(nodes[0].pid, nodes[0].sign_key, msg)
        msg.data = b"tampered"
        nodes[0].host.send(nodes[1].pid, RPC(publish=[msg]))
        net.scheduler.run_for(0.5)
        assert drain(sub) == []

    def test_unsigned_message_rejected_under_strict(self):
        net, nodes = self._signed_pair()
        sub = nodes[1].join("t").subscribe()
        nodes[0].join("t").subscribe()
        net.scheduler.run_for(0.5)
        from go_libp2p_pubsub_tpu.core.types import Message, RPC
        msg = Message(data=b"unsigned", topic="t", from_peer=nodes[0].pid,
                      seqno=b"\1" * 8)
        nodes[0].host.send(nodes[1].pid, RPC(publish=[msg]))
        net.scheduler.run_for(0.5)
        assert drain(sub) == []


class TestValidation:
    def test_rejecting_validator_blocks(self):
        # TestValidate (validation_test.go-style)
        net, nodes = make_net(5, FloodSubRouter, connect="all")
        for x in nodes:
            x.register_topic_validator(
                "t", lambda src, msg: b"bad" not in msg.data)
        subs = [x.join("t").subscribe() for x in nodes]
        net.scheduler.run_for(0.5)
        nodes[0].my_topics["t"].publish(b"good message")
        net.scheduler.run_for(0.5)
        with pytest.raises(ValidationError):
            nodes[1].my_topics["t"].publish(b"bad message")
        net.scheduler.run_for(0.5)
        for s in subs:
            assert [m.data for m in drain(s)] == [b"good message"]

    def test_validator_sees_remote_messages(self):
        net, nodes = make_net(3, FloodSubRouter, connect="all")
        seen = []
        nodes[1].register_topic_validator(
            "t", lambda src, msg: seen.append(msg.data) or True)
        sub = nodes[1].join("t").subscribe()
        nodes[0].join("t").subscribe()
        net.scheduler.run_for(0.5)
        nodes[0].my_topics["t"].publish(b"x")
        net.scheduler.run_for(0.5)
        assert seen == [b"x"]
        assert len(drain(sub)) == 1


class TestRegistry:
    def test_subscription_announcements(self):
        net, nodes = make_net(4, FloodSubRouter, connect="all")
        nodes[0].join("t").subscribe()
        net.scheduler.run_for(0.5)
        for x in nodes[1:]:
            assert nodes[0].pid in x.topics.get("t", set())
        assert nodes[1].list_peers("t") == [nodes[0].pid]

    def test_peer_events(self):
        net, nodes = make_net(3, FloodSubRouter, connect="all")
        t0 = nodes[0].join("t")
        h = t0.event_handler()
        nodes[1].join("t").subscribe()
        net.scheduler.run_for(0.5)
        ev = h.next_peer_event()
        assert ev is not None and ev.type == "join" and ev.peer == nodes[1].pid

    def test_blacklist_drops_messages(self):
        net, nodes = make_net(3, FloodSubRouter, connect="all")
        sub2 = nodes[2].join("t").subscribe()
        nodes[0].join("t").subscribe()
        net.scheduler.run_for(0.5)
        nodes[2].blacklist_peer(nodes[0].pid)
        nodes[0].my_topics["t"].publish(b"nope")
        net.scheduler.run_for(0.5)
        assert drain(sub2) == []

    def test_subscription_filter_blocks_join(self):
        net = Network()
        h = net.add_host()
        ps = PubSub(h, FloodSubRouter(), sign_policy=LAX_NO_SIGN,
                    subscription_filter=AllowlistSubscriptionFilter("ok"))
        ps.join("ok")
        with pytest.raises(ValueError):
            ps.join("denied")

    def test_relay(self):
        # relay pumps messages through an unsubscribed node (topic.go:186-207)
        net, nodes = make_net(3, FloodSubRouter)
        net.connect(nodes[0].host, nodes[1].host)
        net.connect(nodes[1].host, nodes[2].host)
        net.scheduler.run_for(0.1)
        sub2 = nodes[2].join("t").subscribe()
        nodes[1].join("t").relay()
        nodes[0].join("t").subscribe()
        net.scheduler.run_for(0.5)
        nodes[0].my_topics["t"].publish(b"via-relay")
        net.scheduler.run_for(0.5)
        assert [m.data for m in drain(sub2)] == [b"via-relay"]


class TestDeterminism:
    def test_two_runs_identical(self):
        def run():
            net, nodes = make_net(10, GossipSubRouter)
            subs = [x.join("t").subscribe() for x in nodes]
            net.scheduler.run_for(3.0)
            nodes[0].my_topics["t"].publish(b"d")
            net.scheduler.run_for(2.0)
            meshes = tuple(tuple(sorted(x.rt.mesh["t"])) for x in nodes)
            counts = tuple(len(drain(s)) for s in subs)
            return meshes, counts
        assert run() == run()


class TestScoringEndToEnd:
    def _scored_net(self, n=10):
        from go_libp2p_pubsub_tpu.core.params import (
            PeerScoreParams, PeerScoreThresholds, TopicScoreParams)
        net = Network()
        nodes = []
        for i in range(n):
            h = net.add_host()
            sp = PeerScoreParams(
                app_specific_score=lambda p: 0.0,
                decay_interval=1.0, decay_to_zero=0.01,
                topics={"t": TopicScoreParams(
                    topic_weight=1.0, time_in_mesh_quantum=1.0,
                    invalid_message_deliveries_weight=-10.0,
                    invalid_message_deliveries_decay=0.99)})
            th = PeerScoreThresholds(gossip_threshold=-10, publish_threshold=-50,
                                     graylist_threshold=-100)
            rt = GossipSubRouter(score_params=sp, thresholds=th)
            nodes.append(PubSub(h, rt, sign_policy=LAX_NO_SIGN))
        net.connect_all([x.host for x in nodes])
        net.scheduler.run_for(0.1)
        return net, nodes

    def test_invalid_spammer_pruned_and_graylisted(self):
        # TestGossipsubNegativeScore semantics (gossipsub_test.go:1526)
        net, nodes = self._scored_net(8)
        for x in nodes:
            x.register_topic_validator("t", lambda src, msg: b"spam" not in msg.data)
        subs = [x.join("t").subscribe() for x in nodes]
        net.scheduler.run_for(3.0)
        spammer = nodes[0]
        for i in range(10):
            try:
                spammer.my_topics["t"].publish(b"spam %d" % i)
            except ValidationError:
                # local validation blocks; send raw spam directly instead
                from go_libp2p_pubsub_tpu.core.types import Message, RPC
                for peer in list(spammer.peers):
                    spammer.host.send(peer, RPC(publish=[Message(
                        from_peer=spammer.pid, seqno=(1000 + i).to_bytes(8, "big"),
                        data=b"spam %d" % i, topic="t")]))
            net.scheduler.run_for(0.3)
        net.scheduler.run_for(5.0)
        # every honest node now scores the spammer negative and pruned it
        for x in nodes[1:]:
            assert x.rt.score.score(spammer.pid) < 0
            assert spammer.pid not in x.rt.mesh.get("t", set())
        # spam did not reach subscribers
        for s in subs[1:]:
            assert all(b"spam" not in m.data for m in iter(s.next, None))

    def test_graylisted_peer_rpcs_dropped(self):
        net, nodes = self._scored_net(3)
        a, b = nodes[0], nodes[1]
        for x in nodes:
            x.join("t").subscribe()
        net.scheduler.run_for(2.0)
        # push b's score at a below the graylist threshold
        st = a.rt.score.peer_stats[b.pid]
        ts = st.get_topic_stats("t", a.rt.score.params)
        ts.invalid_message_deliveries = 10.0  # -10 * 100 = -1000 < -100
        assert a.rt.accept_from(b.pid).name == "ACCEPT_NONE"


class TestConnManagerIntegration:
    def test_mesh_peers_protected(self):
        net, nodes = make_net(6, GossipSubRouter, connect="all")
        for x in nodes:
            x.join("t").subscribe()
        net.scheduler.run_for(3.0)
        a = nodes[0]
        cm = a.host.conn_manager
        for peer in a.rt.mesh["t"]:
            assert cm.is_protected(peer, "pubsub:t")


class TestReconnects:
    def test_delivery_resumes_after_reconnect(self):
        """floodsub_test.go:234 TestReconnects: kill the connection, watch
        delivery stop, reconnect, watch it resume (dead-peer handling
        pubsub.go:711-757 + notify.go re-adds the peer)."""
        net, nodes = make_net(2, GossipSubRouter, connect="all")
        a, b = nodes
        sub = b.join("t").subscribe()
        a.join("t").subscribe()
        net.scheduler.run_for(1.5)
        a.my_topics["t"].publish(b"one")
        net.scheduler.run_for(0.5)
        assert [m.data for m in drain(sub)] == [b"one"]

        a.host.disconnect(b.pid)
        net.scheduler.run_for(0.5)
        a.my_topics["t"].publish(b"lost")
        net.scheduler.run_for(0.5)
        assert drain(sub) == []            # the link is down

        a.host.connect(b.host)
        net.scheduler.run_for(2.0)         # hello + heartbeat regraft
        a.my_topics["t"].publish(b"back")
        net.scheduler.run_for(1.5)
        datas = [m.data for m in drain(sub)]
        assert b"back" in datas


class TestValidationQueueOverflow:
    def test_queue_overflow_drops_and_traces(self):
        """validation.go:246-260: the front-end queue cap drops messages
        beyond queue_size in one scheduler slot; the tracer records the
        rejections."""
        from go_libp2p_pubsub_tpu.api.validation import Validation
        from go_libp2p_pubsub_tpu.trace import MemoryTracer
        from go_libp2p_pubsub_tpu.trace import events as ev

        net = Network()
        tracer = MemoryTracer()
        ha, hb = net.add_host(), net.add_host()
        a = PubSub(ha, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        b = PubSub(hb, GossipSubRouter(), sign_policy=LAX_NO_SIGN,
                   validation=Validation(queue_size=2, worker_delay=0.05),
                   event_tracer=tracer)
        net.connect(ha, hb)
        net.scheduler.run_for(0.2)
        a.join("t").subscribe()
        sub = b.join("t").subscribe()
        b.register_topic_validator("t", lambda src, msg: 0)
        net.scheduler.run_for(1.5)
        # the burst lands in one scheduler slot, overflowing the 2-deep queue
        for i in range(10):
            a.my_topics["t"].publish(b"m%d" % i)
        net.scheduler.run_for(1.0)
        got = len(drain(sub))
        rejected = [e for e in tracer.events if e.get("type") == "REJECT_MESSAGE"
                    and e["rejectMessage"]["reason"] == ev.REJECT_VALIDATION_QUEUE_FULL]
        assert got < 10
        assert rejected, "queue-full drops must be traced"


class TestValidationThrottled:
    def test_exhausted_async_budget_throttles(self):
        """validation.go:344-356: no async-validation budget left ->
        RejectValidationThrottled; messages are dropped, not delivered."""
        from go_libp2p_pubsub_tpu.api.validation import Validation
        from go_libp2p_pubsub_tpu.trace import MemoryTracer
        from go_libp2p_pubsub_tpu.trace import events as ev

        net = Network()
        tracer = MemoryTracer()
        ha, hb = net.add_host(), net.add_host()
        a = PubSub(ha, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        b = PubSub(hb, GossipSubRouter(), sign_policy=LAX_NO_SIGN,
                   validation=Validation(throttle=0),
                   event_tracer=tracer)
        net.connect(ha, hb)
        net.scheduler.run_for(0.2)
        a.join("t").subscribe()
        sub = b.join("t").subscribe()
        b.register_topic_validator("t", lambda src, msg: 0)
        net.scheduler.run_for(1.5)
        for i in range(5):
            a.my_topics["t"].publish(b"m%d" % i)
        net.scheduler.run_for(1.0)
        assert drain(sub) == []
        throttled = [e for e in tracer.events if e.get("type") == "REJECT_MESSAGE"
                     and e["rejectMessage"]["reason"] == ev.REJECT_VALIDATION_THROTTLED]
        assert len(throttled) == 5


class TestValidatorTimeout:
    def _pair(self, **val_kw):
        from go_libp2p_pubsub_tpu.api.validation import Validation
        from go_libp2p_pubsub_tpu.trace import MemoryTracer

        net = Network()
        tracer = MemoryTracer()
        ha, hb = net.add_host(), net.add_host()
        a = PubSub(ha, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        b = PubSub(hb, GossipSubRouter(), sign_policy=LAX_NO_SIGN,
                   validation=Validation(**val_kw), event_tracer=tracer)
        net.connect(ha, hb)
        net.scheduler.run_for(0.2)
        a.join("t").subscribe()
        sub = b.join("t").subscribe()
        net.scheduler.run_for(1.5)
        return net, a, b, sub, tracer

    def test_deadline_exceeded_is_ignored(self):
        """WithValidatorTimeout (validation.go:564-570): an async validator
        slower than its deadline yields IGNORE — the message is dropped and
        traced as ignored, never delivered."""
        from go_libp2p_pubsub_tpu.trace import events as ev

        net, a, b, sub, tracer = self._pair()

        def slow_accept(src, msg):
            return 0                                  # would accept
        slow_accept.virtual_duration = 2.0            # ... in 2 virtual secs

        b.register_topic_validator("t", slow_accept, timeout=0.5)
        a.my_topics["t"].publish(b"late")
        net.scheduler.run_for(5.0)
        assert drain(sub) == []
        ignored = [e for e in tracer.events if e.get("type") == "REJECT_MESSAGE"
                   and e["rejectMessage"]["reason"] == ev.REJECT_VALIDATION_IGNORED]
        assert ignored, "deadline-exceeded validation must trace as ignored"

    def test_slow_but_within_deadline_delivers_late(self):
        """A validator inside its deadline delivers — after its virtual
        duration elapses, not before (the throttle slot is held meanwhile)."""
        net, a, b, sub, tracer = self._pair()

        def slow_accept(src, msg):
            return 0
        slow_accept.virtual_duration = 1.0

        b.register_topic_validator("t", slow_accept, timeout=5.0)
        a.my_topics["t"].publish(b"ok")
        net.scheduler.run_for(0.5)                    # mid-validation
        assert drain(sub) == []
        net.scheduler.run_for(2.0)                    # past the duration
        got = drain(sub)
        assert [m.data for m in got] == [b"ok"]

    def test_no_timeout_unaffected(self):
        """timeout=0 (the default) leaves slow validators un-deadlined."""
        net, a, b, sub, tracer = self._pair()

        def slow_accept(src, msg):
            return 0
        slow_accept.virtual_duration = 3.0

        b.register_topic_validator("t", slow_accept)
        a.my_topics["t"].publish(b"eventually")
        net.scheduler.run_for(5.0)
        assert [m.data for m in drain(sub)] == [b"eventually"]

    def test_concurrent_validators_latency_is_max(self):
        """validation.go:410-456 runs async validators in parallel
        goroutines: total latency is max(durations), not the sum."""
        net, a, b, sub, tracer = self._pair()

        def v1(src, msg):
            return 0
        v1.virtual_duration = 1.0

        def v2(src, msg):
            return 0
        v2.virtual_duration = 2.0

        b.val.add_default_validator(v1)
        b.register_topic_validator("t", v2)
        a.my_topics["t"].publish(b"x")
        net.scheduler.run_for(2.5)                   # > max(1,2), < 1+2
        assert [m.data for m in drain(sub)] == [b"x"]

    def test_raising_validator_releases_throttle_slots(self):
        """A validator that raises must not leak its throttle slots — the
        old finally-based accounting guaranteed this and so must the
        deferred-verdict path."""
        from go_libp2p_pubsub_tpu.api.validation import Validation
        from go_libp2p_pubsub_tpu.core.types import Message

        val = Validation()

        class P:                                     # minimal PubSub stand-in
            class tracer:
                reject_message = staticmethod(lambda *a: None)
                throttle_peer = staticmethod(lambda *a: None)
        val.p = P()

        def boom(src, msg):
            raise RuntimeError("validator bug")

        val.add_validator("t", boom)
        v = val.topic_vals["t"]
        val.throttled += 1                           # caller-side acquire
        with pytest.raises(RuntimeError):
            val._do_validate_topic([v], "peer", Message(topic="t"), 0)
        assert v.inflight == 0
        assert val.throttled == 0


class TestPeerScoreInspect:
    def test_simple_and_extended_snapshots(self):
        """WithPeerScoreInspect both variants (score.go:127-180): the simple
        fn sees {peer: score}; the extended fn sees PeerScoreSnapshots with
        per-topic counters — mirroring TestPeerScoreInspect-style checks."""
        from go_libp2p_pubsub_tpu.core.params import (
            PeerScoreParams, PeerScoreThresholds, TopicScoreParams)

        net = Network()
        nodes = []
        for i in range(4):
            h = net.add_host()
            sp = PeerScoreParams(
                app_specific_score=lambda p: 7.0,
                app_specific_weight=1.0,
                decay_interval=1.0, decay_to_zero=0.01,
                topics={"t": TopicScoreParams(
                    topic_weight=1.0, time_in_mesh_quantum=1.0,
                    first_message_deliveries_weight=1.0,
                    first_message_deliveries_decay=0.9,
                    first_message_deliveries_cap=100.0)})
            rt = GossipSubRouter(score_params=sp,
                                 thresholds=PeerScoreThresholds())
            nodes.append(PubSub(h, rt, sign_policy=LAX_NO_SIGN))
        simple_dumps, ex_dumps = [], []
        nodes[0].rt.with_peer_score_inspect(simple_dumps.append, 1.0)
        nodes[1].rt.with_peer_score_inspect(ex_dumps.append, 1.0,
                                            extended=True)
        net.connect_all([x.host for x in nodes])
        net.scheduler.run_for(0.2)
        subs = [x.join("t").subscribe() for x in nodes]
        net.scheduler.run_for(2.0)
        for i in range(5):
            nodes[2].my_topics["t"].publish(b"m%d" % i)
            net.scheduler.run_for(0.5)
        net.scheduler.run_for(2.0)

        assert simple_dumps and ex_dumps
        scores = simple_dumps[-1]
        assert set(scores) == {x.pid for x in nodes[1:]}
        snaps = ex_dumps[-1]
        assert set(snaps) == {x.pid for x in nodes if x is not nodes[1]}
        snap = snaps[nodes[2].pid]                    # the publisher
        # raw components are dumped unweighted (score.go:480-494)
        assert snap.app_specific_score == 7.0
        assert snap.behaviour_penalty == 0.0
        ts = snap.topics["t"]
        assert ts.first_message_deliveries > 0        # it delivered firsts
        assert ts.time_in_mesh > 0                    # and sits in the mesh
        # the reported total equals the live score fn
        assert snap.score == pytest.approx(
            nodes[1].rt.score.score(nodes[2].pid))

    def test_inspect_requires_scoring_and_uniqueness(self):
        rt = GossipSubRouter()
        with pytest.raises(ValueError, match="not enabled"):
            rt.with_peer_score_inspect(lambda d: None, 1.0)
        from go_libp2p_pubsub_tpu.core.params import PeerScoreParams
        rt2 = GossipSubRouter(score_params=PeerScoreParams(
            app_specific_score=lambda p: 0.0, decay_interval=1.0))
        rt2.with_peer_score_inspect(lambda d: None, 1.0)
        with pytest.raises(ValueError, match="duplicate"):
            rt2.with_peer_score_inspect(lambda d: None, 1.0, extended=True)


class TestRpcInspector:
    def test_inspector_gates_all_rpcs(self):
        """WithAppSpecificRpcInspector (pubsub.go:1031-1037): a False verdict
        drops the whole RPC before any processing."""
        net = Network()
        ha, hb = net.add_host(), net.add_host()
        a = PubSub(ha, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        seen = []
        def inspector(src, rpc):
            seen.append(src)
            return False                      # drop everything
        b = PubSub(hb, GossipSubRouter(), sign_policy=LAX_NO_SIGN,
                   rpc_inspector=inspector)
        net.connect(ha, hb)
        net.scheduler.run_for(0.2)
        a.join("t").subscribe()
        sub = b.join("t").subscribe()
        net.scheduler.run_for(1.5)
        a.my_topics["t"].publish(b"x")
        net.scheduler.run_for(1.0)
        assert seen, "inspector must have been consulted"
        assert drain(sub) == []               # everything dropped
        # b never even learned a's subscription (announcements inspected too)
        assert a.pid not in b.topics.get("t", set())

    def test_inspector_true_passes(self):
        net = Network()
        ha, hb = net.add_host(), net.add_host()
        a = PubSub(ha, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        b = PubSub(hb, GossipSubRouter(), sign_policy=LAX_NO_SIGN,
                   rpc_inspector=lambda src, rpc: True)
        net.connect(ha, hb)
        net.scheduler.run_for(0.2)
        a.join("t").subscribe()
        sub = b.join("t").subscribe()
        net.scheduler.run_for(1.5)
        a.my_topics["t"].publish(b"x")
        net.scheduler.run_for(1.0)
        assert [m.data for m in drain(sub)] == [b"x"]


class TestRelayRefcounting:
    def test_relay_cancel_releases(self):
        """topic.go:186-207: relays hold the topic joined; the last cancel
        releases it (router Leave fires)."""
        net, nodes = make_net(3, GossipSubRouter, connect="all")
        a, r, b = nodes
        a.join("t").subscribe()
        sub = b.join("t").subscribe()
        cancel1 = r.join("t").relay()
        cancel2 = r.my_topics["t"].relay()
        net.scheduler.run_for(1.5)
        assert "t" in r.rt.mesh              # relay keeps the router joined
        a.my_topics["t"].publish(b"via-relay")
        net.scheduler.run_for(1.0)
        assert b"via-relay" in [m.data for m in drain(sub)]
        cancel1()
        net.scheduler.run_for(0.2)
        assert "t" in r.rt.mesh              # one relay still holds it
        cancel2()
        net.scheduler.run_for(0.2)
        assert "t" not in r.rt.mesh          # last cancel leaves the topic


class TestRandomsubMixed:
    def test_mixed_floodsub_randomsub_delivers(self):
        """TestRandomsubMixed: floodsub and randomsub nodes interoperate on
        the same topic."""
        net = Network()
        nodes = []
        for i in range(16):
            h = net.add_host()
            rt = RandomSubRouter(16) if i % 2 == 0 else FloodSubRouter()
            nodes.append(PubSub(h, rt, sign_policy=LAX_NO_SIGN))
        net.dense_connect([x.host for x in nodes], degree=10)
        net.scheduler.run_for(0.1)
        subs = [x.join("t").subscribe() for x in nodes]
        net.scheduler.run_for(0.5)
        for i in range(4):
            nodes[i].my_topics["t"].publish(b"m%d" % i)
            net.scheduler.run_for(0.5)
        counts = [len(drain(s)) for s in subs]
        assert min(counts) >= 3            # randomsub is probabilistic


class TestAssortedOptions:
    def test_many_options_compose(self):
        """TestPubsubWithAssortedOptions-style smoke: several orthogonal
        options wired at once still route."""
        from go_libp2p_pubsub_tpu.utils.blacklist import MapBlacklist
        from go_libp2p_pubsub_tpu.utils.timecache import Strategy
        net = Network()
        nodes = []
        for i in range(2):
            h = net.add_host()
            nodes.append(PubSub(
                h, GossipSubRouter(), sign_policy=LAX_NO_SIGN,
                msg_id_fn=lambda m: (m.from_peer or "") + "|"
                + (m.seqno or b"").hex(),
                blacklist=MapBlacklist(),
                seen_ttl=60.0, seen_strategy=Strategy.LAST_SEEN,
                max_message_size=1 << 16,
                rpc_inspector=lambda peer, rpc: True,
                peer_filter=lambda pid, topic: True))
        net.connect_all([x.host for x in nodes])
        a, b = nodes
        sub = b.join("t").subscribe()
        a.join("t").subscribe()
        net.scheduler.run_for(1.5)
        a.my_topics["t"].publish(b"opts")
        net.scheduler.run_for(1.0)
        assert [m.data for m in drain(sub)] == [b"opts"]


class TestSubscriptionMultiplicity:
    def test_subscribe_multiple_times_both_delivered(self):
        """TestSubscribeMultipleTimes (pubsub_test.go): two subscriptions on
        one topic each receive every message."""
        net, nodes = make_net(2, GossipSubRouter, connect="all")
        a, b = nodes
        ta = a.join("t")
        s1, s2 = ta.subscribe(), ta.subscribe()
        b.join("t").subscribe()
        net.scheduler.run_for(1.5)
        b.my_topics["t"].publish(b"m")
        net.scheduler.run_for(1.0)
        assert [m.data for m in drain(s1)] == [b"m"]
        assert [m.data for m in drain(s2)] == [b"m"]

    def test_topic_reporting(self):
        """TestPeerTopicReporting/TestSubReporting semantics: GetTopics and
        ListPeers reflect live subscription state."""
        net, nodes = make_net(3, GossipSubRouter, connect="all")
        a, b, c = nodes
        sa = a.join("x").subscribe()
        b.join("x").subscribe()
        b.join("y").subscribe()
        c.join("y").subscribe()
        net.scheduler.run_for(1.0)
        assert a.get_topics() == ["x"]
        assert sorted(b.get_topics()) == ["x", "y"]
        assert set(a.list_peers("x")) == {b.pid}
        assert set(c.list_peers("y")) == {b.pid}
        sa.cancel()
        net.scheduler.run_for(1.0)
        assert a.get_topics() == []
        assert a.pid not in set(b.list_peers("x"))


class TestInvalidAuthor:
    def test_forged_author_rejected(self):
        """TestWithInvalidMessageAuthor semantics: a signed message whose
        author does not match the signing key is rejected at validation."""
        from go_libp2p_pubsub_tpu.api import STRICT_SIGN, generate_keypair
        net = Network()
        key_a, pid_a = generate_keypair(seed=b"real-author")
        key_f, pid_f = generate_keypair(seed=b"forger")
        a = PubSub(net.add_host(peer_id=pid_a), GossipSubRouter(),
                   sign_policy=STRICT_SIGN, sign_key=key_a)
        b = PubSub(net.add_host(peer_id=pid_f), GossipSubRouter(),
                   sign_policy=STRICT_SIGN, sign_key=key_f)
        net.connect(a.host, b.host)
        sub = b.join("t").subscribe()
        ta = a.join("t")
        ta.subscribe()
        net.scheduler.run_for(1.5)
        # forge: sign with the forger's key but claim the real author's id
        with pytest.raises(ValidationError):
            ta.publish(b"forged", custom_key=(pid_a, key_f))
        net.scheduler.run_for(1.0)
        assert drain(sub) == []


class TestFloodsubPluggableProtocol:
    def test_custom_protocol_interops(self):
        """TestFloodSubPluggableProtocol (floodsub_test.go): floodsub nodes
        on a custom protocol id route among themselves; a default-protocol
        node cannot join them."""
        custom = "/myfloodsub/0.1.0"
        net = Network()
        nodes = [PubSub(net.add_host(),
                        FloodSubRouter(protocols=[custom]),
                        sign_policy=LAX_NO_SIGN) for _ in range(3)]
        net.connect_all([n.host for n in nodes])
        subs = [n.join("t").subscribe() for n in nodes]
        net.scheduler.run_for(0.5)
        nodes[0].my_topics["t"].publish(b"m")
        net.scheduler.run_for(0.5)
        for s in subs:
            assert [m.data for m in drain(s)] == [b"m"]
        vanilla = PubSub(net.add_host(), FloodSubRouter(),
                         sign_policy=LAX_NO_SIGN)
        assert not vanilla.host.connect(nodes[0].host)


class TestBlacklistLifecycle:
    def test_blacklist_after_subscribe_blocks_messages(self):
        """TestBlacklist2 (blacklist_test.go:65): blacklisting an already
        connected, announced peer stops its messages."""
        net, nodes = make_net(2, GossipSubRouter, connect="all")
        a, b = nodes
        a.join("t").subscribe()
        sub = b.join("t").subscribe()
        net.scheduler.run_for(1.5)
        b.blacklist_peer(a.pid)
        net.scheduler.run_for(0.2)
        a.my_topics["t"].publish(b"m")
        net.scheduler.run_for(1.0)
        assert drain(sub) == []

    def test_blacklist_before_connect_blocks_announcements(self):
        """TestBlacklist3 (blacklist_test.go:98): a peer blacklisted before
        connecting never registers as a topic peer and delivers nothing."""
        net = Network()
        a = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        b = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        b.blacklist_peer(a.pid)
        net.connect(a.host, b.host)
        a.join("t").subscribe()
        sub = b.join("t").subscribe()
        net.scheduler.run_for(1.5)
        assert a.pid not in b.topics.get("t", set())
        a.my_topics["t"].publish(b"m")
        net.scheduler.run_for(1.0)
        assert drain(sub) == []


class TestTopicEventHandlerCancel:
    def test_cancelled_handler_stops_receiving(self):
        """TestTopicEventHandlerCancel (topic_test.go): after Cancel, peer
        join events no longer reach the handler."""
        net = Network()
        a = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        ta = a.join("t")
        ta.subscribe()
        h = ta.event_handler()
        h.cancel()
        h.cancel()                              # idempotent
        b = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        b.join("t").subscribe()
        net.connect(a.host, b.host)
        net.scheduler.run_for(1.0)
        assert h.next_peer_event() is None
        # a live handler on the same topic still sees the join
        h2 = ta.event_handler()
        ev = h2.next_peer_event()
        assert ev is not None and ev.type == "join" and ev.peer == b.pid


class TestAnnounceRetry:
    def test_dropped_announce_retried_with_jitter(self):
        """pubsub.go:917-969: an announcement dropped on a full peer queue
        is retried after 1..1000ms, re-checking the subscription holds."""
        net, nodes = make_net(2, GossipSubRouter, connect="all")
        a, b = nodes
        a.host.outbound_queue_size = 0          # every send drops
        a.join("t").subscribe()
        net.scheduler.run_for(0.01)
        assert a.pid not in b.topics.get("t", set())
        a.host.outbound_queue_size = 32         # queue drains; retry lands
        net.scheduler.run_for(1.5)
        assert a.pid in b.topics.get("t", set())

    def test_retry_skipped_after_unsubscribe(self):
        net, nodes = make_net(2, GossipSubRouter, connect="all")
        a, b = nodes
        a.host.outbound_queue_size = 0
        sub = a.join("t").subscribe()
        net.scheduler.run_for(0.01)
        sub.cancel()                            # unsubscribe before retry
        a.host.outbound_queue_size = 32
        net.scheduler.run_for(1.5)
        # the subscribe retry noticed the cancel; only the unsubscribe
        # state (possibly also dropped+retried) may have announced
        assert a.pid not in b.topics.get("t", set())


class TestTopicMsgIdFn:
    def test_per_topic_id_function_drives_dedup(self):
        """WithTopicMessageIdFn (pubsub.go:1219-1224): two distinct
        publishes whose custom id collides dedup to one delivery."""
        net, nodes = make_net(2, GossipSubRouter, connect="all")
        a, b = nodes
        ta = a.join("t", msg_id_fn=lambda m: "constant-id")
        tb = b.join("t", msg_id_fn=lambda m: "constant-id")
        sub = tb.subscribe()
        ta.subscribe()
        net.scheduler.run_for(1.5)
        ta.publish(b"one")
        ta.publish(b"two")                      # same custom id: seen-cached
        net.scheduler.run_for(1.0)
        assert [m.data for m in drain(sub)] == [b"one"]

    def test_msg_id_fn_on_already_joined_topic_rejected(self):
        net, nodes = make_net(1, GossipSubRouter)
        nodes[0].join("t")
        with pytest.raises(ValueError):
            nodes[0].join("t", msg_id_fn=lambda m: "x")


class TestTreeTopology:
    def test_multihop_delivery_along_tree(self):
        """TestGossipsubTreeTopology semantics: a message published at a
        leaf crosses multiple hops to every other node."""
        net = Network()
        nodes = [PubSub(net.add_host(), GossipSubRouter(),
                        sign_policy=LAX_NO_SIGN) for _ in range(10)]
        hosts = [n.host for n in nodes]
        # binary-ish tree: i connects to (i-1)//2
        for i in range(1, 10):
            net.connect(hosts[i], hosts[(i - 1) // 2])
        subs = [n.join("t").subscribe() for n in nodes]
        net.scheduler.run_for(3.0)
        nodes[9].my_topics["t"].publish(b"leaf")
        net.scheduler.run_for(3.0)
        for i, s in enumerate(subs):
            assert [m.data for m in drain(s)] == [b"leaf"], f"node {i}"


class TestPreconnectedNodes:
    def test_pubsub_attaches_to_existing_connections(self):
        """pubsub.go:336: PubSub constructed AFTER the host connected still
        sweeps existing connections and routes."""
        net = Network()
        ha, hb = net.add_host(), net.add_host()
        a = PubSub(ha, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        # connect while b has no PubSub yet; empty supported list accepts
        net.connect(ha, hb)
        b = PubSub(hb, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        sub = b.join("t").subscribe()
        a.join("t").subscribe()
        net.scheduler.run_for(2.0)
        a.my_topics["t"].publish(b"pre")
        net.scheduler.run_for(1.0)
        assert [m.data for m in drain(sub)] == [b"pre"]


class TestPublishReadiness:
    def test_publish_defers_until_peers_arrive(self):
        """WithReadiness (topic.go:270-309): routing waits for RouterReady;
        the message goes out once the topic has enough peers."""
        net = Network()
        ha = net.add_host()
        a = PubSub(ha, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        ta = a.join("t")
        ta.subscribe()
        # publish into an empty network with a min-1-peer readiness gate
        ta.publish(b"wait-for-you", ready=ta.ready_min_peers(1))
        net.scheduler.run_for(2.0)

        hb = net.add_host()
        b = PubSub(hb, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        sub = b.join("t").subscribe()
        net.connect(ha, hb)
        net.scheduler.run_for(3.0)           # hello + graft + deferred publish
        assert [m.data for m in drain(sub)] == [b"wait-for-you"]

    def test_ready_publish_routes_immediately(self):
        net, nodes = make_net(2, GossipSubRouter, connect="all")
        a, b = nodes
        ta = a.join("t")
        ta.subscribe()
        sub = b.join("t").subscribe()
        net.scheduler.run_for(1.5)
        ta.publish(b"now", ready=ta.ready_min_peers(1))
        net.scheduler.run_for(0.5)
        assert [m.data for m in drain(sub)] == [b"now"]

    def test_publishes_queue_behind_pending_gate_in_order(self):
        """Later publishes on the topic wait behind a gated one so seqno
        order is preserved for seqno-based replay validators."""
        net = Network()
        ha = net.add_host()
        a = PubSub(ha, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        ta = a.join("t")
        ta.subscribe()
        ta.publish(b"first", ready=ta.ready_min_peers(1))
        ta.publish(b"second")            # queues behind the gated publish
        net.scheduler.run_for(1.0)
        hb = net.add_host()
        b = PubSub(hb, GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        sub = b.join("t").subscribe()
        net.connect(ha, hb)
        net.scheduler.run_for(3.0)
        assert [m.data for m in drain(sub)] == [b"first", b"second"]

    def test_local_only_bypasses_pending_gate(self):
        """local_only never touches the wire (pubsub.go `msg.local`), so it
        must not queue behind a gated publish — it delivers immediately."""
        net = Network()
        a = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        ta = a.join("t")
        sub = ta.subscribe()
        net.scheduler.run_for(0.1)
        ta.publish(b"gated", ready=lambda: False)   # never opens
        ta.publish(b"local", local_only=True)
        net.scheduler.run_for(0.5)
        assert [m.data for m in drain(sub)] == [b"local"]

    def test_reentrant_publish_single_drain_chain(self):
        """A publish issued from a subscriber's on_message handler WHILE the
        drain is delivering (push_local is synchronous) must not start a
        second poll chain, duplicate, or reorder the queue."""
        net = Network()
        a = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        ta = a.join("t")
        sub = ta.subscribe()
        got = []

        def handler(msg):
            got.append(msg.data)
            if msg.data == b"one":
                # fires mid-drain, with "two" still queued behind us
                ta.publish(b"reentrant", ready=lambda: True, ready_poll=0.1)

        sub.on_message = handler
        opened = [False]
        ta.publish(b"one", ready=lambda: opened[0], ready_poll=0.1)
        ta.publish(b"two")
        net.scheduler.run_for(0.5)
        assert got == []                    # gate closed: nothing delivered
        opened[0] = True
        net.scheduler.run_for(1.0)
        assert got == [b"one", b"two", b"reentrant"]
        assert not ta._pending_pubs and not ta._drain_scheduled

    def test_raising_subscriber_does_not_wedge_drain(self):
        """An exception escaping a subscriber handler mid-drain must not
        leave the chain latched: the rest of the queue still routes."""
        net = Network()
        a = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        ta = a.join("t")
        sub = ta.subscribe()
        got = []

        def handler(msg):
            got.append(msg.data)
            if msg.data == b"boom":
                raise TypeError("subscriber bug")

        sub.on_message = handler
        opened = [False]
        ta.publish(b"boom", ready=lambda: opened[0], ready_poll=0.1)
        ta.publish(b"after")
        opened[0] = True
        with pytest.raises(TypeError):
            net.scheduler.run_for(1.0)
        net.scheduler.run_for(1.0)          # chain rescheduled, not wedged
        assert got == [b"boom", b"after"]
        assert not ta._pending_pubs and not ta._drain_scheduled

    def test_cancel_pending_publishes_unblocks_close(self):
        net = Network()
        a = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        ta = a.join("t")
        ta.publish(b"x", ready=lambda: False)
        with pytest.raises(RuntimeError):
            ta.close()
        assert ta.cancel_pending_publishes() == 1
        net.scheduler.run_for(1.0)          # poll chain notices empty queue
        ta.close()

    def test_close_refuses_with_pending_publish(self):
        import pytest
        net = Network()
        a = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        ta = a.join("t")
        ta.publish(b"x", ready=lambda: False)
        with pytest.raises(RuntimeError):
            ta.close()

    def test_zero_poll_rejected(self):
        import pytest
        net = Network()
        a = PubSub(net.add_host(), GossipSubRouter(), sign_policy=LAX_NO_SIGN)
        ta = a.join("t")
        with pytest.raises(ValueError):
            ta.publish(b"x", ready=lambda: False, ready_poll=0.0)
