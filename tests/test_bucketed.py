"""Degree-bucketed edge planes (sim/bucketed, ISSUE 15).

The contract, in order of importance:

- **Bit-exact parity**: under ``bucketed_rng="dense"`` a bucketed run on
  a heavy-tailed graph reproduces the dense engine field for field —
  EVERY SimState plane, not just deliveries — with scoring, gater,
  churn, link faults, and a hub-targeted eclipse all on, under both key
  schedules. The bucketed fork is an execution layout, not a model
  variant.
- **ΣD pricing**: the resting state prices by Σ n_b·k_b instead of
  N·D_max, stays within 2× of a uniform-degree underlay carrying the
  same ΣD even when D_max/D_mean ≥ 16, and the closed-form
  ``powerlaw_1m`` config fits a 16 GiB budget on an 8-way mesh.
- **ΣD execution** (the HLO budget guard): the lowered bucketed step
  contains NO gather sized by N·D_max — per-edge work really runs at
  bucket width. Checked against a positive control (the dense scalar
  step at the same shape MUST trip the same grep).
- **Refusal by name**: configs the fork does not carry raise from
  ``check_bucketable`` instead of silently diverging.
"""

import dataclasses
import functools
import re

import jax
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import (SimConfig, init_state, scenarios,
                                      topology)
from go_libp2p_pubsub_tpu.sim import bucketed as bk
from go_libp2p_pubsub_tpu.sim.engine import run
from go_libp2p_pubsub_tpu.sim.faults import EclipseWindow, FaultPlan
from go_libp2p_pubsub_tpu.sim.invariants import VIOLATION_MASK
from go_libp2p_pubsub_tpu.sim.state import (check_hbm_budget, decode_state,
                                            state_nbytes)

N, T_TICKS = 128, 8
BUCKETS = topology.powerlaw_buckets(N, d_min=4, d_max=16, alpha=2.0,
                                    round_to=4)
K = BUCKETS[0][1]


def _cfg_kw():
    """Everything on at once: scoring, gater, churn, drop/dup faults,
    and an eclipse aimed at the hub bucket (the LOW ids)."""
    plan = FaultPlan(link_drop_prob=0.02, link_dup_prob=0.02,
                     eclipses=(EclipseWindow(2, 6, fraction=0.15),), seed=5)
    return dict(n_peers=N, k_slots=K, n_topics=2, msg_window=8,
                publishers_per_tick=2, prop_substeps=4,
                scoring_enabled=True, gater_enabled=True,
                churn_disconnect_prob=0.05, churn_reconnect_prob=0.2,
                state_precision="f32", fault_plan=plan,
                invariant_mode="record")


@functools.lru_cache(maxsize=None)
def _graph():
    topo = topology.powerlaw(N, K, d_min=4, d_max=16, alpha=2.0, seed=11)
    mal = np.arange(N) >= 112
    return topo, mal


@functools.lru_cache(maxsize=None)
def _dense_traj(key_schedule: str):
    cfg = SimConfig(**_cfg_kw(), key_schedule=key_schedule)
    topo, mal = _graph()
    st = init_state(cfg, topo, malicious=mal)
    out = run(st, cfg, scenarios.default_topic_params(2),
              jax.random.PRNGKey(42), T_TICKS)
    return decode_state(jax.block_until_ready(out), cfg)


def _bucketed_traj(key_schedule: str, bucketed_rng: str):
    cfg = SimConfig(**_cfg_kw(), key_schedule=key_schedule,
                    degree_buckets=BUCKETS, bucketed_rng=bucketed_rng)
    topo, mal = _graph()
    bs = bk.init_bucketed_state(cfg, topo, malicious=mal)
    out = bk.bucketed_run(bs, cfg, scenarios.default_topic_params(2),
                          jax.random.PRNGKey(42), T_TICKS)
    return bk.densify_state(
        bk.decode_bucketed(jax.block_until_ready(out), cfg), cfg)


def _assert_all_fields_equal(dense, densified):
    bad = []
    for f in dense._fields:
        a, b = getattr(dense, f), getattr(densified, f)
        if a is None and b is None:
            continue
        an, bn = np.asarray(a), np.asarray(b)
        if an.shape != bn.shape or not np.array_equal(an, bn):
            eq = float(np.mean(an == bn)) if an.shape == bn.shape else -1.0
            bad.append(f"{f} (shapes {an.shape} vs {bn.shape}, "
                       f"eq_frac={eq:.4f})")
    assert not bad, f"bucketed diverged from dense on: {bad}"


class TestParity:
    # both key schedules are pinned bit-exact; one rides tier-1, the
    # other the slow tier (the tier-1 wall budget is the binding
    # constraint — same discipline as the fault tiers since PR 13)
    @pytest.mark.parametrize("key_schedule", [
        pytest.param("host", marks=pytest.mark.slow),
        "fold_in",
    ])
    def test_bit_exact_vs_dense(self, key_schedule):
        """All SimState fields — deliveries, scores, gater verdicts,
        churn outcomes, fault flags — bit-exact over the trajectory."""
        dense = _dense_traj(key_schedule)
        _assert_all_fields_equal(dense, _bucketed_traj(key_schedule,
                                                       "dense"))
        flags = int(np.asarray(dense.fault_flags))
        assert flags & 0x80, "eclipse window never fired — test is vacuous"
        assert int(np.asarray(dense.delivered_total)) > 0

    def test_bucket_rng_runs_clean(self):
        """The ΣD-cost RNG mode is NOT bit-exact by design, but it must
        run the same program violation-free and actually deliver."""
        out = _bucketed_traj("host", "bucket")
        assert int(np.asarray(out.fault_flags)) & VIOLATION_MASK == 0
        assert int(np.asarray(out.delivered_total)) > 0

    def test_bucketize_densify_roundtrip(self):
        cfg = SimConfig(**_cfg_kw(), degree_buckets=BUCKETS,
                        bucketed_rng="dense")
        topo, mal = _graph()
        dense = decode_state(init_state(cfg, topo, malicious=mal), cfg)
        back = bk.densify_state(bk.bucketize_state(dense, cfg), cfg)
        _assert_all_fields_equal(dense, back)


class TestPricing:
    def test_heavy_tail_prices_under_two_x_uniform(self):
        """Fixed ΣD, D_max/D_mean ≥ 16: the bucketed layout must stay
        within 2× of a uniform-degree underlay carrying the same edge
        count, where the dense N·D_max padding blows up ~30×."""
        n = 65_536
        buckets = ((64, 512), (n - 64, 16))
        sum_d = sum(nb * kb for nb, kb in buckets)
        assert buckets[0][1] >= 16 * (sum_d / n)      # the regime claimed
        # f32: the compact slot8 codec caps k_slots at 127, and this
        # test wants an honest 512-wide hub bucket
        kw = dict(n_peers=n, n_topics=2, msg_window=64,
                  scoring_enabled=True, state_precision="f32")
        bucketed = state_nbytes(SimConfig(**kw, k_slots=512,
                                          degree_buckets=buckets))
        uniform = state_nbytes(SimConfig(**kw, k_slots=-(-sum_d // n)))
        dense_pad = state_nbytes(SimConfig(**kw, k_slots=512))
        assert bucketed["total"] <= 2 * uniform["total"], \
            (bucketed["total"], uniform["total"])
        assert dense_pad["total"] > 8 * bucketed["total"]
        assert bucketed["fields"]["bucket_rev"] == sum_d * 4

    def test_powerlaw_1m_fits_16gib_on_8_shards(self):
        """The acceptance gate bench_powerlaw runs under: the closed-form
        1M-peer config prices within GRAFT_HBM_BUDGET=16GiB per shard on
        an 8-way mesh (no topology build needed — pricing is static)."""
        cfg = scenarios.powerlaw_cfg(1_048_576)
        acct = check_hbm_budget(cfg, 8, budget=16 * 2 ** 30,
                                what="powerlaw_1m")
        assert acct["per_shard"] <= 16 * 2 ** 30
        dense = state_nbytes(dataclasses.replace(cfg, degree_buckets=None))
        assert acct["total"] < 0.6 * dense["total"]

    def test_budget_refusal_names_bucketed_planes(self):
        cfg = scenarios.powerlaw_cfg(131_072)
        with pytest.raises(ValueError, match="GRAFT_HBM_BUDGET"):
            check_hbm_budget(cfg, 1, budget=1 << 20, what="powerlaw_100k")


class TestRefusals:
    def _base(self, **over):
        kw = dict(n_peers=N, k_slots=K, n_topics=2, msg_window=8,
                  degree_buckets=BUCKETS)
        kw.update(over)
        return SimConfig(**kw)

    def test_valid_config_passes(self):
        bk.check_bucketable(self._base())

    @pytest.mark.parametrize("over,msg", [
        (dict(degree_buckets=None), "degree_buckets"),
        (dict(degree_buckets=((64, 16), (32, 8))), "tile the id space"),
        (dict(degree_buckets=((64, 8), (64, 16)), k_slots=8),
         "non-increasing"),
        (dict(k_slots=32), "widest bucket"),
        (dict(bucketed_rng="xla"), "bucketed_rng"),
        (dict(flood_publish=True), "flood_publish"),
        (dict(validation_queue_cap=4), "validation_queue_cap"),
        (dict(sub_leave_prob=0.01), "subscription churn"),
        (dict(hop_mode="pallas"), "dense-only"),
        (dict(n_topics=17), "2\\*n_topics"),
    ])
    def test_refused_by_name(self, over, msg):
        with pytest.raises(ValueError, match=msg):
            bk.check_bucketable(self._base(**over))


def _gathers_at_least(text: str, floor: int) -> list:
    """(result_elems, snippet) of every StableHLO gather whose result
    carries ``floor`` or more elements (test_hlo_gatherfree idiom)."""
    out = []
    for m in re.finditer(
            r'"?stablehlo\.gather"?.*?-> tensor<([0-9x]+)x?[a-z]', text):
        dims = [int(d) for d in m.group(1).split("x") if d]
        elems = int(np.prod(dims)) if dims else 1
        if elems >= floor:
            out.append((elems, m.group(0)[:160]))
    return out


class TestHLOBudget:
    """The CI budget guard from the issue: at N=4096, K=64 the bucketed
    step must lower with ZERO gathers sized by the dense N·D_max plane —
    the structural witness that per-edge cost follows ΣD."""
    N_HLO, K_HLO, M_HLO = 4096, 64, 32

    def _bucketed_text(self):
        n, k = self.N_HLO, self.K_HLO
        buckets = topology.powerlaw_buckets(n, d_min=8, d_max=64)
        assert buckets[0][1] == k
        cfg = SimConfig(n_peers=n, k_slots=k, n_topics=1,
                        msg_window=self.M_HLO, publishers_per_tick=4,
                        prop_substeps=4, scoring_enabled=True,
                        degree_buckets=buckets, bucketed_rng="bucket")
        topo = topology.powerlaw(n, k, d_min=8, d_max=64, seed=1)
        bs = bk.init_bucketed_state(cfg, topo)
        tp = scenarios.default_topic_params(1)
        return jax.jit(bk.bucketed_step, static_argnames=("cfg",)).lower(
            bs, cfg, tp, jax.random.PRNGKey(0)).as_text()

    def test_no_dense_sized_gather_in_bucketed_step(self):
        floor = self.N_HLO * self.K_HLO
        bad = _gathers_at_least(self._bucketed_text(), floor)
        assert not bad, \
            f"N*D_max-sized gathers in the bucketed step: {bad[:5]}"

    def test_dense_scalar_control_trips_the_grep(self):
        """Positive control: the dense scalar step at the SAME shape must
        contain an N·K-sized gather, or the grep is matching nothing."""
        n, k = self.N_HLO, self.K_HLO
        cfg = SimConfig(n_peers=n, k_slots=k, n_topics=1,
                        msg_window=self.M_HLO, publishers_per_tick=4,
                        prop_substeps=4, scoring_enabled=True,
                        edge_gather_mode="scalar")
        from go_libp2p_pubsub_tpu.sim.engine import step
        st = init_state(cfg, topology.sparse(n, k, degree=12, seed=1))
        text = jax.jit(step, static_argnames=("cfg",)).lower(
            st, cfg, scenarios.default_topic_params(1),
            jax.random.PRNGKey(0)).as_text()
        assert _gathers_at_least(text, n * k), \
            "control failed: dense scalar step not visible to the grep"
