"""Mesh self-healing time (the gossipsub.go heartbeat contract).

After a forced disconnect burst in a churn_50k-style config, the mean mesh
degree must recover to >= D_lo within a bounded number of ticks — the
heartbeat's under-subscription grafting plus churn's reconnect path
(gossipsub.go:1413-1427 grafting, pubsub.go:711-757 dead-peer lifecycle).
Checked in BOTH halves: the batched engine (ops/churn take_edges_down as
the burst, churn reconnects as the recovery) and the host-side functional
runtime (Host.disconnect burst, surviving connections regraft).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops.churn import take_edges_down
from go_libp2p_pubsub_tpu.sim import (
    SimConfig, init_state, mesh_degrees, run, topology,
)
from go_libp2p_pubsub_tpu.sim import scenarios

pytestmark = pytest.mark.faults

RECOVERY_BUDGET_TICKS = 25


def _symmetric_burst(topo, fraction, seed=5):
    """[N, K] symmetric edge mask: the unordered pair's hash decides, so
    both directions go down together (the TCP-stream contract the batched
    churn requires)."""
    nbr = topo.neighbors
    n, k = nbr.shape
    rng_vals = {}
    mask = np.zeros((n, k), bool)
    rs = np.random.RandomState(seed)
    for i in range(n):
        for s in range(k):
            j = nbr[i, s]
            if j < 0:
                continue
            pair = (min(i, int(j)), max(i, int(j)))
            if pair not in rng_vals:
                rng_vals[pair] = rs.rand() < fraction
            mask[i, s] = rng_vals[pair]
    return mask


class TestBatchedSelfHealing:
    def test_degree_recovers_after_burst(self):
        """churn_50k-style config at toy scale: converge, burst 50% of
        edges down, recover mean mesh degree >= D_lo within the budget."""
        cfg = SimConfig(
            n_peers=64, k_slots=16, n_topics=1, msg_window=32,
            publishers_per_tick=2, prop_substeps=6,
            scoring_enabled=True, retain_score_ticks=30,
            churn_disconnect_prob=0.0, churn_reconnect_prob=0.3,
            px_enabled=True, accept_px_threshold=-50.0)
        topo = topology.dense(cfg.n_peers, cfg.k_slots, degree=10)
        tp = scenarios.default_topic_params(1)
        st = init_state(cfg, topo)
        st = run(st, cfg, tp, jax.random.PRNGKey(0), 15)
        deg0 = float(np.asarray(mesh_degrees(st)).mean())
        assert deg0 >= cfg.dlo, f"mesh never converged: {deg0}"

        burst = jnp.asarray(_symmetric_burst(topo, 0.5)) & st.connected
        st_b = take_edges_down(st, cfg, tp, burst)
        deg_b = float(np.asarray(mesh_degrees(st_b)).mean())
        assert deg_b < deg0, "burst did not dent the mesh"

        st_r = run(st_b, cfg, tp, jax.random.PRNGKey(1),
                   RECOVERY_BUDGET_TICKS)
        deg_r = float(np.asarray(mesh_degrees(st_r)).mean())
        assert deg_r >= cfg.dlo, \
            f"mesh degree {deg_r} < D_lo {cfg.dlo} after " \
            f"{RECOVERY_BUDGET_TICKS} ticks (was {deg_b} post-burst)"
        # the recovery must not have tripped the sentinel
        assert int(st_r.fault_flags) == 0

    def test_burst_stamps_disconnect_and_clears_mesh(self):
        cfg = SimConfig(n_peers=32, k_slots=8, n_topics=1, msg_window=32,
                        publishers_per_tick=2, prop_substeps=4)
        topo = topology.dense(cfg.n_peers, cfg.k_slots, degree=6)
        tp = scenarios.default_topic_params(1)
        st = run(init_state(cfg, topo), cfg, tp, jax.random.PRNGKey(0), 5)
        burst = jnp.asarray(_symmetric_burst(topo, 0.5)) & st.connected
        st_b = take_edges_down(st, cfg, tp, burst)
        b = np.asarray(burst)
        assert not np.asarray(st_b.connected)[b].any()
        assert not (np.asarray(st_b.mesh) & b[:, None, :]).any()
        assert (np.asarray(st_b.disconnect_tick)[b] == int(st.tick)).all()


class TestHostSelfHealing:
    def test_degree_recovers_after_burst(self):
        """Functional-runtime twin: disconnect ~1/3 of each node's
        connections, let the heartbeat regraft among the survivors, and
        require mean mesh degree >= D_lo within the same tick budget
        (1 tick == 1 s == 1 heartbeat)."""
        from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
        from go_libp2p_pubsub_tpu.net import Network
        from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter

        net = Network()
        nodes = [PubSub(net.add_host(), GossipSubRouter(),
                        sign_policy=LAX_NO_SIGN) for _ in range(20)]
        net.dense_connect([p.host for p in nodes], degree=12)
        [p.join("t").subscribe() for p in nodes]
        net.scheduler.run_for(5.0)
        dlo = nodes[0].rt.params.dlo
        deg0 = np.mean([len(p.rt.mesh.get("t", ())) for p in nodes])
        assert deg0 >= dlo, f"mesh never converged: {deg0}"

        rs = np.random.RandomState(11)
        for i, p in enumerate(nodes):
            for pid in list(p.host.conns):
                if rs.rand() < 0.33:
                    p.host.disconnect(pid)
        deg_b = np.mean([len(p.rt.mesh.get("t", ())) for p in nodes])
        assert deg_b < deg0, "burst did not dent the mesh"

        net.scheduler.run_for(float(RECOVERY_BUDGET_TICKS))
        deg_r = np.mean([len(p.rt.mesh.get("t", ())) for p in nodes])
        assert deg_r >= dlo, \
            f"host mesh degree {deg_r} < D_lo {dlo} after " \
            f"{RECOVERY_BUDGET_TICKS} heartbeats (was {deg_b} post-burst)"
