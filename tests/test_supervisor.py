"""Supervised execution plane (sim/supervisor.py, ISSUE 5).

The core correctness claim: chunked supervised execution — with
checkpoints, kills, resumes, retries, and degraded modes in any
combination — produces a final ``SimState`` bit-identical to the plain
single-scan ``engine.run`` on the same master key. Everything else
(watchdog, ladder, crash dumps, replay, sink flushing) is supervised-run
plumbing proven on top of that claim.
"""

import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import (SimConfig, TopicParams, init_state,
                                      topology)
from go_libp2p_pubsub_tpu.sim import checkpoint
from go_libp2p_pubsub_tpu.sim.engine import run
from go_libp2p_pubsub_tpu.sim.supervisor import (ChunkDeadline,
                                                 SupervisorConfig,
                                                 SupervisorCrash,
                                                 supervised_run)

pytestmark = pytest.mark.supervisor

N_TICKS = 13


def _assert_states_equal(a, b):
    for f, x, y in zip(a._fields, a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"field {f}")


@pytest.fixture(scope="module")
def plain():
    """One shared tiny config + its uninterrupted reference trajectory
    (module-scoped: every test reuses the jit cache for its shapes)."""
    cfg = SimConfig(n_peers=64, k_slots=8, n_topics=1, msg_window=32,
                    publishers_per_tick=2, prop_substeps=4,
                    scoring_enabled=True)
    tp = TopicParams.disabled(1)
    st = init_state(cfg, topology.sparse(64, 8, degree=3))
    key = jax.random.PRNGKey(42)
    return cfg, tp, st, key, run(st, cfg, tp, key, N_TICKS)


def _sup(**kw):
    kw.setdefault("chunk_ticks", 5)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("sleep", lambda s: None)
    return SupervisorConfig(**kw)


class TestChunkedParity:
    def test_chunked_equals_single_scan(self, plain):
        cfg, tp, st, key, ref = plain
        out, rep = supervised_run(st, cfg, tp, key, N_TICKS, _sup())
        _assert_states_equal(ref, out)
        assert rep.chunks_run == 3 and rep.ticks_run == N_TICKS

    def test_chunk_size_one(self, plain):
        cfg, tp, st, key, ref = plain
        out, _ = supervised_run(st, cfg, tp, key, N_TICKS,
                                _sup(chunk_ticks=1))
        _assert_states_equal(ref, out)


class TestKillResume:
    def test_kill_and_resume_bit_identical(self, plain, tmp_path):
        """THE acceptance case: interrupt mid-scan (simulated kill escapes
        the supervisor's retry net), re-invoke, final state bit-identical
        to the uninterrupted run."""
        cfg, tp, st, key, ref = plain
        ck = str(tmp_path / "ck")

        def kill(info):
            if info["chunk_start"] >= 10:
                raise KeyboardInterrupt("simulated preemption")

        with pytest.raises(KeyboardInterrupt):
            supervised_run(st, cfg, tp, key, N_TICKS,
                           _sup(checkpoint_dir=ck), _chunk_hook=kill)
        out, rep = supervised_run(st, cfg, tp, key, N_TICKS,
                                  _sup(checkpoint_dir=ck))
        assert rep.resumed_tick == 10
        assert rep.ticks_run == 3          # only the missing window re-ran
        _assert_states_equal(ref, out)

    def test_resume_ignores_foreign_config_checkpoint(self, plain, tmp_path):
        """A checkpoint stamped under a DIFFERENT config fingerprint is
        skipped (not half-accepted) and the run starts from scratch."""
        cfg, tp, st, key, ref = plain
        ck = str(tmp_path / "ck")
        os.makedirs(ck)
        other = dataclasses.replace(cfg, publishers_per_tick=3)
        mid = run(st, other, tp, key, 5)
        checkpoint.save(os.path.join(ck, "ckpt_t000000005"), mid, cfg=other)
        out, rep = supervised_run(st, cfg, tp, key, N_TICKS,
                                  _sup(checkpoint_dir=ck))
        assert rep.resumed_from is None
        assert any(e["event"] == "resume_skip" for e in rep.events)
        _assert_states_equal(ref, out)


class TestTornCheckpoint:
    def test_truncated_npz_raises_cleanly(self, plain, tmp_path):
        cfg, tp, st, key, _ = plain
        path = str(tmp_path / "torn.npz")
        checkpoint.save(path, st)
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        with pytest.raises(checkpoint.CheckpointCorrupt,
                           match="torn or incomplete"):
            checkpoint.restore(path, st)

    def test_supervisor_falls_back_past_torn_checkpoint(self, plain,
                                                        tmp_path):
        """Kill leaves ckpts at t5 and t10; t10 is then torn (simulated
        partial write of a pre-atomicity save). Resume must fall back to
        t5 and still land bit-identical."""
        cfg, tp, st, key, ref = plain
        ck = str(tmp_path / "ck")

        def kill(info):
            if info["chunk_start"] >= 10:
                raise KeyboardInterrupt("simulated preemption")

        with pytest.raises(KeyboardInterrupt):
            supervised_run(st, cfg, tp, key, N_TICKS,
                           _sup(checkpoint_dir=ck), _chunk_hook=kill)
        newest = os.path.join(ck, "ckpt_t000000010")
        if os.path.isdir(newest):              # orbax backend: gut the dir
            for root, _dirs, files in os.walk(newest):
                for fl in files:
                    os.remove(os.path.join(root, fl))
        else:
            with open(newest + ".npz", "r+b") as f:
                f.truncate(os.path.getsize(newest + ".npz") // 2)
        out, rep = supervised_run(st, cfg, tp, key, N_TICKS,
                                  _sup(checkpoint_dir=ck))
        assert rep.resumed_tick == 5, rep.events
        assert any(e["event"] == "resume_skip" for e in rep.events)
        _assert_states_equal(ref, out)

    def test_save_is_crash_atomic_no_partial_at_final_path(self, plain,
                                                           tmp_path):
        """The final path only ever holds a COMPLETE checkpoint: during
        save the bytes live at a temp path, so a concurrent/killed save
        leaves either the old payload or nothing — verified by checking
        the temp-path discipline directly."""
        cfg, tp, st, key, _ = plain
        path = str(tmp_path / "atomic.npz")
        checkpoint.save(path, st, cfg=cfg)
        first = checkpoint.restore(path, st, cfg=cfg)
        # overwrite with a different state; any failure mode in between
        # must not have corrupted the readable artifact
        st2 = run(st, cfg, tp, key, 2)
        checkpoint.save(path, st2, cfg=cfg)
        back = checkpoint.restore(path, st2, cfg=cfg)
        _assert_states_equal(st2, back)
        assert int(np.asarray(first.tick)) == 0
        leftovers = [f for f in os.listdir(tmp_path) if ".tmp" in f]
        assert leftovers == [], leftovers


class TestWatchdogAndLadder:
    def test_deadline_trip_backoff_degrade_then_parity(self, plain):
        cfg, tp, st, key, ref = plain
        delays = []

        def slow_once(info):
            if info["chunk_start"] == 0 and info["attempt"] == 0:
                time.sleep(1.0)

        sup = _sup(deadline_s=0.4, sleep=delays.append,
                   backoff_base_s=0.25)
        out, rep = supervised_run(st, cfg, tp, key, N_TICKS, sup,
                                  _chunk_hook=slow_once)
        assert rep.retries == 1
        assert delays == [0.25]                # exponential backoff base
        evs = [e["event"] for e in rep.events]
        assert evs[:3] == ["chunk_failed", "degrade", "backoff"]
        assert rep.events[0]["kind"] == "deadline"
        _assert_states_equal(ref, out)          # degraded rungs stay exact

    def test_mode_fallback_rung_first(self, plain):
        """A config on a non-default kernel mode degrades modes before
        shrinking the chunk, and the trajectory stays bit-identical."""
        cfg, tp, st, key, _ = plain
        mcfg = dataclasses.replace(cfg, edge_gather_mode="sort")
        ref = run(st, mcfg, tp, key, N_TICKS)
        fails = iter([True, False])

        def flaky(info):
            if next(fails, False):
                raise RuntimeError("transient")

        out, rep = supervised_run(st, mcfg, tp, key, N_TICKS, _sup(),
                                  _chunk_hook=flaky)
        deg = [e for e in rep.events if e["event"] == "degrade"]
        # explicit conservative formulation, NOT "auto" (auto would
        # resolve right back to the failing mode on its home backend)
        assert deg and deg[0].get("edge_gather_mode") == "scalar"
        assert rep.degrade_level == 1
        _assert_states_equal(ref, out)

    def test_unknown_mode_degrades_instead_of_deadending(self, plain):
        """ISSUE 6 ladder satellite: a mode name the resolvers do not
        know (a future formulation, a typo'd env knob) raises at chunk
        compile — the ladder must map it to the explicit conservative
        floor (_CONSERVATIVE_MODES) and complete the run, never dead-end
        the retry loop on an unresolvable config."""
        cfg, tp, st, key, ref = plain
        bogus = dataclasses.replace(cfg, hop_mode="blocked-onehot-v2")
        out, rep = supervised_run(st, bogus, tp, key, N_TICKS, _sup())
        deg = [e for e in rep.events if e["event"] == "degrade"]
        assert deg and deg[0].get("hop_mode") == "xla"
        assert deg[0].get("edge_gather_mode") == "scalar"
        assert deg[0].get("selection_mode") == "sort"
        # the degraded trajectory equals the plain run (mode parity)
        _assert_states_equal(ref, out)

    def test_backoff_schedule_is_exponential_and_capped(self, plain):
        cfg, tp, st, key, _ = plain
        delays = []
        fails = iter([True, True, True])

        def flaky(info):
            if next(fails, False):
                raise RuntimeError("transient")

        sup = _sup(backoff_base_s=1.0, backoff_factor=2.0,
                   backoff_cap_s=3.0, sleep=delays.append, max_retries=4)
        supervised_run(st, cfg, tp, key, N_TICKS, sup, _chunk_hook=flaky)
        assert delays == [1.0, 2.0, 3.0]        # 4.0 capped to 3.0


class TestCrashDump:
    def test_retries_exhausted_dumps_and_raises(self, plain, tmp_path):
        cfg, tp, st, key, _ = plain

        def boom(info):
            raise RuntimeError("permanent failure")

        with pytest.raises(SupervisorCrash) as ei:
            supervised_run(st, cfg, tp, key, N_TICKS,
                           _sup(max_retries=2, crash_dir=str(tmp_path)),
                           _chunk_hook=boom)
        dump = ei.value.dump_dir
        meta = json.load(open(os.path.join(dump, "crash.json")))
        assert meta["error_type"] == "RuntimeError"
        assert meta["tick_start"] == 0
        assert meta["config_fingerprint"] == \
            checkpoint.config_fingerprint(cfg)
        # the failing window's keys are recorded, replay-ready
        keys = np.asarray(meta["window_key_data"], dtype=np.uint32)
        assert keys.ndim == 2 and keys.shape[1] == 2
        back = checkpoint.restore(os.path.join(dump, "last_good"), st,
                                  cfg=cfg)
        assert int(np.asarray(back.tick)) == 0
        assert ei.value.report.retries == 2

    def test_invariant_trip_is_unrecoverable_no_retry(self, plain,
                                                      tmp_path):
        """An invariant_mode="raise" checkify trip must crash-dump
        IMMEDIATELY — the trajectory is poisoned; retrying the same keys
        would trip again."""
        cfg, tp, st, key, _ = plain
        rcfg = dataclasses.replace(cfg, invariant_mode="raise")
        poisoned = st._replace(halo_overflow=jnp.int32(3))
        with pytest.raises(SupervisorCrash) as ei:
            supervised_run(poisoned, rcfg, tp, key, N_TICKS,
                           _sup(crash_dir=str(tmp_path)))
        assert ei.value.report.retries == 0
        meta = json.load(open(os.path.join(ei.value.dump_dir,
                                           "crash.json")))
        assert "invariant violation" in meta["error"]

    def test_replay_crash_reproduces_clean_and_tripped(self, plain,
                                                       tmp_path):
        from scripts.replay_crash import replay
        cfg, tp, st, key, _ = plain

        def boom(info):
            raise RuntimeError("host-side failure")

        with pytest.raises(SupervisorCrash) as ei:
            supervised_run(st, cfg, tp, key, N_TICKS,
                           _sup(max_retries=1, crash_dir=str(tmp_path)),
                           _chunk_hook=boom)
        # host-side failure: the window itself is healthy -> clean replay
        res = replay(ei.value.dump_dir, like=st, cfg=cfg, tp=tp)
        assert res["tripped"] is False and res["fault_flags"] == 0
        assert res["ticks"] == res["tick_end"] - res["tick_start"]

        # poisoned trajectory: the replay must REPRODUCE the trip
        rcfg = dataclasses.replace(cfg, invariant_mode="raise")
        poisoned = st._replace(halo_overflow=jnp.int32(3))
        with pytest.raises(SupervisorCrash) as ei2:
            supervised_run(poisoned, rcfg, tp, key, N_TICKS,
                           _sup(crash_dir=str(tmp_path / "p")))
        res2 = replay(ei2.value.dump_dir, like=st, cfg=rcfg, tp=tp)
        assert res2["tripped"] is True

    def test_sinks_hard_flushed_on_failure(self, plain, tmp_path):
        from go_libp2p_pubsub_tpu.trace.sinks import JSONTracer
        cfg, tp, st, key, _ = plain
        sink = JSONTracer(str(tmp_path / "trace.ndjson"))
        sink.trace({"type": "PUBLISH_MESSAGE", "peerID": "p0"})

        def boom(info):
            raise RuntimeError("crash with buffered trace")

        with pytest.raises(SupervisorCrash):
            supervised_run(st, cfg, tp, key, N_TICKS,
                           _sup(max_retries=0, crash_dir=str(tmp_path),
                                sinks=(sink,)), _chunk_hook=boom)
        # the buffered event reached disk, fsync'd, without close()
        with open(tmp_path / "trace.ndjson") as f:
            recs = [json.loads(ln) for ln in f]
        assert recs == [{"type": "PUBLISH_MESSAGE", "peerID": "p0"}]


class TestTracedMode:
    def test_traced_chunks_match_engine_run(self, plain, tmp_path):
        """Traced supervised chunks use the pre-split key discipline, so
        the final state equals engine.run AND the event stream is
        chunking-invariant."""
        cfg, tp, st, key, _ = plain
        pcfg = dataclasses.replace(cfg, record_provenance=True)
        ref = run(st, pcfg, tp, key, 8)
        ev_a, ev_b = [], []
        out_a, _ = supervised_run(st, pcfg, tp, key, 8, _sup(chunk_ticks=3),
                                  traced=True, events_out=ev_a)
        out_b, _ = supervised_run(st, pcfg, tp, key, 8, _sup(chunk_ticks=8),
                                  traced=True, events_out=ev_b)
        _assert_states_equal(ref, out_a)
        _assert_states_equal(out_a, out_b)
        assert ev_a == ev_b and len(ev_a) > 0

    def test_failed_attempt_events_discarded(self, plain):
        """A retried chunk must not double-count its ticks' events."""
        cfg, tp, st, key, _ = plain
        pcfg = dataclasses.replace(cfg, record_provenance=True)
        fails = iter([True])

        def flaky(info):
            if next(fails, False):
                raise RuntimeError("transient")

        ev, ref_ev = [], []
        out, rep = supervised_run(st, pcfg, tp, key, 8, _sup(chunk_ticks=4),
                                  traced=True, events_out=ev,
                                  _chunk_hook=flaky)
        assert rep.retries == 1
        supervised_run(st, pcfg, tp, key, 8, _sup(chunk_ticks=4),
                       traced=True, events_out=ref_ev)
        assert ev == ref_ev


class TestPartitionFaultsResume:
    """The acceptance case under an ACTIVE FaultPlan: partition_50k (at
    test scale) interrupted mid-scan across the partition window, resumed,
    bit-identical to the uninterrupted run."""

    def test_partition_kill_resume_parity(self, tmp_path):
        from go_libp2p_pubsub_tpu.sim import scenarios
        cfg, tp, st = scenarios.partition_50k(n_peers=256, k_slots=16,
                                              degree=6, start=2, heal=7)
        key = jax.random.PRNGKey(3)
        n_ticks = 10
        ref = run(st, cfg, tp, key, n_ticks)
        assert int(np.asarray(ref.fault_flags)) != 0   # the plan FIRED
        ck = str(tmp_path / "ck")

        def kill(info):
            if info["chunk_start"] >= 4:    # inside the partition window
                raise KeyboardInterrupt("simulated preemption")

        with pytest.raises(KeyboardInterrupt):
            supervised_run(st, cfg, tp, key, n_ticks,
                           _sup(chunk_ticks=4, checkpoint_dir=ck),
                           _chunk_hook=kill)
        out, rep = supervised_run(st, cfg, tp, key, n_ticks,
                                  _sup(chunk_ticks=4, checkpoint_dir=ck))
        assert rep.resumed_tick == 4
        _assert_states_equal(ref, out)


def test_full_ladder_smoke(tmp_path):
    """CI twin of the scripts/tpu_recheck.sh `supervisor_smoke` step:
    deadline trip -> backoff -> degraded mode -> checkpoint/resume ->
    crash dump -> replay -> chaos-stall recovery on a tiny config, all
    stages green."""
    from scripts.supervisor_smoke import run_smoke
    lines = []
    assert run_smoke(str(tmp_path), emit=lines.append) == 0
    stages = [json.loads(ln) for ln in lines]
    assert [s["stage"] for s in stages] == [
        "deadline_backoff_degrade", "checkpoint_resume",
        "crash_dump_replay", "chaos_stall_recovery"]
    assert all(s["status"] == "ok" for s in stages)
