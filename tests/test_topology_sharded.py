"""Sharded topology construction (ISSUE 13, ROADMAP item 4): the
seeded-hash circulant builder ``topology.sparse_hash``.

Every row of the underlay is a pure function of ``(n, degree, seed,
row)``, so a multi-process launch materializes ONLY its ``[N/P, K]``
rows and the concat across processes equals the single-host build bit
for bit BY CONSTRUCTION. This file pins:

- graph shape: 2·degree-regular, symmetric, slots sorted-neighbor
  ordered, the "+" offset direction one-sidedly outbound;
- the reverse_slot involution (``reverse_slot[j, reverse_slot[i, s]]``
  points back at slot s) computed strictly locally;
- shard parity at P ∈ {2, 4} and chunk-size independence;
- the memory contract: a per-process shard build at 1M peers stays
  under a peak-RSS ceiling a full-table build cannot meet (subprocess,
  numpy only — no jax import inflating the measurement).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.sim import topology


def _full(n, k, degree, seed=314159):
    return topology.sparse_hash(n, k, degree=degree, seed=seed)


class TestGraphShape:
    @pytest.mark.parametrize("n, k, degree", [(96, 16, 4), (256, 16, 6),
                                              (1000, 32, 8)])
    def test_regular_symmetric_sorted(self, n, k, degree):
        topo = _full(n, k, degree)
        nbr, out, rs = topo.neighbors, topo.outbound, topo.reverse_slot
        valid = nbr >= 0
        # 2*degree-regular: exactly 2*degree live slots per row
        assert np.all(valid.sum(1) == 2 * degree)
        # sorted-neighbor slot order on the live prefix
        live = np.where(valid, nbr, np.iinfo(np.int32).max)
        assert np.all(np.diff(live, axis=1) >= 0) or np.all(
            live[:, :-1] <= live[:, 1:])
        # symmetry via the involution: j = nbr[i, s], r = rs[i, s] ->
        # nbr[j, r] == i and rs[j, r] == s
        i = np.repeat(np.arange(n), k).reshape(n, k)
        j, r = nbr[valid], rs[valid]
        assert np.all((r >= 0) & (r < k))
        assert np.array_equal(nbr[j, r], i[valid])
        s = np.broadcast_to(np.arange(k), (n, k))[valid]
        assert np.array_equal(rs[j, r], s)
        # outbound is one-sided: each symmetric edge dialed exactly once
        assert np.array_equal(out[j, r], ~out[valid] & True)
        # no live slot outside the prefix contract the engine assumes
        assert np.all(nbr[~valid] == -1) and np.all(rs[~valid] == -1)

    def test_offsets_are_distinct_and_complement_free(self):
        n = 1024
        offs = topology.hash_offsets(n, 8, seed=7)
        assert len(set(offs.tolist())) == 8
        assert 0 not in offs and not np.any(2 * offs == n)
        assert not (set(offs.tolist()) & {n - o for o in offs.tolist()})

    def test_degree_over_capacity_refuses_by_name(self):
        with pytest.raises(ValueError, match="2\\*degree"):
            topology.sparse_hash(256, 8, degree=8)
        with pytest.raises(ValueError, match="degree"):
            topology.hash_offsets(16, 9)


class TestShardParity:
    @pytest.mark.parametrize("p", [2, 4])
    def test_concat_of_shards_equals_full_build(self, p):
        n, k, degree = 512, 16, 6
        full = _full(n, k, degree)
        nl = n // p
        parts = [topology.sparse_hash(n, k, degree=degree,
                                      rows=(r * nl, nl)) for r in range(p)]
        for field in ("neighbors", "outbound", "reverse_slot"):
            cat = np.concatenate([getattr(t, field) for t in parts])
            np.testing.assert_array_equal(
                cat, getattr(full, field), err_msg=(field, p))

    def test_ragged_splits_concat_to_full_build(self):
        """Uneven row splits — misaligned boundaries and a SHORT last
        shard — also concat bit-for-bit (ISSUE 15: elastic meshes hand
        ragged row ranges to survivors, not tidy n/p blocks)."""
        n, k, degree = 512, 16, 6
        full = _full(n, k, degree)
        for bounds in ([0, 129, 380, 512], [0, 511, 512]):
            parts = [topology.sparse_hash(n, k, degree=degree,
                                          rows=(s, e - s))
                     for s, e in zip(bounds, bounds[1:])]
            for field in ("neighbors", "outbound", "reverse_slot"):
                cat = np.concatenate([getattr(t, field) for t in parts])
                np.testing.assert_array_equal(
                    cat, getattr(full, field), err_msg=(field, bounds))

    def test_chunk_size_does_not_change_the_build(self):
        n, k, degree = 300, 16, 5
        a = topology.sparse_hash(n, k, degree=degree, chunk_rows=7)
        b = topology.sparse_hash(n, k, degree=degree, chunk_rows=10_000)
        for field in ("neighbors", "outbound", "reverse_slot"):
            np.testing.assert_array_equal(getattr(a, field),
                                          getattr(b, field), err_msg=field)

    def test_rows_out_of_bounds_refuses_by_name(self):
        with pytest.raises(ValueError, match="rows"):
            topology.sparse_hash(256, 16, degree=6, rows=(200, 100))


def test_shard_build_rss_stays_under_ceiling_at_1m():
    """The memory contract: an 8-way shard build at 1M×32 materializes
    only [N/8, K] rows — three [131072, 32] planes ≈ 37 MB — so the
    builder subprocess's peak RSS stays under a ceiling the full-table
    build (~300 MB of planes plus working set) cannot meet. numpy-only
    subprocess: a jax import would dwarf the thing being measured."""
    code = """
import resource
import numpy as np
import sys
sys.path.insert(0, %r)
from go_libp2p_pubsub_tpu.sim.topology import sparse_hash

n, k = 1_048_576, 32
topo = sparse_hash(n, k, degree=8, rows=(n // 8 * 3, n // 8))
assert topo.neighbors.shape == (n // 8, k), topo.neighbors.shape
assert np.all((topo.neighbors >= 0).sum(1) == 16)
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
shard_bytes = sum(a.nbytes for a in
                  (topo.neighbors, topo.outbound, topo.reverse_slot))
full_bytes = shard_bytes * 8
print("RSS_OK", peak, shard_bytes, full_bytes)
# ceiling: numpy import (~80 MB) + the shard planes + chunked working
# set — far under the ~300 MB the full-table planes ALONE would add
assert peak < 250 * 2**20, f"shard build peaked at {peak/2**20:.0f} MiB"
assert peak < full_bytes, "shard build costs as much as the full table"
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run([sys.executable, "-c", code % repo],
                         capture_output=True, text=True, timeout=300)
    assert "RSS_OK" in res.stdout, (res.stdout, res.stderr[-2000:])
