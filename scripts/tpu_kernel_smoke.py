"""Compile-and-run smoke test for every Pallas kernel on the real TPU.

Round-4 lesson: interpret-mode parity (the CPU test tier) proves semantics
but NOT that Mosaic can lower the kernel — the first live tunnel window
revealed unsupported-gather failures in every fused kernel. This script
runs each kernel natively (interpret=False) at a small shape and diffs the
output against interpret mode, so a lowering regression is caught the
moment a window is open, one kernel at a time, with full tracebacks.

Usage (tunnel must be live): python scripts/tpu_kernel_smoke.py
Exit code = number of failing kernels.
"""

import sys
import traceback

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main() -> int:
    from go_libp2p_pubsub_tpu.ops import permgather as pg
    from go_libp2p_pubsub_tpu.ops import hopkernel as hk
    from go_libp2p_pubsub_tpu.ops.bits import U32

    if jax.default_backend() != "tpu":
        print(f"default backend is {jax.default_backend()}, not tpu — abort")
        return 1

    rng = np.random.default_rng(0)
    n, k, t, m, w = 1024, 32, 1, 64, 2
    nbr = jnp.asarray(rng.integers(0, n, (n, k)), jnp.int32)
    rk = jnp.asarray(rng.integers(0, k, (n, k)), jnp.int32)
    tab_wn = jnp.asarray(rng.integers(0, 2**32, (w, n), dtype=np.uint64),
                         U32)
    payload_nk = jnp.asarray(rng.integers(0, 2**32, (n, k), dtype=np.uint64),
                             U32)
    table_bits = jnp.asarray(
        rng.integers(0, 2**32, (n, (2 * k + 31) // 32), dtype=np.uint64), U32)
    planes_u8 = jnp.asarray(rng.integers(0, 2, (n, t, k)), jnp.uint8)
    topic_bits = jnp.asarray(
        rng.integers(0, 2**32, (t, w), dtype=np.uint64), U32)
    pend = jnp.asarray(
        np.where(rng.random((n, m)) < 0.1, rng.integers(0, k, (n, m)), -1),
        jnp.int32)
    acc = jnp.zeros((t, k, n), jnp.uint8)

    fails = 0

    def check(name, fn):
        nonlocal fails
        try:
            got = jax.tree.map(np.asarray, fn(False))
            want = jax.tree.map(np.asarray, fn(True))
            jax.tree.map(np.testing.assert_array_equal, want, got)
            print(f"PASS {name}")
        except Exception:
            fails += 1
            print(f"FAIL {name}")
            traceback.print_exc(limit=8)

    check("gather_words_pallas",
          lambda i: pg._gather_words_pallas(tab_wn, nbr, interpret=i))
    check("gather_pallas (edge payload)",
          lambda i: pg._gather_pallas(payload_nk, nbr, rk, interpret=i))
    check("edge_table_pallas",
          lambda i: tuple(pg._edge_table_pallas(table_bits, nbr, rk,
                                                b_planes=2, interpret=i)))
    check("emit_pallas",
          lambda i: hk.emit_pallas(tab_wn, tab_wn ^ U32(0xA5A5A5A5),
                                   planes_u8, topic_bits, nbr, m=m,
                                   budget=m, interpret=i))
    check("emit_pallas (binding budget)",
          lambda i: hk.emit_pallas(tab_wn, tab_wn ^ U32(0xA5A5A5A5),
                                   planes_u8, topic_bits, nbr, m=m,
                                   budget=3, interpret=i))
    check("iwant_resolve_pallas",
          lambda i: hk.iwant_resolve_pallas(
              pend, tab_wn, tab_wn ^ U32(0x33CC33CC), tab_wn | U32(1),
              tab_wn & U32(0xF0F0F0F0), jnp.full((w, 1), U32(0xFFFFFFFF)),
              planes_u8[:, 0, :], topic_bits, nbr, m=m, interpret=i))
    check("hop_pallas",
          lambda i: hk.hop_pallas(
              tab_wn, tab_wn ^ U32(0x55AA55AA), tab_wn & U32(0xFF00FF00),
              jnp.zeros_like(tab_wn), tab_wn | U32(3),
              tab_wn & U32(0x0F0F0F0F), jnp.zeros_like(tab_wn),
              jnp.full((w, 1), U32(0xFFFFFFFF)), nbr, planes_u8, planes_u8,
              topic_bits, acc, acc, acc, interpret=i))
    # --- the pallas-mxu variants: in-kernel gathers rewritten as the
    # gather-free two-level one-hot select (mxutake.take_words_onehot).
    # These are the S1-S7 resurrection candidates — if they lower while
    # the wall repro below still fails, hop_mode="pallas-mxu" is live.
    check("hop_pallas (gather=mxu)",
          lambda i: hk.hop_pallas(
              tab_wn, tab_wn ^ U32(0x55AA55AA), tab_wn & U32(0xFF00FF00),
              jnp.zeros_like(tab_wn), tab_wn | U32(3),
              tab_wn & U32(0x0F0F0F0F), jnp.zeros_like(tab_wn),
              jnp.full((w, 1), U32(0xFFFFFFFF)), nbr, planes_u8, planes_u8,
              topic_bits, acc, acc, acc, gather="mxu", interpret=i))
    check("emit_pallas (gather=mxu)",
          lambda i: hk.emit_pallas(tab_wn, tab_wn ^ U32(0xA5A5A5A5),
                                   planes_u8, topic_bits, nbr, m=m,
                                   budget=3, gather="mxu", interpret=i))
    check("iwant_resolve_pallas (gather=mxu)",
          lambda i: hk.iwant_resolve_pallas(
              pend, tab_wn, tab_wn ^ U32(0x33CC33CC), tab_wn | U32(1),
              tab_wn & U32(0xF0F0F0F0), jnp.full((w, 1), U32(0xFFFFFFFF)),
              planes_u8[:, 0, :], topic_bits, nbr, m=m, gather="mxu",
              interpret=i))
    # --- engine-shaped emit probe (ADVICE r5): the emit kernel mixes
    # prefix_count_words + pack_words in-kernel (1-D iota, masked.T
    # transpose) — the op class Mosaic has historically refused even
    # where interpret mode is exact. This drives the EXACT path the
    # engine would take with hop_mode="pallas" at an engine-real shape
    # (m=128 -> w=4, binding budget): if it FAILS natively while the
    # small emit checks above pass, the pallas emit promotion stays
    # blocked (resolve_emit_mode docstring).
    m_eng, w_eng = 128, 4
    tab_eng = jnp.asarray(
        rng.integers(0, 2**32, (w_eng, n), dtype=np.uint64), U32)
    topic_eng = jnp.asarray(
        rng.integers(0, 2**32, (t, w_eng), dtype=np.uint64), U32)
    assert hk.resolve_emit_mode("pallas", w_eng, n, k) == "pallas", \
        "engine-shaped emit probe no longer matches resolve_emit_mode"
    check("emit resolve path (engine-shaped)",
          lambda i: hk.emit_dispatch(
              tab_eng, tab_eng ^ U32(0xA5A5A5A5), planes_u8, topic_eng,
              nbr, m=m_eng, budget=min(5000, m_eng), interpret=i))
    # --- the Mosaic gather wall, distilled (VERDICT r4 item 3) ---------
    # The exact failure that killed the S1-S7 fused kernels: a table
    # lookup wider than one vreg. Re-tested every window; if it ever
    # PASSES, Mosaic learned to gather and the kernel suite un-blocks.
    def wall_repro(interpret):
        from functools import partial

        from jax.experimental import pallas as pl
        tab = jnp.arange(1024, dtype=jnp.uint32)        # > 128 lanes

        def kern(t_ref, i_ref, o_ref):
            o_ref[:] = t_ref[:][i_ref[:]]               # 1024-wide gather

        return pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec((1024,), lambda: (0,)),
                      pl.BlockSpec((256,), lambda: (0,))],
            out_specs=pl.BlockSpec((256,), lambda: (0,)),
            out_shape=jax.ShapeDtypeStruct((256,), jnp.uint32),
            interpret=interpret,
        )(tab, jnp.asarray(rng.integers(0, 1024, (256,)), jnp.int32))

    try:
        import jax.lib
        libtpu_v = getattr(jax.lib, "xla_extension_version", "?")
        print(f"jax {jax.__version__} / xla_extension_version {libtpu_v}")
        np.testing.assert_array_equal(np.asarray(wall_repro(False)),
                                      np.asarray(wall_repro(True)))
        print("PASS mosaic_gather_wall_repro — MOSAIC LEARNED TO GATHER: "
              "re-promote the S1-S7 kernels (PERF_MODEL.md)")
    except Exception as e:
        print(f"EXPECTED-FAIL mosaic_gather_wall_repro: "
              f"{type(e).__name__}: {str(e)[:300]}")

    # --- the two-level gather-free take (ops/mxutake.py) ----------------
    # No gather op of any width: one-hot MXU block select + VPU lane
    # select. If THIS passes natively, the fused-kernel design returns
    # with its gathers rewritten this way.
    from go_libp2p_pubsub_tpu.ops import mxutake as mt
    idx_flat = jnp.asarray(rng.integers(0, n, (4096,)), jnp.int32)
    check("take_words_twolevel (gather-free)",
          lambda i: mt.take_words_twolevel(tab_wn, idx_flat, interpret=i))
    if fails == 0:
        # native timing at a real shape: vs the measured ~9 ms sort and
        # ~25 ms XLA gather for the 100k hop lookup (PERF_MODEL.md)
        import time
        n_big, l_big = 102400, 102400 * 32
        xb = jnp.asarray(rng.integers(0, 2**32, (2, n_big),
                                      dtype=np.uint64), U32)
        ib = jnp.asarray(rng.integers(0, n_big, (l_big,)), jnp.int32)
        f = jax.jit(lambda x, i: mt.take_words_twolevel(x, i, block_g=4096))
        np.asarray(f(xb, ib))                     # compile + warm
        t0 = time.perf_counter()
        np.asarray(f(xb, ib))
        print(f"take_words_twolevel @N=102400,L=3.3M: "
              f"{(time.perf_counter() - t0) * 1e3:.2f} ms "
              "(vs ~9 ms sort / ~25 ms XLA gather)")

    print(f"{fails} failing kernel(s)")
    return fails


if __name__ == "__main__":
    raise SystemExit(main())
