"""Diagnose the functional-vs-batched mesh-degree offset (VERDICT r3 #4).

Round-3 measured functional mean degree 9.11 vs batched 8.11 on the shared
512-peer underlay (KS 0.227) and the band was pinned, not explained. This
script runs BOTH halves of tests/test_statistical_parity.py's setup and
prints per-tick trajectories:

  batched:    mean degree, grafted-edge count, pruned-edge count (from
              mesh diffs across single ticks), under/over row counts
  functional: GRAFT/PRUNE trace events bucketed per virtual second, plus
              the same mean-degree trajectory sampled per second

The differing decision shows up as the tick where the trajectories part.

Usage: python scripts/parity_diag.py [n_peers] [ticks]   (re-execs scrubbed)
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))


def child_main(n: int, ticks: int) -> None:
    import numpy as np

    import test_statistical_parity as tsp

    # ---- functional half, instrumented per virtual second ----
    from go_libp2p_pubsub_tpu.api import LAX_NO_SIGN, PubSub
    from go_libp2p_pubsub_tpu.core.params import (
        PeerScoreParams, PeerScoreThresholds)
    from go_libp2p_pubsub_tpu.net import Network
    from go_libp2p_pubsub_tpu.routers.gossipsub import GossipSubRouter
    from go_libp2p_pubsub_tpu.trace import MemoryTracer

    net = Network()
    mem = MemoryTracer()
    nodes = []
    for _ in range(n):
        h = net.add_host()
        sp = PeerScoreParams(app_specific_score=lambda p: 0.0,
                             decay_interval=1.0, decay_to_zero=0.01,
                             topics={tsp.TOPIC: tsp.TSP})
        nodes.append(PubSub(h, GossipSubRouter(
            score_params=sp, thresholds=PeerScoreThresholds()),
            sign_policy=LAX_NO_SIGN, event_tracer=mem))
    hosts = [x.host for x in nodes]
    net.dense_connect(hosts, degree=tsp.DEGREE)
    net.scheduler.run_for(0.1)
    for x in nodes:
        x.join(tsp.TOPIC).subscribe()

    f_deg = []
    for t in range(ticks):
        net.scheduler.run_until(0.1 + t + 1.0)
        f_deg.append(np.mean([len(x.rt.mesh.get(tsp.TOPIC, ()))
                              for x in nodes]))
    grafts = {}
    prunes = {}
    for e in mem.events:
        b = int(e.get("timestamp", 0.0))
        if e["type"] == "GRAFT":
            grafts[b] = grafts.get(b, 0) + 1
        elif e["type"] == "PRUNE":
            prunes[b] = prunes.get(b, 0) + 1

    print("== functional (per virtual second) ==")
    for t in range(ticks):
        print(f"  t={t:3d}  mean_deg={f_deg[t]:6.2f}  "
              f"grafts={grafts.get(t, 0):5d}  prunes={prunes.get(t, 0):5d}",
              flush=True)

    # ---- batched half on the SAME underlay, stepped tick by tick ----
    import jax

    from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
    from go_libp2p_pubsub_tpu.sim.config import TopicParams
    from go_libp2p_pubsub_tpu.sim.engine import step_jit

    topo, _ = topology.from_hosts(hosts, tsp.K_SLOTS)
    cfg = SimConfig(n_peers=n, k_slots=tsp.K_SLOTS, n_topics=1,
                    msg_window=64, publishers_per_tick=2, prop_substeps=8,
                    scoring_enabled=True)
    tp = TopicParams.from_topic_params([tsp.TSP])
    st = init_state(cfg, topo, subscribed=np.ones((n, 1), bool))
    key = jax.random.PRNGKey(0)
    print("== batched (per tick) ==")
    for t in range(ticks):
        before = np.asarray(st.mesh)
        st = step_jit(st, cfg, tp, jax.random.fold_in(key, t))
        after = np.asarray(st.mesh)
        deg = after.sum(axis=(1, 2)).mean()
        newly = int((after & ~before).sum())
        removed = int((before & ~after).sum())
        n_deg = after.sum(axis=2)[:, 0]
        under = int((n_deg < cfg.dlo).sum())
        over = int((n_deg > cfg.dhi).sum())
        backoffs = int((np.asarray(st.backoff) > t + 1).sum())
        print(f"  t={t:3d}  mean_deg={deg:6.2f}  grafts={newly:5d}  "
              f"prunes={removed:5d}  under={under:4d}  over={over:4d}  "
              f"backoff_edges={backoffs:6d}", flush=True)


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    ticks = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    if os.environ.get("_PARITY_DIAG_CHILD") == "1":
        child_main(n, ticks)
        return
    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env
    env = cpu_mesh_env(dict(os.environ), 8)
    env["_PARITY_DIAG_CHILD"] = "1"
    raise SystemExit(subprocess.run(
        [sys.executable, "-u", __file__, str(n), str(ticks)],
        env=env).returncode)


if __name__ == "__main__":
    main()
