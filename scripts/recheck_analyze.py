"""Summarize a scripts/tpu_recheck.sh run into decisions.

Reads the per-step logs (default /tmp/tpu_recheck) and prints:
  - the bench table (scenario -> hb/s, platform, delivery), sweeps included;
  - per-family sweep winners (edge-gather modes vs selection modes are
    separate sweeps; a cross-family comparison would be meaningless);
  - the microbench candidate rankings per shape;
  - where to flip the `auto` defaults (ops/permgather.resolve_mode /
    resolve_words_mode, ops/selection.resolve_selection_mode).

Failed runs (bench error lines, value 0.0) are shown as FAILED and
excluded from winner sets; scenarios keep their [platform] tag so a
mid-run CPU fallback can never be compared against TPU numbers.

Usage: python scripts/recheck_analyze.py [log_dir]
"""

import json
import os
import re
import sys

# sweep step -> (family, mode label)
SWEEP_STEPS = {
    "modes_rows": ("edge_gather", "rows"),
    "modes_pallas": ("edge_gather", "pallas"),
    "modes_scalar": ("edge_gather", "scalar"),
    "sel_iter": ("selection", "iter"),
    "sel_ranks": ("selection", "ranks"),
    "bench": ("auto", "auto"),
}


def parse_bench_log(path: str):
    rows = []
    if not os.path.exists(path):
        return rows
    for line in open(path):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return rows


def parse_microbench(path: str):
    rows = []
    if not os.path.exists(path):
        return rows
    shape = None
    for line in open(path):
        m = re.match(r"== (N=\S+ T=\S+ K=\S+ M=\S+ W=\S+) on (\S+) ==", line)
        if m:
            shape = f"{m.group(1)} [{m.group(2)}]"
            continue
        m = re.match(r"(.+?)\s{2,}([\d.]+) ms$", line.rstrip())
        if m and shape:
            rows.append((shape, m.group(1).strip(), float(m.group(2))))
    return rows


def main():
    log_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_recheck"

    print("== bench lines ==")
    # (family, scenario-with-platform) -> {mode: hb/s}
    sweeps: dict[tuple[str, str], dict[str, float]] = {}
    for step, (family, mode) in SWEEP_STEPS.items():
        for d in parse_bench_log(os.path.join(log_dir, f"{step}.log")):
            if d.get("info", "").endswith("sweep"):
                print(f"  [{step}] requested={d.get('requested')} "
                      f"resolved={d.get('resolved', '-')}")
            elif "metric" in d:
                failed = "error" in d
                tag = f"  FAILED: {d['error']}" if failed else ""
                print(f"  [{step}] {d['metric']:45s} {d['value']:>10} "
                      f"{d.get('unit', '')}{tag}")
                if not failed:
                    # keep the [platform] suffix: a mid-run CPU fallback
                    # must never be compared against TPU numbers
                    scen = d["metric"].split("@")[-1]
                    sweeps.setdefault((family, scen), {})[mode] = d["value"]

    print("\n== sweep winners (per family, per scenario+platform) ==")
    auto = {scen: v.get("auto") for (fam, scen), v in sweeps.items()
            if fam == "auto"}
    for (family, scen), by_mode in sorted(sweeps.items()):
        if family == "auto" or not by_mode:
            continue
        ranked = sorted(by_mode.items(), key=lambda kv: -kv[1])
        base = f"; current auto: {auto[scen]}" if auto.get(scen) else ""
        print(f"  {family:12s} {scen:28s} -> {ranked[0][0]} "
              f"({ranked[0][1]} hb/s) of "
              f"{{{', '.join(f'{k}:{v}' for k, v in ranked)}}}{base}")

    print("\n== microbench rankings ==")
    groups: dict[tuple[str, str], list[tuple[str, float]]] = {}
    for log in ("microbench_beacon", "microbench_100k"):
        for shape, label, ms in parse_microbench(
                os.path.join(log_dir, f"{log}.log")):
            fam = ("select" if label.startswith("select") else
                   "edge_gather" if label.startswith("edge_gather") else
                   "msg_gather" if label.startswith("msg gather") else None)
            if fam:
                groups.setdefault((shape, fam), []).append((label, ms))
    for (shape, fam), rows in sorted(groups.items()):
        rows.sort(key=lambda r: r[1])
        print(f"  {shape} {fam}:")
        for i, (label, ms) in enumerate(rows):
            print(f"      {label:44s} {ms:9.3f} ms"
                  f"{' <- winner' if i == 0 else ''}")

    print("\n== next actions ==")
    print("  Flip each family's `auto` branch to its winner on the measured")
    print("  platform: edge gather -> ops/permgather.py resolve_mode;")
    print("  word gather -> resolve_words_mode; selection ->")
    print("  ops/selection.py resolve_selection_mode. Then record the bench")
    print("  table in BASELINE.md and re-run `python bench.py`.")


if __name__ == "__main__":
    main()
