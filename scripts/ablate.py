"""Scanned per-phase timing: each phase runs inside a 10-iteration lax.scan
in ONE jit call, so the remote-TPU per-dispatch latency (~14ms on axon)
amortizes away and the number is the phase's real on-device cost per tick.

Usage: python scripts/ablate.py [scenario] [iters]
  scenario in {1k, 10k_beacon, 50k_churn, 100k_sybil, 100k_sweep, headline_N}
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from go_libp2p_pubsub_tpu.ops.churn import churn_edges, churn_subscriptions
from go_libp2p_pubsub_tpu.ops.gater import gater_decay
from go_libp2p_pubsub_tpu.ops.heartbeat import heartbeat, edge_gather
from go_libp2p_pubsub_tpu.ops.propagate import (
    _edge_forward_mask, _edge_topic_bits, forward_tick, publish)
from go_libp2p_pubsub_tpu.ops.bits import gather_words_rows, n_words
from go_libp2p_pubsub_tpu.ops.score_ops import compute_scores, decay_counters
from go_libp2p_pubsub_tpu.sim import scenarios
from go_libp2p_pubsub_tpu.sim.engine import step


def build(name):
    if name == "1k":
        return scenarios.single_topic_1k()
    if name == "10k_beacon":
        return scenarios.beacon_10k()
    if name == "50k_churn":
        return scenarios.churn_50k()
    if name == "100k_sybil":
        return scenarios.sybil_100k()
    if name == "100k_sweep":
        return scenarios.router_sweep_100k("gossipsub")
    if name.startswith("headline"):
        from __graft_entry__ import _build
        n = int(name.split("_")[1]) if "_" in name else 100_000
        return _build(n_peers=n, k_slots=32, degree=12, msg_window=64,
                      publishers=8)
    raise SystemExit(f"unknown scenario {name}")


def scan_time(fn, state, iters, *, label):
    """fn: (state, key) -> state; time per iteration inside one scan."""

    import numpy as np

    @jax.jit
    def many(st, key):
        def body(c, k):
            # barrier: without it, any phase input the body does not
            # UPDATE is loop-invariant and XLA hoists the phase out of
            # the scan (under-reporting), while closed-over constants
            # hoist the other way — the round-3 microbench fix, applied
            # here too
            c = jax.lax.optimization_barrier(c)
            return fn(c, k), None
        out, _ = jax.lax.scan(body, st, jax.random.split(key, iters))
        return out

    key = jax.random.PRNGKey(0)
    out = many(state, key)            # compile + warm
    np.asarray(out.tick)              # REAL sync: block_until_ready does
    t0 = time.perf_counter()          # not actually block through the
    out = many(state, jax.random.PRNGKey(1))   # axon tunnel
    np.asarray(out.tick)
    dt = (time.perf_counter() - t0 - _fetch_rtt()) / iters
    print(f"{label:28s} {dt*1e3:9.3f} ms/tick", flush=True)
    return dt


_RTT = None


def _fetch_rtt():
    """Measured cost of one dispatch+value-fetch round trip, subtracted
    from every timing (the axon tunnel's is ~66 ms; local backends ~0).
    Measured once at startup rather than hardcoded so the script stays
    correct off the tunnel."""
    global _RTT
    if _RTT is None:
        import numpy as np
        f = jax.jit(lambda: jnp.float32(1.0))
        np.asarray(f())                       # compile + warm
        t0 = time.perf_counter()
        np.asarray(f())
        _RTT = time.perf_counter() - t0
        print(f"(fetch RTT: {_RTT*1e3:.1f} ms — subtracted per run)",
              flush=True)
    return _RTT


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "10k_beacon"
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    cfg, tp, st = build(name)
    n, t, k = st.mesh.shape
    m = cfg.msg_window
    w = n_words(m)
    print(f"== {name}: N={n} T={t} K={k} M={m} W={w} hops={cfg.prop_substeps} "
          f"router={cfg.router} on {jax.devices()[0].platform} ==", flush=True)

    # converge one step so the state is typical
    st = jax.jit(step, static_argnames=("cfg",))(st, cfg, tp,
                                                 jax.random.PRNGKey(42))
    jax.block_until_ready(st)

    scan_time(lambda s, k_: step(s, cfg, tp, k_), st, iters,
              label="FULL step")

    # -- phases (each returns a state so the scan carry stays uniform) --
    def ph_publish(s, k_):
        peers = jax.random.randint(k_, (cfg.publishers_per_tick,), 0, n)
        topics = jnp.zeros((cfg.publishers_per_tick,), jnp.int32)
        return publish(s, cfg, peers, topics, k_)

    scan_time(ph_publish, st, iters, label="publish")
    scan_time(lambda s, k_: decay_counters(s, cfg, tp), st, iters,
              label="decay_counters")

    def ph_scores(s, k_):
        sc = compute_scores(s, cfg, tp)
        return s._replace(behaviour_penalty=s.behaviour_penalty
                          + 0.0 * sc.sum())
    scan_time(ph_scores, st, iters, label="compute_scores")

    def ph_hb(s, k_):
        return heartbeat(s, cfg, tp, k_).state
    scan_time(ph_hb, st, iters, label="heartbeat")

    hb = jax.jit(heartbeat, static_argnames=("cfg",))(
        st, cfg, tp, jax.random.PRNGKey(7))
    jax.block_until_ready(hb)

    def ph_fwd(s, k_):
        return forward_tick(s, cfg, tp, hb.inc_gossip, hb.scores, k_,
                            fwd_send=hb.fwd_send)
    scan_time(ph_fwd, st, iters, label="forward_tick")

    if cfg.churn_disconnect_prob > 0:
        def ph_churn(s, k_):
            return churn_edges(s, cfg, tp, k_, scores_all=hb.scores_all)
        scan_time(ph_churn, st, iters, label="churn_edges")
    if cfg.gater_enabled:
        scan_time(lambda s, k_: gater_decay(s, cfg), st, iters,
                  label="gater_decay")

    # -- forward_tick internals --
    nbr = jnp.clip(st.neighbors, 0, n - 1)

    def ph_gather(s, k_):
        hv = s.have.T                       # seen-set stored packed

        g = gather_words_rows(hv, nbr, m)     # [W,K,N] the per-hop gather
        return s._replace(behaviour_penalty=s.behaviour_penalty
                          + 0.0 * g.sum().astype(jnp.float32))
    scan_time(ph_gather, st, iters, label="1x neighbor word-gather")

    def ph_edge_gather(s, k_):
        eg = edge_gather(s.mesh, s)
        return s._replace(behaviour_penalty=s.behaviour_penalty
                          + 0.0 * eg.sum().astype(jnp.float32))
    scan_time(ph_edge_gather, st, iters, label="1x edge_gather [N,T,K]")

    def ph_fwd_mask(s, k_):
        fm = _edge_forward_mask(s, cfg, k_)
        return s._replace(behaviour_penalty=s.behaviour_penalty
                          + 0.0 * fm.sum().astype(jnp.float32))
    scan_time(ph_fwd_mask, st, iters, label="edge_forward_mask")

    # -- heartbeat internals: the selection kernels at real shapes (CPU
    # profiling shows these dominate the steady-state heartbeat there;
    # this tells us whether the chip agrees) --
    from go_libp2p_pubsub_tpu.ops.selection import select_random, select_top

    def fold(s, x):
        return s._replace(behaviour_penalty=s.behaviour_penalty
                          + 0.0 * x.sum().astype(jnp.float32))

    # scores precomputed OUTSIDE the timed body (hb pattern above) so the
    # phase measures ONLY the selection kernel, not compute_scores
    sc_btk = jax.jit(lambda s: jnp.broadcast_to(
        compute_scores(s, cfg, tp)[:, None, :], (n, t, k)))(st)
    jax.block_until_ready(sc_btk)

    # mode/bounds mirror the engine's own calls (heartbeat.py) so the
    # phase times the formulation the engine actually runs
    def ph_sel_top(s, k_):
        return fold(s, select_top(sc_btk, s.mesh,
                                  jnp.full((n, t), cfg.dscore),
                                  max_count=cfg.dscore,
                                  mode=cfg.selection_mode))
    scan_time(ph_sel_top, st, iters, label="1x select_top [N,T,K]")

    def ph_sel_rand(s, k_):
        return fold(s, select_random(s.mesh, jnp.full((n, t), cfg.d), k_,
                                     max_count=cfg.d,
                                     mode=cfg.selection_mode))
    scan_time(ph_sel_rand, st, iters, label="1x select_random [N,T,K]")

    # -- permutation-gather formulation sweep at real shapes --
    from go_libp2p_pubsub_tpu.ops.permgather import (
        edge_sort_key, resolve_mode, resolve_words_mode)
    sk_w = jax.jit(lambda s: edge_sort_key(
        s.neighbors, s.reverse_slot, k_major=True))(st)
    jax.block_until_ready(sk_w)
    for mode in ("scalar", "rows", "pallas", "sort"):
        rw = resolve_words_mode(mode, w, n, k, have_sort_key=True)
        re_ = resolve_mode(mode, jnp.uint32, n, k, have_sort_key=True)

        def ph_g(s, k_, mode=mode):
            hv = s.have.T                   # seen-set stored packed
            return fold(s, gather_words_rows(hv, nbr, m, mode,
                                             sort_key=sk_w))
        scan_time(ph_g, st, iters,
                  label=f"word-gather[{mode}->{rw}]")

        def ph_e(s, k_, mode=mode):
            return fold(s, edge_gather(s.mesh, s, mode=mode))
        scan_time(ph_e, st, iters,
                  label=f"edge-gather[{mode}->{re_}]")


if __name__ == "__main__":
    main()
