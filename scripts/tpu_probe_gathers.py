"""Honest-methodology on-chip probe of the edge-routing formulations.

Round-4 lesson: through the axon tunnel, ``block_until_ready`` does NOT
block — async-dispatch timing reported 770 TB/s "bandwidth". The only
trustworthy numbers come from chaining the op inside one jit (so its cost
cannot hide in the pipeline) and fetching a VALUE at the end (a real
sync), then subtracting the measured fetch round trip.

This script times, at the 100k headline shape (override: N K M as argv):
  - the XLA gather formulations (2-index, flat 1-index, M-bool rows),
  - the sort-permute apply (1 and 2 payload planes),
  - the hop's non-gather math (prefix/winner/count chain) at uint8 vs
    int32 accumulators — the count_dtype ablation's per-op ground truth.

Run on a live window: python scripts/tpu_probe_gathers.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

ITERS = 20


def fetch_rtt():
    f = jax.jit(lambda: jnp.float32(1.0))
    np.asarray(f())
    t0 = time.perf_counter()
    np.asarray(f())
    return time.perf_counter() - t0


def timed(label, fjit, *args, rtt=0.0):
    r = fjit(*args)
    np.asarray(r).ravel()[0]
    t0 = time.perf_counter()
    r = fjit(*args)
    np.asarray(r).ravel()[0]
    dt = (time.perf_counter() - t0 - rtt) / ITERS
    print(f"{label:52s} {dt * 1e3:9.2f} ms/iter", flush=True)


def chain(body):
    @jax.jit
    def f(x, *rest):
        def b(c, _):
            c = jax.lax.optimization_barrier(c)
            return body(c, *rest), None
        o, _ = jax.lax.scan(b, x, None, length=ITERS)
        return jax.tree.leaves(o)[0].ravel()[:4]
    return f


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    m = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    w = (m + 31) // 32
    print(f"== N={n} K={k} M={m} W={w} on {jax.devices()[0].platform} ==",
          flush=True)
    rtt = fetch_rtt()
    print(f"(fetch RTT {rtt * 1e3:.1f} ms — subtracted)", flush=True)

    from go_libp2p_pubsub_tpu.ops.bits import (
        U32, exclusive_prefix_or, popcount_sum)

    rng = np.random.default_rng(0)
    jn = jnp.asarray(rng.integers(0, n, (n, k)).astype(np.int32))
    rk = jnp.asarray(rng.integers(0, k, (n, k)).astype(np.int32))
    pay = jnp.asarray(
        rng.integers(0, 2**32, (n, k), dtype=np.uint64).astype(np.uint32))
    planes = jnp.asarray(rng.random((n, m)) < 0.3)
    perm = jnp.asarray(rng.permutation(n * k).astype(np.int32))
    allowed = jnp.asarray(
        rng.integers(0, 2**32, (w, k, n), dtype=np.uint64).astype(np.uint32))
    tbw = jnp.asarray(
        rng.integers(0, 2**32, (1, w), dtype=np.uint64).astype(np.uint32))

    timed("gather 2-index payload[jn, rk]",
          chain(lambda c, a, b: c[a, b]), pay, jn, rk, rtt=rtt)
    timed("gather flat payload.ravel()[lin]",
          chain(lambda c, li: c.reshape(-1)[li].reshape(n, k),
                ), pay, (jn * k + rk).reshape(-1), rtt=rtt)
    timed("gather rows planes[nbr] [N,K,M]b",
          chain(lambda c, a: c ^ c[a][:, 0, :]), planes, jn, rtt=rtt)
    timed("sort-permute 1 payload",
          chain(lambda c, p: jax.lax.sort(
              (p, c.reshape(-1)), num_keys=1)[1].reshape(n, k)),
          pay, perm, rtt=rtt)
    timed("sort-permute 2 payloads",
          chain(lambda c, p: (lambda o: (o[1] ^ o[2]).reshape(n, k))(
              jax.lax.sort((p, c.reshape(-1),
                            (c ^ U32(7)).reshape(-1)), num_keys=1))),
          pay, perm, rtt=rtt)

    # hop math chain (no gather): prefix + winners + counts, per acc dtype
    def hop_math(dt):
        def body(f, a):
            offered = jnp.broadcast_to(f[:, None, :], (w, k, n)) & a
            excl = exclusive_prefix_or(offered, axis=1)
            new_from_k = offered & ~excl & ~f[:, None, :]
            cnt = popcount_sum(new_from_k & tbw[0][:, None, None],
                               axis=0, dtype=dt).astype(dt)
            new_any = (excl[:, -1] | offered[:, -1]) & ~f
            return new_any ^ jnp.uint32(cnt.sum(dtype=jnp.uint32) & U32(1))
        return body

    fr = jnp.asarray(
        rng.integers(0, 2**32, (w, n), dtype=np.uint64).astype(np.uint32))
    timed("hop math (uint8 counts)", chain(hop_math(jnp.uint8)),
          fr, allowed, rtt=rtt)
    timed("hop math (int32 counts)", chain(hop_math(jnp.int32)),
          fr, allowed, rtt=rtt)


if __name__ == "__main__":
    main()
