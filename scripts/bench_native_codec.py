"""Is the native C++ trace tensorizer worth its 700 LoC? (VERDICT r3 #7)

The C++ scanner exists for 100k-peer traces at hundreds of MB (SURVEY.md §7
"Host/device boundary in trace replay"). This benchmark builds a synthetic
>= 100 MB encoded TraceEvent stream with a realistic event mix (deliveries,
duplicates, graft/prune, decay boundaries) and measures bytes -> ReplayFeed
throughput both ways:

  python: pb.codec.decode_trace_bytes + trace.replay.tensorize_trace
  native: trace.native.tensorize_bytes (single C++ pass over the bytes)

Prints MB/s for each and the ratio. ROUND4_NOTES.md records the verdict:
the C++ stays only if it is >= 5x at scale.

Usage: python scripts/bench_native_codec.py [target_mb]
(re-execs into a scrubbed-env child: the axon site hook wedges any
in-process jax import while the tunnel is down).
"""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_stream(target_mb: int, n_peers: int = 256, n_topics: int = 4):
    """Synthesize an encoded delimited TraceEvent stream of ~target_mb MB.

    Mix per round (one publisher): 1 PUBLISH + D DELIVERs + 2D DUPLICATEs +
    occasional GRAFT/PRUNE churn; timestamps advance so decay boundaries
    interleave the way a real 1s-heartbeat trace has them.
    """
    from go_libp2p_pubsub_tpu.pb import codec

    peers = [f"peer-{i}" for i in range(n_peers)]
    topics = [f"topic-{i}" for i in range(n_topics)]
    out = bytearray()
    target = target_mb * 1_000_000
    t = 0.0
    rounds = 0
    n_events = 0

    def emit(e):
        nonlocal n_events
        blob = codec.encode_trace_event(e)
        out.extend(codec.write_uvarint(len(blob)))
        out.extend(blob)
        n_events += 1

    while len(out) < target:
        pub = peers[rounds % n_peers]
        topic = topics[rounds % n_topics]
        mid = f"{pub}-m{rounds}"
        t += 0.13
        emit({"type": "PUBLISH_MESSAGE", "peerID": pub, "timestamp": t,
              "publishMessage": {"messageID": mid, "topic": topic}})
        for d in range(12):
            obs = peers[(rounds * 7 + d) % n_peers]
            frm = peers[(rounds * 11 + d) % n_peers]
            emit({"type": "DELIVER_MESSAGE", "peerID": obs, "timestamp": t,
                  "deliverMessage": {"messageID": mid, "topic": topic,
                                     "receivedFrom": frm}})
        for d in range(24):
            obs = peers[(rounds * 5 + d) % n_peers]
            frm = peers[(rounds * 13 + d) % n_peers]
            emit({"type": "DUPLICATE_MESSAGE", "peerID": obs, "timestamp": t,
                  "duplicateMessage": {"messageID": mid, "topic": topic,
                                       "receivedFrom": frm}})
        if rounds % 8 == 0:
            a = peers[(rounds * 3) % n_peers]
            b = peers[(rounds * 3 + 1) % n_peers]
            emit({"type": "GRAFT", "peerID": a, "timestamp": t,
                  "graft": {"peerID": b, "topic": topic}})
            emit({"type": "PRUNE", "peerID": b, "timestamp": t,
                  "prune": {"peerID": a, "topic": topic}})
        rounds += 1
    return bytes(out), rounds, n_events, peers, topics


def child_main(target_mb: int) -> None:
    from go_libp2p_pubsub_tpu.pb import codec
    from go_libp2p_pubsub_tpu.trace import native, tensorize_trace

    t0 = time.perf_counter()
    data, rounds, n_events, peers, topics = build_stream(target_mb)
    mb = len(data) / 1e6
    print(f"stream: {mb:.1f} MB, {n_events} events, {rounds} message ids "
          f"(built in {time.perf_counter() - t0:.1f}s)", flush=True)
    peer_index = {p: i for i, p in enumerate(peers)}
    topic_index = {tp: i for i, tp in enumerate(topics)}
    kw = dict(msg_window=rounds + 1, decay_interval=1.0,
              dup_window=[0.05] * len(topics))

    if not native.available():
        print("native codec NOT available (no toolchain?)", flush=True)
        return

    t0 = time.perf_counter()
    feed_n = native.tensorize_bytes(data, peer_index, topic_index, **kw)
    dt_native = time.perf_counter() - t0

    t0 = time.perf_counter()
    evs = codec.decode_trace_bytes(data)
    dt_decode = time.perf_counter() - t0
    t0 = time.perf_counter()
    feed_p = tensorize_trace(evs, peer_index, topic_index, **kw)
    dt_tensor = time.perf_counter() - t0
    dt_python = dt_decode + dt_tensor

    assert feed_n.op.shape == feed_p.op.shape, "paths disagree on op count"
    import numpy as np
    np.testing.assert_array_equal(feed_n.op, feed_p.op)
    np.testing.assert_array_equal(feed_n.a, feed_p.a)

    print(f"python: {dt_python:7.2f}s  ({mb / dt_python:7.1f} MB/s)  "
          f"[decode {dt_decode:.2f}s + tensorize {dt_tensor:.2f}s]")
    print(f"native: {dt_native:7.2f}s  ({mb / dt_native:7.1f} MB/s)")
    print(f"ratio:  {dt_python / dt_native:.1f}x")


def main() -> None:
    target_mb = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    if os.environ.get("_BENCH_CODEC_CHILD") == "1":
        child_main(target_mb)
        return
    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env
    env = cpu_mesh_env(dict(os.environ))
    env["_BENCH_CODEC_CHILD"] = "1"
    raise SystemExit(subprocess.run(
        [sys.executable, "-u", __file__, str(target_mb)], env=env).returncode)


if __name__ == "__main__":
    main()
