#!/bin/bash
# Tunnel watcher: the axon TPU tunnel has been wedged for two full rounds of
# ~30-min manual polling, so any live window must be captured WITHOUT a human
# (or agent) in the loop. Loop a bounded-subprocess platform probe; on the
# first (alive, n>0, platform=tpu) hit, fire the staged re-measurement
# (scripts/tpu_recheck.sh: microbenches, per-phase ablations, gather/selection
# mode sweeps, full bench) and then one more clean `python bench.py` for the
# record. All output lands under a fixed log dir plus a repo-side results
# directory so the evidence survives the session.
#
# Usage: nohup scripts/tpu_watch.sh >/dev/null 2>&1 &   (or run_in_background)
# Env: TPU_WATCH_SLEEP (secs between probes, default 180),
#      GRAFT_PROBE_TIMEOUT (per-probe budget, default 120),
#      TPU_WATCH_DIR (log dir, default /tmp/tpu_watch),
#      TPU_WATCH_MAX_HOURS (give up after this many hours, default 11).
set -u
cd "$(dirname "$0")/.."
LOGDIR="${TPU_WATCH_DIR:-/tmp/tpu_watch}"
RESULTS="tpu_watch_results"
mkdir -p "$LOGDIR" "$RESULTS"
MAIN_LOG="$LOGDIR/watch.log"
SLEEP_BETWEEN="${TPU_WATCH_SLEEP:-180}"
MAX_HOURS="${TPU_WATCH_MAX_HOURS:-11}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))

log() { echo "[$(date -u +%FT%TZ)] $*" | tee -a "$MAIN_LOG"; }

log "watch start: sleep=${SLEEP_BETWEEN}s probe_timeout=${GRAFT_PROBE_TIMEOUT:-120}s max=${MAX_HOURS}h"

probe_n=0
probe_fail_streak=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  probe_n=$((probe_n + 1))
  # keep probe stderr: a broken watcher (import error, bad PYTHONPATH) must
  # be distinguishable from a dead tunnel, or 11h of window can burn silently
  raw=$(python -c "
from go_libp2p_pubsub_tpu.utils.platform_probe import probe_default_platform_info
alive, n, plat = probe_default_platform_info()
print(f'PROBE {int(alive)} {n} {plat or \"-\"}')" 2>"$LOGDIR/probe_stderr.log")
  probe_rc=$?
  out=$(echo "$raw" | grep '^PROBE' || echo "PROBE 0 0 -")
  read -r _ alive ndev plat <<<"$out"
  log "probe #$probe_n: alive=$alive ndev=$ndev platform=$plat rc=$probe_rc"
  if [ "$probe_rc" -ne 0 ]; then
    probe_fail_streak=$((probe_fail_streak + 1))
    log "probe process FAILED (streak $probe_fail_streak): $(tail -2 "$LOGDIR/probe_stderr.log" | tr '\n' ' ')"
    if [ "$probe_fail_streak" -ge 5 ]; then
      log "ABORT: 5 consecutive probe-process failures — watcher itself is broken, not the tunnel"
      exit 2
    fi
  else
    probe_fail_streak=0
  fi
  if [ "$alive" = "1" ] && [ "$ndev" -ge 1 ] && [ "$plat" = "tpu" ]; then
    log "TUNNEL LIVE ($ndev tpu device(s)) — firing recheck"
    rm -rf /tmp/tpu_recheck   # stale CPU-fallback logs must not pass as TPU evidence
    bash scripts/tpu_recheck.sh 2>&1 | tee -a "$LOGDIR/recheck.log"
    # per-attempt subdir: a mid-run re-wedge falls back to CPU silently, so
    # attempt logs are only promotable to TPU evidence if the platform tag
    # below confirms; until then they carry an UNVERIFIED marker
    attempt="$RESULTS/attempt_$(date -u +%Y%m%dT%H%M%SZ)"
    mkdir -p "$attempt"
    cp -r /tmp/tpu_recheck/. "$attempt/" 2>/dev/null
    log "recheck done — final clean bench for the record"
    # supervised record run (ISSUE 5): the journal lives at a STABLE path
    # so a bench preempted on this hit resumes on the next watch hit
    # (cleared only on success below; journal records carry platform+env
    # fingerprints, so stale CPU-fallback lines can't mask a live window),
    # and bench's SIGTERM flush means the timeout kill below still leaves
    # a complete parseable record
    BENCH_JOURNAL="$RESULTS/bench.journal" \
      timeout 3600 python bench.py 2>&1 | grep -v WARNING | tee "$attempt/bench.log"
    cp "$RESULTS/bench.journal" "$attempt/bench.journal" 2>/dev/null
    if grep -q '"platform": "tpu"' "$attempt/bench.log"; then
      cp "$attempt/bench.log" "$RESULTS/bench_tpu.log"
      rm -f "$RESULTS/bench.journal"   # banked; next session starts fresh
      log "SUCCESS: on-TPU bench captured in $RESULTS/bench_tpu.log (full logs: $attempt)"
      exit 0
    fi
    echo "final bench did not report platform=tpu; recheck step logs may be CPU fallback" \
      > "$attempt/PLATFORM_UNVERIFIED"
    log "bench did not report platform=tpu (window closed mid-run?) — resuming watch"
  fi
  sleep "$SLEEP_BETWEEN"
done
log "watch deadline reached without a live window"
exit 1
