#!/usr/bin/env python
"""Live dashboard over a streaming health journal (sim/telemetry.py).

Tails the fsync'd ``health.jsonl`` a supervised run streams
(``GRAFT_HEALTH_STREAM=path`` / ``SupervisorConfig.health_path``, fleet
and multihost included) and renders the run's vitals without ever
touching the device — the watch-an-unattended-TPU-window tool ROADMAP
item 5 asks for:

- progress: last completed tick / scheduled ticks, chunk cadence
- throughput: heartbeats/sec from consecutive chunk markers' wall stamps
  (recent median), a number comparable to bench.py's metric lines
- delivery fraction per topic (+ sparkline of the recent trend)
- mesh degree min/mean/max, backoff + graylist census, score mean/min
- the decoded ``fault_flags`` health word (a poisoned run shows its
  VIOLATION bits here the moment the chunk that lit them lands)
- checkpoint ticks and crash markers (post-mortem starts here: the crash
  line names the dump directory ``scripts/replay_crash.py`` replays)
- live contract verdicts (ISSUE 20): rendered from the run's journaled
  ``contract_verdict`` notes when the supervisor carries monitors —
  O(new bytes), deduped by deterministic id — with a CONTRACT BREACH /
  VERDICT ABORT banner on failure; runs that stamp contracts but journal
  no verdicts fall back to the tailer's own incremental monitors, and
  pre-PR journals to full re-evaluation over the visible rows
- fleet journals: per-member summary (worst delivery / tripped flags)
- multihost journals: per-rank heartbeat age, relaunch count, degrade
  rung, and a DEAD-RANK banner with the mh_supervisor resume command
  (parallel/resilience.py heartbeats in the run's shared --run-dir)

Usage:
    python scripts/dashboard.py HEALTH_JSONL            # live (2s refresh)
    python scripts/dashboard.py HEALTH_JSONL --once     # one snapshot
    python scripts/dashboard.py HEALTH_JSONL --once --json   # machine form

The journal is read tolerantly (``telemetry.read_journal``): torn tail
lines from a kill mid-append are skipped, resumed runs dedup by tick.
Exit: 0 on a readable journal (even mid-run), 1 when the file never
appears within ``--wait`` seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the dashboard is a host-only tool: it must never grab the (exclusive,
# wedgeable) remote TPU just to pretty-print a journal
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_SPARK = " ▁▂▃▄▅▆▇█"


def _decode_flags(flags, version=None):
    if not flags:
        return []
    try:
        from go_libp2p_pubsub_tpu.sim.invariants import decode_flags
        return decode_flags(int(flags), flags_version=version)
    except ValueError as e:
        # the journal header stamps which fault_flags bit layout wrote it
        # (flags_version); a word from another layout is REFUSED by name —
        # rendering it through the current table would misread moved bits
        return [f"UNDECODABLE({e})"[:140]]
    except Exception:
        return [f"0x{int(flags):x}"]


def _sparkline(vals, width: int = 40) -> str:
    if not vals:
        return ""
    vals = vals[-width:]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(_SPARK[int((v - lo) / span * (len(_SPARK) - 1))]
                   for v in vals)


def _bytes(n) -> str:
    """Human bytes for the pricing block (GiB/MiB/KiB to one decimal)."""
    for unit, width in (("GiB", 2 ** 30), ("MiB", 2 ** 20), ("KiB", 2 ** 10)):
        if n >= width:
            return f"{n / width:.1f}{unit}"
    return f"{int(n)}B"


def _topic_fracs(row: dict) -> list:
    out = []
    t = 0
    while f"delivery_frac_t{t}" in row:
        out.append(row[f"delivery_frac_t{t}"])
        t += 1
    return out


def _hbps(chunks: list, window: int = 8):
    """Recent heartbeats/sec from consecutive chunk markers: prefer each
    marker's ``done_wall`` — stamped when the chunk's DEVICE result was
    confirmed — over ``wall`` (stamped at journal append). Under the
    async supervisor the writer thread appends markers in bursts whenever
    its queue drains, so append-time deltas alias to ~0 or the whole
    burst; dispatch-complete deltas price the device work itself. Old
    journals (no ``done_wall`` field) fall back to ``wall`` per stamp.
    ``rows`` is member-ticks (ticks × active members under fleet, ==
    ticks unbatched), so the number is the AGGREGATE rate — comparable
    to bench.py's metric lines, fleet included. Median of the last few
    deltas."""
    rates = []
    for a, b in list(zip(chunks, chunks[1:]))[-window:]:
        dt = (b.get("done_wall") or b.get("wall", 0)) \
            - (a.get("done_wall") or a.get("wall", 0))
        ticks = b.get("rows") or b.get("ticks") or 0
        if dt > 0 and ticks:
            rates.append(ticks / dt)
    if not rates:
        return None
    rates.sort()
    return rates[len(rates) // 2]


class _Tailer:
    """Incremental journal reader for live mode: O(new bytes) per poll
    and bounded memory regardless of run length — a multi-day unattended
    window's journal grows one row per member-tick, and re-parsing the
    whole file every refresh would lag the interval and grow RSS without
    bound. Keeps exactly the bounded recent window the render uses."""

    MAX_ROWS = 4096

    def __init__(self, path: str):
        import collections
        self.path = path
        self.offset = 0
        self.buf = b""
        self.runs: list = []
        self.chunks = collections.deque(maxlen=64)
        self.chunk_count = 0
        self.notes = collections.deque(maxlen=256)
        self.rows = collections.OrderedDict()
        # live contract verdict plane (ISSUE 20): journaled
        # contract_verdict notes dedup by their deterministic id (a
        # relaunch may re-derive a transition the killed run already
        # journaled — it must render exactly once), and when a run
        # stamps contracts but journals no verdicts (pre-PR journals)
        # the tailer folds rows into its own incremental monitors —
        # O(1) per row instead of the old O(all rows) per refresh
        self.verdicts: dict = {}
        self._mon: tuple | None = None

    def poll(self) -> None:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size < self.offset:              # truncated/rotated: restart
            self.offset, self.buf = 0, b""
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            data = self.buf + f.read()
            self.offset = f.tell()
        lines = data.split(b"\n")
        self.buf = lines.pop()              # torn tail rides to next poll
        for ln in lines:
            if not ln.strip():
                continue
            try:
                d = json.loads(ln)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            kind = d.get("kind")
            if kind == "health":
                key = (d.get("member", -1), d.get("tick"))
                self.rows.pop(key, None)    # resume overlap: last wins
                self.rows[key] = d
                while len(self.rows) > self.MAX_ROWS:
                    self.rows.popitem(last=False)
                self._fold_live(d)
            elif kind == "run":
                self.runs = self.runs[-7:] + [d]
            elif kind == "chunk":
                self.chunks.append(d)
                self.chunk_count += 1
            elif kind == "contract_verdict" and d.get("id"):
                self.verdicts.setdefault(d["id"], d)
            else:
                self.notes.append(d)

    def _fold_live(self, row: dict) -> None:
        """Tailer-side incremental contract monitors: the live fallback
        for journals whose run stamps ``contracts`` but whose supervisor
        journals no verdict notes. One O(1) fold per NEW row — resume
        overlap (a re-sent tick) and fleet journals (per-member streams
        need the batch path) are skipped."""
        if self.verdicts or row.get("member", -1) != -1:
            return
        run = self.runs[-1] if self.runs else None
        specs = run.get("contracts") if run else None
        if not specs:
            return
        key = json.dumps(specs, sort_keys=True)
        if self._mon is None or self._mon[0] != key:
            try:
                from go_libp2p_pubsub_tpu.sim import adversary
                mons = adversary.ContractMonitors(
                    adversary.contracts_from_json(specs))
            except Exception:
                mons = None     # render falls back to batch evaluation
            self._mon = (key, mons, -1)
        key0, mons, last = self._mon
        tick = row.get("tick", -1)
        if mons is None or tick <= last:
            return
        mons.fold_rows([row])
        self._mon = (key0, mons, tick)

    def journal(self) -> dict:
        return {"runs": self.runs, "chunks": list(self.chunks),
                "notes": list(self.notes),
                "verdicts": list(self.verdicts.values()),
                "live_monitors": self._mon[1] if self._mon else None,
                "rows": sorted(self.rows.values(),
                               key=lambda r: (r.get("tick", 0),
                                              r.get("member", -1))),
                "chunks_total": self.chunk_count}


def snapshot(path: str) -> dict:
    """One machine-readable view of the journal (the --json form; the
    text renderer formats exactly this). Reads the whole file — the
    --once path; live mode feeds :func:`_snapshot_of` from a bounded
    incremental :class:`_Tailer` instead."""
    from go_libp2p_pubsub_tpu.sim.telemetry import read_journal

    return _snapshot_of(read_journal(path), path)


def _snapshot_of(j: dict, path: str) -> dict:
    rows = j["rows"]
    run = j["runs"][-1] if j["runs"] else {}
    # terminal markers count only AFTER the newest run header: a resumed
    # run must not inherit its previous window's run_end/window_end
    run_wall = run.get("wall", 0)
    current = [n for n in j["notes"] if n.get("wall", 0) >= run_wall]
    snap: dict = {
        "path": path,
        "run": {k: run.get(k) for k in ("scenario", "n_peers", "n_topics",
                                        "n_ticks", "invariant_mode",
                                        "plane", "group", "member_names")
                if run.get(k) is not None},
        "chunks": j.get("chunks_total", len(j["chunks"])),
        "rows": len(rows),
        "hbps": _hbps(j["chunks"]),
        "checkpoints": [n.get("tick", n.get("done"))
                        for n in j["notes"] if n.get("kind") == "checkpoint"],
        "crashes": [{"tick": n.get("tick"), "dump": n.get("dump"),
                     "error": n.get("error")}
                    for n in current if n.get("kind") == "crash"],
        "done": any(n.get("kind") == "run_end" for n in current),
        # a bounded TPU window stopped cleanly and will resume the same
        # schedule (supervisor max_chunks) — live-tail keeps tailing
        "paused": any(n.get("kind") == "window_end" for n in current),
    }
    _attach_liveness(snap, run)
    _attach_launcher(snap, j)
    _attach_ingest(snap, current)
    if not rows:
        return snap
    members = sorted({r.get("member", -1) for r in rows})
    fleet = members != [-1]
    last_tick = max(r["tick"] for r in rows)
    latest = [r for r in rows if r["tick"] == last_tick]
    head = latest[0]
    fracs = [_topic_fracs(r) for r in latest]
    flat = [f for fr in fracs for f in fr]
    snap.update({
        "tick": last_tick,
        "fleet_members": len(members) if fleet else None,
        "delivery_frac": (sum(flat) / len(flat)) if flat else None,
        "delivery_frac_topics": fracs[0] if not fleet else None,
        "mesh_deg": {k: head.get(f"mesh_deg_{k}")
                     for k in ("min", "mean", "max")},
        "backoff_count": head.get("backoff_count"),
        "graylist_count": head.get("graylist_count"),
        "score_mean": head.get("score_mean"),
        "score_min": head.get("score_min"),
        "published_window": head.get("published_window"),
        "delivered_total": head.get("delivered_total"),
        "halo_overflow": max((r.get("halo_overflow") or 0) for r in latest),
        "fault_flags": None if head.get("fault_flags") is None else
        int(max((r.get("fault_flags") or 0) for r in latest)),
    })
    if snap["run"].get("invariant_mode") == "off":
        # the numeric row schema streams 0 when the sentinel is off, but
        # an untracked run must never read as verified-clean (the same
        # not-tracked ≠ clean rule run_traced's None flags encode)
        snap["fault_flags"] = None
    snap["fault_flag_names"] = _decode_flags(snap["fault_flags"],
                                             version=run.get("flags_version"))
    _attach_verdicts(snap, j, current)
    _attach_attacks(snap, run, rows)
    # recent trend for the sparkline: mean delivery per tick
    trend: dict = {}
    for r in rows:
        fr = _topic_fracs(r)
        if fr:
            trend.setdefault(r["tick"], []).append(sum(fr) / len(fr))
    snap["trend"] = [sum(v) / len(v)
                     for _t, v in sorted(trend.items())[-60:]]
    if fleet:
        worst = min(latest,
                    key=lambda r: (sum(_topic_fracs(r)) /
                                   max(len(_topic_fracs(r)), 1)))
        wf = _topic_fracs(worst)
        snap["worst_member"] = {
            "member": worst.get("member"),
            "delivery_frac": sum(wf) / len(wf) if wf else None,
            "fault_flags": worst.get("fault_flags")}
    return snap


def _attach_liveness(snap: dict, run: dict) -> None:
    """Multihost resilience view (parallel/resilience.py): a run launched
    with a ``--run-dir`` stamps ``mh_run_dir`` (+ rung/relaunch
    provenance) into its health header; the dashboard reads the shared
    directory's heartbeat files and ``mh_journal.jsonl`` live — per-rank
    heartbeat age, relaunch count, the current degrade rung, and a
    DEAD-RANK banner carrying the mh_supervisor resume command."""
    run_dir = run.get("mh_run_dir")
    if not run_dir or not os.path.isdir(run_dir):
        return
    procs = run.get("processes")
    now = time.time()
    ranks = []
    for name in sorted(os.listdir(run_dir)):
        m = re.match(r"hb_rank(\d+)\.json$", name)
        if not m:
            continue
        r = int(m.group(1))
        if isinstance(procs, int) and r >= procs:
            continue    # stale heartbeat from an earlier, larger attempt
        try:
            with open(os.path.join(run_dir, name)) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue    # torn mid-rename read: next refresh gets it
        ranks.append({"rank": r,
                      "age_s": round(now - float(d.get("wall", 0.0)), 1),
                      "tick": d.get("tick"), "chunk": d.get("chunk"),
                      "done": bool(d.get("done"))})
    mh: dict = {"ranks": ranks,
                "relaunches": run.get("mh_relaunches", 0),
                "rung": run.get("mh_rung", 0)}
    jpath = os.path.join(run_dir, "mh_journal.jsonl")
    if os.path.exists(jpath):
        recs = []
        try:
            with open(jpath) as f:
                for ln in f:
                    try:
                        recs.append(json.loads(ln))
                    except ValueError:
                        pass        # torn tail line mid-append
        except OSError:
            pass
        attempts = [r for r in recs if r.get("kind") == "mh_attempt"]
        if attempts:
            mh["relaunches"] = max(mh["relaunches"], len(attempts) - 1)
            mh["rung"] = attempts[-1].get("rung", mh["rung"])
        head = next((r for r in recs if r.get("kind") == "mh_run"), None)
        if head and head.get("resume_cmd"):
            mh["resume_cmd"] = head["resume_cmd"]
    timeout = run.get("mh_peer_timeout_s") or 30.0
    # a finished run's ranks stopped beating LEGITIMATELY — no banner
    mh["dead_ranks"] = [] if snap.get("done") else [
        r["rank"] for r in ranks
        if not r["done"] and r["age_s"] > float(timeout)]
    snap["mh"] = mh


def _attach_launcher(snap: dict, j: dict) -> None:
    """Multihost-launcher view (scripts/run_multihost.py ``--journal``):
    the launcher leads its journal with the run header — engine, process
    and device counts, and for the row-sharded bucketed engine the
    per-(bucket x shard) byte pricing the HBM gate computed closed-form.
    The dashboard renders THAT accounting instead of re-deriving a dense
    [N, K] estimate it can't get right for bucketed layouts. The
    launcher's metric line (same journal) supplies hb/s and delivery for
    engines that refuse the health stream (bucketed: sim/telemetry reads
    the dense planes)."""
    head = next((n for n in reversed(j["notes"])
                 if n.get("info") == "multihost run"), None)
    if head is None:
        return
    snap["launcher"] = {k: head.get(k) for k in (
        "scenario", "engine", "n_peers", "processes", "devices",
        "topology", "state_precision", "state_nbytes_per_shard",
        "bucket_shards") if head.get(k) is not None}
    for k in ("scenario", "n_peers"):
        if snap["run"].get(k) is None and head.get(k) is not None:
            snap["run"][k] = head[k]
    metric = next((n for n in reversed(j["notes"])
                   if "metric" in n and "hbps" in n), None)
    if metric is not None:
        snap["launcher"]["hbps"] = metric.get("hbps")
        snap["launcher"]["delivery_fraction"] = \
            metric.get("delivery_fraction")
        snap["launcher"]["resumed_from"] = metric.get("resumed_from")


def _render_launcher(snap: dict, out: list) -> None:
    la = snap.get("launcher")
    if not la:
        return
    line = f"  engine {la.get('engine', 'dense')}"
    if la.get("processes"):
        line += f"   procs {la['processes']}"
    if la.get("devices"):
        line += f"   devices {la['devices']}"
    if la.get("state_nbytes_per_shard") is not None:
        line += f"   state/shard {_bytes(la['state_nbytes_per_shard'])}"
    out.append(line)
    for b, e in enumerate(la.get("bucket_shards") or []):
        per = sum(v for k, v in e.items() if k not in ("rows", "k_ceil"))
        out.append(f"    bucket b{b} {e['rows']}x{e['k_ceil']}: "
                   f"{_bytes(per)}/shard")
    if la.get("hbps") is not None:
        line = f"  launcher hb/s {la['hbps']}"
        if la.get("delivery_fraction") is not None:
            line += f"   delivery {la['delivery_fraction']}"
        if la.get("resumed_from") is not None:
            line += f"   resumed@{la['resumed_from']}"
        out.append(line)


def _attach_verdicts(snap: dict, j: dict, current: list) -> None:
    """Live contract verdict view (ISSUE 20), in preference order:

    1. journaled ``contract_verdict`` notes (the supervisor's monitors
       already judged the stream — O(new bytes): latest status per
       contract by seq, deduped by deterministic id by the tailer /
       ``telemetry.read_journal``);
    2. the tailer's own incremental monitors (runs that stamp contracts
       but journal no verdicts);
    3. nothing here — ``_attach_attacks`` falls back to full
       re-evaluation over the visible rows (pre-PR journals, fleet).

    Also surfaces the ``verdict_abort``/``contract_alarm`` teardown and
    breach markers for the render banners."""
    verd = j.get("verdicts")
    if verd is None:        # read_journal path: notes, already deduped
        verd = [n for n in j["notes"]
                if n.get("kind") == "contract_verdict"]
    if verd:
        latest: dict = {}
        for v in verd:
            i = v.get("contract", 0)
            if i not in latest or v.get("seq", 0) >= \
                    latest[i].get("seq", 0):
                latest[i] = v
        snap["contracts"] = [
            # note dicts carry the contract's kind as contract_kind
            # ("kind" is the note's own type tag, contract_verdict)
            {"kind": v.get("contract_kind"), "status": v.get("status"),
             "detail": v.get("detail"), "tick": v.get("tick"),
             "source": "journal"}
            for _i, v in sorted(latest.items())]
    else:
        mons = j.get("live_monitors")
        if mons is not None:
            snap["contracts"] = [
                {"kind": r.kind, "status": r.status, "detail": r.detail,
                 "source": "monitor"}
                for r in mons.results(final=bool(snap.get("done")))]
    abort = next((n for n in reversed(current)
                  if n.get("kind") == "verdict_abort"), None)
    if abort is not None:
        snap["verdict_abort"] = {
            "contract": abort.get("contract"),
            "kind": abort.get("contract_kind"),
            "tick": abort.get("tick"), "detail": abort.get("detail")}
    if any(n.get("kind") == "contract_alarm" for n in current):
        snap["contract_alarm"] = True


def _attach_attacks(snap: dict, run: dict, rows: list) -> None:
    """Attack-scenario view (ISSUE 10): the run header stamps its
    ``attack_windows`` schedule (sim/telemetry.py header) and optionally
    its declared ``contracts`` (SupervisorConfig.health_meta); the
    dashboard marks which windows cover the newest tick and evaluates
    the contracts over the visible rows — ``pending`` while a decision
    tick is still ahead, final once the run ended/crashed. Live mode's
    tailer keeps a bounded recent row window, so a long-scrolled-past
    delivery dip may age out of the live view; ``--once`` reads the
    whole journal and judges the full stream."""
    windows = run.get("attack_windows")
    if not windows:
        return
    tick = snap.get("tick", -1)
    snap["attacks"] = [dict(w, active=(w["start"] <= tick
                                       and (w["end"] is None
                                            or tick < w["end"])))
                       for w in windows]
    if "contracts" in snap:
        # the verdict plane already judged the stream (journaled notes or
        # the tailer's incremental monitors) — never re-evaluate O(rows)
        return
    final = bool(snap.get("done") or snap.get("crashes"))
    try:
        from go_libp2p_pubsub_tpu.sim import adversary
        if run.get("contracts"):
            contracts = adversary.contracts_from_json(run["contracts"])
        else:
            contracts = adversary.contracts_from_schedule(windows)
        members = sorted({r.get("member", -1) for r in rows})
        out = []
        for c in contracts:
            per = [c.evaluate(adversary.member_rows(rows, m), final=final)
                   for m in members]
            worst = next((r for r in per if r.status == "fail"),
                         next((r for r in per if r.status == "pending"),
                              per[0]))
            out.append({"kind": worst.kind, "status": worst.status,
                        "detail": worst.detail})
        snap["contracts"] = out
    except Exception as e:           # the dashboard must render anyway
        snap["contracts"] = [{"kind": "error", "status": "fail",
                              "detail": f"contract evaluation failed: {e}"}]


def _attach_ingest(snap: dict, notes: list) -> None:
    """Live command plane view (sim/commands.py): the per-chunk
    ``ingest`` markers carry queue depth, lag, shed and the consumed
    stream offset (telemetry.INGEST_COLUMNS); an ``ingest_stalled``
    marker opens a coast episode and carries the producer-restart
    command the COASTING banner surfaces (the DEAD-RANK pattern)."""
    last = next((n for n in reversed(notes)
                 if n.get("kind") == "ingest"), None)
    if last is None:
        return
    ing = {k: last.get(k) for k in
           ("tick", "directives", "shed", "shed_total", "refused_total",
            "queue_depth", "lag_ticks", "offset", "coasting")}
    if ing.get("coasting"):
        stall = next((n for n in reversed(notes)
                      if n.get("kind") == "ingest_stalled"), None)
        if stall is not None:
            ing["stalled_tick"] = stall.get("tick")
            ing["source"] = stall.get("source")
            ing["resume_cmd"] = stall.get("resume_cmd")
    snap["ingest"] = ing


def _render_ingest(snap: dict, out: list) -> None:
    """The ingest-health block (``_attach_ingest``) — shared by the
    normal render path and the no-health-rows-yet early return."""
    ing = snap.get("ingest")
    if not ing:
        return
    out.append(f"  ingest q {ing.get('queue_depth', 0)}"
               f"   lag {ing.get('lag_ticks', 0)} ticks"
               f"   shed {ing.get('shed_total', 0)}"
               f"   refused {ing.get('refused_total', 0)}"
               f"   offset {ing.get('offset', 0)}")
    if ing.get("coasting") and not snap.get("done"):
        out.append(f"  COASTING: directive ingest stalled @ tick "
                   f"{ing.get('stalled_tick', ing.get('tick'))} — chip "
                   "stepping with empty frames; restart the producer "
                   f"from offset {ing.get('offset', 0)}")
        if ing.get("resume_cmd"):
            out.append(f"    resume: {ing['resume_cmd']}")


def _render_mh(snap: dict, out: list) -> None:
    """The multihost rank-liveness block (``_attach_liveness``) — shared
    by the normal render path and the no-health-rows-yet early return."""
    if not snap.get("mh"):
        return
    mh = snap["mh"]
    if mh.get("ranks"):
        out.append("  ranks " + "  ".join(
            f"r{r['rank']}:" + ("done" if r["done"]
                                else f"t{r['tick']} {r['age_s']:.0f}s")
            for r in mh["ranks"]))
    out.append(f"  relaunches {mh.get('relaunches', 0)}   "
               f"degrade rung {mh.get('rung', 0)}")
    for r in mh.get("dead_ranks", []):
        out.append(f"  DEAD RANK {r}: heartbeat stale — group "
                   "relaunch required")
    if mh.get("dead_ranks") and mh.get("resume_cmd"):
        out.append(f"    resume: {mh['resume_cmd']}")


def render(snap: dict) -> str:
    out = []
    run = snap.get("run", {})
    title = run.get("scenario") or os.path.basename(snap["path"])
    shape = f"{run.get('n_peers', '?')} peers"
    if snap.get("fleet_members"):
        shape += f" x {snap['fleet_members']} members"
    status = "ENDED" if snap.get("done") else (
        "CRASHED" if snap.get("crashes") else
        "PAUSED (resumable)" if snap.get("paused") else "live")
    out.append(f"== graft telemetry :: {title} ({shape}) [{status}] ==")
    ds = run.get("degree_stats")
    if ds:
        # heavy-tailed underlays: the run header states the graph shape
        # every number below was measured on (sim/topology.degree_stats)
        out.append(f"  underlay degree min/mean/p99/max "
                   f"{ds.get('min')}/{ds.get('mean')}/{ds.get('p99')}/"
                   f"{ds.get('max')}   gini {ds.get('gini')}")
    elif run.get("degree_buckets"):
        out.append("  degree buckets " + " ".join(
            f"{nb}x{kb}" for nb, kb in run["degree_buckets"]))
    if "tick" not in snap:
        # a first-chunk crash journals no health rows — the crash pointer
        # (the post-mortem entry point) must still render, and so must the
        # rank-liveness block: a rank that dies during init/compile is
        # exactly the DEAD-RANK-banner case
        out.append("  (no health rows yet)")
        _render_launcher(snap, out)
        _render_mh(snap, out)
        _render_ingest(snap, out)
        for c in snap.get("crashes", []):
            out.append(f"  CRASH @ tick {c.get('tick')}: {c.get('error')}")
            out.append(f"    replay: python scripts/replay_crash.py "
                       f"{c.get('dump')}")
        return "\n".join(out)
    n_ticks = run.get("n_ticks")
    prog = f"tick {snap['tick'] + 1}"
    if isinstance(n_ticks, int):
        prog += f" / {n_ticks}"
    elif isinstance(n_ticks, list):
        prog += f" / {max(n_ticks)}"
    hb = snap.get("hbps")
    out.append(f"  {prog}   chunks {snap['chunks']}   "
               f"hb/s {hb:.2f}" if hb else f"  {prog}   "
               f"chunks {snap['chunks']}   hb/s ?")
    df = snap.get("delivery_frac")
    line = f"  delivery {df:.4f}" if df is not None else "  delivery ?"
    if snap.get("delivery_frac_topics") and \
            len(snap["delivery_frac_topics"]) > 1:
        line += " [" + " ".join(f"{f:.3f}"
                                for f in snap["delivery_frac_topics"]) + "]"
    out.append(line + "   " + _sparkline(snap.get("trend", [])))
    def num(key, spec=""):
        # a partial or degraded row may miss columns; render "?" rather
        # than crash the one tool meant to survive degraded runs
        v = snap.get(key)
        return "?" if v is None else format(v, spec)

    deg = snap.get("mesh_deg", {})
    out.append(f"  mesh degree min/mean/max "
               f"{deg.get('min')}/{deg.get('mean'):.2f}/{deg.get('max')}"
               if deg.get("mean") is not None else "  mesh degree ?")
    out.append(f"  backoff {num('backoff_count')}   "
               f"graylist {num('graylist_count')}   "
               f"score mean/min {num('score_mean', '.3f')}/"
               f"{num('score_min', '.3f')}")
    out.append(f"  window msgs {num('published_window')}   "
               f"delivered(total) {num('delivered_total', '.0f')}   "
               f"halo_overflow {num('halo_overflow')}")
    ff = snap.get("fault_flags")
    if ff is None:
        out.append("  flags: (invariants off)")
    elif ff:
        out.append(f"  flags: 0x{ff:x} " + " ".join(
            snap.get("fault_flag_names", [])))
    else:
        out.append("  flags: clean")
    if snap.get("worst_member"):
        w = snap["worst_member"]
        out.append(f"  worst member #{w['member']}: "
                   f"delivery {w['delivery_frac']:.4f} "
                   f"flags {w['fault_flags']}")
    if snap.get("attacks"):
        live = [w for w in snap["attacks"] if w["active"]]
        sched = [w for w in snap["attacks"] if not w["active"]]
        for w in live:
            end = "∞" if w["end"] is None else w["end"]
            out.append(f"  ATTACK {w['kind']} [{w['start']}, {end}) ACTIVE")
        if sched:
            out.append("  attacks scheduled: " + ", ".join(
                f"{w['kind']}@{w['start']}" for w in sched[:6]))
    for c in snap.get("contracts", []):
        mark = {"pass": "ok", "fail": "FAIL", "pending": "…"}[
            c["status"]] if c["status"] in ("pass", "fail", "pending") \
            else c["status"]
        out.append(f"  contract {c['kind']}: {mark} — {c['detail']}")
    if any(c.get("status") == "fail" for c in snap.get("contracts", [])) \
            and not snap.get("verdict_abort"):
        out.append("  CONTRACT BREACH: a live contract FAILED — verdict "
                   "journaled at the chunk boundary (run continues under "
                   "its verdict policy)")
    if snap.get("verdict_abort"):
        va = snap["verdict_abort"]
        out.append(f"  VERDICT ABORT: contract {va.get('kind')} FAILED @ "
                   f"tick {va.get('tick')} — run tore down at the chunk "
                   "boundary; restore from the last checkpoint")
        if va.get("detail"):
            out.append(f"    {va['detail']}")
    if snap.get("checkpoints"):
        out.append("  checkpoints @ " + ", ".join(
            str(t) for t in snap["checkpoints"][-4:]))
    _render_launcher(snap, out)
    _render_mh(snap, out)
    _render_ingest(snap, out)
    for c in snap.get("crashes", []):
        out.append(f"  CRASH @ tick {c.get('tick')}: {c.get('error')}")
        out.append(f"    replay: python scripts/replay_crash.py "
                   f"{c.get('dump')}")
    return "\n".join(out)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("journal", help="health.jsonl path (GRAFT_HEALTH_STREAM)")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (test/script mode)")
    ap.add_argument("--json", action="store_true",
                    help="emit the snapshot as one JSON object")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="live-mode refresh seconds (default 2)")
    ap.add_argument("--wait", type=float, default=0.0,
                    help="seconds to wait for the journal to appear")
    args = ap.parse_args()

    deadline = time.time() + args.wait
    while not os.path.exists(args.journal):
        if time.time() >= deadline:
            print(f"no journal at {args.journal}", file=sys.stderr)
            return 1
        time.sleep(0.2)

    if args.once:
        snap = snapshot(args.journal)
        try:
            print(json.dumps(snap) if args.json else render(snap),
                  flush=True)
        except BrokenPipeError:         # `... --once | head` is fine
            os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    tailer = _Tailer(args.journal)
    try:
        while True:
            tailer.poll()
            snap = _snapshot_of(tailer.journal(), args.journal)
            body = json.dumps(snap) if args.json else render(snap)
            sys.stdout.write("\x1b[2J\x1b[H" + body + "\n")
            sys.stdout.flush()
            if snap.get("done") or snap.get("crashes"):
                return 0            # run over: leave the last frame up
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
