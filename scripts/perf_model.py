"""Roofline model of the batched engine's per-tick HBM traffic on TPU v5e.

Every kernel in this engine is memory-bound (elementwise/bitwise passes,
gathers, tiny reductions — no MXU work), so the per-tick cost model is
bytes-touched / HBM bandwidth. This script enumerates, phase by phase, the
HBM bytes each design variant touches per tick at a given shape, converts
them to v5e time (819 GB/s), and prints the implied heartbeats/sec — the
number BASELINE.md wants at >= 1000 for the 100k-peer headline config.

Designs modeled:
  current  — what ships today under TPU `auto` modes: `rows` gathers
             (the [N,K,K] / [N,K,M] vector-DMA temporaries that round-2
             measured 2.5x over scalar), associative-scan prefix-OR in the
             hop loop, five [W,K,N] bit-set accumulators.
  planned  — the surgery this model justifies: VMEM-resident Pallas gathers
             (payload tables are <= a few MB packed), a fused Pallas hop
             kernel (gather + K-prefix + per-slot event counts in one pass),
             int8 per-slot count accumulators (events per (t,k,n) per tick
             are bounded by the message window M < 128), and the decay pass
             fused with the score pass.

Cross-check: --cost-analysis compiles each phase on the CURRENT backend and
prints XLA's own bytes-accessed estimate next to the analytic number. On CPU
the lowering differs (scalar gathers, no rows temporaries), so the check
validates the *inventory* (which arrays a phase touches), not the TPU total.

Usage: python scripts/perf_model.py [scenario] [--cost-analysis] [--sharded N]

--sharded N prints the v5e-N projection for the landed design: per-device
HBM traffic is total/N (every [N, ...] array shards on the peer axis), plus
the cross-device exchange the shard_map-wrapped Pallas kernels pay — the
replicated packed lookup tables (parallel/kernel_context.py), one small
all-gather per kernel call. BASELINE.md specifies the 1000 hb/s bar on
v5e-8, so --sharded 8 is the number that answers it.
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_HBM_GBPS = 819.0          # v5e HBM bandwidth per chip
V5E_MS_PER_GB = 1e3 / (V5E_HBM_GBPS)


def fmt_mb(b):
    return b / 1e6


class Phase:
    def __init__(self, name, items):
        self.name = name
        self.items = items                    # list[(label, bytes)]

    @property
    def total(self):
        return sum(b for _, b in self.items)


def model(n, k, t, m, w, hops, p, design, *, gated_selections=2):
    """Per-tick phase inventory. All counts in bytes touched in HBM.

    Conventions: an elementwise pass fused by XLA touches each distinct
    input once (r) and each output once (w). Gathers touch their index
    arrays, their materialized output, and — in `rows` mode — the
    [rows, K]-shaped temporary twice (w+r). int8 counts in `planned`.
    """
    f = 4                                      # f32/i32/u32 itemsize
    b_ntk = n * t * k                          # bool plane
    b_ntk4 = f * n * t * k
    b_nk4 = f * n * k
    b_nk1 = n * k
    b_wkn = f * w * k * n
    b_wn = f * w * n
    b_nm1 = n * m
    b_nm4 = f * n * m
    b_nkk4 = f * n * k * k                     # rows permgather temporary
    b_nkm1 = n * k * m                         # rows words-gather temporary

    def permgather_packed(calls):
        """edge_gather_packed: pack + [N,K] u32 permutation gather + unpack."""
        if design == "current":                # rows: [N,K,K] temp w+r
            per = (b_ntk * 2          # read masks to pack (~2 planes avg)
                   + b_nk4            # write packed payload
                   + b_nk4 * 2        # read jn, rk
                   + b_nkk4 * 2       # rows temporary write+read
                   + b_nk4            # gathered output
                   + b_ntk * 2)       # unpack to bool planes
        else:                                  # pallas: table in VMEM
            per = (b_ntk * 2 + b_nk4   # pack (table read once from HBM)
                   + b_nk4 * 2         # indices
                   + b_nk4             # output
                   + b_ntk * 2)        # unpack
        return calls * per

    def words_gather(calls):
        """gather_words: [W,N] table -> [W,K,N] per-edge windows."""
        if design == "current":                # rows: unpack + [N,K,M] temp
            per = (b_wn + b_nm1        # unpack table to [N,M] bool
                   + b_nk4             # read nbr
                   + b_nkm1 * 2        # [N,K,M] temporary write+read
                   + b_wkn)            # packed output
        else:                                  # pallas: table in VMEM
            per = b_wn + b_nk4 + b_wkn
        return calls * per

    phases = []

    # -- publish: column scatters into the message window --
    phases.append(Phase("publish", [
        ("col scatters (have/deliver/iwant x P cols)", p * n * (1 + 4 + 4)),
        ("msg meta + fanout rows", 6 * 4 * p + 3 * 4 * p),
    ]))

    # -- decay_counters (in `planned` there is NO separate decay pass:
    # scores read counter*decay inline, attribution writes
    # min(counter*decay + arrivals, cap) — same post-tick values, zero
    # extra passes; the mesh_active latch moves into the heartbeat --
    if design == "current":
        phases.append(Phase("decay_counters", [
            ("read fmd/mmd/mfp/imd/bp", 5 * b_ntk4),
            ("read graft_tick/mesh/mesh_active", b_ntk4 + 2 * b_ntk),
            ("write 5 counters + active", 5 * b_ntk4 + b_ntk),
        ]))
    phases.append(Phase("compute_scores", [
        ("read 4 counters + graft/bp", 6 * b_ntk4),
        ("read mesh/active/connected/neighbors", 3 * b_ntk + b_nk4),
        ("write scores + scores_all", 2 * b_nk4),
    ]))

    # -- heartbeat mesh maintenance --
    hb_items = [
        ("mesh-regime masks (~8 fused bool passes)", 8 * 2 * b_ntk),
        ("ungated selections (gossip + graft gate): noise+ranks",
         gated_selections * (b_ntk4 + b_ntk4 + b_ntk)),
        ("backoff/graft_tick/penalty updates", 3 * 2 * b_ntk4),
    ]
    phases.append(Phase("heartbeat logic", hb_items))
    phases.append(Phase("heartbeat edge exchange (3 packed gathers)",
                        [("graft/prune + refuse + gossip/send",
                          permgather_packed(3))]))

    # -- forward_tick --
    if design == "current":
        phases.append(Phase("fwd: IWANT resolve", [
            ("slot bit-planes -> asked_k [W,K,N]", b_wkn + 6 * b_wn),
            ("answers gather", words_gather(1)),
            ("got/broken chain (~4 [W,K,N] passes)", 4 * b_wkn),
            ("budget popcounts", 2 * b_wkn),
        ]))
        phases.append(Phase("fwd: allowed/mesh_eb build", [
            ("fwd_mask+mesh -> 2x [W,K,N]", 2 * (b_ntk + b_wkn)),
        ]))
    else:
        # fused resolve kernel: answer table pinned in VMEM, asked/got/
        # broken computed per peer block, outputs are counts + [W,N] sets;
        # allowed/mesh_eb expand inside the hop kernel from bool planes
        phases.append(Phase("fwd: IWANT resolve (fused)", [
            ("iwant_pending r + answer table + outputs",
             b_nm4 + b_wn * 4 + n * k),
        ]))

    # -- the hop loop --
    if design == "current":
        per_hop = [
            ("frontier gather", words_gather(1) // 1),
            ("& allowed (read+write)", b_wkn * 2),
            ("prefix-OR assoc-scan (5 passes r+w)", 5 * 2 * b_wkn),
            ("new_from_k/new_any", b_wkn * 2 + b_wn),
            ("5 bit-set accumulators r+w", 5 * 2 * b_wkn),
            ("dup/elig chain reads (mesh_eb, offered)", 2 * b_wkn),
            ("have/dlv/frontier [W,N] updates", 6 * b_wn),
        ]
    else:
        # fused Pallas hop kernel: frontier/have/vm tables pinned in VMEM,
        # nbr + masks blocked in, K-prefix unrolled on-chip, outputs are
        # int8 per-slot per-topic event counts (aliased accumulators).
        # gater accs (ig/gdup) compile only when cfg.gater_enabled — the
        # headline config runs without the gater
        per_hop = [
            ("nbr indices", b_nk4),
            ("fwd_mask + mesh bool planes", 2 * b_ntk),
            ("int8 count accs r+w (nv/ni/dup)", 2 * 3 * n * t * k),
            ("frontier/have/vm tables + updates", 8 * b_wn),
        ]
    hop_total = sum(b for _, b in per_hop)
    phases.append(Phase(f"fwd: hop loop x{hops}",
                        [(lbl, b * hops) for lbl, b in per_hop]))

    # -- attribution / state updates --
    if design == "current":
        phases.append(Phase("fwd: attribution", [
            ("popcount 3 bit-set accs x T", 3 * t * b_wkn),
            ("fmd/mmd/imd r+w", 3 * 2 * b_ntk4),
            ("unpack have/newly_dlv, deliver_tick r+w", b_nm1 * 2 + 2 * b_nm4),
        ]))
    else:
        phases.append(Phase("fwd: attribution", [
            ("read int8 count accs", 3 * n * t * k),
            ("fmd/mmd/imd r+w (decay folded in)", 3 * 2 * b_ntk4),
            ("unpack have/newly_dlv, deliver_tick r+w", b_nm1 * 2 + 2 * b_nm4),
        ]))

    # -- gossip emit (IHAVE -> iwant_pending for next tick) --
    if design == "current":
        phases.append(Phase("fwd: gossip emit", [
            ("window pack + offer gather", words_gather(1)),
            ("prefix-OR over K (5 passes r+w)", 5 * 2 * b_wkn),
            ("chosen_k + bits_to_slot (5 reduce_or passes)",
             b_wkn * 2 + 5 * b_wkn),
            ("iwant_pending write", b_nm4),
        ]))
    else:
        phases.append(Phase("fwd: gossip emit", [
            ("fused offer+choose kernel (tables in VMEM)",
             b_wn + b_nk4 + b_wkn // k + b_nm4),
            ("iwant_pending write", b_nm4),
        ]))

    return phases


def report(name, n, k, t, m, w, hops, p, design):
    phases = model(n, k, t, m, w, hops, p, design)
    total = sum(ph.total for ph in phases)
    ms = fmt_mb(total) / 1e3 * V5E_MS_PER_GB
    print(f"\n== {name} [{design}] N={n} K={k} T={t} M={m} W={w} "
          f"hops={hops} P={p} ==")
    for ph in phases:
        pms = fmt_mb(ph.total) / 1e3 * V5E_MS_PER_GB
        print(f"  {ph.name:44s} {fmt_mb(ph.total):9.1f} MB  {pms:7.3f} ms")
        if os.environ.get("PERF_MODEL_DETAIL"):
            for lbl, b in ph.items:
                print(f"      {lbl:52s} {fmt_mb(b):9.1f} MB")
    print(f"  {'TOTAL':44s} {fmt_mb(total):9.1f} MB  {ms:7.3f} ms"
          f"   -> {1e3 / ms:8.1f} hb/s")
    return total, 1e3 / ms


def report_sharded(name, n, k, t, m, w, hops, p, n_dev,
                   ici_gbps=400.0):
    """v5e-N projection for the landed design: per-device roofline time +
    the replicated-table all-gather payload per tick. ICI bandwidth is a
    conservative per-chip number (v5e: 4 links x ~100+ GB/s usable)."""
    phases = model(n, k, t, m, w, hops, p, "planned")
    total = sum(ph.total for ph in phases)
    per_dev = total / n_dev
    f = 4
    wn_table = f * w * n                    # [W, N] u32 packed table
    wb1 = f * n * (((1 * k) + 31) // 32)    # [N, ceil(BK/32)] bit-tables
    wb2 = f * n * (((2 * k) + 31) // 32)
    exchange = [
        ("hop frontier table x hops", hops * wn_table),
        ("IWANT-resolve answer table", wn_table),
        ("gossip-emit window table", wn_table),
        ("edge bit-tables (B=2,1,2 planes)", 2 * wb2 + wb1),
    ]
    ex_total = sum(b for _, b in exchange)
    hbm_ms = fmt_mb(per_dev) / 1e3 * V5E_MS_PER_GB
    ici_ms = fmt_mb(ex_total) / 1e3 * (1e3 / ici_gbps)
    ms = hbm_ms + ici_ms
    print(f"\n== {name} [landed, sharded x{n_dev}] N={n} K={k} T={t} "
          f"M={m} W={w} hops={hops} ==")
    print(f"  {'per-device HBM':44s} {fmt_mb(per_dev):9.1f} MB  "
          f"{hbm_ms:7.3f} ms")
    for lbl, b in exchange:
        print(f"      {lbl:52s} {fmt_mb(b):9.1f} MB")
    print(f"  {'all-gather payload @ ' + str(ici_gbps) + ' GB/s ICI':44s} "
          f"{fmt_mb(ex_total):9.1f} MB  {ici_ms:7.3f} ms")
    print(f"  {'TOTAL':44s} {'':9s}     {ms:7.3f} ms"
          f"   -> {1e3 / ms:8.1f} hb/s")
    return 1e3 / ms


def report_sort_era(name, n, k, t, m, w, hops, p, n_dev=1,
                    sort_ms_per_3m2=5.0, ici_gbps=400.0):
    """The EXECUTABLE model (post live-window): no Pallas gathers — every
    edge routing is a sort-permute whose cost scales ~linearly in slots
    (measured ~5 ms at L=3.2M on v5e through the tunnel), elementwise
    runs at the measured ~232 GB/s, and the ~13 routing ops per tick are
    serially dependent. n_dev > 1 uses the halo route (parallel/halo.py):
    per-shard sorts of ~L/D plus an all_to_all of 4x-capacity buckets."""
    achieved_gbps = 232.0
    l_slots = n * k
    ld = l_slots / n_dev
    sort_ms = sort_ms_per_3m2 * ld / 3.2e6
    n_sorts = hops + 2 + 3          # hops + resolve/emit + 3 exchanges
    f = 4
    elementwise_mb = fmt_mb(
        hops * (12 * f * w * k * n // 4) +      # hop masked-math passes
        6 * f * n * t * k +                      # scores/counters
        4 * f * n * m)                           # [N,M] i32 passes
    ew_ms = elementwise_mb / n_dev / 1e3 * (1e3 / achieved_gbps)
    halo_ms = 0.0
    if n_dev > 1:
        bucket_mb = n_sorts * (4 * ld / n_dev) * n_dev * f / 1e6
        halo_ms = bucket_mb / ici_gbps       # MB over GB/s -> ms
    ms = n_sorts * sort_ms + ew_ms + halo_ms
    print(f"\n== {name} [sort-era{', halo x' + str(n_dev) if n_dev > 1 else ''}]"
          f" N={n} K={k} hops={hops} ==")
    print(f"  {n_sorts} serial sort-permutes @ {sort_ms:5.2f} ms "
          f"{n_sorts * sort_ms:8.2f} ms")
    print(f"  {'elementwise @ 232 GB/s achieved':38s} {ew_ms:8.2f} ms")
    if n_dev > 1:
        print(f"  {'halo all_to_all buckets':38s} {halo_ms:8.2f} ms")
    print(f"  {'TOTAL':38s} {ms:8.2f} ms   -> {1e3 / ms:7.1f} hb/s")
    return 1e3 / ms


def cost_analysis_check(n=10_000, k=32, m=64, p=8):
    """Compile each phase and print XLA's own bytes-accessed — an inventory
    check. MUST run in a process whose environment was scrubbed BEFORE
    python started (see main): the axon site hook monkeypatches
    jax get_backend and initializes its client regardless of an in-process
    JAX_PLATFORMS=cpu assignment, wedging forever when the tunnel is down
    (verified by faulthandler: make_c_api_client inside
    _axon_get_backend_uncached). The CPU lowering is what this cross-check
    documents anyway."""
    import jax
    from __graft_entry__ import _build
    from go_libp2p_pubsub_tpu.ops.heartbeat import heartbeat
    from go_libp2p_pubsub_tpu.ops.propagate import forward_tick, publish
    from go_libp2p_pubsub_tpu.ops.score_ops import compute_scores, decay_counters

    cfg, tp, st = _build(n_peers=n, k_slots=k, degree=12, msg_window=m,
                         publishers=p)
    key = jax.random.PRNGKey(0)

    def ca(label, fn, *args, **static):
        j = jax.jit(fn, static_argnames=tuple(static))
        c = j.lower(*args, **static).compile()
        d = c.cost_analysis()
        d = d[0] if isinstance(d, list) else d
        print(f"  {label:24s} bytes={d.get('bytes accessed', float('nan')) / 1e6:10.1f} MB"
              f"  flops={d.get('flops', 0) / 1e6:10.1f} M")

    print(f"\n== XLA cost_analysis on {jax.default_backend()} @ N={n} ==")
    ca("decay_counters", lambda s: decay_counters(s, cfg, tp), st)
    ca("compute_scores", lambda s: compute_scores(s, cfg, tp), st)
    ca("heartbeat", lambda s, k2: heartbeat(s, cfg, tp, k2), st, key)
    # forward_tick's lower() needs shapes only — eval_shape skips the
    # minutes an un-jitted op-by-op heartbeat dispatch would burn
    hb = jax.eval_shape(lambda s, k2: heartbeat(s, cfg, tp, k2), st, key)
    ca("forward_tick",
       lambda s, g, sc, k2: forward_tick(s, cfg, tp, g, sc, k2),
       hb.state, hb.inc_gossip, hb.scores, key)


def main():
    shapes = {
        "headline_100k": dict(n=100_000, k=32, t=1, m=64, w=2, hops=8, p=8),
        "10k_beacon": dict(n=10_000, k=48, t=9, m=64, w=2, hops=8, p=16),
        "1k": dict(n=1024, k=32, t=1, m=64, w=2, hops=8, p=4),
    }
    which = sys.argv[1] if len(sys.argv) > 1 and not sys.argv[1].startswith("-") \
        else "headline_100k"
    if which not in shapes:
        raise SystemExit(f"unknown scenario {which!r}; "
                         f"choose from {', '.join(shapes)}")
    sh = shapes[which]
    if os.environ.get("_PERF_MODEL_CHILD") != "1":    # parent prints these
        for design in ("current", "planned"):
            report(which, design=design, **sh)
        if "--sharded" in sys.argv:
            n_dev = int(sys.argv[sys.argv.index("--sharded") + 1])
            report_sharded(which, n_dev=n_dev, **sh)
        if "--sort-era" in sys.argv:
            report_sort_era(which, **sh)
            report_sort_era(which, n_dev=8, **sh)
            report_sort_era(which, **{**sh, "k": 16})
    if "--cost-analysis" in sys.argv:
        # cross-check at the chosen shape, downscaled to 10k peers so the
        # CPU compile stays sane (the inventory, not N, is what's checked).
        # Re-exec in a scrubbed-env child: only an env set before process
        # start dodges the axon plugin wedge (see cost_analysis_check).
        if os.environ.get("_PERF_MODEL_CHILD") != "1":
            from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env
            env = cpu_mesh_env(dict(os.environ))
            env["_PERF_MODEL_CHILD"] = "1"
            res = subprocess.run([sys.executable, "-u", __file__, which,
                                  "--cost-analysis"], env=env)
            raise SystemExit(res.returncode)
        cost_analysis_check(n=min(sh["n"], 10_000), k=sh["k"], m=sh["m"],
                            p=sh["p"])


if __name__ == "__main__":
    main()
