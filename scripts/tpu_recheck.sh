#!/bin/bash
# One-shot TPU re-measurement: ordered so a SHORT live window still banks
# the most important artifacts — the full benchmark suite FIRST (the
# round's headline evidence), then the perf-knob sweeps (sort/count-dtype/
# slot-width/selection), then the diagnostics (ablations, microbenches,
# Pallas lowering smoke). Each step logs independently so a tunnel wedge
# mid-way loses only the remaining steps.
# mh_resilience exercises the GRAFT_CHAOS kill -> relaunch -> elastic
# resume path (scripts/mh_supervisor.py) on CPU deliberately: the remote
# TPU admits one client at a time, and what the step proves is the
# recovery protocol, not the backend. --fresh wipes prior chaos markers
# so a re-run refires the kill.
set -u
cd "$(dirname "$0")/.."
mkdir -p /tmp/tpu_recheck
for step in "supervisor_smoke:python scripts/supervisor_smoke.py" \
            "mh_resilience:env JAX_PLATFORMS=cpu GRAFT_CHAOS=kill@1:4 python scripts/mh_supervisor.py --procs 2,1 --scenario frontier_250k --n 128 --ticks 6 --chunk-ticks 2 --seed 7 --run-dir /tmp/tpu_recheck/mh_resilience --fresh --max-relaunches 2 --backoff-base-s 0.2" \
            "bench:python bench.py" \
            "bench_fleet:env BENCH_SCENARIOS=fleet_256x1k,1k_single_topic python bench.py" \
            "bench_frontier:env BENCH_SCENARIOS=frontier_250k,frontier_500k,frontier_1m GRAFT_DEADLINE_S=900 python bench.py" \
            "bench_frontier_xl:env BENCH_SCENARIOS=frontier_4m,frontier_10m GRAFT_DEADLINE_S=900 GRAFT_HBM_BUDGET=16GiB python bench.py" \
            "sweep_scores:env SWEEP_JOURNAL=/tmp/tpu_recheck/sweep_scores.jsonl python scripts/sweep_scores.py --write-perf-model" \
            "telemetry:env BENCH_SCENARIOS=telemetry_1k,telemetry_10k python bench.py" \
            "bench_overlap:env BENCH_SCENARIOS=supervised_overlap_1k,supervised_overlap_10k python bench.py" \
            "bench_ingest:env BENCH_SCENARIOS=ingest_1k,ingest_10k python bench.py" \
            "bench_verdicts:env BENCH_SCENARIOS=verdict_1k,verdict_10k python bench.py" \
            "bench_attacks:env BENCH_SCENARIOS=eclipse_50k,flashcrowd_50k python bench.py" \
            "bench_powerlaw:env BENCH_SCENARIOS=powerlaw_100k,powerlaw_1m,heavytail_eclipse GRAFT_DEADLINE_S=900 GRAFT_HBM_BUDGET=16GiB python bench.py" \
            "bench_powerlaw_mh:env BENCH_SCENARIOS=powerlaw_100k_mh,powerlaw_10m_mh GRAFT_DEADLINE_S=900 GRAFT_HBM_BUDGET=16GiB python bench.py" \
            "modes_sort:env GRAFT_EDGE_GATHER=sort BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "modes_mxu:env GRAFT_EDGE_GATHER=mxu BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "hop_pallas_mxu:env GRAFT_HOP_MODE=pallas-mxu BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "acc_i32:env GRAFT_COUNT_DTYPE=int32 BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "headline_k16:env BENCH_K=16 BENCH_SCENARIOS=headline python bench.py" \
            "headline_k16_i32:env BENCH_K=16 GRAFT_COUNT_DTYPE=int32 BENCH_SCENARIOS=headline python bench.py" \
            "faults_degraded:env GRAFT_FAULT_PLAN=partition=2@3:8,drop=0.02 BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "invariants_off:env GRAFT_INVARIANT_MODE=off BENCH_SCENARIOS=1k_single_topic,headline python bench.py" \
            "modes_rows:env GRAFT_EDGE_GATHER=rows BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "modes_scalar:env GRAFT_EDGE_GATHER=scalar BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "sel_iter:env GRAFT_SELECTION=iter BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "sel_ranks:env GRAFT_SELECTION=ranks BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "sel_sort:env GRAFT_SELECTION=sort BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "calibrate_dispatch:python scripts/calibrate_dispatch.py --out /tmp/tpu_recheck/dispatch_table.json" \
            "bench_dispatched:env GRAFT_DISPATCH_TABLE=/tmp/tpu_recheck/dispatch_table.json BENCH_SCENARIOS=10k_beacon,headline python bench.py" \
            "ablate_100k:python scripts/ablate.py headline_100000 10" \
            "ablate_10k:python scripts/ablate.py 10k_beacon 10" \
            "pallas_smoke:python scripts/tpu_kernel_smoke.py" \
            "probe_gathers:python scripts/tpu_probe_gathers.py" \
            "probe_gathers_k16:python scripts/tpu_probe_gathers.py 100000 16 64" \
            "microbench_beacon:python scripts/microbench_kernels.py 10000 9 48 64" \
            "microbench_100k:python scripts/microbench_kernels.py 100000 1 32 64"; do
  name="${step%%:*}"; cmd="${step#*:}"
  echo "== $name: $cmd =="
  # supervised-run plane (ISSUE 5): each bench step gets its own resumable
  # journal, so a re-run of a preempted recheck skips already-banked
  # configs, and bench's SIGTERM flush turns the `timeout` kill below into
  # a partial-but-parseable record instead of a truncated log
  BENCH_JOURNAL="/tmp/tpu_recheck/journal_$name.jsonl" \
    timeout 1500 $cmd 2>&1 | grep -v WARNING | tee "/tmp/tpu_recheck/$name.log"
  rc=${PIPESTATUS[0]}
  echo "== $name done (rc=$rc) =="
done
