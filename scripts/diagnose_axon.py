"""One-shot bisect of the axon-platform slowdown trigger.

Round-1's 0.95 hb/s @100k was measured in a process where EVERYTHING ran
~1000x slow (even `jax.random.uniform` inside an on-device lax.scan). The
slowdown appears after sim-state construction; this script isolates which
operation flips the platform into the slow mode, by re-measuring a canary
after each candidate trigger.

Run on the real TPU (default env). Prints one line per stage; the first
stage whose canary regresses >10x names the trigger.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def canary():
    """ms per iteration of a tiny on-device scan (20 iters)."""
    @jax.jit
    def runv(x, k):
        ks = jax.random.split(k, 20)
        out, _ = jax.lax.scan(
            lambda c, kk: (c + jax.random.uniform(kk, c.shape), None), x, ks)
        return out
    x0 = jnp.zeros((8192, 32), jnp.float32)
    key = jax.random.PRNGKey(0)
    out = runv(x0, key); jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = runv(x0, key); jax.block_until_ready(out)
    return (time.perf_counter() - t0) / 20 * 1e3


def stage(name, fn):
    fn()
    print(f"{name:44s} canary {canary():9.4f} ms/tick", flush=True)


def main():
    print("platform:", jax.devices()[0].platform, flush=True)
    print(f"{'baseline':44s} canary {canary():9.4f} ms/tick", flush=True)

    # candidate triggers, mildest first
    stage("20 tiny f32 transfers",
          lambda: [jnp.asarray(np.array([float(i)], np.float32))
                   .block_until_ready() for i in range(20)])
    stage("transfer containing inf",
          lambda: jnp.asarray(np.array([np.inf], np.float32)).block_until_ready())
    stage("jnp.full int32 2^30 [8k,32]",
          lambda: jnp.full((8192, 32), 2**30, jnp.int32).block_until_ready())
    stage("30 mixed zeros/full allocs (old init_state)",
          lambda: [jnp.zeros((8192, 1, 32), jnp.float32).block_until_ready()
                   for _ in range(10)]
          + [jnp.full((8192, 32), 2**30, jnp.int32).block_until_ready()
             for _ in range(10)]
          + [jnp.zeros((8192, 64), bool).block_until_ready()
             for _ in range(10)])

    def tp_build():
        from go_libp2p_pubsub_tpu.core.params import TopicScoreParams
        from go_libp2p_pubsub_tpu.sim.config import TopicParams
        tp = TopicParams.from_topic_params([TopicScoreParams(
            skip_atomic_validation=True, time_in_mesh_quantum=1.0)])
        jax.block_until_ready(tuple(tp))
    stage("TopicParams (single [16,T] transfer)", tp_build)

    def state_build():
        from go_libp2p_pubsub_tpu.sim import SimConfig, init_state, topology
        cfg = SimConfig(n_peers=8192, k_slots=32, n_topics=1, msg_window=64)
        st = init_state(cfg, topology.sparse(8192, 32, degree=12))
        jax.block_until_ready(st)
    stage("init_state (jitted on-device build)", state_build)

    def compile_step():
        from __graft_entry__ import _build
        from go_libp2p_pubsub_tpu.sim.engine import step
        cfg, tp, st = _build(n_peers=8192, k_slots=32, degree=12,
                             msg_window=64, publishers=8)
        jax.jit(step, static_argnames=("cfg",)).lower(
            st, cfg, tp, jax.random.PRNGKey(0)).compile()
    stage("compile full step @8k (no exec)", compile_step)

    def run_steps():
        from __graft_entry__ import _build
        from go_libp2p_pubsub_tpu.sim.engine import run
        cfg, tp, st = _build(n_peers=8192, k_slots=32, degree=12,
                             msg_window=64, publishers=8)
        t0 = time.perf_counter()
        st = run(st, cfg, tp, jax.random.PRNGKey(0), 20)
        st.tick.block_until_ready()
        c = time.perf_counter() - t0
        t0 = time.perf_counter()
        st = run(st, cfg, tp, jax.random.PRNGKey(1), 20)
        st.tick.block_until_ready()
        print(f"  run(20) @8k: compile+exec {c:.1f}s, "
              f"exec {(time.perf_counter()-t0)/20*1e3:.2f} ms/tick", flush=True)
    stage("execute run(20) @8k", run_steps)


if __name__ == "__main__":
    main()
