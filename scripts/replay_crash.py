"""Replay a supervisor crash dump: re-run the exact failing tick window
from the last-good checkpoint with invariants raised.

A dump (sim/supervisor.py `_write_crash_dump`) holds the last-good state,
the failing window's per-tick keys, the config fingerprint, and the
decoded health word. Replay restores the state, swaps
``invariant_mode="raise"`` into the config, and drives
``engine.run_checked_keys`` over the recorded keys — a deterministic
re-execution of precisely the ticks that killed the run, with every
violation escalated to a host exception naming its flags.

FLEET dumps (sim/fleet.py `_write_fleet_crash_dump`, ``crash_fleet_*``
directories) carry a [B]-batched last-good state and [C, B_active]
per-tick keys; pass ``--member i`` to restore member ``i`` out of the
batch and replay ITS window alone — the single-lane reproduction of a
batched failure. Fleet dumps carry no scenario metadata (members may mix
configs), so ``--scenario``/``--kwargs`` (or ``replay_fleet()`` with
like/cfg/tp objects) must describe the member being replayed.

Usage:
    python scripts/replay_crash.py CRASH_DIR [--scenario NAME]
        [--record] [--kwargs '{"n_peers": 512}'] [--member I]

The scenario (a ``sim.scenarios.SCENARIOS`` key) and its kwargs default to
what the supervisor stamped into crash.json; pass them explicitly for
dumps written without scenario metadata. ``--record`` replays in record
mode instead (no exception — prints the final flag word). Exit status: 0
clean replay, 3 the invariant trip reproduced, 1 usage/config errors.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_meta(crash_dir: str) -> dict:
    with open(os.path.join(crash_dir, "crash.json")) as f:
        return json.load(f)


def _check_flags_version(meta: dict, crash_dir: str) -> None:
    """Refuse BY NAME to replay a dump recorded under another fault_flags
    bit layout (sim/invariants.FLAGS_VERSION): a version-1 word's
    violation bits 8-9 would silently misread as FAULT_CENSOR/FAULT_WAVE
    under the current layout. Dumps from before versioning (no
    ``flags_version`` field) pass, as before."""
    from go_libp2p_pubsub_tpu.sim.invariants import FLAGS_VERSION
    ver = meta.get("flags_version")
    if ver is not None and int(ver) != FLAGS_VERSION:
        raise SystemExit(
            f"crash dump {crash_dir!r} was recorded under flags_version="
            f"{int(ver)} but this build decodes flags_version="
            f"{FLAGS_VERSION} — the fault_flags bit layouts differ; "
            "replay it with the build that wrote it instead of "
            "misreading its bits")


def replay(crash_dir: str, like=None, cfg=None, tp=None,
           invariant_mode: str = "raise") -> dict:
    """Re-run the dump's failing window; returns a result record with
    ``tripped`` (did the invariant trip reproduce), the final
    ``fault_flags`` when it didn't, and the window bounds.

    ``like``/``cfg``/``tp`` may be passed directly (tests, callers that
    still hold the objects); otherwise they are rebuilt from the
    scenario metadata stamped in crash.json."""
    import jax.numpy as jnp
    import numpy as np

    from go_libp2p_pubsub_tpu.sim import checkpoint
    from go_libp2p_pubsub_tpu.sim.engine import run_checked_keys, run_keys
    from go_libp2p_pubsub_tpu.sim.invariants import decode_flags

    meta = load_meta(crash_dir)
    _check_flags_version(meta, crash_dir)
    if cfg is None or like is None or tp is None:
        from go_libp2p_pubsub_tpu.sim import scenarios
        name = meta.get("scenario")
        if not name:
            raise SystemExit(
                "crash.json carries no scenario metadata; pass --scenario "
                "(and --kwargs) or call replay() with like/cfg/tp objects")
        if name not in scenarios.SCENARIOS:
            raise SystemExit(
                f"unknown scenario {name!r}; known: "
                f"{sorted(scenarios.SCENARIOS)}")
        cfg, tp, like = scenarios.SCENARIOS[name](
            **(meta.get("scenario_kwargs") or {}))
    want = meta.get("config_fingerprint")
    got = checkpoint.config_fingerprint(cfg)
    if want and got != want:
        raise SystemExit(
            f"rebuilt config fingerprint {got[:12]}… does not match the "
            f"dump's {want[:12]}… — wrong scenario/kwargs; replaying under "
            "a drifted config would not reproduce the crash")
    state = checkpoint.restore(os.path.join(crash_dir, "last_good"), like,
                               cfg=cfg)
    keys = jnp.asarray(np.asarray(meta["window_key_data"], dtype=np.uint32))
    replay_cfg = dataclasses.replace(cfg, invariant_mode=invariant_mode)
    result = {"crash_dir": crash_dir, "tick_start": meta["tick_start"],
              "tick_end": meta["tick_end"], "ticks": int(keys.shape[0]),
              "invariant_mode": invariant_mode,
              "original_error": meta.get("error", "")[:200]}
    try:
        if invariant_mode == "raise":
            out = run_checked_keys(state, replay_cfg, tp, keys)
        else:
            out = run_keys(state, replay_cfg, tp, keys)
        flags = int(np.asarray(out.fault_flags))
        result.update(tripped=False, fault_flags=flags,
                      fault_flag_names=decode_flags(flags))
    except Exception as e:
        if "invariant violation" not in str(e):
            raise               # a replay-infra failure, not the trip
        result.update(tripped=True, error=str(e)[:500])
    return result


def is_fleet_dump(meta: dict) -> bool:
    return "fleet_size" in meta


def replay_fleet(crash_dir: str, member: int, like=None, cfg=None, tp=None,
                 invariant_mode: str = "raise") -> dict:
    """Restore member ``member`` (INPUT index, as named in the dump's
    ``member_names``) out of a fleet crash dump's batched last-good state
    and re-run its slice of the failing window.

    ``like``/``cfg``/``tp`` describe ONE member (the same objects a
    ``FleetMember`` carried); fleet dumps stamp no scenario metadata, so
    they are required — from the caller directly or rebuilt by ``main``
    from ``--scenario``/``--kwargs``. The restore verifies the dump's
    fleet-axis-bound fingerprint against the rebuilt config (raise-mode
    members executed in "record" — sim/fleet.py ``_exec_cfg`` — so the
    config is normalized the same way before fingerprinting)."""
    import dataclasses as _dc

    import jax.numpy as jnp
    import numpy as np

    from go_libp2p_pubsub_tpu.sim import checkpoint
    from go_libp2p_pubsub_tpu.sim.engine import run_checked_keys, run_keys
    from go_libp2p_pubsub_tpu.sim.fleet import (_exec_cfg, member_state,
                                                stack_states)
    from go_libp2p_pubsub_tpu.sim.invariants import decode_flags

    meta = load_meta(crash_dir)
    _check_flags_version(meta, crash_dir)
    if not is_fleet_dump(meta):
        raise SystemExit(f"{crash_dir!r} is not a fleet dump; run without "
                         "--member")
    if like is None or cfg is None or tp is None:
        raise SystemExit(
            "fleet dumps carry no scenario metadata (members may mix "
            "configs); pass --scenario/--kwargs or call replay_fleet() "
            "with like/cfg/tp objects for the member being replayed")
    b = int(meta["fleet_size"])
    # --member is the member's INPUT index; a mixed-config fleet splits
    # into groups (one dump per group), so the dump's member_ids map
    # input indices to group positions. Dumps written before member_ids
    # existed fall back to treating --member as the group position.
    ids = meta.get("member_ids")
    if ids is not None:
        if member not in ids:
            raise SystemExit(
                f"--member {member} is not in this dump's config group "
                f"(member_ids: {ids}, names: {meta.get('member_names')}) — "
                "a mixed-config fleet writes one dump per group; this "
                "member crashed (or finished) under a different group")
        gpos = ids.index(member)
    else:
        gpos = member
    if not 0 <= gpos < b:
        raise SystemExit(f"--member {member} outside fleet of {b} "
                         f"(members: {meta.get('member_names')})")
    group_cfg = _exec_cfg(cfg)
    want = meta.get("config_fingerprint")
    got = checkpoint.config_fingerprint(group_cfg, fleet=b)
    if want and got != want:
        raise SystemExit(
            f"rebuilt fleet config fingerprint {got[:12]}… does not match "
            f"the dump's {want[:12]}… — wrong scenario/kwargs (or a "
            "weight-variant member needing explicit cfg/tp); replaying "
            "under a drifted config would not reproduce the crash")
    batched_like = stack_states([like] * b)
    full = checkpoint.restore(os.path.join(crash_dir, "last_good"),
                              batched_like, cfg=group_cfg)
    state = member_state(full, gpos)
    active = meta.get("active_members", list(range(b)))
    if gpos not in active:
        raise SystemExit(
            f"member {member} was not active in the failing window "
            f"(active group positions: {active}) — it had finished or was "
            "retired; its keys are not in the dump")
    pos = active.index(gpos)
    keys = jnp.asarray(np.asarray(meta["window_key_data"],
                                  dtype=np.uint32)[:, pos])
    replay_cfg = _dc.replace(group_cfg, invariant_mode=invariant_mode)
    result = {"crash_dir": crash_dir, "member": member,
              "member_name": (meta.get("member_names") or [None] * b)[gpos],
              "tick_start": meta.get("window_start"),
              "tick_end": meta.get("window_end"),
              "ticks": int(keys.shape[0]),
              "invariant_mode": invariant_mode,
              "original_error": meta.get("error", "")[:200]}
    try:
        if invariant_mode == "raise":
            out = run_checked_keys(state, replay_cfg, tp, keys)
        else:
            out = run_keys(state, replay_cfg, tp, keys)
        flags = int(np.asarray(out.fault_flags))
        result.update(tripped=False, fault_flags=flags,
                      fault_flag_names=decode_flags(flags))
    except Exception as e:
        if "invariant violation" not in str(e):
            raise               # a replay-infra failure, not the trip
        result.update(tripped=True, error=str(e)[:500])
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("crash_dir")
    ap.add_argument("--scenario", default=None,
                    help="sim.scenarios.SCENARIOS key (default: from dump)")
    ap.add_argument("--kwargs", default=None,
                    help="JSON dict of scenario builder kwargs")
    ap.add_argument("--record", action="store_true",
                    help="replay in record mode (collect flags, no raise)")
    ap.add_argument("--member", type=int, default=None,
                    help="fleet dumps: which member (input index) to "
                         "restore and replay")
    args = ap.parse_args()
    mode = "record" if args.record else "raise"
    meta = load_meta(args.crash_dir)
    if is_fleet_dump(meta) and args.member is None:
        print(json.dumps({
            "error": "fleet crash dump: pass --member to pick the lane",
            "fleet_size": meta.get("fleet_size"),
            "member_names": meta.get("member_names"),
            "active_members": meta.get("active_members")}), flush=True)
        return 1
    if args.member is not None:
        if not args.scenario:
            print(json.dumps({"error": "--member needs --scenario (fleet "
                              "dumps carry no scenario metadata)"}),
                  flush=True)
            return 1
        from go_libp2p_pubsub_tpu.sim import scenarios
        if args.scenario not in scenarios.SCENARIOS:
            print(json.dumps({"error": f"unknown scenario "
                              f"{args.scenario!r}",
                              "known": sorted(scenarios.SCENARIOS)}),
                  flush=True)
            return 1
        kwargs = json.loads(args.kwargs) if args.kwargs else {}
        cfg, tp, like = scenarios.SCENARIOS[args.scenario](**kwargs)
        result = replay_fleet(args.crash_dir, args.member, like=like,
                              cfg=cfg, tp=tp, invariant_mode=mode)
        print(json.dumps(result), flush=True)
        return 3 if result.get("tripped") else 0
    if args.scenario:
        # command-line override of the dump's scenario metadata (the dump
        # itself is never mutated): rebuild the objects here and hand them
        # to replay() directly
        from go_libp2p_pubsub_tpu.sim import scenarios
        if args.scenario not in scenarios.SCENARIOS:
            print(json.dumps({"error": f"unknown scenario "
                              f"{args.scenario!r}",
                              "known": sorted(scenarios.SCENARIOS)}),
                  flush=True)
            return 1
        kwargs = json.loads(args.kwargs) if args.kwargs else {}
        cfg, tp, like = scenarios.SCENARIOS[args.scenario](**kwargs)
        result = replay(args.crash_dir, like=like, cfg=cfg, tp=tp,
                        invariant_mode=mode)
    else:
        result = replay(args.crash_dir, invariant_mode=mode)
    print(json.dumps(result), flush=True)
    return 3 if result.get("tripped") else 0


if __name__ == "__main__":
    sys.exit(main())
