"""Replay a supervisor crash dump: re-run the exact failing tick window
from the last-good checkpoint with invariants raised.

A dump (sim/supervisor.py `_write_crash_dump`) holds the last-good state,
the failing window's per-tick keys, the config fingerprint, and the
decoded health word. Replay restores the state, swaps
``invariant_mode="raise"`` into the config, and drives
``engine.run_checked_keys`` over the recorded keys — a deterministic
re-execution of precisely the ticks that killed the run, with every
violation escalated to a host exception naming its flags.

Usage:
    python scripts/replay_crash.py CRASH_DIR [--scenario NAME]
        [--record] [--kwargs '{"n_peers": 512}']

The scenario (a ``sim.scenarios.SCENARIOS`` key) and its kwargs default to
what the supervisor stamped into crash.json; pass them explicitly for
dumps written without scenario metadata. ``--record`` replays in record
mode instead (no exception — prints the final flag word). Exit status: 0
clean replay, 3 the invariant trip reproduced, 1 usage/config errors.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_meta(crash_dir: str) -> dict:
    with open(os.path.join(crash_dir, "crash.json")) as f:
        return json.load(f)


def replay(crash_dir: str, like=None, cfg=None, tp=None,
           invariant_mode: str = "raise") -> dict:
    """Re-run the dump's failing window; returns a result record with
    ``tripped`` (did the invariant trip reproduce), the final
    ``fault_flags`` when it didn't, and the window bounds.

    ``like``/``cfg``/``tp`` may be passed directly (tests, callers that
    still hold the objects); otherwise they are rebuilt from the
    scenario metadata stamped in crash.json."""
    import jax.numpy as jnp
    import numpy as np

    from go_libp2p_pubsub_tpu.sim import checkpoint
    from go_libp2p_pubsub_tpu.sim.engine import run_checked_keys, run_keys
    from go_libp2p_pubsub_tpu.sim.invariants import decode_flags

    meta = load_meta(crash_dir)
    if cfg is None or like is None or tp is None:
        from go_libp2p_pubsub_tpu.sim import scenarios
        name = meta.get("scenario")
        if not name:
            raise SystemExit(
                "crash.json carries no scenario metadata; pass --scenario "
                "(and --kwargs) or call replay() with like/cfg/tp objects")
        if name not in scenarios.SCENARIOS:
            raise SystemExit(
                f"unknown scenario {name!r}; known: "
                f"{sorted(scenarios.SCENARIOS)}")
        cfg, tp, like = scenarios.SCENARIOS[name](
            **(meta.get("scenario_kwargs") or {}))
    want = meta.get("config_fingerprint")
    got = checkpoint.config_fingerprint(cfg)
    if want and got != want:
        raise SystemExit(
            f"rebuilt config fingerprint {got[:12]}… does not match the "
            f"dump's {want[:12]}… — wrong scenario/kwargs; replaying under "
            "a drifted config would not reproduce the crash")
    state = checkpoint.restore(os.path.join(crash_dir, "last_good"), like,
                               cfg=cfg)
    keys = jnp.asarray(np.asarray(meta["window_key_data"], dtype=np.uint32))
    replay_cfg = dataclasses.replace(cfg, invariant_mode=invariant_mode)
    result = {"crash_dir": crash_dir, "tick_start": meta["tick_start"],
              "tick_end": meta["tick_end"], "ticks": int(keys.shape[0]),
              "invariant_mode": invariant_mode,
              "original_error": meta.get("error", "")[:200]}
    try:
        if invariant_mode == "raise":
            out = run_checked_keys(state, replay_cfg, tp, keys)
        else:
            out = run_keys(state, replay_cfg, tp, keys)
        flags = int(np.asarray(out.fault_flags))
        result.update(tripped=False, fault_flags=flags,
                      fault_flag_names=decode_flags(flags))
    except Exception as e:
        if "invariant violation" not in str(e):
            raise               # a replay-infra failure, not the trip
        result.update(tripped=True, error=str(e)[:500])
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("crash_dir")
    ap.add_argument("--scenario", default=None,
                    help="sim.scenarios.SCENARIOS key (default: from dump)")
    ap.add_argument("--kwargs", default=None,
                    help="JSON dict of scenario builder kwargs")
    ap.add_argument("--record", action="store_true",
                    help="replay in record mode (collect flags, no raise)")
    args = ap.parse_args()
    mode = "record" if args.record else "raise"
    if args.scenario:
        # command-line override of the dump's scenario metadata (the dump
        # itself is never mutated): rebuild the objects here and hand them
        # to replay() directly
        from go_libp2p_pubsub_tpu.sim import scenarios
        if args.scenario not in scenarios.SCENARIOS:
            print(json.dumps({"error": f"unknown scenario "
                              f"{args.scenario!r}",
                              "known": sorted(scenarios.SCENARIOS)}),
                  flush=True)
            return 1
        kwargs = json.loads(args.kwargs) if args.kwargs else {}
        cfg, tp, like = scenarios.SCENARIOS[args.scenario](**kwargs)
        result = replay(args.crash_dir, like=like, cfg=cfg, tp=tp,
                        invariant_mode=mode)
    else:
        result = replay(args.crash_dir, invariant_mode=mode)
    print(json.dumps(result), flush=True)
    return 3 if result.get("tripped") else 0


if __name__ == "__main__":
    sys.exit(main())
