#!/usr/bin/env python
"""External directive producer for the live command plane.

Feeds a run's ``--source`` file (scripts/run_multihost.py /
sim/commands.CommandQueue) by copying an input directive stream line by
line, fsync'ing each write — the durability contract the exactly-once
resume leans on: every byte the consumer's stamped ``stream_offset``
covers is on disk, so a producer restarted with ``--from-offset`` (the
offset carried by the run's ``ingest_stalled`` journal marker and the
dashboard's COASTING banner) resumes the copy without duplicating or
dropping a single directive.

    # fresh feed at 200 lines/s
    python scripts/directive_producer.py \
        --stream workload.ndjsonl --out /shared/live.ndjsonl --rate 200

    # restart after a crash, from the offset the run stamped
    python scripts/directive_producer.py \
        --stream workload.ndjsonl --out /shared/live.ndjsonl \
        --from-offset 18342

``--lines N`` stops the copy after N lines and parks the process
(SIGKILL fodder for the resilience drills: the run's stalled-producer
watchdog trips, the run coasts, and the drill restarts the producer from
the stamped offset). ``--from-offset`` is a byte offset into ``--out``
mirroring ``--stream`` byte-for-byte — the copy seeks the INPUT to the
same offset and truncates any torn tail beyond it in the output.
"""

import argparse
import os
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--stream", required=True,
                    help="input NDJSON directive/trace file to feed from")
    ap.add_argument("--out", required=True,
                    help="the run's --source file (appended, fsync'd "
                         "per line)")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="lines per second (0 = as fast as possible)")
    ap.add_argument("--from-offset", type=int, default=0,
                    help="resume the copy at this byte offset (the "
                         "run's stamped stream_offset)")
    ap.add_argument("--lines", type=int, default=None,
                    help="stop after N lines and sleep forever (chaos "
                         "drills SIGKILL the parked process)")
    args = ap.parse_args()

    delay = 1.0 / args.rate if args.rate > 0 else 0.0
    written = 0
    with open(args.stream, "rb") as src:
        src.seek(args.from_offset)
        # byte-mirror discipline: drop any torn/unstamped tail so the
        # output offset realigns with the input offset exactly
        with open(args.out, "ab") as dst:
            if dst.tell() != args.from_offset:
                dst.truncate(args.from_offset)
                dst.seek(args.from_offset)
            for line in src:
                dst.write(line)
                dst.flush()
                os.fsync(dst.fileno())
                written += 1
                if args.lines is not None and written >= args.lines:
                    print(f"[producer] parked after {written} lines at "
                          f"offset {src.tell()}", flush=True)
                    while True:
                        time.sleep(3600)
                if delay:
                    time.sleep(delay)
        end_offset = src.tell()
    print(f"[producer] done: {written} lines, offset {end_offset}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
