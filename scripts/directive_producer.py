#!/usr/bin/env python
"""External directive producer for the live command plane.

Feeds a run's ``--source`` file (scripts/run_multihost.py /
sim/commands.CommandQueue) by copying an input directive stream line by
line, fsync'ing each write — the durability contract the exactly-once
resume leans on: every byte the consumer's stamped ``stream_offset``
covers is on disk, so a producer restarted with ``--from-offset`` (the
offset carried by the run's ``ingest_stalled`` journal marker and the
dashboard's COASTING banner) resumes the copy without duplicating or
dropping a single directive.

    # fresh feed at 200 lines/s
    python scripts/directive_producer.py \
        --stream workload.ndjsonl --out /shared/live.ndjsonl --rate 200

    # restart after a crash, from the offset the run stamped
    python scripts/directive_producer.py \
        --stream workload.ndjsonl --out /shared/live.ndjsonl \
        --from-offset 18342

``--lines N`` stops the copy after N lines and parks the process
(SIGKILL fodder for the resilience drills: the run's stalled-producer
watchdog trips, the run coasts, and the drill restarts the producer from
the stamped offset). ``--from-offset`` is a byte offset into ``--out``
mirroring ``--stream`` byte-for-byte — the copy seeks the INPUT to the
same offset and truncates any torn tail beyond it in the output.

``--scenario`` (ISSUE 20) emits a CANONICAL composed attack stream to
``--out`` instead of copying one — the two composed scenarios ROADMAP
item 2 names, ready to feed a run's ``--source`` (optionally through a
second producer invocation for the rate/park drills):

    # eclipse + censorship landing on one region at tick 4
    python scripts/directive_producer.py --scenario eclipse_censor \
        --out /shared/live.ndjsonl --at 4 --region 8 --attackers 8

    # publish storms hammering the gater's RED admission for 3 ticks
    python scripts/directive_producer.py --scenario storm_red \
        --out /shared/live.ndjsonl --at 4 --attackers 32 --bursts 3
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def scenario_directives(name: str, *, at: int, region: int,
                        attackers: int, bursts: int) -> list:
    """The canonical composed streams (sim/commands.py grammar). Pure —
    tests pin the exact shapes."""
    if name == "eclipse_censor":
        # one timed compose line: the region [0, region) loses its
        # honest edges while the cohort [region, region+attackers)
        # flips into censoring spam actors — both land at ONE boundary
        return [
            {"op": "tick", "tick": at},
            {"op": "compose", "tick": at, "parts": [
                {"op": "attack", "kind": "eclipse",
                 "peers": list(range(region))},
                {"op": "attack", "kind": "censor",
                 "peers": list(range(region, region + attackers))},
            ]},
        ]
    if name == "storm_red":
        # coordinated publish storms, one burst per tick: offered load
        # beyond the run's --directive-slots budget is exactly what the
        # gater's RED admission sheds deterministically (journaled
        # ingest_shed, never a retrace)
        out = [{"op": "tick", "tick": at}]
        for b in range(bursts):
            out.append({"op": "attack", "tick": at + b, "kind": "storm",
                        "topic": 0, "peers": list(range(attackers))})
        return out
    raise ValueError(
        f"--scenario {name!r} unknown (supported: eclipse_censor, "
        "storm_red)")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--stream", default=None,
                    help="input NDJSON directive/trace file to feed from "
                         "(exactly one of --stream/--scenario)")
    ap.add_argument("--scenario", default=None,
                    choices=["eclipse_censor", "storm_red"],
                    help="emit a canonical composed attack stream to "
                         "--out instead of copying --stream")
    ap.add_argument("--at", type=int, default=4,
                    help="--scenario: tick the composed attack lands at")
    ap.add_argument("--region", type=int, default=8,
                    help="--scenario eclipse_censor: eclipsed-region size")
    ap.add_argument("--attackers", type=int, default=8,
                    help="--scenario: attacker cohort size")
    ap.add_argument("--bursts", type=int, default=3,
                    help="--scenario storm_red: storm lines (one per "
                         "tick)")
    ap.add_argument("--out", required=True,
                    help="the run's --source file (appended, fsync'd "
                         "per line)")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="lines per second (0 = as fast as possible)")
    ap.add_argument("--from-offset", type=int, default=0,
                    help="resume the copy at this byte offset (the "
                         "run's stamped stream_offset)")
    ap.add_argument("--lines", type=int, default=None,
                    help="stop after N lines and sleep forever (chaos "
                         "drills SIGKILL the parked process)")
    args = ap.parse_args()

    if (args.stream is None) == (args.scenario is None):
        ap.error("exactly one of --stream / --scenario is required")
    if args.scenario:
        from go_libp2p_pubsub_tpu.sim.commands import write_stream
        directives = scenario_directives(
            args.scenario, at=args.at, region=args.region,
            attackers=args.attackers, bursts=args.bursts)
        write_stream(args.out, directives, end=True)
        print(f"[producer] scenario {args.scenario}: "
              f"{len(directives) + 1} lines -> {args.out}", flush=True)
        return 0

    delay = 1.0 / args.rate if args.rate > 0 else 0.0
    written = 0
    with open(args.stream, "rb") as src:
        src.seek(args.from_offset)
        # byte-mirror discipline: drop any torn/unstamped tail so the
        # output offset realigns with the input offset exactly
        with open(args.out, "ab") as dst:
            if dst.tell() != args.from_offset:
                dst.truncate(args.from_offset)
                dst.seek(args.from_offset)
            for line in src:
                dst.write(line)
                dst.flush()
                os.fsync(dst.fileno())
                written += 1
                if args.lines is not None and written >= args.lines:
                    print(f"[producer] parked after {written} lines at "
                          f"offset {src.tell()}", flush=True)
                    while True:
                        time.sleep(3600)
                if delay:
                    time.sleep(delay)
        end_offset = src.tell()
    print(f"[producer] done: {written} lines, offset {end_offset}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
