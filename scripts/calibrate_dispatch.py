"""Calibrate the dispatch table (ops/dispatch.py) on the live accelerator.

Measures every (op, formulation) pair of the gather families — the
generic [N, K] payload permute, the [W, N] word-table gather, the packed
edge exchange — plus masked selection, at a sweep of engine shapes, and
writes a versioned, platform-fingerprinted dispatch table whose
``measured`` buckets override the analytic ranking. Point
``GRAFT_DISPATCH_TABLE`` at the output and every ``*_mode="auto"``
resolves through the measured winners — the one-env-flip promotion
ROADMAP item 2 describes.

Resumable under the BENCH_JOURNAL discipline: every measurement is
fsync-appended to a journal line as it lands (op, formulation, shape, ms,
platform fingerprint), and a re-invocation skips already-journaled
measurements whose fingerprint matches — one preempted TPU window
refreshes the table incrementally instead of starting over
(scripts/tpu_recheck.sh runs this with a per-step journal).

A formulation that FAILS to lower or execute (the Mosaic gather wall
class) is recorded as failed and quarantined; a formulation ≥
``--quarantine-factor`` times slower than the best at every measured
shape of its op is quarantined as a measured loser (deletion deferred
until a real TPU window confirms — the marker keeps it out of auto while
explicit requests still work).

Usage:
    python scripts/calibrate_dispatch.py [--out PATH] [--journal PATH]
        [--shapes "n,k,m;n,k,m;..."] [--repeats R]
        [--quarantine-factor F]
"""

import argparse
import json
import os
import statistics
import sys
import time
from types import SimpleNamespace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _fingerprint() -> dict:
    from go_libp2p_pubsub_tpu.ops.dispatch import platform_fingerprint
    return platform_fingerprint()


def _time_call(fn, args, repeats: int) -> float:
    """Median wall time of ``fn(*args)`` (a jitted function with TRACED
    operand arguments — a zero-arg thunk closing over its operands would
    let XLA constant-fold the whole computation and time a literal
    fetch) with value-fetch sync — block_until_ready does not block
    through the axon tunnel (bench.py)."""
    np.asarray(jax.tree_util.tree_leaves(fn(*args))[0])   # compile + warm
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(jax.tree_util.tree_leaves(fn(*args))[0])
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples) * 1e3             # ms


def _build_shape(n: int, k: int, m: int, seed: int = 7):
    from go_libp2p_pubsub_tpu.sim import topology
    topo = topology.sparse(n, k, degree=min(12, k - 1), seed=seed)
    st = SimpleNamespace(neighbors=jnp.asarray(topo.neighbors),
                         reverse_slot=jnp.asarray(topo.reverse_slot))
    rng = np.random.default_rng(seed)
    w = (m + 31) // 32
    words = jnp.asarray(rng.integers(0, 2**32, (w, n), dtype=np.uint64),
                        jnp.uint32)
    payload = jnp.asarray(rng.integers(0, 2**32, (n, k), dtype=np.uint64),
                          jnp.uint32)
    nbr = jnp.clip(st.neighbors, 0, n - 1)
    rk = jnp.clip(st.reverse_slot, 0, k - 1)
    return st, words, payload, nbr, rk, w


def _measurements(n: int, k: int, m: int, t: int = 2):
    """Yield (op, formulation, shape_dict, jitted_fn, args) for one shape
    point. Operands travel as TRACED jit arguments (never closed over —
    see _time_call), and a formulation the resolver degrades at this
    shape is not timed under its own label (the measurement would be of
    the degrade target)."""
    import dataclasses

    from go_libp2p_pubsub_tpu.ops.heartbeat import edge_gather_packed
    from go_libp2p_pubsub_tpu.ops.hopkernel import (
        resolve_emit_mode,
        resolve_hop_mode,
    )
    from go_libp2p_pubsub_tpu.ops.permgather import (
        edge_sort_key,
        gather_words,
        permutation_gather,
        resolve_edge_packed_mode,
        resolve_mode,
        resolve_words_mode,
    )
    from go_libp2p_pubsub_tpu.ops.selection import (
        resolve_selection_mode,
        select_random,
    )
    from go_libp2p_pubsub_tpu.sim import SimConfig, TopicParams, init_state
    from go_libp2p_pubsub_tpu.sim.engine import step

    st, words, payload, nbr, rk, w = _build_shape(n, k, m)
    sk_w = edge_sort_key(st.neighbors, st.reverse_slot, k_major=True)
    sk_e = edge_sort_key(st.neighbors, st.reverse_slot, k_major=False)
    rng = np.random.default_rng(3)
    masks = [jnp.asarray(rng.random((n, t, k)) < 0.35) for _ in range(2)]

    for form in ("scalar", "rows", "sort", "mxu", "pallas"):
        if resolve_words_mode(form, w, n, k, have_sort_key=True) != form:
            continue
        fn = jax.jit(lambda x, i, s, f=form: gather_words(x, i, m, f,
                                                          sort_key=s))
        yield "words", form, {"w": w, "n": n, "k": k}, fn, \
            (words, nbr, sk_w)
    for form in ("scalar", "rows", "sort", "mxu", "pallas"):
        if resolve_mode(form, jnp.uint32, n, k, have_sort_key=True) != form:
            continue
        fn = jax.jit(lambda p, i, r, s, f=form: permutation_gather(
            p, i, r, f, sort_key=s))
        yield "edge_permute", form, {"n": n, "k": k}, fn, \
            (payload, nbr, rk, sk_e)
    for form in ("scalar", "rows", "sort", "mxu", "pallas"):
        if resolve_edge_packed_mode(form, n, k, 2 * t) != form:
            continue
        fn = jax.jit(lambda m0, m1, nb, rs, f=form: tuple(edge_gather_packed(
            [m0, m1], SimpleNamespace(neighbors=nb, reverse_slot=rs), f)))
        yield "edge_packed", form, {"n": n, "k": k, "b": 2 * t}, fn, \
            (masks[0], masks[1], st.neighbors, st.reverse_slot)

    key = jax.random.PRNGKey(0)
    mask3 = jnp.asarray(rng.random((n, t, k)) < 0.5)
    count = jnp.asarray(rng.integers(0, 13, (n, t)), jnp.int32)
    for form in ("iter", "sort", "ranks"):
        if resolve_selection_mode(form, k, 12) != form:
            continue
        fn = jax.jit(lambda ms, c, ky, f=form: select_random(
            ms, c, ky, max_count=12, mode=f))
        yield "selection", form, {"k": k, "max_count": 12}, fn, \
            (mask3, count, key)

    # hop/emit: no standalone op exists for the XLA formulations (they
    # are inline in forward_tick), so the comparator is ONE FULL ENGINE
    # STEP per hop_mode — every formulation sees the identical non-hop
    # work, so the relative ranking (all dispatch consumes) is exact,
    # and every eligible formulation lands in the same measured bucket
    cfg0 = SimConfig(n_peers=n, k_slots=k, n_topics=t, msg_window=m,
                     publishers_per_tick=4, prop_substeps=4)
    tp0 = TopicParams.disabled(t)
    from go_libp2p_pubsub_tpu.sim import topology as _topo
    st0 = init_state(cfg0, _topo.sparse(n, k, degree=min(12, k - 1),
                                        seed=7))
    for form in ("xla", "pallas", "pallas-mxu"):
        cfgf = dataclasses.replace(cfg0, hop_mode=form)
        hop_ok = resolve_hop_mode(form, cfgf, w, n, k) == form
        emit_ok = resolve_emit_mode(form, w, n, k) == form
        if not (hop_ok or emit_ok):
            continue
        fn = jax.jit(lambda s0, tp_, ky, c=cfgf: step(s0, c, tp_, ky))
        args = (st0, tp0, jax.random.PRNGKey(1))
        if hop_ok:
            yield "hop", form, {"w": w, "n": n, "k": k}, fn, args
        if emit_ok:
            yield "emit", form, {"w": w, "n": n, "k": k}, fn, args


def _journal_load(path: str, fp: dict) -> dict:
    recs = {}
    if path and os.path.exists(path):
        with open(path) as f:
            for ln in f:
                try:
                    r = json.loads(ln)
                except json.JSONDecodeError:
                    continue        # torn tail line: its point re-runs
                if r.get("fingerprint") == fp and "op" in r:
                    key = (r["op"], r["form"],
                           tuple(sorted(r["shape"].items())))
                    recs[key] = r
    return recs


def _journal_append(path: str, rec: dict) -> None:
    if not path:
        return
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _merge_table(out_path: str, platform: str, fp: dict,
                 journal: dict, quarantine_factor: float) -> dict:
    """Fold the journal's measurements into a dispatch table at
    ``out_path`` (seeded from the existing file, else the shipped
    default — other platforms' entries are preserved)."""
    from go_libp2p_pubsub_tpu.ops.dispatch import (
        DEFAULT_TABLE_PATH,
        OPS,
        load_table,
    )
    base_path = out_path if os.path.exists(out_path) else DEFAULT_TABLE_PATH
    table = json.loads(json.dumps(load_table(base_path)))   # deep copy
    entry = table["platforms"].setdefault(
        platform, json.loads(json.dumps(
            table["platforms"].get("default")
            or next(iter(table["platforms"].values())))))
    entry["fingerprint"] = fp
    # group by (op, shape)
    buckets: dict = {}
    failed: dict = {}
    for (op, form, shape_key), rec in journal.items():
        if "ms" in rec:
            buckets.setdefault((op, shape_key), {})[form] = rec["ms"]
        else:
            failed.setdefault(op, set()).add(form)
    entry["measured"] = [
        {"op": op, "shape": dict(shape_key), "ms": ms}
        for (op, shape_key), ms in sorted(buckets.items())]
    quarantined: dict = {op: sorted(forms) for op, forms in failed.items()}
    if quarantine_factor > 0:
        for op in OPS:
            per_form: dict = {}
            for (bop, _sk), ms in buckets.items():
                if bop != op or not ms:
                    continue
                best = min(ms.values())
                for form, v in ms.items():
                    per_form.setdefault(form, []).append(
                        v >= quarantine_factor * max(best, 1e-6))
            losers = [f for f, flags in per_form.items()
                      if flags and all(flags)]
            for f in losers:
                cur = set(quarantined.get(op, []))
                cur.add(f)
                quarantined[op] = sorted(cur)
    entry["quarantined"] = quarantined
    table["generated_by"] = "scripts/calibrate_dispatch.py"
    tmp = out_path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(table, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, out_path)
    return table


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.environ.get(
        "GRAFT_DISPATCH_TABLE", "dispatch_table_measured.json"))
    ap.add_argument("--journal", default=os.environ.get("BENCH_JOURNAL", ""))
    ap.add_argument("--shapes", default="")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--quarantine-factor", type=float, default=3.0)
    args = ap.parse_args()

    platform = jax.devices()[0].platform
    fp = _fingerprint()
    if not args.shapes:
        # CPU tier: contract-sized shapes; live accelerator: bench shapes
        args.shapes = "1024,32,64;4096,32,64" if platform == "cpu" \
            else "10240,48,64;102400,32,64"
    journal_path = args.journal or args.out + ".journal.jsonl"
    done = _journal_load(journal_path, fp)
    print(json.dumps({"info": "calibrate_dispatch", "platform": platform,
                      "shapes": args.shapes, "out": args.out,
                      "journal": journal_path,
                      "resumed_points": len(done)}), flush=True)

    for spec in args.shapes.split(";"):
        n, k, m = (int(x) for x in spec.split(","))
        for op, form, shape, fn, operands in _measurements(n, k, m):
            key = (op, form, tuple(sorted(shape.items())))
            if key in done:
                continue
            rec = {"op": op, "form": form, "shape": shape,
                   "platform": platform, "fingerprint": fp}
            try:
                rec["ms"] = round(_time_call(fn, operands, args.repeats), 4)
            except Exception as e:      # lowering/runtime failure: the
                rec["error"] = str(e)[:300]   # Mosaic-wall class — the
                                              # form is quarantined
            print(json.dumps(rec), flush=True)
            _journal_append(journal_path, rec)
            done[key] = rec

    table = _merge_table(args.out, platform, fp, done,
                         args.quarantine_factor)
    print(json.dumps({"info": "dispatch table written", "path": args.out,
                      "quarantined":
                      table["platforms"][platform]["quarantined"],
                      "measured_buckets":
                      len(table["platforms"][platform]["measured"])}),
          flush=True)


if __name__ == "__main__":
    main()
